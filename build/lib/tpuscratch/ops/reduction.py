"""Dot-product reduction kernels — the reference's CUDA reductions, TPU-way.

The reference ships three CUDA strategies (SURVEY.md §2.3):
atomicAdd finish (dot_product_kernel, mpicuda2.cu:65-81), two-phase
per-block partials + host accumulate (partial_dot_product_kernel,
mpicuda2.cu:84-100), and single-kernel full reduction where the last block
(detected via __threadfence + atomicInc) reduces the partials
(dot_product_full_kernel, mpicuda4.cu:157-185).

On TPU the whole concurrency problem those strategies manage does not
exist: a Pallas grid executes its steps **sequentially** on a core, so a
running accumulator needs no atomics, fences, or last-block detection —
the idiom is "initialize on first grid step, accumulate every step".
Both reference shapes survive:

- ``dot_partials``: per-block partials (two-phase shape) — one grid step
  writes one partial; the caller sums them (a cheap fused XLA reduce).
- ``dot_full``: single-kernel running accumulation (full-kernel shape) —
  the output block is revisited by every grid step.

fp32 accumulation regardless of input dtype (the fp32-only atomics
limitation at mpicuda2.cu:52-64 does not carry over: bf16/fp32 inputs both
accumulate in fp32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuscratch.ops.common import LANES, to_lanes, use_interpret


def _partials_kernel(off_ref, x_ref, y_ref, o_ref):
    # o_ref is the whole partials vector in SMEM: scalar stores are an
    # SMEM capability (VMEM wants >= (8,128) vector blocks), and the
    # sequential grid makes the per-step slot write race-free
    o_ref[pl.program_id(0)] = jnp.sum(
        (x_ref[:].astype(jnp.float32) + off_ref[0])
        * y_ref[:].astype(jnp.float32)
    )


def _full_kernel(off_ref, x_ref, y_ref, o_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += jnp.sum(
        (x_ref[:].astype(jnp.float32) + off_ref[0])
        * y_ref[:].astype(jnp.float32)
    )[None, None]


def _blocked(x: jax.Array, y: jax.Array, block_rows: int):
    """Block two vectors for a gridded reduction.

    Pads only to the 8x128 tile quantum, then clamps the block to the data
    (small inputs don't pay for a full 512x128 block) and pads the row count
    to a whole number of blocks.
    """
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    x2 = to_lanes(x)
    rows = x2.shape[0]
    block = min(block_rows, rows)
    grid = (rows + block - 1) // block
    pad_rows = grid * block - rows
    if pad_rows:
        x2 = jnp.pad(x2, ((0, pad_rows), (0, 0)))
    y2 = to_lanes(y)
    if pad_rows:
        y2 = jnp.pad(y2, ((0, pad_rows), (0, 0)))
    return x2, y2, grid, block


def _offset_arg(offset) -> jax.Array:
    """Normalize the optional elementwise offset to a (1,) f32 SMEM input.

    ``dot(x + o, y)`` without materializing ``x + o``: the add happens
    inside the kernel, so a loop-carried ``o`` (benchmark anti-hoisting,
    dot_bench.dot_program) costs zero extra HBM traffic — the blocked
    operands stay loop-invariant and XLA hoists their layout prep out of
    the scan.
    """
    if offset is None:
        return jnp.zeros((1,), jnp.float32)
    return jnp.asarray(offset, jnp.float32).reshape(1)


def prep(x: jax.Array, y: jax.Array, block_rows: int = 512):
    """Block two vectors once for repeated prepped-kernel calls.

    XLA does not hoist the pad/reshape out of a scan body on its own, so
    a loop that calls ``dot_full``/``dot_partials`` directly pays a full
    extra read+write of both vectors every iteration. Callers that
    iterate (dot_bench.dot_program) prep once and pass the blocked
    operands to ``dot_full_prepped``/``dot_partials_prepped``."""
    return _blocked(x, y, block_rows)


@functools.partial(jax.jit, static_argnames=("block_rows",))
def dot_partials(x: jax.Array, y: jax.Array, block_rows: int = 512, offset=None) -> jax.Array:
    """Two-phase reduction: Pallas per-block partials, XLA final sum.

    Returns a float32 scalar. Parity: partial_dot_product_kernel + the
    host-side std::accumulate finish (mpicuda2.cu:277-279) — except the
    finish is a fused on-device reduce, not a host loop.
    """
    x2, y2, grid, block = _blocked(x, y, block_rows)
    return dot_partials_prepped(x2, y2, block, offset=offset)


def _check_prepped(x2: jax.Array, y2: jax.Array, block: int) -> None:
    if x2.shape != y2.shape:
        raise ValueError(f"prepped shape mismatch {x2.shape} vs {y2.shape}")
    if x2.ndim != 2 or x2.shape[1] != LANES or x2.shape[0] % block:
        raise ValueError(
            f"prepped operands must be (k*{block}, {LANES}), got {x2.shape} "
            "— use prep() with the same block_rows"
        )


@functools.partial(jax.jit, static_argnames=("block",))
def dot_partials_prepped(x2: jax.Array, y2: jax.Array, block: int, offset=None) -> jax.Array:
    _check_prepped(x2, y2, block)
    grid = x2.shape[0] // block
    partials = pl.pallas_call(
        _partials_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((grid,), jnp.float32),
        interpret=use_interpret(),
    )(_offset_arg(offset), x2, y2)
    return jnp.sum(partials)


@functools.partial(jax.jit, static_argnames=("block_rows",))
def dot_full(x: jax.Array, y: jax.Array, block_rows: int = 512, offset=None) -> jax.Array:
    """Single-kernel full reduction via a running accumulator.

    Parity: dot_product_full_kernel (mpicuda4.cu:157-185) minus its entire
    synchronization apparatus — TPU grid steps are sequential, so the
    revisited output block IS the accumulator.
    """
    x2, y2, grid, block = _blocked(x, y, block_rows)
    return dot_full_prepped(x2, y2, block, offset=offset)


@functools.partial(jax.jit, static_argnames=("block",))
def dot_full_prepped(x2: jax.Array, y2: jax.Array, block: int, offset=None) -> jax.Array:
    _check_prepped(x2, y2, block)
    grid = x2.shape[0] // block
    out = pl.pallas_call(
        _full_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=use_interpret(),
    )(_offset_arg(offset), x2, y2)
    return out[0, 0]


def dot_prepped(x2: jax.Array, y2: jax.Array, block: int, method: str = "full", offset=None) -> jax.Array:
    """Strategy dispatch over pre-blocked operands (see ``prep``) — the
    one method-string table, shared with iterating callers like
    dot_bench so the benchmark cannot silently diverge from the library."""
    if method == "full":
        return dot_full_prepped(x2, y2, block, offset=offset)
    if method == "partials":
        return dot_partials_prepped(x2, y2, block, offset=offset)
    raise ValueError(f"unknown prepped dot method {method!r}")


def dot(x: jax.Array, y: jax.Array, method: str = "full", block_rows: int = 512, offset=None) -> jax.Array:
    """Dot product with strategy selection (REDUCE_GPU/REDUCE_CPU parity,
    mpicuda4.cu:347-355, as a runtime argument instead of a #define).

    methods: 'full' (single kernel), 'partials' (two-phase), 'xla'
    (jnp reference path — the CPU-oracle analogue).
    """
    if method == "full":
        return dot_full(x, y, block_rows, offset=offset)
    if method == "partials":
        return dot_partials(x, y, block_rows, offset=offset)
    if method == "xla":
        xf = x.astype(jnp.float32)
        if offset is not None:
            xf = xf + _offset_arg(offset)[0]  # fuses into the reduce
        return jnp.dot(xf, y.astype(jnp.float32))
    raise ValueError(f"unknown dot method {method!r}")


def local_dot_psum(x_shard: jax.Array, y_shard: jax.Array, axis, method: str = "full", block_rows: int = 512, offset=None):
    """SPMD body: per-shard kernel reduction + psum over ``axis``.

    The distributed dot product end-to-end (mpicuda2-4 parity): each rank
    reduces its shard on-device, then one data-plane collective combines
    them (MPI_Reduce at mpicuda2.cu:293 -> lax.psum). Call inside
    shard_map; see examples/dot_product.py for the driver.
    """
    return lax.psum(dot(x_shard, y_shard, method, block_rows, offset=offset), axis)
