"""Shared kernel plumbing: interpret-mode selection and shape blocking."""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANES = 128  # TPU lane width: last dim of every tile


def use_interpret() -> bool:
    """Pallas interpreter off-TPU — one kernel source, both backends.

    The analogue of the reference's #ifdef GPU dual path (mpicuda2.cu:176),
    but with no second implementation to keep in sync.
    """
    return jax.default_backend() != "tpu"


def to_lanes(x: jax.Array, sublanes_multiple: int = 8) -> jax.Array:
    """Reshape a vector to (rows, 128), zero-padding to full tiles.

    TPU vector registers are (sublane, lane) tiles; 1D reductions are run
    as 2D reductions over this layout. Zero padding is neutral for
    sum-reductions.
    """
    n = x.shape[0]
    row_quantum = LANES * sublanes_multiple
    padded = (n + row_quantum - 1) // row_quantum * row_quantum
    if padded != n:
        x = jnp.pad(x, (0, padded - n))
    return x.reshape(-1, LANES)
