"""Device-side initialization kernels.

Parity: ``init_vector`` filling a vector on-device so no H2D copy is paid
(ref_parallel-dot-product-atomics.cu:45-51) and ``InitKernel`` writing the
rank id into a 2D tile's core (mpi-2d-stencil-subarray-cuda.cu:17-28 —
launched there as w*h blocks of 1 thread; here one vectorized kernel).
Under jax, constants are already materialized on-device, so these exist
mainly to keep initialization inside a fused Pallas pipeline.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpuscratch.ops.common import use_interpret


def _fill_kernel(val_ref, o_ref):
    o_ref[:] = jnp.full_like(o_ref, val_ref[0])


@functools.partial(jax.jit, static_argnames=("shape", "dtype"))
def fill(shape: tuple[int, ...], value, dtype=jnp.float32) -> jax.Array:
    """Fill a (rows, cols) array with ``value`` on-device."""
    val = jnp.asarray([value], dtype=dtype)
    return pl.pallas_call(
        _fill_kernel,
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        interpret=use_interpret(),
    )(val)


def _iota2d_kernel(o_ref):
    h, w = o_ref.shape
    o_ref[:] = (
        jax.lax.broadcasted_iota(o_ref.dtype, (h, w), 0) * w
        + jax.lax.broadcasted_iota(o_ref.dtype, (h, w), 1)
    )


@functools.partial(jax.jit, static_argnames=("shape", "dtype"))
def iota2d(shape: tuple[int, int], dtype=jnp.float32) -> jax.Array:
    """Row-major linear index per cell — the InitKernel test pattern."""
    return pl.pallas_call(
        _iota2d_kernel,
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        interpret=use_interpret(),
    )()
