#!/usr/bin/env python
"""Headline benchmark: 2D 5-point stencil, 1024^2, on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BASELINE.md config 1 (the reference publishes no numbers — this repo
establishes the baseline; see SURVEY.md §6). On a TPU this runs the full
framework path — halo exchange (self-wrap on a 1x1 mesh) + 5-point
update, scanned — with both the XLA and Pallas compute paths, reporting
the faster. ``vs_baseline`` compares against BENCH_BASELINE.json (the
first recorded run) when present, else 1.0.
"""

import json
import pathlib
import sys

BASELINE_FILE = pathlib.Path(__file__).parent / "BENCH_BASELINE.json"
GRID = (1024, 1024)
STEPS = 10


def main() -> int:
    import jax

    from tpuscratch.bench.stencil_bench import bench_stencil
    from tpuscratch.runtime.mesh import make_mesh_2d

    n_dev = len(jax.devices())
    if n_dev == 1:
        mesh = make_mesh_2d((1, 1))
    else:
        from tpuscratch.runtime.topology import factor2d

        rows, cols = factor2d(n_dev)
        if GRID[0] % rows or GRID[1] % cols:
            rows, cols = 1, 1  # indivisible factorization: single device
        mesh = make_mesh_2d((rows, cols))

    best = None
    for impl in ("xla", "pallas", "overlap"):
        try:
            res = bench_stencil(GRID, STEPS, mesh=mesh, impl=impl, iters=5)
        except Exception as e:  # an impl failing shouldn't kill the bench
            print(f"# impl {impl} failed: {e}", file=sys.stderr)
            continue
        if best is None or res.items_per_s > best.items_per_s:
            best = res
    if best is None:
        raise SystemExit("all stencil impls failed")

    value = best.items_per_s
    vs = 1.0
    if BASELINE_FILE.exists():
        base = json.loads(BASELINE_FILE.read_text()).get("value")
        if base:
            vs = value / base
    print(
        json.dumps(
            {
                "metric": "stencil2d_1024x1024_cell_updates_per_s",
                "value": round(value, 1),
                "unit": "cells/s",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
