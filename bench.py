#!/usr/bin/env python
"""Headline benchmark: 2D 5-point stencil, 1024^2, on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BASELINE.md config 1 (the reference publishes no numbers — this repo
establishes the baseline; see SURVEY.md §6). Runs the full framework
path — halo exchange (self-wrap on a 1x1 mesh) + 5-point Jacobi update,
folded into one compiled scan — for each impl in the ``impls`` tuple
below (XLA-fused, deep-halo trapezoid, VMEM-resident Pallas trapezoid)
and reports the fastest.

Methodology notes (measured on the single-chip axon tunnel this repo
develops against):
- fence="readback": block_until_ready alone is NOT a reliable fence on
  remote-tunnel PJRT transports — programs whose device time is provably
  milliseconds "complete" in ~20us. A 4-byte readback is the fence.
- many steps per invocation: the tunnel costs ~150-200 ms fixed per
  fenced program call; hundreds of thousands of scanned steps amortize
  it so the number reflects the chip, not the transport. A quick screen
  across impls picks the winner, which is then re-measured at
  TPUSCRATCH_BENCH_STEPS_FINAL steps. BENCH_BASELINE.json's pin was
  recorded at 100k steps, so if the final re-measure fails the fallback
  re-runs at exactly 100k to stay methodology-compatible with the pin.
"""

import json
import os
import pathlib
import sys

BASELINE_FILE = pathlib.Path(__file__).parent / "BENCH_BASELINE.json"
GRID = (1024, 1024)
PIN_STEPS = 100_000  # step count BENCH_BASELINE.json's value was recorded at


def main() -> int:
    import jax

    from tpuscratch.runtime.mesh import make_mesh_2d

    on_tpu = jax.default_backend() == "tpu"
    steps = int(
        os.environ.get("TPUSCRATCH_BENCH_STEPS", "20000" if on_tpu else "50")
    )
    final_steps = int(
        os.environ.get(
            "TPUSCRATCH_BENCH_STEPS_FINAL", "2000000" if on_tpu else "50"
        )
    )
    iters = int(os.environ.get("TPUSCRATCH_BENCH_ITERS", "3"))

    n_dev = len(jax.devices())
    if n_dev == 1:
        mesh = make_mesh_2d((1, 1))
    else:
        from tpuscratch.runtime.topology import factor2d

        rows, cols = factor2d(n_dev)
        if GRID[0] % rows or GRID[1] % cols:
            rows, cols = 1, 1  # indivisible factorization: single device
        mesh = make_mesh_2d((rows, cols))

    # Phase 1 — screen every impl at a modest step count to find the
    # fastest. Phase 2 — re-measure the winner with enough scanned steps
    # that the transport's fixed per-invocation cost (~150-200 ms on the
    # axon tunnel) is amortized to noise and the number reflects the
    # chip's marginal step rate. BENCH_BASELINE.json was pinned at
    # PIN_STEPS, so a failed phase 2 falls back to PIN_STEPS (not the
    # screen count, whose fixed-cost share would fake a regression).
    from tpuscratch.bench.record import two_phase_stencil

    impls = ("xla", "deep:16", "deep-pallas:16", "deep-pallas:32", "resident:8")
    best, _, final_ok = two_phase_stencil(
        impls, "headline", GRID, mesh, iters,
        screen_steps=steps,
        # the PIN_STEPS fallback is a TPU-methodology concern; on dev
        # backends a 100k-step re-measure would hang smoke runs
        final_steps=(final_steps, PIN_STEPS) if on_tpu else final_steps,
    )
    if not final_ok:
        print(
            f"# WARNING: every re-measure failed; reporting the {steps}-step "
            f"screen number, which is NOT methodology-compatible with the "
            f"{PIN_STEPS}-step BENCH_BASELINE.json pin (fixed tunnel cost "
            f"understates the rate, so vs_baseline reads low)",
            file=sys.stderr,
        )

    value = best.items_per_s
    vs = 1.0
    if BASELINE_FILE.exists():
        base = json.loads(BASELINE_FILE.read_text()).get("value")
        if base:
            vs = value / base
    print(
        json.dumps(
            {
                "metric": "stencil2d_1024x1024_cell_updates_per_s",
                "value": round(value, 1),
                "unit": "cells/s",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
