#!/usr/bin/env python
"""Headline benchmark: 2D 5-point stencil, 1024^2, on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BASELINE.md config 1 (the reference publishes no numbers — this repo
establishes the baseline; see SURVEY.md §6). Runs the full framework
path — halo exchange (self-wrap on a 1x1 mesh) + 5-point Jacobi update,
folded into one compiled scan — for each impl in the ``impls`` tuple
below (XLA-fused, deep-halo trapezoid, VMEM-resident Pallas trapezoid)
and reports the fastest.

Methodology notes (measured on the single-chip axon tunnel this repo
develops against):
- fence="readback": block_until_ready alone is NOT a reliable fence on
  remote-tunnel PJRT transports — programs whose device time is provably
  milliseconds "complete" in ~20us. A 4-byte readback is the fence.
- many steps per invocation: the tunnel costs ~80 ms fixed per fenced
  program call; thousands of scanned steps amortize it so the number
  reflects the chip, not the transport.
"""

import json
import os
import pathlib
import sys

BASELINE_FILE = pathlib.Path(__file__).parent / "BENCH_BASELINE.json"
GRID = (1024, 1024)


def main() -> int:
    import jax

    from tpuscratch.bench.stencil_bench import bench_stencil
    from tpuscratch.runtime.mesh import make_mesh_2d

    on_tpu = jax.default_backend() == "tpu"
    steps = int(
        os.environ.get("TPUSCRATCH_BENCH_STEPS", "100000" if on_tpu else "50")
    )
    iters = int(os.environ.get("TPUSCRATCH_BENCH_ITERS", "3"))

    n_dev = len(jax.devices())
    if n_dev == 1:
        mesh = make_mesh_2d((1, 1))
    else:
        from tpuscratch.runtime.topology import factor2d

        rows, cols = factor2d(n_dev)
        if GRID[0] % rows or GRID[1] % cols:
            rows, cols = 1, 1  # indivisible factorization: single device
        mesh = make_mesh_2d((rows, cols))

    impls = ("xla", "deep:16", "deep-pallas:16", "deep-pallas:32", "resident:8")
    best = None
    for impl in impls:
        try:
            res = bench_stencil(
                GRID, steps, mesh=mesh, impl=impl, iters=iters, fence="readback"
            )
        except Exception as e:  # an impl failing shouldn't kill the bench
            print(f"# impl {impl} failed: {e}", file=sys.stderr)
            continue
        print(f"# {res.summary()}", file=sys.stderr)
        if best is None or res.items_per_s > best.items_per_s:
            best = res
    if best is None:
        raise SystemExit("all stencil impls failed")

    value = best.items_per_s
    vs = 1.0
    if BASELINE_FILE.exists():
        base = json.loads(BASELINE_FILE.read_text()).get("value")
        if base:
            vs = value / base
    print(
        json.dumps(
            {
                "metric": "stencil2d_1024x1024_cell_updates_per_s",
                "value": round(value, 1),
                "unit": "cells/s",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
