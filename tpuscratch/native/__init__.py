"""ctypes binding for the native halo planner (native/src/halo_geometry.cpp).

Loads ``native/libtpuscratch_native.so`` if present (``make -C native``
builds it; ``build()`` does so programmatically). All entry points mirror
the pure-Python topology/layout math one-for-one — tests cross-check them —
so the native path is an accelerator for trace-time planning on large
meshes, never a semantic fork.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import shutil
import subprocess
from typing import Optional

_LIB_NAME = "libtpuscratch_native.so"
_PKG_DIR = pathlib.Path(__file__).resolve().parent
_NATIVE_DIR = _PKG_DIR.parents[1] / "native"


def _lib_path() -> Optional[pathlib.Path]:
    """Resolve the library: explicit env override (must exist), else the
    newest of the dev-tree build and the wheel-shipped package copy."""
    env = os.environ.get("TPUSCRATCH_NATIVE_LIB")
    if env:
        path = pathlib.Path(env)
        if not path.exists():
            raise FileNotFoundError(
                f"TPUSCRATCH_NATIVE_LIB={env} does not exist"
            )
        return path
    existing = [
        p
        for p in (_NATIVE_DIR / _LIB_NAME, _PKG_DIR / _LIB_NAME)
        if p.exists()
    ]
    if not existing:
        return None
    return max(existing, key=lambda p: p.stat().st_mtime)


_lib: Optional[ctypes.CDLL] = None


def build(quiet: bool = True) -> bool:
    """Compile the native library (requires g++/make). True on success.

    Also copies the built .so into the package directory so that wheels
    built afterwards ship it (pyproject package-data picks it up).
    """
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True,
            capture_output=quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    try:
        shutil.copy2(_NATIVE_DIR / _LIB_NAME, _PKG_DIR / _LIB_NAME)
    except OSError:
        pass  # dev tree copy still loadable from native/
    global _lib
    _lib = None  # force reload
    return load() is not None


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, or None when unbuilt/unloadable.

    Exception: an explicit TPUSCRATCH_NATIVE_LIB override pointing at a
    missing file raises FileNotFoundError — a deliberate misconfiguration
    should fail loudly, not silently fall back to another copy.
    """
    global _lib
    if _lib is not None:
        return _lib
    path = _lib_path()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        return None
    i32 = ctypes.c_int32
    p32 = ctypes.POINTER(ctypes.c_int32)
    lib.ts_neighbor.restype = i32
    lib.ts_neighbor.argtypes = [i32] * 7
    lib.ts_send_permutation.restype = i32
    lib.ts_send_permutation.argtypes = [i32] * 6 + [p32, p32]
    lib.ts_halo_rect.restype = None
    lib.ts_halo_rect.argtypes = [i32] * 6 + [p32]
    lib.ts_send_rect.restype = None
    lib.ts_send_rect.argtypes = [i32] * 6 + [p32]
    lib.ts_build_plan.restype = i32
    lib.ts_build_plan.argtypes = [i32] * 9 + [p32] * 6
    try:
        lib.ts_neighbor3d.restype = i32
        lib.ts_neighbor3d.argtypes = [i32] * 10
        lib.ts_build_plan3d.restype = i32
        lib.ts_build_plan3d.argtypes = [i32] * 13 + [p32] * 6
    except AttributeError:
        pass  # pre-3D library build; has_plan3d() reports it
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def has_plan3d() -> bool:
    """Whether the loaded library includes the CURRENT 3D planner ABI
    (an older .so on disk may predate it or carry the pre-`neighbors`
    signature; the Python path then serves 3D plans)."""
    lib = load()
    if lib is None or not hasattr(lib, "ts_build_plan3d"):
        return False
    if not hasattr(lib, "ts_abi_version"):
        return False  # ABI v1: ts_build_plan3d lacks the neighbors arg
    lib.ts_abi_version.restype = ctypes.c_int32
    # exact match, not >=: signature bumps change symbols IN PLACE, so a
    # newer library through this prototype would misread arguments —
    # fail safe to the Python fallback instead
    return lib.ts_abi_version() == 2


def _rect(fn, core_h: int, core_w: int, hy: int, hx: int, dr: int, dc: int):
    out = (ctypes.c_int32 * 4)()
    fn(core_h, core_w, hy, hx, dr, dc, out)
    return tuple(out)


def neighbor(dims, periodic, rank: int, offset) -> Optional[int]:
    lib = load()
    assert lib is not None
    got = lib.ts_neighbor(
        dims[0], dims[1], int(periodic[0]), int(periodic[1]),
        rank, offset[0], offset[1],
    )
    return None if got < 0 else got


def send_permutation(dims, periodic, offset) -> list[tuple[int, int]]:
    lib = load()
    assert lib is not None
    n = dims[0] * dims[1]
    src = (ctypes.c_int32 * n)()
    dst = (ctypes.c_int32 * n)()
    count = lib.ts_send_permutation(
        dims[0], dims[1], int(periodic[0]), int(periodic[1]),
        offset[0], offset[1], src, dst,
    )
    return [(src[i], dst[i]) for i in range(count)]


def halo_rect(core_h, core_w, hy, hx, offset):
    lib = load()
    assert lib is not None
    return _rect(lib.ts_halo_rect, core_h, core_w, hy, hx, *offset)


def send_rect(core_h, core_w, hy, hx, offset):
    lib = load()
    assert lib is not None
    return _rect(lib.ts_send_rect, core_h, core_w, hy, hx, *offset)


def build_plan(dims, periodic, core_h, core_w, hy, hx, neighbors=8):
    """Full plan in one native call. Returns a list of dicts per direction:
    {direction, send_rect, recv_rect, perm} in ALL_DIRECTIONS order."""
    lib = load()
    assert lib is not None
    ndir_max, stride = 8, dims[0] * dims[1]
    dirs = (ctypes.c_int32 * (2 * ndir_max))()
    send_rects = (ctypes.c_int32 * (4 * ndir_max))()
    recv_rects = (ctypes.c_int32 * (4 * ndir_max))()
    perm_src = (ctypes.c_int32 * (ndir_max * stride))()
    perm_dst = (ctypes.c_int32 * (ndir_max * stride))()
    counts = (ctypes.c_int32 * ndir_max)()
    ndirs = lib.ts_build_plan(
        dims[0], dims[1], int(periodic[0]), int(periodic[1]),
        core_h, core_w, hy, hx, neighbors,
        dirs, send_rects, recv_rects, perm_src, perm_dst, counts,
    )
    if ndirs < 0:
        raise ValueError(
            f"native planner rejected dims={dims} core=({core_h},{core_w}) "
            f"halo=({hy},{hx}) neighbors={neighbors}"
        )
    import numpy as np

    # bulk views + tolist(): element-wise ctypes indexing would dominate
    # the whole call on large meshes (8 x ranks perm entries)
    src_np = np.ctypeslib.as_array(perm_src).reshape(ndir_max, stride)
    dst_np = np.ctypeslib.as_array(perm_dst).reshape(ndir_max, stride)
    out = []
    for i in range(ndirs):
        n = counts[i]
        out.append(
            {
                "direction": (dirs[2 * i], dirs[2 * i + 1]),
                "send_rect": tuple(send_rects[4 * i : 4 * i + 4]),
                "recv_rect": tuple(recv_rects[4 * i : 4 * i + 4]),
                "perm": list(
                    zip(src_np[i, :n].tolist(), dst_np[i, :n].tolist())
                ),
            }
        )
    return out


def build_plan3d(dims, periodic, core, halo, neighbors: int = 6):
    """Full 3D plan (6 faces or all 26 directions) in one native call.
    Returns a list of dicts {offset, send_rect, recv_rect, perm} in
    halo3d.OFFSETS26 order; rects are (o0, o1, o2, e0, e1, e2) in padded
    coords."""
    lib = load()
    assert lib is not None and has_plan3d()
    nranks = dims[0] * dims[1] * dims[2]
    nd = 26
    offs = (ctypes.c_int32 * (3 * nd))()
    send_rects = (ctypes.c_int32 * (6 * nd))()
    recv_rects = (ctypes.c_int32 * (6 * nd))()
    perm_src = (ctypes.c_int32 * (nd * nranks))()
    perm_dst = (ctypes.c_int32 * (nd * nranks))()
    counts = (ctypes.c_int32 * nd)()
    nfaces = lib.ts_build_plan3d(
        dims[0], dims[1], dims[2],
        int(periodic[0]), int(periodic[1]), int(periodic[2]),
        core[0], core[1], core[2], halo[0], halo[1], halo[2], neighbors,
        offs, send_rects, recv_rects, perm_src, perm_dst, counts,
    )
    if nfaces < 0:
        raise ValueError(
            f"native 3D planner rejected dims={dims} core={core} "
            f"halo={halo} neighbors={neighbors}"
        )
    import numpy as np

    src_np = np.ctypeslib.as_array(perm_src).reshape(nd, nranks)
    dst_np = np.ctypeslib.as_array(perm_dst).reshape(nd, nranks)
    out = []
    for i in range(nfaces):
        n = counts[i]
        out.append(
            {
                "offset": tuple(offs[3 * i : 3 * i + 3]),
                "send_rect": tuple(send_rects[6 * i : 6 * i + 6]),
                "recv_rect": tuple(recv_rects[6 * i : 6 * i + 6]),
                "perm": list(
                    zip(src_np[i, :n].tolist(), dst_np[i, :n].tolist())
                ),
            }
        )
    return out
