"""ctypes binding for the native pooled host-staging allocator
(native/src/host_pool.cpp) — the TPU-host counterpart of the reference's
pinned ``host_allocator<T>`` (host_allocator.h:58-93).

Page-aligned, size-class-pooled host buffers with optional mlock(2)
page-locking. Used by the pingpong staging ablations (the role
host_allocator plays in mpi-pingpong-gpu-async.cpp:43-49) and available
to any host-staging path (checkpoint serialization, decompose/assemble).

``HostBuffer.view()`` exposes the buffer as a zero-copy numpy array, so
staging is ``view[:] = np.asarray(device_arr)`` in and
``jax.device_put(view)`` out.
"""

from __future__ import annotations

import ctypes
import threading
import weakref
from typing import Optional

import numpy as np

from tpuscratch import native

_STATS_FIELDS = (
    "bytes_in_use",
    "bytes_cached",
    "high_water",
    "alloc_calls",
    "reuse_hits",
    "locked_bytes",
    "lock_failures",
    "page_class",
)

_configured = False


def _lib():
    lib = native.load()
    if lib is None:
        return None
    global _configured
    if not _configured:
        u64 = ctypes.c_uint64
        vp = ctypes.c_void_p
        lib.ts_pool_create.restype = vp
        lib.ts_pool_create.argtypes = [ctypes.c_int32]
        lib.ts_pool_alloc.restype = vp
        lib.ts_pool_alloc.argtypes = [vp, u64]
        lib.ts_pool_free.restype = None
        lib.ts_pool_free.argtypes = [vp, vp]
        lib.ts_pool_trim.restype = None
        lib.ts_pool_trim.argtypes = [vp]
        lib.ts_pool_stats.restype = None
        lib.ts_pool_stats.argtypes = [vp, ctypes.POINTER(u64)]
        lib.ts_pool_destroy.restype = None
        lib.ts_pool_destroy.argtypes = [vp]
        _configured = True
    return lib


def available() -> bool:
    return _lib() is not None


class HostBuffer:
    """One pooled buffer. Returns to the pool on ``free()``/``with`` exit;
    views become invalid afterwards (the buffer may be reused)."""

    def __init__(self, pool: "HostPool", ptr: int, nbytes: int):
        self._pool = pool
        self._ptr: Optional[int] = ptr
        self.nbytes = nbytes
        self._views: list[weakref.ref] = []

    @property
    def ptr(self) -> int:
        if self._ptr is None:
            raise ValueError("buffer already returned to the pool")
        return self._ptr

    def view(self, dtype=np.uint8, shape: Optional[tuple] = None) -> np.ndarray:
        """Zero-copy numpy view of (a prefix of) the buffer.

        Views are tracked (by weakref): ``free()`` refuses to return the
        buffer to the pool while any view is still alive, because writes
        through a stale view would silently corrupt whichever allocation
        reuses the memory."""
        dtype = np.dtype(dtype)
        if shape is None:
            shape = (self.nbytes // dtype.itemsize,)
        need = int(np.prod(shape)) * dtype.itemsize
        if need > self.nbytes:
            raise ValueError(f"view of {need} B exceeds buffer {self.nbytes} B")
        raw = (ctypes.c_byte * need).from_address(self.ptr)
        # anchor the buffer (and through its pool reference, the pool) on
        # the ctypes block at the view's base: a live view must keep the
        # pool's finalizer from destroying the pages under it, even when
        # the caller dropped every other reference
        raw._tpuscratch_buffer = self
        arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
        self._views.append(weakref.ref(arr))
        return arr

    def live_views(self) -> int:
        """Number of still-referenced views of this buffer."""
        self._views = [r for r in self._views if r() is not None]
        return len(self._views)

    def free(self) -> None:
        if self._ptr is not None:
            if self.live_views():
                # dead-but-uncollected reference cycles are common here:
                # jax.device_put aliases host numpy buffers zero-copy and
                # the dropped jax Array leaves a cycle only gc clears
                import gc

                gc.collect()
            if self.live_views():
                raise ValueError(
                    f"freeing buffer with {self.live_views()} live view(s); "
                    "drop the numpy references first"
                )
            self._pool._free(self._ptr)
            self._ptr = None

    def __enter__(self) -> "HostBuffer":
        return self

    def __exit__(self, *exc) -> None:
        self.free()


class HostPool:
    """Pooled page-aligned (optionally page-locked) host buffers.

    ``retry`` (an ``ft.RetryPolicy``) makes :meth:`alloc` absorb
    transient exhaustion: a failed allocation trims the pool's cached
    free lists back to the OS and retries under the policy's backoff —
    the RLIMIT_MEMLOCK budget is shared process-wide, so another pool
    releasing between attempts is a real recovery path.  ``None`` (the
    default) keeps the fail-fast contract."""

    def __init__(self, lock_pages: bool = True, retry=None):
        lib = _lib()
        if lib is None:
            raise RuntimeError(
                "native library unavailable — tpuscratch.native.build() "
                "or `make -C native` first"
            )
        self._retry = retry
        # lifecycle counters cross threads under async checkpointing
        # (alloc on the step loop, free on the background writer):
        # += / -= are non-atomic read-modify-writes, so lock them
        self._stats_lock = threading.Lock()
        self._live = 0        # buffers handed out and not yet freed
        self._live_hw = 0     # high-water of live buffers
        self._trims = 0       # trim() calls (retry pressure + manual)
        self._spill_bytes = 0     # D2H traffic noted by paging tiers
        self._prefetch_bytes = 0  # H2D traffic noted by paging tiers
        self._handle = lib.ts_pool_create(1 if lock_pages else 0)
        if not self._handle:
            raise MemoryError("ts_pool_create failed")
        # reclaim abandoned pools (buffers + mlock'd pages) even without
        # close(): RLIMIT_MEMLOCK is tiny in containers, so leaked locked
        # pages starve later pools
        self._finalizer = weakref.finalize(
            self, lib.ts_pool_destroy, self._handle
        )

    def alloc(self, nbytes: int) -> HostBuffer:
        if nbytes <= 0:
            raise ValueError(f"alloc of {nbytes} bytes")
        ptr = _lib().ts_pool_alloc(self._handle, nbytes)
        if not ptr and self._retry is not None:
            from tpuscratch.ft.retry import retry as _ft_retry

            def attempt() -> int:
                self.trim()  # cached free lists back to the OS first
                p = _lib().ts_pool_alloc(self._handle, nbytes)
                if not p:
                    raise MemoryError(
                        f"host pool exhausted allocating {nbytes} B"
                    )
                return p

            ptr = _ft_retry(attempt, self._retry, op="hostpool.alloc")
        if not ptr:
            raise MemoryError(f"host pool exhausted allocating {nbytes} B")
        with self._stats_lock:
            self._live += 1
            self._live_hw = max(self._live_hw, self._live)
        return HostBuffer(self, ptr, nbytes)

    def alloc_pages(self, n_pages: int, page_nbytes: int) -> HostBuffer:
        """One buffer covering ``n_pages`` page-shaped records of
        ``page_nbytes`` each — the KV paging tier's spill-batch shape
        (serve/kvcache.HostPageStore): a spill of k cold pages costs ONE
        pool allocation, not k, so the size-class free lists see a few
        large batch buffers instead of thousands of page-sized ones."""
        if n_pages < 1:
            raise ValueError(f"alloc_pages of {n_pages} pages")
        return self.alloc(n_pages * page_nbytes)

    def note_spill(self, nbytes: int) -> None:
        """Record device→host paging traffic (lock-guarded: spill runs
        on the engine loop, concurrent pools/threads may share this)."""
        with self._stats_lock:
            self._spill_bytes += int(nbytes)

    def note_prefetch(self, nbytes: int) -> None:
        """Record host→device paging traffic (see :meth:`note_spill`)."""
        with self._stats_lock:
            self._prefetch_bytes += int(nbytes)

    def _free(self, ptr: int) -> None:
        if self._handle:
            _lib().ts_pool_free(self._handle, ptr)
            with self._stats_lock:
                self._live -= 1

    def trim(self) -> None:
        """Release cached (free-listed) buffers back to the OS."""
        _lib().ts_pool_trim(self._handle)
        with self._stats_lock:
            self._trims += 1

    def stats(self) -> dict:
        """Native pool counters plus the Python-side lifecycle view
        (``live_buffers``: handed-out and unfreed, ``trim_calls``) — the
        snapshot ``obs`` surfaces so a staging path's host-buffer
        footprint is observable rather than silent."""
        out = (ctypes.c_uint64 * len(_STATS_FIELDS))()
        _lib().ts_pool_stats(self._handle, out)
        stats = dict(zip(_STATS_FIELDS, (int(v) for v in out)))
        with self._stats_lock:
            stats["live_buffers"] = self._live
            stats["live_buffers_hw"] = self._live_hw
            stats["trim_calls"] = self._trims
            stats["spill_bytes"] = self._spill_bytes
            stats["prefetch_bytes"] = self._prefetch_bytes
        return stats

    def close(self) -> None:
        if self._handle:
            self._finalizer()  # runs ts_pool_destroy once, then detaches
            self._handle = None

    def __enter__(self) -> "HostPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_default: Optional[HostPool] = None


def default_pool() -> HostPool:
    """Process-wide pool (page-locking on, falling back silently where
    RLIMIT_MEMLOCK forbids — see ``stats()['lock_failures']``)."""
    global _default
    if _default is None or _default._handle is None:
        _default = HostPool(lock_pages=True)
    return _default
