"""Serving-side decode throughput and per-token latency (BASELINE row 12).

``python -m tpuscratch.bench.decode_bench [--json PATH]
[--kv-dtype int8|fp8] [--spec K] [--fused auto|on|off] [--macro T]``

``--kv-dtype int8``/``fp8`` runs the sweep on quantized KV pages (~1/4
the cache bytes per token); ``--spec K`` speculates K draft tokens per
verify sweep over an accept-friendly periodic prompt; ``--fused``
selects the decode-sweep kernel (the fused Pallas paged-attention
kernel vs the dense XLA oracle); ``--macro T`` fuses T engine ticks
into one compiled scan (one dispatch + one host sync per T tokens,
``ServeConfig(macro_steps)``) — the serving hot-path levers, locally
sweepable before a record run.

Every row additionally carries the decode-sweep ROOFLINE: the HBM
bytes the measured sweeps moved (static page-count x ledger
bytes-per-token accounting, ``engine.cached_pages`` x
``engine.kv_bytes_per_token``) over the measured wall, as an absolute
rate and as the achieved fraction of the stated platform peak
(:func:`peak_hbm_bytes_per_s`; ``TPUSCRATCH_PEAK_HBM_GBPS`` to
override).  This is the quantity the fused kernel exists to raise —
the 2.42x stencil pin's residency argument applied to serving — and
config 12 regression-gates it upward.

Every training-side row measures steps/s of a compiled program; serving
is judged on different axes — sustained tokens/s at a batch size, and
the per-token latency DISTRIBUTION (a p99 an SLO can hold), which the
batch size trades against.  This bench drives the real engine (host
scheduling included: that loop is part of serving latency, exactly as
the reference's timing brackets include its rank-0 driver), steady
state: every slot busy, one engine tick == one token per slot.

Methodology: submit ``n_slots`` requests with max_new large enough to
hold all slots busy through the measured window, warm up past prefill +
the single decode compile, then time each engine tick individually.
Per-token latency IS the tick time (each slot advances one token per
tick); tokens/s = n_slots / p50.  Under speculation a tick emits a
variable count, so both are measured instead of assumed: tokens per
tick comes from the engine's token counter, and each tick's latency is
scaled by ``n_slots / tokens_that_tick`` so the reported percentiles
stay PER-TOKEN (a verify sweep that lands k accepted tokens costs its
tick once, not k times).  Sampled tokens are pulled to host every tick
(the engine's own np.asarray), so each timing is fenced by
construction.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

from tpuscratch.bench.timing import BenchResult, percentile

#: stated peak HBM bandwidth for the achieved-fraction-of-peak row,
#: overridable via TPUSCRATCH_PEAK_HBM_GBPS.  The TPU default is the
#: v5e spec number; the CPU default is a dual-channel DDR4-3200 PROXY
#: (51.2 GB/s) so CPU artifacts carry a comparable-to-itself fraction —
#: the absolute CPU value is a proxy, the per-artifact TREND is the
#: regression-gated quantity (the config-14 CPU-caveat discipline).
_PEAK_HBM_ENV = "TPUSCRATCH_PEAK_HBM_GBPS"
_DEFAULT_PEAK_HBM_GBPS = {"tpu": 819.0, "cpu": 51.2, "gpu": 900.0}


def peak_hbm_bytes_per_s() -> float:
    """The roofline denominator for the decode sweep (bytes/s)."""
    import jax

    env = os.environ.get(_PEAK_HBM_ENV, "").strip()
    if env:
        return float(env) * 1e9
    plat = jax.default_backend()
    return _DEFAULT_PEAK_HBM_GBPS.get(plat, 51.2) * 1e9


@dataclasses.dataclass(frozen=True)
class DecodeBenchResult:
    """BenchResult plus the latency percentiles a serving SLO reads.

    ``bytes_per_token`` is the STATIC cache-byte footprint per token of
    pool capacity (int8 pages land at ~1/4 of fp32 — the decode-gather
    roofline, see ``obs.ledger.kv_cache_bytes``); ``accept_len_mean``
    is the measured-window mean accepted draft length per verify sweep
    (None with speculation off).

    ``times_per_token_s`` is each tick's time scaled to ONE slot's
    per-token latency: ``tick_s * n_slots / tokens_emitted_that_tick``.
    Without speculation every tick emits exactly ``n_slots`` tokens, so
    it equals the raw tick times; a speculative tick emits ``n_slots +
    accepted`` and the scaling credits the amortization — otherwise the
    per-SWEEP time would be reported as per-token latency, overstating
    it by the mean accepted length."""

    result: BenchResult
    n_slots: int
    kv_dtype: str = "float32"
    spec_k: int = 0
    bytes_per_token: float = 0.0
    accept_len_mean: float | None = None
    times_per_token_s: tuple[float, ...] = ()
    # the decode-sweep roofline (ISSUE 12): HBM bytes the measured
    # window's sweeps moved — per tick, each live slot's page footprint
    # (engine.cached_pages, trapezoid of the tick-boundary samples)
    # times the pool's exact per-token bytes (pages + amortized scale
    # planes, the obs.ledger.kv_cache_bytes accounting) times the
    # tick's ROUND delta (a macro tick sweeps its pages up to
    # macro_steps times per dispatch — ISSUE 15) — over the measured
    # wall, against the stated platform peak.  swept_bytes is STATIC
    # accounting (page counts x ledger bytes), only the wall is sampled.
    swept_bytes: float = 0.0
    achieved_bytes_per_s: float = 0.0
    achieved_frac: float = 0.0
    fused: str = "auto"
    # macro-step decode accounting (ISSUE 15): tokens per decode
    # dispatch the window ran at, and the measured-window dispatch /
    # host-sync cost PER TOKEN — the two static counters macro decode
    # drives down ~T× (exact engine counters over exact token counts,
    # nothing sampled)
    macro_steps: int = 1
    dispatches_per_token: float = 0.0
    host_syncs_per_token: float = 0.0

    @property
    def tokens_per_s(self) -> float:
        return self.result.items_per_s

    @property
    def p50_s(self) -> float:
        return percentile(self.times_per_token_s or self.result.times_s, 50)

    @property
    def p99_s(self) -> float:
        return percentile(self.times_per_token_s or self.result.times_s, 99)

    def summary(self) -> str:
        out = (
            f"{self.result.name}: {self.tokens_per_s:.3e} tok/s, "
            f"per-token p50 {self.p50_s * 1e3:.3f} ms / "
            f"p99 {self.p99_s * 1e3:.3f} ms"
        )
        if self.accept_len_mean is not None:
            out += f", accept len {self.accept_len_mean:.2f}/{self.spec_k}"
        if self.achieved_bytes_per_s:
            out += (
                f", sweep {self.achieved_bytes_per_s / 1e9:.2f} GB/s "
                f"({100 * self.achieved_frac:.1f}% of peak)"
            )
        if self.macro_steps > 1:
            out += (
                f", macro T={self.macro_steps}: "
                f"{self.dispatches_per_token:.4f} dispatches/token, "
                f"{self.host_syncs_per_token:.4f} syncs/token"
            )
        return out


def accept_friendly_prompt(length: int, vocab: int,
                           period: int = 4) -> tuple[int, ...]:
    """A periodic prompt — the workload speculative decoding exists for:
    the prompt-lookup proposer finds its suffix n-gram immediately and
    drafts the pattern's continuation (boilerplate/template traffic)."""
    return tuple((t % period) + 1 for t in range(length))


def shared_prefix_prompts(n: int, length: int, share_ratio: float,
                          vocab: int, seed: int = 0) -> list[tuple[int, ...]]:
    """``n`` prompts of ``length`` tokens sharing their first
    ``round(share_ratio * length)`` tokens — the controllable
    system-prompt workload the prefix-sharing sweep measures (config
    12: prefill FLOPs and fresh-KV bytes vs share ratio).

    The shared prefix and each prompt's private tail are seeded draws,
    so a sweep's workload is a pure function of its arguments; the
    EFFECTIVE page-level share is ``floor(shared_len / page_size)``
    full pages (sharing is full-page-aligned by construction)."""
    import numpy as np

    if not 0.0 <= share_ratio <= 1.0:
        raise ValueError(f"share_ratio must be in [0, 1], got {share_ratio}")
    rng = np.random.default_rng(seed)
    shared_len = round(share_ratio * length)
    prefix = tuple(int(t) for t in rng.integers(0, vocab, shared_len))
    out = []
    for _ in range(n):
        tail = tuple(
            int(t) for t in rng.integers(0, vocab, length - shared_len)
        )
        out.append(prefix + tail)
    return out


def bench_serve_stream(mesh, cfg, scfg, prompts, max_new: int = 8,
                       disagg: bool = False, sink=None,
                       warmup: bool = True) -> dict:
    """Drain one request stream through a fresh engine, timing every
    tick: the ADMISSION-inclusive serving measurement the steady-state
    :func:`bench_decode` deliberately excludes.  This is where the
    prefix-sharing and disaggregation wins live — both change what an
    admission costs, not what a steady decode tick costs.

    Returns a dict of drain-level facts: wall seconds, tokens/s,
    per-TICK latency percentiles, and the engine report's static
    sharing accounting (prefilled vs shared prompt tokens, fresh KV
    bytes — exact counters, not samples).  ``disagg=True`` runs the
    same stream through a :class:`~tpuscratch.serve.disagg.
    DisaggEngine` and adds the handoff accounting.

    ``warmup`` drains one slot-bank's worth of throwaway requests
    first, so every compiled program the measured window touches
    (decode, context/bucket prefill, per-group migration) is already
    built — compile time must not masquerade as admission latency.
    Warmup pages all free back (and their prefix-trie entries die with
    them), so the measured stream's sharing starts cold."""
    from tpuscratch.serve import DisaggEngine, Request, ServeEngine

    eng = (
        DisaggEngine(mesh, cfg, scfg, sink=sink) if disagg
        else ServeEngine(mesh, cfg, scfg, sink=sink)
    )
    if warmup:
        eng.run([
            Request(rid=900_000 + i, prompt=tuple(prompts[0]), max_new=2)
            for i in range(scfg.n_slots)
        ])
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=tuple(p), max_new=max_new))
    inner = eng.engine if disagg else eng
    ptok0, stok0 = inner.prefill_tokens, inner.shared_tokens
    cow0, fresh0 = inner.cow_pages, inner.fresh_kv_bytes
    stage0 = eng._stage_tokens if disagg else 0
    hand0 = eng._handoffs if disagg else 0
    outputs = {}
    times = []
    t0 = time.perf_counter()
    max_steps = 100_000   # the engines' own did-not-drain guard
    while eng.n_queued or eng.n_active or getattr(eng, "n_staged", 0):
        if len(times) >= max_steps:
            raise RuntimeError(
                f"stream did not drain in {max_steps} ticks "
                f"({eng.n_queued} queued, {eng.n_active} active)"
            )
        t1 = time.perf_counter()
        for rid, toks in eng.step():
            outputs[rid] = toks
        times.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    tokens = sum(len(t) for t in outputs.values())
    prefill_tokens = inner.prefill_tokens - ptok0
    shared_tokens = inner.shared_tokens - stok0
    fresh_bytes = inner.fresh_kv_bytes - fresh0
    out = {
        "requests": len(prompts),
        "tokens": tokens,
        "wall_s": wall,
        "tokens_per_s": tokens / wall if wall else 0.0,
        "p50_tick_s": percentile(times, 50),
        "p99_tick_s": percentile(times, 99),
        "prefill_tokens": prefill_tokens,
        "shared_tokens": shared_tokens,
        "cow_pages": inner.cow_pages - cow0,
        "fresh_kv_bytes": fresh_bytes,
        "fresh_kv_bytes_per_token": fresh_bytes / tokens if tokens else 0.0,
        "prefill_frac": (
            prefill_tokens / max(1, prefill_tokens + shared_tokens)
        ),
        "outputs": tuple(sorted(outputs.items())),
    }
    if disagg:
        out["prefill_tokens"] = eng._stage_tokens - stage0
        out["prefill_frac"] = 1.0
        out["handoffs"] = eng._handoffs - hand0
        out["degraded"] = eng._degraded
        out["handoff_wire_bytes"] = (
            eng.handoff_wire_bytes * (eng._handoffs - hand0)
        )
    return out


def arrival_mix_requests(mix, n_requests: int, length: int, vocab: int,
                         seed: int = 0, max_new: int = 8,
                         pools_per_class: int = 1) -> list:
    """One-definition rule (ISSUE 17): request synthesis lives in
    ``bench.traffic`` — config-17 rows and config-19 rows draw from
    the same distributions.  This name survives as a delegate."""
    from tpuscratch.bench.traffic import arrival_mix_requests as impl

    return impl(mix, n_requests, length, vocab, seed=seed,
                max_new=max_new, pools_per_class=pools_per_class)


def bench_router(mesh, cfg, scfg, n_replicas: int, tagged, rcfg=None,
                 warmup: bool = True) -> dict:
    """Drain one multi-tenant ``(class, Request)`` stream through a
    :class:`~tpuscratch.serve.router.FleetRouter` over ``n_replicas``
    fresh engines — the fleet-level measurement (config 17): aggregate
    tokens/s, per-class p50/p99 TTFT and token rates, cross-replica
    ``prefill_frac``, and the affinity/dispatch accounting.  The static
    sharing law (``prefill + shared == submitted``) is asserted on
    every drain — a bench that cannot reconcile its own counters must
    not report them.

    ``warmup`` drains one slot-bank of throwaway requests through EACH
    replica before routing, so every compiled program (prefill buckets,
    decode) exists fleet-wide — compile time must not masquerade as
    TTFT."""
    from tpuscratch.serve import FleetRouter, Request, ServeEngine

    engines = [ServeEngine(mesh, cfg, scfg) for _ in range(n_replicas)]
    if warmup and tagged:
        p0 = tagged[0][1].prompt
        for eng in engines:
            eng.run([
                Request(rid=900_000 + i, prompt=p0, max_new=2)
                for i in range(scfg.n_slots)
            ])
    router = FleetRouter(engines, rcfg=rcfg)
    rep = router.run(tagged)
    if rep.prefill_tokens + rep.shared_tokens != \
            rep.submitted_prompt_tokens:
        raise RuntimeError(
            f"fleet counter law violated: {rep.prefill_tokens} prefilled"
            f" + {rep.shared_tokens} shared != "
            f"{rep.submitted_prompt_tokens} submitted"
        )
    return {
        "replicas": n_replicas,
        "requests": rep.completed,
        "tokens": rep.tokens_generated,
        "wall_s": rep.wall_s,
        "tokens_per_s": rep.tokens_per_s,
        "prefill_tokens": rep.prefill_tokens,
        "shared_tokens": rep.shared_tokens,
        "subpage_tokens": rep.subpage_tokens,
        "prefill_frac": rep.prefill_frac,
        "affinity_hits": rep.affinity_hits,
        "affinity_tokens": rep.affinity_tokens,
        "backpressure_holds": rep.backpressure_holds,
        "reroles": rep.reroles,
        "dispatched": list(rep.dispatched),
        "classes": {
            c.name: {
                "completed": c.completed,
                "tokens": c.tokens,
                "ttft_p50_s": c.ttft_p50_s,
                "ttft_p99_s": c.ttft_p99_s,
                "tokens_per_s": c.tokens_per_s,
            }
            for c in rep.classes
        },
        "outputs": rep.outputs,
    }


def router_mix_setup(on_tpu: bool):
    """The config-17 fleet workload: (serve cfg overrides, replica
    count, arrival mix, request count, prompt length, SLO classes) —
    ONE definition shared by the CLI ``--arrival-mix`` path and
    ``bench.record`` config 17 (the ``default_decode_setup`` rule)."""
    mix = (("latency", 3.0), ("batch", 1.0))
    classes = (
        # chunked-prefill admission for the TTFT class would need a
        # heterogeneous fleet; on the homogeneous record fleet the
        # preference is vacuous and the classes differ by REPORTING
        ("latency", "ttft"),
        ("batch", "throughput"),
    )
    if on_tpu:
        return dict(n_replicas=3, n_requests=48, length=64, max_new=8,
                    mix=mix, classes=classes)
    # sized so the affinity win clears CPU noise: 16 requests over
    # 3x4 fleet slots shares heavily without the over-concentration
    # queueing that larger backlogs pay for affinity (measured: 24+
    # requests trade the prefill saving back as queue wait); length 21
    # puts the 15-token shared prefix 3 tokens past a page boundary,
    # so the sub-page rung saves 3 tokens per boundary copy, not 1
    return dict(n_replicas=3, n_requests=16, length=21, max_new=4,
                mix=mix, classes=classes)


def bench_chunk_longmix(mesh, cfg, scfg, chunk: int, long_len: int = 32,
                        n_resident: int = None, max_new: int = 24) -> dict:
    """The chunked-prefill p99 claim, measured: resident short-prompt
    streams decode while ONE long prompt arrives mid-stream; per-tick
    latency (== resident per-token latency) is compared between the
    monolithic engine (the long prefill lands inside one tick — the
    p99 spike) and ``chunk_prefill=chunk`` (the same compute spread
    over ``ceil(long_len / chunk)`` ticks).  Greedy outputs are
    asserted IDENTICAL across the two runs — the p99 win is scheduling,
    not numerics."""
    import dataclasses as _dc

    from tpuscratch.serve import Request, ServeEngine

    n_res = (scfg.n_slots - 1) if n_resident is None else n_resident
    long_prompt = tuple(1 + t % (scfg.vocab - 1) for t in range(long_len))

    def drive(sc) -> tuple[dict, list[float]]:
        eng = ServeEngine(mesh, cfg, sc)
        # warmup drain compiles EVERY program the measured window will
        # touch (short bucket, long bucket / context chunks, decode) —
        # compile time must not masquerade as the p99 being measured
        eng.run([Request(rid=900_000, prompt=(1, 2), max_new=2),
                 Request(rid=900_001, prompt=long_prompt, max_new=2)])
        for i in range(n_res):
            eng.submit(Request(rid=i, prompt=(1 + i % 4, 2), max_new=max_new))
        outputs, times = {}, []
        arrived = False
        while eng.n_queued or eng.n_active:
            if len(times) >= 100_000:
                raise RuntimeError("long-mix stream did not drain")
            # the long prompt arrives once the residents are in steady
            # decode
            if not arrived and len(times) == 4:
                eng.submit(Request(rid=10_000, prompt=long_prompt,
                                   max_new=4))
                arrived = True
            t0 = time.perf_counter()
            for rid, toks in eng.step():
                outputs[rid] = toks
            times.append(time.perf_counter() - t0)
        return outputs, times

    base_out, base_t = drive(scfg)
    chunk_out, chunk_t = drive(_dc.replace(scfg, chunk_prefill=chunk))
    if base_out != chunk_out:
        raise RuntimeError("chunked long-mix outputs diverged from "
                           "monolithic — the p99 comparison is void")
    return {
        "long_len": long_len,
        "chunk": chunk,
        "p99_s_mono": percentile(base_t, 99),
        "p99_s_chunked": percentile(chunk_t, 99),
        "p99_ratio": percentile(chunk_t, 99) / percentile(base_t, 99),
        "max_s_mono": max(base_t),
        "max_s_chunked": max(chunk_t),
    }


def bench_tiered_residency(mesh, cfg, scfg, host_pages: int,
                           n_requests: int = None, prompt_len: int = None,
                           max_new: int = 8) -> dict:
    """The tiered-KV claim, measured (ISSUE 13): at a FIXED device page
    pool (the HBM stand-in), a long-context many-user backlog drains
    twice — untiered, then with ``kv_host_pages=host_pages`` — and the
    row reports **resident users at fixed HBM** (peak concurrently-
    active requests: untiered, the admission watermark caps it at what
    the device pool seats; tiered, cold pages spill so residency grows
    toward ``(device + host) / footprint``), the **cold-hit p99** (the
    synchronous-prefetch stalls the double-buffered prefetch-ahead
    failed to hide — the tier's latency tax, stated not hidden), and
    **host bytes per emitted token** (exact counters x exact page
    bytes, ``obs.ledger.kv_host_traffic_bytes``).  Greedy outputs are
    asserted IDENTICAL between the two drains — the residency win is
    memory placement, not numerics."""
    import dataclasses as _dc

    from tpuscratch.obs.ledger import kv_host_traffic_bytes
    from tpuscratch.serve import Request, ServeEngine

    if host_pages < 1:
        raise ValueError(f"host_pages must be >= 1, got {host_pages}")
    # long-context shape: each request's footprint is a multi-page slab
    # several of which do NOT fit the device pool at once
    prompt_len = prompt_len or 2 * scfg.page_size
    n_requests = n_requests or 2 * scfg.n_slots
    # the exact workload footprint, NOT inherited headroom: max_seq is
    # the per-sequence device floor, and the whole point is a device
    # pool tight against the aggregate
    scfg = _dc.replace(scfg, max_seq=prompt_len + max_new)
    prompts = [
        tuple(1 + (i + t) % (scfg.vocab - 1) for t in range(prompt_len))
        for i in range(n_requests)
    ]

    def drive(sc) -> dict:
        eng = ServeEngine(mesh, cfg, sc)
        # warmup: compile every program the measured drain touches
        eng.run([Request(rid=900_000 + i, prompt=prompts[0], max_new=2)
                 for i in range(min(2, sc.n_slots))])
        spill0, pref0 = eng.host_spilled_pages, eng.host_prefetched_pages
        cold0 = eng.cold_hits
        # cold-hit SAMPLES from warmup (first-compile-adjacent stalls)
        # must not feed the measured p99: count the post-warmup samples
        # and take them off the window's tail (exact even if the
        # bounded window wraps during the measured drain)
        cold_hist = eng.metrics.histogram("serve/cold_hit_s")
        cold_cnt0 = cold_hist.count
        for i, p in enumerate(prompts):
            eng.submit(Request(rid=i, prompt=p, max_new=max_new))
        outputs, times, peak = {}, [], 0
        t0 = time.perf_counter()
        while eng.n_queued or eng.n_active:
            if len(times) >= 100_000:
                raise RuntimeError("residency stream did not drain")
            t1 = time.perf_counter()
            for rid, toks in eng.step():
                outputs[rid] = toks
            times.append(time.perf_counter() - t1)
            peak = max(peak, eng.n_active)
        wall = time.perf_counter() - t0
        tokens = sum(len(t) for t in outputs.values())
        traffic = kv_host_traffic_bytes(
            eng._kv,
            eng.host_spilled_pages - spill0,
            eng.host_prefetched_pages - pref0,
        )
        cold_hist = eng.metrics.histogram("serve/cold_hit_s")
        n_measured = cold_hist.count - cold_cnt0
        cold_samples = list(cold_hist.window)[-n_measured:] if n_measured else []
        return {
            "outputs": tuple(sorted(outputs.items())),
            "resident_users": peak,
            "tokens": tokens,
            "tokens_per_s": tokens / wall if wall else 0.0,
            "p99_tick_s": percentile(times, 99),
            "cold_hits": eng.cold_hits - cold0,
            "cold_hit_p99_s": (
                percentile(cold_samples, 99) if cold_samples else 0.0
            ),
            "spilled_pages": traffic.spilled_pages,
            "prefetched_pages": traffic.prefetched_pages,
            "host_bytes_per_token": (
                traffic.per_token(tokens) if tokens else 0.0
            ),
        }

    base = drive(scfg)
    tier = drive(_dc.replace(scfg, kv_host_pages=host_pages))
    if tier["outputs"] != base["outputs"]:
        raise RuntimeError(
            "tiered outputs diverged from untiered — the residency "
            "comparison is void"
        )
    for row in (base, tier):
        row.pop("outputs")
    return {
        "device_pages": scfg.n_pages,
        "host_pages": host_pages,
        "prompt_len": prompt_len,
        "requests": n_requests,
        "resident_users": tier["resident_users"],
        "baseline_resident_users": base["resident_users"],
        "residency_gain": (
            tier["resident_users"] / max(1, base["resident_users"])
        ),
        "cold_hits": tier["cold_hits"],
        "cold_hit_p99_s": tier["cold_hit_p99_s"],
        "host_bytes_per_token": tier["host_bytes_per_token"],
        "spilled_pages": tier["spilled_pages"],
        "prefetched_pages": tier["prefetched_pages"],
        "tokens_per_s_tiered": tier["tokens_per_s"],
        "tokens_per_s_base": base["tokens_per_s"],
    }


def bench_budget(scfg, tokens_per_tick: int | None = None,
                 measure_steps: int = 32, warmup_steps: int = 4) -> int:
    """Per-slot generation budget of one :func:`bench_decode` window:
    (warmup + measure + 2) ticks × the tokens a tick can emit per slot
    — ONE definition (the +2 teardown margin and the tick ceiling),
    shared by the bench itself and every caller that must pre-check
    the page reservation.  ``tokens_per_tick`` defaults to the
    config's own ceiling: (spec_k + 1) × the engine's effective macro
    width (``serve.engine.macro_clamp`` — the one shared rule; nothing
    clamps since the host-free lift, so a COMPOSED spec × macro tick
    can emit up to T·(spec_k+1) tokens per slot and the budget scales
    by the product)."""
    if tokens_per_tick is None:
        from tpuscratch.serve.engine import macro_clamp

        tokens_per_tick = (scfg.spec_k + 1) * macro_clamp(scfg)[0]
    return (warmup_steps + measure_steps + 2) * tokens_per_tick


def fitting_batches(scfg, batches, tokens_per_tick: int | None = None,
                    prompt_len: int = 8, measure_steps: int = 32,
                    warmup_steps: int = 4) -> tuple[int, tuple[int, ...]]:
    """(pages one slot reserves, the ``batches`` whose full bank fits
    one group's pool) for a :func:`bench_decode` window — the
    admission-watermark arithmetic, shared by the ``--spec`` and
    ``--macro`` CLI guards and record config 12's macro row so the
    three can never desync from :func:`bench_budget`."""
    budget = bench_budget(scfg, tokens_per_tick,
                          measure_steps=measure_steps,
                          warmup_steps=warmup_steps)
    need = -(-(prompt_len + budget) // scfg.page_size)
    return need, tuple(b for b in batches if b * need <= scfg.n_pages)


def bench_decode(
    mesh,
    cfg,
    scfg,
    prompt_len: int = 8,
    measure_steps: int = 32,
    warmup_steps: int = 4,
    sink=None,
    prompt: tuple[int, ...] | None = None,
) -> DecodeBenchResult:
    """Steady-state decode: all ``scfg.n_slots`` slots busy, per-tick
    timings over ``measure_steps`` ticks after ``warmup_steps`` warm
    ticks (prefill + the one decode compile land in warmup).

    Speculation (``scfg.spec_k > 0``) changes the accounting, not the
    method: a tick still runs one compiled sweep for every slot, but
    emits a VARIABLE token count (base + accepted drafts), so tokens
    per tick is measured from the engine's token counter over the
    window rather than assumed to be ``n_slots``, and the result
    carries the window's mean accepted draft length.  Pass an
    accept-friendly ``prompt`` (:func:`accept_friendly_prompt`) to
    measure the amortization regime rather than the all-rejected floor.

    ``sink`` (an ``obs.sink.Sink``) attaches to the engine, so the
    artifact carries per-tick queue depth, free-page watermark, and
    tick latency alongside this function's tokens/s summary — a serving
    regression is then diagnosable FROM the artifact (was it admission?
    page pressure? a recompile?) instead of just visible in it."""
    from tpuscratch.serve import Request, ServeEngine

    if prompt is not None:
        prompt_len = len(prompt)
    # +1: prefill emits a token; the extra +1 keeps every slot ALIVE
    # through the last measured tick — finishing exactly on it would put
    # the all-slot eviction/free teardown inside the timed window, and
    # with 64 samples p99 interpolates at the max.  A speculative tick
    # can emit up to spec_k + 1 tokens per slot, and a MACRO tick up to
    # the CLAMP-AWARE macro_steps, so the budget (and the pool
    # reservation) scales by that ceiling (bench_budget — one shared
    # definition with the CLI/record fitting guards)
    budget = bench_budget(scfg, measure_steps=measure_steps,
                          warmup_steps=warmup_steps)
    scfg = dataclasses.replace(
        scfg, max_seq=max(scfg.max_seq, prompt_len + budget),
    )
    engine = ServeEngine(mesh, cfg, scfg, sink=sink)
    if prompt is None:
        prompt = tuple(t % scfg.vocab for t in range(1, prompt_len + 1))
    for i in range(scfg.n_slots):
        engine.submit(Request(rid=i, prompt=prompt, max_new=budget))
    for _ in range(warmup_steps):
        engine.step()
    if engine.n_active != scfg.n_slots:
        raise RuntimeError(
            f"warmup left {engine.n_active}/{scfg.n_slots} slots busy — "
            "raise the page pool or lower the batch"
        )
    compiles_before = engine.decode_compiles
    tokens0, slots0 = engine.tokens_generated, engine.slot_steps
    accepted0 = engine.spec_accepted
    disp0, sync0 = engine.dispatches, engine.host_syncs
    rounds0 = engine.decode_rounds
    page_bytes = engine.scfg.page_size * engine.kv_bytes_per_token
    times, tick_tokens = [], []
    swept_bytes = 0.0
    tprev = engine.tokens_generated
    rprev = engine.decode_rounds
    for _ in range(measure_steps):
        # pages the tick's sweeps gather — static accounting (page
        # counts x exact ledger bytes/token); one ROUND reads each live
        # slot's footprint once whether it scores 1 or K queries, and a
        # macro tick runs up to macro_steps rounds per dispatch, so the
        # footprint scales by the tick's round delta (without it a
        # macro tick's sweep traffic would be under-counted ~T× and
        # achieved_frac silently mis-stated).  The footprint GROWS
        # inside the tick as frontiers advance, so the per-round
        # estimate is the trapezoid of the boundary samples — exact
        # for the (linear) steady-state growth either side of a page
        # boundary, and unbiased across them.
        pages_before = engine.cached_pages * page_bytes
        t0 = time.perf_counter()
        engine.step()  # pulls sampled tokens to host: fenced
        times.append(time.perf_counter() - t0)
        pages_after = engine.cached_pages * page_bytes
        swept_bytes += (
            0.5 * (pages_before + pages_after)
            * (engine.decode_rounds - rprev)
        )
        rprev = engine.decode_rounds
        tnow = engine.tokens_generated
        tick_tokens.append(tnow - tprev)
        tprev = tnow
    if engine.decode_compiles != compiles_before:
        raise RuntimeError(
            "decode recompiled inside the measured window "
            f"({compiles_before} -> {engine.decode_compiles})"
        )
    tokens = engine.tokens_generated - tokens0
    sweeps = engine.slot_steps - slots0
    # the LIVE dispatch identities (ISSUE 19): the measured window is
    # steady-state (every slot alive throughout — the warmup/teardown
    # margins guarantee it), so the accounting laws hold EXACTLY and a
    # bench row can never report a dispatch rate the engine didn't run
    disp_d = engine.dispatches - disp0
    sync_d = engine.host_syncs - sync0
    rounds_d = engine.decode_rounds - rounds0
    T = engine.macro_steps_effective
    if sync_d != disp_d:
        raise RuntimeError(
            f"host_syncs delta {sync_d} != dispatches delta {disp_d}"
        )
    if rounds_d > disp_d * T:
        raise RuntimeError(
            f"{rounds_d} token rounds from {disp_d} dispatches at "
            f"T={T} — a dispatch covered more rounds than its scan"
        )
    if scfg.kv_host_pages <= 0:
        # untiered: one wave per tick and every round active mid-stream,
        # so the identities are exact — dispatches == ceil(slot_steps /
        # (T * bank)), the composed-path acceptance law (under spec the
        # bank's sweeps per round replace raw tokens: tokens == sweeps
        # + accepted varies with the accept rate, sweeps do not)
        if rounds_d != disp_d * T:
            raise RuntimeError(
                f"rounds delta {rounds_d} != dispatches {disp_d} * T={T}"
            )
        if sweeps != rounds_d * scfg.n_slots:
            raise RuntimeError(
                f"slot_steps delta {sweeps} != rounds {rounds_d} * "
                f"bank {scfg.n_slots}"
            )
        if disp_d != -(-sweeps // (T * scfg.n_slots)):
            raise RuntimeError(
                f"dispatches {disp_d} != ceil(slot_steps {sweeps} / "
                f"(T={T} * bank {scfg.n_slots}))"
            )
    accept_mean = (
        (engine.spec_accepted - accepted0) / sweeps
        if scfg.spec_k > 0 and sweeps else None
    )
    res = BenchResult(
        name=f"decode b={scfg.n_slots} prompt={prompt_len} "
             f"page={scfg.page_size} kv={scfg.kv_dtype}"
             + (f" spec={scfg.spec_k}" if scfg.spec_k else "")
             + (f" macro={engine.macro_steps_effective}"
                if engine.macro_steps_effective > 1 else ""),
        times_s=tuple(times),
        items=tokens / measure_steps,  # measured tokens per tick
    )
    wall = sum(times)
    achieved = swept_bytes / wall if wall else 0.0
    out = DecodeBenchResult(
        res, scfg.n_slots,
        kv_dtype=scfg.kv_dtype, spec_k=scfg.spec_k,
        bytes_per_token=engine.kv_bytes_per_token,
        accept_len_mean=accept_mean,
        times_per_token_s=tuple(
            t * scfg.n_slots / max(tk, 1)
            for t, tk in zip(times, tick_tokens)
        ),
        swept_bytes=swept_bytes,
        achieved_bytes_per_s=achieved,
        achieved_frac=achieved / peak_hbm_bytes_per_s(),
        fused=scfg.fused_attention,
        macro_steps=engine.macro_steps_effective,
        dispatches_per_token=(engine.dispatches - disp0) / max(1, tokens),
        host_syncs_per_token=(engine.host_syncs - sync0) / max(1, tokens),
    )
    if sink is not None and sink.enabled:
        sink.emit(
            "bench/decode",
            batch=scfg.n_slots, prompt_len=prompt_len,
            measure_steps=measure_steps,
            tokens_per_s=out.tokens_per_s,
            p50_s_per_token=out.p50_s, p99_s_per_token=out.p99_s,
            kv_dtype=scfg.kv_dtype, spec_k=scfg.spec_k,
            bytes_per_token=out.bytes_per_token,
            achieved_hbm_gbps=out.achieved_bytes_per_s / 1e9,
            achieved_frac=out.achieved_frac,
            fused=scfg.fused_attention,
            macro_steps=out.macro_steps,
            dispatches_per_token=out.dispatches_per_token,
            host_syncs_per_token=out.host_syncs_per_token,
            **({"accept_len_mean": accept_mean}
               if accept_mean is not None else {}),
        )
        # scope = this engine's registry: the sweep runs one engine per
        # batch size into ONE file, and the report must merge them, not
        # keep only the last engine's snapshot
        sink.emit_metrics(engine.metrics.snapshot(),
                          scope=engine.metrics.id)
        sink.flush()
    return out


def sweep(mesh, cfg, scfg, batch_sizes, **kw) -> list[DecodeBenchResult]:
    """``bench_decode`` across batch (slot-count) sizes — the
    throughput/latency trade curve."""
    out = []
    for b in batch_sizes:
        sc = dataclasses.replace(scfg, n_slots=b)
        r = bench_decode(mesh, cfg, sc, **kw)
        print(f"# {r.summary()}", file=sys.stderr)
        out.append(r)
    return out


def tiered_residency_setup(scfg, on_tpu: bool):
    """The long-context residency workload's serve shape: the row-12
    model at a deliberately TIGHT device pool, shared by the CLI
    ``--long-context`` path and ``bench.record`` config 12's
    ``serve_kv_tiered`` row (the one-definition rule of
    :func:`default_decode_setup`)."""
    import dataclasses as _dc

    return _dc.replace(
        scfg,
        n_pages=48 if on_tpu else 12,
        kv_host_pages=0,
    )


def default_decode_setup(on_tpu: bool):
    """The BASELINE row-12 workload: (model cfg, serve cfg, batch sizes,
    bench kwargs).  ONE definition shared by this module's CLI and
    ``bench.record`` config 12, so the standalone bench and the recorder
    can never silently measure different shapes."""
    from tpuscratch.models.transformer import TransformerConfig
    from tpuscratch.serve import ServeConfig

    cfg = (
        TransformerConfig(d_model=1024, n_heads=8, n_experts=4, d_ff=4096,
                          n_layers=4, capacity_factor=2.0)
        if on_tpu
        else TransformerConfig(d_model=32, n_heads=2, n_experts=2, d_ff=64,
                               n_layers=1)
    )
    scfg = ServeConfig(n_pages=512 if on_tpu else 64,
                       page_size=16 if on_tpu else 4,
                       vocab=1024 if on_tpu else 32)
    batches = (1, 8, 32) if on_tpu else (1, 4)
    kwargs = dict(prompt_len=64 if on_tpu else 4,
                  measure_steps=64 if on_tpu else 8)
    return cfg, scfg, batches, kwargs


def main(argv=None) -> int:
    import jax

    from tpuscratch.runtime.mesh import make_mesh

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default=None)
    ap.add_argument("--obs", default=None,
                    help="obs JSONL path (per-tick engine telemetry)")
    ap.add_argument("--kv-dtype", default="float32",
                    choices=("float32", "int8", "fp8"),
                    help="KV-cache page dtype (int8/fp8: quantized "
                         "pages, ~1/4 the cache bytes per token; fp8 "
                         "is the accuracy-per-byte e4m3 rung at the "
                         "same bytes)")
    ap.add_argument("--fused", default="auto",
                    choices=("auto", "on", "off"),
                    help="decode-sweep kernel: the fused Pallas "
                         "paged-attention kernel ('auto' uses it on a "
                         "real TPU; 'on' forces it, interpret-mode "
                         "off-TPU — orders of magnitude slower, a "
                         "correctness tool) vs the dense XLA oracle")
    ap.add_argument("--spec", type=int, default=0, metavar="K",
                    help="speculative draft tokens per verify sweep "
                         "(0 = off); sweeps use an accept-friendly "
                         "periodic prompt so the amortization regime "
                         "is what gets measured")
    ap.add_argument("--macro", type=int, default=1, metavar="T",
                    help="device-resident macro-step decode: token "
                         "rounds per engine dispatch (1 = the "
                         "per-token legacy program; T > 1 fuses T "
                         "ticks into one compiled lax.scan — one "
                         "dispatch + one host sync per T rounds, "
                         "bit-identical greedy output; composes with "
                         "--spec — up to T*(K+1) tokens per dispatch "
                         "— and with --kv-host-pages, whose wave "
                         "prefetch overlaps the running scan)")
    ap.add_argument("--share-ratio", default=None, metavar="R[,R...]",
                    help="run the PREFIX-SHARING stream workload at "
                         "these prompt share ratios (comma-separated, "
                         "e.g. 0,0.5,0.9) instead of the steady-state "
                         "sweep: shared-prefix prompts, prefix_share "
                         "engines, admission-inclusive timing — the "
                         "prefill-FLOPs/fresh-KV-bytes-vs-ratio curve")
    ap.add_argument("--chunk-prefill", type=int, default=0, metavar="N",
                    help="prefill chunk tokens per tick (0 = off): with "
                         "--share-ratio it rides the stream engines; "
                         "alone it runs the long-prompt-mix p99 "
                         "comparison (monolithic vs chunked)")
    ap.add_argument("--kv-host-pages", type=int, default=0, metavar="N",
                    help="host-tier page slots per dp group (0 = off): "
                         "cold KV pages spill to pinned host buffers "
                         "and prefetch back ahead of the decode sweep "
                         "— rides the steady-state sweep, or sizes the "
                         "tier for --long-context")
    ap.add_argument("--arrival-mix", default=None,
                    metavar="CLS:RATE[:TARGET][,...]",
                    help="run the FLEET-router workload instead of the "
                         "steady-state sweep: a multi-tenant arrival "
                         "mix (rates weight the interleave; TARGET is "
                         "ttft|throughput, default throughput) drains "
                         "through a FleetRouter twice — prefix "
                         "affinity on then off, identical greedy "
                         "outputs asserted — reporting aggregate "
                         "tokens/s, per-class p99 TTFT, and cross-"
                         "replica prefill_frac; 'default' uses the "
                         "config-17 canonical mix")
    ap.add_argument("--replicas", type=int, default=0, metavar="N",
                    help="fleet size for --arrival-mix (default: the "
                         "config-17 setup's)")
    ap.add_argument("--long-context", action="store_true",
                    help="run the long-context resident-users sweep "
                         "instead of the steady-state sweep: a many-"
                         "user backlog at a deliberately tight device "
                         "pool, untiered vs tiered (identical greedy "
                         "outputs asserted) — resident users at fixed "
                         "HBM, cold-hit p99, host bytes/token")
    ap.add_argument("--cpu-devices", type=int, default=0)
    args = ap.parse_args(argv)
    if args.cpu_devices:
        from tpuscratch.runtime.hostenv import force_cpu_devices

        force_cpu_devices(args.cpu_devices)

    from tpuscratch.obs.sink import open_sink

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, batches, kwargs = default_decode_setup(on_tpu)
    scfg = dataclasses.replace(scfg, kv_dtype=args.kv_dtype,
                               spec_k=args.spec,
                               fused_attention=args.fused,
                               macro_steps=max(1, args.macro),
                               kv_host_pages=max(0, args.kv_host_pages)
                               if not args.long_context else 0)

    if args.long_context:
        # a deliberately TIGHT device pool (the fixed-HBM stand-in):
        # the untiered watermark caps residents well below the slot
        # bank, the host tier lifts the cap — that delta is the row
        tight = tiered_residency_setup(scfg, on_tpu)
        host = args.kv_host_pages or 2 * tight.n_pages
        row = bench_tiered_residency(mesh, cfg, tight, host)
        print(f"# long-context: residents "
              f"{row['baseline_resident_users']} -> "
              f"{row['resident_users']} "
              f"({row['residency_gain']:.2f}x) at {row['device_pages']} "
              f"device pages; cold-hit p99 "
              f"{row['cold_hit_p99_s'] * 1e3:.3f} ms, host "
              f"{row['host_bytes_per_token']:.0f} B/token",
              file=sys.stderr)
        payload = {"platform": jax.default_backend(), "tiered": row}
        print(json.dumps(payload))
        if args.json:
            with open(args.json, "a") as f:
                f.write(json.dumps(payload) + "\n")
        return 0

    if args.arrival_mix is not None:
        from tpuscratch.serve.router import RouterConfig, SLOClass

        setup = router_mix_setup(on_tpu)
        if args.arrival_mix == "default":
            mix = list(setup["mix"])
            targets = dict(setup["classes"])
        else:
            mix, targets = [], {}
            for part in args.arrival_mix.split(","):
                bits = part.split(":")
                if len(bits) not in (2, 3):
                    ap.error(f"bad --arrival-mix entry {part!r} "
                             "(want CLS:RATE[:TARGET])")
                mix.append((bits[0], float(bits[1])))
                targets[bits[0]] = bits[2] if len(bits) == 3 \
                    else "throughput"
        n_rep = args.replicas or setup["n_replicas"]
        length, max_new = setup["length"], setup["max_new"]
        scfg = dataclasses.replace(
            scfg, prefix_share=True,
            max_seq=max(scfg.max_seq, length + max_new),
        )
        tagged = arrival_mix_requests(mix, setup["n_requests"], length,
                                      scfg.vocab, max_new=max_new)
        classes = tuple(
            SLOClass(n, target=targets.get(n, "throughput"))
            for n, _ in mix
        )
        rows = {}
        for aff in (True, False):
            row = bench_router(
                mesh, cfg, scfg, n_rep, tagged,
                rcfg=RouterConfig(affinity=aff, classes=classes),
            )
            tag = "affinity_on" if aff else "affinity_off"
            rows[tag] = row
            cls99 = ", ".join(
                f"{n} p99 TTFT {c['ttft_p99_s'] * 1e3:.1f} ms"
                for n, c in sorted(row["classes"].items())
            )
            print(f"# router {tag}: {row['tokens_per_s']:.3e} tok/s "
                  f"aggregate, prefill_frac {row['prefill_frac']:.3f} "
                  f"({cls99})", file=sys.stderr)
        if rows["affinity_on"].pop("outputs") != \
                rows["affinity_off"].pop("outputs"):
            raise RuntimeError("affinity on/off outputs diverged — "
                               "the routing comparison is void")
        payload = {"platform": jax.default_backend(), "router": rows}
        print(json.dumps(payload))
        if args.json:
            with open(args.json, "a") as f:
                f.write(json.dumps(payload) + "\n")
        return 0

    if args.share_ratio is not None:
        ratios = [float(r) for r in args.share_ratio.split(",")]
        # >= 4 pages of prompt so the swept ratios differ at page
        # granularity (sharing is full-page-aligned)
        length = max(4 * scfg.page_size, kwargs.get("prompt_len", 8))
        n_req = scfg.n_slots * 2
        max_new = 8
        scfg = dataclasses.replace(
            scfg, prefix_share=True, chunk_prefill=args.chunk_prefill,
            max_seq=max(scfg.max_seq, length + max_new),
        )
        rows = []
        for r in ratios:
            prompts = shared_prefix_prompts(n_req, length, r, scfg.vocab)
            row = bench_serve_stream(mesh, cfg, scfg, prompts,
                                     max_new=max_new)
            row.pop("outputs")
            row["share_ratio"] = r
            print(f"# share {r}: prefill_frac "
                  f"{row['prefill_frac']:.3f}, fresh "
                  f"{row['fresh_kv_bytes_per_token']:.0f} B/token, "
                  f"p99 tick {row['p99_tick_s'] * 1e3:.3f} ms",
                  file=sys.stderr)
            rows.append(row)
        payload = {"platform": jax.default_backend(),
                   "share_sweep": rows}
        print(json.dumps(payload))
        if args.json:
            with open(args.json, "a") as f:
                f.write(json.dumps(payload) + "\n")
        return 0

    if args.chunk_prefill:
        long_len = 256 if on_tpu else 32
        row = bench_chunk_longmix(
            mesh, cfg,
            dataclasses.replace(
                scfg, max_seq=max(scfg.max_seq, long_len + 32),
                n_pages=max(scfg.n_pages, 64),
            ),
            chunk=args.chunk_prefill,
            long_len=long_len,
        )
        print(f"# long-mix p99: mono {row['p99_s_mono'] * 1e3:.3f} ms -> "
              f"chunked {row['p99_s_chunked'] * 1e3:.3f} ms "
              f"({row['p99_ratio']:.3f}x)", file=sys.stderr)
        payload = {"platform": jax.default_backend(), "longmix": row}
        print(json.dumps(payload))
        if args.json:
            with open(args.json, "a") as f:
                f.write(json.dumps(payload) + "\n")
        return 0
    if args.spec:
        kwargs["prompt"] = accept_friendly_prompt(
            kwargs.pop("prompt_len", 8), scfg.vocab
        )
        # a speculative slot's budget (hence page reservation) scales by
        # spec + 1 — times the macro width when composed (bench_budget's
        # own product rule); drop sweep points whose full bank cannot
        # fit the pool — the admission watermark would (correctly)
        # refuse them
        need, fitting = fitting_batches(
            scfg, batches,
            prompt_len=len(kwargs["prompt"]),
            measure_steps=kwargs.get("measure_steps", 32),
            warmup_steps=kwargs.get("warmup_steps", 4),
        )
        for b in set(batches) - set(fitting):
            print(f"# batch {b} skipped: speculative reservation "
                  f"{b * need} pages > pool {scfg.n_pages}",
                  file=sys.stderr)
        if not fitting:
            ap.error(
                f"--spec {args.spec}: even batch 1 reserves {need} pages "
                f"> pool {scfg.n_pages}; lower --spec or the measured "
                "window"
            )
        batches = fitting
    if args.macro > 1 and not args.spec:
        # a macro slot's budget (hence page reservation) scales by T —
        # the speculative fitting rule, through fitting_batches (under
        # --spec the block above already sized the composed bank;
        # --kv-host-pages composes too since the host-free lift, same
        # T-scaled reservation)
        need, fitting = fitting_batches(
            scfg, batches,
            prompt_len=kwargs.get("prompt_len", 8),
            measure_steps=kwargs.get("measure_steps", 32),
            warmup_steps=kwargs.get("warmup_steps", 4),
        )
        for b in set(batches) - set(fitting):
            print(f"# batch {b} skipped: macro T={args.macro} "
                  f"reservation {b * need} pages > pool {scfg.n_pages}",
                  file=sys.stderr)
        if not fitting:
            ap.error(
                f"--macro {args.macro}: even batch 1 reserves {need} "
                f"pages > pool {scfg.n_pages}; lower --macro or the "
                "measured window"
            )
        batches = fitting
    rows = []
    # context-managed: a sweep that dies mid-run (OOM at a large batch)
    # still flushes the buffered ticks — exactly the telemetry needed to
    # diagnose the failure
    with open_sink(
        args.obs,
        run={"bench": "decode", "platform": jax.default_backend()},
        host=jax.process_index(),
    ) as sink:
        for r in sweep(mesh, cfg, scfg, batches, sink=sink, **kwargs):
            row = {
                "batch": r.n_slots,
                "tokens_per_s": r.tokens_per_s,
                "p50_s_per_token": r.p50_s,
                "p99_s_per_token": r.p99_s,
                "kv_dtype": r.kv_dtype,
                "spec_k": r.spec_k,
                "bytes_per_token": r.bytes_per_token,
                "achieved_hbm_gbps": r.achieved_bytes_per_s / 1e9,
                "achieved_frac": r.achieved_frac,
                "fused": r.fused,
                "macro_steps": r.macro_steps,
                "dispatches_per_token": r.dispatches_per_token,
                "host_syncs_per_token": r.host_syncs_per_token,
            }
            if r.accept_len_mean is not None:
                row["accept_len_mean"] = r.accept_len_mean
            rows.append(row)
    payload = {"platform": jax.default_backend(), "sweep": rows}
    print(json.dumps(payload))
    if args.json:
        # the file gets the platform too — a CPU-proxy number must never
        # masquerade as a chip number (record.py's own discipline)
        with open(args.json, "a") as f:
            f.write(json.dumps(payload) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
