"""Serving-side decode throughput and per-token latency (BASELINE row 12).

``python -m tpuscratch.bench.decode_bench [--json PATH]``

Every training-side row measures steps/s of a compiled program; serving
is judged on different axes — sustained tokens/s at a batch size, and
the per-token latency DISTRIBUTION (a p99 an SLO can hold), which the
batch size trades against.  This bench drives the real engine (host
scheduling included: that loop is part of serving latency, exactly as
the reference's timing brackets include its rank-0 driver), steady
state: every slot busy, one engine tick == one token per slot.

Methodology: submit ``n_slots`` requests with max_new large enough to
hold all slots busy through the measured window, warm up past prefill +
the single decode compile, then time each engine tick individually.
Per-token latency IS the tick time (each slot advances one token per
tick); tokens/s = n_slots / p50.  Sampled tokens are pulled to host
every tick (the engine's own np.asarray), so each timing is fenced by
construction.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

from tpuscratch.bench.timing import BenchResult, percentile


@dataclasses.dataclass(frozen=True)
class DecodeBenchResult:
    """BenchResult plus the latency percentiles a serving SLO reads."""

    result: BenchResult
    n_slots: int

    @property
    def tokens_per_s(self) -> float:
        return self.result.items_per_s

    @property
    def p50_s(self) -> float:
        return self.result.p50

    @property
    def p99_s(self) -> float:
        return percentile(self.result.times_s, 99)

    def summary(self) -> str:
        return (
            f"{self.result.name}: {self.tokens_per_s:.3e} tok/s, "
            f"per-token p50 {self.p50_s * 1e3:.3f} ms / "
            f"p99 {self.p99_s * 1e3:.3f} ms"
        )


def bench_decode(
    mesh,
    cfg,
    scfg,
    prompt_len: int = 8,
    measure_steps: int = 32,
    warmup_steps: int = 4,
    sink=None,
) -> DecodeBenchResult:
    """Steady-state decode: all ``scfg.n_slots`` slots busy, per-tick
    timings over ``measure_steps`` ticks after ``warmup_steps`` warm
    ticks (prefill + the one decode compile land in warmup).

    ``sink`` (an ``obs.sink.Sink``) attaches to the engine, so the
    artifact carries per-tick queue depth, free-page watermark, and
    tick latency alongside this function's tokens/s summary — a serving
    regression is then diagnosable FROM the artifact (was it admission?
    page pressure? a recompile?) instead of just visible in it."""
    from tpuscratch.serve import Request, ServeEngine

    scfg = dataclasses.replace(
        scfg, max_seq=max(scfg.max_seq,
                          prompt_len + warmup_steps + measure_steps + 2),
    )
    engine = ServeEngine(mesh, cfg, scfg, sink=sink)
    # +1: prefill emits a token; the extra +1 keeps every slot ALIVE
    # through the last measured tick — finishing exactly on it would put
    # the all-slot eviction/free teardown inside the timed window, and
    # with 64 samples p99 interpolates at the max
    budget = warmup_steps + measure_steps + 2
    for i in range(scfg.n_slots):
        engine.submit(Request(
            rid=i, prompt=tuple(t % scfg.vocab for t in range(1, prompt_len + 1)),
            max_new=budget,
        ))
    for _ in range(warmup_steps):
        engine.step()
    if engine.n_active != scfg.n_slots:
        raise RuntimeError(
            f"warmup left {engine.n_active}/{scfg.n_slots} slots busy — "
            "raise the page pool or lower the batch"
        )
    compiles_before = engine.decode_compiles
    times = []
    for _ in range(measure_steps):
        t0 = time.perf_counter()
        engine.step()  # pulls sampled tokens to host: fenced
        times.append(time.perf_counter() - t0)
    if engine.decode_compiles != compiles_before:
        raise RuntimeError(
            "decode recompiled inside the measured window "
            f"({compiles_before} -> {engine.decode_compiles})"
        )
    res = BenchResult(
        name=f"decode b={scfg.n_slots} prompt={prompt_len} "
             f"page={scfg.page_size}",
        times_s=tuple(times),
        items=scfg.n_slots,  # tokens per tick
    )
    out = DecodeBenchResult(res, scfg.n_slots)
    if sink is not None and sink.enabled:
        sink.emit(
            "bench/decode",
            batch=scfg.n_slots, prompt_len=prompt_len,
            measure_steps=measure_steps,
            tokens_per_s=out.tokens_per_s,
            p50_s_per_token=out.p50_s, p99_s_per_token=out.p99_s,
        )
        # scope = this engine's registry: the sweep runs one engine per
        # batch size into ONE file, and the report must merge them, not
        # keep only the last engine's snapshot
        sink.emit_metrics(engine.metrics.snapshot(),
                          scope=engine.metrics.id)
        sink.flush()
    return out


def sweep(mesh, cfg, scfg, batch_sizes, **kw) -> list[DecodeBenchResult]:
    """``bench_decode`` across batch (slot-count) sizes — the
    throughput/latency trade curve."""
    out = []
    for b in batch_sizes:
        sc = dataclasses.replace(scfg, n_slots=b)
        r = bench_decode(mesh, cfg, sc, **kw)
        print(f"# {r.summary()}", file=sys.stderr)
        out.append(r)
    return out


def default_decode_setup(on_tpu: bool):
    """The BASELINE row-12 workload: (model cfg, serve cfg, batch sizes,
    bench kwargs).  ONE definition shared by this module's CLI and
    ``bench.record`` config 12, so the standalone bench and the recorder
    can never silently measure different shapes."""
    from tpuscratch.models.transformer import TransformerConfig
    from tpuscratch.serve import ServeConfig

    cfg = (
        TransformerConfig(d_model=1024, n_heads=8, n_experts=4, d_ff=4096,
                          n_layers=4, capacity_factor=2.0)
        if on_tpu
        else TransformerConfig(d_model=32, n_heads=2, n_experts=2, d_ff=64,
                               n_layers=1)
    )
    scfg = ServeConfig(n_pages=512 if on_tpu else 64,
                       page_size=16 if on_tpu else 4,
                       vocab=1024 if on_tpu else 32)
    batches = (1, 8, 32) if on_tpu else (1, 4)
    kwargs = dict(prompt_len=64 if on_tpu else 4,
                  measure_steps=64 if on_tpu else 8)
    return cfg, scfg, batches, kwargs


def main(argv=None) -> int:
    import jax

    from tpuscratch.runtime.mesh import make_mesh

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default=None)
    ap.add_argument("--obs", default=None,
                    help="obs JSONL path (per-tick engine telemetry)")
    ap.add_argument("--cpu-devices", type=int, default=0)
    args = ap.parse_args(argv)
    if args.cpu_devices:
        from tpuscratch.runtime.hostenv import force_cpu_devices

        force_cpu_devices(args.cpu_devices)

    from tpuscratch.obs.sink import open_sink

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, batches, kwargs = default_decode_setup(on_tpu)
    rows = []
    # context-managed: a sweep that dies mid-run (OOM at a large batch)
    # still flushes the buffered ticks — exactly the telemetry needed to
    # diagnose the failure
    with open_sink(
        args.obs,
        run={"bench": "decode", "platform": jax.default_backend()},
        host=jax.process_index(),
    ) as sink:
        for r in sweep(mesh, cfg, scfg, batches, sink=sink, **kwargs):
            rows.append({
                "batch": r.n_slots,
                "tokens_per_s": r.tokens_per_s,
                "p50_s_per_token": r.p50_s,
                "p99_s_per_token": r.p99_s,
            })
    payload = {"platform": jax.default_backend(), "sweep": rows}
    print(json.dumps(payload))
    if args.json:
        # the file gets the platform too — a CPU-proxy number must never
        # masquerade as a chip number (record.py's own discipline)
        with open(args.json, "a") as f:
            f.write(json.dumps(payload) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
