"""Flash-attention throughput benchmark (beyond-reference config).

The reference has no attention (SURVEY.md §2.7); this measures the
framework's long-context MXU kernel (ops/attention.py) with the same
methodology as the stencil/dot benches: many calls folded into one
compiled scan so the transport's fixed per-invocation cost amortizes
away, a loop-carried zero-valued offset defeating loop-invariant
hoisting, and readback fencing.

Reported metric: attention TFLOP/s at (S, H, D), counting the standard
4*S*T*H*D multiply-accumulate FLOPs (halved for causal via the kernel's
block skip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.ops.attention import flash_attention


def attention_program(
    causal: bool, rounds: int, block_q: int = 1024,
    block_k: int | None = None,
):
    """jit'd fn(q, k, v) running ``rounds`` flash calls in one scan.

    Anti-hoisting: each round perturbs q by a loop-carried scalar that is
    always 0 in value (previous output times zero) but that the compiler
    cannot prove constant, so no round is hoisted. The perturbation is in
    the DATA (one extra q-sized HBM read+write per round, ~5% at this
    shape), not the offsets — offsets stay compile-time ints so the
    benchmark measures the compact causal grid, the path real
    self-attention callers take."""

    @jax.jit
    def run(q, k, v):
        def step(carry, _):
            eps, _prev = carry
            out = flash_attention(
                q + eps, k, v, causal=causal,
                block_q=block_q, block_k=block_k,
            )
            # carry (not stack) the output: stacked scan ys would
            # materialize rounds * S*H*D*4 bytes of HBM
            return (out[0, 0, 0] * 0, out), None

        init = (jnp.zeros((), q.dtype), jnp.zeros(q.shape, q.dtype))
        (_, last), _ = lax.scan(step, init, None, length=rounds)
        return last

    return run


def bench_attention(
    S: int = 4096,
    H: int = 8,
    D: int = 128,
    causal: bool = True,
    rounds: int = 50,
    iters: int = 3,
    fence: str = "readback",
    dtype=jnp.float32,
    block_q: int = 1024,
    # None = the kernel's own tuned defaults (an explicit value — even
    # 1024 — is a resource bound honored in forward AND backward)
    block_k: int | None = None,
    max_tflops: float = 250.0,
) -> BenchResult:
    """``max_tflops`` is the same implausibility defense as dot_bench's
    ``max_gbps``: the anti-hoisting chain hangs on XLA never constant-
    folding the f32 ``out * 0`` into the loop-carried offset — if a
    future simplifier does, the scanned calls collapse to one and the
    rate explodes past any physical MXU roofline (~197 bf16 TFLOP/s on
    v5e). Raise the bound for faster parts rather than deleting it."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((S, H, D)), dtype=dtype)
    k = jnp.asarray(rng.standard_normal((S, H, D)), dtype=dtype)
    v = jnp.asarray(rng.standard_normal((S, H, D)), dtype=dtype)
    f = attention_program(causal, rounds, block_q=block_q, block_k=block_k)
    flops_per_call = 4 * S * S * H * D * (0.5 if causal else 1.0)
    res = time_device(
        f, q, k, v,
        iters=iters, warmup=2, fence=fence,
        name=f"flash S={S} H={H} D={D} causal={causal} x{rounds}",
        items=int(flops_per_call) * rounds,  # items = FLOPs
    )
    if rounds > 1 and res.items_per_s / 1e12 > max_tflops:
        raise AssertionError(
            f"implausible {res.items_per_s / 1e12:.0f} TFLOP/s "
            f"(> {max_tflops:.0f}): the scanned attention was likely "
            "hoisted out of the loop; fix attention_program's "
            "loop-carried offset before trusting this benchmark"
        )
    return res
