"""Chip race: flash-attention BACKWARD variants (round 5, VERDICT r4
weak #2 / next #2).

Races, at the config-6 shape (S=T=4096, H=8, D=128):

- the dense-grid backward at block retunes (bq, bk) in {512, 1024,
  2048}^2 combos, f32 and bf16, causal and non-causal;
- the compact-causal backward grids (this round's kernels — masked
  pairs cost neither grid steps nor DMA, interior pairs skip mask
  arithmetic) against the dense causal grid.

The measured quantity is the full backward call (delta + dq kernel +
dkv kernel), scanned ``rounds`` times with a perturbation threaded
through ``do`` so XLA cannot hoist the calls; TFLOP/s uses the standard
2.5x-forward accounting (5 essential backward matmuls vs the forward's
2): fwd = 4*S*T*D*H MACs-as-2FLOPs, causal credited at half.

Usage: python -m tpuscratch.bench.attn_bwd_bench [rounds]
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np

from tpuscratch.bench.timing import time_device
from tpuscratch.ops import attention as A

S = T = 4096
H = 8
D = 128


def bwd_once(q, k, v, do, lse, delta, causal, bq, bk, compact):
    if compact:
        r = A._flash_bwd_compact(q, k, v, do, lse, delta, 0, 0, bq, bk)
        assert r is not None
        return r
    qoff = jnp.zeros((1,), jnp.int32)
    koff = jnp.zeros((1,), jnp.int32)
    return A._flash_bwd_call(q, k, v, do, lse, delta, qoff, koff, causal,
                             bq, bk)


@functools.partial(jax.jit, static_argnames=("causal", "bq", "bk",
                                             "compact", "rounds"))
def bwd_scan(q, k, v, do, o, lse, causal, bq, bk, compact, rounds):
    def body(c, _):
        # thread the carry through do so each round's call is live
        do_r = do + c * 1e-30
        delta = jnp.sum(
            do_r.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
        )
        dq, dk, dv = bwd_once(q, k, v, do_r, lse, delta, causal, bq, bk,
                              compact)
        # consume ALL THREE outputs or XLA dead-code-eliminates the
        # dkv kernel entirely (observed: "295 TFLOP/s f32")
        return c + dq[0, 0, 0] + dk[0, 0, 0].astype(jnp.float32) \
            + dv[0, 0, 0].astype(jnp.float32), ()

    c, _ = jax.lax.scan(body, jnp.float32(0), None, length=rounds)
    return c


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    rng = np.random.default_rng(11)

    for dt in (jnp.float32, jnp.bfloat16):
        q = jnp.asarray(rng.standard_normal((H, S, D)), dt)
        k = jnp.asarray(rng.standard_normal((H, T, D)), dt)
        v = jnp.asarray(rng.standard_normal((H, T, D)), dt)
        do = jnp.asarray(rng.standard_normal((H, S, D)), dt)
        for causal in (False, True):
            # state-mode forward once, outside the timed region
            acc, m, l = A._flash_fwd_call(
                q, k, v, jnp.zeros((1,), jnp.int32),
                jnp.zeros((1,), jnp.int32), causal, 1024, 1024, True,
            )
            l_safe = jnp.maximum(l, 1e-30)
            o = (acc / l_safe[:, :, None]).astype(dt)
            lse = m + jnp.log(l_safe)
            flops = 2.5 * 4 * S * T * D * H * (0.5 if causal else 1.0)
            combos = [(1024, 1024, False), (512, 1024, False),
                      (1024, 512, False), (512, 512, False),
                      (2048, 1024, False), (1024, 2048, False)]
            if causal:
                combos += [(1024, 1024, True), (512, 1024, True),
                           (512, 512, True), (1024, 512, True)]
            for bq, bk, compact in combos:
                try:
                    # MARGINAL ms/bwd by round-count differencing: the
                    # ~150-200 ms fixed tunnel cost per fenced
                    # invocation is 3-4 ms/round at rounds=50 — larger
                    # than the quantity measured
                    lo, hi = rounds, 4 * rounds
                    r_lo = time_device(
                        bwd_scan, q, k, v, do, o, lse, causal, bq, bk,
                        compact, lo, warmup=1, iters=3, fence="readback",
                    )
                    r_hi = time_device(
                        bwd_scan, q, k, v, do, o, lse, causal, bq, bk,
                        compact, hi, warmup=1, iters=3, fence="readback",
                    )
                except Exception as e:
                    print(f"# {dt.__name__} causal={causal} bq={bq} "
                          f"bk={bk} compact={compact}: FAILED {e}")
                    continue
                ms = (r_hi.p50 - r_lo.p50) * 1e3 / (hi - lo)
                tf = flops / (ms * 1e-3) / 1e12
                print(
                    f"# {dt.__name__} causal={int(causal)} bq={bq} "
                    f"bk={bk} {'compact' if compact else 'dense'}: "
                    f"{ms:.3f} ms/bwd = {tf:.1f} TFLOP/s",
                    flush=True,
                )


if __name__ == "__main__":
    main()
