"""Chip race: 27-point streamed-kernel tuning (round 5, VERDICT r4
weak #1 / next #4).

The round-4 27-point stream:2 recorded 4.41 ms/step at 256x512x512 —
7x the 7-point's 0.632 for ~3.9x the FLOPs — with two named causes:
the band auto-drop to 4 (_VMEM_CEILING_27) and the three accumulating
read-modify-write stores per substep.  This harness races:

  r4      : per-dz-slab stores (ysplit27=0), band=4   (the baseline)
  ysplit2 : y-halved single-store substep,   band=4
  ysplit2+8: same, band=8 (restored DMA window efficiency)
  ysplit4+8: quarter-chunks, band=8
  deeper folds (stream:4) on the winner's form

Marginal ms/step by step-count differencing; bit-exactness asserted
against the XLA compact 27-point path at small steps.

Usage: python -m tpuscratch.bench.stream27_chip
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from tpuscratch.bench.timing import time_device
from tpuscratch.halo.halo3d import OFFSETS26
from tpuscratch.ops.stencil_stream import seven_point_streamed_pallas

CZ, CY, CX = 256, 512, 512


def c27():
    rng = np.random.default_rng(3)
    w = rng.uniform(0.01, 0.03, 27)
    return tuple(float(x) for x in w)


@functools.partial(jax.jit, static_argnames=("steps", "k", "band",
                                             "ysplit"))
def run(core, steps, k, band, ysplit):
    coeffs = c27()

    def body(c, _):
        a_mz, a_pz = c[CZ - k :], c[:k]
        return seven_point_streamed_pallas(
            c, a_mz, a_pz, (CZ, CY, CX), coeffs, k, band=band,
            ysplit27=ysplit,
        ), ()

    out, _ = jax.lax.scan(body, core, None, length=steps // k)
    return out


def main():
    import sys

    sel = set(sys.argv[1].split(",")) if len(sys.argv) > 1 else None
    rng = np.random.default_rng(9)
    core = jnp.asarray(
        rng.standard_normal((CZ, CY, CX)), jnp.float32
    )

    if sel is None or "eq" in sel:
        # correctness: ysplit form == r4 form at 4 steps
        a = np.asarray(run(core, 4, 2, 4, 2))
        b = np.asarray(run(core, 4, 2, 4, 0))
        err = float(np.max(np.abs(a - b)))
        print(f"# ysplit2 vs r4 form max|diff| (4 steps): {err:.3e}",
              flush=True)
        assert err < 1e-5

    cells = CZ * CY * CX
    variants = [
        ("v0: r4 band=4 k=2", 2, 4, 0),
        ("v1: ysplit2 band=4 k=2", 2, 4, 2),
        ("v2: ysplit2 band=8 k=2", 2, 8, 2),
        ("v3: ysplit4 band=8 k=2", 2, 8, 4),
        ("v4: ysplit2 band=8 k=4", 4, 8, 2),
        ("v5: ysplit4 band=8 k=4", 4, 8, 4),
    ]
    for name, k, band, ys in variants:
        if sel is not None and name.split(":")[0] not in sel:
            continue
        try:
            lo, hi = 20 * k, 60 * k
            r_lo = time_device(run, core, lo, k, band, ys, warmup=1,
                               iters=3, fence="readback")
            r_hi = time_device(run, core, hi, k, band, ys, warmup=1,
                               iters=3, fence="readback")
            marg = (r_hi.p50 - r_lo.p50) / (hi - lo) * 1e3
            print(
                f"# {name}: marginal {marg:.3f} ms/step = "
                f"{cells / (marg * 1e-3):.3e} cells/s",
                flush=True,
            )
        except Exception as e:
            msg = str(e).split(chr(10))[0][:160]
            print(f"# {name}: FAILED {msg}", flush=True)


if __name__ == "__main__":
    main()
