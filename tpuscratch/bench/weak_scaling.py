"""Weak-scaling stencil benchmark (BASELINE config 5).

Fixed per-chip tile, growing device mesh: ideal scaling keeps per-chip
cell-updates/s constant, so efficiency(N) = rate_per_chip(N) /
rate_per_chip(1). The reference has no weak-scaling harness — its scaling
story is the qualitative capacity note at
/root/reference/mpicuda2.cu:44-47 — so this establishes the methodology
the reference lacks: same program, same per-rank work, mesh as the only
variable. On one host the mesh is virtual CPU devices (the reference's
N-ranks-on-one-box trick, mpicuda2.cu:31-32); on a slice it is the real
chip grid.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax

from tpuscratch.bench.stencil_bench import bench_stencil
from tpuscratch.bench.timing import BenchResult
from tpuscratch.runtime.mesh import make_mesh_2d
from tpuscratch.runtime.topology import factor2d


@dataclasses.dataclass(frozen=True)
class WeakScalingPoint:
    n_devices: int
    dims: tuple[int, int]
    grid: tuple[int, int]
    result: BenchResult
    halo_bytes_per_chip_step: float  # analytic, from the exchange plan
    cells_per_chip_step: int

    @property
    def per_chip_rate(self) -> float:
        return self.result.items_per_s / self.n_devices

    @property
    def comm_ratio(self) -> float:
        """Exact analytic halo bytes per computed cell per step — the
        quantity weak-scaling efficiency actually depends on. Unlike the
        measured CPU-mesh rates (virtual devices share host cores, so
        their per-chip rate collapses by construction), this number is
        meaningful on any host and transfers directly to a real slice."""
        return self.halo_bytes_per_chip_step / self.cells_per_chip_step


def halo_traffic_per_chip(
    dims: tuple[int, int],
    per_chip: tuple[int, int],
    impl: str = "xla",
    itemsize: int = 4,
) -> tuple[float, int]:
    """(off-chip halo bytes per chip per step, cells per chip per step),
    computed EXACTLY from the exchange plan: every transfer whose
    ppermute pair leaves the rank counts its send-strip bytes; self-wrap
    pairs (1-wide axes) move nothing over ICI. Deep-halo impls amortize a
    k-deep exchange over k steps."""
    from tpuscratch.halo.exchange import HaloSpec
    from tpuscratch.halo.layout import TileLayout
    from tpuscratch.runtime.topology import CartTopology

    halo, steps_per_exchange = 1, 1
    if impl.startswith("deep"):
        _, _, depth = impl.partition(":")
        halo = int(depth) if depth else 8
        steps_per_exchange = halo
    topo = CartTopology(tuple(dims), (True, True))
    lay = TileLayout(per_chip[0], per_chip[1], halo, halo)
    spec = HaloSpec(layout=lay, topology=topo)
    total = 0
    for t in spec.plan():
        strip = t.send.shape[0] * t.send.shape[1] * itemsize
        total += strip * sum(1 for s, d in t.perm if s != d)
    per_chip_bytes = total / topo.size / steps_per_exchange
    return per_chip_bytes, per_chip[0] * per_chip[1]


def halo3d_traffic_per_chip(
    dims: tuple[int, int, int],
    per_chip: tuple[int, int, int],
    itemsize: int = 4,
    depth: int = 1,
    sweeps_per_exchange: int = 1,
) -> tuple[float, int]:
    """(off-chip halo bytes per chip per sweep, cells per chip per
    sweep) for 3D solver tiles — the 2D :func:`halo_traffic_per_chip`
    one dimension up, computed EXACTLY from the exchange plan.

    ``depth=1`` prices the per-sweep faces exchange (6 slabs,
    ``halo.halo3d.FACES`` plan); ``depth>1`` prices the deep
    AXIS-SEQUENTIAL exchange (``halo.halo3d.halo_exchange3d_seq``: 6
    slabs whose extents grow by the earlier axes' ghost bands — the
    edge/corner data rides transitively), amortized over
    ``sweeps_per_exchange`` sweeps.  The s-step smoothers use
    ``depth=s, sweeps_per_exchange=s`` (Jacobi) or ``depth=2s,
    sweeps_per_exchange=s`` (red-black GS, two half-updates per sweep);
    self-wrap pairs on 1-wide axes move nothing over the wire, exactly
    as in 2D."""
    from tpuscratch.halo.halo3d import (
        HaloSpec3D,
        TileLayout3D,
        seq_exchange_wire_bytes,
    )
    from tpuscratch.runtime.topology import CartTopology

    topo = CartTopology(tuple(dims), (True, True, True))
    lay = TileLayout3D(tuple(per_chip), (depth,) * 3)
    spec = HaloSpec3D(layout=lay, topology=topo,
                      axes=("z", "row", "col"), neighbors=6)
    if depth == 1:
        total = 0
        for t in spec.plan():
            total += t.send.size * itemsize * sum(
                1 for s, d in t.perm if s != d)
        per_chip_bytes = total / topo.size
    else:
        per_chip_bytes = seq_exchange_wire_bytes(spec, itemsize)
    cells = per_chip[0] * per_chip[1] * per_chip[2]
    return per_chip_bytes / sweeps_per_exchange, cells


def bench_weak_scaling(
    per_chip: tuple[int, int] = (1024, 1024),
    steps: int = 10,
    device_counts: Optional[Sequence[int]] = None,
    impl: str = "xla",
    iters: int = 5,
    fence: str = "block",
) -> list[WeakScalingPoint]:
    """One point per device count; global grid grows with the mesh."""
    avail = len(jax.devices())
    if device_counts is None:
        device_counts = [n for n in (1, 2, 4, 8, 16) if n <= avail]
    points = []
    for n in sorted(device_counts):
        if n > avail:
            raise ValueError(f"{n} devices requested, {avail} visible")
        rows, cols = factor2d(n)
        grid = (rows * per_chip[0], cols * per_chip[1])
        mesh = make_mesh_2d((rows, cols), devices=jax.devices()[:n])
        halo_bytes, cells = halo_traffic_per_chip((rows, cols), per_chip, impl)
        points.append(
            WeakScalingPoint(
                n_devices=n,
                dims=(rows, cols),
                grid=grid,
                result=bench_stencil(
                    grid, steps, mesh=mesh, impl=impl, iters=iters, fence=fence
                ),
                halo_bytes_per_chip_step=halo_bytes,
                cells_per_chip_step=cells,
            )
        )
    return points


def efficiency(points: Sequence[WeakScalingPoint]) -> dict[int, float]:
    """Per-chip-rate ratio vs the smallest-mesh point."""
    if not points:
        raise ValueError("no points")
    base = min(points, key=lambda p: p.n_devices).per_chip_rate
    return {p.n_devices: p.per_chip_rate / base for p in points}


def report(points: Sequence[WeakScalingPoint]) -> str:
    eff = efficiency(points)
    lines = []
    for p in points:
        lines.append(
            f"{p.n_devices:3d} dev {p.dims[0]}x{p.dims[1]}  grid "
            f"{p.grid[0]}x{p.grid[1]}  {p.per_chip_rate:.3e} cells/s/chip  "
            f"eff {eff[p.n_devices] * 100:5.1f}%  "
            f"halo {p.comm_ratio:.4f} B/cell (analytic)"
        )
    return "\n".join(lines)
