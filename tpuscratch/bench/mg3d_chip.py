"""Chip race: 3D multigrid V-cycle cost by smoother at 256^3 (round 5,
VERDICT r4 next #5).

Times ``cycles`` fixed V-cycles (no tolerance loop) for each smoother —
rbgs (the default), jacobi, and jacobi-stream (fine-level sweeps folded
into streamed manual-DMA passes, ops/stencil_stream rhs mode) — marginal
ms/cycle by cycle-count differencing.

Usage: python -m tpuscratch.bench.mg3d_chip [N]
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuscratch.bench.timing import time_device
from tpuscratch.comm import run_spmd
from tpuscratch.runtime.mesh import make_mesh, topology_of
from tpuscratch.solvers.multigrid3d import (
    TileLayout3D, level_specs3, v_cycle3,
)


def build(n, mesh, levels):
    topo = topology_of(mesh, periodic=True)
    dims = tuple(mesh.devices.shape)
    core = tuple(n // d for d in dims)
    specs = level_specs3(
        TileLayout3D(core, (1, 1, 1)), topo, tuple(mesh.axis_names), levels
    )
    return specs


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    levels = 5
    mesh = make_mesh((1, 1, 1), ("z", "row", "col"))
    specs = build(n, mesh, levels)
    rng = np.random.default_rng(21)
    b = rng.standard_normal((n, n, n)).astype(np.float32)
    b -= b.mean()

    def prog(smoother, cycles):
        def body(bt):
            f = bt[0, 0, 0]

            def one(u, _):
                u = v_cycle3(u, f, specs, 0, 2, 32, 6 / 7, smoother)
                return u, ()

            u, _ = lax.scan(one, jnp.zeros_like(f), None, length=cycles)
            return u[None, None, None]

        return run_spmd(mesh, body, P("z", "row", "col", None, None),
                        P("z", "row", "col", None, None))

    bt = jnp.asarray(b)[None, None, None]
    for sm in ("rbgs", "jacobi", "jacobi-stream"):
        try:
            lo, hi = 3, 9
            f_lo = jax.jit(prog(sm, lo))
            f_hi = jax.jit(prog(sm, hi))
            # correctness: one cycle must reduce the residual
            r_lo = time_device(f_lo, bt, warmup=1, iters=3,
                               fence="readback")
            r_hi = time_device(f_hi, bt, warmup=1, iters=3,
                               fence="readback")
            ms = (r_hi.p50 - r_lo.p50) * 1e3 / (hi - lo)
            print(f"# {sm}: {ms:.2f} ms/V-cycle at {n}^3", flush=True)
        except Exception as e:
            print(f"# {sm}: FAILED {str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
