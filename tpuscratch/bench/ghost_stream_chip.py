"""Chip race: the 2D streamed kernel's GHOST-COLUMN mode (round 5).

Validates on real silicon that the ghost-mode Mosaic program compiles
and runs, checks it bit-for-bit against the wrap-mode kernel on a
periodic torus (where both are defined and must agree), and measures
the marginal ms/step by step-count differencing at 8192^2 — the number
VERDICT r4 item 1 asks for (>= 1e11 cells/s target; wrap-mode
stream:32 = 1.89e11, BASELINE row 4).

Degenerate single-chip stand-in for the 4x4 mesh: gl/gr are built from
the core's own wrap slices (exactly what a rank on a periodic torus
receives from its neighbors), so the kernel executes the full
ghost-mode code path — per-band slab patching, the [core | gr | gl]
window, the clipped final substep — with zero hops.

Usage: python -m tpuscratch.bench.ghost_stream_chip [N] [depth]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpuscratch.bench.timing import time_device
from tpuscratch.ops.stencil_stream import nine_point_streamed_2d

C5 = (0.25, 0.25, 0.25, 0.25, 0.0)


def ghost_pass(core, H, W, k, coeffs, mode):
    """One depth-k pass; ghosts from the core's own wrap slices."""
    a_top, a_bot = core[H - k :], core[:k]
    if mode == "wrap":
        return nine_point_streamed_2d(
            core, a_top, a_bot, (H, W), coeffs, k
        )
    # ghost-column slabs spanning global rows [-k, H+k), periodic wrap:
    # gl = cols [-k, 0) = cols [W-k, W); corner rows wrap too
    colsL = core[:, W - k :]
    colsR = core[:, :k]
    gl = jnp.concatenate([colsL[H - k :], colsL, colsL[:k]], axis=0)
    gr = jnp.concatenate([colsR[H - k :], colsR, colsR[:k]], axis=0)
    return nine_point_streamed_2d(
        core, a_top, a_bot, (H, W), coeffs, k, gl=gl, gr=gr
    )


def run(core, steps, k, mode, coeffs=C5):
    H, W = core.shape

    def body(c, _):
        return ghost_pass(c, H, W, k, coeffs, mode), ()

    out, _ = jax.lax.scan(body, core, None, length=steps // k)
    return out


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    rng = np.random.default_rng(5)

    # 1. equality: ghost mode == wrap mode on the torus, 1024^2
    small = jnp.asarray(rng.standard_normal((1024, 1024)), jnp.float32)
    t0 = time.time()
    a = np.asarray(run(small, 2 * k, k, "ghost"))
    print(f"# ghost-mode compile+run 1024^2: {time.time() - t0:.1f}s")
    b = np.asarray(run(small, 2 * k, k, "wrap"))
    err = float(np.max(np.abs(a - b)))
    print(f"# ghost vs wrap max|diff| at 1024^2, {2 * k} steps: {err:.3e}")
    assert err < 1e-5, "ghost mode disagrees with wrap mode"

    # 2. marginal rate at N^2 by step-count differencing
    big = jnp.asarray(rng.standard_normal((N, N)), jnp.float32)
    for mode in ("wrap", "ghost"):
        lo, hi = 5 * k, 20 * k
        jit_lo = jax.jit(lambda c, lo=lo, mode=mode: run(c, lo, k, mode))
        jit_hi = jax.jit(lambda c, hi=hi, mode=mode: run(c, hi, k, mode))
        ms_lo = time_device(jit_lo, big, warmup=1, iters=3,
                            fence="readback").p50 * 1e3
        ms_hi = time_device(jit_hi, big, warmup=1, iters=3,
                            fence="readback").p50 * 1e3
        marg = (ms_hi - ms_lo) / (hi - lo)
        rate = N * N / (marg * 1e-3)
        print(
            f"# {mode}:{k} {N}^2: p50 {ms_lo:.1f}/{ms_hi:.1f} ms at "
            f"{lo}/{hi} steps -> marginal {marg:.3f} ms/step = "
            f"{rate:.3e} cells/s"
        )


if __name__ == "__main__":
    main()
