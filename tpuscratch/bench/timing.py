"""Timing conventions for device benchmarks.

Keeps the reference's two instrumentation idioms (SURVEY.md §5):
- **max-min span**: every rank stamps begin/end; the reported wall time is
  ``max(ends) - min(begins)`` across ranks (mpicuda3.cu:315-325). Kept as a
  pure function over per-process timestamp lists.
- **segmented timing**: bracket exactly the phase being measured —
  MPI_Wtime around the transfer, separate from the D2H copy
  (mpi-pingpong-gpu.cpp:51-57); the NO_GPU_MALLOC_TIME carve-out excluding
  allocation (mpicuda3.cu:221-240). Under jax the equivalent discipline is
  ``block_until_ready`` brackets with compile (warmup) excluded — dispatch
  is async exactly like CUDA launches, so un-bracketed timers measure
  nothing, the same pitfall the reference's clock() placement dodges.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax

# canonical home is obs.metrics (the observability subsystem owns the
# timing-merge conventions); these aliases keep bench callers working
from tpuscratch.obs.metrics import percentile, span_max_min  # noqa: F401


@dataclasses.dataclass(frozen=True)
class BenchResult:
    name: str
    times_s: tuple[float, ...]
    bytes_moved: int = 0
    items: int = 0

    @property
    def p50(self) -> float:
        return percentile(self.times_s, 50)

    @property
    def best(self) -> float:
        return min(self.times_s)

    @property
    def gbps(self) -> float:
        """GB/s at the median."""
        return self.bytes_moved / self.p50 / 1e9 if self.bytes_moved else 0.0

    @property
    def items_per_s(self) -> float:
        return self.items / self.p50 if self.items else 0.0

    def summary(self) -> str:
        parts = [f"{self.name}: p50 {self.p50 * 1e3:.3f} ms"]
        if self.bytes_moved:
            parts.append(f"{self.gbps:.2f} GB/s")
        if self.items:
            parts.append(f"{self.items_per_s:.3e} items/s")
        return ", ".join(parts)


def _fence(out, mode: str):
    """Wait until ``out`` is actually computed.

    ``"block"`` trusts jax.block_until_ready. ``"readback"`` additionally
    copies one element of the first output leaf to the host — the only
    fence some remote-tunnel PJRT transports honor reliably (observed:
    block_until_ready returning in ~20us for programs whose device time
    is provably milliseconds). The 4-byte D2H costs one transport round
    trip, so readback-fenced runs must amortize it with enough work per
    iteration.
    """
    jax.block_until_ready(out)
    if mode == "readback":
        import numpy as np

        leaf = jax.tree_util.tree_leaves(out)[0]
        # one-element slice, NOT ravel(): a reshape of a sharded array
        # would dispatch a cross-device gather inside the timed region
        np.asarray(leaf[(0,) * leaf.ndim])
    elif mode != "block":
        raise ValueError(f"unknown fence mode {mode!r}")
    return out


def time_device(
    fn: Callable,
    *args,
    iters: int = 10,
    warmup: int = 2,
    name: str = "bench",
    bytes_moved: int = 0,
    items: int = 0,
    fence: str = "block",
) -> BenchResult:
    """Fence-bracketed per-iteration timings.

    ``warmup`` runs (compile + cache effects) are excluded, the analogue of
    NO_GPU_MALLOC_TIME excluding one-time setup from the window. ``fence``
    picks the completion barrier — see ``_fence``.
    """
    for _ in range(warmup):
        _fence(fn(*args), fence)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _fence(fn(*args), fence)
        times.append(time.perf_counter() - t0)
    return BenchResult(
        name=name, times_s=tuple(times), bytes_moved=bytes_moved, items=items
    )
