"""Pingpong latency/bandwidth probe over mesh links.

The reference's probe sends one round trip of N doubles GPU-to-GPU and
times it with MPI_Wtime, separately timing the D2H copy, verifying the
echo, and printing PASSED/FAILED with sizes and times
(/root/reference/test-benchmark/mpi-pingpong-gpu.cpp:24-87; async variant
with host-staging ablations at mpi-pingpong-gpu-async.cpp:43-106). Here the
round trip is a pair of ppermutes between two mesh ranks (ICI on TPU); the
device-direct property is free (jax.Arrays live on device), and the
HOST_COPY ablation becomes an explicit device->host->device staging timing
so the comparison the reference makes is still measurable.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.comm import run_spmd
from tpuscratch.comm.p2p import pingpong

DEFAULT_SIZES = tuple(8 * 4**i for i in range(13))  # 8 B ... 128 MiB (f32)


def pingpong_program(mesh: Mesh, axis: str, n_elems: int, a: int = 0, b: int = 1, rounds: int = 1):
    """Compiled SPMD pingpong: rank a's shard bounces to b and back."""
    return run_spmd(
        mesh,
        lambda x: pingpong(x, axis, a=a, b=b, rounds=rounds),
        P(axis),
        P(axis),
    )


def verify_echo(mesh: Mesh, axis: str, n_elems: int) -> bool:
    """PASSED/FAILED self-check: the echoed payload equals the original
    (mpi-pingpong-gpu.cpp:58-61)."""
    n = mesh.devices.size
    payload = np.zeros((n, n_elems), dtype=np.float32)
    payload[0] = np.random.default_rng(0).standard_normal(n_elems)
    # b = n-1: partner rank 1 normally, self-loop on a degenerate
    # 1-device mesh (the full code path, zero-hop transport)
    f = pingpong_program(mesh, axis, n_elems, b=n - 1)
    out = np.asarray(f(jnp.asarray(payload.reshape(-1)))).reshape(n, n_elems)
    return bool((out[0] == payload[0]).all())


def sweep(
    mesh: Mesh,
    axis: str = "x",
    sizes_bytes: Sequence[int] = DEFAULT_SIZES,
    rounds: int = 1,
    iters: int = 10,
    fence: str = "block",
) -> list[BenchResult]:
    """Latency/BW sweep over message sizes (8 B - 128 MB by default).

    One round trip moves the payload twice, so bytes_moved = 2 * size *
    rounds. p50 over ``iters`` timed repetitions after warmup.
    """
    n = mesh.devices.size
    results = []
    for size in sizes_bytes:
        n_elems = max(1, size // 4)  # f32 payload
        f = pingpong_program(mesh, axis, n_elems, b=n - 1, rounds=rounds)
        x = jnp.zeros(n * n_elems, dtype=jnp.float32)
        results.append(
            time_device(
                f,
                x,
                iters=iters,
                warmup=2,
                fence=fence,
                name=f"pingpong {size}B",
                bytes_moved=2 * n_elems * 4 * rounds,
            )
        )
    return results


def host_staging_roundtrip(n_elems: int, iters: int = 10) -> BenchResult:
    """The HOST_COPY ablation: device -> host -> device staging, timed —
    what GPU-direct (device-resident arrays) saves
    (mpi-pingpong-gpu-async.cpp:59-70)."""
    x = jnp.zeros(n_elems, dtype=jnp.float32)
    jax.block_until_ready(x)

    def stage(v):
        host = np.asarray(v)          # D2H
        return jax.device_put(host)   # H2D

    return time_device(
        stage, x, iters=iters, warmup=1,
        name=f"host staging {n_elems * 4}B", bytes_moved=2 * n_elems * 4,
    )


def _buffer_staging(view: np.ndarray, n_elems: int, iters: int, label: str) -> BenchResult:
    """device -> host -> persistent staging buffer -> device, with the
    buffer's allocator as the only variable."""
    x = jnp.zeros(n_elems, dtype=jnp.float32)
    jax.block_until_ready(x)

    def stage(v):
        np.copyto(view, np.asarray(v))   # D2H then memcpy into the buffer
        return jax.device_put(view)      # H2D out of it

    return time_device(
        stage, x, iters=iters, warmup=1,
        name=f"{label} staging {n_elems * 4}B",
        bytes_moved=2 * n_elems * 4,
    )


def native_pool_staging_roundtrip(n_elems: int, iters: int = 10) -> BenchResult:
    """The reference's ``host_allocator`` ablation: stage through the
    native pooled page-aligned (mlocked where permitted) buffer
    (native/src/host_pool.cpp; host_allocator.h:58-93 is the CUDA
    counterpart, exercised the same way by
    mpi-pingpong-gpu-async.cpp:43-49).

    Compare against ``pageable_buffer_staging_roundtrip`` — identical
    copy structure, only the buffer's allocator differs. (jax offers no
    D2H-into-caller-buffer API, so unlike the reference's
    cudaMemcpy-into-pinned path both variants pay an extra host memcpy;
    the A/B isolates the allocator, which is what the PAGE_LOCKED switch
    ablates in the reference.)"""
    from tpuscratch.native import hostpool

    buf = hostpool.default_pool().alloc(n_elems * 4)
    try:
        view = buf.view(np.float32, (n_elems,))
        try:
            return _buffer_staging(view, n_elems, iters, "native-pool")
        finally:
            del view  # the buffer refuses to free while views are alive
    finally:
        buf.free()


def pageable_buffer_staging_roundtrip(n_elems: int, iters: int = 10) -> BenchResult:
    """Control for the native-pool ablation: same persistent-staging-buffer
    copy structure through a plain pageable numpy allocation."""
    view = np.empty(n_elems, dtype=np.float32)
    return _buffer_staging(view, n_elems, iters, "pageable-buffer")


def pinned_staging_roundtrip(
    n_elems: int, pinned: bool = True, iters: int = 10
) -> BenchResult:
    """The PAGE_LOCKED ablation: stage through page-locked vs pageable
    host memory spaces (mpi-pingpong-gpu-async.cpp:43-49) — here XLA
    memory kinds ``pinned_host`` vs ``unpinned_host``."""
    from tpuscratch.runtime import memory

    x = jnp.zeros(n_elems, dtype=jnp.float32)
    jax.block_until_ready(x)
    label = "pinned" if pinned else "pageable"
    return time_device(
        lambda v: memory.host_roundtrip(v, pinned=pinned),
        x, iters=iters, warmup=1,
        name=f"{label} staging {n_elems * 4}B", bytes_moved=2 * n_elems * 4,
    )
