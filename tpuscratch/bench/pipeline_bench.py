"""Pipeline-parallel schedule benchmark: measured vs analytic bubble.

GPipe's idle fraction is (n-1)/(M+n-1) by construction
(parallel/pipeline.py). This bench validates that the EXECUTED schedule
has that shape, not just the formula: wall time of a pipelined run must
scale as ticks = M + n - 1 (one extra tick per extra microbatch), not as
M * n (a degenerate sequential execution). The per-tick cost is taken
from the slope between two microbatch counts, and

    measured_bubble(M) = 1 - M * tick_cost / wall(M)

— the share of wall time beyond the M "useful" ticks. For a healthy
pipeline this lands near the analytic value (fixed dispatch overhead
pushes it slightly above); a schedule that silently serialized would
report ~0 while the analytic value is large, so the comparison catches
breakage in either direction.

The reference has no pipeline (SURVEY.md §2.7); its measurement idiom —
wall-clock spans around the hot loop, reported beside the configuration
(mpicuda3.cu:315-325) — is what this follows.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpuscratch.bench.timing import time_device
from tpuscratch.parallel import ShardingPlan, bubble_fraction
from tpuscratch.runtime.mesh import make_mesh


@dataclasses.dataclass(frozen=True)
class PipelineBubbleResult:
    n_stages: int
    n_micro: int
    wall_s: float          # p50 wall for n_micro microbatches
    tick_s: float          # marginal cost of one extra microbatch (tick)
    measured_bubble: float
    analytic_bubble: float
    proxy: bool            # True when devices are virtual (CPU mesh)

    def summary(self) -> str:
        return (
            f"pipeline {self.n_stages} stages x {self.n_micro} micro: "
            f"wall {self.wall_s * 1e3:.2f} ms, tick {self.tick_s * 1e6:.0f} us, "
            f"bubble measured {self.measured_bubble:.3f} vs "
            f"analytic {self.analytic_bubble:.3f}"
            + (" [cpu-mesh proxy]" if self.proxy else "")
        )


def bench_pipeline_bubble(
    n_micro: int = 8,
    feature: int = 256,
    iters: int = 10,
    axis: str = "stage",
    mesh=None,
    fence: str = "block",
) -> PipelineBubbleResult:
    """Measure the GPipe schedule's bubble on the available devices.

    Runs the same stage chain at ``n_micro`` and ``2 * n_micro``
    microbatches; the wall-time difference prices one tick.

    The program is built THROUGH a ``ShardingPlan``
    (``plan.pipeline_program``), not by calling ``pipeline_apply``
    directly — so the bench measures the same ``gpipe_scan`` schedule
    the trainer's pipelined loss runs, reached through the same plan
    validation the trainer uses.

    On a virtual CPU mesh the default stage count is capped at the HOST
    CORE count: stages can only overlap on real execution units, and
    timing more virtual devices than cores measures the scheduler, not
    the schedule (the weak-scaling bench has the same caveat). Results
    are flagged ``proxy`` off-TPU either way — the numbers that matter
    come from a real multi-chip slice.
    """
    proxy = jax.default_backend() != "tpu"
    if mesh is None:
        devs = jax.devices()
        if proxy:
            import os

            devs = devs[: max(2, min(len(devs), os.cpu_count() or 1))]
        # dp/sp are trivial here, but the mesh carries them so the SAME
        # ShardingPlan type the trainer consumes drives this bench
        mesh = make_mesh((1, 1, len(devs)), ("dp", "sp", axis), devs)
    elif "dp" not in mesh.axis_names or "sp" not in mesh.axis_names:
        # a legacy 1-axis stage mesh: rebuild with trivial dp/sp axes
        # over the same devices so the plan's axis roles resolve
        mesh = make_mesh((1, 1, mesh.devices.size), ("dp", "sp", axis),
                         list(mesh.devices.flat))
    n = mesh.shape[axis]
    plan = ShardingPlan(mesh, pp=axis, n_micro=n_micro)
    rng = np.random.default_rng(0)
    Ws = jnp.asarray(
        rng.standard_normal((n, feature, feature)).astype(np.float32) * 0.1
    )

    def stage(W, x):
        return jnp.tanh(x @ W[0])

    def program(M):
        f = plan.pipeline_program(stage)
        micro = jnp.asarray(
            rng.standard_normal((M, feature)).astype(np.float32)
        )
        return f, micro

    walls = {}
    for M in (n_micro, 2 * n_micro):
        f, micro = program(M)
        r = time_device(
            f, Ws, micro, iters=iters, warmup=2, fence=fence,
            name=f"pipeline n={n} M={M}",
        )
        walls[M] = r.p50

    tick = max((walls[2 * n_micro] - walls[n_micro]) / n_micro, 1e-12)
    measured = 1.0 - (n_micro * tick) / walls[n_micro]
    return PipelineBubbleResult(
        n_stages=n,
        n_micro=n_micro,
        wall_s=walls[n_micro],
        tick_s=tick,
        measured_bubble=measured,
        analytic_bubble=bubble_fraction(n, n_micro),
        proxy=proxy,
    )


def main() -> int:
    for M in (4, 8, 32):
        print(bench_pipeline_bubble(n_micro=M).summary())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
