"""Distributed-FFT / matmul-DFT throughput benchmark (beyond reference).

Measures the pair-plane matmul DFT (parallel/fft.py) — the transform
backend TPU runtimes without complex support use — with the repo's
standard methodology: many transform round trips folded into one
compiled scan (amortizing the tunnel's fixed per-invocation cost), a
loop-carried perturbation that is zero in value but opaque to the
compiler (so rounds cannot be hoisted), and readback fencing.

Each round is a forward + inverse 2D transform (keeps the carry's
magnitude stable across arbitrarily many rounds and self-checks the
round trip at the end). FLOP accounting: one 2D pair-DFT direction is 4
real (N,N)@(N,N) matmuls per axis x 2 axes = 8 N^3 multiply-adds =
16 N^3 FLOPs, so a round trip counts 32 N^3 FLOPs.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.comm import run_spmd
from tpuscratch.parallel.fft import fft2_sharded_pair


def dft_roundtrip_program(mesh: Mesh, axis: str, rounds: int,
                          method: str = "direct"):
    """jit'd fn(re, im) running ``rounds`` fwd+inv pair-FFTs in one scan."""

    def body(re, im):
        def step(carry, _):
            r, i = carry
            fr, fi = fft2_sharded_pair(r, i, axis, method=method)
            br, bi = fft2_sharded_pair(
                fr, fi, axis, inverse=True, method=method
            )
            # loop-carried zero (mean of the difference from the input,
            # which IS zero up to rounding) the compiler can't fold away
            eps = jnp.mean(br - r) * 0.0
            return (br + eps, bi + eps), ()

        (re, im), _ = lax.scan(step, (re, im), None, length=rounds)
        return re, im

    return run_spmd(mesh, body, (P(axis), P(axis)), (P(axis), P(axis)))


def pair_fft_flops(n: int, method: str, rounds: int) -> int:
    """FLOPs of ``rounds`` fwd+inv 2D pair transforms at the given
    method's OWN cost: direct = 32 n^3 (4 real (n,n)@(n,n) matmuls per
    axis per direction), four-step = 32 n^2 (n1+n2) for the two sub-DFT
    einsum batches (twiddle's O(n^2) elementwise is noise). Cross-method
    comparisons must use seconds per round, not these."""
    from tpuscratch.parallel.fft import _split, resolve_method

    if resolve_method(n, method) == "four-step":
        n1, n2 = _split(n)
        return 32 * n * n * (n1 + n2) * rounds
    return 32 * n**3 * rounds


def bench_dft(
    n: Optional[int] = None,
    rounds: Optional[int] = None,
    iters: int = 3,
    mesh: Optional[Mesh] = None,
    fence: str = "readback",
    method: str = "direct",
) -> BenchResult:
    """Pair-FFT round-trip throughput on an n x n f32 pair.

    Defaults size the scan so the chip work dwarfs the tunnel's fixed
    ~150-200 ms per-invocation cost: 1000 rounds at 1024^2 is 3.4e13
    FLOPs (~1.1 s marginal at the measured rate) vs a few-round smoke
    size on CPU backends. ``method`` selects the local transform
    (direct dense DFT / four-step / auto); ``items`` is that method's
    own FLOP count (see :func:`pair_fft_flops`), so compare methods by
    ``p50``, not ``items_per_s``.
    """
    from tpuscratch.runtime.mesh import make_mesh_1d

    on_tpu = jax.default_backend() == "tpu"
    n = n if n is not None else (1024 if on_tpu else 64)
    rounds = rounds if rounds is not None else (1000 if on_tpu else 3)
    mesh = mesh if mesh is not None else make_mesh_1d("x", 1)
    (axis,) = mesh.axis_names
    rng = np.random.default_rng(0)
    re = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))
    im = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))
    prog = dft_roundtrip_program(mesh, axis, rounds, method)
    # verify the round trip BEFORE timing (this run doubles as compile
    # warmup; time_device's own warmup then costs only execution)
    out = prog(re, im)
    err = float(jnp.max(jnp.abs(out[0] - re)))
    if err > 1e-2 * float(jnp.max(jnp.abs(re))):
        raise AssertionError(f"round trip drifted: err {err}")
    flops = pair_fft_flops(n, method, rounds)
    return time_device(
        prog, re, im, iters=iters, warmup=1, fence=fence,
        name=f"pair-FFT[{method}] fwd+inv {n}x{n} x{rounds}", items=flops,
    )


def main() -> int:
    r = bench_dft()
    tflops = r.items_per_s / 1e12
    print(f"{r.summary()} -> {tflops:.1f} TFLOP/s (precision=HIGHEST f32)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
