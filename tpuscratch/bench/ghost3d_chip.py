"""Chip probe: round-5 kernel modes that only CPU-interpret tests had
covered — (a) the 3D ghost-strip streamed kernel (distributed y/x on a
degenerate 1-chip mesh: strips built from the core's own wrap slices,
exercising the full ghost code path — per-band strip slicing, in-kernel
aging, corner strip), (b) the 9-point HBM-banded DMA kernel
(columns-first schedule + corner-extended ghost columns).

Both are compile-risk probes (Mosaic accepts things in interpret mode
it rejects on silicon) + bit-exactness checks + a marginal rate each.

Usage: python -m tpuscratch.bench.ghost3d_chip
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np

from tpuscratch.bench.timing import time_device
from tpuscratch.ops.stencil_stream import seven_point_streamed_pallas

CZ, CY, CX = 256, 512, 512
C7 = (1 / 6, 1 / 6, 1 / 6, 1 / 6, 1 / 6, 1 / 6, 0.0)


@functools.partial(jax.jit, static_argnames=("steps", "k", "mode"))
def run3d(core, steps, k, mode):
    def body(c, _):
        a_mz, a_pz = c[CZ - k :], c[:k]
        kw = {}
        if mode in ("gy", "gyx"):
            colsY = c[:, CY - k :, :]  # my -y ghosts = wrap rows
            top = jnp.concatenate(
                [colsY[CZ - k :], colsY, colsY[:k]], axis=0)
            colsY2 = c[:, :k, :]
            bot = jnp.concatenate(
                [colsY2[CZ - k :], colsY2, colsY2[:k]], axis=0)
            kw["gy"] = jnp.concatenate([bot, top], axis=1)  # [plus|minus]
        if mode in ("gx", "gyx"):
            colsL = c[:, :, CX - k :]
            gl = jnp.concatenate([colsL[CZ - k :], colsL, colsL[:k]],
                                 axis=0)
            colsR = c[:, :, :k]
            gr = jnp.concatenate([colsR[CZ - k :], colsR, colsR[:k]],
                                 axis=0)
            kw["gx"] = jnp.concatenate([gr, gl], axis=2)
        if mode == "gyx":
            cc = c[:, CY - k :, CX - k :]
            # corner quadrants [y-plus | y-minus] x [x-plus | x-minus]
            def zext(blk):
                return jnp.concatenate(
                    [blk[CZ - k :], blk, blk[:k]], axis=0)

            qpp = zext(c[:, :k, :k])
            qpm = zext(c[:, :k, CX - k :])
            qmp = zext(c[:, CY - k :, :k])
            qmm = zext(cc)
            kw["gc"] = jnp.concatenate([
                jnp.concatenate([qpp, qpm], axis=2),
                jnp.concatenate([qmp, qmm], axis=2),
            ], axis=1)
        return seven_point_streamed_pallas(
            c, a_mz, a_pz, (CZ, CY, CX), C7, k, **kw
        ), ()

    out, _ = jax.lax.scan(body, core, None, length=steps // k)
    return out


def probe_3d():
    rng = np.random.default_rng(33)
    core = jnp.asarray(rng.standard_normal((CZ, CY, CX)), jnp.float32)
    base = np.asarray(run3d(core, 4, 2, "wrap"))
    for mode in ("gy", "gx", "gyx"):
        try:
            got = np.asarray(run3d(core, 4, 2, mode))
            err = float(np.max(np.abs(got - base)))
            sys.stdout.write(
                f"# 3D ghost mode {mode}: compiles, max|diff| vs wrap "
                f"= {err:.3e}\n")
            sys.stdout.flush()
            assert err < 1e-5
        except Exception as e:
            sys.stdout.write(
                f"# 3D ghost mode {mode}: FAILED {str(e)[:160]}\n")
            sys.stdout.flush()
            return
    # rate for the full gyx mode vs wrap (k=4, marginal)
    for mode in ("wrap", "gyx"):
        lo, hi = 40, 120
        r_lo = time_device(run3d, core, lo, 4, mode, warmup=1, iters=3,
                           fence="readback")
        r_hi = time_device(run3d, core, hi, 4, mode, warmup=1, iters=3,
                           fence="readback")
        ms = (r_hi.p50 - r_lo.p50) * 1e3 / (hi - lo)
        sys.stdout.write(
            f"# 3D stream:4 {mode}: {ms:.3f} ms/step = "
            f"{CZ * CY * CX / (ms * 1e-3):.3e} cells/s\n")
        sys.stdout.flush()


def probe_hbm9():
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.halo.driver import decompose
    from tpuscratch.halo.exchange import HaloSpec
    from tpuscratch.halo.layout import TileLayout
    from tpuscratch.halo.stencil import run_stencil
    from tpuscratch.ops.halo_dma import run_stencil_dma_hbm
    from tpuscratch.runtime.mesh import make_mesh_2d
    from tpuscratch.runtime.topology import CartTopology

    H = W = 2048
    c9 = (0.15, 0.15, 0.1, 0.1, 0.05, 0.05, 0.08, 0.07, 0.25)
    mesh = make_mesh_2d((1, 1))
    topo = CartTopology((1, 1), (True, True))
    lay = TileLayout(H, W, 1, 1)
    spec = HaloSpec(layout=lay, topology=topo, neighbors=8)
    rng = np.random.default_rng(34)
    world = rng.standard_normal((H, W)).astype(np.float32)
    tiles = jnp.asarray(decompose(world, topo, lay))

    outs = {}
    for name, fn in (
        ("xla", lambda t: run_stencil(t, spec, 3, c9)),
        ("hbm9", lambda t: run_stencil_dma_hbm(t, spec, 3, c9)),
    ):
        try:
            f = run_spmd(
                mesh, lambda x, fn=fn: fn(x[0, 0])[None, None],
                P("row", "col", None, None), P("row", "col", None, None),
            )
            outs[name] = np.asarray(f(tiles))[:, :, 1:-1, 1:-1]
        except Exception as e:
            sys.stdout.write(f"# hbm 9-point {name}: FAILED "
                             f"{str(e)[:160]}\n")
            sys.stdout.flush()
            return
    err = float(np.max(np.abs(outs["hbm9"] - outs["xla"])))
    sys.stdout.write(
        f"# hbm 9-point 2048^2 x3 steps on chip: compiles, max|diff| "
        f"vs xla = {err:.3e}\n")
    sys.stdout.flush()
    assert err < 1e-4


if __name__ == "__main__":
    probe_3d()
    probe_hbm9()
