"""Collective microbenchmark sweep — the nccl-tests analogue.

The reference exercises its backend's collectives ad hoc inside programs
(Gather in mpi6.cpp:89, Reduce in mpicuda2.cu:293, Allreduce in
mpi9.cpp:51-54); the standard way to characterize a comm backend today is
a per-collective bandwidth sweep (nccl-tests / its TPU equivalents). This
module sweeps the framework's five collective shapes over message sizes
with the repo's fenced-timing methodology and reports **bus bandwidth**
— algorithm bandwidth scaled by the data each link must actually carry —
so numbers are comparable across collectives and device counts:

    allreduce       busBW = algBW * 2(n-1)/n
    all_gather      busBW = algBW * (n-1)/n    (size = the gathered total)
    reduce_scatter  busBW = algBW * (n-1)/n
    all_to_all      busBW = algBW * (n-1)/n
    ppermute ring   busBW = algBW             (every link carries the shard)

Each op chains ``rounds`` times through a ``lax.scan`` whose carry feeds
the next round, so a multi-round measurement cannot be constant-folded
or overlapped away; shape-changing collectives are folded back to the
input shape inside the round by purely LOCAL ops (slice / tile), so the
round's only collective traffic is the op under test.

On this repo's hardware the sweep is a CPU-mesh proxy (one real chip =
no links); the harness is the deliverable, ready to re-run on a slice.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.comm import run_spmd

#: per-device payload sizes, 1 KiB .. 4 MiB f32 by default
DEFAULT_SIZES = tuple(1024 * 4**i for i in range(7))

COLLECTIVES = ("psum", "all_gather", "psum_scatter", "all_to_all", "ppermute")


def _round_fn(name: str, axis: str, n: int):
    """One chained round: local shard -> same-shaped local shard."""
    if name == "psum":
        # mean keeps the carry's scale stable across rounds
        return lambda x: lax.psum(x, axis) * (1.0 / n)
    if name == "all_gather":
        # gather the full axis, keep my stripe as the next carry
        def f(x):
            full = lax.all_gather(x, axis, tiled=True)
            i = lax.axis_index(axis)
            return lax.dynamic_slice_in_dim(full, i * x.shape[0], x.shape[0])
        return f
    if name == "psum_scatter":
        # scatter-reduce to 1/n; restore the carry shape LOCALLY (tile) so
        # the round's only collective traffic is the op under test
        def f(x):
            piece = lax.psum_scatter(x, axis, tiled=True) * (1.0 / n)
            return jnp.tile(piece, n)
        return f
    if name == "all_to_all":
        return lambda x: lax.all_to_all(
            x, axis, split_axis=0, concat_axis=0, tiled=True
        )
    if name == "ppermute":
        def f(x):
            perm = [(i, (i + 1) % n) for i in range(n)]
            return lax.ppermute(x, axis, perm)
        return f
    raise ValueError(f"unknown collective {name!r}; have {COLLECTIVES}")


def _bus_bytes(name: str, n: int, shard_bytes: int, rounds: int) -> int:
    """Bytes-per-link-convention (nccl-tests busBW) for one sweep point."""
    if name == "psum":
        per_round = 2 * (n - 1) * shard_bytes // n
    elif name == "all_gather":
        # convention applies (n-1)/n to the GATHERED total (n * shard):
        # each link in a ring gather really carries (n-1) shards
        per_round = (n - 1) * shard_bytes
    elif name in ("psum_scatter", "all_to_all"):
        per_round = (n - 1) * shard_bytes // n
    elif name == "ppermute":
        per_round = shard_bytes
    else:
        raise ValueError(name)
    return per_round * rounds


def collective_program(mesh: Mesh, axis: str, name: str, rounds: int):
    """Compiled SPMD program: ``rounds`` chained executions of ``name``."""
    n = mesh.devices.size
    step = _round_fn(name, axis, n)

    def body(x):
        def scan_step(carry, _):
            return step(carry), ()

        out, _ = lax.scan(scan_step, x, None, length=rounds)
        return out

    return run_spmd(mesh, body, P(axis), P(axis))


def verify(mesh: Mesh, axis: str = "x", n_elems: int = 256) -> bool:
    """PASSED/FAILED self-check: one round of every collective against
    numpy (the reference's echo-verify convention,
    mpi-pingpong-gpu.cpp:58-61)."""
    n = mesh.devices.size
    rng = np.random.default_rng(0)
    world = rng.standard_normal((n, n_elems)).astype(np.float32)
    flat = jnp.asarray(world.reshape(-1))
    ok = True
    for name in COLLECTIVES:
        out = np.asarray(collective_program(mesh, axis, name, 1)(flat))
        out = out.reshape(n, n_elems)
        if name == "psum":
            expect = np.broadcast_to(world.mean(0), (n, n_elems))
        elif name == "all_gather":
            expect = world  # gather-then-keep-my-stripe is the identity
        elif name == "psum_scatter":
            # rank r holds its scattered piece (mean of everyone's r-th
            # slice), tiled back to the carry shape locally
            pieces = world.mean(0).reshape(n, n_elems // n)
            expect = np.stack([np.tile(pieces[r], n) for r in range(n)])
        elif name == "all_to_all":
            blocks = world.reshape(n, n, n_elems // n)
            expect = blocks.transpose(1, 0, 2).reshape(n, n_elems)
        else:  # ppermute ring shift
            expect = np.roll(world, 1, axis=0)
        ok &= bool(np.allclose(out, expect, atol=1e-5))
    return ok


def sweep(
    mesh: Mesh,
    axis: str = "x",
    names: Sequence[str] = COLLECTIVES,
    sizes_bytes: Sequence[int] = DEFAULT_SIZES,
    rounds: int = 10,
    iters: int = 10,
    fence: str = "block",
) -> list[BenchResult]:
    """Per-collective bandwidth sweep; GB/s in the results is busBW."""
    n = mesh.devices.size
    results = []
    for name in names:
        for size in sizes_bytes:
            n_elems = max(n, size // 4 // n * n)  # shard size, axis-divisible
            f = collective_program(mesh, axis, name, rounds)
            x = jnp.zeros(n * n_elems, dtype=jnp.float32)
            results.append(
                time_device(
                    f, x, iters=iters, warmup=2, fence=fence,
                    name=f"{name} {n_elems * 4}B x{rounds}",
                    bytes_moved=_bus_bytes(name, n, n_elems * 4, rounds),
                )
            )
    return results


def main() -> int:
    from tpuscratch.runtime.hostenv import ensure_devices

    jax = ensure_devices(8)
    from tpuscratch.runtime.mesh import make_mesh_1d

    mesh = make_mesh_1d("x", 8)
    print(f"# collective sweep on {mesh.devices.size}-device "
          f"{jax.default_backend()} mesh (busBW convention)")
    print("# echo-verify:", "PASSED" if verify(mesh) else "FAILED")
    for r in sweep(mesh):
        print(r.summary())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
