"""Seeded chaos sweep over the checkpointed trainer — the ft subsystem's
proof-by-bench: inject a deterministic fault schedule (NaN'd steps,
checkpoint-IO failures, simulated preemptions), run the trainer under
``supervise`` with the guard ladder on, and report what the stack did
with every fault: retried, skipped, clipped, rolled back, or restarted
— nothing aborted.

    python -m tpuscratch.bench.chaos_sweep [--seeds=4] [--steps=24]

Also measures guard overhead: the guarded compiled step (finiteness
reduce + spike check + clip select folded in) timed against the plain
step on the same 2x2 CPU mesh — the acceptance budget is < 3% of step
time.
"""

from __future__ import annotations

import shutil
import tempfile
import time


def _setup():
    from tpuscratch.runtime.hostenv import force_cpu_devices

    force_cpu_devices(4)
    from tpuscratch.models.transformer import TransformerConfig
    from tpuscratch.runtime.mesh import make_mesh

    mesh = make_mesh((2, 2), ("dp", "sp"))
    cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2, d_ff=32,
                            capacity_factor=2.0)
    return mesh, cfg


def sweep(mesh, cfg, seeds: int, steps: int, save_every: int) -> list[dict]:
    from tpuscratch.ft import (
        ChaosPlan,
        Fault,
        GuardPolicy,
        RestartBudget,
        supervise_train,
    )
    from tpuscratch.ft.guards import GuardState
    from tpuscratch.obs.metrics import MetricsRegistry

    rows = []
    for seed in range(seeds):
        plan = ChaosPlan(seed, [
            # NaN one step in ~6 (heals on replay: times bounds total)
            Fault("train/grad", p=1.0 / 6, times=2, kind="nan"),
            # one transient checkpoint-IO failure at the manifest stage
            Fault("ckpt/save", stage="manifest", p=0.5, times=1),
            # one preemption somewhere past the first save
            Fault("train/preempt", p=0.34, times=1, kind="preempt"),
        ])
        metrics = MetricsRegistry()
        # a shared GuardState (like the plan) persists across restarts,
        # so the row's skip/rollback counts cover EVERY invocation, not
        # just the one that completed
        guard = GuardState(GuardPolicy(max_skips=0, max_rollbacks=8))
        work = tempfile.mkdtemp(prefix="chaos_sweep_")
        t0 = time.perf_counter()
        try:
            _, rep = supervise_train(
                mesh, cfg, steps, f"{work}/ckpt",
                save_every=save_every, seed=seed,
                chaos=plan, guard=guard,
                budget=RestartBudget(max_restarts=4),
                metrics=metrics,
            )
        finally:
            shutil.rmtree(work, ignore_errors=True)
        rows.append({
            "seed": seed,
            "injected": sum(plan.stats().values()),
            "by_site": plan.stats(),
            "skipped": rep.skipped,
            "rolled_back": rep.rollbacks,
            "restarts": int(metrics.counter("ft/restarts").value),
            "final_step": rep.final_step,
            # the COMPLETING invocation's last loss; a preemption after
            # the final save restarts into a zero-step resume (no losses)
            "final_loss": rep.losses[-1] if rep.losses else None,
            "wall_s": time.perf_counter() - t0,
        })
    return rows


def guard_overhead(mesh, reps: int = 20) -> tuple[float, float]:
    """(plain step s, guarded step s) — best-of timing of the two
    compiled programs on identical data; the guard adds one isfinite
    reduce, a spike compare, and a where-select per leaf.  Measured at a
    training-shaped size (the sweep's toy config would put fixed
    microseconds of guard math against a near-empty step)."""
    import jax
    import jax.numpy as jnp

    from tpuscratch.models.trainer import synthetic_batch
    from tpuscratch.models.transformer import (
        TransformerConfig,
        init_params,
        train_step,
    )

    cfg = TransformerConfig(d_model=64, n_heads=4, n_experts=4, d_ff=256,
                            n_layers=2, capacity_factor=2.0)
    plain = train_step(mesh, cfg, lr=0.05)
    guarded = train_step(mesh, cfg, lr=0.05, guard=(1e9, 1e9))
    params = init_params(0, cfg)
    x, y = synthetic_batch(0, 0, 8, 64, cfg.d_model)
    rl = jnp.asarray(float("nan"), jnp.float32)

    jax.block_until_ready(plain(params, x, y))       # warm both programs
    jax.block_until_ready(guarded(params, x, y, rl))
    best_plain = best_guard = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        p = params
        for _ in range(reps):
            p, loss = plain(p, x, y)
        jax.block_until_ready(loss)
        best_plain = min(best_plain, (time.perf_counter() - t0) / reps)
        t0 = time.perf_counter()
        p = params
        for _ in range(reps):
            p, loss, gnorm, st = guarded(p, x, y, rl)
        jax.block_until_ready(loss)
        best_guard = min(best_guard, (time.perf_counter() - t0) / reps)
    return best_plain, best_guard


def main(argv=None) -> int:
    import sys

    args = sys.argv[1:] if argv is None else list(argv)
    opts = {"seeds": 4, "steps": 24, "save_every": 4}
    for a in args:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            opts[k.replace("-", "_")] = int(v)
    mesh, cfg = _setup()
    print(f"chaos sweep: {opts['seeds']} seeds x {opts['steps']} steps "
          f"(save_every={opts['save_every']}) on a 2x2 CPU mesh")
    print(f"{'seed':>4} {'injected':>8} {'skipped':>7} {'rolledback':>10} "
          f"{'restarts':>8} {'step':>5} {'final_loss':>10} {'wall_s':>7}"
          f"  by_site")
    for r in sweep(mesh, cfg, opts["seeds"], opts["steps"],
                   opts["save_every"]):
        loss = (f"{r['final_loss']:.5f}" if r["final_loss"] is not None
                else "-")
        print(f"{r['seed']:>4} {r['injected']:>8} {r['skipped']:>7} "
              f"{r['rolled_back']:>10} {r['restarts']:>8} "
              f"{r['final_step']:>5} {loss:>10} {r['wall_s']:>7.2f}  "
              f"{r['by_site']}")
    plain_s, guard_s = guard_overhead(mesh)
    pct = 100.0 * (guard_s - plain_s) / plain_s
    print(f"guard overhead: plain {plain_s * 1e3:.3f} ms/step, guarded "
          f"{guard_s * 1e3:.3f} ms/step -> {pct:+.2f}% (budget < 3%)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
