"""The DMA-fabric bound race behind BASELINE row 9 (run on a chip:
``python -m tpuscratch.bench.dma_bound [anchors] [manual] [main2]``).

Question: can ANY Pallas DMA form stream 256x512x512 f32 faster than the
standard BlockSpec pipeline's ~320 GB/s?  Answer (v5e, marginal
ms/step): no — one monolithic HBM->HBM DMA 1.64 (327 GB/s rd+wr), K=2/4/8
concurrent slab DMAs 1.59-1.77, manual double-buffered VMEM bounce at
every band/buffer shape 1.58-1.70, multi-lane concurrent streams
1.62-1.79, vs the XLA non-DMA vector path 0.94 (568 GB/s).  ~330 GB/s is
the chip's total DMA-fabric copy rate; the lever past it is arithmetic
intensity (ops/stencil_stream.py folds k substeps per pass).

Run ON THE CHIP (default env).  One long-lived process; marginal rates by
step-count differencing inside compiled scans.

Schedule (per slot s = b % nbuf, separate read + write buffers so no
DMA/DMA buffer conflicts):
  wait rd(s, b); wait wr(s, b-nbuf); compute wbuf[s] from rbuf[s];
  start wr(s, b); start rd(s, b+nbuf).
Reads run nbuf bands ahead; writes lag, on their own semaphores.
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuscratch.bench.timing import time_device

NZ, CY, CX = 256, 512, 512
DT = jnp.float32
BYTES = NZ * CY * CX * 4


def manual_stream(band: int, nbuf: int, mode: str):
    """mode: 'copy' = VMEM bounce (wbuf[s] = rbuf[s]); 'touch' = *c."""
    nb = NZ // band
    assert NZ % band == 0

    def kernel(c_ref, in_hbm, out_hbm, rbuf, wbuf, rsem, wsem):
        def rd(slot, b):
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(b * band, band)], rbuf.at[slot],
                rsem.at[slot])

        def wr(slot, b):
            return pltpu.make_async_copy(
                wbuf.at[slot], out_hbm.at[pl.ds(b * band, band)],
                wsem.at[slot])

        for i in range(min(nbuf, nb)):
            rd(i, i).start()

        def body(b, carry):
            slot = jax.lax.rem(b, nbuf)
            rd(slot, b).wait()

            @pl.when(b >= nbuf)
            def _():
                wr(slot, b - nbuf).wait()

            if mode == "touch":
                wbuf[slot] = rbuf[slot] * c_ref[0]
            else:
                wbuf[slot] = rbuf[slot]
            wr(slot, b).start()

            @pl.when(b + nbuf < nb)
            def _():
                rd(slot, b + nbuf).start()

            return carry

        jax.lax.fori_loop(0, nb, body, 0)
        for i in range(max(0, nb - nbuf), nb):
            wr(i % nbuf, i).wait()

    call = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        out_shape=jax.ShapeDtypeStruct((NZ, CY, CX), DT),
        scratch_shapes=[
            pltpu.VMEM((nbuf, band, CY, CX), DT),
            pltpu.VMEM((nbuf, band, CY, CX), DT),
            pltpu.SemaphoreType.DMA((nbuf,)),
            pltpu.SemaphoreType.DMA((nbuf,)),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=120 << 20,
        ),
    )

    def step(x, c):
        return call(c, x)

    return step


def hbm2hbm():
    """Direct HBM->HBM DMA, no VMEM bounce — the raw engine rate."""

    def kernel(in_hbm, out_hbm, sem):
        cp = pltpu.make_async_copy(in_hbm, out_hbm, sem)
        cp.start()
        cp.wait()

    call = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        out_shape=jax.ShapeDtypeStruct((NZ, CY, CX), DT),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )

    def step(x, c):
        return call(x)

    return step


def xla_touch():
    def step(x, c):
        return x * c

    return step


def scanned(step, nsteps):
    @jax.jit
    def run(x, c):
        def body(carry, _):
            return step(carry, c), None

        y, _ = jax.lax.scan(body, x, None, length=nsteps)
        return y[0, 0, 0]

    return run


def race(name, step, steps_lo=50, steps_hi=250, iters=3):
    x = jnp.ones((NZ, CY, CX), DT)
    c = jnp.full((1,), 1.0 + 2 ** -20, DT)
    try:
        lo = time_device(scanned(step, steps_lo), x, c, iters=iters,
                         warmup=1, fence="readback", name=f"{name}@{steps_lo}")
        hi = time_device(scanned(step, steps_hi), x, c, iters=iters,
                         warmup=1, fence="readback", name=f"{name}@{steps_hi}")
    except Exception as e:
        msg = str(e).replace("\n", " | ")[:400]
        print(f"{name}: FAILED {msg}", flush=True)
        return
    marg = (hi.p50 - lo.p50) / (steps_hi - steps_lo)
    gbps = 2 * BYTES / marg / 1e9
    print(f"{name}: marginal {marg * 1e3:.3f} ms/step  "
          f"({gbps:.0f} GB/s rd+wr)", flush=True)


def main():
    which = sys.argv[1:] or ["anchors", "manual"]
    print(f"devices: {jax.devices()}", flush=True)
    if "anchors" in which:
        race("xla-touch", xla_touch())
        race("hbm2hbm-dma", hbm2hbm())
    if "manual" in which:
        for band, nbuf, mode in [
            (8, 2, "copy"), (8, 3, "copy"), (16, 2, "copy"),
            (8, 2, "touch"), (8, 3, "touch"), (16, 2, "touch"),
        ]:
            race(f"manual-{mode}-band{band}-nbuf{nbuf}",
                 manual_stream(band, nbuf, mode))


if __name__ == "__main__" and "main2" not in sys.argv:
    main()


def kway_hbm2hbm(K: int):
    """K concurrent HBM->HBM DMAs on disjoint z-slabs, own semaphores."""
    slab = NZ // K

    def kernel(in_hbm, out_hbm, sem):
        cps = [
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(i * slab, slab)],
                out_hbm.at[pl.ds(i * slab, slab)],
                sem.at[i],
            )
            for i in range(K)
        ]
        for cp in cps:
            cp.start()
        for cp in cps:
            cp.wait()

    call = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        out_shape=jax.ShapeDtypeStruct((NZ, CY, CX), DT),
        scratch_shapes=[pltpu.SemaphoreType.DMA((K,))],
    )

    def step(x, c):
        return call(x)

    return step


def lanes_stream(band: int, nbuf: int, L: int, mode: str = "touch"):
    """L independent double-buffered streams over disjoint z-halves —
    DMAs across lanes run concurrently on separate semaphores."""
    nb_lane = NZ // L // band
    assert NZ % (L * band) == 0

    def kernel(c_ref, in_hbm, out_hbm, rbuf, wbuf, rsem, wsem):
        def rd(lane, slot, b):
            z = (lane * nb_lane + b) * band
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(z, band)], rbuf.at[lane, slot],
                rsem.at[lane, slot])

        def wr(lane, slot, b):
            z = (lane * nb_lane + b) * band
            return pltpu.make_async_copy(
                wbuf.at[lane, slot], out_hbm.at[pl.ds(z, band)],
                wsem.at[lane, slot])

        for lane in range(L):
            for i in range(min(nbuf, nb_lane)):
                rd(lane, i, i).start()

        def body(b, carry):
            slot = jax.lax.rem(b, nbuf)
            for lane in range(L):
                rd(lane, slot, b).wait()

                @pl.when(b >= nbuf)
                def _(lane=lane):
                    wr(lane, slot, b - nbuf).wait()

                if mode == "touch":
                    wbuf[lane, slot] = rbuf[lane, slot] * c_ref[0]
                else:
                    wbuf[lane, slot] = rbuf[lane, slot]
                wr(lane, slot, b).start()

                @pl.when(b + nbuf < nb_lane)
                def _(lane=lane, slot=slot):
                    rd(lane, slot, b + nbuf).start()

            return carry

        jax.lax.fori_loop(0, nb_lane, body, 0)
        for lane in range(L):
            for i in range(max(0, nb_lane - nbuf), nb_lane):
                wr(lane, i % nbuf, i).wait()

    call = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        out_shape=jax.ShapeDtypeStruct((NZ, CY, CX), DT),
        scratch_shapes=[
            pltpu.VMEM((L, nbuf, band, CY, CX), DT),
            pltpu.VMEM((L, nbuf, band, CY, CX), DT),
            pltpu.SemaphoreType.DMA((L, nbuf)),
            pltpu.SemaphoreType.DMA((L, nbuf)),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=120 << 20,
        ),
    )

    def step(x, c):
        return call(c, x)

    return step


def main2():
    print(f"devices: {jax.devices()}", flush=True)
    for K in (2, 4, 8):
        race(f"hbm2hbm-{K}way", kway_hbm2hbm(K))
    for band, nbuf, L in [(8, 2, 2), (8, 2, 4), (4, 2, 4), (8, 3, 2)]:
        race(f"lanes{L}-touch-band{band}-nbuf{nbuf}",
             lanes_stream(band, nbuf, L))


if __name__ == "__main__" and "main2" in sys.argv:
    main2()
