"""Million-request traffic harness: trace-driven load for the fleet.

Every serving claim so far rests on synthetic arrival mixes of a few
hundred requests (``decode_bench.arrival_mix_requests``).  The
reference's production reality is PBS/SLURM *job streams* — batch
arrivals with diurnal shape, bursts, and faults that take whole ranks
out (mpierr.h's answer: abort the world).  This module is that reality
for the serving stack, in three pieces:

1. :class:`TraceGenerator` — a seeded, deterministic trace: tenant
   populations with Zipf-distributed shared-prefix reuse ("system
   prompts" — a few prefixes take most of the traffic, the SOSP '23
   sharing argument's actual shape), diurnal + Poisson-burst arrivals,
   mixed SLO classes, and long-tail (geometric) prompt/output lengths.
   Determinism is structural, not incidental:

   - the WHOLE trace is a pure function of ``TrafficConfig`` — same
     seed, byte-identical trace (no call-order state feeds any draw);
   - each tenant's request CONTENT stream is keyed on
     ``(seed, tenant, k)`` where ``k`` is the tenant's own sequence
     number — NOT the global rid or arrival tick — so tenant streams
     are independent of interleave: change another tenant's weight and
     this tenant's k-th request is still the same request;
   - arrivals are a pure function of the tick: Poisson draws at rate
     ``base_rate x diurnal(t) x burst(t)``, where ``burst(t)`` is
     computed from seeded per-tick ignition draws over a trailing
     window — no ignition "state machine" whose phase could drift.

2. :func:`run_traffic` — the byte-budgeted OPEN loop: the trace is
   materialized lazily (a generator), at most ``open_budget`` requests
   are live (submitted-but-unfinished) at once, and finished outputs
   fold into an order-independent digest instead of accumulating — a
   500k-request run holds O(open_budget) requests and O(1) outputs in
   memory.  The digest is the fleet-scale bit-identity handle: a
   chaos-churned run and a clean run of the same trace must fold to
   the same digest (the house invariant, at scale).

3. :func:`run_traffic_closed` — the CLOSED loop (ISSUE 18): a bounded
   population of per-tenant clients, each holding at most one open
   request, thinking a seeded geometric number of ticks between
   requests (think times compress with the diurnal/burst rate, so the
   crest still crests), and RE-SUBMITTING a shed request after seeded
   backoff (:class:`RetryPolicy`) — the retry-storm amplification loop
   that makes naive shedding metastable.  The determinism laws carry
   over: per-tenant request budgets make the request SET a pure
   function of the config (not of fleet speed), content stays keyed on
   ``(seed, tenant, k)``, rids are a pure function of ``(tenant, k)``,
   and the digest over non-shed completions is order-independent — a
   storm run and a clean run of the same trace fold to the same value
   once the storm's terminally-shed rids are excluded.

4. Trace record/replay: :meth:`TraceGenerator.dump_jsonl` writes the
   production-format log (one JSON object per arrival) and
   :func:`replay_jsonl` drives the same harnesses from the file,
   round-trip digest-identical to the generator.

5. One-definition rule: this module owns request synthesis.
   ``decode_bench.arrival_mix_requests`` (config 17's workload) now
   delegates here, so config-17 and config-19 rows draw from the same
   distributions — the odd shared-prefix rule (never page-aligned, so
   the sub-page rung is always exercised) lives in ONE place
   (:func:`odd_prefix_len`).

Tests: tests/test_traffic.py (markers ``traffic``, ``overload``).
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Iterator, Optional

import numpy as np

from tpuscratch.serve.engine import Request

# domain tags for the per-draw SeedSequences: distinct streams per
# purpose so adding a draw to one never shifts another
_ARRIVALS = zlib.crc32(b"traffic/arrivals")
_BURST = zlib.crc32(b"traffic/burst")
_REQ = zlib.crc32(b"traffic/req")
_POOL = zlib.crc32(b"traffic/pool")
_THINK = zlib.crc32(b"traffic/think")
_RETRY = zlib.crc32(b"traffic/retry")


def odd_prefix_len(length: int) -> int:
    """The shared-prefix length rule (ONE definition): ~3/4 of
    ``length``, forced ODD so the shared prefix can never be
    page-aligned (page sizes are even) — every pool exercises the
    sub-page boundary rung and ``subpage_tokens`` stays observably
    positive."""
    return max(1, (3 * length) // 4) | 1


def arrival_mix_requests(mix, n_requests: int, length: int, vocab: int,
                         seed: int = 0, max_new: int = 8,
                         pools_per_class: int = 1) -> list:
    """A multi-tenant arrival stream: ``mix`` is ``[(class, rate),
    ...]`` and the returned ``(class, Request)`` pairs interleave the
    classes proportionally to their rates (seeded draws — the workload
    is a pure function of its arguments, the config-12 rule).  Each
    class owns ``pools_per_class`` shared-prefix pools (its "system
    prompts"): every request draws one pool's prefix plus a private
    tail, so same-class traffic shares pages and CROSS-class traffic
    never does — the workload prefix-affine routing exists for.  The
    prefix is ~3/4 of ``length``, forced odd so it is never
    page-aligned — the sub-page boundary rung is always exercised.

    Config 17's fixed-size closed-loop workload; the open-loop,
    stream-scale twin is :class:`TraceGenerator`."""
    if not mix:
        raise ValueError("arrival mix needs at least one class:rate pair")
    rng = np.random.default_rng(seed)
    names = [name for name, _ in mix]
    rates = np.array([float(r) for _, r in mix])
    if (rates <= 0).any():
        raise ValueError(f"rates must be positive: {mix}")
    probs = rates / rates.sum()
    prefix_len = odd_prefix_len(length)
    pools = {
        name: [
            tuple(int(t) for t in rng.integers(0, vocab, prefix_len))
            for _ in range(pools_per_class)
        ]
        for name in names
    }
    out = []
    for i in range(n_requests):
        name = names[int(rng.choice(len(names), p=probs))]
        prefix = pools[name][int(rng.integers(0, pools_per_class))]
        tail = tuple(
            int(t) for t in rng.integers(0, vocab, length - prefix_len)
        )
        out.append((name, Request(rid=i, prompt=prefix + tail,
                                  max_new=max_new)))
    return out


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant population in the trace.

    ``weight`` sets the tenant's share of arrivals; ``cls`` names the
    SLO class its requests are submitted under (must exist in the
    router's ``RouterConfig.classes``).  Each tenant owns
    ``n_prefixes`` shared prefixes ("system prompts") of
    ``odd_prefix_len(prompt_len)`` tokens; requests pick one
    Zipf-distributed with exponent ``zipf_a`` (prefix 1 takes most of
    the traffic — the reuse distribution prefix-affine routing and
    paged sharing are built for).  ``tail_p`` / ``out_p`` are the
    geometric success rates for the private-tail length and the output
    budget — the long-tail halves of the length distributions, capped
    by the config so every request fits ``max_seq``."""

    name: str
    cls: str = "default"
    weight: float = 1.0
    n_prefixes: int = 4
    zipf_a: float = 1.2
    tail_p: float = 0.5
    out_p: float = 0.5

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.n_prefixes < 1:
            raise ValueError(
                f"n_prefixes must be >= 1, got {self.n_prefixes}"
            )
        if self.zipf_a <= 0:
            raise ValueError(f"zipf_a must be > 0, got {self.zipf_a}")
        if not (0 < self.tail_p <= 1) or not (0 < self.out_p <= 1):
            raise ValueError(
                f"tail_p/out_p must be in (0, 1], got "
                f"{self.tail_p}/{self.out_p}"
            )


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    """The trace is a pure function of this config (plus an item
    count).  ``base_rate`` is mean arrivals per fleet tick; the
    instantaneous rate is ``base_rate x (1 + diurnal_amp x
    sin(2 pi t / diurnal_period)) x (burst_mult if a burst window
    covers t)`` — the diurnal sine is the day cycle, the seeded
    ignition process (probability ``burst_p`` per tick, each ignition
    opening a ``burst_len``-tick window) is the thundering herd.
    Lengths: prompts are ``odd_prefix_len(prompt_len)`` shared tokens
    plus a geometric private tail in ``[1, tail_cap]``; output budgets
    are geometric in ``[1, out_cap]`` — size ``max_seq`` at least
    ``odd_prefix_len(prompt_len) + tail_cap + out_cap``."""

    seed: int = 0
    tenants: tuple[TenantSpec, ...] = (TenantSpec("t0"),)
    vocab: int = 16
    prompt_len: int = 16
    tail_cap: int = 4
    out_cap: int = 4
    base_rate: float = 2.0
    diurnal_period: int = 256
    diurnal_amp: float = 0.5
    burst_p: float = 0.02
    burst_len: int = 16
    burst_mult: float = 4.0

    def __post_init__(self):
        if not self.tenants:
            raise ValueError("TrafficConfig needs at least one tenant")
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        if self.vocab < 2:
            raise ValueError(f"vocab must be >= 2, got {self.vocab}")
        if self.prompt_len < 1 or self.tail_cap < 1 or self.out_cap < 1:
            raise ValueError(
                "prompt_len, tail_cap, out_cap must be >= 1"
            )
        if self.base_rate <= 0:
            raise ValueError(
                f"base_rate must be > 0, got {self.base_rate}"
            )
        if self.diurnal_period < 1 or self.burst_len < 1:
            raise ValueError("diurnal_period and burst_len must be >= 1")
        if not (0 <= self.diurnal_amp < 1):
            raise ValueError(
                f"diurnal_amp must be in [0, 1), got {self.diurnal_amp}"
            )
        if not (0 <= self.burst_p <= 1):
            raise ValueError(
                f"burst_p must be in [0, 1], got {self.burst_p}"
            )
        if self.burst_mult < 1:
            raise ValueError(
                f"burst_mult must be >= 1, got {self.burst_mult}"
            )

    @property
    def max_prompt_len(self) -> int:
        return odd_prefix_len(self.prompt_len) + self.tail_cap

    @property
    def max_total_len(self) -> int:
        """Smallest ``max_seq`` that admits every possible request."""
        return self.max_prompt_len + self.out_cap


@dataclasses.dataclass(frozen=True)
class TraceItem:
    """One arrival: tick, tenant, SLO class, and the materialized
    :class:`Request`.  ``rid`` (inside ``req``) is the global arrival
    index — unique fleet-wide, the PRNG-stream key."""

    t: int
    tenant: str
    cls: str
    req: Request

    def encode(self) -> bytes:
        """Canonical byte form — the unit the determinism law's
        digest folds (same seed => byte-identical trace)."""
        return repr((self.t, self.tenant, self.cls, self.req.rid,
                     self.req.prompt, self.req.max_new)).encode()


class TraceGenerator:
    """Seeded deterministic trace: see the module docstring for the
    three determinism properties.  ``stream(n)`` is a GENERATOR —
    nothing is materialized until iterated, so the harness can hold a
    million-request trace as one config object."""

    def __init__(self, cfg: TrafficConfig):
        self.cfg = cfg
        w = np.array([t.weight for t in cfg.tenants])
        self._tenant_probs = w / w.sum()
        # per-tenant Zipf pmf over its prefix pool: pmf(k) ~ 1/k^a
        self._zipf = {}
        self._pools = {}
        prefix_len = odd_prefix_len(cfg.prompt_len)
        for spec in cfg.tenants:
            ranks = np.arange(1, spec.n_prefixes + 1, dtype=np.float64)
            pmf = ranks ** -spec.zipf_a
            self._zipf[spec.name] = pmf / pmf.sum()
            # the pool itself is keyed on (seed, tenant) only — part
            # of the tenant's interleave-independent identity
            rng = np.random.default_rng(np.random.SeedSequence(
                [cfg.seed, _POOL, zlib.crc32(spec.name.encode())]
            ))
            self._pools[spec.name] = [
                tuple(int(x) for x in rng.integers(0, cfg.vocab,
                                                   prefix_len))
                for _ in range(spec.n_prefixes)
            ]
        self._by_name = {t.name: t for t in cfg.tenants}

    # ---- the arrival process (pure functions of the tick) ---------------

    def burst_active(self, t: int) -> bool:
        """True when any seeded ignition in the trailing ``burst_len``
        window fired — burst state WITHOUT a state machine: the same
        tick always answers the same way, whatever was queried before."""
        cfg = self.cfg
        if cfg.burst_p <= 0:
            return False
        for s in range(max(0, t - cfg.burst_len + 1), t + 1):
            rng = np.random.default_rng(
                np.random.SeedSequence([cfg.seed, _BURST, s])
            )
            if float(rng.random()) < cfg.burst_p:
                return True
        return False

    def rate_at(self, t: int) -> float:
        """Instantaneous arrival rate: diurnal sine x burst multiplier."""
        cfg = self.cfg
        diurnal = 1.0 + cfg.diurnal_amp * float(
            np.sin(2.0 * np.pi * t / cfg.diurnal_period)
        )
        mult = cfg.burst_mult if self.burst_active(t) else 1.0
        return cfg.base_rate * diurnal * mult

    def _arrivals_at(self, t: int) -> list[str]:
        """Tenant names arriving at tick ``t`` — Poisson count at
        ``rate_at(t)``, tenants drawn by weight; one pure-fn rng per
        tick, so the trace never depends on iteration history."""
        rng = np.random.default_rng(
            np.random.SeedSequence([self.cfg.seed, _ARRIVALS, t])
        )
        n = int(rng.poisson(self.rate_at(t)))
        if n == 0:
            return []
        idx = rng.choice(len(self.cfg.tenants), size=n,
                         p=self._tenant_probs)
        return [self.cfg.tenants[int(i)].name for i in idx]

    # ---- request content (pure function of (seed, tenant, k)) -----------

    def _materialize(self, tenant: str, k: int, rid: int) -> Request:
        """The tenant's ``k``-th request — content keyed on
        ``(seed, tenant, k)``, NOT on rid or tick: the
        interleave-independence law.  ``rid`` only names the request."""
        cfg = self.cfg
        spec = self._by_name[tenant]
        rng = np.random.default_rng(np.random.SeedSequence(
            [cfg.seed, _REQ, zlib.crc32(tenant.encode()), k]
        ))
        pool_i = int(rng.choice(spec.n_prefixes, p=self._zipf[tenant]))
        prefix = self._pools[tenant][pool_i]
        # geometric long tails, capped so every request fits max_seq
        tail_len = min(cfg.tail_cap, int(rng.geometric(spec.tail_p)))
        tail = tuple(int(x) for x in rng.integers(0, cfg.vocab, tail_len))
        max_new = min(cfg.out_cap, int(rng.geometric(spec.out_p)))
        return Request(rid=rid, prompt=prefix + tail, max_new=max_new)

    # ---- the stream ------------------------------------------------------

    def stream(self, n_requests: int,
               rid_base: int = 0) -> Iterator[TraceItem]:
        """Lazily yield the first ``n_requests`` arrivals in tick
        order.  rids are ``rid_base + arrival index``; per-tenant
        sequence numbers count independently (the content key)."""
        seq: dict[str, int] = {t.name: 0 for t in self.cfg.tenants}
        rid = rid_base
        t = 0
        emitted = 0
        while emitted < n_requests:
            for tenant in self._arrivals_at(t):
                if emitted >= n_requests:
                    break
                k = seq[tenant]
                seq[tenant] = k + 1
                req = self._materialize(tenant, k, rid)
                yield TraceItem(t=t, tenant=tenant,
                                cls=self._by_name[tenant].cls, req=req)
                rid += 1
                emitted += 1
            t += 1

    def digest(self, n_requests: int) -> int:
        """Sequential CRC fold over the canonical byte form of the
        first ``n_requests`` items — the "same seed => byte-identical
        trace" law's O(1)-memory witness."""
        h = 0
        for item in self.stream(n_requests):
            h = zlib.crc32(item.encode(), h)
        return h

    def dump_jsonl(self, path, n_requests: int, rid_base: int = 0) -> int:
        """Record the first ``n_requests`` arrivals as a JSONL log —
        one object per arrival, the production log format
        :func:`replay_jsonl` replays.  Returns the item count written.
        The round trip is LOSSLESS: a replayed trace's ``digest`` and
        every harness run over it are bit-identical to the generator's
        (tested), so a recorded production log and a synthetic config
        are interchangeable drivers."""
        import json

        n = 0
        with open(path, "w") as f:
            for item in self.stream(n_requests, rid_base=rid_base):
                f.write(json.dumps({
                    "t": item.t, "tenant": item.tenant, "cls": item.cls,
                    "rid": item.req.rid,
                    "prompt": list(item.req.prompt),
                    "max_new": item.req.max_new,
                }) + "\n")
                n += 1
        return n


class TraceReplay:
    """A recorded trace, duck-typed to the :class:`TraceGenerator`
    surface the harnesses use (``stream`` / ``digest``) — so
    ``run_traffic``/``run_traffic_closed`` drive a production log and a
    synthetic config through the same code path.  Recorded rids are
    authoritative: ``stream``'s ``rid_base`` is accepted for interface
    compatibility and ignored."""

    def __init__(self, items: list):
        self.items = list(items)

    def stream(self, n_requests: int,
               rid_base: int = 0) -> Iterator[TraceItem]:
        yield from self.items[:n_requests]

    def digest(self, n_requests: int) -> int:
        h = 0
        for item in self.items[:n_requests]:
            h = zlib.crc32(item.encode(), h)
        return h


def replay_jsonl(path) -> TraceReplay:
    """Load a :meth:`TraceGenerator.dump_jsonl` log (or any log in its
    format) into a :class:`TraceReplay`."""
    import json

    items = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            items.append(TraceItem(
                t=int(d["t"]), tenant=d["tenant"], cls=d["cls"],
                req=Request(rid=int(d["rid"]),
                            prompt=tuple(int(x) for x in d["prompt"]),
                            max_new=int(d["max_new"])),
            ))
    return TraceReplay(items)


# ---- the open-loop harness ----------------------------------------------


def fold_output(digest: int, rid: int, toks: tuple) -> int:
    """Order-INDEPENDENT output digest fold: per-request CRCs are
    summed mod 2^64, so a chaos run (which finishes requests in a
    different order) and a clean run of the same trace fold to the
    same value exactly when every request emitted the same tokens —
    the fleet-scale bit-identity handle that never holds the outputs."""
    h = zlib.crc32(repr((rid, tuple(int(t) for t in toks))).encode())
    return (digest + h) & 0xFFFFFFFFFFFFFFFF


@dataclasses.dataclass(frozen=True)
class TrafficReport:
    """One harness run (open or closed loop): the router's drain-window
    report plus the stream-scale handles — the output digest
    (bit-identity over non-shed completions), the peak open-request
    count (the byte budget's witness: ``peak_open <= open_budget`` /
    total client concurrency, always), and the tick count.

    Overload fields (ISSUE 18): ``sheds`` counts RequestShed outcomes
    (every shed leg, including later-retried ones), ``retries`` the
    re-submissions the closed loop's :class:`RetryPolicy` issued,
    ``abandoned`` the requests that exhausted their retry budget (the
    TERMINAL sheds — ``shed_rids`` names them, the exclusion set a
    clean-fleet digest pairing needs).  In the open loop every shed is
    terminal (``retries == 0``, ``abandoned == sheds``)."""

    report: object               # RouterReport for the whole window
    digest: int
    submitted: int
    peak_open: int
    ticks: int
    wall_s: float
    sheds: int = 0
    retries: int = 0
    abandoned: int = 0
    shed_rids: tuple[int, ...] = ()


def _check_request_law(router, where: str) -> None:
    """The per-tick request-count law (ISSUE 18): every request the
    router accepted is exactly one of finished, shed, or open —
    asserted LIVE, every tick, not just at drain."""
    sub = router.submitted_requests
    fin = router.finished_requests
    shed = router.shed_requests
    open_ = router.open_requests
    if sub != fin + shed + open_:
        raise AssertionError(
            f"request-count law violated at {where}: submitted {sub} "
            f"!= finished {fin} + shed {shed} + open {open_}"
        )


def run_traffic(router, gen: TraceGenerator, n_requests: int, *,
                open_budget: int, max_steps: int = 2_000_000,
                check_law: bool = True, rid_base: int = 0,
                exclude_rids: frozenset = frozenset()) -> TrafficReport:
    """Stream ``n_requests`` of ``gen``'s trace through ``router``
    under a byte-budgeted OPEN loop, then drain.

    Each fleet tick admits every trace item whose arrival tick has
    come — but never more than ``open_budget`` live (submitted-but-
    unfinished) requests: when the fleet falls behind a burst, the
    un-admitted tail of the trace stays UN-MATERIALIZED (the generator
    simply isn't advanced), so memory is O(open_budget) whatever the
    trace length.  Finished outputs fold into :func:`fold_output`'s
    digest and are dropped.

    The report is the router's own drain-window accounting
    (:meth:`FleetRouter._begin_drain` / ``_drain_report`` — the same
    definitions ``run`` uses), and when ``check_law`` is set BOTH
    counter laws are asserted: the token law ``prefill + shared ==
    submitted + readmitted_tokens`` at drain (exact under any
    replica-kill schedule, shed prompts excluded from the submitted
    leg) and the request-count law ``submitted == finished + shed +
    open`` at EVERY tick.  Open-loop sheds are terminal (no client to
    retry them); ``exclude_rids`` skips those rids in the digest fold
    so a clean run pairs bit-identically with a shedding storm run."""
    if open_budget < 1:
        raise ValueError(f"open_budget must be >= 1, got {open_budget}")
    items = gen.stream(n_requests, rid_base=rid_base)
    pending: Optional[TraceItem] = next(items, None)
    snap = router._begin_drain()
    digest = 0
    submitted = finished = tokens = sheds = 0
    shed_rids: list[int] = []
    peak_open = 0
    ticks = 0
    t0 = time.perf_counter()
    while pending is not None or router.busy:
        if ticks >= max_steps:
            raise RuntimeError(
                f"traffic run did not complete in {max_steps} ticks "
                f"({submitted - finished - sheds} open, "
                f"{pending is not None and 'trace remaining' or 'trace done'})"
            )
        # admit: every due arrival, while the byte budget holds (shed
        # requests are no longer live — their budget slots free up)
        while (pending is not None and pending.t <= ticks
               and submitted - finished - sheds < open_budget):
            router.submit(pending.req, tenant=pending.cls)
            submitted += 1
            pending = next(items, None)
        peak_open = max(peak_open, submitted - finished - sheds)
        for rid, toks in router.step():
            if rid not in exclude_rids:
                digest = fold_output(digest, rid, toks)
            finished += 1
            tokens += len(toks)
        for s in router.take_shed():
            sheds += 1
            shed_rids.append(s.rid)
        if check_law:
            _check_request_law(router, f"tick {ticks}")
        ticks += 1
    wall = time.perf_counter() - t0
    report = router._drain_report(snap, wall, completed=finished,
                                  tokens=tokens)
    if check_law:
        lhs = report.prefill_tokens + report.shared_tokens
        rhs = (report.submitted_prompt_tokens
               + report.readmitted_tokens)
        if lhs != rhs:
            raise AssertionError(
                f"fleet counter law violated under churn: prefill "
                f"{report.prefill_tokens} + shared "
                f"{report.shared_tokens} = {lhs} != submitted "
                f"{report.submitted_prompt_tokens} + readmitted "
                f"{report.readmitted_tokens} = {rhs}"
            )
    if finished + sheds != submitted:
        raise AssertionError(
            f"open loop lost requests: {submitted} submitted, "
            f"{finished} finished + {sheds} shed"
        )
    return TrafficReport(report=report, digest=digest,
                         submitted=submitted, peak_open=peak_open,
                         ticks=ticks, wall_s=wall,
                         sheds=sheds, retries=0, abandoned=sheds,
                         shed_rids=tuple(shed_rids))


# ---- the closed-loop harness (ISSUE 18) ----------------------------------


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Seeded, tick-denominated client retry: a shed request
    re-submits after ``backoff_ticks x mult^(attempt-1)`` ticks plus a
    seeded jitter draw in ``[0, jitter_ticks]`` — keyed on
    ``(seed, rid, attempt)``, so the retry storm is a pure function of
    the trace, never of wall clock.  After ``max_attempts`` legs the
    request is ABANDONED (terminal — the client gives up and moves
    on).  Deliberately distinct from ``ft.retry.RetryPolicy``: that
    one is the SERVER's wall-clock transient-fault absorber; this one
    is the CLIENT behavior that amplifies overload (the metastable
    loop shedding must survive)."""

    max_attempts: int = 3        # total legs, first submission included
    backoff_ticks: int = 2
    mult: float = 2.0
    jitter_ticks: int = 1

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.backoff_ticks < 1 or self.mult < 1:
            raise ValueError("backoff_ticks and mult must be >= 1")
        if self.jitter_ticks < 0:
            raise ValueError(
                f"jitter_ticks must be >= 0, got {self.jitter_ticks}"
            )

    def backoff_at(self, seed: int, rid: int, attempt: int) -> int:
        """Ticks until the ``attempt``-th re-submission of ``rid``
        (attempt 1 = first retry)."""
        base = int(round(self.backoff_ticks * self.mult ** (attempt - 1)))
        if self.jitter_ticks > 0:
            rng = np.random.default_rng(np.random.SeedSequence(
                [seed, _RETRY, rid, attempt]
            ))
            base += int(rng.integers(0, self.jitter_ticks + 1))
        return max(1, base)


@dataclasses.dataclass(frozen=True)
class ClosedLoopSpec:
    """The client population: ``concurrency`` clients per tenant
    (overridable per tenant), each holding at most ONE open request
    and thinking a seeded geometric(``think_p``) number of ticks
    between requests.  Think times DIVIDE by the trace's instantaneous
    rate factor (``rate_at(t) / base_rate``), so the diurnal sine and
    burst ignitions still shape closed-loop load — the crest still
    crests.  ``retry`` re-submits shed requests after backoff (None:
    a shed is immediately terminal)."""

    concurrency: int = 4
    per_tenant: tuple[tuple[str, int], ...] = ()
    think_p: float = 0.5
    retry: Optional[RetryPolicy] = None

    def __post_init__(self):
        if self.concurrency < 1:
            raise ValueError(
                f"concurrency must be >= 1, got {self.concurrency}"
            )
        if not (0 < self.think_p <= 1):
            raise ValueError(
                f"think_p must be in (0, 1], got {self.think_p}"
            )

    def clients_for(self, tenant: str) -> int:
        for name, n in self.per_tenant:
            if name == tenant:
                return n
        return self.concurrency


def _tenant_quotas(tenants, spec: ClosedLoopSpec,
                   n_requests: int) -> dict[str, int]:
    """Per-tenant request budgets summing to ``n_requests``,
    proportional to client counts with remainders to earlier tenants.
    A FIXED split is what keeps the closed-loop request SET a pure
    function of the config: which client starts a tenant's k-th
    request depends on fleet speed, but the set of (tenant, k) pairs
    — and therefore the rids and contents — never does."""
    counts = {t.name: spec.clients_for(t.name) for t in tenants}
    total = sum(counts.values())
    quotas = {}
    given = 0
    for i, t in enumerate(tenants):
        if i == len(tenants) - 1:
            quotas[t.name] = n_requests - given
        else:
            q = (n_requests * counts[t.name]) // total
            quotas[t.name] = q
            given += q
    return quotas


def run_traffic_closed(router, gen: TraceGenerator, n_requests: int, *,
                       spec: ClosedLoopSpec,
                       max_steps: int = 2_000_000,
                       check_law: bool = True, rid_base: int = 0,
                       exclude_rids: frozenset = frozenset()
                       ) -> TrafficReport:
    """Drive ``router`` with a CLOSED loop of think-time clients over
    ``gen``'s request content (the arrival process is the clients, not
    the trace's Poisson stream — ``gen`` supplies tenants, classes,
    and the ``(seed, tenant, k)``-keyed request contents).

    Determinism: per-tenant quotas fix the request set
    (:func:`_tenant_quotas`), rids are ``rid_base + k x n_tenants +
    tenant_index`` (a pure function of the content key, so the same
    request carries the same rid — and emits the same tokens — on any
    fleet), think and backoff draws are seeded and tick-denominated.
    With the router's logical shed clock (``RouterConfig.tick_s``) the
    ENTIRE storm — who sheds, who retries, who abandons — is a pure
    function of (config, fleet, plan): repeat runs are bit-identical.

    A shed request re-submits under ``spec.retry`` with the SAME rid
    (the router forgets shed rids, and rid keys the PRNG stream — the
    retry leg emits identical tokens); after ``max_attempts`` legs it
    is abandoned (terminal).  The digest folds non-shed completions,
    order-independent; ``exclude_rids`` (a storm run's
    ``shed_rids``) makes a clean-fleet pairing bit-comparable.  Both
    counter laws are asserted under ``check_law``, the request-count
    law at every tick."""
    tenants = gen.cfg.tenants
    names = [t.name for t in tenants]
    cls_of = {t.name: t.cls for t in tenants}
    quotas = _tenant_quotas(tenants, spec, n_requests)
    seed = gen.cfg.seed
    # one content counter per tenant, shared by its clients: which
    # client starts request k is timing; WHAT request k is, is not
    seq = {n: 0 for n in names}
    # clients: (tenant, client_idx) -> dict(state); think stream keyed
    # per client so client populations draw independently
    clients = []
    for ti, t in enumerate(tenants):
        for c in range(spec.clients_for(t.name)):
            clients.append({
                "tenant": t.name, "idx": c, "draws": 0,
                "ready_at": 0, "rid": None,
            })

    def think(client, tick: int) -> int:
        """Seeded think duration starting at ``tick``: geometric
        draw, compressed by the instantaneous rate factor so bursts
        and the diurnal crest reach the closed loop."""
        rng = np.random.default_rng(np.random.SeedSequence(
            [seed, _THINK, zlib.crc32(client["tenant"].encode()),
             client["idx"], client["draws"]]
        ))
        client["draws"] += 1
        raw = int(rng.geometric(spec.think_p))
        factor = gen.rate_at(tick) / gen.cfg.base_rate
        return max(1, int(round(raw / max(factor, 1e-9))))

    snap = router._begin_drain()
    digest = 0
    started = finished = tokens = sheds = retries = abandoned = 0
    shed_rids: list[int] = []
    owner: dict[int, dict] = {}        # rid -> waiting client
    reqs: dict[int, object] = {}       # rid -> Request (for retries)
    attempts: dict[int, int] = {}      # rid -> legs submitted
    due: dict[int, list[int]] = {}     # tick -> rids to re-submit
    peak_open = 0
    ticks = 0
    t0 = time.perf_counter()
    while True:
        if ticks >= max_steps:
            raise RuntimeError(
                f"closed loop did not complete in {max_steps} ticks "
                f"({started} started, {finished} finished, "
                f"{abandoned} abandoned)"
            )
        # 1) due retries first (rid order — deterministic), then new
        # starts (tenant config order, client index order)
        for rid in sorted(due.pop(ticks, ())):
            router.submit(reqs[rid], tenant=cls_of[owner[rid]["tenant"]])
            attempts[rid] += 1
            retries += 1
        for client in clients:
            if client["rid"] is not None or client["ready_at"] > ticks:
                continue
            tn = client["tenant"]
            if seq[tn] >= quotas[tn]:
                continue
            k = seq[tn]
            seq[tn] = k + 1
            rid = rid_base + k * len(names) + names.index(tn)
            req = gen._materialize(tn, k, rid)
            router.submit(req, tenant=cls_of[tn])
            started += 1
            client["rid"] = rid
            owner[rid] = client
            reqs[rid] = req
            attempts[rid] = 1
        peak_open = max(peak_open, router.open_requests)
        # 2) one fleet tick; completions wake their clients
        for rid, toks in router.step():
            if rid not in exclude_rids:
                digest = fold_output(digest, rid, toks)
            finished += 1
            tokens += len(toks)
            client = owner.pop(rid)
            client["rid"] = None
            client["ready_at"] = ticks + think(client, ticks)
            reqs.pop(rid, None)
            attempts.pop(rid, None)
        # 3) sheds: retry with backoff, or abandon (terminal)
        for s in router.take_shed():
            sheds += 1
            rid = s.rid
            legs = attempts[rid]
            if (spec.retry is not None
                    and legs < spec.retry.max_attempts):
                back = spec.retry.backoff_at(seed, rid, legs)
                due.setdefault(ticks + 1 + back, []).append(rid)
            else:
                abandoned += 1
                shed_rids.append(rid)
                client = owner.pop(rid)
                client["rid"] = None
                client["ready_at"] = ticks + think(client, ticks)
                reqs.pop(rid, None)
                attempts.pop(rid, None)
        if check_law:
            _check_request_law(router, f"tick {ticks}")
        ticks += 1
        if (not owner and not due and not router.busy
                and all(seq[n] >= quotas[n] for n in names)):
            break
    wall = time.perf_counter() - t0
    report = router._drain_report(snap, wall, completed=finished,
                                  tokens=tokens)
    if check_law:
        lhs = report.prefill_tokens + report.shared_tokens
        rhs = (report.submitted_prompt_tokens
               + report.readmitted_tokens)
        if lhs != rhs:
            raise AssertionError(
                f"fleet counter law violated: prefill "
                f"{report.prefill_tokens} + shared "
                f"{report.shared_tokens} = {lhs} != submitted "
                f"{report.submitted_prompt_tokens} + readmitted "
                f"{report.readmitted_tokens} = {rhs}"
            )
    if finished + abandoned != started:
        raise AssertionError(
            f"closed loop lost requests: {started} started, "
            f"{finished} finished + {abandoned} abandoned"
        )
    return TrafficReport(report=report, digest=digest,
                         submitted=started, peak_open=peak_open,
                         ticks=ticks, wall_s=wall,
                         sheds=sheds, retries=retries,
                         abandoned=abandoned,
                         shed_rids=tuple(shed_rids))


# ---- the config-19 workload (one definition) -----------------------------


def traffic_chaos_setup(on_tpu: bool, vocab: int) -> dict:
    """The config-19 workload: trace config, fleet size, open budget,
    SLO classes, and the fixed chaos plan's clauses — ONE definition
    shared by ``bench.record`` config 19, ``examples/ex34_traffic``,
    and the traffic tests (the ``router_mix_setup`` rule).  The chaos
    schedule is tick-explicit (``at`` clauses, not rates): a fixed
    plan makes the readmitted/dropped counters exact recorded values,
    so regress can gate them as static counters."""
    tenants = (
        TenantSpec("acme", cls="latency", weight=3.0, n_prefixes=4),
        TenantSpec("globex", cls="batch", weight=1.0, n_prefixes=2),
    )
    classes = (("latency", "ttft"), ("batch", "throughput"))
    if on_tpu:
        tcfg = TrafficConfig(
            seed=19, tenants=tenants, vocab=vocab, prompt_len=64,
            tail_cap=8, out_cap=8, base_rate=8.0, diurnal_period=256,
            diurnal_amp=0.5, burst_p=0.02, burst_len=16, burst_mult=4.0,
        )
        return dict(tcfg=tcfg, n_requests=2000, open_budget=128,
                    n_replicas=3, classes=classes,
                    kills=((8, 0), (40, 1)), stall=(24, 2),
                    down_ticks=8)
    # CPU proxy: config 17's prompt scale (length 21 -> 15-token odd
    # shared prefix).  The kills target replicas 0 and 1: affinity
    # concentrates each tenant's prefix family on the replica its
    # first request landed on (least-loaded order: acme -> 0,
    # globex -> 1), so those are the replicas that are mid-stream
    # when they die — a kill on the idle spare would re-admit nothing.
    # Tick 9 is this trace's burst crest (replica 0 carries ~7 active
    # decodes + a deep queue), so the first kill loses PREFILLED and
    # GENERATED work, not just queued prompts — the goodput fraction
    # has something real to charge
    tcfg = TrafficConfig(
        seed=19, tenants=tenants, vocab=vocab, prompt_len=21,
        tail_cap=4, out_cap=4, base_rate=2.0, diurnal_period=64,
        diurnal_amp=0.5, burst_p=0.05, burst_len=8, burst_mult=3.0,
    )
    return dict(tcfg=tcfg, n_requests=96, open_budget=24,
                n_replicas=3, classes=classes,
                kills=((9, 0), (13, 1)), stall=(7, 2), down_ticks=6)


def chaos_plan_for(setup: dict):
    """The setup's fixed replica-chaos plan (fresh per run — ``times``
    budgets are consumed state)."""
    from tpuscratch.ft.chaos import ChaosPlan, Fault

    faults = [
        Fault(site="serve/replica", at=(t,), key=rep, kind="kill",
              down_ticks=setup["down_ticks"])
        for t, rep in setup["kills"]
    ]
    t, rep = setup["stall"]
    faults.append(Fault(site="serve/replica", at=(t,), key=rep,
                        kind="stall", down_ticks=setup["down_ticks"]))
    return ChaosPlan(seed=17, faults=faults)


def bench_traffic(mesh, cfg, scfg, setup: dict, chaos: bool) -> dict:
    """One open-loop traffic run over a FRESH fleet (fresh engines,
    fresh plan — chaos budgets and reservoirs must not leak between
    arms), chaos on or off, flattened to a row dict.  The zero-loss
    law (``dropped == 0``), the generalized counter law, and (under
    chaos) readmission actually happening are asserted HERE — every
    consumer measures the same claims."""
    from tpuscratch.serve.engine import ServeEngine
    from tpuscratch.serve.router import FleetRouter, RouterConfig, SLOClass

    rcfg = RouterConfig(classes=tuple(
        SLOClass(n, target=t) for n, t in setup["classes"]
    ))
    router = FleetRouter(
        [ServeEngine(mesh, cfg, scfg)
         for _ in range(setup["n_replicas"])],
        rcfg=rcfg,
        chaos=chaos_plan_for(setup) if chaos else None,
    )
    tr = run_traffic(router, TraceGenerator(setup["tcfg"]),
                     setup["n_requests"],
                     open_budget=setup["open_budget"])
    rep = tr.report
    if rep.dropped != 0:
        raise AssertionError(
            f"zero-loss law violated: {rep.dropped} dropped"
        )
    if chaos and rep.readmitted == 0:
        raise AssertionError(
            "chaos arm re-admitted nothing — the kills fired on empty "
            "replicas (workload/schedule drifted)"
        )
    row = {
        "replicas": setup["n_replicas"],
        "requests": tr.submitted,
        "digest": tr.digest,
        "peak_open": tr.peak_open,
        "ticks": tr.ticks,
        "wall_s": tr.wall_s,
        "tokens_per_s": rep.tokens_per_s,
        "kills": rep.kills,
        "stalls": rep.stalls,
        "readmitted": rep.readmitted,
        "readmitted_tokens": rep.readmitted_tokens,
        "lost_tokens": rep.lost_tokens,
        "dropped": rep.dropped,
        "classes": {
            c.name: {
                "completed": c.completed,
                "ttft_p50_s": c.ttft_p50_s,
                "ttft_p99_s": c.ttft_p99_s,
                "goodput_frac": c.goodput_frac,
                "readmitted": c.readmitted,
            }
            for c in rep.classes
        },
    }
    return row


# ---- the config-20 workload (one definition) -----------------------------


def overload_setup(on_tpu: bool, vocab: int) -> dict:
    """The config-20 overload-survival workload: a deliberately
    OVERCOMMITTED closed loop (client concurrency sized past the storm
    fleet's slot capacity) with a rack-scale correlated kill at a
    burst-crest tick, SLO-aware shedding on, retry storm on — one
    definition shared by ``bench.record`` config 20 and the overload
    tests.  The shed clock is LOGICAL (``tick_s=1.0``, deadlines in
    fleet ticks), so the whole storm — who sheds, who retries, who
    abandons, every digest — is a pure function of this setup.

    ``kill_tick`` sits inside the trace's first burst window (seeded
    ignition — verified by ``TraceGenerator.burst_active`` in the
    tests), so the rack dies at the crest: the storm arm must survive
    crest + rack loss + retry amplification with the TOP class intact
    (zero latency sheds, bounded p99 TTFT) while the batch class
    sheds.  The CLEAN pair is the same trace on an uncommitted fleet
    (more replicas, no chaos): zero sheds, and — with the storm's
    terminally-shed rids excluded — a bit-identical output digest."""
    tenants = (
        TenantSpec("acme", cls="latency", weight=3.0, n_prefixes=4),
        TenantSpec("globex", cls="batch", weight=1.0, n_prefixes=2),
    )
    # class order IS priority: latency (index 0) is the top class —
    # displacement protects it; its generous deadline makes the
    # zero-top-shed gate a measured fact, not a vacuous default.
    # max_queue is what makes overload VISIBLE to the shed layer: it
    # bounds per-replica dispatch depth so excess work holds in the
    # router queue (where it ages against shed_after_s) instead of
    # disappearing into unbounded replica-internal queues
    classes = (
        dict(name="latency", target="ttft", shed_after_s=60.0,
             max_queue=4),
        dict(name="batch", target="throughput", shed_after_s=6.0,
             max_queue=2),
    )
    retry = RetryPolicy(max_attempts=3, backoff_ticks=2, mult=2.0,
                        jitter_ticks=1)
    if on_tpu:
        tcfg = TrafficConfig(
            seed=20, tenants=tenants, vocab=vocab, prompt_len=64,
            tail_cap=8, out_cap=8, base_rate=8.0, diurnal_period=256,
            diurnal_amp=0.5, burst_p=0.02, burst_len=16, burst_mult=4.0,
        )
        return dict(tcfg=tcfg, n_requests=1200, classes=classes,
                    spec=ClosedLoopSpec(
                        concurrency=16,
                        per_tenant=(("globex", 48),),
                        think_p=0.6, retry=retry),
                    n_replicas_storm=3, n_replicas_clean=5,
                    rack=(0, 1), kill_tick=8, down_ticks=24,
                    tick_s=1.0)
    tcfg = TrafficConfig(
        seed=20, tenants=tenants, vocab=vocab, prompt_len=21,
        tail_cap=4, out_cap=4, base_rate=2.0, diurnal_period=64,
        diurnal_amp=0.5, burst_p=0.05, burst_len=8, burst_mult=3.0,
    )
    return dict(tcfg=tcfg, n_requests=160, classes=classes,
                spec=ClosedLoopSpec(
                    concurrency=4,
                    per_tenant=(("globex", 12),),
                    think_p=0.6, retry=retry),
                n_replicas_storm=3, n_replicas_clean=5,
                rack=(0, 1), kill_tick=6, down_ticks=20,
                tick_s=1.0)


def overload_plan_for(setup: dict):
    """The setup's correlated rack-kill plan (fresh per run — budgets
    and domain ignitions are consumed state): ONE seeded ignition at
    ``kill_tick`` takes out every replica in ``rack`` in the same
    fleet tick."""
    from tpuscratch.ft.chaos import ChaosPlan, Fault

    return ChaosPlan(seed=20, faults=[
        Fault(site="serve/replica", at=(setup["kill_tick"],),
              domain=setup["rack"], kind="kill",
              down_ticks=setup["down_ticks"]),
    ])


def overload_router(mesh, cfg, scfg, setup: dict, storm: bool):
    """A fresh fleet for one config-20 arm: the overcommitted 3-replica
    storm fleet (rack-kill plan armed) or the uncommitted clean fleet
    (more replicas, no chaos)."""
    from tpuscratch.serve.engine import ServeEngine
    from tpuscratch.serve.router import FleetRouter, RouterConfig, SLOClass

    rcfg = RouterConfig(
        classes=tuple(SLOClass(**c) for c in setup["classes"]),
        tick_s=setup["tick_s"],
    )
    n = setup["n_replicas_storm" if storm else "n_replicas_clean"]
    return FleetRouter(
        [ServeEngine(mesh, cfg, scfg) for _ in range(n)],
        rcfg=rcfg,
        chaos=overload_plan_for(setup) if storm else None,
    )


def bench_overload(mesh, cfg, scfg, setup: dict, storm: bool,
                   exclude_rids: frozenset = frozenset()) -> dict:
    """One config-20 arm, flattened to a row dict.  The survival
    claims are asserted HERE (every consumer measures the same laws):
    zero drops always; under the storm — the rack kill actually fired,
    the retry storm actually looped, the BATCH class shed while the
    LATENCY class shed ZERO, and ``peak_open`` stayed bounded by the
    client population; on the clean fleet — zero sheds.  The row
    carries ``shed_rids`` so the record config can pair the clean
    arm's digest against the storm's (pop it before emitting)."""
    tr = run_traffic_closed(
        overload_router(mesh, cfg, scfg, setup, storm),
        TraceGenerator(setup["tcfg"]), setup["n_requests"],
        spec=setup["spec"], exclude_rids=exclude_rids,
    )
    rep = tr.report
    if rep.dropped != 0:
        raise AssertionError(
            f"zero-loss law violated: {rep.dropped} dropped"
        )
    by_cls = {c.name: c for c in rep.classes}
    n_clients = sum(
        setup["spec"].clients_for(t.name) for t in setup["tcfg"].tenants
    )
    if tr.peak_open > n_clients:
        raise AssertionError(
            f"closed loop leaked: peak_open {tr.peak_open} > "
            f"{n_clients} clients"
        )
    if storm:
        if rep.kills != len(setup["rack"]):
            raise AssertionError(
                f"rack kill misfired: {rep.kills} kills, expected "
                f"{len(setup['rack'])} (schedule drifted off the crest)"
            )
        if by_cls["latency"].shed != 0:
            raise AssertionError(
                f"TOP class shed {by_cls['latency'].shed} requests — "
                "displacement failed while batch had work to give up"
            )
        if by_cls["batch"].shed == 0:
            raise AssertionError(
                "storm arm shed nothing — the overload never "
                "materialized (workload drifted)"
            )
        if tr.retries == 0:
            raise AssertionError(
                "storm arm never retried — the retry storm is dead "
                "(spec drifted)"
            )
    elif tr.sheds != 0:
        raise AssertionError(
            f"clean fleet shed {tr.sheds} requests — it is not "
            "actually uncommitted (capacity drifted)"
        )
    done = {n: by_cls[n].completed for n in by_cls}
    return {
        "replicas": setup[
            "n_replicas_storm" if storm else "n_replicas_clean"],
        "requests": tr.submitted,
        "digest": tr.digest,
        "peak_open": tr.peak_open,
        "ticks": tr.ticks,
        "wall_s": tr.wall_s,
        "tokens_per_s": rep.tokens_per_s,
        "kills": rep.kills,
        "readmitted": rep.readmitted,
        "dropped": rep.dropped,
        "sheds": tr.sheds,
        "retries": tr.retries,
        "abandoned": tr.abandoned,
        "shed_rids": tr.shed_rids,
        "shed_frac": (tr.abandoned / tr.submitted
                      if tr.submitted else 0.0),
        "classes": {
            c.name: {
                "completed": c.completed,
                "ttft_p99_s": c.ttft_p99_s,
                "goodput_frac": c.goodput_frac,
                "sheds": c.shed,
                "shed_frac": (c.shed / (c.completed + c.shed)
                              if c.completed + c.shed else 0.0),
            }
            for c in rep.classes
        },
        "completed_latency": done.get("latency", 0),
        "completed_batch": done.get("batch", 0),
    }


# ---- the config-22 workload (one definition) -----------------------------


def bench_reqtrace(mesh, cfg, scfg, setup: dict, traced: bool) -> dict:
    """One config-22 arm: the config-19 chaos workload (replica kills +
    stall + head-of-queue re-admission) over a fresh fleet, with or
    without a fleet-wide :class:`~tpuscratch.obs.reqtrace.ReqTracer`
    attached.  The tentpole claims are asserted HERE (one definition
    for the record config and the tests): every drained request's
    bucket decomposition sums to its e2e latency EXACTLY
    (``RequestTrace.check`` raises inside ``collect`` every fleet tick
    — the live half of the gate, re-asserted over the full forest at
    drain), at least one kill victim's trace carries wasted work, and
    the exported span forest passes the extended (async + flow event)
    Chrome-trace validator.  Digest bit-identity between a traced and
    an untraced arm — tracing observes, never perturbs — is the record
    config's cross-arm check; the row carries the digest for it."""
    from tpuscratch.obs.reqtrace import ReqTracer
    from tpuscratch.obs.trace import validate_chrome_trace
    from tpuscratch.serve.engine import ServeEngine
    from tpuscratch.serve.router import FleetRouter, RouterConfig, SLOClass

    rcfg = RouterConfig(classes=tuple(
        SLOClass(n, target=t) for n, t in setup["classes"]
    ))
    tracer = ReqTracer(sample_rate=1.0) if traced else None
    router = FleetRouter(
        [ServeEngine(mesh, cfg, scfg)
         for _ in range(setup["n_replicas"])],
        rcfg=rcfg,
        chaos=chaos_plan_for(setup),
        tracer=tracer,
    )
    tr = run_traffic(router, TraceGenerator(setup["tcfg"]),
                     setup["n_requests"],
                     open_budget=setup["open_budget"])
    rep = tr.report
    if rep.dropped != 0:
        raise AssertionError(
            f"zero-loss law violated: {rep.dropped} dropped"
        )
    if rep.readmitted == 0:
        raise AssertionError(
            "chaos arm re-admitted nothing — the kills fired on empty "
            "replicas (workload/schedule drifted)"
        )
    row = {
        "traced": int(traced),
        "replicas": setup["n_replicas"],
        "requests": tr.submitted,
        "digest": tr.digest,
        "peak_open": tr.peak_open,
        "ticks": tr.ticks,
        "wall_s": tr.wall_s,
        "tokens_per_s": rep.tokens_per_s,
        "kills": rep.kills,
        "readmitted": rep.readmitted,
    }
    if traced:
        tracer.collect()
        traces = list(tracer.traces.values())
        if not traces:
            raise AssertionError("traced arm collected zero traces")
        for t in traces:
            t.check()  # exact decomposition, re-asserted over the forest
        if not any(t.buckets["waste"] > 0 for t in traces):
            raise AssertionError(
                "no trace carries wasted work — the kill victims' "
                "re-prefill legs went missing (lineage drifted)"
            )
        validate_chrome_trace(tracer.chrome_trace())
        row["n_traces"] = len(traces)
        row["waste_traces"] = sum(
            1 for t in traces if t.buckets["waste"] > 0
        )
        for cls, fields in tracer.decomposition().items():
            for name, st in fields.items():
                if name in ("e2e", "ttft"):
                    continue
                row[f"decomp_{name}_s_{cls}"] = st["mean"]
    return row
