"""Chip race: Adam update forms over a ~180M-param synthetic tree
(round 5, VERDICT r4 weak #4 / next #7).

Variants:
  3-map   : the trainer's round-4 form (three jax.tree.maps: mu, nu, w)
  1-map   : single tree.map computing (w', m', v') per leaf in one
            closure (tests whether XLA's fusion was the gap)
  pallas  : ops/adam.py fused single-pass kernel, f32 moments
  pallas-bf16m : same kernel, bf16 moment storage (20 B/element)

Marginal ms/update by scanning ``rounds`` updates with the grads
perturbed per round (so nothing hoists).  The 7-access/element f32
roofline at ~700 GB/s is ~7.2 ms for 180M params; 3-map measured ~13.8
in the composed step.

Usage: python -m tpuscratch.bench.adam_bench [rounds]
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np

from tpuscratch.bench.timing import time_device
from tpuscratch.models.transformer import _adam_update
from tpuscratch.ops.adam import fused_adam_tree

LEAVES = {
    "wq": (4, 1024, 1024), "wk": (4, 1024, 1024),
    "wv": (4, 1024, 1024), "wo": (4, 1024, 1024),
    "w1": (4, 4, 1024, 4096), "w2": (4, 4, 4096, 1024),
    "emb": (50257, 1024), "head": (1024, 50257),
}  # ~180M params


def make_tree(rng, dtype=jnp.float32):
    return {
        k: jnp.asarray(rng.standard_normal(s) * 0.01, dtype)
        for k, s in LEAVES.items()
    }


@functools.partial(jax.jit, static_argnames=("form", "rounds"))
def run(params, grads, mu, nu, form, rounds):
    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-3

    def body(carry, _):
        params, mu, nu, t = carry
        g = jax.tree.map(lambda x: x + t * 1e-30, grads)
        t = t + 1.0
        tf = t
        alpha = lr * jnp.sqrt(1.0 - b2**tf) / (1.0 - b1**tf)
        if form == "3-map":
            opt = {"mu": mu, "nu": nu, "t": t.astype(jnp.int32) - 1}
            params, opt = _adam_update(params, opt, g, lr, b1, b2, eps)
            mu, nu = opt["mu"], opt["nu"]
        elif form.startswith("1-map"):
            def upd(w, gg, m, v):
                # bf16-moment storage: accumulate f32, store back quantized
                m2 = b1 * m.astype(jnp.float32) + (1.0 - b1) * gg
                v2 = b2 * v.astype(jnp.float32) + (1.0 - b2) * gg * gg
                return (w - alpha * m2 / (jnp.sqrt(v2) + eps),
                        m2.astype(m.dtype), v2.astype(v.dtype))

            out = jax.tree.map(upd, params, g, mu, nu)
            params = jax.tree.map(lambda o: o[0], out,
                                  is_leaf=lambda x: isinstance(x, tuple))
            mu = jax.tree.map(lambda o: o[1], out,
                              is_leaf=lambda x: isinstance(x, tuple))
            nu = jax.tree.map(lambda o: o[2], out,
                              is_leaf=lambda x: isinstance(x, tuple))
        else:  # pallas forms
            params, mu, nu = fused_adam_tree(params, g, mu, nu, alpha,
                                             b1, b2, eps)
        return (params, mu, nu, t), ()

    (params, mu, nu, _), _ = jax.lax.scan(
        body, (params, mu, nu, jnp.float32(0)), None, length=rounds
    )
    return params["emb"][0, 0]


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    rng = np.random.default_rng(17)
    params = make_tree(rng)
    grads = make_tree(rng)
    n = sum(np.prod(s) for s in LEAVES.values())
    print(f"# {n / 1e6:.1f}M params, {rounds} scanned updates")

    # correctness gate before any timing: the pallas kernel must match
    # the tree-map oracle (a wrong-but-fast kernel must not win a race)
    mu0 = make_tree(rng)
    nu0 = jax.tree.map(jnp.abs, make_tree(rng))
    w_a = run(params, grads, mu0, nu0, "3-map", 3)
    w_b = run(params, grads, mu0, nu0, "pallas", 3)
    err = float(jnp.abs(w_a - w_b))
    print(f"# pallas vs 3-map |diff| after 3 updates: {err:.3e}")
    assert err < 1e-5, "fused Adam kernel disagrees with the oracle"

    for form, mdt in (("3-map", jnp.float32), ("1-map", jnp.float32),
                      ("1-map-bf16m", jnp.bfloat16),
                      ("pallas", jnp.float32),
                      ("pallas-bf16m", jnp.bfloat16)):
        mu = make_tree(rng, mdt)
        nu = jax.tree.map(lambda x: jnp.abs(x), make_tree(rng, mdt))
        try:
            r = time_device(run, params, grads, mu, nu, form, rounds,
                            warmup=1, iters=3, fence="readback")
        except Exception as e:
            print(f"# {form}: FAILED {str(e)[:160]}", flush=True)
            continue
        ms = r.p50 * 1e3 / rounds
        bytes_el = 28 if mdt == jnp.float32 else 20
        gbps = bytes_el * 1e-9 * n / (ms * 1e-3)
        print(f"# {form}: {ms:.2f} ms/update ({gbps:.0f} GB/s effective)",
              flush=True)


if __name__ == "__main__":
    main()
