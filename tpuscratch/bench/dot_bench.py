"""Distributed dot-product benchmark (mpicuda3/4 timing parity).

End-to-end: shard two vectors over the mesh, per-shard Pallas reduction,
one psum, report elements/s. The reference's wall-time convention —
every rank stamps begin/end, span = max(end)-min(begin) across ranks
(mpicuda3.cu:315-325) — collapses in a single-process mesh to a
block_until_ready bracket (all shards complete before the bracket closes);
on multi-process slices use ``timing.span_max_min`` over per-process
stamps. The NO_GPU_MALLOC_TIME carve-out is the warmup exclusion.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.comm import run_spmd
from tpuscratch.ops.reduction import local_dot_psum


def dot_program(mesh: Mesh, axis: str = "x", method: str = "full", block_rows: int = 512):
    return run_spmd(
        mesh,
        lambda a, b: local_dot_psum(a, b, axis, method=method, block_rows=block_rows),
        (P(axis), P(axis)),
        P(),
    )


def bench_dot(
    mesh: Mesh,
    n_elems: int = 100_000_000,
    axis: str = "x",
    method: str = "full",
    iters: int = 5,
    check: bool = True,
    fence: str = "block",
) -> BenchResult:
    """Time the distributed dot of ``n_elems`` f32 (BASELINE config 2)."""
    n_dev = mesh.devices.size
    n_elems = (n_elems // n_dev) * n_dev  # even shards
    x = jnp.ones(n_elems, dtype=jnp.float32)
    f = dot_program(mesh, axis, method)
    if check:
        got = float(f(x, x))
        if abs(got - n_elems) > 1e-3 * n_elems:
            raise AssertionError(f"dot self-check FAILED: {got} != {n_elems}")
    return time_device(
        f, x, x,
        iters=iters, warmup=2, fence=fence,
        name=f"dot {n_elems:.0e} f32 ({method})", items=n_elems,
        bytes_moved=2 * 4 * n_elems,
    )
