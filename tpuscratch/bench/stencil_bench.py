"""Stencil throughput benchmark: cell-updates/sec (the headline metric).

BASELINE configs 1 (1024^2 single device) and 4/5 (multi-chip meshes,
weak scaling). A measured iteration = one halo exchange + one 5-point
update of every core cell; steps are folded into one compiled scan so
per-step dispatch cost doesn't pollute the number.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.halo.driver import decompose, make_stencil_program
from tpuscratch.halo.exchange import HaloSpec
from tpuscratch.halo.layout import TileLayout
from tpuscratch.runtime.mesh import make_mesh_2d, topology_of


def bench_stencil(
    grid: tuple[int, int] = (1024, 1024),
    steps: int = 10,
    mesh: Optional[Mesh] = None,
    impl: str = "xla",
    iters: int = 5,
    dtype=jnp.float32,
    fence: str = "block",
) -> BenchResult:
    """cell-updates/s for ``steps`` iterations of the full pipeline on a
    ``grid`` world decomposed over ``mesh`` (default: all devices)."""
    mesh = mesh if mesh is not None else make_mesh_2d()
    topo = topology_of(mesh, periodic=True)
    rows, cols = topo.dims
    if grid[0] % rows or grid[1] % cols:
        raise ValueError(f"grid {grid} not divisible by mesh {topo.dims}")
    halo, unroll, label = 1, None, impl
    if impl.startswith("deep"):
        # "deep:K" / "deep-pallas:K" = trapezoid scheme, K-deep halo
        # (K steps per exchange)
        impl, _, depth = impl.partition(":")
        halo = int(depth) if depth else min(steps, 8)
    elif impl.startswith("resident"):
        # "resident[:U]" = whole grid VMEM-resident, U-way inner unroll
        impl, _, u = impl.partition(":")
        unroll = int(u) if u else 8
    elif impl.endswith("+unroll"):
        impl, unroll = impl.removesuffix("+unroll"), steps
    layout = TileLayout(grid[0] // rows, grid[1] // cols, halo, halo)
    spec = HaloSpec(layout=layout, topology=topo, axes=tuple(mesh.axis_names))
    program = make_stencil_program(mesh, spec, steps, impl=impl, unroll=unroll)

    rng = np.random.default_rng(0)
    world = rng.standard_normal(grid).astype(np.dtype(dtype) if dtype != jnp.bfloat16 else np.float32)
    tiles = jnp.asarray(decompose(world, topo, layout), dtype=dtype)

    return time_device(
        program, tiles,
        iters=iters, warmup=2, fence=fence,
        name=f"stencil {grid[0]}x{grid[1]} x{steps} on {rows}x{cols} ({label})",
        items=grid[0] * grid[1] * steps,
    )


def bench_stencil3d(
    grid: tuple[int, int, int] = (64, 64, 64),
    steps: int = 10,
    mesh: Optional[Mesh] = None,
    impl: str = "compact",
    iters: int = 5,
    fence: str = "block",
    coeffs=None,
) -> BenchResult:
    """cell-updates/s for the 3D face-halo 7-point pipeline
    (halo.halo3d) on a ``grid`` world over a 3-axis mesh."""
    import jax

    from tpuscratch.halo.halo3d import (
        HaloSpec3D,
        TileLayout3D,
        decompose3d,
        decompose3d_cores,
        make_stencil3d_program,
    )
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.runtime.topology import CartTopology, factor3d

    if mesh is None:
        mesh = make_mesh(factor3d(len(jax.devices())), ("z", "row", "col"))
    dims = tuple(mesh.devices.shape)
    if any(g % d for g, d in zip(grid, dims)):
        raise ValueError(f"grid {grid} not divisible by mesh {dims}")
    topo = CartTopology(dims, (True,) * 3)
    layout = TileLayout3D(tuple(g // d for g, d in zip(grid, dims)))
    spec = HaloSpec3D(
        layout=layout, topology=topo, axes=tuple(mesh.axis_names),
        neighbors=26 if coeffs is not None and len(coeffs) == 27 else 6,
    )
    if coeffs is None:
        program = make_stencil3d_program(mesh, spec, steps, impl=impl)
    else:
        program = make_stencil3d_program(mesh, spec, steps, tuple(coeffs),
                                         impl)
    rng = np.random.default_rng(0)
    world = rng.standard_normal(grid).astype(np.float32)
    if impl.startswith(("compact", "stream")):
        tiles = jnp.asarray(decompose3d_cores(world, dims))
    else:
        tiles = jnp.asarray(decompose3d(world, topo, layout))
    cells = grid[0] * grid[1] * grid[2]
    return time_device(
        program, tiles, iters=iters, warmup=2, fence=fence,
        name=f"stencil3d {grid[0]}x{grid[1]}x{grid[2]} x{steps} on "
             f"{dims[0]}x{dims[1]}x{dims[2]} "
             f"({impl}{'' if coeffs is None else f',{len(coeffs)}pt'})",
        items=cells * steps,
    )
