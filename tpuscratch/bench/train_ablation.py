"""Row-11 ablation harness (BASELINE row 11, round 4): where the
composed train step spends its time under bf16, and the degenerate
pipeline-parallel rows.

Run on a chip: ``python -m tpuscratch.bench.train_ablation``.
Findings (v5e, 20-step scans, ms/step): f32 116.0 / bf16 110.6 —
fwd-only 39.1 vs 33.6 (bf16's whole gain; DEFAULT f32 matmuls already
run single-pass bf16 on the MXU), backward dtype-insensitive, MoE
backward 4.6x its forward (scatter transpose + cap-padded dW),
pp 1x1x1 M=1 117.6 (+1.4% schedule overhead), M=4 121.7.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuscratch.bench.train_bench import bench_train
from tpuscratch.bench.timing import time_device
from tpuscratch.comm import run_spmd
from tpuscratch.models.transformer import (
    TransformerConfig, _loss, init_params, param_spec, param_spec_pp,
    stack_layers, train_step_pp_fn,
)
from tpuscratch.runtime.mesh import make_mesh

BASE = TransformerConfig(
    d_model=1024, n_heads=8, n_experts=4, d_ff=4096, n_layers=4,
    capacity_factor=2.0, attn_impl="pallas",
)
B, S, STEPS = 8, 2048, 20


def run(label, cfg, optimizer="sgd"):
    mesh = make_mesh((1, 1), ("dp", "sp"))
    try:
        r = bench_train(mesh, cfg, batch=B, seq=S, steps=STEPS, iters=3,
                        optimizer=optimizer)
        ms = r.p50 / STEPS * 1e3
        print(f"{label}: {ms:.1f} ms/step  {r.items_per_s:.3e} tok/s",
              flush=True)
        return ms
    except Exception as e:
        print(f"{label}: FAILED {str(e)[:300]}", flush=True)
        return None


def fwd_only(label, cfg):
    mesh = make_mesh((1, 1), ("dp", "sp"))
    pspec = param_spec(cfg)

    def body(params, x, y):
        def one(xc, _):
            loss = _loss(params, xc, y, cfg, "sp", "dp")
            return xc + loss.astype(xc.dtype) * 1e-6, loss

        xf, losses = lax.scan(one, x, None, length=STEPS)
        return xf[0, 0, 0] + losses[-1]

    prog = run_spmd(mesh, body, (pspec, P("dp", "sp"), P("dp", "sp")), P())
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, S, cfg.d_model)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, S, cfg.d_model)).astype(np.float32))
    params = init_params(0, cfg)
    r = time_device(prog, params, x, y, iters=3, warmup=1, fence="readback",
                    name=label)
    print(f"{label}: {r.p50 / STEPS * 1e3:.1f} ms/step", flush=True)


def pp_row_bench(cfg, batch, seq, steps, n_micro, iters=3,
                 fence="readback"):
    """tokens/s of the 3-axis train step on the degenerate 1x1x1 mesh
    (schedule-overhead row; the recorder's config 11 calls this)."""
    mesh = make_mesh((1, 1, 1), ("dp", "sp", "stage"))
    pspec = param_spec_pp(cfg)
    step = train_step_pp_fn(cfg, lr=1e-3, n_micro=n_micro)

    def body(params, x, y):
        def one(p, _):
            p, loss = step(p, x, y)
            return p, loss

        params, losses = lax.scan(one, params, None, length=steps)
        return params, losses[-1]

    prog = run_spmd(mesh, body, (pspec, P("dp", "sp"), P("dp", "sp")),
                    (pspec, P()))
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32)
    )
    y = jnp.asarray(
        rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32)
    )
    stacked = stack_layers(init_params(0, cfg))
    _, loss = prog(stacked, x, y)
    assert np.isfinite(float(loss)), float(loss)
    return time_device(
        prog, stacked, x, y, iters=iters, warmup=1, fence=fence,
        name=(f"train-pp d{cfg.d_model} L{cfg.n_layers} M={n_micro} "
              f"b{batch} s{seq} x{steps} on 1x1x1"),
        items=batch * seq * steps,
    )


def pp_row(n_micro):
    r = pp_row_bench(BASE, batch=B, seq=S, steps=STEPS, n_micro=n_micro)
    ms = r.p50 / STEPS * 1e3
    print(f"pp degenerate 1x1x1 M={n_micro}: {ms:.1f} ms/step  "
          f"{r.items_per_s:.3e} tok/s", flush=True)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    bf = dataclasses.replace(BASE, compute_dtype="bfloat16")
    run("f32 full (row-11 anchor)", BASE)
    run("bf16 full", bf)
    run("bf16 attn=xla (dense hops)", dataclasses.replace(bf, attn_impl="xla"))
    run("bf16 e=1 cap=1 (MoE share)", dataclasses.replace(
        bf, n_experts=1, capacity_factor=1.0))
    run("bf16 adam", bf, optimizer="adam")
    fwd_only("bf16 fwd-only (loss scan)", bf)
    fwd_only("f32 fwd-only (loss scan)", BASE)
    fwd_only("bf16 e=1 cap=1 fwd-only", dataclasses.replace(
        bf, n_experts=1, capacity_factor=1.0))
    run("f32 adam", BASE, optimizer="adam")
    pp_row(1)
    pp_row(4)
