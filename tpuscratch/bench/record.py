"""Record the BASELINE.md measurement configs on whatever is available.

``python -m tpuscratch.bench.record [--configs 1,2] [--json PATH]``

The reference publishes no numbers (SURVEY.md §6) — this harness produces
the ones this repo establishes. Configs follow BASELINE.md:

1. 2D 5-point stencil, 1024^2, single device     (real chip when present)
2. distributed dot-product psum, 1e8 f32         (real chip when present)
3. pingpong sweep 8 B - 128 MB                   (needs >= 2 devices; on a
   single-chip session this runs on a virtual CPU mesh — a methodology
   proxy, NOT an ICI number, and is labeled as such)
4. 8192^2 stencil on a 4x4 mesh                  (16 devices; CPU proxy
   on single-chip sessions)
5. weak-scaling stencil, fixed per-chip tile     (ditto)
6. flash attention TFLOP/s, causal + full        (real chip when present)
7. per-collective busBW sweep                    (needs >= 2 devices;
   CPU proxy on single-chip sessions)
8. matmul-form pair-DFT round-trip TFLOP/s       (real chip when present)
9. 3D 7-point stencil cell-updates/s             (per-device tile scales
   with the mesh; real chip when present)
10. remote-DMA halo kernel, 1024^2 self-wrap     (real chip when present)
11. composed-training tokens/s, f32 + bf16       (real chip when present)
12. serve decode tokens/s + per-token p50/p99 over a batch-size sweep,
    plus the quantized-KV static bytes/token row and the speculative-
    decoding row (tokens/s + accept length on an accept-friendly
    prompt)                                      (real chip when present)
13. replicated vs ZeRO-sharded training tokens/s at dp in {1,2,4} with
    the static grad-sync wire bytes beside each rate, plus the
    deferred-sync accumulation sweep             (CPU proxy off-chip)
14. ShardingPlan overlap ablation: plan-composed ZeRO tokens/s +
    step time at pp x dp in {1,2}^2, decomposed (overlap) vs serial
    sync schedule, ledger-asserted equal wire bytes
                                                 (CPU proxy off-chip)
15. solver weak-scaling + communication-avoiding ablation: supervised
    3D multigrid cells/s over growing meshes with analytic comm_ratio,
    s-step smoothing vs per-sweep (ledger ppermutes/cycle), classic vs
    pipelined CG (ledger psums/iter)             (CPU proxy off-chip)
16. elastic-FT goodput under one injected preemption: blocking vs
    async checkpointing for the trainer / halo driver / solver runner,
    badput bucket shares summing to wall exactly (CPU proxy off-chip)
17. fleet router: a multi-tenant arrival mix drained through N engine
    replicas, prefix affinity on vs off (identical greedy outputs
    asserted) — aggregate tokens/s, per-class p99 TTFT, cross-replica
    prefill_frac, sub-page sharing counters      (CPU proxy off-chip)

Each config prints one JSON line with the platform recorded, so CPU-proxy
numbers can never masquerade as chip numbers.
"""

from __future__ import annotations

import argparse
import json
import sys
import os


from tpuscratch.runtime.hostenv import on_device_requested


class Needs(RuntimeError):
    """A config's hardware prerequisite is absent — an expected skip, not
    a failure (exit code stays 0)."""


def _platform():
    import jax

    return jax.default_backend()


def _emit(out: list, **kv) -> None:
    kv.setdefault("platform", _platform())
    out.append(kv)
    print(json.dumps(kv), flush=True)


def _race(config_no, impls, bench_fn):
    """(best result, winning impl): run ``bench_fn(impl)`` for each impl,
    report each to stderr, return the items_per_s argmax. A failing impl
    is reported and skipped; ALL failing raises."""
    best, best_impl = None, None
    for impl in impls:
        try:
            r = bench_fn(impl)
        except Exception as e:  # one impl failing shouldn't kill the config
            print(f"# config {config_no} impl {impl} failed: {e}",
                  file=sys.stderr)
            continue
        print(f"# {r.summary()}", file=sys.stderr)
        if best is None or r.items_per_s > best.items_per_s:
            best, best_impl = r, impl
    if best is None:
        raise RuntimeError(f"all config-{config_no} impls failed")
    return best, best_impl


def _best_stencil(impls, config_no, grid, steps, mesh, iters):
    """2D-stencil specialization of :func:`_race`."""
    from tpuscratch.bench.stencil_bench import bench_stencil

    return _race(
        config_no, impls,
        lambda impl: bench_stencil(grid, steps, mesh=mesh, impl=impl,
                                   iters=iters, fence="readback"),
    )


def two_phase_stencil(impls, config_no, grid, mesh, iters,
                      screen_steps, final_steps):
    """Screen ``impls`` at ``screen_steps``, then re-measure the winner at
    ``final_steps`` so the transport's fixed per-invocation cost (~150-200
    ms on the axon tunnel) amortizes to noise. Returns (best, impl,
    final_ok): ``final_ok`` False means every re-measure failed and
    ``best`` is the screen-phase number, whose fixed-cost share
    understates the chip rate."""
    from tpuscratch.bench.stencil_bench import bench_stencil

    best, best_impl = _best_stencil(impls, config_no, grid, screen_steps,
                                    mesh, iters)
    if not isinstance(final_steps, tuple):
        final_steps = (final_steps,)
    attempts = [s for s in final_steps if s > screen_steps]
    for steps in attempts:
        try:
            r = bench_stencil(grid, steps, mesh=mesh, impl=best_impl,
                              iters=iters, fence="readback")
            print(f"# final: {r.summary()}", file=sys.stderr)
            return r, best_impl, True
        except Exception as e:
            print(f"# re-measure at {steps} steps failed: {e}",
                  file=sys.stderr)
    # no re-measure needed (screen already at/above target) => ok; every
    # attempt failed => screen number stands but is flagged not-ok
    return best, best_impl, not attempts


def config1_stencil_single(out: list, iters: int = 3) -> None:
    import jax

    from tpuscratch.runtime.mesh import make_mesh_2d

    on_tpu = jax.default_backend() == "tpu"
    best, _, final_ok = two_phase_stencil(
        ("xla", "deep:16", "deep-pallas:16", "resident:8"), 1,
        (1024, 1024), make_mesh_2d((1, 1)), iters,
        screen_steps=20000 if on_tpu else 50,
        final_steps=2000000 if on_tpu else 50)
    _emit(
        out,
        config=1,
        metric="stencil2d_1024x1024_cell_updates_per_s",
        value=best.items_per_s,
        p50_s=best.p50,
        detail=best.name + ("" if final_ok else ":screen-only"),
    )


def config2_dot(out: list, iters: int = 10) -> None:
    import jax

    from tpuscratch.bench.dot_bench import bench_dot
    from tpuscratch.runtime.mesh import make_mesh_1d

    mesh = make_mesh_1d("x", devices=jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    # latency: one fenced invocation (the reference's per-call number);
    # throughput: enough scanned rounds to amortize the fixed transport
    # cost down to the HBM roofline
    lat = bench_dot(mesh, n_elems=100_000_000, iters=iters, check=True,
                    fence="readback")
    _emit(
        out,
        config=2,
        metric="dot_1e8_f32_call_latency_s",
        value=lat.p50,
        detail=lat.name,
        n_devices=mesh.devices.size,
    )
    # throughput: screen the three reduction strategies (Pallas full /
    # Pallas partials / fused XLA — all within ~5% of the HBM roofline
    # once the benchmark preps lane blocks outside the scan), then
    # re-measure the winner with enough rounds to amortize the fixed
    # transport cost
    screen_rounds, final_rounds = (200, 2000) if on_tpu else (2, 2)
    it = max(2, iters // 3)
    # plausibility bound: default is tuned to v5e-class HBM (dot_bench
    # docstring); on faster-HBM parts set TPUSCRATCH_DOT_MAX_GBPS to
    # ~1.3x that part's per-core roofline
    import os

    max_gbps = float(os.environ.get("TPUSCRATCH_DOT_MAX_GBPS", "1000"))
    best = None
    for m in ("full", "partials", "xla"):
        try:
            r = bench_dot(mesh, n_elems=100_000_000, iters=it, check=True,
                          fence="readback", method=m, rounds=screen_rounds,
                          max_gbps=max_gbps)
        except Exception as e:
            print(f"# config 2 method {m} failed: {e}", file=sys.stderr)
            continue
        print(f"# {r.summary()}", file=sys.stderr)
        if best is None or r.items_per_s > best[0].items_per_s:
            best = (r, m)
    if best is None:
        raise RuntimeError("all config-2 methods failed")
    thr = best[0]
    screen_fallback = False
    if final_rounds > screen_rounds:
        try:
            thr = bench_dot(mesh, n_elems=100_000_000, iters=it, check=True,
                            fence="readback", method=best[1],
                            rounds=final_rounds, max_gbps=max_gbps)
            print(f"# final: {thr.summary()}", file=sys.stderr)
        except Exception as e:  # keep the valid screen number
            screen_fallback = True
            print(f"# config 2 final re-measure failed, using screen: {e}",
                  file=sys.stderr)
            print(
                f"# WARNING: config 2 value is the {screen_rounds}-round "
                "screen measurement — the fixed per-invocation transport "
                "cost is NOT amortized as in the "
                f"{final_rounds}-round methodology; treat as a lower bound",
                file=sys.stderr,
            )
    _emit(
        out,
        config=2,
        metric="dot_1e8_f32_elements_per_s",
        value=thr.items_per_s,
        p50_s=thr.p50,
        detail=thr.name + (" [screen-fallback]" if screen_fallback else ""),
        n_devices=mesh.devices.size,
    )


def config3_pingpong(out: list, iters: int = 10) -> None:
    import jax

    from tpuscratch.bench.pingpong import DEFAULT_SIZES, sweep, verify_echo
    from tpuscratch.runtime.mesh import make_mesh_1d

    degenerate = len(jax.devices()) < 2 and on_device_requested()
    if len(jax.devices()) < 2 and not degenerate:
        raise Needs(
            "pingpong needs >= 2 devices; set TPUSCRATCH_ON_DEVICE=1 to "
            "run the full code path as a 1-device self-loop"
        )
    n = min(2, len(jax.devices()))
    mesh = make_mesh_1d("x", devices=jax.devices()[:n])
    if not verify_echo(mesh, "x", 1024):
        raise AssertionError("pingpong echo self-check FAILED")
    results = sweep(mesh, sizes_bytes=DEFAULT_SIZES, iters=iters,
                    fence="readback")
    peak = max(results, key=lambda r: r.gbps)
    small = results[0]
    _emit(
        out,
        config=3,
        metric="pingpong_peak_GBps",
        value=peak.gbps,
        p50_latency_s_smallest=small.p50,
        detail=f"peak at {peak.name}; echo PASSED"
        + (" [degenerate 1-device self-loop]" if degenerate else ""),
        degenerate=degenerate,
        sweep=[
            {"bytes": r.bytes_moved // 2, "p50_s": r.p50, "gbps": r.gbps}
            for r in results
        ],
    )


def config4_stencil_mesh(out: list, iters: int = 5) -> None:
    import jax

    from tpuscratch.runtime.mesh import make_mesh_2d
    from tpuscratch.runtime.topology import factor2d

    avail = len(jax.devices())
    degenerate = avail < 16 and on_device_requested()
    if avail < 16 and not degenerate:
        raise Needs(
            "config 4 needs a 4x4 mesh (16 devices); set "
            "TPUSCRATCH_ON_DEVICE=1 to run degenerately on what's visible"
        )
    # degenerate counts clamp to a power of two so the fixed 8192^2 grid
    # stays divisible by the mesh dims
    n = 16 if avail >= 16 else 1 << (avail.bit_length() - 1)
    dims = (4, 4) if n == 16 else factor2d(n)
    mesh = make_mesh_2d(dims, devices=jax.devices()[:n])
    # the remote-DMA kernels are real contenders on chips; under the
    # CPU proxy they would run in the Mosaic interpreter (hours at this
    # size).  'dma' (VMEM-resident) correctly refuses the 1 GB core and
    # records the structural loss; 'dma-hbm' (round 4) streams the core
    # in row bands
    on_tpu = jax.default_backend() == "tpu"
    # round 5: the streamed kernel's ghost-column mode serves ANY
    # cartesian layout, so stream:k races on the TRUE mesh alongside
    # the per-step paths (no more row-slab mesh swap).  Screen at 320
    # steps so every candidate executes its labeled fold depth and the
    # ~190 ms fixed tunnel cost does not rank the race on noise, then
    # re-measure the winner at >= 2048 steps so the recorded value is
    # marginal-dominant (within ~1.3x of the true per-step rate —
    # config 1's own discipline applied here, VERDICT r4 weak #5)
    impls = ("xla", "overlap", "deep:4") + (
        ("dma", "dma-hbm", "stream:16", "stream:32") if on_tpu else ()
    )
    steps4 = 320 if on_tpu else 10
    best, _, final_ok = two_phase_stencil(
        impls, 4, (8192, 8192), mesh, iters,
        screen_steps=steps4, final_steps=2048 if on_tpu else 10)
    _emit(
        out,
        config=4,
        metric="stencil2d_8192x8192_4x4_cell_updates_per_s_per_chip",
        value=best.items_per_s / n,
        # ':screen-only' = every long re-measure failed and this value is
        # the screen-phase number, whose fixed-cost share understates the
        # chip rate — BASELINE rows must show which discipline produced
        # the number (ADVICE r5)
        p50_s=best.p50,
        detail=best.name
        + ("" if final_ok else ":screen-only")
        + (f" [degenerate {dims[0]}x{dims[1]} mesh]" if n < 16 else ""),
        n_devices=n,
    )


def config5_weak_scaling(out: list, per_chip: int = 1024, iters: int = 3) -> None:
    import jax

    from tpuscratch.bench.weak_scaling import bench_weak_scaling, efficiency

    counts = [n for n in (1, 2, 4, 8, 16) if n <= len(jax.devices())]
    degenerate = len(counts) < 2 and on_device_requested()
    if len(counts) < 2 and not degenerate:
        raise Needs(
            "weak scaling needs >= 2 devices; set TPUSCRATCH_ON_DEVICE=1 "
            "to exercise the harness degenerately on one chip"
        )
    pts = bench_weak_scaling(
        per_chip=(per_chip, per_chip), steps=10, device_counts=counts,
        iters=iters, fence="readback"
    )
    eff = efficiency(pts)
    _emit(
        out,
        config=5,
        metric="weak_scaling_efficiency",
        value=eff[counts[-1]],
        per_chip_tile=per_chip,
        points={str(n): e for n, e in eff.items()},
        halo_bytes_per_cell={
            str(p.n_devices): p.comm_ratio for p in pts
        },
        detail=f"per-chip rate at N vs N=1, tile {per_chip}^2 x10 steps"
        + (" [degenerate 1-chip]" if degenerate else ""),
    )


def config6_flash_attention(out: list, iters: int = 3) -> None:
    """Beyond-reference: flash-attention TFLOP/s (ops/attention.py).

    The reference has no attention; this records the framework's
    long-context MXU kernel so the number is reproducible rather than a
    one-off probe."""
    import jax

    from tpuscratch.bench.attention_bench import bench_attention

    on_tpu = jax.default_backend() == "tpu"
    for causal in (True, False):
        r = bench_attention(
            S=4096 if on_tpu else 64,
            H=8 if on_tpu else 2,
            D=128 if on_tpu else 16,
            causal=causal,
            rounds=2000 if on_tpu else 2,
            iters=iters,
        )
        print(f"# {r.summary()}", file=sys.stderr)
        _emit(
            out,
            config=6,
            metric=f"flash_attention_{'causal' if causal else 'full'}_tflops",
            value=r.items_per_s / 1e12,  # items = FLOPs
            p50_s=r.p50,
            detail=r.name,
        )


def config7_collectives(out: list, iters: int = 10) -> None:
    """Beyond-reference: per-collective busBW sweep (BASELINE row 7).

    Host-memory proxy on the CPU mesh; re-run on a slice for ICI."""
    import jax

    from tpuscratch.bench.collective_bench import sweep, verify
    from tpuscratch.runtime.mesh import make_mesh_1d

    n = min(8, len(jax.devices()))
    degenerate = n < 2 and on_device_requested()
    if n < 2 and not degenerate:
        raise Needs(
            "collective sweep needs >= 2 devices (use --cpu-devices 8, "
            "or TPUSCRATCH_ON_DEVICE=1 for a 1-device degenerate run)"
        )
    n = max(n, 1)
    mesh = make_mesh_1d("x", n)
    if not verify(mesh):
        raise AssertionError("collective echo-verify FAILED")
    on_tpu = jax.default_backend() == "tpu"
    peaks: dict[str, float] = {}
    for r in sweep(mesh, iters=iters,
                   fence="readback" if on_tpu else "block"):
        name = r.name.split()[0]
        peaks[name] = max(peaks.get(name, 0.0), r.gbps)
        print(f"# {r.summary()}", file=sys.stderr)
    _emit(
        out,
        config=7,
        metric="collective_busbw_peak_gbps",
        value=max(peaks.values()),
        peaks=peaks,
        degenerate=degenerate,
        detail=f"busBW peaks over 1KiB-4MiB/device on {n} devices; "
        "echo-verify PASSED"
        + (" [degenerate 1-device]" if degenerate else ""),
    )


def config8_dft(out: list, iters: int = 3) -> None:
    """Beyond-reference: pair-FFT round-trip (BASELINE row 8).

    Headline stays the 1024^2 direct-DFT TFLOP/s for continuity with the
    round-1 row, then the direct-vs-four-step crossover race: seconds
    per fwd+inv round trip at 1024^2 / 4096^2 / 8192^2, winner per size
    (cross-method FLOP rates are incomparable — the four-step does
    O(sqrt N) MACs/element — so the race metric is p50/round)."""
    import jax

    from tpuscratch.bench.fft_bench import bench_dft

    r = bench_dft(iters=iters)
    print(f"# {r.summary()}", file=sys.stderr)
    _emit(
        out,
        config=8,
        metric="pair_dft_roundtrip_tflops",
        value=r.items_per_s / 1e12,
        p50_s=r.p50,
        detail=f"{r.name} (precision=HIGHEST f32)",
    )

    on_tpu = jax.default_backend() == "tpu"
    # 512 brackets FOUR_STEP_MIN from below (its 16x32 sub-DFT factors
    # are where MXU efficiency should finally lose to the dense matmul)
    sizes = (512, 1024, 4096, 8192) if on_tpu else (64, 128)
    target_flops = 2e13 if on_tpu else 2e7  # ~1s of chip MXU work
    race: dict[str, dict] = {}
    for n in sizes:
        per: dict[str, float] = {}
        for method in ("direct", "four-step"):
            from tpuscratch.bench.fft_bench import pair_fft_flops

            per_round = pair_fft_flops(n, method, 1)
            # direct's trace constants are TWO (n, n) f32 DFT tables
            # (cos + sin, parallel/fft._dft_tables) — at 8192^2 that is
            # 536 MB of constants, which the tunnel's remote compile
            # rejects (observed: Broken pipe, wedging the harness).
            # Gate on the actual trigger: the table size, not the FLOP
            # count (4096's 134 MB compiles and races fine; 8192's
            # 536 MB does not).
            if method == "direct" and n * n * 4 * 2 > 2.0e8:
                print(f"# config 8 {method}@{n} skipped: {n}x{n} f32 "
                      f"cos+sin DFT tables ({n * n * 4 * 2 / 1e6:.0f} MB) "
                      "exceed the remote-compile constant budget; "
                      "structural DNF", file=sys.stderr)
                continue
            rounds = max(1, min(1000, int(target_flops / per_round)))
            try:
                r = bench_dft(n=n, rounds=rounds, iters=iters,
                              method=method,
                              fence="readback" if on_tpu else "block")
            except Exception as e:
                print(f"# config 8 {method}@{n} failed: {e}",
                      file=sys.stderr)
                continue
            per[method] = r.p50 / rounds
            print(f"# {r.summary()} -> {r.p50 / rounds * 1e3:.2f} ms/round",
                  file=sys.stderr)
        if per:
            winner = min(per, key=per.get)
            race[str(n)] = {
                "winner": winner,
                "s_per_roundtrip": per,
            }
    if race:
        # headline value pinned to the 1024^2 winner so the metric stays
        # comparable round over round regardless of which sizes race
        ref = race.get("1024") or race[max(race, key=int)]
        _emit(
            out,
            config=8,
            metric="pair_fft_crossover",
            value=ref["s_per_roundtrip"][ref["winner"]],
            race=race,
            detail="s per fwd+inv 2D round trip at 1024^2 (winner); "
            "full race in 'race'",
        )


def config9_stencil3d(out: list, iters: int = 3) -> None:
    """Beyond-reference: 3D 7-point stencil cell-updates/s (BASELINE row 9)."""
    import jax

    from tpuscratch.bench.stencil_bench import bench_stencil3d
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.runtime.topology import factor3d

    on_tpu = jax.default_backend() == "tpu"
    n = len(jax.devices())
    dims = factor3d(n)
    mesh = make_mesh(dims, ("z", "row", "col"))
    # per-DEVICE tile is fixed; the grid scales with the mesh so a slice
    # run measures real per-chip work, never a degenerate sliver
    tile = (256, 512, 512) if on_tpu else (8, 8, 8)
    grid = tuple(t * d for t, d in zip(tile, dims))
    # screen the kernel paths at a modest step count, re-measure the
    # winner at full depth (the config-1 two-phase methodology).  The
    # deep-z streamed kernel (stream:k) folds k substeps per manual-DMA
    # pass — the only lever past the chip's ~330 GB/s DMA-fabric copy
    # bound (BASELINE row 9) — and needs a z-slab (or 1-chip) mesh;
    # compact-asm serves distributed y/x meshes
    z_slab = dims[1] == 1 and dims[2] == 1
    if on_tpu:
        impls = ("compact-asm", "stream:4") if z_slab else ("compact-asm",)
    else:
        impls = ("compact",)
    r, winner = _race(
        9, impls,
        lambda impl: bench_stencil3d(
            grid=grid, steps=300 if on_tpu else 3, mesh=mesh, impl=impl,
            iters=iters, fence="readback" if on_tpu else "block",
        ),
    )
    steps_measured = 300 if on_tpu else 3
    screen_only = False
    if on_tpu:
        screen_only = True
        try:
            r = bench_stencil3d(
                grid=grid, steps=3000, mesh=mesh, impl=winner,
                iters=iters, fence="readback",
            )
            steps_measured = 3000
            screen_only = False
            print(f"# final: {r.summary()}", file=sys.stderr)
        except Exception as e:
            print(f"# config 9 final re-measure failed, using screen: {e}",
                  file=sys.stderr)
    extra = {"screen_only": True} if screen_only else {}
    _emit(
        out,
        config=9,
        metric="stencil3d_cell_updates_per_s",
        value=r.items_per_s,
        p50_s=r.p50,
        steps=steps_measured,
        detail=r.name,
        **extra,
    )


def config10_dma_halo(out: list, iters: int = 3) -> None:
    """Remote-DMA halo kernel microbench (BASELINE row 10): the
    driver-spec-named structural-overlap mechanism, raced in its self-wrap
    form on the single chip against its XLA-scheduled and VMEM-resident
    rivals. Its real value is multi-chip (ghost strips on the DMA engine
    while the interior computes); this row pins the reproducible
    single-chip number that PARITY.md used to carry as prose."""
    import jax

    from tpuscratch.runtime.mesh import make_mesh_2d

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        # the Mosaic interpreter at 1024^2 takes hours; smoke the path
        # at a toy size so the harness stays CI-runnable
        grid, steps = (64, 64), 4
        impls = ("overlap", "dma", "dma-deep:4")
    else:
        grid, steps = (1024, 1024), 20000
        impls = ("overlap", "dma", "dma-deep:8", "resident:8")
    from tpuscratch.bench.stencil_bench import bench_stencil

    mesh = make_mesh_2d((1, 1))
    rows = {}
    for impl in impls:
        try:
            r = bench_stencil(grid, steps, mesh=mesh, impl=impl,
                              iters=iters, fence="readback")
        except Exception as e:
            print(f"# config 10 impl {impl} failed: {e}", file=sys.stderr)
            continue
        rows[impl] = r
        print(f"# {r.summary()}", file=sys.stderr)
    if not rows:
        raise RuntimeError("all config-10 impls failed")
    dma_best = max(
        (r for i, r in rows.items() if i.startswith("dma")),
        key=lambda r: r.items_per_s,
        default=None,
    )
    if dma_best is None:
        raise RuntimeError("no dma impl survived config 10")
    _emit(
        out,
        config=10,
        metric=f"dma_halo_{grid[0]}x{grid[1]}_cell_updates_per_s",
        value=dma_best.items_per_s,
        p50_s=dma_best.p50,
        us_per_step={i: r.p50 / steps * 1e6 for i, r in rows.items()},
        detail=dma_best.name,
    )


def config11_train(out: list, iters: int = 3) -> None:
    """Composed-training throughput (BASELINE row 11): tokens/s of the
    full dp x sp train step — ring attention + expert MoE + grad + SGD
    in one program — f32 and bf16, with the FLOP estimate recorded so
    the rate carries its own roofline argument."""
    import dataclasses

    import jax

    from tpuscratch.bench.train_bench import bench_train, train_flops_per_token
    from tpuscratch.models.transformer import TransformerConfig
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    base = (
        TransformerConfig(
            d_model=1024, n_heads=8, n_experts=4, d_ff=4096, n_layers=4,
            capacity_factor=2.0, attn_impl="pallas",
        )
        if on_tpu
        else TransformerConfig(
            d_model=32, n_heads=2, n_experts=2, d_ff=64, n_layers=1
        )
    )
    seq = 2048 if on_tpu else 16
    batch = 8 if on_tpu else 2
    emitted = 0
    for dtype in ("float32", "bfloat16"):
        cfg = dataclasses.replace(base, compute_dtype=dtype)
        try:
            r = bench_train(
                mesh=mesh, cfg=cfg, batch=batch, seq=seq,
                steps=20 if on_tpu else 2, iters=iters,
                fence="readback" if on_tpu else "block",
            )
        except Exception as e:
            print(f"# config 11 {dtype} failed: {e}", file=sys.stderr)
            continue
        fpt = train_flops_per_token(cfg, seq)
        print(f"# {r.summary()} -> {r.items_per_s:.3e} tok/s, "
              f"~{r.items_per_s * fpt / 1e12:.1f} TFLOP/s model",
              file=sys.stderr)
        _emit(
            out,
            config=11,
            metric=f"train_tokens_per_s_{dtype}",
            value=r.items_per_s,
            p50_s=r.p50,
            flops_per_token=fpt,
            model_tflops=r.items_per_s * fpt / 1e12,
            detail=r.name,
        )
        emitted += 1
    if not emitted:
        raise RuntimeError("all config-11 dtypes failed")

    # the 3-axis composed step (dp x sp x stage GPipe, round 4): the
    # degenerate 1x1x1 row records the schedule's single-chip overhead
    # vs the plain step above (stage-axis invariance itself is gated by
    # the dryrun's bit-exactness check)
    try:
        from tpuscratch.bench.train_ablation import pp_row_bench

        r = pp_row_bench(base, batch=batch, seq=seq,
                         steps=20 if on_tpu else 2,
                         n_micro=4 if on_tpu else 2, iters=iters,
                         fence="readback" if on_tpu else "block")
        print(f"# {r.summary()} -> {r.items_per_s:.3e} tok/s",
              file=sys.stderr)
        _emit(
            out,
            config=11,
            metric="train_pp_tokens_per_s",
            value=r.items_per_s,
            p50_s=r.p50,
            detail=r.name,
        )
    except Exception as e:
        print(f"# config 11 pp failed: {e}", file=sys.stderr)


def _median_of(runs, key):
    """The run whose ``key`` is the median — the ONE selection policy
    behind every noise-robust re-measure (``_median_run`` and the
    config-17 interleaved arms), so a future tuning changes them all
    together."""
    runs = sorted(runs, key=key)
    return runs[len(runs) // 2]


def _median_run(fn, key, k: int = 3):
    """Run ``fn`` ``k`` times and return the run whose ``key`` is the
    median — the noise-robust re-measure (ISSUE 14): on the 1-core CPU
    proxy, single-shot wall-clock rates swing up to ~40% on SAME-CODE
    control runs (a background process stealing the core mid-window),
    and the median run discards the stolen-window outliers while
    keeping one COHERENT run's fields (a field-wise median would mix
    runs and break cross-field consistency, e.g. ``value`` vs its own
    ``p50_s``).  Static counter fields are identical across runs by
    construction, so which run is picked never changes them."""
    return _median_of([fn() for _ in range(k)], key)


def config12_decode(out: list, obs_path=None) -> None:
    """Serving decode throughput/latency (tpuscratch.serve): steady-state
    engine ticks — continuous batching, paged KV cache, one compiled
    decode program — tokens/s and the per-token latency tail across a
    batch-size sweep (the throughput/SLO trade curve serving lives on).

    No ``iters`` knob: the latency percentiles come from per-tick
    samples within one continuous steady-state window
    (``default_decode_setup``'s ``measure_steps``), not from repeated
    invocations — repetitions would restart the engine and re-pay
    prefill, measuring admission rather than decode.  The wall-clock
    rows ARE re-measured median-of-3 (``_median_run``, ISSUE 14): each
    repeat is a complete window and the median-by-headline run is the
    row, so a background process stealing the core mid-window cannot
    masquerade as a code change — averaging across windows would
    instead blend the stolen window in.  With ``--obs`` the JSONL
    artifact carries ALL repeats' per-tick telemetry (each window is
    its own ``bench/decode`` event; match the emitted row by its
    tokens/s to find the median window's ticks).

    ``obs_path`` attaches an obs JSONL sink to the benched engines, so
    the recorded artifact carries per-tick queue depth, free-page
    watermark, and tick latency next to the headline tokens/s — a
    regression in this row is then diagnosable from the artifact
    (``python -m tpuscratch.obs.report <obs_path>``).

    Three rows: the headline fp32 non-speculative sweep (unchanged
    semantics — ``--check`` against pre-speculation artifacts stays
    apples-to-apples), a STATIC ``serve_kv_cache_bytes`` row proving the
    int8 page footprint (bytes per token + int8/f32 ratio, the
    ledger-verified half of the quantized-KV claim), and a
    ``serve_decode_spec`` row measuring speculative decoding on an
    accept-friendly periodic prompt — tokens/s, the same-workload
    non-speculative rate, their ratio (``spec_speedup``), and the mean
    accepted draft length (regression directions: bytes/ratio down,
    tokens-per-s/accept/speedup up — ``obs.regress``)."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from tpuscratch.bench.decode_bench import (
        accept_friendly_prompt,
        bench_decode,
        default_decode_setup,
        sweep,
    )
    from tpuscratch.obs.ledger import kv_cache_bytes
    from tpuscratch.obs.sink import open_sink
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve.kvcache import CacheGeometry, init_kv_cache

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, batches, kwargs = default_decode_setup(on_tpu)
    with open_sink(
        obs_path,
        run={"bench": "record/config12", "platform": jax.default_backend()},
        host=jax.process_index(),
    ) as sink:
        # median-of-3 re-measure on every wall-clock row below (the
        # ISSUE-14 noise-robust records satellite): each repeat is a
        # complete steady-state window, the median-by-headline run is
        # the row
        results = _median_run(
            lambda: sweep(mesh, cfg, scfg, batches, sink=sink, **kwargs),
            key=lambda rs: max(r.tokens_per_s for r in rs),
        )
        best = max(results, key=lambda r: r.tokens_per_s)
        _emit(
            out,
            config=12,
            metric="serve_decode_tokens_per_s",
            value=best.tokens_per_s,
            p50_s=best.p50_s,
            p99_s=best.p99_s,
            sweep=[
                {
                    "batch": r.n_slots,
                    "tokens_per_s": r.tokens_per_s,
                    "p50_s_per_token": r.p50_s,
                    "p99_s_per_token": r.p99_s,
                }
                for r in results
            ],
            detail=best.summary()
            + (f" [obs: {obs_path}]" if obs_path else ""),
        )

        # decode-sweep roofline (ISSUE 12): the achieved fraction of
        # peak HBM bandwidth on the sweep — static swept-byte
        # accounting (engine.cached_pages x ledger bytes/token) over
        # the measured wall, against the stated platform peak
        # (TPUSCRATCH_PEAK_HBM_GBPS to override; the CPU default is a
        # documented proxy, so the CPU row gates its own trend).  On
        # TPU the row also measures the fused Pallas kernel against
        # the dense oracle at the best sweep batch (fused_speedup, the
        # raw-speed claim of the kernel family); off-TPU "fused" is
        # interpret-mode — a correctness tool, not a rate — so the
        # field is absent there, the Needs-style hardware skip.
        from tpuscratch.bench.decode_bench import peak_hbm_bytes_per_s

        roofline_row = dict(
            config=12,
            metric="serve_decode_roofline",
            value=best.achieved_frac,
            achieved_frac=best.achieved_frac,
            achieved_hbm_gbps=best.achieved_bytes_per_s / 1e9,
            peak_hbm_gbps=peak_hbm_bytes_per_s() / 1e9,
            kernel=("fused" if on_tpu else "dense"),
        )
        if on_tpu:
            r_fused = bench_decode(
                mesh, cfg, _dc.replace(scfg, n_slots=best.n_slots,
                                       fused_attention="on"),
                sink=sink, **kwargs,
            )
            r_dense = bench_decode(
                mesh, cfg, _dc.replace(scfg, n_slots=best.n_slots,
                                       fused_attention="off"),
                sink=sink, **kwargs,
            )
            roofline_row["fused_speedup"] = (
                r_fused.tokens_per_s / r_dense.tokens_per_s
            )
            roofline_row["achieved_frac"] = r_fused.achieved_frac
            roofline_row["value"] = r_fused.achieved_frac
            roofline_row["achieved_hbm_gbps"] = (
                r_fused.achieved_bytes_per_s / 1e9
            )
        roofline_row["detail"] = (
            f"{roofline_row['achieved_hbm_gbps']:.3f} GB/s achieved "
            f"({100 * roofline_row['achieved_frac']:.2f}% of "
            f"{roofline_row['peak_hbm_gbps']:.0f} GB/s peak, "
            f"{roofline_row['kernel']} kernel"
            + (f", fused {roofline_row['fused_speedup']:.2f}x dense"
               if "fused_speedup" in roofline_row else "")
            + ")"
        )
        _emit(out, **roofline_row)

        # static cache-byte proof at this row's geometry: int8 pages +
        # scales vs fp32 pages, per token of pool capacity — exact, not
        # sampled (the ZeRO grad-leg pattern applied to serving HBM)
        geom = CacheGeometry(cfg.n_layers, scfg.n_pages, scfg.page_size,
                             cfg.n_heads, cfg.d_head)
        b_f32 = kv_cache_bytes(init_kv_cache(geom))
        b_int8 = kv_cache_bytes(init_kv_cache(geom, dtype=jnp.int8))
        _emit(
            out,
            config=12,
            metric="serve_kv_cache_bytes",
            bytes_per_token_f32=b_f32 / geom.max_tokens,
            bytes_per_token_int8=b_int8 / geom.max_tokens,
            int8_ratio=b_int8 / b_f32,
            detail=f"{b_f32 / geom.max_tokens:.0f} -> "
                   f"{b_int8 / geom.max_tokens:.0f} B/token "
                   f"({b_int8 / b_f32:.3f}x) at config-12 geometry",
        )

        # speculative decoding on an accept-friendly periodic prompt
        # (the amortization regime), with the same-workload
        # non-speculative rate beside it.  Batch capped below the sweep
        # maximum on TPU: a speculative slot's budget (and page
        # reservation) scales by spec_k + 1, and 32 slots of that would
        # outgrow the row's page pool — the admission watermark would
        # (correctly) refuse to fill the bank
        batch = min(batches[-1], 8) if on_tpu else batches[-1]
        prompt = accept_friendly_prompt(
            kwargs.get("prompt_len", 8), scfg.vocab
        )
        kw = {k: v for k, v in kwargs.items() if k != "prompt_len"}
        r_base = _median_run(
            lambda: bench_decode(
                mesh, cfg, _dc.replace(scfg, n_slots=batch),
                prompt=prompt, sink=sink, **kw,
            ),
            key=lambda r: r.tokens_per_s,
        )
        r_spec = _median_run(
            lambda: bench_decode(
                mesh, cfg, _dc.replace(scfg, n_slots=batch,
                                       spec_k=4 if on_tpu else 3),
                prompt=prompt, sink=sink, **kw,
            ),
            key=lambda r: r.tokens_per_s,
        )
        print(f"# {r_spec.summary()} (vs {r_base.tokens_per_s:.3e} tok/s "
              "non-spec)", file=sys.stderr)
        _emit(
            out,
            config=12,
            metric="serve_decode_spec",
            value=r_spec.tokens_per_s,
            nospec_tokens_per_s=r_base.tokens_per_s,
            spec_speedup=r_spec.tokens_per_s / r_base.tokens_per_s,
            accept_len_mean=r_spec.accept_len_mean,
            p50_s=r_spec.p50_s,
            p99_s=r_spec.p99_s,
            detail=r_spec.summary(),
        )

        # disaggregated serving rows (ISSUE 8).  serve_prefix_share:
        # the share-ratio sweep's STATIC accounting — the fraction of
        # prompt tokens actually prefilled and the fresh-KV bytes per
        # emitted token are exact engine counters, so their monotone
        # drop with the share ratio is a proof, not a measurement —
        # plus the chunked-prefill long-mix p99 comparison (identical
        # greedy outputs asserted inside the bench; the p99 drop is
        # pure scheduling).  serve_disagg_tokens_per_s: the same
        # stream drained monolithic vs prefill/decode-split, with the
        # static per-handoff migration payload beside it.
        from tpuscratch.bench.decode_bench import (
            bench_chunk_longmix,
            bench_serve_stream,
            shared_prefix_prompts,
        )

        length = max(4 * scfg.page_size, kwargs.get("prompt_len", 8))
        max_new = 8
        stream_scfg = _dc.replace(
            scfg, max_seq=max(scfg.max_seq, length + max_new)
        )
        share_scfg = _dc.replace(stream_scfg, prefix_share=True)
        share_rows = {}
        for ratio in (0.0, 0.5, 0.9):
            prompts = shared_prefix_prompts(
                scfg.n_slots * 2, length, ratio, scfg.vocab
            )
            share_rows[ratio] = _median_run(
                lambda: bench_serve_stream(
                    mesh, cfg, share_scfg, prompts, max_new=max_new,
                    sink=sink,
                ),
                key=lambda row: row["p99_tick_s"],
            )
            print(
                f"# share {ratio}: prefill_frac "
                f"{share_rows[ratio]['prefill_frac']:.3f}, fresh "
                f"{share_rows[ratio]['fresh_kv_bytes_per_token']:.0f} "
                f"B/token, p99 "
                f"{share_rows[ratio]['p99_tick_s'] * 1e3:.2f} ms",
                file=sys.stderr,
            )
        long_len = 256 if on_tpu else 32
        longmix = _median_run(
            lambda: bench_chunk_longmix(
                mesh, cfg,
                _dc.replace(scfg,
                            max_seq=max(scfg.max_seq, long_len + 32),
                            n_pages=max(scfg.n_pages, 64)),
                chunk=scfg.page_size,
                long_len=long_len,
            ),
            key=lambda row: row["p99_ratio"],
        )
        print(
            f"# long-mix p99: mono {longmix['p99_s_mono'] * 1e3:.2f} ms "
            f"-> chunked {longmix['p99_s_chunked'] * 1e3:.2f} ms "
            f"({longmix['p99_ratio']:.3f}x)", file=sys.stderr,
        )
        _emit(
            out,
            config=12,
            metric="serve_prefix_share",
            prefill_frac_r50=share_rows[0.5]["prefill_frac"],
            prefill_frac_r90=share_rows[0.9]["prefill_frac"],
            fresh_kv_bytes_per_token_r0=share_rows[0.0][
                "fresh_kv_bytes_per_token"],
            fresh_kv_bytes_per_token_r50=share_rows[0.5][
                "fresh_kv_bytes_per_token"],
            fresh_kv_bytes_per_token_r90=share_rows[0.9][
                "fresh_kv_bytes_per_token"],
            p99_s_r0=share_rows[0.0]["p99_tick_s"],
            p99_s_r90=share_rows[0.9]["p99_tick_s"],
            p99_s_longmix_mono=longmix["p99_s_mono"],
            p99_s_longmix_chunked=longmix["p99_s_chunked"],
            longmix_p99_ratio=longmix["p99_ratio"],
            detail=(
                f"prefill_frac 1 -> "
                f"{share_rows[0.5]['prefill_frac']:.3f} -> "
                f"{share_rows[0.9]['prefill_frac']:.3f} at share "
                f"0/0.5/0.9; long-mix p99 "
                f"{longmix['p99_ratio']:.3f}x chunked"
            ),
        )

        prompts0 = shared_prefix_prompts(
            scfg.n_slots * 2, length, 0.0, scfg.vocab
        )
        mono_stream = _median_run(
            lambda: bench_serve_stream(
                mesh, cfg, stream_scfg, prompts0, max_new=max_new,
                sink=sink,
            ),
            key=lambda row: row["tokens_per_s"],
        )
        disagg_stream = _median_run(
            lambda: bench_serve_stream(
                mesh, cfg, stream_scfg, prompts0, max_new=max_new,
                disagg=True, sink=sink,
            ),
            key=lambda row: row["tokens_per_s"],
        )
        if disagg_stream["outputs"] != mono_stream["outputs"]:
            raise RuntimeError(
                "disaggregated outputs diverged from monolithic"
            )
        print(
            f"# disagg: {disagg_stream['tokens_per_s']:.3e} tok/s vs "
            f"{mono_stream['tokens_per_s']:.3e} monolithic, "
            f"{disagg_stream['handoffs']} handoffs, "
            f"{disagg_stream['degraded']} degraded", file=sys.stderr,
        )
        _emit(
            out,
            config=12,
            metric="serve_disagg_tokens_per_s",
            value=disagg_stream["tokens_per_s"],
            mono_tokens_per_s=mono_stream["tokens_per_s"],
            p99_s=disagg_stream["p99_tick_s"],
            handoff_bytes_per_token=(
                disagg_stream["handoff_wire_bytes"]
                / max(1, disagg_stream["tokens"])
            ),
            handoffs=disagg_stream["handoffs"],
            degraded=disagg_stream["degraded"],
            detail=(
                f"{disagg_stream['handoffs']} handoffs, "
                f"{disagg_stream['degraded']} degraded, "
                f"{disagg_stream['handoff_wire_bytes']:.0f} B shipped"
            ),
        )

        # tiered KV memory (ISSUE 13): resident users at FIXED HBM —
        # the long-context many-user backlog at a deliberately tight
        # device pool, untiered vs host-tiered (identical greedy
        # outputs asserted inside the bench), with the tier's costs
        # STATED: cold-hit p99 (the synchronous-prefetch stalls the
        # double-buffered prefetch-ahead failed to hide) and host
        # bytes/token (exact page-move counters x exact ledger page
        # bytes — static accounting, only wall time is sampled).
        # Directions (obs.regress): resident/users up; cold/p99/bytes
        # down.
        from tpuscratch.bench.decode_bench import (
            bench_tiered_residency,
            tiered_residency_setup,
        )

        tight = tiered_residency_setup(scfg, on_tpu)
        tiered = bench_tiered_residency(mesh, cfg, tight,
                                        2 * tight.n_pages)
        print(
            f"# tiered: residents {tiered['baseline_resident_users']} "
            f"-> {tiered['resident_users']} "
            f"({tiered['residency_gain']:.2f}x) at "
            f"{tiered['device_pages']} device pages; cold-hit p99 "
            f"{tiered['cold_hit_p99_s'] * 1e3:.2f} ms, host "
            f"{tiered['host_bytes_per_token']:.0f} B/token",
            file=sys.stderr,
        )
        _emit(
            out,
            config=12,
            metric="serve_kv_tiered",
            value=tiered["resident_users"],
            resident_users=tiered["resident_users"],
            baseline_resident_users=tiered["baseline_resident_users"],
            residency_gain=tiered["residency_gain"],
            cold_hit_p99_s=tiered["cold_hit_p99_s"],
            cold_hits=tiered["cold_hits"],
            host_bytes_per_token=tiered["host_bytes_per_token"],
            device_pages=tiered["device_pages"],
            host_pages=tiered["host_pages"],
            detail=(
                f"residents {tiered['baseline_resident_users']} -> "
                f"{tiered['resident_users']} "
                f"({tiered['residency_gain']:.2f}x) at fixed "
                f"{tiered['device_pages']}-page device pool; cold-hit "
                f"p99 {tiered['cold_hit_p99_s'] * 1e3:.2f} ms, "
                f"{tiered['host_bytes_per_token']:.0f} host B/token"
            ),
        )

        # device-resident macro-step decode (ISSUE 15): the SAME
        # steady-state workload at macro_steps T in {1, 4, 16} — one
        # compiled lax.scan dispatch and one sampling host-sync per T
        # tokens instead of per token.  dispatches/token and host
        # syncs/token are EXACT engine counters over exact token counts
        # (static, tight regression band — they must drop ~T×);
        # tokens/s is the measured wall-clock payoff (median-of-3,
        # CPU-proxy noise floors apply off-TPU only — the PR-14 floor
        # discipline).  Greedy bit-identity across T is test-gated
        # (tests/test_serve_macro.py), not re-proven here.
        # a macro slot's budget (hence page reservation) scales by T:
        # pick the largest sweep batch whose T=16 bank fits the pool
        # (decode_bench.fitting_batches — the one shared sizing rule),
        # same batch at every T so the comparison is apples-to-apples
        from tpuscratch.bench.decode_bench import fitting_batches

        _, _fit = fitting_batches(
            scfg, batches, 16,
            prompt_len=kwargs.get("prompt_len", 8),
            measure_steps=kwargs.get("measure_steps", 32),
            warmup_steps=kwargs.get("warmup_steps", 4),
        )
        macro_batch = max(_fit or (1,))
        macro_rows = {}
        for T in (1, 4, 16):
            macro_rows[T] = _median_run(
                lambda T=T: bench_decode(
                    mesh, cfg, _dc.replace(scfg, n_slots=macro_batch,
                                           macro_steps=T),
                    sink=sink, **kwargs,
                ),
                key=lambda r: r.tokens_per_s,
            )
            print(f"# macro T={T}: {macro_rows[T].summary()}",
                  file=sys.stderr)
        r1, r16 = macro_rows[1], macro_rows[16]
        _emit(
            out,
            config=12,
            metric="serve_decode_macro",
            value=r16.tokens_per_s,
            tokens_per_s_t1=r1.tokens_per_s,
            tokens_per_s_t4=macro_rows[4].tokens_per_s,
            tokens_per_s_t16=r16.tokens_per_s,
            macro_speedup=r16.tokens_per_s / r1.tokens_per_s,
            dispatches_per_token_t1=r1.dispatches_per_token,
            dispatches_per_token_t4=macro_rows[4].dispatches_per_token,
            dispatches_per_token_t16=r16.dispatches_per_token,
            host_syncs_per_token_t1=r1.host_syncs_per_token,
            host_syncs_per_token_t16=r16.host_syncs_per_token,
            detail=(
                f"T=16 {r16.tokens_per_s:.3e} tok/s "
                f"({r16.tokens_per_s / r1.tokens_per_s:.2f}x vs T=1); "
                f"dispatches/token "
                f"{r1.dispatches_per_token:.4f} -> "
                f"{r16.dispatches_per_token:.4f}, host syncs/token "
                f"{r1.host_syncs_per_token:.4f} -> "
                f"{r16.host_syncs_per_token:.4f}"
            ),
        )


def config13_zero_train(out: list, iters: int = 3) -> None:
    """Replicated vs ZeRO-sharded training (ISSUE 4): tokens/s of the
    Adam train step at dp in {1, 2, 4}, next to the STATIC grad-sync
    wire bytes the obs ledger reads off each compiled program — the row
    that captures both halves of the ZeRO trade (measured rate, proven
    comm).  The static bytes are exact (not sampled): reintroducing a
    full gradient all-reduce shows up as grad_ratio jumping from ~0.5
    to ~1.0 regardless of measurement noise.  The accum sweep records
    the deferred-sync amortization: one reduce-scatter + all-gather per
    k microbatches."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpuscratch.bench.train_bench import bench_train
    from tpuscratch.models.transformer import (
        TransformerConfig,
        init_adam_state,
        init_params,
        train_step_adam,
    )
    from tpuscratch.models.zero import init_zero_adam_state, train_step_zero
    from tpuscratch.obs import ledger as obs_ledger
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    base = (
        TransformerConfig(
            d_model=1024, n_heads=8, n_experts=4, d_ff=4096, n_layers=4,
            capacity_factor=2.0, attn_impl="pallas",
        )
        if on_tpu
        else TransformerConfig(
            d_model=32, n_heads=2, n_experts=4, d_ff=64, n_layers=1
        )
    )
    seq = 2048 if on_tpu else 16
    batch_per_dp = 8 if on_tpu else 2
    avail = len(jax.devices())
    emitted = 0
    for dp in (1, 2, 4):
        if dp > avail:
            print(f"# config 13 dp={dp} skipped: {avail} device(s)",
                  file=sys.stderr)
            continue
        cfg = dataclasses.replace(base, n_experts=max(base.n_experts, dp))
        mesh = make_mesh((dp, 1), ("dp", "sp"), jax.devices()[:dp])
        params = init_params(0, cfg)
        x = jnp.zeros((dp * batch_per_dp, seq, cfg.d_model), jnp.float32)
        rep_gs = obs_ledger.grad_sync_wire_bytes(obs_ledger.analyze(
            train_step_adam(mesh, cfg), params, init_adam_state(params),
            x, x,
        ))
        zero_gs = obs_ledger.grad_sync_wire_bytes(obs_ledger.analyze(
            train_step_zero(mesh, cfg, donate=False), params,
            init_zero_adam_state(params, dp), x, x,
        ))
        row = {
            "dp": dp,
            "grad_sync_bytes_replicated": rep_gs.grad,
            "grad_sync_bytes_zero": zero_gs.grad,
            "grad_ratio": (zero_gs.grad / rep_gs.grad
                           if rep_gs.grad else None),
            "zero_all_gather_bytes": zero_gs.all_gather,
        }
        for zero in (False, True):
            try:
                r = bench_train(
                    mesh=mesh, cfg=cfg, batch=dp * batch_per_dp, seq=seq,
                    steps=20 if on_tpu else 2, iters=iters,
                    fence="readback" if on_tpu else "block",
                    optimizer="adam", zero=zero,
                )
            except Exception as e:
                print(f"# config 13 dp={dp} zero={zero} failed: {e}",
                      file=sys.stderr)
                continue
            print(f"# {r.summary()} -> {r.items_per_s:.3e} tok/s",
                  file=sys.stderr)
            row["zero_tokens_per_s" if zero else "repl_tokens_per_s"] = (
                r.items_per_s
            )
        if "repl_tokens_per_s" not in row and \
                "zero_tokens_per_s" not in row:
            continue
        _emit(out, config=13, metric=f"zero_vs_replicated_dp{dp}", **row)
        emitted += 1
    if not emitted:
        raise RuntimeError("all config-13 dp points failed")

    # deferred-sync accumulation sweep (largest mesh that fit): static
    # per-microbatch sync bytes ÷ k alongside the measured rate
    dp = min(4, avail) if avail >= 2 else 1
    dp = {1: 1, 2: 2, 3: 2}.get(dp, 4)
    cfg = dataclasses.replace(base, n_experts=max(base.n_experts, dp))
    mesh = make_mesh((dp, 1), ("dp", "sp"), jax.devices()[:dp])
    sweep = []
    for k in (1, 2, 4):
        params = init_params(0, cfg)
        xk = jnp.zeros(
            ((k,) if k > 1 else ()) + (dp * batch_per_dp, seq, cfg.d_model),
            jnp.float32,
        )
        gs = obs_ledger.grad_sync_wire_bytes(obs_ledger.analyze(
            train_step_zero(mesh, cfg, accum_steps=k, donate=False),
            params, init_zero_adam_state(params, dp), xk, xk,
        ))
        entry = {"accum": k,
                 "sync_bytes_per_microbatch": gs.per_microbatch(k)}
        try:
            r = bench_train(
                mesh=mesh, cfg=cfg, batch=dp * batch_per_dp, seq=seq,
                steps=10 if on_tpu else 2, iters=iters,
                fence="readback" if on_tpu else "block",
                optimizer="adam", zero=True, accum_steps=k,
            )
            print(f"# {r.summary()} -> {r.items_per_s:.3e} tok/s",
                  file=sys.stderr)
            entry["tokens_per_s"] = r.items_per_s
        except Exception as e:
            print(f"# config 13 accum={k} failed: {e}", file=sys.stderr)
        sweep.append(entry)
    _emit(out, config=13, metric="zero_accum_sweep", dp=dp, sweep=sweep)


def config14_plan_overlap(out: list, iters: int = 2) -> None:
    """Comm/compute overlap ablation on the plan-composed ZeRO step
    (ISSUE 7): tokens/s and step time of ``train(plan=...)``'s program
    at pp x dp in {1,2}^2, overlap (decomposed per-block RS/AG chains)
    vs serial (one flat RS -> update -> AG), with the static ledger
    beside each rate — the proof obligations are (a) total wire bytes
    IDENTICAL across the two schedules (the decomposition moves the
    collective count, never the bytes) and (b) overlap's tokens/s at or
    above serial's.  Regression directions all registered in
    ``obs.regress``: tokens/s and speedup up, step_s down, bytes down
    (equal here), achieved-* up.

    ``achieved_flops_per_s`` is the ledger-derived achieved rate
    (static FLOPs / measured step); with ``TPUSCRATCH_PEAK_FLOPS`` set
    (chip peak, FLOP/s) each row also carries the roofline
    ``achieved_fraction_*`` — the before/after MFU argument.

    CPU-proxy caveat (every off-chip row in this harness carries one):
    on the virtual CPU mesh part of the overlap win comes from the
    per-block fused-Adam invocations behaving better in Mosaic
    interpret mode than one large call — the scheduling overlap of the
    decomposed collectives is the chip-side mechanism.  The ablation
    still compares the two SHIPPED schedules of the same math at equal
    wire bytes; re-run on a slice for the ICI-grounded number."""
    import jax
    import jax.numpy as jnp

    from tpuscratch.bench.train_bench import bench_train
    from tpuscratch.models.transformer import (
        TransformerConfig,
        init_params,
        stack_layers,
    )
    from tpuscratch.models.zero import (
        init_plan_zero_state,
        init_zero_adam_state,
        train_step_plan,
        train_step_zero,
    )
    from tpuscratch.obs import ledger as obs_ledger
    from tpuscratch.parallel import ShardingPlan
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    # CPU geometry is sized so the flat sync legs carry real megabytes
    # (a toy d_model=32 row would measure dispatch jitter, not the
    # schedule); still CPU-proxy — re-run on a slice for ICI truth
    cfg = (
        TransformerConfig(
            d_model=1024, n_heads=8, n_experts=4, d_ff=4096, n_layers=4,
            capacity_factor=2.0, attn_impl="pallas",
        )
        if on_tpu
        else TransformerConfig(
            d_model=512, n_heads=4, n_experts=2, d_ff=1024, n_layers=2,
            capacity_factor=2.0,
        )
    )
    seq = 2048 if on_tpu else 64
    batch_per_dp = 8 if on_tpu else 4
    steps = 10 if on_tpu else 3
    peak = float(os.environ.get("TPUSCRATCH_PEAK_FLOPS", "0"))
    avail = len(jax.devices())
    emitted = 0
    for pp in (1, 2):
        for dpn in (1, 2):
            need = pp * dpn
            if need > avail:
                print(f"# config 14 pp={pp} dp={dpn} skipped: {avail} "
                      f"device(s)", file=sys.stderr)
                continue
            mesh = make_mesh((dpn, 1, pp), ("dp", "sp", "pp"),
                             jax.devices()[:need])
            n_micro = 2 if pp > 1 else 1
            batch = dpn * batch_per_dp
            params = init_params(0, cfg)
            x = jnp.zeros((batch, seq, cfg.d_model), jnp.float32)
            row = {"pp": pp, "n_micro": n_micro}
            for ov in (False, True):
                tag = "overlap" if ov else "serial"
                plan = ShardingPlan(mesh, pp="pp", n_micro=n_micro,
                                    overlap=ov)
                # static half first: the compiled step's collective
                # schedule and wire bytes (exact, not sampled)
                if plan.pipelined:
                    st = stack_layers(params)
                    led = obs_ledger.analyze(
                        train_step_plan(plan, cfg, donate=False), st,
                        init_plan_zero_state(st, plan), x, x,
                    )
                else:
                    led = obs_ledger.analyze(
                        train_step_zero(
                            mesh, cfg, donate=False,
                            overlap_blocks=plan.overlap_blocks,
                        ),
                        params, init_zero_adam_state(params, dpn), x, x,
                    )
                counts = led.counts()
                row[f"wire_bytes_{tag}"] = led.total_wire_bytes()
                row[f"rs_ops_{tag}"] = counts.get("reduce-scatter", 0)
                row[f"ag_ops_{tag}"] = counts.get("all-gather", 0)
                try:
                    r = bench_train(
                        plan=plan, cfg=cfg, batch=batch, seq=seq,
                        steps=steps, iters=iters,
                        fence="readback" if on_tpu else "block",
                        optimizer="adam", zero=True,
                    )
                except Exception as e:
                    print(f"# config 14 pp={pp} dp={dpn} {tag} failed: "
                          f"{e}", file=sys.stderr)
                    continue
                print(f"# {r.summary()} -> {r.items_per_s:.3e} tok/s",
                      file=sys.stderr)
                row[f"tokens_per_s_{tag}"] = r.items_per_s
                row[f"step_s_{tag}"] = r.p50 / steps
                if led.flops:
                    ach = led.flops * steps / r.p50
                    row[f"achieved_flops_per_s_{tag}"] = ach
                    if peak > 0:
                        row[f"achieved_fraction_{tag}"] = ach / peak
            if ("tokens_per_s_overlap" not in row
                    or "tokens_per_s_serial" not in row):
                continue
            row["overlap_speedup"] = (
                row["tokens_per_s_overlap"] / row["tokens_per_s_serial"]
            )
            equal = row["wire_bytes_overlap"] == row["wire_bytes_serial"]
            if not equal:
                print(f"# config 14 pp={pp} dp={dpn}: WIRE BYTES "
                      f"DIVERGED {row['wire_bytes_serial']} -> "
                      f"{row['wire_bytes_overlap']}", file=sys.stderr)
            _emit(
                out,
                config=14,
                metric=f"plan_overlap_pp{pp}_dp{dpn}_tokens_per_s",
                value=row["tokens_per_s_overlap"],
                detail=(
                    f"overlap {row['overlap_speedup']:.2f}x serial; "
                    f"wire bytes {'EQUAL' if equal else 'DIVERGED'} "
                    f"({row['rs_ops_serial']}+{row['ag_ops_serial']} -> "
                    f"{row['rs_ops_overlap']}+{row['ag_ops_overlap']} "
                    f"RS+AG ops)"
                ),
                **row,
            )
            emitted += 1
    if not emitted:
        raise RuntimeError("all config-14 grid points failed")


def config15_solver(out: list, iters: int = 2) -> None:
    """Solver weak-scaling + communication-avoiding ablation (ISSUE 10):
    the reference repo's actual workload (stencil + benchmarking,
    PAPER.md capabilities 7-8) operated through the production runner.

    Three row families, every new field direction-registered in
    ``obs.regress``:

    - ``solver_weak_mg3d_<n>dev``: fixed per-chip 3D tile over growing
      meshes through the SUPERVISED runner — cells/s, V-cycles to
      tolerance, analytic ``comm_ratio`` (halo bytes per computed cell
      per sweep, from the exchange plan — the number that transfers to
      a real slice), per-chip ``efficiency`` vs the 1-device point.
    - ``solver_ca_smoothing``: s_step=1 vs s_step=2 (damped Jacobi,
      the smoother whose fold reaches the launch-bound coarse levels)
      on the largest mesh — measured cells/s + ``deep_speedup``,
      identical cycle counts, ledger ppermutes/sweep and halo
      bytes/sweep (exact).
    - ``solver_ca_cg``: classic vs pipelined CG — time-to-tolerance,
      iterations, and the static psum counts (3 vs 2 total; 2 vs ONE
      per iteration).  CPU-proxy caveat: on the virtual CPU mesh psum
      latency is a thread rendezvous, so the pipelined variant's extra
      vector work can outweigh the saved collective — the LEDGER
      column is the claim that transfers to a slice (the config-14
      discipline), and the smoothing row carries the measured CPU win.
    """
    import time

    import jax
    import numpy as np

    from tpuscratch.bench.weak_scaling import halo3d_traffic_per_chip
    from tpuscratch.obs import ledger as obs_ledger
    from tpuscratch.runtime.mesh import make_mesh, make_mesh_2d
    from tpuscratch.runtime.topology import factor2d

    on_tpu = jax.default_backend() == "tpu"
    per_chip = 32 if on_tpu else 16
    tol = 1e-6
    avail = len(jax.devices())
    rng = np.random.default_rng(0)

    def solve_timed(b, mesh, dims, **kw):
        import shutil
        import tempfile

        from tpuscratch.solvers import checkpointed_mg3d_solve

        best = None
        for _ in range(iters):
            wd = tempfile.mkdtemp(prefix="tpuscratch_c15_")
            try:
                t0 = time.perf_counter()
                _, rep = checkpointed_mg3d_solve(
                    b, f"{wd}/ck", mesh=mesh, tol=tol,
                    chunk_cycles=64, **kw,
                )
                wall = time.perf_counter() - t0
            finally:
                shutil.rmtree(wd, ignore_errors=True)
            if best is None or wall < best[0]:
                best = (wall, rep)
        return best

    # --- weak scaling through the supervised runner -------------------
    shapes = [(1, 1, 1), (2, 1, 1), (2, 2, 1), (2, 2, 2)]
    points = []
    for dims in shapes:
        n = dims[0] * dims[1] * dims[2]
        if n > avail:
            print(f"# config 15 mesh {dims} skipped: {avail} device(s)",
                  file=sys.stderr)
            continue
        world = tuple(d * per_chip for d in dims)
        b = rng.standard_normal(world).astype(np.float32)
        b -= b.mean()
        mesh = make_mesh(dims, ("z", "row", "col"), jax.devices()[:n])
        try:
            wall, rep = solve_timed(b, mesh, dims)
        except Exception as e:
            print(f"# config 15 mesh {dims} failed: {e}", file=sys.stderr)
            continue
        cells = float(np.prod(world))
        rate = cells * rep.cycles / wall
        halo_b, cells_chip = halo3d_traffic_per_chip(dims, (per_chip,) * 3)
        points.append({
            "dims": dims, "n": n, "rate": rate, "cycles": rep.cycles,
            "comm_ratio": halo_b / cells_chip, "wall": wall,
        })
    if not points:
        raise RuntimeError("all config-15 weak-scaling points failed")
    base_rate = points[0]["rate"] / points[0]["n"]
    for p in points:
        per_chip_rate = p["rate"] / p["n"]
        _emit(
            out,
            config=15,
            metric=f"solver_weak_mg3d_{p['n']}dev",
            value=p["rate"],
            cells_per_s=p["rate"],
            cycles=p["cycles"],
            comm_ratio=p["comm_ratio"],
            efficiency=per_chip_rate / base_rate,
            solve_s=p["wall"],
            n_devices=p["n"],
            detail=(
                f"{p['dims'][0]}x{p['dims'][1]}x{p['dims'][2]} mesh, "
                f"{per_chip}^3/chip, {p['cycles']} cycles, "
                f"{p['comm_ratio']:.3f} B/cell analytic"
            ),
        )

    # --- CA smoothing ablation on the largest mesh --------------------
    big = points[-1]
    dims = big["dims"]
    n = big["n"]
    world = tuple(d * per_chip for d in dims)
    b = rng.standard_normal(world).astype(np.float32)
    b -= b.mean()
    mesh = make_mesh(dims, ("z", "row", "col"), jax.devices()[:n])
    cells = float(np.prod(world))
    row = {}
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.halo.halo3d import HaloSpec3D, TileLayout3D
    from tpuscratch.runtime.mesh import topology_of
    from tpuscratch.solvers.multigrid3d import (
        jacobi_smooth3,
        jacobi_smooth3_deep,
    )

    # smoother collective budget, ledger-read and normalized to
    # PER-SWEEP launches at the coarse-smoothing regime (16 sweeps —
    # where launches dominate and the fold bites): the per-sweep
    # program's fori_loop body is exactly one sweep (6 ppermutes), the
    # deep program is fully unrolled so its static count IS its dynamic
    # count (ceil(16/s) state exchanges + one rhs fill)
    sweeps = 16
    topo15 = topology_of(mesh, periodic=True)
    spec15 = HaloSpec3D(
        layout=TileLayout3D((per_chip,) * 3, (1, 1, 1)), topology=topo15,
        axes=tuple(mesh.axis_names), neighbors=6,
    )
    sp15 = P(*mesh.axis_names, None, None, None)
    smooth_arg = jnp.zeros(dims + (per_chip,) * 3, jnp.float32)

    def smoother_ledger(fn, sweeps_in_program):
        prog = run_spmd(
            mesh,
            lambda a, f: fn(a[0, 0, 0], f[0, 0, 0])[None, None, None],
            (sp15, sp15), sp15,
        )
        led = obs_ledger.analyze(prog, smooth_arg, smooth_arg)
        return (led.count("collective-permute") / sweeps_in_program,
                led.wire_bytes().get("collective-permute", 0.0)
                / sweeps_in_program)

    for s_step in (1, 2):
        try:
            wall, rep = solve_timed(b, mesh, dims, s_step=s_step,
                                    smoother="jacobi")
        except Exception as e:
            print(f"# config 15 s_step={s_step} failed: {e}",
                  file=sys.stderr)
            continue
        tag = f"s{s_step}"
        row[f"cells_per_s_{tag}"] = cells * rep.cycles / wall
        row[f"cycles_{tag}"] = rep.cycles
        row[f"solve_s_{tag}"] = wall
        if s_step == 1:
            ppermutes, wire = smoother_ledger(
                lambda u, f: jacobi_smooth3(u, f, spec15, 6 / 7, 1), 1
            )
        else:
            ppermutes, wire = smoother_ledger(
                lambda u, f: jacobi_smooth3_deep(u, f, spec15, 6 / 7,
                                                 sweeps, s_step),
                sweeps,
            )
        row[f"ppermutes_per_sweep_{tag}"] = ppermutes
        row[f"halo_bytes_per_sweep_{tag}"] = wire
    if "cells_per_s_s1" in row and "cells_per_s_s2" in row:
        row["deep_speedup"] = row["cells_per_s_s2"] / row["cells_per_s_s1"]
        _emit(
            out,
            config=15,
            metric="solver_ca_smoothing",
            value=row["deep_speedup"],
            **row,
            detail=(
                f"s-step smoothing {row['deep_speedup']:.3f}x cells/s, "
                f"ppermutes/sweep {row['ppermutes_per_sweep_s1']:.0f} -> "
                f"{row['ppermutes_per_sweep_s2']:.0f} (ledger), cycles "
                f"{row['cycles_s1']} == {row['cycles_s2']}"
            ),
        )

    # --- CG ablation: classic vs pipelined ----------------------------
    from tpuscratch.halo.driver import _setup
    from tpuscratch.solvers import poisson_solve
    from tpuscratch.solvers.cg import _poisson_program

    n2 = 256 if on_tpu else 64
    cg_tol = 1e-5
    b2 = rng.standard_normal((n2, n2)).astype(np.float32)
    mesh2 = make_mesh_2d(factor2d(min(4, avail)))
    cg_row = {}
    mesh_s, topo_s, layout_s, spec_s = _setup(
        (n2, n2), mesh2, (1, 1), periodic=False, neighbors=4
    )
    for method in ("cg", "pipelined"):
        try:
            poisson_solve(b2, mesh2, tol=cg_tol, max_iters=4 * n2,
                          method=method)  # warm the program cache
            best = None
            for _ in range(iters):
                t0 = time.perf_counter()
                _, k, relres = poisson_solve(
                    b2, mesh2, tol=cg_tol, max_iters=4 * n2, method=method
                )
                best = min(best or np.inf, time.perf_counter() - t0)
        except Exception as e:
            print(f"# config 15 {method} failed: {e}", file=sys.stderr)
            continue
        tag = "classic" if method == "cg" else "pipelined"
        cg_row[f"solve_s_{tag}"] = best
        cg_row[f"iterations_{tag}"] = int(k)
        led = obs_ledger.analyze(
            _poisson_program(mesh_s, spec_s, cg_tol, 4 * n2, method),
            jnp.zeros(
                tuple(topo_s.dims) + (n2 // topo_s.dims[0],
                                      n2 // topo_s.dims[1]),
                jnp.float32,
            ),
        )
        # 1 init + per-iteration psums (while body appears once)
        cg_row[f"psums_total_{tag}"] = led.count("all-reduce")
        cg_row[f"psums_per_iter_{tag}"] = led.count("all-reduce") - 1
    if "solve_s_classic" in cg_row and "solve_s_pipelined" in cg_row:
        cg_row["pipelined_speedup"] = (
            cg_row["solve_s_classic"] / cg_row["solve_s_pipelined"]
        )
        _emit(
            out,
            config=15,
            metric="solver_ca_cg",
            value=cg_row["psums_per_iter_pipelined"],
            **cg_row,
            detail=(
                f"psums/iter {cg_row['psums_per_iter_classic']} -> "
                f"{cg_row['psums_per_iter_pipelined']} (ledger), iters "
                f"{cg_row['iterations_classic']} -> "
                f"{cg_row['iterations_pipelined']} (restart-segment "
                f"penalty), time-to-tol {cg_row['pipelined_speedup']:.3f}x "
                f"[{_platform()} proxy: psum latency is a thread "
                f"rendezvous off-chip — the saved launch is the slice-"
                f"side claim]"
            ),
        )


def config16_elastic_goodput(out: list) -> None:
    """Elastic fault tolerance under chaos (ISSUE 11): an ex26-style
    preempt-and-restart run for each of the three chunked workloads
    (trainer, halo driver, solver runner), once with BLOCKING saves and
    once with ASYNC checkpointing, each accounted by ``obs.goodput``
    from its own JSONL artifact — buckets summing to wall exactly
    (``GoodputReport.check`` is called live).  One row per workload,
    with the ``checkpoint``/``restart`` badput shares and the goodput
    fraction direction-registered in ``obs.regress`` (shares down,
    goodput up), so ``record.py --check`` gates the async win the way
    the ZeRO 0.5x grad leg is gated."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from tpuscratch.ft.chaos import ChaosPlan, Fault
    from tpuscratch.ft.supervisor import (
        RestartBudget,
        supervise,
        supervise_train,
    )
    from tpuscratch.obs.goodput import goodput_report
    from tpuscratch.obs.report import load_events
    from tpuscratch.obs.sink import Sink
    from tpuscratch.runtime.mesh import make_mesh, make_mesh_2d
    from tpuscratch.runtime.topology import factor2d

    avail = len(jax.devices())
    budget = RestartBudget(max_restarts=3, backoff_s=0.05,
                           max_backoff_s=0.2)
    rng = np.random.default_rng(0)

    def run_train(ck, sink, async_on):
        from tpuscratch.models.transformer import TransformerConfig

        # the state must be big enough that SERIALIZATION is the cost
        # (the regime real checkpoints live in): ~16 MB params+moments
        n = min(4, avail)
        mesh = make_mesh((n, 1), ("dp", "sp"), jax.devices()[:n])
        cfg = TransformerConfig(d_model=256, n_heads=2, n_experts=n,
                                d_ff=512, n_layers=2,
                                capacity_factor=2.0)
        chaos = ChaosPlan(0, [Fault("train/preempt", at=(10,),
                                    kind="preempt")])
        supervise_train(mesh, cfg, 20, ck, budget=budget, sink=sink,
                        obs=sink, chaos=chaos, save_every=2,
                        batch=2 * n, seq=32, optimizer="adam",
                        async_ckpt=async_on)

    def run_halo(ck, sink, async_on):
        from tpuscratch.halo.driver import checkpointed_stencil

        mesh = make_mesh_2d(factor2d(min(4, avail)))
        world = rng.standard_normal((1024, 1024)).astype(np.float32)
        chaos = ChaosPlan(0, [Fault("halo/preempt", at=(20,),
                                    kind="preempt")])
        supervise(
            lambda: checkpointed_stencil(
                world, 40, ck, save_every=5, mesh=mesh, sink=sink,
                chaos=chaos, async_ckpt=async_on,
            ),
            budget=budget, sink=sink,
        )

    def make_solver():
        """Built (and WARMED) before any mode's sink exists: the
        lru-cached chunk program is shared across both modes, and its
        compile must not land inside either mode's accounting window
        (the sink's wall starts at its `run` header) — otherwise the
        first-measured mode eats the whole compile and the shares
        compare compile, not saves."""
        import shutil as _sh
        import tempfile as _tf

        from tpuscratch.solvers import (
            checkpointed_mg3d_solve,
            supervised_mg3d_solve,
        )

        dims = (2, 2, 1) if avail >= 4 else (1, 1, 1)
        n = dims[0] * dims[1] * dims[2]
        world = tuple(d * 32 for d in dims)
        b = rng.standard_normal(world).astype(np.float32)
        b -= b.mean()
        mesh = make_mesh(dims, ("z", "row", "col"), jax.devices()[:n])
        solve_kw = dict(mesh=mesh, tol=1e-7, max_cycles=24,
                        chunk_cycles=4)
        wwd = _tf.mkdtemp(prefix="tpuscratch_c16_warm_")
        try:
            checkpointed_mg3d_solve(b, f"{wwd}/ck", **solve_kw)
        finally:
            _sh.rmtree(wwd, ignore_errors=True)

        def run_solver(ck, sink, async_on):
            chaos = ChaosPlan(0, [Fault("solver/preempt", at=(8,),
                                        kind="preempt")])
            supervised_mg3d_solve(
                b, ck, sink=sink, chaos=chaos, budget=budget,
                async_ckpt=async_on, **solve_kw,
            )

        return run_solver

    def share(rep, bucket):
        return rep.buckets.get(bucket, 0.0) / rep.wall_s if rep.wall_s \
            else 0.0

    emitted = 0
    for name, make_body in (("train", lambda: run_train),
                            ("halo", lambda: run_halo),
                            ("solver", make_solver)):
        reports = {}
        write_s = 0.0
        try:
            body = make_body()
            for mode, async_on in (("blocking", False), ("async", True)):
                wd = tempfile.mkdtemp(prefix=f"tpuscratch_c16_{name}_")
                try:
                    path = f"{wd}/obs.jsonl"
                    sink = Sink(path, run={
                        "bench": f"record/config16/{name}", "mode": mode,
                        "platform": jax.default_backend(),
                    })
                    body(f"{wd}/ck", sink, async_on)
                    sink.close()
                    events = load_events([path])
                    rep = goodput_report(events)
                    rep.check()  # buckets sum to wall EXACTLY, or raise
                    reports[mode] = rep
                    if async_on:
                        # the overlapped background write wall — NOT
                        # badput (it ran concurrently; what stalled the
                        # loop is inside the snapshot brackets), shown
                        # for scale
                        write_s = sum(
                            e.get("wall_s", 0.0) for e in events
                            if e.get("event") == "ckpt/write"
                        )
                finally:
                    shutil.rmtree(wd, ignore_errors=True)
        except Exception as e:
            print(f"# config 16 {name} failed: {e}", file=sys.stderr)
            continue
        blk, asy = reports["blocking"], reports["async"]
        row = {
            "checkpoint_share_blocking": share(blk, "checkpoint"),
            "checkpoint_share_async": share(asy, "checkpoint"),
            "restart_share_blocking": share(blk, "restart"),
            "restart_share_async": share(asy, "restart"),
            "goodput_fraction_blocking": blk.goodput_fraction,
            "goodput_fraction_async": asy.goodput_fraction,
            "wall_s_blocking": blk.wall_s,
            "wall_s_async": asy.wall_s,
            "overlapped_write_s": write_s,
        }
        _emit(
            out,
            config=16,
            metric=f"elastic_goodput_{name}",
            # the headline is the async GOODPUT fraction (matching the
            # metric name's inferred direction, higher); the gated
            # badput shares ride as direction-registered fields
            value=row["goodput_fraction_async"],
            **row,
            detail=(
                f"checkpoint badput share "
                f"{100 * row['checkpoint_share_blocking']:.1f}% -> "
                f"{100 * row['checkpoint_share_async']:.1f}% "
                f"(blocking -> async), goodput "
                f"{100 * row['goodput_fraction_blocking']:.1f}% -> "
                f"{100 * row['goodput_fraction_async']:.1f}%, one "
                f"injected preemption + supervised restart, buckets "
                f"sum-checked"
            ),
        )
        emitted += 1
    if not emitted:
        raise RuntimeError("all config-16 workloads failed")


def config17_serve_router(out: list) -> None:
    """Fleet router (ISSUE 14): the canonical multi-tenant arrival mix
    (``decode_bench.router_mix_setup`` — the one-definition rule)
    drained through a FleetRouter over N fresh engine replicas, prefix
    affinity ON then OFF, identical greedy outputs asserted by
    ``bench_router``'s caller.  The headline is the affinity-on
    aggregate tokens/s; the gated fields are the cross-replica
    ``prefill_frac`` (static counters — affinity concentrating tenants
    must keep it below the affinity-off control), per-class p99 TTFT
    (direction ``ttft`` lower, judged against the widened
    ``_NOISE_FLOORS`` band), and the sharing counters
    (``shared``/``subpage``/``affinity`` higher).  The fleet counter
    law ``prefill + shared == submitted`` is asserted inside
    ``bench_router`` on every drain."""
    import dataclasses as _dc

    import jax

    from tpuscratch.bench.decode_bench import (
        arrival_mix_requests,
        bench_router,
        default_decode_setup,
        router_mix_setup,
    )
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve.router import RouterConfig, SLOClass

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, _batches, _kw = default_decode_setup(on_tpu)
    setup = router_mix_setup(on_tpu)
    scfg = _dc.replace(
        scfg, prefix_share=True,
        max_seq=max(scfg.max_seq, setup["length"] + setup["max_new"]),
    )
    tagged = arrival_mix_requests(
        setup["mix"], setup["n_requests"], setup["length"], scfg.vocab,
        max_new=setup["max_new"],
    )
    classes = tuple(SLOClass(n, target=t) for n, t in setup["classes"])
    # median-of-k re-measure (the noise-robust-records satellite): the
    # rate and TTFT fields are measured k times per arm, interleaved so
    # machine drift hits both arms alike, and the row is each arm's
    # median-tokens/s drain — the static counter fields are identical
    # across repeats (deterministic workload), so picking one WHOLE
    # drain keeps the row's counters self-consistent
    runs = {True: [], False: []}
    for _rep in range(3):
        for aff in (True, False):
            runs[aff].append(bench_router(
                mesh, cfg, scfg, setup["n_replicas"], tagged,
                rcfg=RouterConfig(affinity=aff, classes=classes),
            ))
    outs = {r.pop("outputs") for rs in runs.values() for r in rs}
    if len(outs) != 1:
        raise RuntimeError(
            "config 17: outputs diverged across routing arms/repeats "
            "— routing changed what was emitted"
        )

    def by_rate(r):
        return r["tokens_per_s"]

    on, off = _median_of(runs[True], by_rate), _median_of(runs[False], by_rate)
    if on["prefill_frac"] > off["prefill_frac"]:
        # static counters on a deterministic workload: affinity must
        # concentrate sharing, this is arithmetic, not measurement
        raise RuntimeError(
            f"config 17: affinity-on prefill_frac {on['prefill_frac']} "
            f"above affinity-off {off['prefill_frac']}"
        )
    per_class = {}
    for name, c in sorted(on["classes"].items()):
        per_class[f"ttft_p99_s_{name}"] = c["ttft_p99_s"]
        per_class[f"ttft_p50_s_{name}"] = c["ttft_p50_s"]
        per_class[f"tokens_per_s_{name}"] = c["tokens_per_s"]
    print(
        f"# config 17: affinity {on['tokens_per_s']:.3e} tok/s vs "
        f"{off['tokens_per_s']:.3e} off "
        f"({on['tokens_per_s'] / off['tokens_per_s']:.3f}x), "
        f"prefill_frac {on['prefill_frac']:.3f} vs "
        f"{off['prefill_frac']:.3f}, subpage {on['subpage_tokens']} tok",
        file=sys.stderr,
    )
    _emit(
        out,
        config=17,
        metric="serve_router_tokens_per_s",
        value=on["tokens_per_s"],
        tokens_per_s_affinity_off=off["tokens_per_s"],
        affinity_speedup=on["tokens_per_s"] / off["tokens_per_s"],
        prefill_frac=on["prefill_frac"],
        prefill_frac_affinity_off=off["prefill_frac"],
        shared_tokens=on["shared_tokens"],
        subpage_tokens=on["subpage_tokens"],
        affinity_hits=on["affinity_hits"],
        affinity_tokens=on["affinity_tokens"],
        replicas=on["replicas"],
        requests=on["requests"],
        **per_class,
        detail=(
            f"{on['replicas']} replicas, {on['requests']} requests, "
            f"affinity on/off prefill_frac {on['prefill_frac']:.3f}/"
            f"{off['prefill_frac']:.3f}, aggregate "
            f"{on['tokens_per_s']:.3e}/{off['tokens_per_s']:.3e} tok/s, "
            f"{on['subpage_tokens']} sub-page tokens (not "
            f"page-quantized), outputs identical"
        ),
    )


def config18_cosched(out: list) -> None:
    """Mesh co-scheduling (ISSUE 16): a training run and an MG3D solve
    time-slicing ONE mesh under ``runtime.scheduler.MeshScheduler``'s
    goodput-share policy, vs the same two jobs run back-to-back solo.
    Both arms' results are asserted BIT-identical (the chunk-boundary
    preemption contract), both streams are accounted by
    ``obs.goodput.by_workload`` with the partition invariants checked
    live (per-workload buckets sum to per-workload walls; the walls sum
    to the scheduler wall exactly).  Gated fields: aggregate goodput
    fraction (higher), achieved-vs-target ``share_err`` (lower), and
    per-context-switch overhead ``switch_s`` (lower), all with CPU
    noise floors in ``obs.regress``."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from tpuscratch.models.trainer import train_program
    from tpuscratch.models.transformer import TransformerConfig
    from tpuscratch.obs.goodput import by_workload
    from tpuscratch.obs.report import load_events
    from tpuscratch.obs.sink import Sink
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.runtime.scheduler import GoodputShare, MeshScheduler
    from tpuscratch.solvers.runner import mg3d_solve_program

    avail = len(jax.devices())
    n = min(4, avail)
    rng = np.random.default_rng(0)
    mesh = make_mesh((n, 1), ("dp", "sp"), jax.devices()[:n])
    cfg = TransformerConfig(d_model=128, n_heads=2, n_experts=n,
                            d_ff=256, n_layers=2)
    steps, save_every = 16, 2
    sdims = (2, 2, 1) if avail >= 4 else (1, 1, 1)
    ns = sdims[0] * sdims[1] * sdims[2]
    b = rng.standard_normal(
        tuple(d * 32 for d in sdims)).astype(np.float32)
    b -= b.mean()
    smesh = make_mesh(sdims, ("z", "row", "col"), jax.devices()[:ns])
    solve_kw = dict(mesh=smesh, tol=1e-7, max_cycles=24, chunk_cycles=4)
    targets = {"train": 0.7, "solver": 0.3}

    def tprog(ck, sink):
        return train_program(mesh, cfg, steps, ck,
                             save_every=save_every, batch=2 * n, seq=32,
                             optimizer="adam", obs=sink)

    def sprog(ck, sink):
        return mg3d_solve_program(b, ck, sink=sink, **solve_kw)

    # warm both compiled programs OUTSIDE any accounting window (the
    # config-16 discipline: the lru-cached solver chunk program and the
    # jit cache are shared across arms, so neither arm's first chunk
    # should eat the compile into its goodput window)
    wwd = tempfile.mkdtemp(prefix="tpuscratch_c18_warm_")
    try:
        tprog(f"{wwd}/t", None).run()
        sprog(f"{wwd}/s", None).run()
    finally:
        shutil.rmtree(wwd, ignore_errors=True)

    arms = {}
    for mode in ("solo", "cosched"):
        wd = tempfile.mkdtemp(prefix=f"tpuscratch_c18_{mode}_")
        try:
            path = f"{wd}/obs.jsonl"
            sink = Sink(path, run={
                "bench": f"record/config18/{mode}",
                "platform": jax.default_backend(),
            })
            sched_ev = None
            if mode == "solo":
                r_train = tprog(f"{wd}/ckt", sink).run()
                r_solve = sprog(f"{wd}/cks", sink).run()
            else:
                sched = MeshScheduler(policy=GoodputShare(targets),
                                      sink=sink)
                sched.add(tprog(f"{wd}/ckt", sink))
                sched.add(sprog(f"{wd}/cks", sink))
                res = sched.run()
                r_train, r_solve = res["train"], res["solver"]
            sink.close()
            events = load_events([path])
            wg = by_workload(events, targets=targets)
            wg.check()  # both partition invariants, live, or raise
            if mode == "cosched":
                sched_ev = next(e for e in events
                                if e.get("event") == "sched/run")
            arms[mode] = (r_train, r_solve, wg, sched_ev)
        finally:
            shutil.rmtree(wd, ignore_errors=True)

    (p_solo, rep_solo), (x_solo, _), wg_solo, _ = arms["solo"]
    (p_co, rep_co), (x_co, srep_co), wg_co, sched_ev = arms["cosched"]
    same_params = all(
        bool(np.array_equal(np.asarray(a), np.asarray(c)))
        for a, c in zip(jax.tree.leaves(p_solo), jax.tree.leaves(p_co))
    )
    if not (same_params and rep_solo.losses == rep_co.losses
            and np.array_equal(x_solo, x_co)):
        raise RuntimeError(
            "co-scheduled results differ from solo — the chunk-boundary "
            "preemption contract is broken"
        )

    def agg_goodput(wg):
        step = sum(r.buckets.get("step", 0.0) for r in wg.reports.values())
        return step / wg.wall_s if wg.wall_s else 0.0

    shares = wg_co.shares
    share_err = max(abs(shares[k] - targets[k]) for k in targets)
    switches = int(sched_ev.get("switches") or 0)
    switch_s = (float(sched_ev.get("overhead_s") or 0.0)
                / max(switches, 1))
    row = {
        "goodput_fraction_cosched": agg_goodput(wg_co),
        "goodput_fraction_solo": agg_goodput(wg_solo),
        "share_train": shares.get("train", 0.0),
        "share_solver": shares.get("solver", 0.0),
        "target_train": targets["train"],
        "target_solver": targets["solver"],
        "share_err": share_err,
        "switches": switches,
        "switch_s": switch_s,
        "wall_s_cosched": wg_co.wall_s,
        "wall_s_solo": wg_solo.wall_s,
        "solver_cycles": srep_co.cycles,
    }
    _emit(
        out,
        config=18,
        metric="cosched_goodput_train_solver",
        # headline: the co-scheduled aggregate goodput fraction (the
        # metric name's "goodput" substring infers higher-is-better);
        # share_err / switch_s ride as direction-registered fields
        value=row["goodput_fraction_cosched"],
        **row,
        detail=(
            f"train+solver on one mesh, GoodputShare targets "
            f"{targets['train']:.0%}/{targets['solver']:.0%}, achieved "
            f"{row['share_train']:.1%}/{row['share_solver']:.1%} "
            f"(err {share_err:.1%}), {switches} switches at "
            f"{1e3 * switch_s:.2f} ms/switch, results bit-identical to "
            f"solo, both partition checks live"
        ),
    )


def config19_traffic_chaos(out: list) -> None:
    """SLO compliance under fleet chaos (ISSUE 17): the config-19
    trace (``bench.traffic.traffic_chaos_setup`` — seeded tenants,
    Zipf prefix reuse, diurnal + burst arrivals, long-tail lengths)
    streamed OPEN-loop through a 3-replica FleetRouter twice per
    repeat — once under the fixed replica-kill/stall ChaosPlan, once
    clean — with the output DIGESTS asserted identical across every
    arm and repeat (replica churn must not change one emitted token).
    The headline is the under-churn aggregate tokens/s; the gated
    fields are per-class p99 TTFT (``ttft`` lower, widened band),
    per-class goodput fraction (``goodput`` higher — exact token
    counters: delivered work over delivered + re-prefilled + killed),
    and the zero-loss counters (``readmitted`` higher at the fixed
    plan, ``dropped`` lower — recorded 0).  The generalized counter
    law ``prefill + shared == submitted + readmitted`` is asserted
    inside ``run_traffic`` on every arm."""
    import dataclasses as _dc

    import jax

    from tpuscratch.bench.decode_bench import default_decode_setup
    from tpuscratch.bench.traffic import bench_traffic, traffic_chaos_setup
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, _batches, _kw = default_decode_setup(on_tpu)
    setup = traffic_chaos_setup(on_tpu, scfg.vocab)
    scfg = _dc.replace(
        scfg, prefix_share=True,
        max_seq=max(scfg.max_seq, setup["tcfg"].max_total_len),
    )
    # interleaved median-of-3 per arm (the config-17 discipline):
    # machine drift hits chaos and clean alike; static counters are
    # identical across repeats, so one whole median run keeps the
    # row's counters self-consistent
    runs = {True: [], False: []}
    for _rep in range(3):
        for chaos in (True, False):
            runs[chaos].append(
                bench_traffic(mesh, cfg, scfg, setup, chaos=chaos)
            )
    digests = {r.pop("digest") for rs in runs.values() for r in rs}
    if len(digests) != 1:
        raise RuntimeError(
            "config 19: output digests diverged across chaos/clean "
            "arms — replica churn changed what was emitted"
        )

    def by_rate(r):
        return r["tokens_per_s"]

    ch = _median_of(runs[True], by_rate)
    cl = _median_of(runs[False], by_rate)
    per_class = {}
    for name, c in sorted(ch["classes"].items()):
        per_class[f"ttft_p99_s_{name}"] = c["ttft_p99_s"]
        per_class[f"ttft_p50_s_{name}"] = c["ttft_p50_s"]
        per_class[f"goodput_frac_{name}"] = c["goodput_frac"]
    print(
        f"# config 19: chaos {ch['tokens_per_s']:.3e} tok/s vs "
        f"{cl['tokens_per_s']:.3e} clean over {ch['requests']} "
        f"requests, {ch['kills']} kills/{ch['stalls']} stalls, "
        f"{ch['readmitted']} readmitted ({ch['readmitted_tokens']} "
        f"tok), {ch['dropped']} dropped, digests identical",
        file=sys.stderr,
    )
    _emit(
        out,
        config=19,
        metric="traffic_chaos_tokens_per_s",
        value=ch["tokens_per_s"],
        tokens_per_s_clean=cl["tokens_per_s"],
        readmitted=ch["readmitted"],
        readmitted_tokens=ch["readmitted_tokens"],
        dropped=ch["dropped"],
        kills=ch["kills"],
        stalls=ch["stalls"],
        replicas=ch["replicas"],
        requests=ch["requests"],
        peak_open=ch["peak_open"],
        wall_s_chaos=ch["wall_s"],
        wall_s_clean=cl["wall_s"],
        **per_class,
        detail=(
            f"{ch['replicas']} replicas, {ch['requests']}-request "
            f"open-loop trace (budget {ch['peak_open']} peak open), "
            f"{ch['kills']} replica kills + {ch['stalls']} stall, "
            f"{ch['readmitted']} requests re-admitted "
            f"({ch['readmitted_tokens']} prompt tok re-prefilled, "
            f"{ch['lost_tokens']} generated tok lost), 0 dropped, "
            f"chaos/clean digests identical, "
            f"{ch['tokens_per_s']:.3e}/{cl['tokens_per_s']:.3e} tok/s"
        ),
    )


def config20_overload(out: list) -> None:
    """Overload survival (ISSUE 18): the config-20 storm
    (``bench.traffic.overload_setup`` — an overcommitted closed loop
    of think-time clients, diurnal + burst arrivals, seeded retry
    policy) run twice per repeat — once on the 3-replica storm fleet
    with a correlated RACK kill at the burst crest and SLO shedding
    armed, once on the 5-replica clean fleet — with the clean arm's
    digest (storm's terminally-shed rids excluded) asserted
    bit-identical to the storm's: overload control may drop work, but
    only EXPLICITLY, and everything else is untouched.  The survival
    claims (zero drops, zero TOP-class sheds, batch sheds > 0, retry
    storm live, rack kill fired, peak_open bounded by the client
    population) are asserted inside ``bench_overload``; the gated
    fields here are the shed/retry/abandon counters (``sheds`` lower —
    deterministic on the logical shed clock, tight band;
    ``sheds_latency`` recorded 0 is the zero-top-shed gate), per-class
    p99 TTFT and goodput fraction, and the zero-loss counters.  The
    request law ``submitted == finished + shed + open`` is asserted
    every fleet tick inside ``run_traffic_closed``."""
    import dataclasses as _dc

    import jax

    from tpuscratch.bench.decode_bench import default_decode_setup
    from tpuscratch.bench.traffic import bench_overload, overload_setup
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, _batches, _kw = default_decode_setup(on_tpu)
    setup = overload_setup(on_tpu, scfg.vocab)
    scfg = _dc.replace(
        scfg, prefix_share=True,
        max_seq=max(scfg.max_seq, setup["tcfg"].max_total_len),
    )
    # interleaved median-of-3 per arm (the config-17 discipline), with
    # the digest PAIRING checked per repeat: each clean run excludes
    # exactly its paired storm run's terminally-shed rids
    storms, cleans = [], []
    for _rep in range(3):
        st = bench_overload(mesh, cfg, scfg, setup, storm=True)
        cl = bench_overload(mesh, cfg, scfg, setup, storm=False,
                            exclude_rids=frozenset(st["shed_rids"]))
        if cl["digest"] != st["digest"]:
            raise RuntimeError(
                "config 20: clean digest (shed rids excluded) differs "
                "from the storm's — shedding changed a surviving "
                "request's output"
            )
        storms.append(st)
        cleans.append(cl)
    if len({tuple(r.pop("shed_rids")) for r in storms + cleans}) > 2:
        # storm repeats must shed the SAME rids (logical shed clock);
        # clean repeats shed none — at most {storm set, ()} distinct
        raise RuntimeError(
            "config 20: shed sets diverged across repeats — the storm "
            "is not deterministic"
        )
    digests = {r.pop("digest") for r in storms + cleans}
    if len(digests) != 1:
        raise RuntimeError(
            "config 20: output digests diverged across repeats"
        )

    def by_rate(r):
        return r["tokens_per_s"]

    st = _median_of(storms, by_rate)
    cl = _median_of(cleans, by_rate)
    per_class = {}
    for name, c in sorted(st["classes"].items()):
        per_class[f"ttft_p99_s_{name}"] = c["ttft_p99_s"]
        per_class[f"goodput_frac_{name}"] = c["goodput_frac"]
        per_class[f"sheds_{name}"] = c["sheds"]
        per_class[f"shed_frac_{name}"] = c["shed_frac"]
    print(
        f"# config 20: storm {st['tokens_per_s']:.3e} tok/s vs "
        f"{cl['tokens_per_s']:.3e} clean over {st['requests']} "
        f"requests, {st['kills']} rack kills, {st['sheds']} sheds "
        f"(latency {per_class['sheds_latency']}), {st['retries']} "
        f"retries, {st['abandoned']} abandoned, {st['dropped']} "
        f"dropped, digests identical",
        file=sys.stderr,
    )
    _emit(
        out,
        config=20,
        metric="overload_survival_tokens_per_s",
        value=st["tokens_per_s"],
        tokens_per_s_clean=cl["tokens_per_s"],
        sheds=st["sheds"],
        sheds_clean=cl["sheds"],
        retries=st["retries"],
        abandoned=st["abandoned"],
        shed_frac=st["shed_frac"],
        readmitted=st["readmitted"],
        dropped=st["dropped"],
        kills=st["kills"],
        replicas=st["replicas"],
        requests=st["requests"],
        peak_open=st["peak_open"],
        completed_latency=st["completed_latency"],
        completed_batch=st["completed_batch"],
        ticks_storm=st["ticks"],
        ticks_clean=cl["ticks"],
        wall_s_storm=st["wall_s"],
        wall_s_clean=cl["wall_s"],
        **per_class,
        detail=(
            f"{st['replicas']}-replica storm vs {cl['replicas']}-"
            f"replica clean, {st['requests']}-request closed loop "
            f"(peak {st['peak_open']} open), rack kill of "
            f"{st['kills']} replicas at the burst crest, "
            f"{st['sheds']} batch sheds / 0 latency sheds, "
            f"{st['retries']} retries, {st['abandoned']} abandoned, "
            f"{st['readmitted']} readmitted, 0 dropped, digests "
            f"identical with shed rids excluded, "
            f"{st['tokens_per_s']:.3e}/{cl['tokens_per_s']:.3e} tok/s"
        ),
    )


def config21_hostfree(out: list) -> None:
    """Host-free decode (ISSUE 19): the two compositions the old macro
    clamp forbade, each measured at macro_steps T=1 vs T=4 on the SAME
    workload and batch.

    ``serve_decode_spec_macro``: speculative decoding (spec_k drafts,
    accept-friendly periodic prompt) INSIDE the macro scan — the
    in-carry propose/verify/accept path, up to T*(spec_k+1) token
    rounds per dispatch.  ``serve_decode_macro_tiered``: a
    host-offloaded KV tier (kv_host_pages) under the macro scan — the
    next wave's prefetch is issued behind the running scan instead of
    clamping it to T=1.

    Each row's dispatches/token and host-syncs/token are EXACT engine
    counters over exact token counts (static, tight regression band);
    tokens/s is the measured wall-clock (median-of-3; CPU-proxy noise
    floors apply off-TPU — the PR-14 discipline).  The direction claim
    of the ISSUE — composed T=4 dispatches/token STRICTLY below the
    T=1 baseline's — is asserted here (RuntimeError), not just left to
    ``--check``: a rebuilt clamp cannot produce a quietly-flat row.
    Greedy bit-identity of the composed paths to the T=1 engine is
    test-gated (tests/test_serve_hostfree.py), not re-proven here."""
    import dataclasses as _dc

    import jax

    from tpuscratch.bench.decode_bench import (
        accept_friendly_prompt,
        bench_decode,
        default_decode_setup,
        fitting_batches,
    )
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, batches, kwargs = default_decode_setup(on_tpu)
    fit_kw = dict(
        prompt_len=kwargs.get("prompt_len", 8),
        measure_steps=kwargs.get("measure_steps", 32),
        warmup_steps=kwargs.get("warmup_steps", 4),
    )

    # --- spec x macro: one batch that fits the COMPOSED T=4 page
    # reservation (bench_budget's (spec_k+1)*T product rule via
    # fitting_batches — the one shared sizing arithmetic), same batch
    # at T=1 so the comparison is apples-to-apples
    spec_k = 4 if on_tpu else 3
    spec4 = _dc.replace(scfg, spec_k=spec_k, macro_steps=4)
    _, _fit = fitting_batches(spec4, batches, **fit_kw)
    batch = max(_fit or (1,))
    prompt = accept_friendly_prompt(kwargs.get("prompt_len", 8),
                                    scfg.vocab)
    kw = {k: v for k, v in kwargs.items() if k != "prompt_len"}
    srows = {}
    for T in (1, 4):
        srows[T] = _median_run(
            lambda T=T: bench_decode(
                mesh, cfg,
                _dc.replace(spec4, n_slots=batch, macro_steps=T),
                prompt=prompt, **kw,
            ),
            key=lambda r: r.tokens_per_s,
        )
        print(f"# spec{spec_k} x macro T={T}: {srows[T].summary()}",
              file=sys.stderr)
    s1, s4 = srows[1], srows[4]
    if not s4.dispatches_per_token < s1.dispatches_per_token:
        raise RuntimeError(
            "spec x macro dispatches/token did not drop: "
            f"T=4 {s4.dispatches_per_token:.4f} vs "
            f"T=1 {s1.dispatches_per_token:.4f} — the macro clamp "
            "is back (ISSUE 19 lift regressed)"
        )
    _emit(
        out,
        config=21,
        metric="serve_decode_spec_macro",
        value=s4.tokens_per_s,
        tokens_per_s_t1=s1.tokens_per_s,
        tokens_per_s_t4=s4.tokens_per_s,
        dispatches_per_token_t1=s1.dispatches_per_token,
        dispatches_per_token_t4=s4.dispatches_per_token,
        host_syncs_per_token_t4=s4.host_syncs_per_token,
        accept_len_mean_t4=s4.accept_len_mean,
        detail=(
            f"spec_k={spec_k} x T=4: {s4.tokens_per_s:.3e} tok/s, "
            f"dispatches/token {s1.dispatches_per_token:.4f} -> "
            f"{s4.dispatches_per_token:.4f}, accept len "
            f"{s4.accept_len_mean:.2f}/{spec_k}"
        ),
    )

    # --- tiered x macro: host tier as deep as the device pool; the
    # batch fits the T=4 DEVICE reservation (the host tier extends
    # capacity, not the admission watermark)
    tier4 = _dc.replace(scfg, kv_host_pages=scfg.n_pages, macro_steps=4)
    _, _fit_t = fitting_batches(tier4, batches, **fit_kw)
    tbatch = max(_fit_t or (1,))
    trows = {}
    for T in (1, 4):
        trows[T] = _median_run(
            lambda T=T: bench_decode(
                mesh, cfg,
                _dc.replace(tier4, n_slots=tbatch, macro_steps=T),
                **kwargs,
            ),
            key=lambda r: r.tokens_per_s,
        )
        print(f"# tiered x macro T={T}: {trows[T].summary()}",
              file=sys.stderr)
    t1, t4 = trows[1], trows[4]
    if not t4.dispatches_per_token < t1.dispatches_per_token:
        raise RuntimeError(
            "tiered x macro dispatches/token did not drop: "
            f"T=4 {t4.dispatches_per_token:.4f} vs "
            f"T=1 {t1.dispatches_per_token:.4f} — the macro clamp "
            "is back (ISSUE 19 lift regressed)"
        )
    _emit(
        out,
        config=21,
        metric="serve_decode_macro_tiered",
        value=t4.tokens_per_s,
        tokens_per_s_t1=t1.tokens_per_s,
        tokens_per_s_t4=t4.tokens_per_s,
        dispatches_per_token_t1=t1.dispatches_per_token,
        dispatches_per_token_t4=t4.dispatches_per_token,
        host_syncs_per_token_t4=t4.host_syncs_per_token,
        detail=(
            f"kv_host_pages={tier4.kv_host_pages} x T=4: "
            f"{t4.tokens_per_s:.3e} tok/s, dispatches/token "
            f"{t1.dispatches_per_token:.4f} -> "
            f"{t4.dispatches_per_token:.4f}"
        ),
    )


def config22_reqtrace(out: list) -> None:
    """Request-trace decomposition (ISSUE 20): the config-19 chaos
    workload (replica kills + stall + head-of-queue re-admission) run
    twice per repeat — once with a fleet-wide per-request tracer
    (``obs.reqtrace.ReqTracer``, sample_rate=1.0) shared across the
    router and every replica, once untraced — with the output DIGESTS
    asserted identical per pair (tracing observes, never perturbs) and
    the measured tracing overhead gated under 2% of the untraced
    tokens/s.  Inside the traced arm ``bench_reqtrace`` asserts the
    tentpole invariants live: every drained request's bucket
    decomposition sums to its e2e latency EXACTLY
    (``RequestTrace.check`` raises inside ``collect`` every fleet
    tick), at least one kill victim's trace carries wasted work, and
    the exported span forest passes the extended (async + flow event)
    Chrome-trace validator.  The gated fields are the per-class bucket
    means (``decomp_*`` — queue/handoff/waste lower, on CPU-proxy
    noise floors) and the overhead fraction (lower)."""
    import dataclasses as _dc

    import jax

    from tpuscratch.bench.decode_bench import default_decode_setup
    from tpuscratch.bench.traffic import bench_reqtrace, traffic_chaos_setup
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg, scfg, _batches, _kw = default_decode_setup(on_tpu)
    setup = traffic_chaos_setup(on_tpu, scfg.vocab)
    scfg = _dc.replace(
        scfg, prefix_share=True,
        max_seq=max(scfg.max_seq, setup["tcfg"].max_total_len),
    )
    # interleaved pairs (the config-17 discipline): machine drift hits
    # traced and untraced alike; the digest pairing is checked PER
    # pair, so one perturbing hook cannot hide behind a median
    pairs = []
    for _rep in range(3):
        un = bench_reqtrace(mesh, cfg, scfg, setup, traced=False)
        td = bench_reqtrace(mesh, cfg, scfg, setup, traced=True)
        if td["digest"] != un["digest"]:
            raise RuntimeError(
                "config 22: traced digest differs from untraced — "
                "tracing perturbed what the fleet emitted"
            )
        pairs.append((un, td))
    # overhead: the MIN over pairs of the traced arm's fractional
    # tokens/s deficit — any single pair bounds the true overhead from
    # above, and one-sided scheduler noise inflates single pairs
    overhead = min(
        max(0.0, 1.0 - td["tokens_per_s"] / un["tokens_per_s"])
        for un, td in pairs
    )
    if overhead >= 0.02:
        raise RuntimeError(
            f"config 22: tracing overhead {overhead:.1%} >= 2% of "
            "untraced tokens/s in every pair — the observe-only "
            "contract regressed"
        )

    def by_rate(r):
        return r["tokens_per_s"]

    un = _median_of([p[0] for p in pairs], by_rate)
    td = _median_of([p[1] for p in pairs], by_rate)
    decomp = {k: v for k, v in sorted(td.items())
              if k.startswith("decomp_")}
    print(
        f"# config 22: traced {td['tokens_per_s']:.3e} tok/s vs "
        f"{un['tokens_per_s']:.3e} untraced (overhead {overhead:.2%}), "
        f"{td['n_traces']} traces ({td['waste_traces']} with waste), "
        f"{td['kills']} kills, {td['readmitted']} readmitted, "
        f"digests identical, every decomposition exact",
        file=sys.stderr,
    )
    _emit(
        out,
        config=22,
        metric="request_trace_decomposition",
        value=td["tokens_per_s"],
        tokens_per_s_untraced=un["tokens_per_s"],
        trace_overhead_frac=overhead,
        n_traces=td["n_traces"],
        waste_traces=td["waste_traces"],
        kills=td["kills"],
        readmitted=td["readmitted"],
        requests=td["requests"],
        replicas=td["replicas"],
        ticks=td["ticks"],
        wall_s_traced=td["wall_s"],
        wall_s_untraced=un["wall_s"],
        **decomp,
        detail=(
            f"{td['replicas']} replicas, {td['requests']}-request "
            f"chaos trace, {td['n_traces']} span trees collected "
            f"({td['waste_traces']} carrying kill/degrade waste), "
            f"every bucket decomposition sums to e2e exactly, traced/"
            f"untraced digests identical, overhead {overhead:.2%} "
            f"( {td['tokens_per_s']:.3e} vs {un['tokens_per_s']:.3e} "
            f"tok/s), Perfetto flow export validated"
        ),
    )


CONFIGS = {
    1: config1_stencil_single,
    2: config2_dot,
    3: config3_pingpong,
    4: config4_stencil_mesh,
    5: config5_weak_scaling,
    6: config6_flash_attention,
    7: config7_collectives,
    8: config8_dft,
    9: config9_stencil3d,
    10: config10_dma_halo,
    11: config11_train,
    12: config12_decode,
    13: config13_zero_train,
    14: config14_plan_overlap,
    15: config15_solver,
    16: config16_elastic_goodput,
    17: config17_serve_router,
    18: config18_cosched,
    19: config19_traffic_chaos,
    20: config20_overload,
    21: config21_hostfree,
    22: config22_reqtrace,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--configs",
                    default="1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17,18,"
                            "19,20,21,22")
    ap.add_argument("--json", default=None, help="append results to this file")
    ap.add_argument("--obs", default=None,
                    help="obs JSONL path: config 12 attaches the engine "
                         "sink and emits per-tick telemetry there "
                         "(opt-in: the instrumented ticks are labeled in "
                         "the row's detail, so recorded numbers stay "
                         "comparable with pre-obs rows by default)")
    ap.add_argument("--check", default=None, metavar="BASE.json",
                    help="after measuring, diff this run's rows against "
                         "BASE.json through tpuscratch.obs.regress; a "
                         "beyond-noise regression makes the exit code "
                         "nonzero — the enforceable form of the BENCH_* "
                         "trajectory")
    ap.add_argument("--noise", type=float, default=0.1,
                    help="fractional noise band for --check (default 0.1)")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh first (dev path)")
    args = ap.parse_args(argv)

    if args.cpu_devices:
        from tpuscratch.runtime.hostenv import force_cpu_devices

        force_cpu_devices(args.cpu_devices)

    out: list = []
    rc = 0
    for c in (int(x) for x in args.configs.split(",")):
        kw = {"obs_path": args.obs} if c == 12 else {}
        try:
            CONFIGS[c](out, **kw)
        except Exception as e:  # keep going; report what failed
            print(f"# config {c} skipped: {e}", file=sys.stderr)
            rc = rc or (0 if isinstance(e, Needs) else 1)
    if args.json:
        with open(args.json, "a") as f:
            for row in out:
                f.write(json.dumps(row) + "\n")
    if args.check:
        from tpuscratch.obs.regress import (
            compare,
            format_findings,
            has_regression,
            index_rows,
            load_rows,
        )

        findings = compare(load_rows(args.check), index_rows(out),
                           noise=args.noise)
        print(format_findings(findings, args.noise), file=sys.stderr)
        if has_regression(findings):
            rc = rc or 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
