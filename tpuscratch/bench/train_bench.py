"""Composed-training throughput: tokens/s of the full train step.

Every other BASELINE row is a kernel or collective microbench; this one
measures the thing the framework exists to compose — the dp x sp
transformer train step (models/transformer.py: ring attention over sp,
expert-parallel MoE over dp, grad + copy-axis reduction + SGD in ONE
compiled program) — end to end, with the repo's standard methodology:
many steps folded into one compiled scan, loop-carried data dependence
so steps cannot be hoisted, readback fencing.

FLOP accounting (reported alongside tokens/s for the roofline argument):
active parameters per token = 4 d^2 (attention projections) + 2 d d_ff
(the ONE routed expert) per layer; a train step costs ~6 FLOPs per
active parameter per token (fwd 2, bwd 4), plus attention's
sequence-quadratic term 12 S d per token per layer (QK^T and PV, fwd +
bwd, x0.5 when causal). MoE capacity slack (capacity_factor tokens
processed per expert slot vs tokens routed) is charged at the router's
capacity, i.e. the arithmetic actually executed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    param_spec,
    train_step_fn,
)


def train_flops_per_token(cfg: TransformerConfig, seq: int) -> float:
    """Approximate train-step FLOPs per token (see module docstring)."""
    d, f = cfg.d_model, cfg.d_ff
    dense = 4 * d * d + cfg.capacity_factor * 2 * d * f
    attn = 12 * seq * d * (0.5 if cfg.causal else 1.0)
    return 6.0 * dense * cfg.n_layers + attn * cfg.n_layers


def train_throughput_program(mesh: Mesh, cfg: TransformerConfig, steps: int,
                             lr: float = 1e-3, optimizer: str = "sgd"):
    """jit'd fn(params, x, y) -> (params, loss) running ``steps`` train
    steps in one scan (the data is reused — throughput, not learning).
    ``optimizer='adam'`` carries the moment state through the scan too
    (initialized fresh inside the program — throughput, not a resumable
    run)."""
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.models.transformer import (
        init_adam_state,
        train_step_adam_fn,
    )

    if optimizer not in ("sgd", "adam"):
        raise ValueError(f"optimizer must be sgd|adam, got {optimizer!r}")
    if optimizer == "adam":
        step = train_step_adam_fn(cfg, lr=lr)

        def body(params, x, y):
            def one(carry, _):
                p, o = carry
                p, o, loss = step(p, o, x, y)
                return (p, o), loss

            (params, _), losses = lax.scan(
                one, (params, init_adam_state(params)), None, length=steps
            )
            return params, losses[-1]
    else:
        step = train_step_fn(cfg, lr=lr)

        def body(params, x, y):
            # params are the loop carry: every step reads the previous
            # step's update, so the scan cannot be collapsed or hoisted
            def one(p, _):
                p, loss = step(p, x, y)
                return p, loss

            params, losses = lax.scan(one, params, None, length=steps)
            return params, losses[-1]

    pspec = param_spec(cfg)
    return run_spmd(
        mesh,
        body,
        (pspec, P("dp", "sp"), P("dp", "sp")),
        (pspec, P()),
    )


def bench_train(
    mesh: Optional[Mesh] = None,
    cfg: Optional[TransformerConfig] = None,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    steps: Optional[int] = None,
    iters: int = 3,
    fence: str = "readback",
    seed: int = 0,
    optimizer: str = "sgd",
) -> BenchResult:
    """tokens/s of the composed train step; items = tokens processed."""
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    if mesh is None:
        mesh = make_mesh((1, 1), ("dp", "sp"))
    if cfg is None:
        cfg = (
            TransformerConfig(
                d_model=1024, n_heads=8, n_experts=4, d_ff=4096,
                n_layers=4, capacity_factor=2.0, attn_impl="pallas",
            )
            if on_tpu
            else TransformerConfig(
                d_model=32, n_heads=2, n_experts=2, d_ff=64, n_layers=1,
                capacity_factor=2.0,
            )
        )
    batch = batch if batch is not None else (8 if on_tpu else 2 * mesh.shape["dp"])
    seq = seq if seq is not None else (2048 if on_tpu else 8 * mesh.shape["sp"])
    steps = steps if steps is not None else (20 if on_tpu else 2)

    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32))
    params = init_params(seed, cfg)
    prog = train_throughput_program(mesh, cfg, steps, optimizer=optimizer)
    # correctness gate doubles as compile warmup: the loss must be finite
    out_params, loss = prog(params, x, y)
    if not np.isfinite(float(loss)):
        raise AssertionError(f"train step produced loss {float(loss)}")
    tokens = batch * seq * steps
    return time_device(
        prog, params, x, y, iters=iters, warmup=1, fence=fence,
        name=(
            f"train d{cfg.d_model} ff{cfg.d_ff} L{cfg.n_layers} "
            f"e{cfg.n_experts} {cfg.compute_dtype} {optimizer} b{batch} "
            f"s{seq} x{steps} on {mesh.shape['dp']}x{mesh.shape['sp']} "
            f"({cfg.attn_impl})"
        ),
        items=tokens,
    )


@dataclasses.dataclass(frozen=True)
class ObsOverhead:
    """Per-step instrumentation cost against the train step's cost."""

    step_s: float        # best fenced seconds per compiled train step
    instr_s: float       # seconds per full per-step obs update

    @property
    def base_steps_per_s(self) -> float:
        return 1.0 / self.step_s

    @property
    def obs_steps_per_s(self) -> float:
        return 1.0 / (self.step_s + self.instr_s)

    @property
    def overhead(self) -> float:
        """Fractional slowdown from instrumentation (0.01 == 1%)."""
        return self.instr_s / (self.step_s + self.instr_s)

    def summary(self) -> str:
        return (
            f"obs overhead: step {self.step_s * 1e6:.1f} us + instr "
            f"{self.instr_s * 1e6:.2f} us/step = {100 * self.overhead:.3f}%"
            f" ({self.base_steps_per_s:.2f} -> "
            f"{self.obs_steps_per_s:.2f} steps/s)"
        )


def bench_obs_overhead(
    mesh: Optional[Mesh] = None,
    cfg: Optional[TransformerConfig] = None,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    steps: int = 50,
    iters: int = 3,
    seed: int = 0,
    sink_path: Optional[str] = None,
    emit_every: int = 10,
) -> ObsOverhead:
    """Measure what per-step metrics cost against the train step.

    The two terms are measured separately and combined — NOT as the
    difference of two end-to-end timings, which on sub-millisecond CPU
    steps is dominated by dispatch jitter and swings tens of percent
    either way: (a) the compiled step's best fenced time over ``iters``
    runs of ``steps`` steps; (b) the cost of the obs update as wired in
    the trainer — registry counter/gauge/histogram writes EVERY step,
    one buffered sink event every ``emit_every`` steps (the trainer
    emits per save chunk; ``save_every`` defaults to 10) — amortized
    over thousands of repetitions.  The subsystem's budget for
    ``overhead`` is < 2% even against this sub-millisecond CPU step
    (the pessimistic denominator: a real chip config's step is
    milliseconds)."""
    import tempfile
    import time

    from tpuscratch.models.transformer import train_step
    from tpuscratch.obs.metrics import MetricsRegistry
    from tpuscratch.obs.sink import Sink
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    if mesh is None:
        mesh = make_mesh((1, 1), ("dp", "sp"))
    if cfg is None:
        cfg = (
            TransformerConfig(
                d_model=1024, n_heads=8, n_experts=4, d_ff=4096,
                n_layers=4, capacity_factor=2.0, attn_impl="pallas",
            )
            if on_tpu
            else TransformerConfig(
                d_model=32, n_heads=2, n_experts=2, d_ff=64, n_layers=1,
                capacity_factor=2.0,
            )
        )
    batch = batch if batch is not None else 2 * mesh.shape["dp"]
    seq = seq if seq is not None else 8 * mesh.shape["sp"]
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32))
    params0 = init_params(seed, cfg)
    fn = train_step(mesh, cfg)
    jax.block_until_ready(fn(params0, x, y))  # compile outside the window

    step_best = float("inf")
    for _ in range(iters):
        params = params0
        t0 = time.perf_counter()
        for _ in range(steps):
            params, loss = fn(params, x, y)
        jax.block_until_ready(loss)
        step_best = min(step_best, (time.perf_counter() - t0) / steps)

    reps = 5000
    instr_best = float("inf")
    with tempfile.TemporaryDirectory(prefix="obs_overhead_") as tmp:
        path = sink_path or f"{tmp}/overhead.jsonl"
        with Sink(path, run={"bench": "obs-overhead"}) as sink:
            metrics = MetricsRegistry()
            for _ in range(iters):
                t0 = time.perf_counter()
                for i in range(reps):
                    metrics.counter("train/steps").inc()
                    metrics.gauge("train/last_step").set(i)
                    metrics.histogram("train/step_s").observe(step_best)
                    if i % emit_every == 0:
                        sink.emit("train/chunk", step=i, loss=0.0,
                                  grad_norm=0.0, compiles=1)
                instr_best = min(
                    instr_best, (time.perf_counter() - t0) / reps
                )
    return ObsOverhead(step_s=step_best, instr_s=instr_best)


def main() -> int:
    import sys

    if "--obs-overhead" in sys.argv[1:]:
        o = bench_obs_overhead()
        print(o.summary())
        return 0
    r = bench_train()
    print(f"{r.summary()} -> {r.items_per_s:.3e} tokens/s")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
