"""Composed-training throughput: tokens/s of the full train step.

Every other BASELINE row is a kernel or collective microbench; this one
measures the thing the framework exists to compose — the dp x sp
transformer train step (models/transformer.py: ring attention over sp,
expert-parallel MoE over dp, grad + copy-axis reduction + SGD in ONE
compiled program) — end to end, with the repo's standard methodology:
many steps folded into one compiled scan, loop-carried data dependence
so steps cannot be hoisted, readback fencing.

FLOP accounting (reported alongside tokens/s for the roofline argument):
active parameters per token = 4 d^2 (attention projections) + 2 d d_ff
(the ONE routed expert) per layer; a train step costs ~6 FLOPs per
active parameter per token (fwd 2, bwd 4), plus attention's
sequence-quadratic term 12 S d per token per layer (QK^T and PV, fwd +
bwd, x0.5 when causal). MoE capacity slack (capacity_factor tokens
processed per expert slot vs tokens routed) is charged at the router's
capacity, i.e. the arithmetic actually executed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    param_spec,
    train_step_fn,
)


def train_flops_per_token(cfg: TransformerConfig, seq: int) -> float:
    """Approximate train-step FLOPs per token (see module docstring)."""
    d, f = cfg.d_model, cfg.d_ff
    dense = 4 * d * d + cfg.capacity_factor * 2 * d * f
    attn = 12 * seq * d * (0.5 if cfg.causal else 1.0)
    return 6.0 * dense * cfg.n_layers + attn * cfg.n_layers


def train_throughput_program(mesh: Mesh, cfg: TransformerConfig, steps: int,
                             lr: float = 1e-3, optimizer: str = "sgd",
                             zero: bool = False, accum_steps: int = 1,
                             plan=None):
    """jit'd fn(params, x, y) -> (params, loss) running ``steps`` train
    steps in one scan (the data is reused — throughput, not learning).
    ``optimizer='adam'`` carries the moment state through the scan too
    (initialized fresh inside the program — throughput, not a resumable
    run).  ``zero=True`` swaps in the ZeRO-sharded step
    (``models.zero``: reduce-scatter grad sync, dp-sharded flat Adam
    shards carried through the scan, trailing param all-gather);
    ``accum_steps=k`` (ZeRO only) shapes x, y as ``(k, batch, seq, d)``
    and defers the one gradient sync to the last microbatch.

    ``plan`` (a ``parallel.ShardingPlan`` over ``mesh``) selects the
    plan-composed program: its overlap policy threads into the ZeRO
    sync legs, and a PIPELINED plan scans the 3-axis GPipe + ZeRO step
    over stage-stacked params (pass the ``stack_layers`` layout)."""
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.models.transformer import (
        init_adam_state,
        param_spec as _param_spec,
        train_step_adam_fn,
    )

    if optimizer not in ("sgd", "adam"):
        raise ValueError(f"optimizer must be sgd|adam, got {optimizer!r}")
    if zero and optimizer != "adam":
        raise ValueError("zero=True requires optimizer='adam'")
    if accum_steps > 1 and not zero:
        raise ValueError("accum_steps > 1 is the ZeRO deferred-sync path")
    overlap_blocks = plan.overlap_blocks if plan is not None else 0
    if plan is not None and plan.pipelined and accum_steps != 1:
        raise ValueError("a pipelined plan already microbatches through "
                         "n_micro; accum_steps must be 1")
    if plan is not None and plan.pipelined:
        from jax import lax as _lax

        from tpuscratch.models.transformer import param_spec_pp
        from tpuscratch.models.zero import (
            local_zero_state,
            train_step_plan_fn,
        )

        if optimizer != "adam":
            raise ValueError("a pipelined plan trains with adam")
        step = train_step_plan_fn(
            cfg, plan.n_micro, lr=lr, sp=plan.sp, dp=plan.dp,
            stage=plan.pp, zero=zero, overlap_blocks=overlap_blocks,
        )
        n_dp = plan.dp_size

        def body(params, x, y):
            def one(carry, _):
                p, o = carry
                p, o, loss = step(p, o, x, y)
                return (p, o), loss

            opt0 = (local_zero_state(params, n_dp) if zero
                    else init_adam_state(params))
            (params, _), losses = _lax.scan(
                one, (params, opt0), None, length=steps
            )
            return params, losses[-1]

        pspec = param_spec_pp(cfg, plan.pp, plan.dp)
        dspec = plan.data_spec()
        return run_spmd(plan.mesh, body, (pspec, dspec, dspec),
                        (pspec, P()))
    if zero:
        from jax import lax as _lax

        from tpuscratch.models.zero import (
            local_zero_state,
            train_step_zero_fn,
        )

        step = train_step_zero_fn(cfg, lr=lr, accum_steps=accum_steps,
                                  overlap_blocks=overlap_blocks)
        n_dp = mesh.shape["dp"]

        def body(params, x, y):
            def one(carry, _):
                p, o = carry
                p, o, loss = step(p, o, x, y)
                return (p, o), loss

            (params, _), losses = _lax.scan(
                one, (params, local_zero_state(params, n_dp)), None,
                length=steps,
            )
            return params, losses[-1]

        pspec = _param_spec(cfg)
        dspec = (P("dp", "sp") if accum_steps == 1
                 else P(None, "dp", "sp"))
        return run_spmd(mesh, body, (pspec, dspec, dspec), (pspec, P()))
    if optimizer == "adam":
        step = train_step_adam_fn(cfg, lr=lr)

        def body(params, x, y):
            def one(carry, _):
                p, o = carry
                p, o, loss = step(p, o, x, y)
                return (p, o), loss

            (params, _), losses = lax.scan(
                one, (params, init_adam_state(params)), None, length=steps
            )
            return params, losses[-1]
    else:
        step = train_step_fn(cfg, lr=lr)

        def body(params, x, y):
            # params are the loop carry: every step reads the previous
            # step's update, so the scan cannot be collapsed or hoisted
            def one(p, _):
                p, loss = step(p, x, y)
                return p, loss

            params, losses = lax.scan(one, params, None, length=steps)
            return params, losses[-1]

    pspec = param_spec(cfg)
    return run_spmd(
        mesh,
        body,
        (pspec, P("dp", "sp"), P("dp", "sp")),
        (pspec, P()),
    )


def bench_train(
    mesh: Optional[Mesh] = None,
    cfg: Optional[TransformerConfig] = None,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    steps: Optional[int] = None,
    iters: int = 3,
    fence: str = "readback",
    seed: int = 0,
    optimizer: str = "sgd",
    zero: bool = False,
    accum_steps: int = 1,
    plan=None,
) -> BenchResult:
    """tokens/s of the composed train step; items = tokens processed.
    ``zero``/``accum_steps``: the ZeRO-sharded step (see
    :func:`train_throughput_program`) — with accumulation every scanned
    step consumes ``accum_steps`` microbatches, and the token count
    scales accordingly.  ``plan``: bench the plan-composed program (the
    same step path the trainer runs) — pipelined plans stack the layer
    params and stream ``plan.n_micro`` microbatches per step."""
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    if plan is not None:
        mesh = plan.mesh
    if mesh is None:
        mesh = make_mesh((1, 1), ("dp", "sp"))
    if cfg is None:
        cfg = (
            TransformerConfig(
                d_model=1024, n_heads=8, n_experts=4, d_ff=4096,
                n_layers=4, capacity_factor=2.0, attn_impl="pallas",
            )
            if on_tpu
            else TransformerConfig(
                d_model=32, n_heads=2, n_experts=2, d_ff=64, n_layers=1,
                capacity_factor=2.0,
            )
        )
    batch = batch if batch is not None else (8 if on_tpu else 2 * mesh.shape["dp"])
    seq = seq if seq is not None else (2048 if on_tpu else 8 * mesh.shape["sp"])
    steps = steps if steps is not None else (20 if on_tpu else 2)

    rng = np.random.default_rng(seed)
    shape = (batch, seq, cfg.d_model)
    if accum_steps > 1:
        shape = (accum_steps,) + shape
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    y = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    params = init_params(seed, cfg)
    pipelined = plan is not None and plan.pipelined
    if pipelined:
        from tpuscratch.models.transformer import stack_layers

        params = stack_layers(params)
    prog = train_throughput_program(mesh, cfg, steps, optimizer=optimizer,
                                    zero=zero, accum_steps=accum_steps,
                                    plan=plan)
    # correctness gate doubles as compile warmup: the loss must be finite
    out_params, loss = prog(params, x, y)
    if not np.isfinite(float(loss)):
        raise AssertionError(f"train step produced loss {float(loss)}")
    tokens = batch * seq * steps * accum_steps
    opt_tag = f"{'zero-' if zero else ''}{optimizer}" + (
        f"-accum{accum_steps}" if accum_steps > 1 else ""
    )
    if plan is not None:
        ov = plan.overlap_blocks
        opt_tag += (f"-pp{plan.pp_size}-M{plan.n_micro}" if pipelined
                    else "") + (f"-ov{ov}" if ov else "-serial")
    mesh_tag = "x".join(str(s) for s in mesh.devices.shape)
    return time_device(
        prog, params, x, y, iters=iters, warmup=1, fence=fence,
        name=(
            f"train d{cfg.d_model} ff{cfg.d_ff} L{cfg.n_layers} "
            f"e{cfg.n_experts} {cfg.compute_dtype} {opt_tag} b{batch} "
            f"s{seq} x{steps} on {mesh_tag} "
            f"({cfg.attn_impl})"
        ),
        items=tokens,
    )


@dataclasses.dataclass(frozen=True)
class ObsOverhead:
    """Per-step instrumentation cost against the train step's cost."""

    step_s: float        # best fenced seconds per compiled train step
    instr_s: float       # seconds per full per-step obs update

    @property
    def base_steps_per_s(self) -> float:
        return 1.0 / self.step_s

    @property
    def obs_steps_per_s(self) -> float:
        return 1.0 / (self.step_s + self.instr_s)

    @property
    def overhead(self) -> float:
        """Fractional slowdown from instrumentation (0.01 == 1%)."""
        return self.instr_s / (self.step_s + self.instr_s)

    def summary(self) -> str:
        return (
            f"obs overhead: step {self.step_s * 1e6:.1f} us + instr "
            f"{self.instr_s * 1e6:.2f} us/step = {100 * self.overhead:.3f}%"
            f" ({self.base_steps_per_s:.2f} -> "
            f"{self.obs_steps_per_s:.2f} steps/s)"
        )


def bench_obs_overhead(
    mesh: Optional[Mesh] = None,
    cfg: Optional[TransformerConfig] = None,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    steps: int = 50,
    iters: int = 3,
    seed: int = 0,
    sink_path: Optional[str] = None,
    emit_every: int = 10,
) -> ObsOverhead:
    """Measure what per-step metrics cost against the train step.

    The two terms are measured separately and combined — NOT as the
    difference of two end-to-end timings, which on sub-millisecond CPU
    steps is dominated by dispatch jitter and swings tens of percent
    either way: (a) the compiled step's best fenced time over ``iters``
    runs of ``steps`` steps; (b) the cost of the obs update as wired in
    the trainer — registry counter/gauge/histogram writes EVERY step,
    one flight-recorder span bracket plus one buffered sink event every
    ``emit_every`` steps (the trainer brackets and emits per save chunk;
    ``save_every`` defaults to 10) — amortized over thousands of
    repetitions.  The subsystem's budget for ``overhead`` is < 2% even
    against this sub-millisecond CPU step (the pessimistic denominator:
    a real chip config's step is milliseconds); since the trace layer
    landed, that budget covers the recorder too."""
    import tempfile
    import time

    from tpuscratch.models.transformer import train_step
    from tpuscratch.obs.metrics import MetricsRegistry
    from tpuscratch.obs.sink import Sink
    from tpuscratch.obs.trace import FlightRecorder
    from tpuscratch.runtime.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    if mesh is None:
        mesh = make_mesh((1, 1), ("dp", "sp"))
    if cfg is None:
        cfg = (
            TransformerConfig(
                d_model=1024, n_heads=8, n_experts=4, d_ff=4096,
                n_layers=4, capacity_factor=2.0, attn_impl="pallas",
            )
            if on_tpu
            else TransformerConfig(
                d_model=32, n_heads=2, n_experts=2, d_ff=64, n_layers=1,
                capacity_factor=2.0,
            )
        )
    batch = batch if batch is not None else 2 * mesh.shape["dp"]
    seq = seq if seq is not None else 8 * mesh.shape["sp"]
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((batch, seq, cfg.d_model)).astype(np.float32))
    params0 = init_params(seed, cfg)
    fn = train_step(mesh, cfg)
    jax.block_until_ready(fn(params0, x, y))  # compile outside the window

    step_best = float("inf")
    for _ in range(iters):
        params = params0
        t0 = time.perf_counter()
        for _ in range(steps):
            params, loss = fn(params, x, y)
        jax.block_until_ready(loss)
        step_best = min(step_best, (time.perf_counter() - t0) / steps)

    reps = 5000
    instr_best = float("inf")
    with tempfile.TemporaryDirectory(prefix="obs_overhead_") as tmp:
        path = sink_path or f"{tmp}/overhead.jsonl"
        with Sink(path, run={"bench": "obs-overhead"}) as sink:
            metrics = MetricsRegistry()
            rec = FlightRecorder()
            for _ in range(iters):
                t0 = time.perf_counter()
                sp = rec.open_span("bench/chunk")
                for i in range(reps):
                    metrics.counter("train/steps").inc()
                    metrics.gauge("train/last_step").set(i)
                    metrics.histogram("train/step_s").observe(step_best)
                    if i % emit_every == 0:
                        # chunk boundary, the trainer's shape: close the
                        # chunk bracket, emit, open the next
                        rec.close_span(sp)
                        sp = rec.open_span("bench/chunk")
                        sink.emit("train/chunk", step=i, loss=0.0,
                                  grad_norm=0.0, compiles=1)
                rec.close_span(sp)
                instr_best = min(
                    instr_best, (time.perf_counter() - t0) / reps
                )
    return ObsOverhead(step_s=step_best, instr_s=instr_best)


def _int_flag(argv, flag, default):
    if flag not in argv:
        return default
    try:
        return int(argv[argv.index(flag) + 1])
    except (IndexError, ValueError):
        raise SystemExit(f"usage: {flag} N")


def main() -> int:
    import sys

    argv = sys.argv[1:]
    cpu_devices = _int_flag(argv, "--cpu-devices", 0)
    if cpu_devices:
        from tpuscratch.runtime.hostenv import force_cpu_devices

        force_cpu_devices(cpu_devices)
    if "--obs-overhead" in argv:
        o = bench_obs_overhead()
        print(o.summary())
        return 0
    zero = "--zero" in argv
    optimizer = "adam" if (zero or "--adam" in argv) else "sgd"
    if "--pp" in argv or "--overlap" in argv or "--no-overlap" in argv:
        # the plan-composed ablation row, runnable standalone:
        #   train_bench --pp N [--dp D] [--micro M] --overlap|--no-overlap
        # pp > 1 (or micro > 1) scans the 3-axis GPipe + ZeRO step; the
        # overlap flag toggles the decomposed sync schedule (record.py
        # config 14 sweeps the same grid)
        from tpuscratch.parallel import ShardingPlan
        from tpuscratch.runtime.mesh import make_mesh

        pp = _int_flag(argv, "--pp", 1)
        dp = _int_flag(argv, "--dp", 1)
        micro = _int_flag(argv, "--micro", 2 if pp > 1 else 1)
        need = dp * pp
        if need > len(jax.devices()):
            raise SystemExit(
                f"--pp {pp} --dp {dp} needs {need} devices, have "
                f"{len(jax.devices())} (use --cpu-devices N)"
            )
        mesh = make_mesh((dp, 1, pp), ("dp", "sp", "pp"),
                         jax.devices()[:need])
        plan = ShardingPlan(mesh, pp="pp", n_micro=micro,
                            overlap="--no-overlap" not in argv)
        on_tpu = jax.default_backend() == "tpu"
        # layer count: the default depth rounded UP to a multiple of pp
        # (stages own equal layer slices)
        layers = -(-(4 if on_tpu else 2) // pp) * pp
        cfg = (
            TransformerConfig(
                d_model=1024, n_heads=8, n_experts=4, d_ff=4096,
                n_layers=layers, capacity_factor=2.0, attn_impl="pallas",
            )
            if on_tpu
            else TransformerConfig(
                d_model=32, n_heads=2, n_experts=2, d_ff=64,
                n_layers=layers, capacity_factor=2.0,
            )
        )
        r = bench_train(plan=plan, cfg=cfg, optimizer="adam", zero=True,
                        batch=max(2 * dp, dp * micro))
        print(f"{r.summary()} -> {r.items_per_s:.3e} tokens/s")
        return 0
    if "--accum" in argv:
        # --accum k1,k2,...: the deferred-sync sweep — one row per
        # accumulation depth, same optimizer/mesh, so the k-fold sync
        # cut shows up as the tokens/s delta down the column
        at = argv.index("--accum") + 1
        try:
            ks = [int(k) for k in argv[at].split(",")]
        except (IndexError, ValueError):
            print("usage: train_bench --accum K1[,K2,...]  (e.g. "
                  "--accum 1,2,4)", file=sys.stderr)
            return 2
        for k in ks:
            r = bench_train(zero=True, accum_steps=k, optimizer="adam")
            print(f"{r.summary()} -> {r.items_per_s:.3e} tokens/s")
        return 0
    r = bench_train(zero=zero, optimizer=optimizer)
    print(f"{r.summary()} -> {r.items_per_s:.3e} tokens/s")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
