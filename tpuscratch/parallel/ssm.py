"""Sequence-parallel linear recurrence — the SSM scan over a mesh axis.

The third long-context strategy next to ring attention and Ulysses: state
-space models advance ``h_t = a_t * h_{t-1} + b_t`` along the sequence,
and a sequence sharded across devices needs the recurrence carried over
shard boundaries. The classical distributed-prefix structure applies
(Blelloch scan at cluster scale): the pair ``(a, b)`` composes
associatively —

    (a1, b1) . (a2, b2) = (a1*a2, b2 + a2*b1)   [apply seg 1, then seg 2]

— so each device scans its shard locally (``lax.associative_scan`` on
the VPU), publishes its shard AGGREGATE (one (D,) pair, not the
sequence), and the cross-device exclusive scan of those n aggregates
costs one small all_gather + a static n-step combine, exactly the
prefix_sum pattern (comm.collectives.prefix_sum) lifted to a
non-commutative monoid. Communication is O(n * D) bytes total,
independent of sequence length — the same "exchange aggregates, not
payloads" shape as the reference's two-phase reduction
(/root/reference/mpicuda4.cu:157-185, per-block partials then a final
combine), here along time instead of across a vector.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _combine(left, right):
    """Compose two (A, B) recurrence segments, left first."""
    a1, b1 = left
    a2, b2 = right
    return a1 * a2, b2 + a2 * b1


def local_scan(a: jnp.ndarray, b: jnp.ndarray):
    """Inclusive scan of ``h_t = a_t h_{t-1} + b_t`` (h_{-1}=0) along
    axis 0, plus the shard aggregate (A, B) describing the whole shard as
    one segment."""
    cum_a, cum_b = lax.associative_scan(_combine, (a, b), axis=0)
    return (cum_a, cum_b), (cum_a[-1], cum_b[-1])


def ssm_scan(a: jnp.ndarray, b: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Distributed inclusive scan of the recurrence over ``axis_name``.

    ``a``, ``b`` are this device's (T/n, ...) shards of the per-step decay
    and input sequences; returns the (T/n, ...) shard of ``h``. SPMD: call
    inside shard_map over a 1D (sub)mesh axis.
    """
    if a.shape != b.shape:
        raise ValueError(f"a {a.shape} != b {b.shape}")
    (cum_a, cum_b), (agg_a, agg_b) = local_scan(a, b)

    # exclusive scan of the shard aggregates over the mesh axis: the
    # incoming state h_in each shard must continue from. Aggregates are
    # tiny (one element per feature), so one all_gather + a static
    # masked combine beats a log-tree of ppermutes at mesh sizes.
    me = lax.axis_index(axis_name)
    all_a = lax.all_gather(agg_a, axis_name)  # (n, ...) on every rank
    all_b = lax.all_gather(agg_b, axis_name)
    n = all_a.shape[0]
    carry = (jnp.ones_like(agg_a), jnp.zeros_like(agg_b))
    for i in range(n):  # static in the trace; masked for ranks >= me
        combined = _combine(carry, (all_a[i], all_b[i]))
        use = i < me
        carry = tuple(
            jnp.where(use, c_new, c_old)
            for c_new, c_old in zip(combined, carry)
        )
    _, h_in = carry

    # continue the local scan from h_in: h_t = cum_b_t + cum_a_t * h_in
    return cum_b + cum_a * h_in
