"""Distributed 2D FFT: local transforms + all_to_all transpose.

The classic pencil-decomposition FFT (FFTW-MPI / heFFTe shape): transform
the locally-contiguous axis, globally transpose so the other axis becomes
local, transform it. Under MPI the transpose is ``MPI_Alltoall`` of
manually packed blocks; here it is ONE ``lax.all_to_all`` with
``tiled=True`` — the packing/unpacking the reference does by hand with
derived datatypes (/root/reference/mpi-complex-types.cpp builds exactly
such strided block exchanges) dissolves into the split/concat axes of the
collective, and XLA lays the blocks out with no intermediate copies.

This is the third communication topology the framework ships, after the
neighbor ``ppermute`` (halo/) and the ring (parallel/ring.py): the
all-pairs personalized exchange — same collective the MoE layer uses for
token dispatch (parallel/expert.py), exercised here on a dense numeric
kernel with an exact oracle.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np
from jax import lax


def _transpose(x: jnp.ndarray, axis_name: str, *, to_pencil: bool) -> jnp.ndarray:
    """Tiled all_to_all global transpose: row block <-> column pencil.

    ``to_pencil`` scatters the local W axis and gathers everyone's row
    blocks (source order == row-block order, so rows arrive sorted); the
    reverse move restores the row-sharded layout.
    """
    split, concat = (1, 0) if to_pencil else (0, 1)
    return lax.all_to_all(
        x, axis_name, split_axis=split, concat_axis=concat, tiled=True
    )


def _transpose_pair(re, im, axis_name: str, *, to_pencil: bool):
    """:func:`_transpose` for an (re, im) pair as ONE collective.

    The transpose dominates the distributed FFT's wall clock, so the pair
    path stacks the planes and pays a single all_to_all (of twice the
    payload) instead of two latencies per transpose. Rank-generic: after
    the stack, ``to_pencil`` always splits the LAST axis and gathers the
    leading grid axis (axis 1), whatever the rank — the same helper
    serves the 2D (H/n, W) and 3D (Z/n, X, Y) layouts.
    """
    z = jnp.stack([re, im])
    last = z.ndim - 1
    split, concat = (last, 1) if to_pencil else (1, last)
    z = lax.all_to_all(
        z, axis_name, split_axis=split, concat_axis=concat, tiled=True
    )
    return z[0], z[1]


def fft2_sharded(
    local: jnp.ndarray,
    axis_name: str,
    *,
    inverse: bool = False,
    restore_layout: bool = True,
) -> jnp.ndarray:
    """2D (i)FFT of a row-sharded grid, SPMD over ``axis_name``.

    ``local`` is this device's (H/n, W) row block of the global (H, W)
    grid, real or complex. Returns the same row-block layout when
    ``restore_layout`` (one extra all_to_all); otherwise the transposed
    pencil layout — an (H, W/n) column block — saving the transpose when
    the caller's next op is happy with it (e.g. a spectral multiply that
    knows its coordinates, solvers/spectral.py).
    """
    f = jnp.fft.ifft if inverse else jnp.fft.fft
    y = f(jnp.asarray(local, jnp.complex64), axis=1)
    y = _transpose(y, axis_name, to_pencil=True)
    y = f(y, axis=0)
    if restore_layout:
        y = _transpose(y, axis_name, to_pencil=False)
    return y


def ifft2_sharded(
    local: jnp.ndarray, axis_name: str, *, restore_layout: bool = True
) -> jnp.ndarray:
    """Inverse of :func:`fft2_sharded` (separable, so axis order is free)."""
    return fft2_sharded(
        local, axis_name, inverse=True, restore_layout=restore_layout
    )


# ---------------------------------------------------------------------------
# Matmul-form DFT on (real, imag) float32 pairs — the MXU path.
#
# Some TPU runtimes (this repo's axon tunnel among them) have no complex
# dtype at all: complex64 fails device transfer AND compilation with
# UNIMPLEMENTED. The TPU-native answer is not emulation of the radix-2
# butterfly — scalar-heavy, MXU-hostile — but the DFT as two dense
# matmuls per axis on separate real/imag planes: O(N) more FLOPs than an
# FFT, and for the N the MXU chews through at hundreds of TFLOP/s the
# matmul form wins on wall clock anyway for moderate grids. Forward
# matrix F[k,j] = exp(-2*pi*i*k*j/N) = C - i*S; inverse (C + i*S)/N.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _dft_tables(n: int) -> tuple[np.ndarray, np.ndarray]:
    """(cos, sin) of the n-point DFT angle matrix, f32 trace constants."""
    k = np.arange(n, dtype=np.float64)
    ang = 2.0 * np.pi * np.outer(k, k) / n
    return np.cos(ang).astype(np.float32), np.sin(ang).astype(np.float32)


def _dft_axis(re, im, axis: int, inverse: bool):
    """Transform one axis of the (re, im) pair by dense DFT matmul.

    precision=HIGHEST is load-bearing: the TPU default lowers f32 matmul
    inputs to bf16 passes, and with O(N) accumulation per DFT coefficient
    that costs ~1e-2 relative error at N=512 (measured: a Poisson solve
    residual of 1.0 instead of 1e-4). HIGHEST selects the full-f32 MXU
    emulation — more passes, still a fraction of the all_to_all time.
    """
    n = re.shape[axis]
    c, s = (jnp.asarray(t) for t in _dft_tables(n))
    hi = jnp.matmul  # bound with full precision below
    mm = (
        (lambda x, m: hi(x, m, precision=lax.Precision.HIGHEST))
        if axis == 1
        else (lambda x, m: hi(m, x, precision=lax.Precision.HIGHEST))
    )
    if inverse:  # (xr + i xi)(C + iS)/n
        yr = (mm(re, c) - mm(im, s)) / n
        yi = (mm(im, c) + mm(re, s)) / n
    else:  # (xr + i xi)(C - iS)
        yr = mm(re, c) + mm(im, s)
        yi = mm(im, c) - mm(re, s)
    return yr, yi


# ---------------------------------------------------------------------------
# Four-step (Cooley-Tukey N = N1*N2) matmul FFT on pair planes.
#
# The O(N^2) dense DFT above is MXU-roofline-bound but pays N MACs per
# element; splitting N into N1*N2 pays N1+N2 per element — still every
# FLOP a matmul (sub-DFT matrices of size N1 and N2, batched over the
# other factor), plus one elementwise twiddle plane. The decimation:
# x[j1*N2 + j2] -> B[k1,j2] = F_N1 @ x  (contract j1)
#              -> C = B * W_N^(k1*j2)   (twiddle)
#              -> X[k1 + N1*k2] = C @ F_N2 (contract j2), read out k2-major.
# This is radix-sqrt(N) Cooley-Tukey — the classical "four-step" NUMA/
# out-of-core FFT — which maps onto the MXU where a radix-2 Stockham's
# butterflies would be VPU-bound gather/scatter. One split is enough for
# the sizes a 2D grid axis reaches (N1,N2 <= 128 at N=16384). Reference
# lineage: this is the transform layer the reference's complex-typed
# strided exchanges exist to feed
# (/root/reference/mpi-complex-types.cpp:35-88); the reference ships the
# datatype machinery, never the transform.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _split(n: int):
    """(n1, n2) with n1*n2 == n, or None when n is prime/too small.

    Preference is NOT the FLOP-minimal balanced split: n2 = 128 makes
    step B's contraction exactly one MXU pass deep, which beats the
    extra n1+n2 arithmetic — chip-raced at 1024 ((8,128) 17% over the
    balanced (32,32) despite 2.1x the MACs) and 4096 ((32,128) 9% over
    (64,64)). The 1024 floor below is this rule's OWN measured
    threshold (under it, the n1 side's tiny sub-DFT loses more than
    lane fill returns) — deliberately independent of FOUR_STEP_MIN,
    which gates auto-DISPATCH, so retuning one never silently degrades
    the other."""
    if n >= 1024 and n % 128 == 0:
        return (n // 128, 128)
    return _balanced_factor(n)


@functools.lru_cache(maxsize=None)
def _twiddle_tables(n1: int, n2: int, n: int):
    """(cos, sin) of W_n^(k1*j2), the four-step twiddle plane."""
    ang = 2.0 * np.pi * np.outer(
        np.arange(n1, dtype=np.float64), np.arange(n2, dtype=np.float64)
    ) / n
    return np.cos(ang).astype(np.float32), np.sin(ang).astype(np.float32)


def _cplx_einsum(spec: str, c, s, xr, xi, inverse: bool):
    """Complex einsum (C -+ iS) . (xr + i xi), the constant operand first
    in ``spec``. Forward uses C - iS, inverse C + iS; scaling is the
    caller's job."""
    ee = functools.partial(jnp.einsum, precision=lax.Precision.HIGHEST)
    sgn = -1.0 if inverse else 1.0
    yr = ee(spec, c, xr) + sgn * ee(spec, s, xi)
    yi = ee(spec, c, xi) - sgn * ee(spec, s, xr)
    return yr, yi


#: n1-side sub-DFT lengths at or above this are themselves four-step
#: decomposed (the "eight-step" recursion).  0 = DISABLED, which the
#: chip race decided (BASELINE row 8, v5e): the recursion LOSES at every
#: raced size — 4096² 19.2 vs 12.65 ms/round, 8192² 84.6 vs 58.3,
#: 16384² 319 vs 226 — because the dense F_n1 contraction is one
#: 32/64/128-deep MXU pass while the m1+m2 sub-contractions are 8/16
#: deep and underfill the array; the MAC savings never pay for the fill
#: loss.  Same physics as the n2=128 split rule beating the balanced
#: split.  The path stays correct and force-enabled in tests.
EIGHT_STEP_MIN = 0


def _balanced_factor(n: int):
    """(d, n // d) with d the largest divisor <= sqrt(n), or None for a
    prime/too-small n (shared by _split's sub-1024 rule and the
    eight-step recursion)."""
    best = None
    for d in range(2, int(n**0.5) + 1):
        if n % d == 0:
            best = d
    return (best, n // best) if best else None


def _sub_split(n1: int, min_n: int | None = None):
    """Balanced (m1, m2) factoring of the n1 side for the eight-step
    recursion, or None when n1 is below the threshold (default
    :data:`EIGHT_STEP_MIN`; 0 means never), or prime."""
    m = EIGHT_STEP_MIN if min_n is None else min_n
    if not m or n1 < m:
        return None
    return _balanced_factor(n1)


def _sub_dft_n1(xr, xi, n1: int, inverse: bool, axis: int):
    """DFT of length n1 over the n1 axis of the reshaped four-step
    tensor — (h, n1, n2) for axis==1, (n1, n2, w) for axis==0.  Dense
    F_n1 contraction, or its own four-step split when n1 is composite
    and >= EIGHT_STEP_MIN (the eight-step recursion: same decimation,
    one level down, unscaled — the outer caller owns the 1/n)."""
    sub = _sub_split(n1)
    if sub is None:
        c1, s1 = (jnp.asarray(t) for t in _dft_tables(n1))
        spec = "ab,hbw->haw" if axis == 1 else "ab,bcw->acw"
        return _cplx_einsum(spec, c1, s1, xr, xi, inverse)
    m1, m2 = sub
    cm1, sm1 = (jnp.asarray(t) for t in _dft_tables(m1))
    cm2, sm2 = (jnp.asarray(t) for t in _dft_tables(m2))
    tc, ts = (jnp.asarray(t) for t in _twiddle_tables(m1, m2, n1))
    sgn = -1.0 if inverse else 1.0
    if axis == 1:
        h, _, n2 = xr.shape
        ur = xr.reshape(h, m1, m2, n2)
        ui = xi.reshape(h, m1, m2, n2)
        tr, ti = _cplx_einsum("pu,huvw->hpvw", cm1, sm1, ur, ui, inverse)
        tw_r = tr * tc[:, :, None] + sgn * ti * ts[:, :, None]
        tw_i = ti * tc[:, :, None] - sgn * tr * ts[:, :, None]
        # k1 = p + m1*q: emitting (q, p) C-order seats the digits
        br, bi = _cplx_einsum("qv,hpvw->hqpw", cm2, sm2, tw_r, tw_i,
                              inverse)
        return br.reshape(h, n1, n2), bi.reshape(h, n1, n2)
    _, n2, w = xr.shape
    ur = xr.reshape(m1, m2, n2, w)
    ui = xi.reshape(m1, m2, n2, w)
    tr, ti = _cplx_einsum("pu,uvcw->pvcw", cm1, sm1, ur, ui, inverse)
    tw_r = tr * tc[:, :, None, None] + sgn * ti * ts[:, :, None, None]
    tw_i = ti * tc[:, :, None, None] - sgn * tr * ts[:, :, None, None]
    br, bi = _cplx_einsum("qv,pvcw->qpcw", cm2, sm2, tw_r, tw_i, inverse)
    return br.reshape(n1, n2, w), bi.reshape(n1, n2, w)


def _four_step_axis(re, im, axis: int, inverse: bool):
    """Transform one axis of the (re, im) pair by the four-step matmul
    FFT. Requires a composite axis length (see :func:`_split`).  The
    n1-side sub-DFT recurses one level (eight-step) when
    :func:`_sub_split` allows."""
    n = re.shape[axis]
    n1, n2 = _split(n)
    c2, s2 = (jnp.asarray(t) for t in _dft_tables(n2))
    tc, ts = (jnp.asarray(t) for t in _twiddle_tables(n1, n2, n))
    sgn = -1.0 if inverse else 1.0

    if axis == 1:
        h = re.shape[0]
        xr = re.reshape(h, n1, n2)
        xi = im.reshape(h, n1, n2)
        br, bi = _sub_dft_n1(xr, xi, n1, inverse, axis)
        # twiddle: (br + i bi) * (tc -+ i ts), broadcast over rows
        cr = br * tc + sgn * bi * ts
        ci = bi * tc - sgn * br * ts
        yr, yi = _cplx_einsum("jm,haj->hma", c2, s2, cr, ci, inverse)
        yr = yr.reshape(h, n)
        yi = yi.reshape(h, n)
    else:
        w = re.shape[1]
        xr = re.reshape(n1, n2, w)
        xi = im.reshape(n1, n2, w)
        br, bi = _sub_dft_n1(xr, xi, n1, inverse, axis)
        cr = br * tc[:, :, None] + sgn * bi * ts[:, :, None]
        ci = bi * tc[:, :, None] - sgn * br * ts[:, :, None]
        yr, yi = _cplx_einsum("jm,ajw->maw", c2, s2, cr, ci, inverse)
        yr = yr.reshape(n, w)
        yi = yi.reshape(n, w)
    if inverse:
        yr = yr / n
        yi = yi / n
    return yr, yi


#: Axis lengths at or above this use the four-step path under
#: method="auto" (chip-raced crossover, see BASELINE.md row 8).
FOUR_STEP_MIN = 1024


def resolve_method(n: int, method: str) -> str:
    """The single source of the method-dispatch rule: 'auto' becomes
    'four-step' for composite lengths at/above :data:`FOUR_STEP_MIN`,
    else 'direct'; an explicit 'four-step' on a prime/too-small length is
    a ValueError (not a crash inside tracing). Bench FLOP accounting
    (bench/fft_bench.pair_fft_flops) resolves through here too, so it
    can never diverge from what actually runs."""
    if method == "auto":
        return (
            "four-step"
            if n >= FOUR_STEP_MIN and _split(n) is not None
            else "direct"
        )
    if method == "four-step" and _split(n) is None:
        raise ValueError(
            f"four-step needs a composite axis length >= 4, got {n}"
        )
    if method not in ("four-step", "direct"):
        raise ValueError(f"unknown pair-FFT method {method!r}")
    return method


def _pair_axis(re, im, axis: int, inverse: bool, method: str):
    method = resolve_method(re.shape[axis], method)
    if method == "four-step":
        return _four_step_axis(re, im, axis, inverse)
    return _dft_axis(re, im, axis, inverse)


def fft2_sharded_pair(
    re: jnp.ndarray,
    im: jnp.ndarray,
    axis_name: str,
    *,
    inverse: bool = False,
    restore_layout: bool = True,
    method: str = "auto",
):
    """:func:`fft2_sharded` on (real, imag) f32 planes — no complex dtype.

    Same pencil decomposition and all_to_all transposes, with each local
    transform on the MXU: ``method='direct'`` is the dense O(N) MACs/elt
    DFT matmul pair, ``'four-step'`` the O(sqrt(N)) MACs/elt split-radix
    decomposition (needs a composite axis length), ``'auto'`` (default)
    picks four-step from :data:`FOUR_STEP_MIN` up. Returns the (re, im)
    pair in the same layout contract as :func:`fft2_sharded`.
    """
    re, im = _pair_axis(re, im, 1, inverse, method)
    re, im = _transpose_pair(re, im, axis_name, to_pencil=True)
    re, im = _pair_axis(re, im, 0, inverse, method)
    if restore_layout:
        re, im = _transpose_pair(re, im, axis_name, to_pencil=False)
    return re, im


def ifft2_from_pencil(pencil: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Inverse 2D FFT starting from the transposed pencil layout.

    Takes the (H, W/n) column block :func:`fft2_sharded` returns with
    ``restore_layout=False`` and comes back to the (H/n, W) row block —
    the forward path run backwards, saving one transpose per round trip.
    """
    y = jnp.fft.ifft(pencil, axis=0)
    y = _transpose(y, axis_name, to_pencil=False)
    return jnp.fft.ifft(y, axis=1)


def ifft2_from_pencil_pair(re, im, axis_name: str, method: str = "auto"):
    """Pair-plane (MXU matmul) version of :func:`ifft2_from_pencil`."""
    re, im = _pair_axis(re, im, 0, True, method)
    re, im = _transpose_pair(re, im, axis_name, to_pencil=False)
    return _pair_axis(re, im, 1, True, method)


# ---------------------------------------------------------------------------
# 3D: the same pencil decomposition one dimension up. Local block is the
# z-shard (Z/n, Y, X); X and Y transform locally (last-axis reshape), ONE
# all_to_all repartitions z, and Z transforms locally in the pencil
# layout (X, Y/n, Z). Complex path and (re, im) pair path mirror 2D.
# ---------------------------------------------------------------------------


def _pair_last(re, im, inverse: bool, method: str):
    """Transform the LAST axis of an arbitrary-rank pair by flattening
    the leading dims — reuses the whole 2D machinery (incl. four-step)."""
    shape = re.shape
    yr, yi = _pair_axis(
        re.reshape(-1, shape[-1]), im.reshape(-1, shape[-1]),
        1, inverse, method,
    )
    return yr.reshape(shape), yi.reshape(shape)


def fft3_sharded_pair(
    re: jnp.ndarray,
    im: jnp.ndarray,
    axis_name: str,
    *,
    inverse: bool = False,
    restore_layout: bool = True,
    method: str = "auto",
):
    """3D (i)FFT of a z-sharded (Z/n, Y, X) pair, SPMD over ``axis_name``.

    Complex-free MXU path (see :func:`fft2_sharded_pair`). Returns the
    same (Z/n, Y, X) layout when ``restore_layout``; otherwise the
    transposed pencil — an (X, Y/n, Z) block whose device-local
    coordinates are (kx = all, ky = shard, kz = all), which is what a
    spectral multiply wants (solvers.spectral.periodic_poisson3d_fft).
    """
    re, im = _pair_last(re, im, inverse, method)                    # X
    re, im = jnp.swapaxes(re, 1, 2), jnp.swapaxes(im, 1, 2)         # (Z/n, X, Y)
    re, im = _pair_last(re, im, inverse, method)                    # Y
    re, im = _transpose_pair(re, im, axis_name, to_pencil=True)     # (Z, X, Y/n)
    re = jnp.transpose(re, (1, 2, 0))
    im = jnp.transpose(im, (1, 2, 0))                               # (X, Y/n, Z)
    re, im = _pair_last(re, im, inverse, method)                    # Z
    if restore_layout:
        re, im = ifft3_restore_pair(re, im, axis_name)
    return re, im


def ifft3_restore_pair(re, im, axis_name: str):
    """Bring an (X, Y/n, Z) pencil pair back to the (Z/n, Y, X) row
    layout (no transform — pure layout moves, shared by forward-restore
    and the inverse path)."""
    re = jnp.transpose(re, (2, 0, 1))
    im = jnp.transpose(im, (2, 0, 1))                               # (Z, X, Y/n)
    re, im = _transpose_pair(re, im, axis_name, to_pencil=False)    # (Z/n, X, Y)
    return jnp.swapaxes(re, 1, 2), jnp.swapaxes(im, 1, 2)


def ifft3_from_pencil_pair(re, im, axis_name: str, method: str = "auto"):
    """Inverse 3D FFT starting from the (X, Y/n, Z) pencil — the forward
    path run backwards, saving one all_to_all per round trip."""
    re, im = _pair_last(re, im, True, method)                       # Z
    re = jnp.transpose(re, (2, 0, 1))
    im = jnp.transpose(im, (2, 0, 1))                               # (Z, X, Y/n)
    re, im = _transpose_pair(re, im, axis_name, to_pencil=False)    # (Z/n, X, Y)
    re, im = _pair_last(re, im, True, method)                       # Y
    re, im = jnp.swapaxes(re, 1, 2), jnp.swapaxes(im, 1, 2)         # (Z/n, Y, X)
    return _pair_last(re, im, True, method)                         # X


def ifft3_from_pencil(pencil: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Complex-dtype inverse 3D FFT from the (X, Y/n, Z) pencil — the
    `jnp.fft` sibling of :func:`ifft3_from_pencil_pair`."""
    z = jnp.fft.ifft(pencil, axis=2)                                # Z
    z = jnp.transpose(z, (2, 0, 1))                                 # (Z, X, Y/n)
    z = lax.all_to_all(z, axis_name, split_axis=0, concat_axis=2, tiled=True)
    z = jnp.fft.ifft(z, axis=2)                                     # Y
    z = jnp.swapaxes(z, 1, 2)                                       # (Z/n, Y, X)
    return jnp.fft.ifft(z, axis=2)                                  # X


def fft3_sharded(
    local: jnp.ndarray,
    axis_name: str,
    *,
    inverse: bool = False,
    restore_layout: bool = True,
) -> jnp.ndarray:
    """Complex-dtype 3D (i)FFT of a z-sharded (Z/n, Y, X) block — the
    `jnp.fft` sibling of :func:`fft3_sharded_pair`, same layout contract."""
    f = jnp.fft.ifft if inverse else jnp.fft.fft
    y = f(jnp.asarray(local, jnp.complex64), axis=2)                # X
    y = jnp.swapaxes(y, 1, 2)                                       # (Z/n, X, Y)
    y = f(y, axis=2)                                                # Y
    z = lax.all_to_all(y, axis_name, split_axis=2, concat_axis=0, tiled=True)
    z = jnp.transpose(z, (1, 2, 0))                                 # (X, Y/n, Z)
    z = f(z, axis=2)                                                # Z
    if restore_layout:
        z = jnp.transpose(z, (2, 0, 1))                             # (Z, X, Y/n)
        z = lax.all_to_all(z, axis_name, split_axis=0, concat_axis=2, tiled=True)
        z = jnp.swapaxes(z, 1, 2)                                   # (Z/n, Y, X)
    return z


def complex_supported() -> bool:
    """Whether the default backend can run complex64 at all.

    Deliberately NOT a runtime probe: on the axon tunnel a failed complex
    ``device_put`` leaves the PJRT client wedged — every subsequent
    transfer in the process then fails UNIMPLEMENTED (observed), so
    probing would break the very backend it tests. Classification is
    static — the tunnel identifies itself in ``platform_version`` — with
    ``TPUSCRATCH_COMPLEX=0/1`` as the override, read on every call so
    tests and late configuration can flip it.
    """
    import os

    override = os.environ.get("TPUSCRATCH_COMPLEX")
    if override is not None:
        # case/spelling-tolerant: "False", "NO", "off" must all disable —
        # a truthy-by-accident override would wedge the axon client
        return override.strip().lower() not in ("0", "false", "no", "off", "")
    return _platform_has_complex()


@functools.lru_cache(maxsize=1)
def _platform_has_complex() -> bool:
    import jax

    version = getattr(jax.devices()[0].client, "platform_version", "")
    return "axon" not in version
