"""Ring attention: exact attention over a sequence sharded around a ring.

Each rank holds one block of the sequence: Q stays put, the (K, V) block
rotates around the mesh axis; every hop combines the incoming KV block
into a running online-softmax state (max, normalizer, weighted sum), so
the full (seq x seq) score matrix never materializes and per-chip memory
stays O(seq/n). The rotation is the framework's ring primitive
(parallel.ring.ring_scan -> lax.ppermute over ICI); the accumulation is
the blockwise-reduction pattern of the reference's partial-sums kernels
(SURVEY.md §2.7 maps both skeletons).

Causal masking works on global positions: rank r's Q block covers rows
[r*S, (r+1)*S); the block arriving at hop i originated on rank
(r - i) mod n and covers the matching K rows. Fully-masked hops contribute
exp(-inf)=0 via the running max, so no special-casing per hop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tpuscratch.comm.p2p import ring_perm
from tpuscratch.parallel.ring import ring_scan
from tpuscratch.parallel.scores import NEG_INF, masked_scores


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    causal: bool = False,
    impl: str = "xla",
) -> jax.Array:
    """Exact multi-head attention, sequence sharded over ``axis``.

    q, k, v: (S, H, D) — this rank's block of a global (n*S, H, D)
    sequence. Returns this rank's (S, H, D) block of the attention output,
    bit-equivalent (up to fp assoc.) to attention on the gathered sequence.
    Call inside shard_map with the sequence dimension sharded over
    ``axis``.

    ``impl``: 'xla' computes each hop's block scores densely; 'pallas'
    runs the flash-attention kernel (ops.attention) per hop with
    ``return_state=True`` and softmax-merges the per-hop (out, m, l) —
    same math, MXU-scheduled, and the per-hop (H, S, S) score block never
    materializes (the long-block regime). The pallas path is trainable:
    its custom VJP runs the standard ring backward — a second KV
    rotation where each hop applies the flash backward kernels against
    the GLOBAL log-sum-exp and the visiting block accumulates its dk/dv
    on the way home (the k/v blocks themselves stop one hop early).
    """
    if q.ndim != 3 or q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"expected equal (S,H,D) blocks, got {q.shape}/{k.shape}/{v.shape}")
    if impl == "pallas":
        return _ring_flash(q, k, v, axis, causal)
    if impl != "xla":
        raise ValueError(f"unknown ring attention impl {impl!r}")
    S, H, D = q.shape
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    q32 = q.astype(jnp.float32)

    rows = me * S + jnp.arange(S)  # global Q positions

    # online-softmax state: running max m, normalizer l, weighted sum o
    init = (
        jnp.full((H, S), NEG_INF, dtype=jnp.float32),
        jnp.zeros((H, S), dtype=jnp.float32),
        jnp.zeros((S, H, D), dtype=jnp.float32),
    )

    def combine_xla(state, kv_block, hop):
        m, l, o = state
        kb, vb = kv_block
        src = (me - hop) % n  # origin rank of this KV block
        cols = src * S + jnp.arange(S)  # global K positions
        if causal:
            mask = rows[:, None] >= cols[None, :]
        else:
            mask = jnp.ones((S, S), dtype=bool)
        s = masked_scores(q32, kb, mask)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, :, None])          # (H, S, T)
        # guard: when every score so far is masked, s - m_new == 0 for
        # masked entries and exp would count them; zero them explicitly so
        # correctness doesn't depend on the self-block arriving first
        p = jnp.where(s > NEG_INF * 0.5, p, 0.0)
        corr = jnp.exp(m - m_new)                   # (H, S)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("hst,thd->shd", p, vb.astype(jnp.float32))
        o = o * corr.T[:, :, None] + pv
        return (m_new, l, o)

    # return_payload=False: the KV pair is discarded after the last hop, so
    # the homeward rotation (one extra 2*S*H*D transfer) is skipped
    (m, l, o), _ = ring_scan(
        combine_xla, init, (k, v), axis, return_payload=False
    )
    out = o / l.T[:, :, None]
    return out.astype(q.dtype)


def _ring_flash_forward(q, k, v, axis, causal):
    """Flash-kernel hops + exact softmax-merge. Returns
    (out (S,H,D), m (H,S), l (H,S))."""
    from tpuscratch.ops.attention import flash_attention

    S, H, D = q.shape
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    init = (
        jnp.full((H, S), NEG_INF, dtype=jnp.float32),
        jnp.zeros((H, S), dtype=jnp.float32),
        jnp.zeros((S, H, D), dtype=jnp.float32),
    )

    def combine(state, kv_block, hop):
        m, l, o = state
        kb, vb = kv_block
        src = (me - hop) % n
        # per-hop flash over this KV block, in global coordinates;
        # acc_i is the hop's raw fp32 weighted sum (no normalization)
        acc_i, m_i, l_i = flash_attention(
            q, kb, vb, causal=causal,
            q_offset=me * S, kv_offset=src * S, return_state=True,
        )
        # exact softmax-merge: rescale both sides to the new running max
        m_new = jnp.maximum(m, m_i)
        c_old = jnp.exp(m - m_new)                   # (H, S)
        c_new = jnp.exp(m_i - m_new)
        l_new = l * c_old + l_i * c_new
        o_new = o * c_old.T[:, :, None] + acc_i * c_new.T[:, :, None]
        return (m_new, l_new, o_new)

    (m, l, o), _ = ring_scan(combine, init, (k, v), axis, return_payload=False)
    l_safe = jnp.maximum(l, 1e-30)
    out = (o / l_safe.T[:, :, None]).astype(q.dtype)
    return out, m, l_safe


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash(q, k, v, axis, causal):
    return _ring_flash_forward(q, k, v, axis, causal)[0]


def _ring_flash_fwd(q, k, v, axis, causal):
    out, m, l = _ring_flash_forward(q, k, v, axis, causal)
    lse = m + jnp.log(l)  # global log-sum-exp rows, (H, S)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis, causal, res, do):
    """The standard ring-attention backward: rotate the KV blocks with
    their gradient accumulators; every hop runs the flash backward
    kernels against the saved GLOBAL lse, adds dq locally, and
    accumulates dk/dv onto the visiting block. dk/dv make the full n
    hops home; the spent k/v blocks stop one hop early (the same
    homeward transfer the forward's return_payload=False skips)."""
    from tpuscratch.ops.attention import _flash_bwd_call, _pick_block

    q, k, v, out, lse = res
    S, H, D = q.shape
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    bq = _pick_block(S, 512, "S")
    bk = _pick_block(S, 1024, "T")
    qh = jnp.swapaxes(q, 0, 1)
    doh = jnp.swapaxes(do.astype(jnp.float32), 0, 1)
    delta = jnp.sum(
        doh * jnp.swapaxes(out, 0, 1).astype(jnp.float32), axis=-1
    )  # (H, S)

    # rotate head-major (ppermute is layout-agnostic): one transpose per
    # tensor total instead of one per hop, and fp32 gradient partials
    # throughout — a single cast at the end, not one per contribution
    perm = ring_perm(n, 1, periodic=True)

    def contrib(dq_acc, kbh, vbh, dkh, dvh, hop):
        src = (me - hop) % n
        dq_c, dk_c, dv_c = _flash_bwd_call(
            qh, kbh, vbh, doh, lse, delta,
            jnp.asarray(me * S, jnp.int32).reshape(1),
            jnp.asarray(src * S, jnp.int32).reshape(1),
            causal, bq, bk, out_dtype=jnp.float32,
        )
        return dq_acc + dq_c, dkh + dk_c, dvh + dv_c

    def hop(state, i):
        dq_acc, kbh, vbh, dkh, dvh = state
        dq_acc, dkh, dvh = contrib(dq_acc, kbh, vbh, dkh, dvh, i)
        kbh, vbh, dkh, dvh = jax.tree.map(
            lambda b: lax.ppermute(b, axis, perm), (kbh, vbh, dkh, dvh)
        )
        return (dq_acc, kbh, vbh, dkh, dvh), ()

    zero_h = jnp.zeros((H, S, D), jnp.float32)
    state = (zero_h, jnp.swapaxes(k, 0, 1), jnp.swapaxes(v, 0, 1),
             zero_h, zero_h)
    if n > 1:
        state, _ = lax.scan(hop, state, jnp.arange(n - 1))
    dq, kbh, vbh, dkh, dvh = state
    # final combine, then send ONLY dk/dv home — the k/v blocks are
    # spent, so their homeward rotation (the 2*S*H*D transfer the
    # forward's return_payload=False also skips) is dropped
    dq, dkh, dvh = contrib(dq, kbh, vbh, dkh, dvh, jnp.asarray(n - 1))
    dkh, dvh = jax.tree.map(
        lambda b: lax.ppermute(b, axis, perm), (dkh, dvh)
    )
    return (
        jnp.swapaxes(dq, 0, 1).astype(q.dtype),
        jnp.swapaxes(dkh, 0, 1).astype(k.dtype),
        jnp.swapaxes(dvh, 0, 1).astype(v.dtype),
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)
