"""One ShardingPlan for dp x sp x pp (x ep): the pytree -> mesh-axes layer.

The reference's flagship structural idea is a single cartesian process
topology that every kernel composes against (mpi10.cpp builds ONE
``MPI_Cart_create`` communicator; stencil2D.h addresses every exchange
through it).  This module is that layer for the training stack: a
**ShardingPlan** names the mesh axes once — data parallel (``dp``),
sequence parallel (``sp``), pipeline stages (``pp``), experts (``ep``,
riding the dp axis in the supported EP-groups==DP-groups layout) — and
the step builders (``models.trainer.train``, ``models.zero``) consume
the plan instead of hardcoding a dp x sp mesh.  ``train(plan=...)``
then composes dp x sp x pp (x ep) with ZeRO-sharded optimizer moments
in one compiled step.

Axes are validated against the live mesh AT CONSTRUCTION: a plan naming
an axis the mesh does not have fails here with the axis named, instead
of surfacing later as an opaque ``shard_map`` binding error three
layers down.

The plan also carries the comm/compute **overlap** policy for the
ZeRO sync legs: ``overlap=True`` decomposes the one flat gradient
reduce-scatter and the one trailing param all-gather into
``prefetch_blocks`` independent per-block chains (block i's all-gather
in flight while block i+1's update computes — the ``parallel.ring``
hop-overlap idiom applied to the sync legs; MegaScale NSDI'24 /
Wang et al. ASPLOS'23's decomposed-collective pattern).  Total wire
bytes are unchanged — only the collective count/schedule moves — which
``obs.ledger`` asserts statically (tests/test_plan.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ShardingPlan"]

#: the logical axis roles a plan can map onto mesh axes
_LOGICAL = ("dp", "sp", "pp", "ep")


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """pytree-path -> mesh-axes mapping with named axes dp/sp/pp(/ep).

    ``dp``/``sp``/``pp``/``ep`` are MESH AXIS NAMES (strings); ``pp``
    and ``ep`` are optional.  ``ep`` defaults to the dp axis — the
    EP-groups==DP-groups layout the MoE dispatch is built on (different
    dp ranks hold different experts).  ``n_micro`` is the GPipe
    microbatch count per step when a pp axis is in play; ``overlap``
    turns the blockwise sync decomposition on (``prefetch_blocks``
    chains), off reproduces the serial RS -> update -> AG schedule.

    The plan is the unit the checkpoint layer records: its
    :meth:`describe` dict joins the resume identity, and a
    mismatched-plan resume raises the same ``CommError`` contract as a
    mismatched-|dp| ZeRO restore.
    """

    mesh: Mesh
    dp: str = "dp"
    sp: str = "sp"
    pp: Optional[str] = None
    ep: Optional[str] = None
    n_micro: int = 1
    overlap: bool = True
    prefetch_blocks: int = 4

    def __post_init__(self):
        named = {"dp": self.dp, "sp": self.sp, "pp": self.pp,
                 "ep": self.ep}
        axis_names = tuple(self.mesh.axis_names)
        for logical in _LOGICAL:
            name = named[logical]
            if name is None:
                continue
            if name not in axis_names:
                raise ValueError(
                    f"ShardingPlan {logical}={name!r} is not an axis of "
                    f"the mesh (axes: {axis_names}) — the plan validates "
                    f"against the live mesh at construction so this "
                    f"surfaces here, not as a shard_map binding failure"
                )
        if self.n_micro < 1:
            raise ValueError(f"n_micro must be >= 1, got {self.n_micro}")
        if self.pp is None and self.n_micro != 1:
            raise ValueError(
                "n_micro > 1 is the GPipe microbatch count: it needs a "
                "pp axis (pass pp=<stage axis name>)"
            )
        if self.prefetch_blocks < 1:
            raise ValueError(
                f"prefetch_blocks must be >= 1, got {self.prefetch_blocks}"
            )

    # -- axis sizes ----------------------------------------------------
    @property
    def ep_axis(self) -> str:
        """The mesh axis carrying experts (the dp axis unless a distinct
        ep axis was named)."""
        return self.ep if self.ep is not None else self.dp

    def axis_size(self, logical: str) -> int:
        """|axis| of a logical role ('dp'|'sp'|'pp'|'ep'); 1 for an
        absent pp axis."""
        name = {"dp": self.dp, "sp": self.sp, "pp": self.pp,
                "ep": self.ep_axis}[logical]
        return 1 if name is None else int(self.mesh.shape[name])

    @property
    def dp_size(self) -> int:
        return self.axis_size("dp")

    @property
    def sp_size(self) -> int:
        return self.axis_size("sp")

    @property
    def pp_size(self) -> int:
        return self.axis_size("pp")

    @property
    def pipelined(self) -> bool:
        """True when this plan selects the pipelined (stacked-stage)
        step: a pp axis with more than one stage or more than one
        microbatch.  A pp=1, n_micro=1 plan runs the EXACT legacy
        dp x sp program (bit-identical, test-gated)."""
        return self.pp is not None and (self.pp_size > 1 or self.n_micro > 1)

    @property
    def overlap_blocks(self) -> int:
        """Block count for the decomposed sync legs; 0 = serial (the
        unchunked RS -> update -> AG schedule)."""
        return self.prefetch_blocks if self.overlap else 0

    # -- pytree-path -> mesh-axes --------------------------------------
    def spec(self, *logical) -> P:
        """PartitionSpec from LOGICAL axis roles: each entry is None,
        one of 'dp'/'sp'/'pp'/'ep', or a tuple of them (sharding one
        array dim over several mesh axes) — resolved onto this plan's
        mesh axis names.  The one place logical roles become mesh
        axes."""
        table = {"dp": self.dp, "sp": self.sp, "pp": self.pp,
                 "ep": self.ep_axis, None: None}

        def resolve(entry):
            if isinstance(entry, tuple):
                return tuple(resolve(e) for e in entry)
            if entry not in table:
                raise ValueError(
                    f"unknown logical axis {entry!r}: one of {_LOGICAL}"
                )
            name = table[entry]
            if name is None and entry is not None:
                raise ValueError(
                    f"logical axis {entry!r} is not mapped by this plan"
                )
            return name

        return P(*(resolve(e) for e in logical))

    def tree_spec(self, tree, rule: Callable) -> object:
        """The pytree-path -> mesh-axes mapping in tree form: build a
        PartitionSpec pytree for ``tree`` by mapping each leaf's path
        through ``rule(path, leaf) -> (logical axes...)`` and resolving
        the logical roles onto this plan's mesh axes via
        :meth:`spec`."""
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec(*rule(path, leaf)), tree
        )

    def data_spec(self, accum_steps: int = 1) -> P:
        """Spec of a (batch, seq, d) batch — batch over dp, sequence
        over sp (a leading unsharded microbatch axis under
        accumulation)."""
        return (P(self.dp, self.sp) if accum_steps == 1
                else P(None, self.dp, self.sp))

    # -- identity ------------------------------------------------------
    def describe(self) -> dict:
        """Normalized plan identity for checkpoint metadata: axis sizes
        plus the microbatch schedule.  A pp=1, n_micro=1 plan describes
        identically to the legacy (plan-less) dp x sp run — they ARE
        the same program — so resumes interoperate; anything else
        mismatching raises the trainer's CommError contract."""
        return {
            "dp": self.dp_size,
            "sp": self.sp_size,
            "pp": self.pp_size if self.pipelined else 1,
            "n_micro": self.n_micro if self.pipelined else 1,
        }

    # -- programs ------------------------------------------------------
    def pipeline_program(self, stage_fn):
        """Compiled GPipe program over this plan's pp axis: jit'd
        fn(stage_params, micro) -> (M, ...) outputs, stage parameters
        sharded over pp on their leading axis.  ``bench.pipeline_bench``
        routes here so the schedule it measures is the one the
        trainer's pipelined loss runs (both are
        ``parallel.pipeline.gpipe_scan``), reached through the same
        plan validation."""
        if self.pp is None:
            raise ValueError(
                "pipeline_program needs a pp axis (pass pp=<axis name>)"
            )
        from tpuscratch.comm import run_spmd
        from tpuscratch.parallel.pipeline import pipeline_apply

        return run_spmd(
            self.mesh,
            lambda W, m: pipeline_apply(stage_fn, W, m, self.pp),
            (P(self.pp), P()),
            P(),
        )
