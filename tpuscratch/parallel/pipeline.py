"""Staged pipeline parallelism: microbatches streaming through a stage chain.

Beyond-parity capability (the reference's closest structure is the 2-rank
lock-step token passing of mpi4, SURVEY.md §2.7): each mesh rank owns ONE
stage of a layer chain; activations hop stage-to-stage over an open
ppermute chain while microbatches stream in behind each other — the GPipe
schedule. With M microbatches over n stages the schedule runs M + n - 1
ticks, so bubble overhead is (n-1)/(M+n-1); every tick every stage
computes on a different microbatch, which is what makes it pipeline (not
sequential) parallelism.

SPMD formulation: one `lax.scan` over ticks inside shard_map. Stage
parameters arrive pre-sharded over the stage axis (in_specs P("stage")),
the microbatch stack is replicated, and the output stack is returned
replicated via a masked psum from the last stage. Stage shapes must be
uniform (every stage maps (..., F) -> (..., F)) — the standard equal-width
pipeline regime.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

StageFn = Callable[[Any, jax.Array], jax.Array]


def gpipe_scan(
    stage_fn: Callable[[jax.Array], tuple[jax.Array, jax.Array]],
    micro: jax.Array,
    axis: str,
) -> tuple[jax.Array, jax.Array]:
    """The GPipe tick loop itself — the ONE schedule implementation both
    :func:`pipeline_apply` and the trainer's pipelined loss
    (``models.transformer._pp_loss_fn``) run, so the pipeline bench and
    the training hot path measure the same code.

    ``stage_fn(x) -> (y, aux)``: this rank's stage (close over its
    parameters), shape-preserving, plus a scalar auxiliary term (the
    MoE load-balance loss; return ``0.0`` when unused).  ``micro``:
    (M, ...) microbatch stack, replicated across ``axis``.  Runs
    ``M + n - 1`` ticks of the open ppermute chain and returns

    - ``out``: the (M, ...) outputs of the full stage chain, replicated
      over ``axis`` (masked psum from the last stage);
    - ``aux``: the sum over (stage, valid tick) of ``stage_fn``'s aux
      term — warmup/drain ticks where a stage holds no real microbatch
      are masked out, so bubble compute never pollutes the loss.

    Call inside shard_map over ``axis``.
    """
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    M = micro.shape[0]
    ticks = M + n - 1
    shift = [(i, i + 1) for i in range(n - 1)]  # open chain: stage i -> i+1

    out0 = jnp.zeros_like(micro)
    act0 = jnp.zeros_like(micro[0])

    def tick(state, t):
        act, out, aux_acc = state
        incoming = lax.ppermute(act, axis, shift) if n > 1 else act
        inject = jnp.where(t < M, micro[jnp.clip(t, 0, M - 1)], 0.0)
        x = jnp.where(me == 0, inject, incoming)
        y, aux = stage_fn(x)
        valid = jnp.logical_and(t - me >= 0, t - me < M)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        emit = t - (n - 1)  # microbatch index leaving the last stage
        upd = lax.dynamic_update_slice(
            out, y[None], (jnp.clip(emit, 0, M - 1),) + (0,) * y.ndim
        )
        out = jnp.where((me == n - 1) & (emit >= 0), upd, out)
        return (y, out, aux_acc), ()

    (_, out, aux_acc), _ = lax.scan(
        tick, (act0, out0, jnp.float32(0.0)), jnp.arange(ticks)
    )
    # only the last stage's buffer holds results; replicate it
    out = lax.psum(jnp.where(me == n - 1, out, 0.0), axis)
    return out, lax.psum(aux_acc, axis)


def pipeline_apply(
    stage_fn: StageFn,
    params: Any,
    micro: jax.Array,
    axis: str,
) -> jax.Array:
    """Apply the full stage chain to every microbatch, pipelined.

    ``stage_fn(params, x)``: this rank's stage; shape-preserving.
    ``params``: this rank's stage parameters (shard the stacked (n, ...)
    parameters over ``axis`` via in_specs).
    ``micro``: (M, ...) microbatch stack, replicated across the axis.
    Returns the (M, ...) outputs of stage_{n-1}(...stage_0(x)...),
    replicated. Call inside shard_map over ``axis``.
    """
    n = lax.axis_size(axis)
    if n == 1:
        return jax.vmap(lambda x: stage_fn(params, x))(micro)
    out, _ = gpipe_scan(
        lambda x: (stage_fn(params, x), jnp.float32(0.0)), micro, axis
    )
    return out


def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """Idle fraction of the GPipe schedule: (n-1)/(M+n-1)."""
    if n_stages < 1 or n_micro < 1:
        raise ValueError("need at least one stage and one microbatch")
    return (n_stages - 1) / (n_micro + n_stages - 1)
