"""Long-context parallelism: ring pipelines and sequence/context parallel
attention.

The reference has no attention or sequence dimension (SURVEY.md §2.7), but
its communication skeletons are exactly what long-context parallelism is
built from: the ring/neighbor exchange (mpi5) and blockwise-partitioned
reduction (mpicuda4's per-block partials). This package composes those
primitives — already present in tpuscratch.comm — into the two standard
sequence-parallel attention schemes:

- ``ring``: a generic rotate-and-combine pipeline over a mesh axis
  (the load-bearing structure of ring attention, ring allreduce, etc.).
- ``ring_attention``: blockwise attention with KV blocks rotating around
  the ring and online-softmax accumulation — O(seq/n) memory per chip,
  communication overlapped hop by hop over ICI.
- ``ulysses``: all-to-all sequence parallelism — switch from
  sequence-sharded to head-sharded with one all_to_all, run exact local
  attention, switch back.
- ``pipeline``: staged (GPipe-style) pipeline parallelism — one stage per
  rank, microbatches streaming through an open ppermute chain.
- ``expert``: expert parallelism — capacity-routed MoE dispatch/combine
  via all_to_all over an expert axis.
- ``fft``: pencil-decomposition 2D FFT — local transforms plus a global
  all_to_all transpose (the FFTW-MPI/heFFTe pattern).
- ``ssm``: sequence-parallel linear recurrence — local associative scan
  plus an exclusive scan of shard aggregates (distributed Blelloch-style
  prefix structure, O(n*d_state) bytes regardless of sequence length).
- ``plan``: the ONE ShardingPlan composing all of the above — a
  pytree-path -> mesh-axes mapping with named dp/sp/pp(/ep) roles,
  validated against the live mesh at construction, that the trainer and
  the ZeRO step consume instead of hardcoded dp x sp assumptions (the
  moral successor of the reference's cartesian-topology layer).
"""

from tpuscratch.parallel.expert import expert_parallel_ffn, topk_routing  # noqa: F401
from tpuscratch.parallel.fft import fft2_sharded, ifft2_sharded  # noqa: F401
from tpuscratch.parallel.pipeline import (  # noqa: F401
    bubble_fraction,
    gpipe_scan,
    pipeline_apply,
)
from tpuscratch.parallel.plan import ShardingPlan  # noqa: F401
from tpuscratch.parallel.ring import ring_scan  # noqa: F401
from tpuscratch.parallel.ring_attention import ring_attention  # noqa: F401
from tpuscratch.parallel.ssm import ssm_scan  # noqa: F401
from tpuscratch.parallel.ulysses import ulysses_attention  # noqa: F401
