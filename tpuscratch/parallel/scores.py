"""Shared attention-score math for the sequence-parallel schemes.

One definition of the scale, the mask sentinel, and the fp32 einsum so the
ring and Ulysses paths (which tests assert agree) cannot silently diverge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def masked_scores(q: jax.Array, k: jax.Array, mask: jax.Array) -> jax.Array:
    """Masked scaled scores (H, S, T) in fp32.

    q: (S, H, D), k: (T, H, D), mask: (S, T) boolean (True = attend).
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "shd,thd->hst", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    return jnp.where(mask[None, :, :], s, NEG_INF)


def masked_softmax(s: jax.Array, mask: jax.Array) -> jax.Array:
    """fp32 attention weights over the last axis of masked scores.

    ``mask`` (True = attend) must broadcast to ``s``.  THE one
    normalize-with-guard definition for the non-online paths (the serve
    prefill and ``ops.attention.decode_attention``): masked entries are
    re-zeroed AFTER exponentiation (a fully-masked row has max NEG_INF,
    making s - m == 0 there), and fully-masked rows come back as all-zero
    weights instead of NaN.
    """
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    return p / jnp.maximum(l, 1e-30)
