"""Expert parallelism: routed MoE feed-forward over an expert mesh axis.

Beyond-parity capability (the reference has no expert routing anywhere —
SURVEY.md §2.7 lists EP as absent), but its structural ancestors are the
same ones the reference exercises: the scatter of typed records to ranks
(/root/reference/mpi8.cpp:53 struct scatter) and sub-communicator
reduction (/root/reference/mpi9.cpp:51-54). Here tokens are the records,
experts the ranks, and the transport is one ``all_to_all`` over ICI in
each direction — the TPU-native replacement for per-pair Isend/Irecv.

Scheme (Switch-Transformer style, everything static-shaped for XLA):

1. route: a linear gate scores every local token against all experts;
   top-k selection with per-(rank, expert) capacity ``C`` — tokens past
   capacity are dropped (their combine weight is zero), keeping shapes
   static.
2. dispatch: each expert's capacity slots GATHER their token's row
   (index-form sparse routing, the default — O(E*C*D) data movement);
   ``all_to_all`` over the expert axis hands each rank the slots of ITS
   experts from every rank.
3. expert compute: each rank applies its local experts' FFN to its
   (E_local, n*C, D) batch — a large static matmul per expert, MXU-shaped.
4. combine: reverse ``all_to_all``, then each token gathers its k slots
   back, weighted by the gate probability.

``impl='einsum'`` selects the classic one-hot formulation instead
(``einsum('tec,td->ecd')`` / ``einsum('tec,ecd->td')``): same
assignment (equality-tested fwd + grad), but its (T, E, C) tensors cost
T*E*C*D MACs per direction — 4x the expert FFN itself at the composed
trainer's shapes; switching the default to sparse measured 1.8x on the
whole train step (BASELINE row 11).

The load-balance auxiliary loss (mean fraction-routed x mean gate mass,
scaled by E) is returned alongside — it is what keeps routing from
collapsing onto one expert/rank.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from tpuscratch.comm.collectives import all_to_all


class Routing(NamedTuple):
    """Static-shaped routing plan for one rank's tokens.

    dispatch: (T, E, C) 0/1 — token t occupies slot c of expert e.
    combine:  (T, E, C) float — dispatch weighted by the gate probability.
    aux_loss: scalar load-balance loss (1.0 == perfectly uniform top-1).
    """

    dispatch: jax.Array
    combine: jax.Array
    aux_loss: jax.Array


def capacity(tokens: int, n_experts: int, factor: float = 1.25) -> int:
    """Per-expert capacity slots for ``tokens`` local tokens: the expected
    even share times ``factor``, at least 1."""
    return max(1, int(tokens * factor / n_experts))


def _routing_rounds(logits: jax.Array, cap: int, k: int):
    """The shared assignment core of both routing formulations: greedy
    iterated masked top-1 with per-expert capacity accounting across the
    k rounds. Yields per-round (choice (T,), gate (T,), onehot (T, E),
    slot (T,), kept (T,)) and finally returns the Switch load-balance
    aux loss — the ONE place the tie-breaking / used / remaining math
    lives, so the dense and sparse plans cannot drift apart."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    remaining = probs
    used = jnp.zeros((E,), dtype=jnp.int32)
    rounds = []
    top1_frac = None
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)  # (T,)
        gate = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)  # (T, E)
        if top1_frac is None:
            top1_frac = onehot.astype(jnp.float32).mean(axis=0)  # (E,)
        # slot index = tokens for the same expert ahead of me + already used
        ahead = jnp.cumsum(onehot, axis=0) - onehot  # (T, E)
        slot = jnp.sum((ahead + used[None, :]) * onehot, axis=-1)  # (T,)
        kept = slot < cap
        rounds.append((choice, gate, onehot, slot, kept))
        used = used + jnp.sum(onehot * kept[:, None].astype(jnp.int32), axis=0)
        remaining = remaining * (1 - onehot)  # mask chosen expert, next round
    # Switch load-balance loss: E * <frac routed to e> . <mean gate prob e>
    aux = E * jnp.sum(top1_frac * probs.mean(axis=0))
    return rounds, aux


def topk_routing(logits: jax.Array, cap: int, k: int = 1) -> Routing:
    """Top-k capacity routing from gate ``logits`` (T, E), one-hot form.

    Experts are chosen greedily (iterated masked top-1, the standard
    static-shaped formulation); each choice claims the next free capacity
    slot of its expert, and choices past slot ``cap`` are dropped —
    dropped tokens simply contribute zero to the combine, mirroring how
    the reference keeps buffers fixed-size and probe-sized rather than
    reallocating (/root/reference/mpi3.cpp:28-32).
    """
    T, E = logits.shape
    dispatch = jnp.zeros((T, E, cap), dtype=jnp.float32)
    combine = jnp.zeros((T, E, cap), dtype=jnp.float32)
    rounds, aux = _routing_rounds(logits, cap, k)
    for choice, gate, onehot, slot, kept in rounds:
        slot_1h = jax.nn.one_hot(slot, cap, dtype=jnp.float32)  # (T, C)
        sel = (kept[:, None] & (onehot == 1)).astype(jnp.float32)  # (T, E)
        dispatch = dispatch + sel[:, :, None] * slot_1h[:, None, :]
        combine = combine + (gate[:, None] * sel)[:, :, None] * slot_1h[:, None, :]
    return Routing(dispatch, combine, aux)


class SparseRouting(NamedTuple):
    """Index-form routing plan — the same assignment as :class:`Routing`
    without the (T, E, C) one-hot tensors, whose dispatch/combine
    einsums cost T*E*C*D MACs (4x the expert FFN itself at the composed
    trainer's shapes) and materialize T*E*C elements.

    slot_token:  (E, C) int32 — which local token fills each slot.
    slot_filled: (E, C) 0/1 — slot actually claimed this batch.
    tok_flat:    (T, k) int32 — flat e*C+c slot per routing round.
    tok_gate:    (T, k) float — gate weight per round (0 if dropped).
    tok_kept:    (T, k) 0/1 — routing round actually landed a slot.
    slot_gate:   (E, C) float — the claiming token's gate weight (the
                 combine transpose reads it: the slot<->token map is a
                 bijection on filled slots, so both backward directions
                 are GATHERS through the inverse index instead of the
                 scatter-adds autodiff would emit — see
                 :func:`_sparse_dispatch` / :func:`_sparse_combine`).
    aux_loss:    scalar load-balance loss.
    """

    slot_token: jax.Array
    slot_filled: jax.Array
    tok_flat: jax.Array
    tok_gate: jax.Array
    tok_kept: jax.Array
    slot_gate: jax.Array
    aux_loss: jax.Array


def sparse_topk_routing(logits: jax.Array, cap: int, k: int = 1) -> SparseRouting:
    """:func:`topk_routing`'s assignment in index form (O(T) routing
    state instead of O(T*E*C)); equality with the dense plan is tested.
    Dropped choices scatter out of bounds (mode='drop') and carry zero
    gate weight, so they vanish from both directions."""
    T, E = logits.shape
    slot_token = jnp.zeros((E * cap,), dtype=jnp.int32)
    slot_filled = jnp.zeros((E * cap,), dtype=jnp.float32)
    slot_gate = jnp.zeros((E * cap,), dtype=jnp.float32)
    tok_flat = []
    tok_gate = []
    tok_kept = []
    rounds, aux = _routing_rounds(logits, cap, k)
    for choice, gate, onehot, slot, kept in rounds:
        flat = choice * cap + slot
        oob = jnp.where(kept, flat, E * cap)  # out of bounds -> dropped
        slot_token = slot_token.at[oob].set(
            jnp.arange(T, dtype=jnp.int32), mode="drop"
        )
        slot_filled = slot_filled.at[oob].set(1.0, mode="drop")
        # the claiming token's gate weight, for the combine transpose;
        # stop_gradient: this array only feeds the custom backward (the
        # differentiable gate path is tok_gate)
        slot_gate = slot_gate.at[oob].set(
            lax.stop_gradient(gate), mode="drop"
        )
        tok_flat.append(jnp.where(kept, flat, 0))
        tok_gate.append(jnp.where(kept, gate, 0.0))
        tok_kept.append(kept.astype(jnp.float32))
    return SparseRouting(
        slot_token.reshape(E, cap),
        slot_filled.reshape(E, cap),
        jnp.stack(tok_flat, axis=1),
        jnp.stack(tok_gate, axis=1),
        jnp.stack(tok_kept, axis=1),
        slot_gate.reshape(E, cap),
        aux,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _sparse_dispatch(x, slot_token, slot_filled, tok_flat, tok_kept):
    """(T, D) tokens -> (E, C, D) packed slots by gather.

    Custom VJP: autodiff's transpose of the gather is a (E*C, D)
    scatter-ADD into (T, D) — the chip-measured hotspot of the MoE
    backward (BASELINE row 11: backward 4.6x its forward).  The routing
    map is a bijection on filled slots, so dx is instead a GATHER
    through the token-side index: dx[t] = sum_j kept[t,j] *
    ct[tok_flat[t,j]]."""
    return x[slot_token] * slot_filled[:, :, None]


def _sparse_dispatch_fwd(x, slot_token, slot_filled, tok_flat, tok_kept):
    out = x[slot_token] * slot_filled[:, :, None]
    return out, (x.shape, tok_flat, tok_kept)


def _sparse_dispatch_bwd(res, ct):
    (T, D), tok_flat, tok_kept = res
    ct_flat = ct.reshape(-1, D)
    dx = jnp.sum(tok_kept[:, :, None] * ct_flat[tok_flat], axis=1)
    return (
        dx,
        jnp.zeros(ct.shape[:2], jax.dtypes.float0),   # slot_token (int)
        jnp.zeros(ct.shape[:2], ct.dtype),            # slot_filled
        jnp.zeros(tok_flat.shape, jax.dtypes.float0),  # tok_flat (int)
        jnp.zeros(tok_kept.shape, ct.dtype),          # tok_kept
    )


_sparse_dispatch.defvjp(_sparse_dispatch_fwd, _sparse_dispatch_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _sparse_combine(flat, tok_gate, tok_flat, slot_token, slot_gate):
    """(E*C, D) slot outputs -> (T, D) by indexed gather-and-weight.

    Custom VJP: the gather's transpose is a (T*k, D) scatter-ADD into
    (E*C, D); through the inverse index it is a gather instead:
    dflat[s] = slot_gate[s] * ct[slot_token[s]] (slot_gate is zero on
    unclaimed slots, so they receive nothing — matching the scatter)."""
    return jnp.sum(tok_gate[:, :, None] * flat[tok_flat], axis=1)


def _sparse_combine_fwd(flat, tok_gate, tok_flat, slot_token, slot_gate):
    out = jnp.sum(tok_gate[:, :, None] * flat[tok_flat], axis=1)
    # tok_gate is NOT a residual: dgate recomputes from flat and ct
    # (the routing always builds f32 gates)
    return out, (flat, tok_flat, slot_token, slot_gate)


def _sparse_combine_bwd(res, ct):
    flat, tok_flat, slot_token, slot_gate = res
    dflat = (
        ct[slot_token.reshape(-1)] * slot_gate.reshape(-1)[:, None]
    ).astype(flat.dtype)
    dgate = jnp.einsum("tkd,td->tk", flat[tok_flat], ct)
    return (
        dflat,
        dgate.astype(jnp.float32),
        jnp.zeros(tok_flat.shape, jax.dtypes.float0),
        jnp.zeros(slot_token.shape, jax.dtypes.float0),
        jnp.zeros(slot_gate.shape, slot_gate.dtype),
    )


_sparse_combine.defvjp(_sparse_combine_fwd, _sparse_combine_bwd)


def expert_ffn(x: jax.Array, w_in: jax.Array, w_out: jax.Array) -> jax.Array:
    """The per-expert MLP: (E, C', D) x (E, D, F) -> relu -> (E, C', D).

    One batched einsum per layer — E experts' matmuls fused into a single
    MXU-shaped contraction (vs the reference's one-kernel-per-rank
    compute, /root/reference/mpicuda2.cu:265-275)."""
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", x, w_in))
    return jnp.einsum("ecf,efd->ecd", h, w_out).astype(x.dtype)


def expert_parallel_ffn(
    x: jax.Array,
    gate_w: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    axis: str,
    capacity_factor: float = 1.25,
    k: int = 1,
    impl: str = "sparse",
) -> tuple[jax.Array, jax.Array]:
    """Routed MoE layer, experts sharded over mesh ``axis``. Call inside
    shard_map.

    x: (T, D) local tokens. gate_w: (D, E_total) replicated gate.
    w_in/w_out: (E_local, D, F)/(E_local, F, D) THIS rank's experts.
    Returns (out (T, D), aux_loss scalar). E_total = axis_size * E_local.

    ``impl='sparse'`` (default) dispatches by gather and combines by
    indexed gather-and-weight — O(E*C*D) data movement; ``'einsum'``
    keeps the one-hot formulation, whose (T, E, C) tensors cost
    T*E*C*D MACs per direction (4x the expert FFN at the composed
    trainer's shapes — chip-raced, see BASELINE row 11). Both paths
    compute the identical assignment (equality-tested, fwd and grad).
    """
    if impl not in ("sparse", "einsum"):
        raise ValueError(f"impl must be sparse|einsum, got {impl!r}")
    n = lax.axis_size(axis)
    T, D = x.shape
    e_local = w_in.shape[0]
    e_total = n * e_local
    if gate_w.shape != (D, e_total):
        raise ValueError(
            f"gate_w {gate_w.shape} != ({D}, {e_total}) for "
            f"{e_local} local experts on a {n}-way axis"
        )
    cap = capacity(T, e_total, capacity_factor)
    logits = x @ gate_w
    if impl == "einsum":
        route = topk_routing(logits, cap, k=k)
        # pack: (T, E_total, C) x (T, D) -> (E_total, C, D)
        packed = jnp.einsum(
            "tec,td->ecd", route.dispatch, x.astype(jnp.float32)
        )
    else:
        route = sparse_topk_routing(logits, cap, k=k)
        # pack by gather: slot (e, c) takes its token's row, empties
        # zero — custom VJP turns the backward scatter-add into a
        # gather through the token-side index
        packed = _sparse_dispatch(
            x.astype(jnp.float32), route.slot_token, route.slot_filled,
            route.tok_flat, route.tok_kept,
        )
    # route out: split experts across ranks, gather every rank's slots for
    # mine -> (E_local, n*C, D)
    routed = all_to_all(packed, axis, split_axis=0, concat_axis=1, tiled=True)
    y = expert_ffn(routed, w_in.astype(jnp.float32), w_out.astype(jnp.float32))
    # route back: inverse all_to_all -> (E_total, C, D), slots back at the
    # rank whose tokens filled them
    back = all_to_all(y, axis, split_axis=1, concat_axis=0, tiled=True)
    if impl == "einsum":
        out = jnp.einsum("tec,ecd->td", route.combine, back)
    else:
        flat = back.reshape(e_total * cap, D)
        # each token reads its k slots back, weighted by its gate
        # (dropped rounds carry zero weight, their index is a dummy 0);
        # custom VJP: dflat is a gather through the slot-side index
        out = _sparse_combine(
            flat, route.tok_gate, route.tok_flat, route.slot_token,
            route.slot_gate,
        )
    return out.astype(x.dtype), route.aux_loss
