"""Seeded, deterministic fault injection — the chaos harness.

A :class:`ChaosPlan` is a pure description of WHICH faults fire WHERE
and WHEN: every firing decision is a function of ``(seed, fault, site,
occurrence index)``, so the same plan produces the same fault schedule
on every run — the property that lets a chaos test assert bit-identical
recovery instead of "it probably survived".  Instrumented layers query
the plan through small hooks (``corrupt_batch``, ``maybe_fail``,
``maybe_preempt``, ``save_hook``, ``wrap_collective``); a layer given no
plan runs zero hook code, so the uninstrumented path is unchanged — the
same contract as the obs grad-norm output.

Site vocabulary (what the instrumented layers query):

- ``"train/grad"``    — corrupt a step's batch so its gradients go
  NaN/Inf through the unmodified compiled step (``kind="nan"|"inf"``).
- ``"train/preempt"`` / ``"halo/preempt"`` / ``"solver/preempt"`` —
  simulated scheduler preemption at a chunk boundary, AFTER the save
  (``kind="preempt"``).
- ``"ckpt/save"``     — checkpoint IO: fail (``"error"``), stall
  (``"stall"``), or SIGKILL the process (``"kill"``) at a named stage
  inside :func:`runtime.checkpoint.save` (``stage=``).
- ``"ckpt/snapshot"`` — the BLOCKING device→host staging half of an
  async checkpoint (:class:`runtime.async_ckpt.AsyncCheckpointer`):
  fail/stall/SIGKILL before the copy (occurrences auto-counted per
  snapshot).
- ``"ckpt/write"``    — the BACKGROUND writer half of an async
  checkpoint: the same named-stage vocabulary as ``ckpt/save``
  (``begin``/``leaf_<i>``/``manifest``/``swap``/``publish``/``end``),
  fired from inside the writer thread's ``checkpoint.save`` — so the
  async path gets the same deterministic kill-mid-save matrix coverage
  the blocking path has.
- ``"serve/prefill"`` — fail a request's prefill admission
  (``key=rid`` targets one request; ``times`` bounds transience).
- ``"serve/replica"`` — REPLICA-scoped fleet chaos (ISSUE 17): the
  fleet router queries this site once per (fleet tick, replica) with
  ``index=tick`` and ``key=replica``.  ``kind="kill"`` tears the whole
  ``ServeEngine`` down mid-stream (``ServeEngine.evacuate``) and the
  router re-admits its in-flight + queued requests elsewhere with
  deterministic replay; ``kind="stall"`` freezes the replica (no
  ticks, no dispatches) without losing its state.  ``down_ticks``
  sizes the outage in fleet ticks before the elastic re-join
  (``None``: the router's ``rejoin_ticks`` default).  Explicit
  ``index=tick`` keeps the schedule a pure function of the plan — the
  chaos-vs-clean bit-identity runs fire at the same ticks.
  ``Fault(domain=(0, 1, 2))`` makes the clause a CORRELATED fault
  domain (ISSUE 18 — replicas sharing a rack/power feed/switch): one
  seeded ignition at an occurrence index fires the clause for EVERY
  key in the domain at that index, consuming ONE ``times`` budget per
  ignition rather than one per member — a rack dies whole, in the
  same fleet tick, off one draw.
- ``"comm/<op>"``     — a transient :class:`InjectedFault` (a
  ``CommError``) raised from a collective wrapper around a compiled
  program (:meth:`ChaosPlan.wrap_collective`); the chunked drivers
  query ``comm/halo_chunk`` / ``comm/solver_chunk`` before each
  compiled chunk.

The reference has nothing to compare: its faults all funnel into
``MPI_Abort`` (mpierr.h:37-43).  This module is the part of fault
tolerance the reference could not even test — injecting the failure on
purpose, deterministically.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
import zlib
from typing import Callable, Optional, Sequence

import numpy as np

from tpuscratch.obs.sink import NullSink
from tpuscratch.runtime.errors import CommError


class Preempted(RuntimeError):
    """A (simulated or real) scheduler preemption: the run must stop NOW
    and be re-invoked — the supervisor's restartable signal."""

    def __init__(self, site: str, index: Optional[int] = None):
        self.site = site
        self.index = index
        super().__init__(f"preempted at {site}"
                         + (f" (index {index})" if index is not None else ""))


class InjectedFault(CommError):
    """A chaos-injected transient failure.  A ``CommError`` so the
    raise-vs-abort policy layer and the supervisor's restartable set both
    treat it like a real comm-layer fault; constructed WITHOUT an op when
    the injection site doesn't know which op wraps it — ``guarded``
    attaches the name (``CommError.with_op``) so retry logs name the
    failing op."""


def bind_sink(plan: Optional["ChaosPlan"], sink) -> None:
    """Point ``plan``'s ``ft/fault`` events at the instrumented layer's
    sink — the one binding rule trainer and halo driver share: only an
    unbound plan (still on the NullSink) is rebound, and only to an
    enabled sink, so a caller-chosen sink is never overridden."""
    if plan is not None and isinstance(plan.sink, NullSink) and sink.enabled:
        plan.sink = sink


def bind_tracer(plan: Optional["ChaosPlan"], tracer) -> None:
    """The ``bind_sink`` rule for the per-request tracer
    (:mod:`tpuscratch.obs.reqtrace`): a rid-keyed firing then drops a
    ``fault`` mark into that request's span tree, so an injected
    handoff/prefill fault shows up INSIDE the victim's causal trace
    rather than only in the fleet-wide ``ft/fault`` stream.  Only an
    unbound plan is rebound, and only to an enabled tracer."""
    if plan is not None and plan.tracer is None and tracer is not None \
            and tracer.enabled:
        plan.tracer = tracer


@dataclasses.dataclass
class Fault:
    """One fault clause of a plan.

    ``at`` names explicit occurrence indices (a step number, a save
    count, a per-rid attempt index — whatever the site passes); ``p``
    instead fires at a seeded rate per occurrence.  ``times`` bounds the
    TOTAL number of firings (``None`` = unlimited: a deterministic,
    never-healing fault — the quarantine test case); ``key`` restricts
    the clause to one site key (e.g. a request rid, a replica index);
    ``stage`` restricts ``ckpt/save`` clauses to one named stage inside
    ``save``.  ``down_ticks`` sizes a ``serve/replica`` outage in fleet
    ticks (the tick-denominated twin of ``stall_s``: replica chaos is
    scheduled in ticks so the fault matrix stays deterministic, not
    wall-clocked).  ``domain`` is the CORRELATED twin of ``key``: the
    clause matches every key in the group, and one ignition at an
    occurrence index fires for ALL of them at that index off a single
    ``times`` budget — the rack / power-feed / switch failure unit.
    ``key`` and ``domain`` are mutually exclusive.
    """

    site: str
    at: Optional[Sequence[int]] = None   # explicit occurrence indices
    p: float = 0.0                       # else: seeded firing rate
    times: Optional[int] = 1             # firing budget; None = unlimited
    key: Optional[int] = None            # site key selector (e.g. rid)
    kind: str = "error"                  # error | nan | inf | stall | preempt | kill
    stage: Optional[str] = None          # ckpt/save stage selector
    stall_s: float = 0.0                 # sleep length for kind="stall"
    down_ticks: Optional[int] = None     # serve/replica outage length
    domain: Optional[Sequence[int]] = None  # correlated key group (a rack)

    def __post_init__(self):
        if self.key is not None and self.domain is not None:
            raise ValueError("Fault: key and domain are mutually exclusive")


def rack_domains(n_replicas: int, rack_size: int) -> tuple[tuple[int, ...], ...]:
    """Partition ``range(n_replicas)`` into contiguous racks of
    ``rack_size`` — the conventional domain layout for ``Fault(domain=)``
    clauses (the last rack may be short)."""
    if rack_size <= 0:
        raise ValueError("rack_domains: rack_size must be positive")
    return tuple(
        tuple(range(lo, min(lo + rack_size, n_replicas)))
        for lo in range(0, n_replicas, rack_size)
    )


class ChaosPlan:
    """A deterministic fault schedule over the site vocabulary.

    Occurrence indices are either passed explicitly by the site (the
    trainer passes the global step, so a rolled-back replay re-queries
    the SAME indices and a ``times``-exhausted fault stays consumed — the
    recover-then-bit-identical property) or auto-counted per
    ``(site, stage, key)`` when the site has no natural index (checkpoint
    saves, prefill attempts).

    ``sink`` (an ``obs.sink.Sink``) receives one ``ft/fault`` event per
    firing; instrumented layers bind their sink onto the plan so injected
    faults land in the same JSONL stream as the recovery events.
    """

    def __init__(self, seed: int, faults: Sequence[Fault] = (), sink=None):
        self.seed = int(seed)
        self.faults = tuple(faults)
        self._left = [f.times for f in self.faults]
        self._occ: dict = {}
        self._domain_fired: set = set()  # (fault_i, index) ignitions
        self.fired: dict[str, int] = {}
        self.sink = sink if sink is not None else NullSink()
        self.tracer = None  # bound via bind_tracer (obs.reqtrace)

    # ---- the schedule --------------------------------------------------

    def _rate_fires(self, fault_i: int, site: str, index: int) -> bool:
        """Pure function of (seed, fault, site, index) — the determinism
        contract: no call-order state feeds the draw."""
        ss = np.random.SeedSequence(
            [self.seed, fault_i, zlib.crc32(site.encode()), int(index)]
        )
        return float(np.random.default_rng(ss).random()) < self.faults[fault_i].p

    def should_fire(self, site: str, index: Optional[int] = None,
                    key: Optional[int] = None,
                    stage: Optional[str] = None) -> Optional[Fault]:
        """First matching, unexhausted clause that fires at this
        occurrence — consumed from its ``times`` budget — or ``None``.
        ``index=None`` auto-counts occurrences per (site, stage, key).
        A ``domain`` clause consumes ONE budget unit per (clause, index)
        ignition: the first domain member seen at an index pays; later
        members at the same index fire free (even past exhaustion), so
        every replica in the rack dies off the same draw."""
        if index is None:
            occ_key = (site, stage, key)
            index = self._occ.get(occ_key, 0)
            self._occ[occ_key] = index + 1
        for i, f in enumerate(self.faults):
            if f.site != site:
                continue
            if f.key is not None and key != f.key:
                continue
            if f.domain is not None and key not in tuple(f.domain):
                continue
            if f.stage is not None and stage != f.stage:
                continue
            ignited = f.domain is not None and (i, index) in self._domain_fired
            if self._left[i] == 0 and not ignited:
                continue
            if f.at is not None:
                fires = index in tuple(f.at)
            else:
                fires = f.p > 0 and self._rate_fires(i, site, index)
            if not fires:
                continue
            if not ignited:
                if self._left[i] is not None:
                    self._left[i] -= 1
                if f.domain is not None:
                    self._domain_fired.add((i, index))
            self.fired[site] = self.fired.get(site, 0) + 1
            self.sink.emit(
                "ft/fault", site=site, index=index, kind=f.kind,
                **({"key": key} if key is not None else {}),
                **({"stage": stage} if stage is not None else {}),
            )
            if (self.tracer is not None and key is not None
                    and site != "serve/replica"
                    and site.startswith(("serve/", "comm/"))):
                # rid-keyed serve-path sites only (serve/replica keys on
                # the REPLICA index, which could collide with a rid);
                # the tracer drops marks for rids it is not following
                self.tracer.mark(key, "fault", time.perf_counter(),
                                 site=site, fault=f.kind)
            return f
        return None

    def stats(self) -> dict[str, int]:
        """{site: firings so far} — the sweep bench's injected-fault count."""
        return dict(self.fired)

    # ---- the hooks instrumented layers call ----------------------------

    def corrupt_batch(self, x, step: int):
        """Return ``x`` with one poisoned element when a ``train/grad``
        clause fires at ``step`` — NaN (or Inf) flows through the
        UNMODIFIED compiled step into the loss and every gradient leaf,
        which is exactly what the device-side guard must catch."""
        f = self.should_fire("train/grad", index=step)
        if f is None:
            return x
        import jax.numpy as jnp

        bad = jnp.inf if f.kind == "inf" else jnp.nan
        x = jnp.asarray(x)
        return x.at[(0,) * x.ndim].set(bad)

    def maybe_fail(self, site: str, index: Optional[int] = None,
                   key: Optional[int] = None, op: str = "") -> None:
        """Raise an :class:`InjectedFault` (or stall, or hard-kill) when a
        clause fires.  ``stall`` sleeps and RETURNS — the call proceeds;
        the watchdog in ``ft.retry`` is what turns a stall into a
        failure."""
        f = self.should_fire(site, index=index, key=key)
        if f is None:
            return
        if f.kind == "stall":
            time.sleep(f.stall_s)
            return
        if f.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        if f.kind == "preempt":
            raise Preempted(site, index)
        raise InjectedFault(op, f"injected {f.kind} fault at {site}")

    def maybe_preempt(self, site: str = "train/preempt",
                      index: Optional[int] = None) -> None:
        """Raise :class:`Preempted` when a clause fires — called at chunk
        boundaries AFTER the save, so the restarted run resumes exactly
        where the preempted one stopped."""
        if self.should_fire(site, index=index) is not None:
            raise Preempted(site, index)

    def stage_hook(self, site: str) -> Callable[[str], None]:
        """A ``checkpoint.save(hook=...)``-shaped adapter for ``site``:
        each named stage queries a clause of that site (occurrences
        auto-counted PER STAGE, so ``Fault(stage="publish", at=(1,))``
        means "the second occurrence's publish point").  ``ckpt/save``
        is the blocking save path's site; ``ckpt/write`` the async
        background writer's — same stage vocabulary, separately
        injectable."""

        def hook(stage: str) -> None:
            f = self.should_fire(site, stage=stage)
            if f is None:
                return
            if f.kind == "stall":
                time.sleep(f.stall_s)
                return
            if f.kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            raise OSError(f"injected checkpoint IO failure at {stage!r}")

        return hook

    def save_hook(self) -> Callable[[str], None]:
        """:meth:`stage_hook` bound to the blocking ``ckpt/save`` site."""
        return self.stage_hook("ckpt/save")

    def wrap_collective(self, fn, op: str):
        """Wrap a compiled program (host-level): each call first queries
        ``comm/<op>`` — a firing raises a transient :class:`InjectedFault`
        carrying the op name, the fault class ``mpierr.h`` could only
        abort on and the supervisor now restarts through."""
        site = f"comm/{op}"

        def wrapped(*args, **kwargs):
            self.maybe_fail(site, op=op)
            return fn(*args, **kwargs)

        return wrapped
