"""The preemption supervisor: restart, restore, replay — bounded.

``mpierr.h``'s ABORT policy ends the job on the first failure; the
supervisor is the inverse contract for failures that are the STEADY
state of large runs (preempted slices, transient comm faults, flaky
checkpoint IO): catch the restartable class, back off, re-invoke — and
let the checkpoint layer's resume-from-``latest_step`` plus the
trainer's bit-identical replay contract turn "the job died" into "the
job continued".  The restart budget is the supervisor's own bounded
rung: a failure that keeps recurring past it escalates to the caller
(``RestartsExhausted``), the same discipline as ``guards``.

Events + metrics flow through ``obs``: one ``ft/restart`` per caught
failure, ``ft/run`` on completion, counters in the (optional) registry.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, TypeVar

from tpuscratch.ft.chaos import Preempted
from tpuscratch.ft.retry import jittered_backoff
from tpuscratch.obs.metrics import MetricsRegistry
from tpuscratch.obs.sink import NullSink
from tpuscratch.runtime.errors import CommError

T = TypeVar("T")

#: failures worth re-invoking for, by default: preemptions (the run was
#: healthy), comm-layer faults (transient by assumption — the bounded
#: budget is what makes that assumption safe), and IO errors (flaky
#: filesystem under the checkpoint dir).  GuardFailure is deliberately
#: absent: a poisoned data stream does not heal by restarting.
RESTARTABLE = (Preempted, CommError, OSError)

#: "not passed" sentinel for supervise_program's obs kwargs — ``None``
#: is a meaningful value there (supervise's own defaults), while an
#: omitted kwarg derives from the program itself
_UNSET = object()


class RestartsExhausted(RuntimeError):
    """The restart budget is spent — chained to the last failure."""


@dataclasses.dataclass(frozen=True)
class RestartBudget:
    """How many re-invocations, and how fast: exponential backoff from
    ``backoff_s`` capped at ``max_backoff_s``, jittered deterministically
    from ``seed`` (``ft.retry.jittered_backoff`` — the same formula the
    retry policy sleeps on)."""

    max_restarts: int = 3
    backoff_s: float = 0.0
    max_backoff_s: float = 5.0
    jitter: float = 0.1
    seed: int = 0

    def delay(self, restart: int) -> float:
        return jittered_backoff(self.seed, restart - 1, self.backoff_s,
                                2.0, self.max_backoff_s, self.jitter)


def supervise(
    fn: Callable[[], T],
    *,
    budget: RestartBudget = RestartBudget(),
    restartable: tuple = RESTARTABLE,
    sink=None,
    metrics: Optional[MetricsRegistry] = None,
    recorder=None,
    log: Callable[[str], None] = lambda s: None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run ``fn()`` under the restart loop; return its result.

    ``fn`` must be RE-INVOCABLE: each call picks up where the last left
    off (the trainer does, via ``ckpt_dir`` resume — that is the whole
    design of the checkpoint layer).  Failures outside ``restartable``
    propagate immediately; restartable ones are counted, emitted as
    ``ft/restart`` events (with the ``backoff_s`` about to be slept —
    the goodput report's "restart" badput bucket), backed off, and
    re-invoked until the budget runs out (``RestartsExhausted``).
    ``recorder`` (an ``obs.trace.FlightRecorder``) additionally marks
    each restart as an instant on the flight-recorder timeline."""
    sink = sink if sink is not None else NullSink()
    metrics = metrics if metrics is not None else MetricsRegistry()
    restarts = 0
    t0 = time.perf_counter()
    while True:
        try:
            out = fn()
        except restartable as exc:
            if restarts >= budget.max_restarts:
                # a give-up is NOT a restart: fn() will not be re-invoked,
                # so neither the counter nor an ft/restart event fires
                sink.emit("ft/give_up", restarts=restarts,
                          error=f"{type(exc).__name__}: {exc}")
                sink.flush()
                raise RestartsExhausted(
                    f"restart budget {budget.max_restarts} exhausted"
                ) from exc
            restarts += 1
            metrics.counter("ft/restarts").inc()
            op = getattr(exc, "op", None) or getattr(exc, "site", None)
            log(f"supervisor restart {restarts}/{budget.max_restarts}: "
                f"{type(exc).__name__}: {exc}")
            d = budget.delay(restarts)
            if recorder is not None:
                recorder.instant("ft/restart", restart=restarts,
                                 error=type(exc).__name__)
            if d > 0:
                sleep(d)
            # emitted AFTER the backoff: duration-carrying events are
            # stamped at the END of their activity (the goodput
            # convention), so [t - backoff_s, t] is the slept window
            sink.emit(
                "ft/restart", restart=restarts,
                error=f"{type(exc).__name__}: {exc}",
                backoff_s=round(d, 6),
                **({"op": op} if op else {}),
            )
            continue
        sink.emit(
            "ft/run", restarts=restarts,
            wall_s=round(time.perf_counter() - t0, 6),
        )
        sink.flush()
        return out


def supervise_program(
    program_or_factory,
    *,
    budget: Optional[RestartBudget] = None,
    restartable: Optional[tuple] = None,
    sink=_UNSET,
    metrics=_UNSET,
    recorder=_UNSET,
    log: Callable[[str], None] = lambda s: None,
    sleep: Callable[[float], None] = time.sleep,
):
    """:func:`supervise` around a ``runtime.chunked.ChunkedProgram`` —
    the restart-loop glue the three chunked drivers used to re-plumb
    individually (each wiring its own attempt closure + sink/metrics/
    recorder kwargs through ``supervise``).

    ``program_or_factory`` is either a built program (its ``remake``
    factory provides the restart re-invocation — resumed from
    ``ckpt_dir``, chaos plan persisting across restarts) or a zero-arg
    factory returning a fresh program per attempt.  The obs kwargs
    default to the PROGRAM'S OWN sink/metrics/recorder when omitted, so
    ``supervise_program(train_program(...))`` emits its ``ft/restart``
    events into the same (workload-tagged) stream the program writes —
    pass them explicitly (``None`` included) to override.  Returns the
    completing attempt's ``run()`` result."""
    from tpuscratch.runtime.chunked import ChunkedProgram  # lazy: cycle

    if isinstance(program_or_factory, ChunkedProgram):
        first = program_or_factory
        remake = first.remake
        if remake is None:
            raise ValueError(
                f"supervise_program: program {first.workload!r} has no "
                "remake factory — restarts cannot re-invoke it"
            )
    else:
        remake = program_or_factory
        first = remake()
    if sink is _UNSET:
        sink = first.sink
    if metrics is _UNSET:
        metrics = first.metrics
    if recorder is _UNSET:
        recorder = first.rec
    box = {"program": first}

    def attempt():
        program = box["program"]
        if program is None:
            program = remake()
        box["program"] = None  # consumed: a failed attempt remakes
        return program.run()

    return supervise(attempt, budget=budget or RestartBudget(),
                     restartable=(restartable if restartable is not None
                                  else RESTARTABLE),
                     sink=sink, metrics=metrics, recorder=recorder,
                     log=log, sleep=sleep)


def supervise_elastic(
    make_attempt: Callable[[list], T],
    *,
    devices_fn: Callable[[], list],
    budget: RestartBudget = RestartBudget(),
    restartable: tuple = RESTARTABLE,
    sink=None,
    metrics: Optional[MetricsRegistry] = None,
    recorder=None,
    log: Callable[[str], None] = lambda s: None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """:func:`supervise` for PREEMPTED-AND-SHRUNK capacity: each
    (re)invocation first re-queries ``devices_fn()`` for the SURVIVING
    devices and rebuilds the attempt on them via
    ``make_attempt(devices)`` — so a run that lost part of its slice
    restarts on what is left instead of failing its mesh build forever.
    A capacity change between attempts is emitted as one ``ft/elastic``
    event (and counted in ``ft/elastic_reshards``); the attempt body is
    responsible for making the shrunk resume legal (the trainer's
    ``reshard=True`` restore-time regroup)."""
    sink_ = sink if sink is not None else NullSink()
    metrics_ = metrics if metrics is not None else MetricsRegistry()
    seen = {"n": None}

    def attempt():
        devices = list(devices_fn())
        if not devices:
            raise RuntimeError("supervise_elastic: no surviving devices")
        if seen["n"] is not None and len(devices) != seen["n"]:
            metrics_.counter("ft/elastic_reshards").inc()
            sink_.emit("ft/elastic", devices=len(devices),
                       previous=seen["n"])
            log(f"elastic restart: {seen['n']} -> {len(devices)} "
                f"device(s)")
        seen["n"] = len(devices)
        return make_attempt(devices)

    return supervise(attempt, budget=budget, restartable=restartable,
                     sink=sink_, metrics=metrics_, recorder=recorder,
                     log=log, sleep=sleep)


def supervise_train_elastic(cfg, steps: int, ckpt_dir: str, *,
                            mesh_of: Optional[Callable] = None,
                            devices_fn: Optional[Callable] = None,
                            budget: RestartBudget = RestartBudget(),
                            restartable: tuple = RESTARTABLE,
                            sink=None,
                            metrics: Optional[MetricsRegistry] = None,
                            recorder=None,
                            log: Callable[[str], None] = lambda s: None,
                            sleep: Callable[[float], None] = time.sleep,
                            **train_kw):
    """The elastic ``supervise_train``: each restart rebuilds the mesh
    from the surviving devices (``mesh_of(devices)``; default: an
    all-dp ``(n, 1)`` dp x sp mesh) and resumes training on it with
    ``reshard=True`` — a preempted-and-shrunk slice continues from
    ``latest_step`` with the ZeRO moment shards regrouped onto the
    shrunk plan instead of dying on the mesh-mismatch ``CommError``.

    The data trajectory must survive the mesh change, so ``batch`` and
    ``seq`` are pinned up front: from an existing checkpoint's metadata
    when one is present, else from the INITIAL mesh's defaults — a
    shrunk restart then replays the same stream (global batch constant;
    it must stay divisible by every surviving ``|dp|``)."""
    import jax

    from tpuscratch.runtime import checkpoint
    from tpuscratch.runtime.mesh import make_mesh

    devices_fn = devices_fn if devices_fn is not None else jax.devices
    if mesh_of is None:
        def mesh_of(devices):
            return make_mesh((len(devices), 1), ("dp", "sp"), devices)
    train_kw.setdefault("reshard", True)
    if recorder is not None:
        train_kw.setdefault("recorder", recorder)
    if "batch" not in train_kw or "seq" not in train_kw:
        if checkpoint.latest_step(ckpt_dir) is not None:
            _, meta = checkpoint.peek_metadata(ckpt_dir)
            batch, seq = meta.get("batch"), meta.get("seq")
        else:
            shape = dict(mesh_of(list(devices_fn())).shape)
            batch = 2 * shape.get("dp", 1)
            seq = 8 * shape.get("sp", 1)
        if batch is not None:
            train_kw.setdefault("batch", batch)
        if seq is not None:
            train_kw.setdefault("seq", seq)

    from tpuscratch.models.trainer import train  # lazy: avoids the cycle

    def make_attempt(devices):
        return train(mesh_of(devices), cfg, steps, ckpt_dir, **train_kw)

    return supervise_elastic(make_attempt, devices_fn=devices_fn,
                             budget=budget, restartable=restartable,
                             sink=sink, metrics=metrics,
                             recorder=recorder, log=log, sleep=sleep)


def supervise_train(mesh, cfg, steps: int, ckpt_dir: str, *,
                    budget: RestartBudget = RestartBudget(),
                    restartable: tuple = RESTARTABLE,
                    sink=None, metrics: Optional[MetricsRegistry] = None,
                    recorder=None,
                    log: Callable[[str], None] = lambda s: None,
                    sleep: Callable[[float], None] = time.sleep,
                    **train_kw):
    """:func:`supervise` around ``models.trainer.train`` — each restart
    re-invokes ``train`` with the same arguments, which resumes from
    ``latest_step(ckpt_dir)`` and replays deterministically (the
    bit-identical contract ``tests/test_trainer.py`` proves).  A chaos
    plan passed via ``train_kw['chaos']`` persists ACROSS restarts, so a
    ``times``-bounded fault consumed before the preemption stays
    consumed in the replay.  A ``recorder`` is shared with the trainer
    (every restart's chunks land on ONE flight-recorder timeline, with
    the restart instants between them).  Returns
    ``(params, TrainReport)`` of the completing invocation."""
    from tpuscratch.models.trainer import train_program  # lazy: cycle

    if recorder is not None:
        train_kw.setdefault("recorder", recorder)

    def factory():
        return train_program(mesh, cfg, steps, ckpt_dir, **train_kw)

    return supervise_program(factory, budget=budget,
                             restartable=restartable, sink=sink,
                             metrics=metrics, recorder=recorder,
                             log=log, sleep=sleep)
