"""The preemption supervisor: restart, restore, replay — bounded.

``mpierr.h``'s ABORT policy ends the job on the first failure; the
supervisor is the inverse contract for failures that are the STEADY
state of large runs (preempted slices, transient comm faults, flaky
checkpoint IO): catch the restartable class, back off, re-invoke — and
let the checkpoint layer's resume-from-``latest_step`` plus the
trainer's bit-identical replay contract turn "the job died" into "the
job continued".  The restart budget is the supervisor's own bounded
rung: a failure that keeps recurring past it escalates to the caller
(``RestartsExhausted``), the same discipline as ``guards``.

Events + metrics flow through ``obs``: one ``ft/restart`` per caught
failure, ``ft/run`` on completion, counters in the (optional) registry.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, TypeVar

from tpuscratch.ft.chaos import Preempted
from tpuscratch.ft.retry import jittered_backoff
from tpuscratch.obs.metrics import MetricsRegistry
from tpuscratch.obs.sink import NullSink
from tpuscratch.runtime.errors import CommError

T = TypeVar("T")

#: failures worth re-invoking for, by default: preemptions (the run was
#: healthy), comm-layer faults (transient by assumption — the bounded
#: budget is what makes that assumption safe), and IO errors (flaky
#: filesystem under the checkpoint dir).  GuardFailure is deliberately
#: absent: a poisoned data stream does not heal by restarting.
RESTARTABLE = (Preempted, CommError, OSError)


class RestartsExhausted(RuntimeError):
    """The restart budget is spent — chained to the last failure."""


@dataclasses.dataclass(frozen=True)
class RestartBudget:
    """How many re-invocations, and how fast: exponential backoff from
    ``backoff_s`` capped at ``max_backoff_s``, jittered deterministically
    from ``seed`` (``ft.retry.jittered_backoff`` — the same formula the
    retry policy sleeps on)."""

    max_restarts: int = 3
    backoff_s: float = 0.0
    max_backoff_s: float = 5.0
    jitter: float = 0.1
    seed: int = 0

    def delay(self, restart: int) -> float:
        return jittered_backoff(self.seed, restart - 1, self.backoff_s,
                                2.0, self.max_backoff_s, self.jitter)


def supervise(
    fn: Callable[[], T],
    *,
    budget: RestartBudget = RestartBudget(),
    restartable: tuple = RESTARTABLE,
    sink=None,
    metrics: Optional[MetricsRegistry] = None,
    recorder=None,
    log: Callable[[str], None] = lambda s: None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run ``fn()`` under the restart loop; return its result.

    ``fn`` must be RE-INVOCABLE: each call picks up where the last left
    off (the trainer does, via ``ckpt_dir`` resume — that is the whole
    design of the checkpoint layer).  Failures outside ``restartable``
    propagate immediately; restartable ones are counted, emitted as
    ``ft/restart`` events (with the ``backoff_s`` about to be slept —
    the goodput report's "restart" badput bucket), backed off, and
    re-invoked until the budget runs out (``RestartsExhausted``).
    ``recorder`` (an ``obs.trace.FlightRecorder``) additionally marks
    each restart as an instant on the flight-recorder timeline."""
    sink = sink if sink is not None else NullSink()
    metrics = metrics if metrics is not None else MetricsRegistry()
    restarts = 0
    t0 = time.perf_counter()
    while True:
        try:
            out = fn()
        except restartable as exc:
            if restarts >= budget.max_restarts:
                # a give-up is NOT a restart: fn() will not be re-invoked,
                # so neither the counter nor an ft/restart event fires
                sink.emit("ft/give_up", restarts=restarts,
                          error=f"{type(exc).__name__}: {exc}")
                sink.flush()
                raise RestartsExhausted(
                    f"restart budget {budget.max_restarts} exhausted"
                ) from exc
            restarts += 1
            metrics.counter("ft/restarts").inc()
            op = getattr(exc, "op", None) or getattr(exc, "site", None)
            log(f"supervisor restart {restarts}/{budget.max_restarts}: "
                f"{type(exc).__name__}: {exc}")
            d = budget.delay(restarts)
            if recorder is not None:
                recorder.instant("ft/restart", restart=restarts,
                                 error=type(exc).__name__)
            if d > 0:
                sleep(d)
            # emitted AFTER the backoff: duration-carrying events are
            # stamped at the END of their activity (the goodput
            # convention), so [t - backoff_s, t] is the slept window
            sink.emit(
                "ft/restart", restart=restarts,
                error=f"{type(exc).__name__}: {exc}",
                backoff_s=round(d, 6),
                **({"op": op} if op else {}),
            )
            continue
        sink.emit(
            "ft/run", restarts=restarts,
            wall_s=round(time.perf_counter() - t0, 6),
        )
        sink.flush()
        return out


def supervise_train(mesh, cfg, steps: int, ckpt_dir: str, *,
                    budget: RestartBudget = RestartBudget(),
                    restartable: tuple = RESTARTABLE,
                    sink=None, metrics: Optional[MetricsRegistry] = None,
                    recorder=None,
                    log: Callable[[str], None] = lambda s: None,
                    sleep: Callable[[float], None] = time.sleep,
                    **train_kw):
    """:func:`supervise` around ``models.trainer.train`` — each restart
    re-invokes ``train`` with the same arguments, which resumes from
    ``latest_step(ckpt_dir)`` and replays deterministically (the
    bit-identical contract ``tests/test_trainer.py`` proves).  A chaos
    plan passed via ``train_kw['chaos']`` persists ACROSS restarts, so a
    ``times``-bounded fault consumed before the preemption stays
    consumed in the replay.  A ``recorder`` is shared with the trainer
    (every restart's chunks land on ONE flight-recorder timeline, with
    the restart instants between them).  Returns
    ``(params, TrainReport)`` of the completing invocation."""
    from tpuscratch.models.trainer import train  # lazy: avoids the cycle

    if recorder is not None:
        train_kw.setdefault("recorder", recorder)

    def attempt():
        return train(mesh, cfg, steps, ckpt_dir, **train_kw)

    return supervise(attempt, budget=budget, restartable=restartable,
                     sink=sink, metrics=metrics, recorder=recorder,
                     log=log, sleep=sleep)
