"""Guarded training policy: the host half of the in-step health guard.

The device half lives INSIDE the compiled train step
(``models.transformer.train_step(..., guard=(clip_norm, spike_factor))``):
an all-axis ``comm.collectives`` reduce of the local isfinite flag (loss
and gradient — a NaN/Inf in any leaf propagates into the global grad
norm), a loss-spike check against the caller-fed reference loss, an
in-program clip of over-norm gradients, and a ``where``-select that
passes params (and optimizer state) through UNCHANGED on a skipped step
— one extra int32 status scalar out.  When no guard is requested the
step body is byte-identical to the unguarded one.

This module holds the policy knobs and the host-side escalation ladder
the trainer runs on the statuses it reads back each chunk:

    skip-step (in-program, free)        — a non-finite or spiking step
                                          applies nothing;
    clip (in-program, counted)          — an over-norm but finite step
                                          applies the clipped update;
    rollback-to-last-checkpoint (host)  — MORE than ``max_skips``
                                          consecutive skips (the
                                          tolerated streak) means the
                                          stream is poisoned, not
                                          glitched: restore and replay.

Every rung is bounded and counted: ``max_rollbacks`` exceeded raises
:class:`GuardFailure` — at that point the run needs a human, not a
policy.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

#: the status vocabulary of the guarded step's extra scalar output
STATUS_OK, STATUS_CLIPPED, STATUS_SKIPPED = 0, 1, 2


class GuardFailure(RuntimeError):
    """The rollback budget is spent and steps still skip — the bounded
    end of the escalation ladder (deliberately NOT restartable by the
    supervisor: replaying a poisoned stream forever is the livelock this
    package exists to prevent)."""


@dataclasses.dataclass(frozen=True)
class GuardPolicy:
    """Knobs for the guarded train step + escalation ladder.

    ``clip_norm``/``spike_factor`` are compiled INTO the step (inf
    disables each check at zero cost — ``where`` against an inf
    threshold); ``max_skips``/``max_rollbacks`` bound the host ladder."""

    clip_norm: float = math.inf      # grad-norm above this → clipped
    spike_factor: float = math.inf   # loss > factor * ref_loss → skipped
    max_skips: int = 2               # consecutive skips before rollback
    max_rollbacks: int = 1           # rollbacks before GuardFailure

    def step_guard(self) -> tuple[float, float]:
        """The (clip_norm, spike_factor) pair the step builders take."""
        return (self.clip_norm, self.spike_factor)


class GuardState:
    """Counts statuses and decides escalation; one per training run."""

    def __init__(self, policy: GuardPolicy):
        self.policy = policy
        self.skips = 0
        self.clips = 0
        self.rollbacks = 0
        self.streak = 0   # CONSECUTIVE skips, carried across chunks

    def observe(self, statuses: Sequence[int]) -> bool:
        """Fold one chunk's per-step statuses in; True ⇒ the chunk must
        be rolled back (discarded, restored, replayed)."""
        need_rollback = False
        for s in statuses:
            if s == STATUS_SKIPPED:
                self.skips += 1
                self.streak += 1
                if self.streak > self.policy.max_skips:
                    need_rollback = True
            else:
                self.streak = 0
                if s == STATUS_CLIPPED:
                    self.clips += 1
        return need_rollback

    def rolled_back(self) -> None:
        """Record one rollback; raises :class:`GuardFailure` past the
        budget.  Resets the skip streak — the replay gets a fresh run at
        the ladder."""
        self.rollbacks += 1
        self.streak = 0
        if self.rollbacks > self.policy.max_rollbacks:
            raise GuardFailure(
                f"guard rolled back {self.rollbacks} times "
                f"(budget {self.policy.max_rollbacks}) and steps still "
                f"skip — {self.skips} skipped, {self.clips} clipped"
            )
