"""tpuscratch.ft — fault injection, guarded training, and supervision.

The reference's entire robustness story is ``mpierr.h``'s raise-or-abort
dual policy (ported as ``runtime.errors``): every failure is either an
exception or a job teardown.  The stack grown around it — checkpointed
trainer, continuous-batching serve engine, obs — has failure surfaces
that abort-on-error cannot serve: a preempted TPU slice, a NaN'd
gradient, a torn checkpoint write, a poison request that
deterministically fails prefill.  Production-scale systems treat failure
as the steady state (MegaScale-style fault tolerance, Bamboo-style
preemption resilience); this package is the subsystem that makes every
failure either retried, rolled back, degraded, or quarantined — and the
deterministic chaos harness that proves it:

- **chaos**      — ``ChaosPlan(seed, faults)``: a seeded, deterministic
  fault injector pluggable behind hooks in the trainer, the halo driver,
  the serve engine, and ``checkpoint.save`` (hooks compile to nothing
  when absent, the obs grad-norm contract).
- **guards**     — ``GuardPolicy`` + the host-side escalation ladder for
  the device-side finiteness/loss-spike guard folded into the compiled
  train step (``models.transformer`` ``guard=``): skip-step →
  clip → rollback-to-last-checkpoint, each bounded and counted.
- **retry**      — generic ``retry(fn, policy)`` with exponential
  backoff, deterministic jitter, and a wall-clock watchdog; used by
  checkpoint save/restore, ``native.hostpool`` allocation, and serve
  prefill.
- **supervisor** — ``supervise(fn)`` / ``supervise_train(...)``: the
  restart loop that catches preemptions and transient comm faults,
  resumes from ``latest_step`` (the bit-identical replay the trainer
  already proves), enforces a restart budget with backoff, and emits
  ``ft/restart`` / ``ft/rollback`` / ``ft/fault`` events through obs.
  ``supervise_elastic`` / ``supervise_train_elastic`` are the
  PREEMPTED-AND-SHRUNK form: each restart re-queries the surviving
  devices, rebuilds the mesh, and resumes with the ZeRO moment shards
  regrouped onto the shrunk plan (``models.zero.reshard_state`` via
  ``train(reshard=True)``) — capacity loss becomes a continuation, not
  a terminal ``CommError``.
"""

from tpuscratch.ft.chaos import (  # noqa: F401
    ChaosPlan,
    Fault,
    InjectedFault,
    Preempted,
    bind_sink,
)
from tpuscratch.ft.guards import (  # noqa: F401
    STATUS_CLIPPED,
    STATUS_OK,
    STATUS_SKIPPED,
    GuardFailure,
    GuardPolicy,
    GuardState,
)
from tpuscratch.ft.retry import (  # noqa: F401
    DEFAULT_SAVE_RETRY,
    RetryPolicy,
    RetryTimeout,
    WatchdogTimeout,
    retry,
)
from tpuscratch.ft.supervisor import (  # noqa: F401
    RestartBudget,
    RestartsExhausted,
    supervise,
    supervise_elastic,
    supervise_train,
    supervise_train_elastic,
)
