"""Bounded retry with exponential backoff, deterministic jitter, and a
wall-clock watchdog.

The generic transient-failure absorber the rest of ``ft`` builds on:
checkpoint save/restore (a flaky filesystem), ``native.hostpool``
allocation (a transiently-exhausted locked-page budget), and serve
prefill (a transient device error) all route through :func:`retry`.
Jitter is DETERMINISTIC — drawn from ``SeedSequence([seed, attempt])``,
never from wall clock — so a chaos test's retry timeline is replayable;
the watchdog abandons a stalled attempt (``attempt_timeout_s``, thread
side-car) and bounds the whole call (``timeout_s``) so a hung save can
never wedge the supervisor's restart loop.  An abandoned attempt KEEPS
RUNNING on its daemon thread — only wrap calls that tolerate a zombie
duplicate: ``checkpoint.save`` qualifies (same-step publishes are
idempotent and its overwrite asides are call-unique, so a zombie and
its retry never collide on a path), arbitrary stateful calls may not.

This module is jax-free and imports only ``runtime.errors``; logs name
the failing op via ``CommError.op`` when the exception carries one.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable, Optional, TypeVar

import numpy as np

T = TypeVar("T")


class RetryTimeout(TimeoutError):
    """The TOTAL wall-clock budget (``timeout_s``) ran out between
    attempts — raised chained to the last failure."""

    def __init__(self, op: str, elapsed_s: float, budget_s: float):
        self.op = op
        super().__init__(
            f"{op}: retry budget exhausted after {elapsed_s:.3f}s "
            f"(timeout {budget_s:.3f}s)"
        )


class WatchdogTimeout(TimeoutError):
    """One attempt exceeded ``attempt_timeout_s`` and was abandoned (the
    stalled call keeps running on its daemon side-car thread; its late
    result is dropped).  A ``TimeoutError`` → retryable by default."""

    def __init__(self, op: str, timeout_s: float):
        self.op = op
        super().__init__(f"{op}: attempt exceeded watchdog {timeout_s:.3f}s")


def jittered_backoff(seed: int, n: int, base_s: float, multiplier: float,
                     max_s: float, jitter: float) -> float:
    """The ONE exponential-backoff-with-deterministic-jitter formula
    (``RetryPolicy.delay`` and the supervisor's ``RestartBudget.delay``
    both route here): ``base_s * multiplier**n`` capped at ``max_s``,
    scaled by a seeded uniform draw in ``±jitter`` — a pure function of
    ``(seed, n)``, never of wall clock, so a chaos test's backoff
    timeline is replayable."""
    d = min(max_s, base_s * multiplier ** n)
    if jitter and d > 0:
        ss = np.random.SeedSequence([seed, n])
        u = float(np.random.default_rng(ss).random())  # [0, 1)
        d *= 1.0 + jitter * (2.0 * u - 1.0)
    return max(0.0, d)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How hard to try: attempts, backoff curve, jitter seed, watchdogs.

    ``delay(attempt)`` is a pure function of the policy
    (:func:`jittered_backoff`), so two runs with the same policy sleep
    the same schedule (the chaos determinism contract)."""

    max_attempts: int = 3
    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1                       # fraction of the delay
    seed: int = 0
    timeout_s: Optional[float] = None         # total wall budget
    attempt_timeout_s: Optional[float] = None  # per-attempt watchdog
    retryable: tuple = (Exception,)

    def delay(self, attempt: int) -> float:
        return jittered_backoff(self.seed, attempt, self.base_s,
                                self.multiplier, self.max_s, self.jitter)


#: the checkpoint-save policy the trainer and halo driver share when a
#: chaos plan is attached and the caller gave no explicit policy:
#: absorb transient IO faults fast, fail within ~a tenth of a second
DEFAULT_SAVE_RETRY = RetryPolicy(max_attempts=3, base_s=0.01, max_s=0.1)


def _call_with_watchdog(fn: Callable[[], T], timeout_s: float, op: str) -> T:
    box: dict = {}

    def run():
        try:
            box["value"] = fn()
        except BaseException as exc:  # relayed to the caller thread
            box["error"] = exc

    t = threading.Thread(target=run, daemon=True, name=f"ft-watchdog:{op}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise WatchdogTimeout(op, timeout_s)
    if "error" in box:
        raise box["error"]
    return box["value"]


def retry(fn: Callable[[], T], policy: RetryPolicy = RetryPolicy(), *,
          op: Optional[str] = None,
          log: Callable[[str], None] = lambda s: None,
          sleep: Callable[[float], None] = time.sleep) -> T:
    """Call ``fn()`` under ``policy``; return its result or re-raise the
    last failure once attempts (or the wall budget) are exhausted.

    ``op`` names the call in logs and timeout errors; an exception that
    carries its own ``.op`` (a ``CommError``, a guarded block's wrap)
    wins, so retry logs name the actual failing op, not the call site's
    guess."""
    name = op or getattr(fn, "__name__", "call")
    t0 = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(max(1, policy.max_attempts)):
        elapsed = time.monotonic() - t0
        if policy.timeout_s is not None and elapsed > policy.timeout_s:
            raise RetryTimeout(name, elapsed, policy.timeout_s) from last
        try:
            if policy.attempt_timeout_s is None:
                return fn()
            return _call_with_watchdog(fn, policy.attempt_timeout_s, name)
        except policy.retryable as exc:
            last = exc
            failing = getattr(exc, "op", None) or name
            log(
                f"retry {attempt + 1}/{policy.max_attempts} "
                f"[{failing}]: {type(exc).__name__}: {exc}"
            )
            if attempt + 1 >= policy.max_attempts:
                break
            d = policy.delay(attempt)
            if policy.timeout_s is not None:
                # never sleep past the wall budget
                d = min(d, max(0.0, policy.timeout_s -
                               (time.monotonic() - t0)))
            if d > 0 and math.isfinite(d):
                sleep(d)
    assert last is not None
    raise last
