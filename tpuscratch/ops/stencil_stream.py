"""Deep-z streamed 3D stencil: k Jacobi substeps per HBM pass, manual
double-buffered DMA streaming.

Why this exists — the measured DMA bound (round 4, v5e, 256x512x512 f32,
marginal ms/step by step-count differencing):

- XLA fused elementwise 1-read+1-write: 0.94 ms (~568 GB/s rd+wr)
- ONE monolithic HBM->HBM DMA:          1.64 ms (~327 GB/s)
- 2/4/8 CONCURRENT slab DMAs:           1.59-1.77 ms (~300-340 GB/s)
- manual double-buffered VMEM bounce,
  every band/buffer-depth shape raced:  1.58-1.70 ms (~315-340 GB/s)

i.e. ~330 GB/s is the chip's TOTAL DMA-fabric copy rate — independent of
queue count, window shape, or buffering depth — so every DMA-driven
Pallas form (the standard BlockSpec pipeline included) floors at ~1.6
ms/step for a 268 MB grid, and no amount of pipeline re-plumbing moves
it.  The lever that DOES move it is arithmetic intensity: fold ``depth``
Jacobi substeps into one read+write pass so the per-step HBM traffic
divides by ``depth``.  This is the framework's own 2D deep-halo
trapezoid (halo/stencil.py ``deep:k``) one dimension up, fused with the
manual-DMA streaming the round-3 verdict asked for.  The reference's
analogue is the exchange serving any ghost depth
(/root/reference/stencil2d/stencil2D.h:116-117) while moving strided
data without materializing it (stencil2D.h:210-228).

Scheme: the core streams through VMEM in z-bands.  Each band's read
window carries ``depth`` extra planes per side (G-coords over the
ghosted array [a_mz | core | a_pz]); ``depth`` ring-decomposed 7-point
substeps shrink the window by one plane per side each, landing exactly
the band's final planes, which stream back out.

Chip rule (round-5, chip-probed): the kernel family is a Mosaic
remote-compile DNF for plane widths cx < 128 on silicon (sub-lane-tile
planes; the CPU interpreter accepts them) — callers that may see small
cores (the multigrid coarse levels) must gate on cx >= 128.  The z ghosts arrive as
small (depth, cy, cx) VMEM inputs patched into the first/last windows —
never a separate DMA channel.  y/x must self-wrap (degenerate periodic
axes): their ghost lines are read from the band's own planes, the same
economy as ``seven_point_assembled_pallas``; distributed y/x axes use
``compact-asm`` instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuscratch.ops.common import interpret_params, mosaic_params, use_interpret
from tpuscratch.ops.stencil_kernel import _asm3d_compute, _largest_divisor_band


_VMEM_CEILING = 100 << 20
#: the 27-point substep's temp pressure adds to the buffer footprint.
#: Round 4 (per-dz accumulating stores): band=8 at 512^2 planes was a
#: Mosaic remote-compile DNF and a 48 MB ceiling forced band=4.  Round
#: 5's y-split single-store substep (ysplit27=4) halves-and-halves the
#: live temps: band=8 compiles and runs on chip at 3.510 ms/step vs the
#: round-4 form's 4.861 (256x512x512, k=2) — this ceiling now lands the
#: chooser on band=8 for k=2 at 512^2 planes
_VMEM_CEILING_27 = 72 << 20


def weight_cube(coeffs27, offsets26) -> tuple:
    """Map OFFSETS26-ordered coefficients (+ center last) to a nested
    (3, 3, 3) tuple W[dz+1][dy+1][dx+1] — the static layout the kernel's
    27-point substep unrolls over."""
    W = [[[0.0] * 3 for _ in range(3)] for _ in range(3)]
    for (dz, dy, dx), cw in zip(offsets26, coeffs27[:-1]):
        W[dz + 1][dy + 1][dx + 1] = float(cw)
    W[1][1][1] = float(coeffs27[-1])
    return tuple(tuple(tuple(r) for r in p) for p in W)


def _substep27(o_ref, t, P: int, cy: int, cx: int, W, ysplit: int = 4):
    """One 27-point substep on a (P, cy, cx) window value: for each
    output plane, the three dz-shifted planes each contribute a 9-point
    with periodic y/x wrap — ring-decomposed exactly like the 7-point
    (_asm3d_compute): pure shifted slices in the interior, line-sized
    wrapped concats on the four borders.  On z-slab meshes the
    full-extent ghost slabs carry the edge/corner neighbor data
    implicitly, which is why 26-neighbor exchange machinery is not
    needed on this path.

    ``ysplit``: the interior is computed in that many y-chunks, each a
    single 27-term store (round 5 — was one accumulating store per dz
    slab).  The chunking caps live temps at a fraction of the plane
    (what the per-dz store boundaries did) while writing each output
    element ONCE instead of read-modify-writing it three times.
    ``ysplit=0`` selects the round-4 per-dz-slab form (kept for the
    race/regression harness)."""
    slabs = (t[0 : P - 2], t[1 : P - 1], t[2:P])  # dz = -1, 0, +1

    def shx(line, dx):
        # x-shift with periodic wrap on a (n, 1, cx) line
        if dx == 0:
            return line
        if dx < 0:
            return jnp.concatenate([line[:, :, -1:], line[:, :, :-1]], axis=2)
        return jnp.concatenate([line[:, :, 1:], line[:, :, :1]], axis=2)

    if ysplit:
        # interior in y-chunks: one fused 27-term store per chunk
        n_in = cy - 2
        step = -(-n_in // ysplit)
        lo = 1
        while lo < cy - 1:
            hi = min(lo + step, cy - 1)
            acc = None
            for iz, u in enumerate(slabs):
                for dy in (-1, 0, 1):
                    for dx in (-1, 0, 1):
                        cw = W[iz][dy + 1][dx + 1]
                        term = cw * u[
                            :, lo + dy : hi + dy, 1 + dx : cx - 1 + dx
                        ]
                        acc = term if acc is None else acc + term
            o_ref[:, lo:hi, 1 : cx - 1] = acc
            lo = hi
    else:
        # round-4 form: one accumulating STORE per dz slab — the store
        # boundaries cap live temps at one 9-term sum, at the price of
        # 3x output-buffer read-modify-write traffic
        for iz, u in enumerate(slabs):
            acc = None
            for dy in (-1, 0, 1):
                for dx in (-1, 0, 1):
                    cw = W[iz][dy + 1][dx + 1]
                    term = cw * u[
                        :, 1 + dy : cy - 1 + dy, 1 + dx : cx - 1 + dx
                    ]
                    acc = term if acc is None else acc + term
            if iz == 0:
                o_ref[:, 1 : cy - 1, 1 : cx - 1] = acc
            else:
                o_ref[:, 1 : cy - 1, 1 : cx - 1] = (
                    o_ref[:, 1 : cy - 1, 1 : cx - 1] + acc
                )

    # top / bottom rows: y wraps to the slab's own far rows, x wrap by
    # line concat (the corner cells fall out of the wrapped shifts)
    for row, ys in ((0, (cy - 1, 0, 1)), (cy - 1, (cy - 2, cy - 1, 0))):
        acc = None
        for iz, u in enumerate(slabs):
            for dy, ysrc in zip((-1, 0, 1), ys):
                line = u[:, ysrc : ysrc + 1, :]
                for dx in (-1, 0, 1):
                    term = W[iz][dy + 1][dx + 1] * shx(line, dx)
                    acc = term if acc is None else acc + term
        o_ref[:, row : row + 1, :] = acc

    # left / right columns (interior rows only): y by plain slices, x
    # wraps to the slab's own far columns
    for col, xs in ((0, (cx - 1, 0, 1)), (cx - 1, (cx - 2, cx - 1, 0))):
        acc = None
        for iz, u in enumerate(slabs):
            for dx, xsrc in zip((-1, 0, 1), xs):
                colv = u[:, :, xsrc : xsrc + 1]
                for dy in (-1, 0, 1):
                    term = (
                        W[iz][dy + 1][dx + 1]
                        * colv[:, 1 + dy : cy - 1 + dy, :]
                    )
                    acc = term if acc is None else acc + term
        o_ref[:, 1 : cy - 1, col : col + 1] = acc


def _sub7_interior(E, P: int, w):
    """7-point update of an extended (P, R+2, C+2) value's interior:
    returns (P-2, R, C).  The extended array's CORNER cells are never
    read (no diagonal terms), so callers may pad them with garbage."""
    up, dn, c = E[0 : P - 2], E[2:P], E[1 : P - 1]
    return (
        w[0] * up[:, 1:-1, 1:-1] + w[1] * dn[:, 1:-1, 1:-1]
        + w[2] * c[:, 0:-2, 1:-1] + w[3] * c[:, 2:, 1:-1]
        + w[4] * c[:, 1:-1, 0:-2] + w[5] * c[:, 1:-1, 2:]
        + w[6] * c[:, 1:-1, 1:-1]
    )


def _age3d_strips(t, gyv, gxv, gcv, P: int, cy: int, cx: int, k: int, w,
                  ghost_y: bool, ghost_x: bool):
    """One 7-point substep of the 3D ghost strips (round 5 — the 2D
    ghost-strip scheme lifted one dimension up, VERDICT r4 missing #3).

    Strip layouts mirror the 2D [plus | minus] convention: ``gyv``
    (P, 2k, cx) rows = [global y in [cy, cy+k) | [-k, 0)]; ``gxv``
    (P, cy, 2k) columns likewise; ``gcv`` (P, 2k, 2k) is the xy-corner
    strip (rows like gy, columns like gx), needed because strip aging
    reads across the y/x ghost corner even though the 7-point core
    never does.  Each strip's extended neighborhood is assembled from
    LINE-sized pieces (its outer neighbors are real core edge lines or
    the sibling strips), so no full-window lane concat ever happens —
    the economy the 2D chip race forced.  Internal [plus | minus] seams
    corrupt one cell per side per substep, the ghost budget k buys.
    Returns (gy', gx', gc') at z-extent P - 2 (None where not
    carried)."""
    gy2 = gx2 = gc2 = None
    if ghost_y:
        ext = jnp.concatenate(
            [t[:, cy - 1 : cy, :], gyv, t[:, 0:1, :]], axis=1
        )  # (P, 2k+2, cx)
        if ghost_x:
            wcol = jnp.concatenate(
                [gxv[:, cy - 1 : cy, 2 * k - 1 : 2 * k],
                 gcv[:, :, 2 * k - 1 : 2 * k],
                 gxv[:, 0:1, 2 * k - 1 : 2 * k]], axis=1)
            ecol = jnp.concatenate(
                [gxv[:, cy - 1 : cy, 0:1], gcv[:, :, 0:1],
                 gxv[:, 0:1, 0:1]], axis=1)
        else:  # x self-wraps
            wcol, ecol = ext[:, :, cx - 1 : cx], ext[:, :, 0:1]
        E = jnp.concatenate([wcol, ext, ecol], axis=2)
        gy2 = _sub7_interior(E, P, w)
    if ghost_x:
        ext = jnp.concatenate(
            [t[:, :, cx - 1 : cx], gxv, t[:, :, 0:1]], axis=2
        )  # (P, cy, 2k+2)
        if ghost_y:
            nrow = jnp.concatenate(
                [gyv[:, 2 * k - 1 : 2 * k, cx - 1 : cx],
                 gcv[:, 2 * k - 1 : 2 * k, :],
                 gyv[:, 2 * k - 1 : 2 * k, 0:1]], axis=2)
            srow = jnp.concatenate(
                [gyv[:, 0:1, cx - 1 : cx], gcv[:, 0:1, :],
                 gyv[:, 0:1, 0:1]], axis=2)
        else:  # y self-wraps
            nrow, srow = ext[:, cy - 1 : cy, :], ext[:, 0:1, :]
        E = jnp.concatenate([nrow, ext, srow], axis=1)
        gx2 = _sub7_interior(E, P, w)
    if ghost_y and ghost_x:
        inner = jnp.concatenate(
            [gyv[:, :, cx - 1 : cx], gcv, gyv[:, :, 0:1]], axis=2
        )  # (P, 2k, 2k+2)
        # E-corner cells are unread: pad the gx edge rows with edge dups
        rowN = jnp.concatenate(
            [gxv[:, cy - 1 : cy, 0:1], gxv[:, cy - 1 : cy, :],
             gxv[:, cy - 1 : cy, 2 * k - 1 : 2 * k]], axis=2)
        rowS = jnp.concatenate(
            [gxv[:, 0:1, 0:1], gxv[:, 0:1, :],
             gxv[:, 0:1, 2 * k - 1 : 2 * k]], axis=2)
        E = jnp.concatenate([rowN, inner, rowS], axis=1)
        gc2 = _sub7_interior(E, P, w)
    return gy2, gx2, gc2


def _stream_kernel(flags_ref, mz_ref, pz_ref, gy_ref, gx_ref, gc_ref,
                   in_hbm, rhs_hbm, out_hbm, rbuf, ping, pong, gyping,
                   gypong, gxping, gxpong, gcping, gcpong, frbuf, wbuf,
                   rsem, fsem, wsem, *,
                   band: int, depth: int, nb: int,
                   nbuf: int, cy: int, cx: int, coeffs7, carry_tail: bool,
                   ysplit27: int = 4, ghost_y: bool = False,
                   ghost_x: bool = False, has_rhs: bool = False,
                   rhs_coeff: float = 0.0):
    k, P0 = depth, band + 2 * depth
    w = coeffs7

    if has_rhs:
        # rhs windows are UNIFORM (the caller pre-ghosts rhs to
        # (cz + 2k, cy, cx), so window [b*band, b*band + P0) is exact
        # and in-bounds for every band, first and last included)
        def rd_f(slot, b):
            return pltpu.make_async_copy(
                rhs_hbm.at[pl.ds(b * band, P0)], frbuf.at[slot],
                fsem.at[slot])

    if carry_tail:
        # successive windows overlap by 2k planes; each band hands its
        # tail to the next band's head by a VMEM copy, so the DMA reads
        # each core plane ONCE per pass (read traffic 1x core instead of
        # (band+2k)/band x) — requires nbuf == 2 and band > depth
        def rd(slot, b):
            # the non-overlapping remainder: core[b*band + k, +band)
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(b * band + k, band)],
                rbuf.at[slot, pl.ds(2 * k, band)], rsem.at[slot])

        def rd_last(slot):
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(nb * band - band + k, band - k)],
                rbuf.at[slot, pl.ds(2 * k, band - k)], rsem.at[slot])
    else:
        def rd(slot, b):
            # window over G = [mz | core | pz] at s0 = b*band, length P0;
            # the core part only — ghost planes are patched in from VMEM
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(b * band - k, P0)], rbuf.at[slot],
                rsem.at[slot])

        def rd_last(slot):
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(nb * band - band - k, band + k)],
                rbuf.at[slot, pl.ds(0, band + k)], rsem.at[slot])

    def rd_first(slot):
        return pltpu.make_async_copy(
            in_hbm.at[pl.ds(0, band + k)],
            rbuf.at[slot, pl.ds(k, band + k)], rsem.at[slot])

    def wr(slot, b):
        return pltpu.make_async_copy(
            wbuf.at[slot], out_hbm.at[pl.ds(b * band, band)], wsem.at[slot])

    # warmup: bands 0..nbuf-1 (nb >= 2 is enforced by the dispatcher)
    rd_first(0).start()
    if has_rhs:
        rd_f(0, 0).start()
    for i in range(1, min(nbuf, nb)):
        if i == nb - 1:
            rd_last(i).start()
        else:
            rd(i, i).start()
        if has_rhs:
            rd_f(i, i).start()

    def body(b, loop_carry):
        slot = jax.lax.rem(b, nbuf)

        @pl.when(b == 0)
        def _():
            rd_first(slot).wait()
            rbuf[slot, 0:k] = mz_ref[:]

        @pl.when(b == nb - 1)
        def _():
            rd_last(slot).wait()
            rbuf[slot, band + k:] = pz_ref[:]

        @pl.when(jnp.logical_and(b > 0, b < nb - 1))
        def _():
            rd(slot, b).wait()

        if has_rhs:
            rd_f(slot, b).wait()

        if carry_tail:
            # hand this window's 2k-plane tail to the next band's head
            # (its DMA, already in flight, fills only [2k:])
            @pl.when(b < nb - 1)
            def _():
                other = jax.lax.rem(b + 1, nbuf)
                rbuf[other, pl.ds(0, 2 * k)] = rbuf[slot, pl.ds(band, 2 * k)]

        @pl.when(b >= nbuf)
        def _():
            wr(slot, b - nbuf).wait()

        # depth ring-decomposed substeps, one plane shed per side each:
        # src coord j at substep s is window coord j + s
        ghost = ghost_y or ghost_x
        if ghost:
            # this window's strip segments (strip z-row i = global
            # plane i - k; the window starts at global b*band - k)
            gyv = gy_ref[pl.ds(b * band, P0)] if ghost_y else None
            gxv = gx_ref[pl.ds(b * band, P0)] if ghost_x else None
            gcv = (gc_ref[pl.ds(b * band, P0)]
                   if (ghost_y and ghost_x) else None)
        for s in range(k):
            P = P0 - 2 * s
            last = s == k - 1
            src = rbuf.at[slot] if s == 0 else (ping if s % 2 else pong)
            dst = wbuf.at[slot] if last else (pong if s % 2 else ping)
            t = src[pl.ds(0, P)] if s else src[:]
            o_ref = dst.at[pl.ds(0, P - 2)] if not last else dst
            if len(w) == 3:  # (3,3,3) weight cube: the 27-point form
                _substep27(o_ref, t, P, cy, cx, w, ysplit27)
            else:
                c = t[1 : P - 1]
                if ghost_y:
                    gym = gyv[1 : P - 1]
                    my, py = gym[:, 2 * k - 1 : 2 * k, :], gym[:, 0:1, :]
                else:
                    my, py = c[:, cy - 1 : cy, :], c[:, 0:1, :]
                if ghost_x:
                    gxm = gxv[1 : P - 1]
                    mx, px = gxm[:, :, 2 * k - 1 : 2 * k], gxm[:, :, 0:1]
                else:
                    mx, px = c[:, :, cx - 1 : cx], c[:, :, 0:1]
                fv = (frbuf[slot, pl.ds(s + 1, P - 2)] if has_rhs
                      else None)
                _asm3d_compute(
                    o_ref,
                    t[0 : P - 2], t[2:P], c,
                    my, py, mx, px,
                    cy, cx, w,
                    fterm=fv, fc=rhs_coeff,
                )
            if ghost and not last:
                # age the strips alongside the window
                gy2, gx2, gc2 = _age3d_strips(
                    t, gyv, gxv, gcv, P, cy, cx, k, w, ghost_y, ghost_x
                )
                if ghost_y:
                    gydst = gypong if s % 2 else gyping
                    gydst[pl.ds(0, P - 2)] = gy2
                if ghost_x:
                    gxdst = gxpong if s % 2 else gxping
                    gxdst[pl.ds(0, P - 2)] = gx2
                if ghost_y and ghost_x:
                    gcdst = gcpong if s % 2 else gcping
                    gcdst[pl.ds(0, P - 2)] = gc2
            # OPEN boundaries re-impose the zero-ghost condition every
            # substep: the k-s-1 cells still acting as ghosts after
            # substep s+1 must stay zero on physical-end ranks (the
            # flags are per-rank traced scalars — interior ranks' ghost
            # data is real neighbor state and rightly evolves).
            # flags: [z-, z+, y-, y+, x-, x+]
            g = k - s - 1
            if g > 0:
                z = jnp.zeros((g, cy, cx), mz_ref.dtype)

                @pl.when(jnp.logical_and(flags_ref[0] == 1, b == 0))
                def _(dst=dst, z=z):
                    dst[pl.ds(0, g)] = z

                @pl.when(jnp.logical_and(flags_ref[1] == 1, b == nb - 1))
                def _(dst=dst, z=z, P=P):
                    dst[pl.ds(P - 2 - g, g)] = z

                # z-open also pins the strips' z-end planes
                if ghost:
                    strip_dsts = []
                    if ghost_y:
                        strip_dsts.append((gydst, (g, 2 * k, cx)))
                    if ghost_x:
                        strip_dsts.append((gxdst, (g, cy, 2 * k)))
                    if ghost_y and ghost_x:
                        strip_dsts.append((gcdst, (g, 2 * k, 2 * k)))
                    for gdst, shape in strip_dsts:
                        zg = jnp.zeros(shape, mz_ref.dtype)

                        @pl.when(jnp.logical_and(flags_ref[0] == 1,
                                                 b == 0))
                        def _(gdst=gdst, zg=zg):
                            gdst[pl.ds(0, g)] = zg

                        @pl.when(jnp.logical_and(flags_ref[1] == 1,
                                                 b == nb - 1))
                        def _(gdst=gdst, zg=zg, P=P):
                            gdst[pl.ds(P - 2 - g, g)] = zg
                # y/x-open zero the strips' still-ghost rows/columns
                # on EVERY band (those cells span all bands)
                if ghost_y:
                    zy = jnp.zeros((P - 2, g, cx), mz_ref.dtype)

                    @pl.when(flags_ref[2] == 1)  # y- : global [-g, 0)
                    def _(gydst=gydst, zy=zy, g=g, P=P):
                        gydst[pl.ds(0, P - 2), 2 * k - g : 2 * k, :] = zy

                    @pl.when(flags_ref[3] == 1)  # y+ : global [cy, cy+g)
                    def _(gydst=gydst, zy=zy, g=g, P=P):
                        gydst[pl.ds(0, P - 2), 0:g, :] = zy
                if ghost_x:
                    zx = jnp.zeros((P - 2, cy, g), mz_ref.dtype)

                    @pl.when(flags_ref[4] == 1)  # x-
                    def _(gxdst=gxdst, zx=zx, g=g, P=P):
                        gxdst[pl.ds(0, P - 2), :, 2 * k - g : 2 * k] = zx

                    @pl.when(flags_ref[5] == 1)  # x+
                    def _(gxdst=gxdst, zx=zx, g=g, P=P):
                        gxdst[pl.ds(0, P - 2), :, 0:g] = zx
                if ghost_y and ghost_x:
                    zcy = jnp.zeros((P - 2, g, 2 * k), mz_ref.dtype)
                    zcx = jnp.zeros((P - 2, 2 * k, g), mz_ref.dtype)

                    @pl.when(flags_ref[2] == 1)
                    def _(gcdst=gcdst, zcy=zcy, g=g, P=P):
                        gcdst[pl.ds(0, P - 2), 2 * k - g : 2 * k, :] = zcy

                    @pl.when(flags_ref[3] == 1)
                    def _(gcdst=gcdst, zcy=zcy, g=g, P=P):
                        gcdst[pl.ds(0, P - 2), 0:g, :] = zcy

                    @pl.when(flags_ref[4] == 1)
                    def _(gcdst=gcdst, zcx=zcx, g=g, P=P):
                        gcdst[pl.ds(0, P - 2), :, 2 * k - g : 2 * k] = zcx

                    @pl.when(flags_ref[5] == 1)
                    def _(gcdst=gcdst, zcx=zcx, g=g, P=P):
                        gcdst[pl.ds(0, P - 2), :, 0:g] = zcx
            if ghost and not last:
                # re-read the (possibly zero-pinned) aged strips
                if ghost_y:
                    gybuf = gypong if s % 2 else gyping
                    gyv = gybuf[pl.ds(0, P - 2)]
                if ghost_x:
                    gxbuf = gxpong if s % 2 else gxping
                    gxv = gxbuf[pl.ds(0, P - 2)]
                if ghost_y and ghost_x:
                    gcbuf = gcpong if s % 2 else gcping
                    gcv = gcbuf[pl.ds(0, P - 2)]
        wr(slot, b).start()

        @pl.when(b + nbuf < nb - 1)
        def _():
            rd(slot, b + nbuf).start()

        @pl.when(b + nbuf == nb - 1)
        def _():
            rd_last(slot).start()

        if has_rhs:
            @pl.when(b + nbuf < nb)
            def _():
                rd_f(slot, b + nbuf).start()

        return loop_carry

    jax.lax.fori_loop(0, nb, body, 0)
    for i in range(max(0, nb - nbuf), nb):
        wr(i % nbuf, i).wait()


def stream_band(cz: int, cy: int, cx: int, depth: int, itemsize: int,
                nbuf: int = 2, budget_bytes: int = _VMEM_CEILING,
                has_rhs: bool = False, ghost_y: bool = False,
                ghost_x: bool = False) -> int:
    """Largest divisor band of ``cz`` whose full VMEM footprint (read
    slots + ping/pong intermediates + write slots, plus the rhs read
    slots and ghost-strip buffers when those modes are on) fits, with
    >= 2 bands so the first/last-band window structure holds."""
    plane = cy * cx * itemsize
    k = depth

    def cost(b):
        P0 = b + 2 * depth
        # nbuf read slots + ping/pong intermediates + nbuf write slots
        # + the two (depth, cy, cx) ghost-slab VMEM inputs
        c = (
            (nbuf * P0 + 2 * (P0 - 2) + nbuf * b) * plane
            + 2 * depth * plane
        )
        if has_rhs:
            # rhs read slots (the pre-ghosted rhs itself stays in HBM)
            c += nbuf * P0 * plane
        if ghost_y:  # gy input + strip ping/pong
            c += ((cz + 2 * k) + 2 * (P0 - 2)) * 2 * k * cx * itemsize
        if ghost_x:
            c += ((cz + 2 * k) + 2 * (P0 - 2)) * cy * 128 * itemsize
        if ghost_y and ghost_x:
            c += ((cz + 2 * k) + 2 * (P0 - 2)) * 2 * k * 128 * itemsize
        return c

    band = _largest_divisor_band(cz, cost, budget_bytes, strict=True)
    while band > 1 and cz // band < 2:
        band = next((d for d in range(band - 1, 0, -1) if cz % d == 0), 1)
    if cost(band) > budget_bytes or band < depth or cz // band < 2:
        raise ValueError(
            f"no band of cz={cz} gives >= 2 bands of >= depth={depth} "
            f"planes within {budget_bytes >> 20} MB VMEM (the window "
            "needs band >= depth); lower the depth"
        )
    return band


@functools.partial(
    jax.jit,
    static_argnames=("core_shape", "coeffs7", "depth", "band", "nbuf",
                     "budget_bytes", "carry_tail", "ysplit27", "rhs_coeff"),
)
def seven_point_streamed_pallas(
    core: jax.Array,
    a_mz: jax.Array,
    a_pz: jax.Array,
    core_shape: tuple[int, int, int],
    coeffs7,
    depth: int,
    band: int | None = None,
    nbuf: int = 2,
    budget_bytes: int = _VMEM_CEILING,
    open_flags: jax.Array | None = None,
    carry_tail: bool | None = None,
    ysplit27: int = 4,
    gy: jax.Array | None = None,
    gx: jax.Array | None = None,
    gc: jax.Array | None = None,
    rhs: jax.Array | None = None,
    rhs_coeff: float = 0.0,
) -> jax.Array:
    """``depth`` 7-point Jacobi substeps in ONE manual-DMA streaming pass.

    ``rhs``: optional PRE-GHOSTED (cz + 2*depth, cy, cx) pointwise
    field; each substep's output cells additionally get ``rhs_coeff *
    rhs`` at their own coordinates — the affine term that makes the
    kernel a damped-Jacobi SMOOTHER (u' = stencil(u) + (omega/6) f)
    folding ``depth`` sweeps per HBM pass.  The rhs streams through
    its own double-buffered uniform band windows (~1.5x the pure-
    stencil HBM traffic).  7-point z-slab mode only.

    ``a_mz``/``a_pz``: (depth, cy, cx) z-ghost slabs (the -z neighbor's
    far planes / +z neighbor's near planes, or the core's own wrap
    slices when z self-wraps).  Returns the core after ``depth`` steps.

    y/x column modes (round 5 — the 2D ghost-strip scheme one dimension
    up): with ``gy``/``gx``/``gc`` None the axis self-wraps in-kernel
    (z-slab mode, zero ghost machinery).  A DISTRIBUTED (or open) y
    axis rides ``gy`` (cz + 2k, 2k, cx) ghost strips in the [plus |
    minus] layout; a distributed x axis rides ``gx`` (cz + 2k, cy, 2k);
    when BOTH are distributed the (cz + 2k, 2k, 2k) xy-corner strip
    ``gc`` must also be given (strip aging reads across the corner even
    though the 7-point core never does).  All strips span global planes
    [-depth, cz + depth) — their z-corner segments carry the diagonal
    z-neighbors' data.  7-point only: the 27-point form stays z-slab
    (its full-extent ghost slabs carry every edge/corner value
    implicitly; ghosted-axis corner channels would re-derive the whole
    26-neighbor exchange in-kernel).

    ``open_flags``: (6,) int32 — [z-, z+, y-, y+, x-, x+]; 1 marks this
    rank's side as a physical OPEN boundary, re-imposing the zero-ghost
    condition every substep (per-rank traced values: shard_map traces
    one program for all ranks).  None means every side receives real
    ghost data.  (2,) legacy values mean [z-, z+].

    ``carry_tail``: hand each window's 2k-plane overlap to the next
    band by VMEM copy instead of re-reading it — HBM read traffic drops
    from (band+2k)/band x to 1x core per pass.  Default (None) enables
    it whenever the structure allows (nbuf == 2, band > depth).

    ``coeffs7`` may also be 27 OFFSETS26-ordered coefficients (+ center
    last): each substep then runs three dz-shifted 9-point ring
    decompositions — the 27-point stencil on the fast streamed path.
    On z-slab meshes the full-extent ghost slabs already carry every
    edge/corner neighbor value, so no extra exchange machinery rides
    along (the reference treats stencil width as a parameter of the
    same exchange, stencil2D.h:116-117).
    """
    cz, cy, cx = core_shape
    k = depth
    # the chooser budget decides the band; the Mosaic vmem limit stays
    # at the full ceiling (the 27-point band must shrink to leave the
    # allocator room for its substep temps, NOT because the buffers
    # stop fitting — chip-probed: band=4 at 512^2 planes compiles under
    # the 120 MB limit, band=8 does not, and band=4 under a 58 MB limit
    # does not either)
    chooser_budget = budget_bytes
    if len(coeffs7) == 27:
        from tpuscratch.halo.halo3d import OFFSETS26

        coeffs7 = weight_cube(tuple(coeffs7), OFFSETS26)
        if budget_bytes == _VMEM_CEILING:
            chooser_budget = _VMEM_CEILING_27
    elif len(coeffs7) != 7:
        raise ValueError(
            f"need 7 or 27 coefficients, got {len(coeffs7)}"
        )
    if tuple(core.shape) != core_shape:
        raise ValueError(f"core {core.shape} != {core_shape}")
    if a_mz.shape != (k, cy, cx) or a_pz.shape != (k, cy, cx):
        raise ValueError(
            f"ghost slabs must be ({k}, {cy}, {cx}), got "
            f"{a_mz.shape}/{a_pz.shape}"
        )
    if k < 1:
        raise ValueError(f"depth must be >= 1, got {k}")
    ghost_y, ghost_x = gy is not None, gx is not None
    if ghost_y or ghost_x:
        if len(coeffs7) == 3:  # already cubed -> was 27 coefficients
            raise ValueError(
                "ghosted y/x axes are 7-point only; the 27-point form "
                "needs a z-slab mesh (impl='compact-asm' serves "
                "distributed y/x)"
            )
        if ghost_y and gy.shape != (cz + 2 * k, 2 * k, cx):
            raise ValueError(
                f"gy must be ({cz + 2 * k}, {2 * k}, {cx}), got {gy.shape}"
            )
        if ghost_x and gx.shape != (cz + 2 * k, cy, 2 * k):
            raise ValueError(
                f"gx must be ({cz + 2 * k}, {cy}, {2 * k}), got {gx.shape}"
            )
        if (ghost_y and ghost_x) != (gc is not None):
            raise ValueError(
                "gc (the xy-corner strip) is required exactly when both "
                "gy and gx are given"
            )
        if gc is not None and gc.shape != (cz + 2 * k, 2 * k, 2 * k):
            raise ValueError(
                f"gc must be ({cz + 2 * k}, {2 * k}, {2 * k}), "
                f"got {gc.shape}"
            )
        if (ghost_y and k > cy) or (ghost_x and k > cx):
            raise ValueError(f"depth {k} exceeds a ghosted plane extent")
    has_rhs = rhs is not None
    if has_rhs:
        if len(coeffs7) == 3:
            raise ValueError("rhs smoothing is 7-point only")
        if ghost_y or ghost_x:
            raise ValueError(
                "rhs smoothing needs a z-slab mesh (self-wrapping y/x); "
                "ghosted y/x axes are not supported with rhs"
            )
        if rhs.shape != (cz + 2 * k, cy, cx):
            raise ValueError(
                f"rhs must be PRE-GHOSTED ({cz + 2 * k}, {cy}, {cx}), "
                f"got {rhs.shape}"
            )
    if band is None:
        band = stream_band(cz, cy, cx, k, core.dtype.itemsize, nbuf,
                           chooser_budget, has_rhs=has_rhs,
                           ghost_y=ghost_y, ghost_x=ghost_x)
    if cz % band or cz // band < 2:
        raise ValueError(
            f"band {band} must divide cz {cz} with at least 2 bands"
        )
    if k > band:
        raise ValueError(
            f"depth {k} > band {band}: the second band's window would "
            "need -z ghosts; lower depth or raise the VMEM budget"
        )
    if cy < 3 or cx < 3:
        raise ValueError(f"plane extents must be >= 3, got {cy}x{cx}")
    nb = cz // band
    P0 = band + 2 * k
    dt = core.dtype
    if open_flags is None:
        open_flags = jnp.zeros((6,), jnp.int32)
    elif open_flags.shape == (2,):  # legacy z-only callers
        open_flags = jnp.concatenate(
            [open_flags, jnp.zeros((4,), open_flags.dtype)]
        )
    if carry_tail is None:
        carry_tail = nbuf == 2 and band > k
    elif carry_tail and (nbuf != 2 or band <= k):
        raise ValueError(
            f"carry_tail needs nbuf == 2 and band > depth, got "
            f"nbuf={nbuf} band={band} depth={k}"
        )
    dummy = jnp.zeros((1, 1, 1), dt)
    if not ghost_y:
        gy = dummy
    if not ghost_x:
        gx = dummy
    if gc is None:
        gc = dummy
    if not has_rhs:
        rhs = dummy
    P2 = max(P0 - 2, 1)

    def strip_scr(cond, shape):
        return pltpu.VMEM(shape if cond else (1, 1, 1), dt)

    kern = functools.partial(
        _stream_kernel, band=band, depth=k, nb=nb, nbuf=nbuf, cy=cy, cx=cx,
        coeffs7=tuple(coeffs7), carry_tail=carry_tail, ysplit27=ysplit27,
        ghost_y=ghost_y, ghost_x=ghost_x, has_rhs=has_rhs,
        rhs_coeff=float(rhs_coeff),
    )
    interpret = interpret_params() if use_interpret() else False
    return pl.pallas_call(
        kern,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        out_shape=jax.ShapeDtypeStruct((cz, cy, cx), dt),
        scratch_shapes=[
            pltpu.VMEM((nbuf, P0, cy, cx), dt),      # read slots
            pltpu.VMEM((max(P0 - 2, 1), cy, cx), dt),  # ping
            pltpu.VMEM((max(P0 - 2, 1), cy, cx), dt),  # pong
            strip_scr(ghost_y, (P2, 2 * k, cx)),     # gy ping
            strip_scr(ghost_y, (P2, 2 * k, cx)),     # gy pong
            strip_scr(ghost_x, (P2, cy, 2 * k)),     # gx ping
            strip_scr(ghost_x, (P2, cy, 2 * k)),     # gx pong
            strip_scr(ghost_y and ghost_x, (P2, 2 * k, 2 * k)),  # gc ping
            strip_scr(ghost_y and ghost_x, (P2, 2 * k, 2 * k)),  # gc pong
            strip_scr(has_rhs, (nbuf, P0, cy, cx)),  # rhs read slots
            pltpu.VMEM((nbuf, band, cy, cx), dt),    # write slots
            pltpu.SemaphoreType.DMA((nbuf,)),
            pltpu.SemaphoreType.DMA((nbuf,)),
            pltpu.SemaphoreType.DMA((nbuf,)),
        ],
        interpret=interpret,
        **mosaic_params(vmem_limit_bytes=int(budget_bytes * 1.2)),
    )(open_flags.astype(jnp.int32), a_mz, a_pz, gy, gx, gc, core, rhs)


# ---------------------------------------------------------------------------
# The 2D twin: row-banded deep streaming for (H, W) grids.
#
# Same economics as the 3D kernel (k substeps per manual-DMA pass divide
# the per-step HBM traffic by k past the ~330 GB/s DMA-fabric bound), but
# the 2D row dimension IS the sublane dimension, so the 3D kernel's
# overlapping ghost-extended windows would violate the chip DMA rules
# BASELINE row 4 records (8-row alignment, affine provably-in-bounds
# offsets, one descriptor geometry).  This kernel therefore reads EXACT
# band-row windows (offset b*band, length band — aligned and in-bounds by
# construction) and assembles the (band + 2k)-row compute window at VALUE
# level: the top k halo rows ride the fori carry (each band's pass-start
# rows [band-k, band)), the bottom k rows come from the NEXT band's
# window (waited one band ahead), and the grid ends splice in the ghost
# slabs.
#
# The column axis comes in TWO modes (round 5 — before that only the
# first existed, capping the canonical 2D-decomposed config at 6.7x
# slower paths, VERDICT r4 missing #1):
#
# - wrap mode: x self-wraps in-kernel (full-extent rows).  Zero ghost
#   machinery; serves row-slab decompositions with a periodic column
#   axis.  9-point coefficients cost nothing extra — the full-extent
#   rows carry the diagonal neighbors implicitly.
#
# - ghost mode: columns are DISTRIBUTED (or open-ended).  Each pass
#   receives (H + 2k, k) ghost-column slabs gl/gr spanning global rows
#   [-k, H + k) — the x neighbors' edge columns with the DIAGONAL
#   neighbors' k x k corner blocks at the ends, exactly the 8-channel
#   transfer set of the reference's exchange (stencil2D.h:232-244,
#   :389-428) at ghost depth k.  The ghost columns are NOT concatenated
#   onto the core window (chip-raced: a per-band lane-concat into a
#   (P0, W + 2k) buffer relayouts ~5 MB per band and cost 0.33 ms/step
#   at 8192^2/k=32 — 71% over wrap mode).  Instead the core window
#   stays at width W exactly as in wrap mode, and the ghosts ride a
#   separate narrow (P, 2k) strip laid out [gr | gl]:
#     - the core substep reads its two edge neighbors from the strip
#       (column 0's west = strip column 2k-1 = global -1; column W-1's
#       east = strip column 0 = global W) — everything else is the
#       wrap-mode code;
#     - the strip EVOLVES by its own small 9-point substep over
#       [core_last_col | strip | core_first_col], so depth-k passes see
#       correctly-aged ghosts; its interior seam (gr's far edge against
#       gl's far edge, non-adjacent global columns) corrupts one more
#       column per side per substep — precisely the ghost budget k
#       buys — so after k substeps the core [0, W) is exact while the
#       strip is spent.
# ---------------------------------------------------------------------------


def _substep2d(o_ref, t, P: int, W: int, w9, gv=None, k: int = 0):
    """One 9-point substep on a (P, W) window value: rows shrink by one
    per side (ring decomposition: interior columns by shifted slices,
    the two edge columns by single-column reads).  ``w9``: (3, 3) weight
    grid w9[dy+1][dx+1]; zero weights are skipped statically, so
    5-point coefficients pay no diagonal work.

    With ``gv`` None the x axis wraps periodically (wrap mode).  With
    ``gv`` a (P, 2k) [gr | gl] ghost strip (ghost mode), the two edge
    columns read their out-of-tile neighbor from the strip instead —
    column 0's west is strip column 2k-1 (global -1), column W-1's east
    is strip column 0 (global W).  ONE compute body serves both modes."""
    rows = {-1: t[0 : P - 2], 0: t[1 : P - 1], 1: t[2:P]}
    grows = None if gv is None else {
        -1: gv[0 : P - 2], 0: gv[1 : P - 1], 1: gv[2:P]
    }

    def shifted(dy, dx, lo, hi):
        u = rows[dy]
        if dx == 0:
            return u[:, lo:hi]
        if lo == 1 and hi == W - 1:  # interior: pure slice
            return u[:, 1 + dx : W - 1 + dx]
        c = lo + dx
        if grows is not None and c < 0:    # column 0's west -> global -1
            return grows[dy][:, 2 * k - 1 : 2 * k]
        if grows is not None and c >= W:   # column W-1's east -> global W
            return grows[dy][:, 0:1]
        c %= W  # wrap mode: edge columns read the far side
        return u[:, c : c + 1]

    for lo, hi in ((1, W - 1), (0, 1), (W - 1, W)):
        acc = None
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                cw = w9[dy + 1][dx + 1]
                if cw == 0.0:
                    continue
                term = cw * shifted(dy, dx, lo, hi)
                acc = term if acc is None else acc + term
        o_ref[0 : P - 2, lo:hi] = acc


def _substep2d_gstrip(go_ref, t, gv, P: int, W: int, k: int, w9):
    """One substep of the (P, 2k) ghost strip itself: 9-point over
    [core_last_col | gr | gl | core_first_col] (the strip's outer
    neighbors are real core columns; its interior gr/gl seam is the
    non-adjacent-columns seam whose garbage the depth budget absorbs).
    Writes the aged (P - 2, 2k) strip to ``go_ref``."""
    ext = jnp.concatenate([t[:, W - 1 : W], gv, t[:, 0:1]], axis=1)
    rows = {-1: ext[0 : P - 2], 0: ext[1 : P - 1], 1: ext[2:P]}
    acc = None
    for dy in (-1, 0, 1):
        u = rows[dy]
        for dx in (-1, 0, 1):
            cw = w9[dy + 1][dx + 1]
            if cw == 0.0:
                continue
            term = cw * u[:, 1 + dx : 2 * k + 1 + dx]
            acc = term if acc is None else acc + term
    go_ref[0 : P - 2, :] = acc


def _stream2d_kernel(flags_ref, mt_ref, mb_ref, gl_ref, gr_ref, in_hbm,
                     out_hbm, rbuf, ping, pong, gping, gpong, wbuf,
                     rsem, wsem, *,
                     band: int, depth: int, nb: int, W: int, w9,
                     ghost_x: bool):
    k = depth
    P0 = band + 2 * k

    def rd(slot, b):
        return pltpu.make_async_copy(
            in_hbm.at[pl.ds(b * band, band)], rbuf.at[slot], rsem.at[slot])

    def wr(slot, b):
        return pltpu.make_async_copy(
            wbuf.at[slot], out_hbm.at[pl.ds(b * band, band)], wsem.at[slot])

    rd(0, 0).start()
    if nb > 1:
        rd(1, 1).start()
    rd(0, 0).wait()

    def body(b, carry_k):
        slot = jax.lax.rem(b, 2)
        nxt = jax.lax.rem(b + 1, 2)

        @pl.when(b + 1 < nb)
        def _():
            rd(nxt, b + 1).wait()

        @pl.when(b >= 2)
        def _():
            wr(slot, b - 2).wait()

        t = rbuf[slot]                     # (band, W) pass-start rows
        next_k = rbuf[nxt][0:k]
        bot_k = jnp.where(b == nb - 1, mb_ref[:], next_k)
        V = jnp.concatenate([carry_k, t, bot_k], axis=0)  # (P0, W)
        if ghost_x:
            # this window's ghost strip [gr | gl] from the (H + 2k, k)
            # slabs (slab row i = global row i - k; the window starts
            # at global row b*band - k = slab row b*band); 2k lanes —
            # the big core window is never lane-concatenated
            gv = jnp.concatenate(
                [gr_ref[pl.ds(b * band, P0)],
                 gl_ref[pl.ds(b * band, P0)]], axis=1
            )                               # (P0, 2k)
        new_carry = t[band - k : band]

        # the substep chain sheds one row per side per substep; ping and
        # pong are static refs, so their stores are plain static ranges
        src_val = V
        for s in range(k):
            P = P0 - 2 * s
            last = s == k - 1
            # at s == k-1, P - 2 == band: the final substep fills the
            # write buffer exactly
            dst = wbuf.at[slot] if last else (pong if s % 2 else ping)
            if ghost_x:
                _substep2d(dst, src_val, P, W, w9, gv, k)
                if not last:  # age the strip alongside the core
                    gdst = gpong if s % 2 else gping
                    _substep2d_gstrip(gdst, src_val, gv, P, W, k, w9)
            else:
                _substep2d(dst, src_val, P, W, w9)
            # OPEN y ends: the rows still acting as ghosts after substep
            # s+1 must stay zero on the physical-end bands (the strip
            # rows age in lockstep, so zero them too)
            g = k - s - 1
            if g > 0:
                z = jnp.zeros((g, W), mt_ref.dtype)

                @pl.when(jnp.logical_and(flags_ref[0] == 1, b == 0))
                def _(dst=dst, z=z, g=g):
                    dst[pl.ds(0, g)] = z

                @pl.when(jnp.logical_and(flags_ref[1] == 1, b == nb - 1))
                def _(dst=dst, z=z, g=g, P=P):
                    dst[pl.ds(P - 2 - g, g)] = z
            if ghost_x and g > 0:
                zg = jnp.zeros((g, 2 * k), mt_ref.dtype)

                @pl.when(jnp.logical_and(flags_ref[0] == 1, b == 0))
                def _(gdst=gdst, zg=zg, g=g):
                    gdst[pl.ds(0, g)] = zg

                @pl.when(jnp.logical_and(flags_ref[1] == 1, b == nb - 1))
                def _(gdst=gdst, zg=zg, g=g, P=P):
                    gdst[pl.ds(P - 2 - g, g)] = zg

                # OPEN x ends: the g ghost columns still in play must
                # stay zero — global [-g, 0) = strip [2k - g, 2k),
                # global [W, W + g) = strip [0, g) — on EVERY band
                zc = jnp.zeros((P - 2, g), mt_ref.dtype)

                @pl.when(flags_ref[2] == 1)
                def _(gdst=gdst, zc=zc, g=g, P=P):
                    gdst[0 : P - 2, 2 * k - g : 2 * k] = zc

                @pl.when(flags_ref[3] == 1)
                def _(gdst=gdst, zc=zc, g=g, P=P):
                    gdst[0 : P - 2, 0:g] = zc
            if not last:
                buf = pong if s % 2 else ping
                src_val = buf[pl.ds(0, P - 2)]
                if ghost_x:
                    gbuf = gpong if s % 2 else gping
                    gv = gbuf[pl.ds(0, P - 2)]

        wr(slot, b).start()

        @pl.when(b + 2 < nb)
        def _():
            rd(slot, b + 2).start()

        return new_carry

    jax.lax.fori_loop(0, nb, body, mt_ref[:])
    for i in range(max(0, nb - 2), nb):
        wr(i % 2, i).wait()


def weight_grid(coeffs9) -> tuple:
    """nine_point coeff order (n, s, w, e, nw, ne, sw, se, center) ->
    (3, 3) grid W[dy+1][dx+1]; 5-point coeffs get zero diagonals."""
    c = tuple(float(x) for x in coeffs9)
    if len(c) == 5:
        c = c[:4] + (0.0,) * 4 + c[4:]
    if len(c) != 9:
        raise ValueError(f"need 5 or 9 coefficients, got {len(c)}")
    n, s, w, e, nw, ne, sw, se, cc = c
    return ((nw, n, ne), (w, cc, e), (sw, s, se))


def stream2d_band(H: int, W: int, depth: int, itemsize: int,
                  budget_bytes: int, ghost_x: bool = False) -> int:
    """Largest 8-multiple divisor band of ``H`` whose kernel footprint
    (read/write double-buffers at core width, ping/pong at window width,
    plus the ghost-column slabs in ghost mode) fits the budget, with
    >= 2 bands.  8-multiples only: the DMA windows are 8-row-tile
    aligned AND 8-row-multiple lengths (chip rule, BASELINE row 4) — a
    non-8 band passes the CPU interpreter and DNFs on silicon."""
    k = depth

    def cost(b):
        c = (4 * b + 2 * (b + 2 * k - 2)) * W
        if ghost_x:
            # gl/gr slabs + ghost-strip ping/pong, lane-padded to 128
            c += 2 * (H + 2 * k) * 128 + 2 * (b + 2 * k) * 128
        return c * itemsize

    for d in range(H // 2, 7, -1):
        if H % d == 0 and d % 8 == 0 and d >= k and cost(d) <= budget_bytes:
            return d
    raise ValueError(
        f"no 8-aligned band of H={H} gives >= 2 bands of >= depth={k} "
        f"rows within {budget_bytes >> 20} MB VMEM (need 8 | H and "
        "H >= 16); lower the depth or raise the budget"
    )


@functools.partial(
    jax.jit,
    static_argnames=("core_shape", "coeffs", "depth", "band",
                     "budget_bytes"),
)
def nine_point_streamed_2d(
    core: jax.Array,
    a_top: jax.Array,
    a_bot: jax.Array,
    core_shape: tuple[int, int],
    coeffs,
    depth: int,
    band: int | None = None,
    budget_bytes: int = _VMEM_CEILING,
    open_flags: jax.Array | None = None,
    gl: jax.Array | None = None,
    gr: jax.Array | None = None,
) -> jax.Array:
    """``depth`` 5/9-point Jacobi substeps in ONE streaming pass over an
    (H, W) grid — the 2D twin of :func:`seven_point_streamed_pallas`
    (see the section comment for why its window scheme differs).

    ``a_top``/``a_bot``: (depth, W) ghost-row slabs (the row-slab
    neighbors' far rows, or the core's own wrap slices).

    Column modes (see the section comment): with ``gl``/``gr`` None, x
    self-wraps in-kernel (wrap mode — periodic column axis only).  With
    ``gl``/``gr`` given as (H + 2*depth, depth) ghost-column slabs
    spanning global rows [-depth, H + depth) — x-neighbor edge columns
    with the diagonal neighbors' corner blocks at the ends — the kernel
    serves DISTRIBUTED or open column layouts (ghost mode).

    ``open_flags``: (4,) int32 marking physical open [top, bottom,
    left, right] ends (left/right meaningful in ghost mode only).
    """
    H, W = core_shape
    k = depth
    if tuple(core.shape) != core_shape:
        raise ValueError(f"core {core.shape} != {core_shape}")
    if a_top.shape != (k, W) or a_bot.shape != (k, W):
        raise ValueError(
            f"ghost slabs must be ({k}, {W}), got {a_top.shape}/{a_bot.shape}"
        )
    if (gl is None) != (gr is None):
        raise ValueError("gl and gr must be given together")
    ghost_x = gl is not None
    if ghost_x and (gl.shape != (H + 2 * k, k) or gr.shape != (H + 2 * k, k)):
        raise ValueError(
            f"ghost-column slabs must be ({H + 2 * k}, {k}), got "
            f"{gl.shape}/{gr.shape}"
        )
    if k < 1:
        raise ValueError(f"depth must be >= 1, got {k}")
    w9 = weight_grid(coeffs)
    if H % 8:
        raise ValueError(
            f"H {H} must be a multiple of 8 (the DMA windows are "
            "8-row-tile aligned; a non-8 H passes the CPU interpreter "
            "but is a Mosaic remote-compile DNF on chip)"
        )
    if band is None:
        band = stream2d_band(H, W, k, core.dtype.itemsize,
                             budget_bytes // 2, ghost_x)
    if H % band or H // band < 2 or band % 8:
        raise ValueError(
            f"band {band} must be an 8-multiple divisor of H {H} with "
            ">= 2 bands (8-row DMA-window alignment, BASELINE row 4)"
        )
    if k > band:
        raise ValueError(f"depth {k} > band {band}")
    if W < 3:
        raise ValueError(f"W must be >= 3, got {W}")
    if ghost_x and k > W:
        raise ValueError(f"depth {k} > core width {W} in ghost mode")
    nb = H // band
    P0 = band + 2 * k
    dt = core.dtype
    if open_flags is None:
        open_flags = jnp.zeros((4,), jnp.int32)
    elif open_flags.shape == (2,):  # legacy top/bottom-only callers
        open_flags = jnp.concatenate(
            [open_flags, jnp.zeros((2,), open_flags.dtype)]
        )
    if not ghost_x:
        gl = gr = jnp.zeros((1, 1), dt)  # unused dummies, uniform arity
    kern = functools.partial(
        _stream2d_kernel, band=band, depth=k, nb=nb, W=W, w9=w9,
        ghost_x=ghost_x,
    )
    interpret = interpret_params() if use_interpret() else False
    return pl.pallas_call(
        kern,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        out_shape=jax.ShapeDtypeStruct((H, W), dt),
        scratch_shapes=[
            pltpu.VMEM((2, band, W), dt),            # read windows
            pltpu.VMEM((max(P0 - 2, 1), W), dt),     # ping
            pltpu.VMEM((max(P0 - 2, 1), W), dt),     # pong
            # ghost-strip ping/pong ((1, 1) dummies in wrap mode)
            pltpu.VMEM((max(P0 - 2, 1) if ghost_x else 1,
                        2 * k if ghost_x else 1), dt),
            pltpu.VMEM((max(P0 - 2, 1) if ghost_x else 1,
                        2 * k if ghost_x else 1), dt),
            pltpu.VMEM((2, band, W), dt),            # write bands
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
        **mosaic_params(vmem_limit_bytes=budget_bytes),
    )(open_flags.astype(jnp.int32), a_top, a_bot, gl, gr, core)
