"""Device compute kernels (Pallas) — the reference's CUDA kernel layer.

Every ``__global__`` kernel in the reference maps to a Pallas TPU kernel
here, designed for the VPU/MXU rather than translated from CUDA:

- dot-product reductions (atomic / two-phase / single-kernel,
  mpicuda2-4.cu) -> ``reduction.dot_partials`` / ``reduction.dot_full``
- ``init_vector`` / ``InitKernel`` device-side fills
  (ref_parallel-dot-product-atomics.cu:45-51,
  mpi-2d-stencil-subarray-cuda.cu:17-28) -> ``fill.fill`` / ``fill.iota2d``
- the stencil ``Compute`` placeholder (mpi-2d-stencil-subarray.cpp:27)
  -> a real 5-point stencil kernel in ``stencil_kernel``

All kernels run in Pallas interpreter mode off-TPU, so the same code path
is exercised by CPU tests and TPU benchmarks.
"""

from tpuscratch.ops.reduction import dot, dot_full, dot_partials  # noqa: F401
from tpuscratch.ops.fill import fill, iota2d  # noqa: F401
from tpuscratch.ops.halo_dma import run_stencil_dma  # noqa: F401
from tpuscratch.ops.stencil_kernel import (  # noqa: F401
    five_point_blocked,
    five_point_pallas,
)
