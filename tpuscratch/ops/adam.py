"""Fused single-pass Adam update — one kernel reads (w, g, m, v) and
writes (w', m', v') per element, the 7-access/element HBM roofline for
the optimizer step.

Why: BASELINE row 11 measured the Adam premium at ~13.8 ms/step over
SGD for ~180M params — two elementwise moment passes plus the update,
about 2x the 7-access roofline (~7 ms at v5e HBM rates) because XLA
schedules the three tree-mapped passes as separate loop nests over
each leaf (VERDICT r4 weak #4).  The reference has no optimizer at all
(SURVEY §2.7 — this surface is beyond parity); the kernel follows the
framework's standard one-source dual-backend policy (Mosaic interpret
off-TPU).

Two variants:
- :func:`fused_adam_tree` — f32 moments, drop-in for the tree-mapped
  update (bit-comparable modulo fma reassociation);
- ``moment_dtype=bfloat16`` — halves the moment traffic (20 B/element
  instead of 28); the moments quantize to bf16 but the params stay f32
  master copies (the usual mixed-precision optimizer layout).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuscratch.ops.common import mosaic_params, use_interpret

_COLS = 1024
_BAND = 512  # rows per grid step: 7 x (512, 1024) f32 buffers = 14 MB


def _adam_kernel(alpha_ref, w_ref, g_ref, m_ref, v_ref,
                 nw_ref, nm_ref, nv_ref, *, b1: float, b2: float,
                 eps: float):
    g = g_ref[...]
    m = b1 * m_ref[...].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[...].astype(jnp.float32) + (1.0 - b2) * (g * g)
    nm_ref[...] = m.astype(nm_ref.dtype)
    nv_ref[...] = v.astype(nv_ref.dtype)
    nw_ref[...] = w_ref[...] - alpha_ref[0] * m / (jnp.sqrt(v) + eps)


def _fused_adam_flat_call(w, g, m, v, alpha, b1, b2, eps):
    """(rows, _COLS) f32 arrays -> (w', m', v'), one pass."""
    rows = w.shape[0]
    band = min(_BAND, rows)
    while rows % band:
        band //= 2
    grid = rows // band
    spec = pl.BlockSpec((band, _COLS), lambda i: (i, 0))
    interpret = use_interpret()
    return pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec, spec, spec, spec,
        ],
        out_specs=(spec, spec, spec),
        out_shape=(
            jax.ShapeDtypeStruct(w.shape, w.dtype),
            jax.ShapeDtypeStruct(m.shape, m.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        interpret=interpret,
        **mosaic_params(),
    )(alpha.reshape(1), w, g, m, v)


_fused_adam_flat = functools.partial(
    jax.jit, static_argnames=("b1", "b2", "eps")
)(_fused_adam_flat_call)
#: same program with w/m/v DONATED: the update aliases its input HBM, so
#: the optimizer never holds old and new copies of a moment at once —
#: only the gradient buffer rides alongside the state (4 live
#: buffers/element instead of 7 at the peak)
_fused_adam_flat_donated = functools.partial(
    jax.jit, static_argnames=("b1", "b2", "eps"), donate_argnums=(0, 2, 3)
)(_fused_adam_flat_call)


def fused_adam_flat(w, g, m, v, alpha, b1: float = 0.9, b2: float = 0.999,
                    eps: float = 1e-8, donate: bool = True):
    """Public flat-shard entry: one fused pass over ``(rows, 1024)``
    f32 arrays (the ZeRO per-rank layout), returning (w', m', v').

    ``donate=True`` (default) hands the w/m/v input buffers to the
    outputs — the params and both moments are updated IN PLACE and the
    passed arrays are consumed (``.is_deleted()`` afterwards; asserted
    against ``runtime.memory.live_bytes`` in tests/test_ops.py).  Only
    eager callers get the aliasing; under an outer jit trace use the
    outer program's donation instead (``models.zero.train_step_zero``
    donates its optimizer-state argument)."""
    if w.ndim != 2 or w.shape[1] != _COLS:
        raise ValueError(
            f"fused_adam_flat takes (rows, {_COLS}) arrays, got {w.shape}"
        )
    alpha = jnp.asarray(alpha, jnp.float32)
    fn = _fused_adam_flat_donated if donate else _fused_adam_flat
    return fn(w, g, m, v, alpha, b1=b1, b2=b2, eps=eps)


def _to_flat(x):
    n = x.size
    rows = -(-n // _COLS)
    # pad rows to the sublane quantum (8) by default, but to a full _BAND
    # when the waste stays under 1/16th of the leaf: an awkward row count
    # (the 50257x1024 embedding flattens to 50257 rows) would otherwise
    # collapse the band chooser in _fused_adam_flat to band=8 — thousands
    # of tiny grid steps on the kernel's own headline benchmark (ADVICE
    # r5; +0.9% memory there).  The waste bound keeps mid-size leaves
    # honest — e.g. 576 rows would pad to 1024 (+78%) under an
    # unconditional quantum, while the halving chooser already gives
    # them band=64.
    band_pad = (-rows) % _BAND
    quantum = _BAND if rows >= _BAND and band_pad * 16 <= rows else 8
    rows8 = -(-rows // quantum) * quantum
    pad = rows8 * _COLS - n
    fx = x.reshape(-1)
    if pad:
        fx = jnp.concatenate([fx, jnp.zeros((pad,), x.dtype)])
    return fx.reshape(rows8, _COLS)


def fused_adam_tree(params, grads, mu, nu, alpha, b1=0.9, b2=0.999,
                    eps=1e-8, donate=False):
    """Per-leaf fused Adam: returns (new_params, new_mu, new_nu) pytrees.
    ``alpha`` is the bias-corrected step size (traced scalar).  Moments
    may be bf16 (storage) — accumulation is always f32.  ``donate=True``
    (eager callers only — under an outer trace aliasing is the outer
    jit's job) donates each leaf's flattened w/m/v staging buffers, so
    the update never holds two copies of a moment in HBM."""
    flat, treedef = jax.tree.flatten(params)
    gflat = jax.tree.leaves(grads)
    mflat = jax.tree.leaves(mu)
    vflat = jax.tree.leaves(nu)
    nw, nm, nv = [], [], []
    alpha = jnp.asarray(alpha, jnp.float32)
    update = _fused_adam_flat_donated if donate else _fused_adam_flat
    for w, g, m, v in zip(flat, gflat, mflat, vflat):
        w2, m2, v2 = update(
            _to_flat(w), _to_flat(g.astype(jnp.float32)), _to_flat(m),
            _to_flat(v), alpha, b1, b2, eps,
        )
        n = w.size
        nw.append(w2.reshape(-1)[:n].reshape(w.shape))
        nm.append(m2.reshape(-1)[:n].reshape(m.shape))
        nv.append(v2.reshape(-1)[:n].reshape(v.shape))
    return (
        jax.tree.unflatten(treedef, nw),
        jax.tree.unflatten(treedef, nm),
        jax.tree.unflatten(treedef, nv),
    )
