"""Pallas flash-attention kernel — blockwise exact attention, MXU path.

The reference has no attention anywhere (SURVEY.md §2.7: no sequence
dimension exists); this kernel is part of the framework's long-context
surface, beyond reference parity. The sequence-parallel schemes in
``tpuscratch.parallel`` bound *cross-chip* memory by sharding the
sequence; this kernel bounds *on-chip* memory for the local attention
those schemes still compute — most importantly the Ulysses path, whose
all-to-all hands every rank the FULL global sequence for its head slice
(parallel/ulysses.py), where a naive (S, S) score materialization is
exactly the memory blowup flash attention exists to avoid.

Shape contract matches ``parallel.scores.masked_scores`` semantics:
q (S, H, D), k/v (T, H, D), fp32 online-softmax accumulation, causal
masking on global positions via ``q_offset``/``kv_offset`` (scalars, so
ring-attention hops can reuse the kernel with rotated K origins).

Kernel structure (the canonical TPU flash schedule):
- grid (H, S/block_q, T/block_k); the KV axis is the innermost,
  sequential ("arbitrary") dimension — the VMEM scratch carrying the
  online-softmax state (running max, normalizer, fp32 accumulator) is
  revisited across KV steps, initialized at the first step, and the
  normalized output is emitted at the last.
- both matmuls (scores = q @ k^T, update = p @ v) hit the MXU with
  ``preferred_element_type=float32``; the VPU handles the softmax
  bookkeeping in between.
- the running max / normalizer live in (block_q, 8) VMEM scratch with
  values broadcast across the 8 lanes: Mosaic wants lane-complete vector
  stores, 8 lanes is the narrowest legal layout, and a broadcast store +
  column-0 read is free compared to the relayouts a (block_q, 1) slice
  store would trigger.
- bf16 inputs run the MXU passes in bf16 (fp32 accumulation), roughly
  doubling the matmul rate vs the fp32-input path; the online-softmax
  state stays fp32 throughout.

The second half of this module is the SERVING side of the same
residency argument: a fused Pallas paged-attention kernel family
(:func:`paged_attention`) streaming block-paged KV pools from HBM
exactly once per step — page gather, int8/fp8 dequantization, and the
flash-style online softmax in ONE kernel — dispatched behind the three
cached entry points (:func:`decode_attention`, :func:`verify_attention`,
and through them the context-prefill program), with the dense XLA
formulation kept as the interpret-mode/CPU oracle and fallback.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import numpy as np

from tpuscratch.ops.common import mosaic_params, use_interpret
from tpuscratch.parallel.scores import NEG_INF, masked_softmax

#: Lane width of the m/l running-state planes. 8 is the narrowest layout
#: Mosaic accepts for lane-complete stores; vs the 128-lane broadcast it
#: cuts the per-KV-step state traffic 16x, measured worth ~3% non-causal
#: and ~7% causal at S=4096 on v5e.
_STATE_LANES = 8


def _mm_dtype(ref):
    """MXU operand dtype: bf16 inputs stay bf16 (native-rate systolic
    passes, fp32 accumulation via preferred_element_type — the
    FlashAttention-2 choice); everything else computes in fp32."""
    return jnp.bfloat16 if ref.dtype == jnp.bfloat16 else jnp.float32


def _raw_scores(q_ref, k_ref, scale):
    """q @ k^T on the MXU, fp32 out, scale folded into the (bq, D) q
    operand — 1/bk-th the VPU cost of scaling the (bq, bk) score
    matrix after the matmul."""
    q = q_ref[0].astype(_mm_dtype(q_ref)) * _mm_dtype(q_ref)(scale)
    k = k_ref[0].astype(_mm_dtype(k_ref))
    return lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _causal_keep(row0, col0, block_q: int, block_k: int):
    """The (block_q, block_k) boolean causal predicate (True = kept) for
    the block at origin (row0, col0) — origins may be traced (SMEM
    offsets) or static ints.  THE one mask-geometry definition for every
    kernel in this module: the forward masks scores to NEG_INF through
    it (:func:`_causal_mask`), the compact backward kernels select
    p -> 0 through it directly (the post-exp equivalent)."""
    rows = row0 + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = col0 + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return rows >= cols


def _causal_mask(s, row0, col0, block_q: int, block_k: int):
    """Mask ``s`` below the causal diagonal (see :func:`_causal_keep`)."""
    return jnp.where(_causal_keep(row0, col0, block_q, block_k), s, NEG_INF)


def _score_block(
    q_ref, k_ref, qoff_ref, koff_ref, i, j,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    """Scaled (and causally masked) score block + the masked-p guard.

    THE one definition shared by the dense forward and both backward
    kernels (the compact forward composes the same ``_raw_scores`` /
    ``_causal_mask`` pieces with static offsets). A masking fix applied
    here cannot leave forward and gradient inconsistent. Returns
    (s, guard) where ``p`` values must be passed through
    ``jnp.where(guard, p, 0.0)`` after exponentiation (rows whose every
    score is masked otherwise exponentiate s - m == 0)."""
    s = _raw_scores(q_ref, k_ref, scale)
    if causal:
        s = _causal_mask(
            s, qoff_ref[0] + i * block_q, koff_ref[0] + j * block_k,
            block_q, block_k,
        )
    return s, s > NEG_INF * 0.5


def _block_needed(qoff_ref, koff_ref, i, j, causal, block_q, block_k):
    """Block-level causal skip predicate (shared by all three kernels):
    a KV block strictly above the Q block's last row contributes
    nothing — its MXU/VPU work is skipped here, and its DMA is skipped
    by the ``_kv_clamp``/``_q_clamp`` index maps, which pin the block
    index at the diagonal so Mosaic's pipeline issues no new copy for
    masked-out grid steps (~2x on long causal sequences)."""
    if not causal:
        return True
    first_masked_col = qoff_ref[0] + (i + 1) * block_q
    return koff_ref[0] + j * block_k < first_masked_col


def _online_update(s, guard, v_ref, m_scr, l_scr, acc_scr):
    """One online-softmax accumulation of a masked score block into the
    running (m, l, acc) state — THE one update body shared by the dense
    and compact forward kernels. ``guard`` zeroes fully-masked rows
    (which keep m == NEG_INF, making s - m == 0 for masked entries) so
    correctness is hop-order independent (same guard as
    parallel/ring_attention.py); pass None for unmasked blocks."""
    m_prev = m_scr[:, 0]                       # (block_q,)
    l_prev = l_scr[:, 0]
    m_new = jnp.maximum(m_prev, s.max(axis=1))
    p = jnp.exp(s - m_new[:, None])
    if guard is not None:
        p = jnp.where(guard, p, 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + p.sum(axis=1)
    mmdt = _mm_dtype(v_ref)
    acc_scr[...] = acc_scr[...] * corr[:, None] + lax.dot(
        p.astype(mmdt), v_ref[0].astype(mmdt),
        preferred_element_type=jnp.float32,
    )
    m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)


def _online_first(s, guard, v_ref, m_scr, l_scr, acc_scr):
    """First KV step fused with state initialization: writes (m, l, acc)
    directly from the block instead of zero-initializing and then
    correcting — saves the acc zero-store, its read-back, and the corr
    multiply on every q block's first step. Equivalent by algebra:
    m_prev = -inf makes corr = 0 and l_prev = 0, so the first
    _online_update reduces to exactly this."""
    m_new = s.max(axis=1)
    p = jnp.exp(s - m_new[:, None])
    if guard is not None:
        p = jnp.where(guard, p, 0.0)
    l_new = p.sum(axis=1)
    mmdt = _mm_dtype(v_ref)
    acc_scr[...] = lax.dot(
        p.astype(mmdt), v_ref[0].astype(mmdt),
        preferred_element_type=jnp.float32,
    )
    m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)


def _emit_output(o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr):
    """Final write-out, shared by the dense and compact forward kernels."""
    if m_ref is None:
        l_fin = l_scr[:, 0]
        safe = jnp.where(l_fin > 0.0, l_fin, 1.0)  # fully-masked row->0
        o_ref[0] = (acc_scr[...] / safe[:, None]).astype(o_ref.dtype)
    else:
        # state mode: emit the RAW fp32 accumulator (no divide, no
        # dtype cast — the caller's softmax-merge stays exact) plus
        # the running max / normalizer broadcast over an 8-lane
        # plane. Mosaic requires lane-complete block stores and a
        # sublane-divisible block shape, which rules out both a bare
        # (1, block_q) state row and the full 128-lane broadcast;
        # 8 lanes is the narrowest legal layout (column 0 is read
        # back outside).
        o_ref[0] = acc_scr[...]
        m_ref[0] = m_scr[:, :8]
        l_ref[0] = l_scr[:, :8]


def _flash_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, nk: int,
    m_ref=None, l_ref=None,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    # j == 0 fuses init into the first accumulation (_online_first); it
    # runs unconditionally — when even the first block is fully masked
    # (a ring hop whose KV is entirely in the future), the mask zeroes p
    # and the fused write produces the same (NEG_INF, 0, 0) state the
    # explicit init did, at the cost of one wasted MXU block on a case
    # the schedule hits at most once per hop.
    @pl.when(j == 0)
    def _first():
        s, guard = _score_block(
            q_ref, k_ref, qoff_ref, koff_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        _online_first(
            s, guard if causal else None, v_ref, m_scr, l_scr, acc_scr
        )

    @pl.when(jnp.logical_and(
        j > 0,
        _block_needed(qoff_ref, koff_ref, i, j, causal, block_q, block_k),
    ))
    def _compute():
        s, guard = _score_block(
            q_ref, k_ref, qoff_ref, koff_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        _online_update(
            s, guard if causal else None, v_ref, m_scr, l_scr, acc_scr
        )

    @pl.when(j == nk - 1)
    def _emit():
        _emit_output(o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr)


def _flash_kernel_state(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
    m_scr, l_scr, acc_scr, **kw,
):
    """Positional reordering for the three-output variant: pallas passes
    (inputs..., outputs..., scratch...); the base kernel wants the state
    outputs as keywords."""
    _flash_kernel(
        qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref,
        m_scr, l_scr, acc_scr, m_ref=m_ref, l_ref=l_ref, **kw,
    )


# ---- compact causal grid -------------------------------------------------
#
# The dense (H, nq, nk) causal grid wastes two things even with the
# index-map DMA clamp: ~40% of grid steps are empty (masked-out blocks
# still step the pipeline), and every COMPUTED block pays the
# iota/compare/select masking cost although only the blocks straddling
# the diagonal need it. Measured on v5e at S=4096 (f32, bq=512/bk=1024)
# the two together cap causal at ~70 of the ~78 TFLOP/s the block
# granularity allows. The compact grid schedules exactly the needed
# (q block, kv block) pairs — grid (H, n_pairs) — through scalar-prefetch
# index tables, classifying each pair full (no mask math) or diagonal
# (masked): the splash-attention idea, rebuilt for this kernel's layout.
# Offsets must be compile-time ints (self-attention's 0/0 case); ring
# hops with traced offsets take the dense grid.

_FLAG_MASKED = 1  # block straddles the diagonal: apply the causal mask
_FLAG_EMIT = 2    # last scheduled kv block for this q block: emit output


def _compact_applies(bq: int, dq_off: int) -> bool:
    """The compact schedule exists iff even the FIRST q block reaches the
    diagonal (its last kv block index is >= 0); later blocks only reach
    further. Cheap dispatch test — ``_causal_pairs`` builds the actual
    tables inside the jitted path."""
    return dq_off + bq - 1 >= 0


def _causal_pairs(nq, nk, bq, bk, dq_off: int):
    """Static (i, j, flags) schedule for causal attention with
    row-col offset difference ``dq_off = q_offset - kv_offset``.
    Returns None when some q block needs no kv block at all (fully
    masked rows) — the dense grid handles that case."""
    pairs = []
    for i in range(nq):
        last = min(nk - 1, (dq_off + (i + 1) * bq - 1) // bk)
        if last < 0:
            return None
        for j in range(last + 1):
            full = (j + 1) * bk - 1 <= dq_off + i * bq
            flags = (0 if full else _FLAG_MASKED) | (
                _FLAG_EMIT if j == last else 0
            )
            pairs.append((i, j, flags))
    return pairs


def _flash_kernel_compact(
    i_tab, j_tab, flag_tab, q_ref, k_ref, v_ref, *rest,
    scale: float, qoff: int, koff: int, block_q: int, block_k: int,
    state: bool,
):
    if state:
        o_ref, m_ref, l_ref = rest[0], rest[1], rest[2]
        m_scr, l_scr, acc_scr = rest[3:]
    else:
        o_ref, m_ref, l_ref = rest[0], None, None
        m_scr, l_scr, acc_scr = rest[1:]
    p = pl.program_id(1)
    i, j, flags = i_tab[p], j_tab[p], flag_tab[p]

    def update(masked: bool, first: bool):
        s = _raw_scores(q_ref, k_ref, scale)
        guard = None
        if masked:
            s = _causal_mask(
                s, qoff + i * block_q, koff + j * block_k, block_q, block_k
            )
            guard = s > NEG_INF * 0.5
        body = _online_first if first else _online_update
        body(s, guard, v_ref, m_scr, l_scr, acc_scr)

    # first KV step fused with init (see _online_first); the masked/full
    # split stays so full blocks pay no mask arithmetic
    masked = flags & _FLAG_MASKED != 0

    @pl.when(jnp.logical_and(j == 0, masked))
    def _first_diagonal():
        update(True, True)

    @pl.when(jnp.logical_and(j == 0, jnp.logical_not(masked)))
    def _first_full():
        update(False, True)

    @pl.when(jnp.logical_and(j > 0, masked))
    def _diagonal():
        update(True, False)

    @pl.when(jnp.logical_and(j > 0, jnp.logical_not(masked)))
    def _full():
        update(False, False)

    @pl.when(flags & _FLAG_EMIT != 0)
    def _emit():
        _emit_output(o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr)


def _flash_fwd_compact(qh, kh, vh, qoff: int, koff: int, bq, bk,
                       return_state):
    """Compact-causal-grid forward. ``qoff``/``koff`` are Python ints
    (folded into the kernel); returns None when the schedule does not
    apply (caller falls back to the dense grid)."""
    H, S, D = qh.shape
    T = kh.shape[1]
    bk = _fwd_block_k(T, bk)
    nq, nk = S // bq, T // bk
    pairs = _causal_pairs(nq, nk, bq, bk, qoff - koff)
    if pairs is None:
        return None
    i_tab = jnp.asarray([p[0] for p in pairs], jnp.int32)
    j_tab = jnp.asarray([p[1] for p in pairs], jnp.int32)
    flag_tab = jnp.asarray([p[2] for p in pairs], jnp.int32)
    scale = 1.0 / float(D) ** 0.5
    kern = functools.partial(
        _flash_kernel_compact,
        scale=scale, qoff=qoff, koff=koff, block_q=bq, block_k=bk,
        state=return_state,
    )
    params = mosaic_params(dimension_semantics=("parallel", "arbitrary"))
    qspec = pl.BlockSpec((1, bq, D), lambda h, p, it, jt, ft: (h, it[p], 0))
    kvspec = pl.BlockSpec((1, bk, D), lambda h, p, it, jt, ft: (h, jt[p], 0))
    in_specs = [qspec, kvspec, kvspec]
    inputs = [qh, kh, vh]
    out_specs = [qspec]
    out_shape = [jax.ShapeDtypeStruct((H, S, D), qh.dtype)]
    if return_state:
        out_shape[0] = jax.ShapeDtypeStruct((H, S, D), jnp.float32)
        out_specs += [
            pl.BlockSpec((1, bq, 8), lambda h, p, it, jt, ft: (h, it[p], 0))
        ] * 2
        out_shape += [jax.ShapeDtypeStruct((H, S, 8), jnp.float32)] * 2
    res = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(H, len(pairs)),
            in_specs=in_specs,
            out_specs=out_specs if return_state else out_specs[0],
            scratch_shapes=[
                pltpu.VMEM((bq, _STATE_LANES), jnp.float32),
                pltpu.VMEM((bq, _STATE_LANES), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ],
        ),
        out_shape=out_shape if return_state else out_shape[0],
        interpret=use_interpret(),
        **params,
    )(i_tab, j_tab, flag_tab, *inputs)
    if return_state:
        acc, m, l = res
        return acc, m[..., 0], l[..., 0]
    return res


def _pick_block(n: int, want: int, name: str) -> int:
    """Largest power-of-two block <= want that divides n.

    Refuses blocks below the 8-row sublane quantum (unless the dimension
    itself is smaller): a sequence length with no power-of-two divisor
    would silently degrade to per-row grid steps, orders of magnitude
    slower than the dense fallback — pad the sequence instead."""
    b = want
    while b > 1 and n % b:
        b //= 2
    if b < 8 and n >= 8:
        raise ValueError(
            f"{name}={n} has no power-of-two block divisor >= 8; pad the "
            "sequence to a multiple of 8 (or use the dense xla path)"
        )
    return max(b, 1)


def _dq_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, nk: int,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_block_needed(qoff_ref, koff_ref, i, j, causal, block_q, block_k))
    def _compute():
        s, guard = _score_block(
            q_ref, k_ref, qoff_ref, koff_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        mmdt = _mm_dtype(k_ref)
        k = k_ref[0].astype(mmdt)
        v = v_ref[0].astype(mmdt)
        do = do_ref[0].astype(mmdt)
        lse = lse_ref[0][:, 0]
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(guard, p, 0.0)  # fully-masked-row guard
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, 0][:, None])
        # scale folded into the small (bk, D) k operand, not (bq, bk) ds
        dq_scr[...] += lax.dot(
            ds.astype(mmdt), k * mmdt(scale),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nk - 1)
    def _emit():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(
    qoff_ref, koff_ref, k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, nq: int,
):
    j = pl.program_id(1)  # kv block
    i = pl.program_id(2)  # q block (innermost, sequential)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_block_needed(qoff_ref, koff_ref, i, j, causal, block_q, block_k))
    def _compute():
        s, guard = _score_block(
            q_ref, k_ref, qoff_ref, koff_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        mmdt = _mm_dtype(q_ref)
        q = q_ref[0].astype(mmdt)
        v = v_ref[0].astype(mmdt)
        do = do_ref[0].astype(mmdt)
        lse = lse_ref[0][:, 0]
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(guard, p, 0.0)
        # dv += p^T @ do ; ds = p * (do v^T - delta) ; dk += ds^T @ q
        dv_scr[...] += lax.dot_general(
            p.astype(mmdt), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, 0][:, None])
        dk_scr[...] += lax.dot_general(
            ds.astype(mmdt), q * mmdt(scale), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == nq - 1)
    def _emit():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _causal_pairs_kv(nq, nk, bq, bk, dq_off: int):
    """Static (j, i, flags) schedule for the CAUSAL dkv backward — the
    kv-major mirror of :func:`_causal_pairs`: for each kv block j, only
    the q blocks at or below its diagonal (i >= first) contribute.
    Returns None when some kv block has no contributing q block (the
    dense grid handles that case)."""
    pairs = []
    for j in range(nk):
        first = max(0, (-dq_off + j * bk) // bq)
        if first >= nq:
            return None
        for i in range(first, nq):
            full = (j + 1) * bk - 1 <= dq_off + i * bq
            flags = (0 if full else _FLAG_MASKED) | (
                _FLAG_EMIT if i == nq - 1 else 0
            )
            pairs.append((j, i, flags))
    return pairs


def _dq_kernel_compact(
    i_tab, j_tab, flag_tab, q_ref, k_ref, v_ref, do_ref, lse_ref,
    delta_ref, dq_ref, dq_scr,
    *, scale: float, qoff: int, koff: int, block_q: int, block_k: int,
):
    """Compact-causal-grid dq: grid (H, n_pairs) over exactly the
    (q block, kv block) pairs at or below the diagonal (the forward's
    splash-style schedule, applied to the backward — masked-out pairs
    cost neither grid steps nor DMA, and interior pairs skip the mask
    arithmetic entirely)."""
    p_ = pl.program_id(1)
    i, j, flags = i_tab[p_], j_tab[p_], flag_tab[p_]
    masked = flags & _FLAG_MASKED != 0

    def compute(apply_mask: bool, first: bool):
        s = _raw_scores(q_ref, k_ref, scale)
        mmdt = _mm_dtype(k_ref)
        lse = lse_ref[0][:, 0]
        p = jnp.exp(s - lse[:, None])
        if apply_mask:
            # p -> 0 through the shared geometry (also zeroes
            # fully-masked rows, whose lse is the -inf sentinel)
            p = jnp.where(
                _causal_keep(qoff + i * block_q, koff + j * block_k,
                             block_q, block_k),
                p, 0.0,
            )
        do = do_ref[0].astype(mmdt)
        v = v_ref[0].astype(mmdt)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, 0][:, None])
        contrib = lax.dot(
            ds.astype(mmdt), k_ref[0].astype(mmdt) * mmdt(scale),
            preferred_element_type=jnp.float32,
        )
        if first:  # first KV pair fused with init (no zero-store)
            dq_scr[...] = contrib
        else:
            dq_scr[...] += contrib

    @pl.when(jnp.logical_and(j == 0, masked))
    def _fm():
        compute(True, True)

    @pl.when(jnp.logical_and(j == 0, jnp.logical_not(masked)))
    def _ff():
        compute(False, True)

    @pl.when(jnp.logical_and(j > 0, masked))
    def _m():
        compute(True, False)

    @pl.when(jnp.logical_and(j > 0, jnp.logical_not(masked)))
    def _f():
        compute(False, False)

    @pl.when(flags & _FLAG_EMIT != 0)
    def _emit():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel_compact(
    j_tab, i_tab, flag_tab, first_tab, k_ref, v_ref, q_ref, do_ref,
    lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale: float, qoff: int, koff: int, block_q: int, block_k: int,
):
    """Compact-causal-grid dk/dv: the kv-major mirror (pairs from
    :func:`_causal_pairs_kv`).  ``first_tab[p] == 1`` marks each kv
    block's first contributing q pair (init fuses into it)."""
    p_ = pl.program_id(1)
    i, j = i_tab[p_], j_tab[p_]
    flags = flag_tab[p_]
    first = first_tab[p_] == 1
    masked = flags & _FLAG_MASKED != 0

    def compute(apply_mask: bool, is_first: bool):
        s = _raw_scores(q_ref, k_ref, scale)
        mmdt = _mm_dtype(q_ref)
        lse = lse_ref[0][:, 0]
        p = jnp.exp(s - lse[:, None])
        if apply_mask:
            p = jnp.where(
                _causal_keep(qoff + i * block_q, koff + j * block_k,
                             block_q, block_k),
                p, 0.0,
            )
        do = do_ref[0].astype(mmdt)
        v = v_ref[0].astype(mmdt)
        q = q_ref[0].astype(mmdt)
        dv_c = lax.dot_general(
            p.astype(mmdt), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, 0][:, None])
        dk_c = lax.dot_general(
            ds.astype(mmdt), q * mmdt(scale), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if is_first:
            dv_scr[...] = dv_c
            dk_scr[...] = dk_c
        else:
            dv_scr[...] += dv_c
            dk_scr[...] += dk_c

    @pl.when(jnp.logical_and(first, masked))
    def _fm():
        compute(True, True)

    @pl.when(jnp.logical_and(first, jnp.logical_not(masked)))
    def _ff():
        compute(False, True)

    @pl.when(jnp.logical_and(jnp.logical_not(first), masked))
    def _m():
        compute(True, False)

    @pl.when(
        jnp.logical_and(jnp.logical_not(first), jnp.logical_not(masked))
    )
    def _f():
        compute(False, False)

    @pl.when(flags & _FLAG_EMIT != 0)
    def _emit():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_compact(q, k, v, do, lse, delta, qoff: int, koff: int,
                       bq, bk, out_dtype=None):
    """Compact-causal-grid backward (static int offsets).  Returns None
    when either schedule does not apply — the caller falls back to the
    dense-grid :func:`_flash_bwd_call`."""
    H, S, D = q.shape
    T = k.shape[1]
    # the compact backward reuses the forward's block resolution (its
    # grids are pair tables, not the scratch-bound dense sweep the
    # _bwd_block_k retune exists for)
    bk = _fwd_block_k(T, bk)
    nq, nk = S // bq, T // bk
    dq_off = qoff - koff
    pairs_q = _causal_pairs(nq, nk, bq, bk, dq_off)
    pairs_kv = _causal_pairs_kv(nq, nk, bq, bk, dq_off)
    if pairs_q is None or pairs_kv is None:
        return None
    scale = 1.0 / float(D) ** 0.5
    interpret = use_interpret()
    params = mosaic_params(dimension_semantics=("parallel", "arbitrary"))
    lse_p, delta_p = _plane(lse), _plane(delta)

    it_q = jnp.asarray([p[0] for p in pairs_q], jnp.int32)
    jt_q = jnp.asarray([p[1] for p in pairs_q], jnp.int32)
    ft_q = jnp.asarray([p[2] for p in pairs_q], jnp.int32)
    qspec = pl.BlockSpec((1, bq, D), lambda h, p, it, jt, ft: (h, it[p], 0))
    kvspec = pl.BlockSpec((1, bk, D), lambda h, p, it, jt, ft: (h, jt[p], 0))
    rowspec = pl.BlockSpec((1, bq, 8), lambda h, p, it, jt, ft: (h, it[p], 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel_compact, scale=scale, qoff=qoff, koff=koff,
            block_q=bq, block_k=bk,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(H, len(pairs_q)),
            in_specs=[qspec, kvspec, kvspec, qspec, rowspec, rowspec],
            out_specs=qspec,
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((H, S, D), out_dtype or q.dtype),
        interpret=interpret,
        **params,
    )(it_q, jt_q, ft_q, q, k, v, do, lse_p, delta_p)

    jt_k = jnp.asarray([p[0] for p in pairs_kv], jnp.int32)
    it_k = jnp.asarray([p[1] for p in pairs_kv], jnp.int32)
    ft_k = jnp.asarray([p[2] for p in pairs_kv], jnp.int32)
    # first contributing pair per kv block: position 0 or a j change
    first_k = jnp.asarray(
        [1 if (n == 0 or pairs_kv[n - 1][0] != p[0]) else 0
         for n, p in enumerate(pairs_kv)], jnp.int32,
    )
    kspec2 = pl.BlockSpec(
        (1, bk, D), lambda h, p, jt, it, ft, fi: (h, jt[p], 0)
    )
    qspec2 = pl.BlockSpec(
        (1, bq, D), lambda h, p, jt, it, ft, fi: (h, it[p], 0)
    )
    rowspec2 = pl.BlockSpec(
        (1, bq, 8), lambda h, p, jt, it, ft, fi: (h, it[p], 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel_compact, scale=scale, qoff=qoff, koff=koff,
            block_q=bq, block_k=bk,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(H, len(pairs_kv)),
            in_specs=[kspec2, kspec2, qspec2, qspec2, rowspec2, rowspec2],
            out_specs=[kspec2, kspec2],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((H, T, D), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((H, T, D), out_dtype or v.dtype),
        ],
        interpret=interpret,
        **params,
    )(jt_k, it_k, ft_k, first_k, k, v, q, do, lse_p, delta_p)
    return dq, dk, dv


def _plane(x):  # (H, S) -> (H, S, 8) lane-broadcast input plane
    return jnp.broadcast_to(x[:, :, None], (*x.shape, 8))


def _kv_clamp(causal, bq, bk, nk):
    """KV-side index map for the (h, q block, kv block) grids.

    For causal attention the map clamps the kv block index to the last
    block touching the q block's diagonal: grid steps beyond it keep the
    SAME block index, and Mosaic's pipeline only issues a copy when the
    index changes — so masked-out KV blocks cost neither compute (the
    ``_block_needed`` guard) nor DMA (this clamp). The offsets arrive as
    scalar-prefetch arguments, so ring hops with rotated origins clamp
    correctly at runtime."""
    if not causal:
        return lambda h, i, j, qoff, koff: (h, j, 0)

    def imap(h, i, j, qoff, koff):
        last = (qoff[0] - koff[0] + (i + 1) * bq - 1) // bk
        return h, jnp.maximum(0, jnp.minimum(j, last)), 0

    return imap


def _q_clamp(causal, bq, bk, nq):
    """Q-side index map for the (h, kv block, q block) dkv grid: the
    mirror clamp — q blocks strictly above a kv block's diagonal are
    masked, so the index is pinned at the first contributing q block."""
    if not causal:
        return lambda h, j, i, qoff, koff: (h, i, 0)

    def imap(h, j, i, qoff, koff):
        first = (koff[0] - qoff[0] + j * bk) // bq
        return h, jnp.minimum(nq - 1, jnp.maximum(i, first)), 0

    return imap


def _flash_bwd_call(q, k, v, do, lse, delta, qoff, koff, causal, bq, bk,
                    out_dtype=None):
    """dq/dk/dv via the two backward kernels. All of q/k/v/do are
    (H, SorT, D) head-major; lse/delta are (H, S). ``out_dtype``
    overrides the gradient dtype (callers accumulating across several
    calls — the ring backward — want fp32 partials, casting once at the
    end instead of quantizing every contribution)."""
    H, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    scale = 1.0 / float(D) ** 0.5
    interpret = use_interpret()
    params = mosaic_params(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )
    lse_p, delta_p = _plane(lse), _plane(delta)
    qspec = pl.BlockSpec((1, bq, D), lambda h, a, b, *_: (h, a, 0))
    kspec = pl.BlockSpec((1, bk, D), _kv_clamp(causal, bq, bk, nk))
    rowspec = pl.BlockSpec((1, bq, 8), lambda h, a, b, *_: (h, a, 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, nk=nk,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(H, nq, nk),
            in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
            out_specs=qspec,
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((H, S, D), out_dtype or q.dtype),
        interpret=interpret,
        **params,
    )(qoff, koff, q, k, v, do, lse_p, delta_p)
    # dkv grid: (h, kv block, q block); q-side specs index by the LAST
    # grid axis now
    qspec2 = pl.BlockSpec((1, bq, D), _q_clamp(causal, bq, bk, nq))
    kspec2 = pl.BlockSpec((1, bk, D), lambda h, b, a, *_: (h, b, 0))
    rowspec2 = pl.BlockSpec((1, bq, 8), _q_clamp(causal, bq, bk, nq))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, nq=nq,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(H, nk, nq),
            in_specs=[kspec2, kspec2, qspec2, qspec2, rowspec2, rowspec2],
            out_specs=[kspec2, kspec2],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((H, T, D), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((H, T, D), out_dtype or v.dtype),
        ],
        interpret=interpret,
        **params,
    )(qoff, koff, k, v, q, do, lse_p, delta_p)
    return dq, dk, dv


def _flash_fwd_call(qh, kh, vh, qoff, koff, causal, bq, bk, return_state):
    """The forward pallas_call, head-major: qh (H, S, D), kh/vh (H, T, D).
    Plain: out (H, S, D). State: (acc (H, S, D) f32, m (H, S), l (H, S))."""
    H, S, D = qh.shape
    T = kh.shape[1]
    bk = _fwd_block_k(T, bk)
    nq, nk = S // bq, T // bk
    scale = 1.0 / float(D) ** 0.5
    kern = functools.partial(
        _flash_kernel_state if return_state else _flash_kernel,
        scale=scale, causal=causal, block_q=bq, block_k=bk, nk=nk,
    )
    interpret = use_interpret()
    params = mosaic_params(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )
    kvspec = pl.BlockSpec((1, bk, D), _kv_clamp(causal, bq, bk, nk))
    out_specs = [pl.BlockSpec((1, bq, D), lambda h, i, j, *_: (h, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((H, S, D), qh.dtype)]
    if return_state:
        # raw fp32 accumulator + 8-lane state planes (column 0 = value)
        out_shape[0] = jax.ShapeDtypeStruct((H, S, D), jnp.float32)
        out_specs += [pl.BlockSpec((1, bq, 8), lambda h, i, j, *_: (h, i, 0))] * 2
        out_shape += [jax.ShapeDtypeStruct((H, S, 8), jnp.float32)] * 2
    res = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(H, nq, nk),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda h, i, j, *_: (h, i, 0)),
                kvspec,
                kvspec,
            ],
            out_specs=out_specs if return_state else out_specs[0],
            scratch_shapes=[
                pltpu.VMEM((bq, _STATE_LANES), jnp.float32),
                pltpu.VMEM((bq, _STATE_LANES), jnp.float32),
                pltpu.VMEM((bq, D), jnp.float32),
            ],
        ),
        out_shape=out_shape if return_state else out_shape[0],
        interpret=interpret,
        **params,
    )(qoff, koff, qh, kh, vh)
    if return_state:
        acc, m, l = res
        return acc, m[..., 0], l[..., 0]
    return res


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_diff(qh, kh, vh, qoff, koff, causal, bq, bk):
    """Differentiable head-major flash attention (the custom-vjp seam)."""
    return _flash_fwd_call(qh, kh, vh, qoff, koff, causal, bq, bk, False)


def _flash_diff_fwd(qh, kh, vh, qoff, koff, causal, bq, bk):
    acc, m, l = _flash_fwd_call(qh, kh, vh, qoff, koff, causal, bq, bk, True)
    l_safe = jnp.maximum(l, 1e-30)
    o = (acc / l_safe[:, :, None]).astype(qh.dtype)
    lse = m + jnp.log(l_safe)  # log-sum-exp: all the backward needs
    # o saved in the INPUT dtype (FlashAttention-2's choice): for bf16
    # training the residual costs half the fp32 accumulator; delta still
    # accumulates in fp32 from the casts
    return o, (qh, kh, vh, qoff, koff, o, lse)


#: forward KV-block tuning target when the caller leaves ``block_k=None``
_DEFAULT_BLOCK_K = 1024


def _fwd_block_k(T: int, bk) -> int:
    """Resolve the public ``block_k`` for the forward kernels: ``None``
    (the caller said nothing) takes the tuned default; an explicit value
    is a resource bound and is used as-is."""
    return _pick_block(T, _DEFAULT_BLOCK_K, "T") if bk is None else bk


def _bwd_block_k(dtype, T: int, bk) -> int:
    """Backward KV-block retune (round-5 chip race, BASELINE row 6):
    the dense backward kernels run fastest with bk=512 in f32 — at
    bk=1024 the dkv kernel's (bk, D) scratch pair sits at the
    scoped-vmem edge and measured UNSTABLE (1.4-2.4 ms across runs;
    bk=2048 is an outright compile DNF) — and bk=2048 in bf16 (half
    the bytes: 127.7 vs 109.2 TFLOP/s non-causal).  The backward
    kernels are block-independent of the forward (lse/delta are
    per-row), so the retune differs from the forward's — but ONLY on a
    true default: ``bk`` arrives as ``None`` when the caller left
    ``block_k`` unset, and anything else (including an explicit 1024)
    is a resource bound respected in the backward too (ADVICE r5)."""
    if bk is not None:
        return bk
    return _pick_block(T, 2048 if dtype == jnp.bfloat16 else 512, "T")


def _flash_diff_bwd(causal, bq, bk, res, do):
    qh, kh, vh, qoff, koff, o, lse = res
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (H, S)
    dq, dk, dv = _flash_bwd_call(
        qh, kh, vh, do, lse, delta, qoff, koff, causal, bq,
        _bwd_block_k(qh.dtype, kh.shape[1], bk),
    )
    # integer offsets are non-differentiable: float0 cotangents
    zero = np.zeros(qoff.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, zero, zero


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff_compact(qh, kh, vh, qoff, koff, bq, bk):
    """Differentiable compact-causal-grid flash attention. ``qoff``/
    ``koff`` are static ints; forward takes the compact grid, backward
    takes the compact backward grids (:func:`_flash_bwd_compact` —
    round 5), falling back to the dense-grid kernels when either pair
    schedule does not apply."""
    return _flash_fwd_compact(qh, kh, vh, qoff, koff, bq, bk, False)


def _flash_diff_compact_fwd(qh, kh, vh, qoff, koff, bq, bk):
    acc, m, l = _flash_fwd_compact(qh, kh, vh, qoff, koff, bq, bk, True)
    l_safe = jnp.maximum(l, 1e-30)
    o = (acc / l_safe[:, :, None]).astype(qh.dtype)
    lse = m + jnp.log(l_safe)
    return o, (qh, kh, vh, o, lse)


def _flash_diff_compact_bwd(qoff, koff, bq, bk, res, do):
    qh, kh, vh, o, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # static offsets -> the compact-causal backward grids (round 5:
    # masked-out pairs cost neither grid steps nor DMA, interior pairs
    # skip the mask arithmetic — the forward's schedule applied to the
    # backward); dense-grid fallback when the schedule does not apply
    r = _flash_bwd_compact(qh, kh, vh, do, lse, delta, qoff, koff, bq, bk)
    if r is None:
        r = _flash_bwd_call(
            qh, kh, vh, do, lse, delta,
            jnp.asarray(qoff, jnp.int32).reshape(1),
            jnp.asarray(koff, jnp.int32).reshape(1),
            True, bq, _bwd_block_k(qh.dtype, kh.shape[1], bk),
        )
    dq, dk, dv = r
    return dq, dk, dv


_flash_diff_compact.defvjp(_flash_diff_compact_fwd, _flash_diff_compact_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "return_state"),
)
def _flash_dense(q, k, v, causal, q_offset, kv_offset, block_q, block_k,
                 return_state):
    """Dense-grid path: any (possibly traced) offsets; masked-out causal
    blocks skip compute (``_block_needed``) and DMA (``_kv_clamp``)."""
    qh = jnp.swapaxes(q, 0, 1)  # (H, S, D)
    kh = jnp.swapaxes(k, 0, 1)
    vh = jnp.swapaxes(v, 0, 1)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(kv_offset, jnp.int32).reshape(1)
    if return_state:
        acc, m, l = _flash_fwd_call(
            qh, kh, vh, qoff, koff, causal, block_q, block_k, True
        )
        return jnp.swapaxes(acc, 0, 1), m, l
    out = _flash_diff(qh, kh, vh, qoff, koff, causal, block_q, block_k)
    return jnp.swapaxes(out, 0, 1)


@functools.partial(
    jax.jit,
    static_argnames=("q_offset", "kv_offset", "block_q", "block_k",
                     "return_state"),
)
def _flash_compact(q, k, v, q_offset, kv_offset, block_q, block_k,
                   return_state):
    """Compact-causal-grid path: static int offsets baked into the
    schedule tables and mask iotas."""
    qh = jnp.swapaxes(q, 0, 1)
    kh = jnp.swapaxes(k, 0, 1)
    vh = jnp.swapaxes(v, 0, 1)
    if return_state:
        acc, m, l = _flash_fwd_compact(
            qh, kh, vh, q_offset, kv_offset, block_q, block_k, True
        )
        return jnp.swapaxes(acc, 0, 1), m, l
    out = _flash_diff_compact(
        qh, kh, vh, q_offset, kv_offset, block_q, block_k
    )
    return jnp.swapaxes(out, 0, 1)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    q_offset=0,
    kv_offset=0,
    block_q: int = 1024,
    block_k: int | None = None,
    return_state: bool = False,
):
    """Exact attention with O(S·D) memory per head: q (S, H, D),
    k/v (T, H, D) -> (S, H, D). Offsets place the blocks in global
    coordinates for causal masking (both default 0: a self-contained
    sequence).

    Differentiable: a custom VJP recomputes score blocks from the saved
    log-sum-exp (the standard flash backward — two Pallas kernels
    producing dq and dk/dv, never materializing the (S, T) score
    matrix).

    Causal calls with compile-time int offsets (the ordinary
    self-attention case) take the compact grid: only the (q, kv) block
    pairs at or below the diagonal are scheduled (scalar-prefetch index
    tables), and interior blocks skip the mask arithmetic entirely.
    Traced offsets — ring-attention hops — take the dense grid, whose
    per-block predicate skips masked compute and whose clamped index
    maps skip the masked blocks' DMA.

    ``return_state=True`` changes the contract for cross-block merging
    (ring attention's hops): returns ``(acc, m, l)`` where ``acc`` is the
    UNNORMALIZED fp32 weighted sum (S, H, D) and ``m``/``l`` are the
    running max / normalizer, each (H, S) fp32. The caller merges blocks
    with ``acc*exp(m-m')`` algebra and divides by the merged ``l`` once
    at the end — exact, with no per-hop normalize/un-normalize round
    trip through the input dtype. The state mode is forward-only.

    ``block_k=None`` (the default) picks the tuned KV block per kernel —
    1024 forward, the per-dtype :func:`_bwd_block_k` retune backward; an
    explicit value (even 1024) is an explicit resource bound honored by
    BOTH directions."""
    if q.ndim != 3 or k.shape != v.shape or q.shape[1:] != k.shape[1:]:
        raise ValueError(f"bad attention shapes {q.shape}/{k.shape}/{v.shape}")
    S, H, D = q.shape
    T = k.shape[0]
    bq = _pick_block(S, block_q, "S")
    # None rides through dispatch so the backward can tell a true default
    # from an explicit 1024 (ADVICE r5); explicit values validate here
    bk = None if block_k is None else _pick_block(T, block_k, "T")

    static_offsets = isinstance(q_offset, (int, np.integer)) and isinstance(
        kv_offset, (int, np.integer)
    )
    if (
        causal
        and static_offsets
        and _compact_applies(bq, int(q_offset) - int(kv_offset))
    ):
        return _flash_compact(
            q, k, v, int(q_offset), int(kv_offset), bq, bk, return_state
        )
    return _flash_dense(
        q, k, v, causal, q_offset, kv_offset, bq, bk, return_state
    )


# ---- cached decode attention ---------------------------------------------


def _gather_pages(pages, table):
    """Each sequence's contiguous cache view IN THE POOL DTYPE: pages
    (P, page, H, D) gathered by a clipped (B, max_pages) table into
    (B, T, H, D).

    Quantized pools gather raw int8/fp8 bytes — a quarter of the fp32
    sweep, which is the decode roofline — and dequantization is FOLDED
    into the score/output contractions by the callers
    (:func:`_position_scale`): the per-page scale is constant across
    ``d_head``, so ``q . (k * s) == (q . k) * s`` up to fp
    reassociation, and the oracle never materializes a fp32
    ``(B, T, H, D)`` expansion of the pool it reads (its peak memory
    used to be 4x the int8 pool; now the gathered view stays 1 byte per
    element and the scale rides as a (B, T, H) plane)."""
    B, max_pages = table.shape
    page_size, H, D = pages.shape[1:]
    return pages[table].reshape(B, max_pages * page_size, H, D)


def _position_scale(page_scale, table, page_size):
    """Per-POSITION dequantization plane (B, T, H) from the per-page
    (P, H) scale plane: each page's scale repeated over its tokens —
    the small operand the dense oracle folds into its contractions
    instead of dequantizing the full (B, T, H, D) gather."""
    return jnp.repeat(page_scale[table], page_size, axis=1)


def _check_decode_operands(q, k_pages, v_pages, page_table, seq_lens):
    if k_pages.ndim != 4 or k_pages.shape != v_pages.shape:
        raise ValueError(
            f"bad decode shapes q={q.shape} k={k_pages.shape} "
            f"v={v_pages.shape}"
        )
    B, H, D = q.shape[0], q.shape[-2], q.shape[-1]
    n_pages, page_size, Hp, Dp = k_pages.shape
    if (Hp, Dp) != (H, D) or page_table.shape[0] != B or seq_lens.shape != (B,):
        raise ValueError(
            f"mismatched decode operands: q={q.shape} pages={k_pages.shape} "
            f"table={page_table.shape} lens={seq_lens.shape}"
        )


# ---- fused paged-attention kernel family ---------------------------------
#
# The decode sweep is ONE pass over the KV pool per step, and the dense
# formulation above pays it as three separate XLA ops — page gather,
# dequantize, attention — each a round trip through HBM.  The fused
# kernel streams every page exactly once: grid (batch, page), the page
# table scalar-prefetched so each sequence's pages DMA HBM -> VMEM in
# table order (Mosaic double-buffers the copies behind the compute),
# int8/fp8 pages dequantized in VMEM against their per-page scale
# planes, and the softmax accumulated flash-style (running max /
# normalizer revisited across page steps — the same online-update
# algebra as the training kernel above).  One kernel serves all three
# cached entry points: decode is K=1, speculative verify K=spec_k+1,
# chunked context prefill K=chunk — the K queries ride the same sweep,
# which is exactly the amortization argument those paths were built on.
#
# The dense formulation stays as the interpret-mode/CPU oracle and the
# fallback for unsupported geometries (the runtime/compat.py /
# stencil_kernel.py gating idiom: one numerics contract, the fast path
# behind a capability check).

_FUSED_ENV = "TPUSCRATCH_FUSED_ATTN"


def fused_attention_default() -> bool:
    """The fused-kernel policy when a caller passes ``fused=None``:
    ``TPUSCRATCH_FUSED_ATTN`` in {1, on, true} forces the Pallas kernel
    (interpret mode off-TPU — the oracle-equivalence tests run this),
    {0, off, false} forces the dense oracle, and unset follows the
    backend: fused on a real TPU, dense elsewhere (interpret-mode
    pallas is a correctness tool, not a CPU serving path)."""
    env = os.environ.get(_FUSED_ENV, "").strip().lower()
    if env in ("1", "on", "true"):
        return True
    if env in ("0", "off", "false"):
        return False
    return not use_interpret()


def paged_attention_supported(H: int, D: int, page_size: int,
                              kv_dtype) -> str | None:
    """None when the fused kernel supports this geometry on the CURRENT
    backend, else the reason it does not (the ``auto`` dispatch falls
    back to the dense oracle; ``fused=True`` raises it).

    Interpret mode accepts anything.  Compiled Mosaic wants lane/sublane
    -aligned blocks: D a multiple of 128 (lanes), H a multiple of 8
    (fp32 sublanes) so the (page, H, D) page block and the transposed
    (H, *, D) matmul operands lay out without per-step relayouts, and
    page_size >= 8 so a page spans at least one sublane tile.  The
    record-config-12 TPU geometry (H=8, D=128, page=16) qualifies;
    sub-byte-aligned toy geometries take the oracle.  The query count K
    is deliberately NOT a constraint (it rides the sublane dim of the
    (H*K, ·) state scratch, legal at any count)."""
    del kv_dtype  # quantized pools share the fp32 state layout in VMEM
    if use_interpret():
        return None
    if D % 128:
        return f"d_head {D} not a multiple of the 128-lane width"
    if H % 8:
        return f"n_heads {H} not a multiple of the 8-sublane quantum"
    if page_size % 8:
        return f"page_size {page_size} below/off the 8-sublane quantum"
    return None


#: VMEM scratch budget of the fused paged kernel, in STATE ROWS (the
#: sublane extent of the (H*K, ·) online-softmax scratch: m/l lanes +
#: the (H*K, D) fp32 accumulator).  The record-config-12 geometries sit
#: far under it (H=8, K<=16 -> 128 rows); a large-H model (e.g. H=128
#: at K=8 -> 1024+) overflows, and the grid then gains a head-block
#: axis (:func:`_head_block`).  Override for tests / other chips via
#: the env var.
_PAGED_STATE_ROWS_ENV = "TPUSCRATCH_PAGED_STATE_ROWS"
_PAGED_STATE_ROWS_DEFAULT = 512


def _paged_state_rows() -> int:
    env = os.environ.get(_PAGED_STATE_ROWS_ENV, "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return _PAGED_STATE_ROWS_DEFAULT


def _head_block(H: int, K: int) -> int:
    """Heads per grid step of the fused paged kernel: all of them while
    ``H*K`` state rows fit the scratch budget, else the largest divisor
    of ``H`` that does (compiled Mosaic additionally keeps the
    8-sublane quantum; interpret mode accepts any divisor).  Falls back
    to the full H when no divisor qualifies — the un-split kernel is
    still correct, just scratch-hungry."""
    budget = _paged_state_rows()
    if H * K <= budget:
        return H
    for h in range(H - 1, 0, -1):
        if H % h:
            continue
        if h * K > budget:
            continue
        if not use_interpret() and h % 8:
            continue
        return h
    return H


def _use_paged_kernel(fused: bool | None, hd: tuple[int, int],
                      k_pages) -> bool:
    """Resolve the ``fused`` argument of the cached entry points."""
    H, D = hd
    page_size = k_pages.shape[1]
    if fused is False:
        return False
    why = paged_attention_supported(H, D, page_size, k_pages.dtype)
    if fused is None:
        return fused_attention_default() and why is None
    if why is not None:
        raise ValueError(f"fused=True but the paged kernel cannot run: {why}")
    return True


def _paged_kernel(
    tbl_ref, len_ref, q_ref, k_ref, v_ref, *rest,
    scale: float, page: int, K: int, H: int, D: int, nj: int,
    quantized: bool, head_grid: bool = False,
):
    """One (sequence b[, head block h], page j) grid step of the fused
    sweep.

    Scalar-prefetch refs: tbl (B, max_pages) clipped page ids, lens (B,)
    true cached lengths.  Blocks: q (1, K, H, D) — constant across j;
    k/v (1, page, H, D) — THE page, in the pool dtype, selected by the
    prefetched table (the index map clamps past-the-end steps to the
    last needed page, so masked-out pages cost no DMA — the
    ``_kv_clamp`` idiom); ks/vs (1, H) scale planes when quantized.
    Scratch: m/l (H*K, 8) running max/normalizer (lane-broadcast, the
    ``_STATE_LANES`` layout), acc (H*K, D) fp32 accumulator.

    Rows are ordered head-major (row h*K + kq is head h, query kq) so
    the per-page score block computes as ONE head-batched MXU pass and
    the online-softmax state updates stay 2D elementwise.

    ``head_grid``: the LARGE-H variant — the grid gains a head-block
    axis (B, H/Hb, max_pages) when the full ``H*K`` state rows would
    overflow the VMEM scratch budget (``_paged_state_rows``), and
    ``H`` here is the per-block head count Hb.  Each (b, h) pair runs
    its own page sweep against its own scratch; the head axis rides
    the BLOCK index maps, so this kernel body is unchanged beyond
    which program_id is the page step."""
    if quantized:
        ks_ref, vs_ref, o_ref = rest[0], rest[1], rest[2]
        m_scr, l_scr, acc_scr = rest[3:]
    else:
        ks_ref = vs_ref = None
        o_ref = rest[0]
        m_scr, l_scr, acc_scr = rest[1:]
    b = pl.program_id(0)
    j = pl.program_id(2 if head_grid else 1)
    seq_len = len_ref[b]
    # pages this sequence's sweep must read: query position kq attends
    # cache entries < seq_len + kq, so the frontier is seq_len + K - 1
    n_need = (seq_len + K - 1 + page - 1) // page

    def dequant(ref, s_ref):
        x = ref[0].astype(jnp.float32)                 # (page, H, D)
        if quantized:
            x = x * s_ref[0][None, :, None]            # (H,) scale plane
        return x

    def masked_scores():
        k = dequant(k_ref, ks_ref)
        qh = jnp.swapaxes(q_ref[0].astype(jnp.float32), 0, 1)  # (H, K, D)
        kh = jnp.swapaxes(k, 0, 1)                             # (H, page, D)
        s = lax.dot_general(
            qh, kh, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                              # (H, K, page)
        t = j * page + lax.broadcasted_iota(jnp.int32, (H, K, page), 2)
        kq = lax.broadcasted_iota(jnp.int32, (H, K, page), 1)
        s = jnp.where(t < seq_len + kq, s, NEG_INF)
        s2 = s.reshape(H * K, page)
        return s2, s2 > NEG_INF * 0.5

    def pv(p2):
        """(H*K, page) probabilities x the dequantized page -> (H*K, D)."""
        vh = jnp.swapaxes(dequant(v_ref, vs_ref), 0, 1)        # (H, page, D)
        c = lax.dot_general(
            p2.reshape(H, K, page), vh, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return c.reshape(H * K, D)

    # first page fuses init into the accumulation (_online_first's
    # algebra); an IDLE slot (seq_len == 0) initializes empty state
    # instead, so the emit divides 0/1 and returns the oracle's zeros
    @pl.when(jnp.logical_and(j == 0, seq_len > 0))
    def _first():
        s2, guard = masked_scores()
        m_new = s2.max(axis=1)
        p = jnp.where(guard, jnp.exp(s2 - m_new[:, None]), 0.0)
        l_new = p.sum(axis=1)
        acc_scr[...] = pv(p)
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(jnp.logical_and(j == 0, seq_len == 0))
    def _idle():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    # seq_len > 0 guard: an IDLE slot still has n_need = ceil((K-1)/page)
    # > 1 when K exceeds page_size + 1 (draft/chunk queries extend the
    # frontier past page 0 even with nothing cached), and its ragged
    # mask `t < 0 + kq` would admit whatever clamped page the sentinel
    # table points at — the dense oracle's `seq_lens > 0` guard, here
    @pl.when(jnp.logical_and(seq_len > 0,
                             jnp.logical_and(j > 0, j < n_need)))
    def _update():
        s2, guard = masked_scores()
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, s2.max(axis=1))
        p = jnp.where(guard, jnp.exp(s2 - m_new[:, None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, 0] * corr + p.sum(axis=1)
        acc_scr[...] = acc_scr[...] * corr[:, None] + pv(p)
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == nj - 1)
    def _emit():
        l_fin = l_scr[:, 0]
        safe = jnp.where(l_fin > 0.0, l_fin, 1.0)
        o = (acc_scr[...] / safe[:, None]).reshape(H, K, D)
        o_ref[0] = jnp.swapaxes(o, 0, 1).astype(o_ref.dtype)


def paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    seq_lens: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """The fused Pallas paged-attention sweep: q (B, K, H, D) against a
    (P, page, H, D) page pool -> (B, K, H, D), streaming each needed
    page from HBM exactly once — gather + dequantize + flash-style
    attention in ONE kernel (see the section comment above).  Operand
    contract (tables, sentinels, ragged ``seq_lens``, idle slots,
    quantized scale planes) is exactly :func:`verify_attention`'s; the
    public entry points dispatch here, callers should not need to.

    Numerics: fp32 throughout (quantized pages dequantize in VMEM
    before the MXU), online-softmax accumulation — equal to the dense
    oracle up to summation-order reassociation (the oracle-equivalence
    property tests in tests/test_attention.py pin the bound)."""
    B, K, H, D = q.shape
    n_pages, page_size = k_pages.shape[:2]
    max_pages = page_table.shape[1]
    quantized = k_scale is not None
    table = jnp.clip(page_table, 0, n_pages - 1).astype(jnp.int32)
    lens = seq_lens.astype(jnp.int32)
    # head-grid variant (ISSUE 15, the PR-12 large-H remainder): when
    # the full H*K state rows overflow the VMEM scratch budget, the
    # grid gains a head-block axis and each (sequence, head block)
    # pair runs its own page sweep — heads are independent in
    # attention, so splitting them changes nothing but the scratch
    # footprint (oracle-equivalence pinned at FUSED_PAGED_ATOL)
    Hb = _head_block(H, K)
    head_grid = Hb < H

    if head_grid:
        def kv_imap(b, h, j, tbl, ln):
            last = jnp.maximum(
                (ln[b] + K - 1 + page_size - 1) // page_size - 1, 0
            )
            return tbl[b, jnp.minimum(j, last)], 0, h, 0

        def scale_imap(b, h, j, tbl, ln):
            p_, _, _, _ = kv_imap(b, h, j, tbl, ln)
            return p_, h

        qspec = pl.BlockSpec(
            (1, K, Hb, D), lambda b, h, j, tbl, ln: (b, 0, h, 0)
        )
        grid = (B, H // Hb, max_pages)
        semantics = ("parallel", "parallel", "arbitrary")
    else:
        def kv_imap(b, j, tbl, ln):
            last = jnp.maximum(
                (ln[b] + K - 1 + page_size - 1) // page_size - 1, 0
            )
            return tbl[b, jnp.minimum(j, last)], 0, 0, 0

        def scale_imap(b, j, tbl, ln):
            p_, _, _, _ = kv_imap(b, j, tbl, ln)
            return p_, 0

        qspec = pl.BlockSpec((1, K, Hb, D), lambda b, j, tbl, ln: (b, 0, 0, 0))
        grid = (B, max_pages)
        semantics = ("parallel", "arbitrary")
    kvspec = pl.BlockSpec((1, page_size, Hb, D), kv_imap)
    in_specs = [qspec, kvspec, kvspec]
    inputs = [q, k_pages, v_pages]
    if quantized:
        sspec = pl.BlockSpec((1, Hb), scale_imap)
        in_specs += [sspec, sspec]
        inputs += [k_scale, v_scale]
    kern = functools.partial(
        _paged_kernel,
        scale=1.0 / float(D) ** 0.5, page=page_size,
        K=K, H=Hb, D=D, nj=max_pages, quantized=quantized,
        head_grid=head_grid,
    )
    params = mosaic_params(dimension_semantics=semantics)
    return pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=qspec,
            scratch_shapes=[
                pltpu.VMEM((Hb * K, _STATE_LANES), jnp.float32),
                pltpu.VMEM((Hb * K, _STATE_LANES), jnp.float32),
                pltpu.VMEM((Hb * K, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, H, D), q.dtype),
        interpret=use_interpret(),
        **params,
    )(table, lens, *inputs)


def _verify_attention_dense(q, k_pages, v_pages, page_table, seq_lens,
                            k_scale, v_scale):
    """The dense-XLA formulation — the interpret-mode/CPU ORACLE and
    fallback for the fused paged kernel, for BOTH entry points (decode
    dispatches through it at K=1, exactly as the fused branch does).
    Quantization scales fold into the score/output contractions (see
    :func:`_gather_pages`); the clip before gathering lands sentinel
    table entries on page 0, whose scores the length mask removes."""
    B, K, H, D = q.shape
    n_pages, page_size = k_pages.shape[:2]
    table = jnp.clip(page_table, 0, n_pages - 1)
    T = page_table.shape[1] * page_size
    k = _gather_pages(k_pages, table)             # ONE sweep for K queries
    v = _gather_pages(v_pages, table)
    scale = 1.0 / float(D) ** 0.5
    s = jnp.einsum(
        "bkhd,bthd->bkht", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if k_scale is not None:
        ks = _position_scale(k_scale, table, page_size)     # (B, T, H)
        s = s * ks.transpose(0, 2, 1)[:, None]              # (B, 1, H, T)
    lens = seq_lens[:, None, None, None] + jnp.arange(K)[None, :, None, None]
    valid = jnp.arange(T)[None, None, None, :] < lens       # (B, K, 1, T)
    valid = valid & (seq_lens[:, None, None, None] > 0)     # idle slots -> 0
    p = masked_softmax(jnp.where(valid, s, NEG_INF), valid)
    if v_scale is not None:
        vs = _position_scale(v_scale, table, page_size)
        p = p * vs.transpose(0, 2, 1)[:, None]
    out = jnp.einsum("bkht,bthd->bkhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    seq_lens: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    fused: bool | None = None,
) -> jax.Array:
    """Single-token attention over a block-paged KV cache (serve path).

    q (B, H, D) — each sequence's current-token query; k_pages/v_pages
    (P, page_size, H, D) — one layer's page pool (``tpuscratch.serve.
    kvcache`` layout); page_table (B, max_pages) int32 — each sequence's
    page ids in sequence order, with out-of-range ids (the allocator's
    sentinel) marking unallocated tail entries; seq_lens (B,) int32 —
    each sequence's true cached length INCLUDING the current token
    (its K/V must already be written). Returns (B, H, D).

    ``k_scale``/``v_scale`` (P, H) fp32 — required when the pools are
    quantized (int8 / fp8-e4m3, ``serve.kvcache.quantize_pages``
    layout): the gather moves the 1-byte pages (a quarter of the fp32
    bytes — and bytes ARE the decode roofline) and the scale folds into
    the score/output contractions.

    Each sequence reads its pages in table order and masks key
    positions at or beyond its true length — the ragged-batch analogue
    of the flash kernel's causal offset masking, sharing its scale
    (1/sqrt(D)) and mask sentinel so the cached path cannot drift from
    the training-side score math.  Sequences with ``seq_len == 0``
    (empty decode slots) return zeros rather than NaN.

    ``fused`` selects the kernel: ``True`` runs the Pallas paged
    kernel (:func:`paged_attention` — page gather, dequantize, and
    flash-style accumulation in ONE pass over the pool, the
    ``resident:8`` residency idiom applied to serving); ``False`` the
    dense XLA oracle (three separate ops — gather, dequantize-fold,
    attention); ``None`` (default) follows :func:`fused_attention_
    default` — fused on a real TPU when the geometry is supported,
    dense elsewhere, overridable via ``TPUSCRATCH_FUSED_ATTN``.
    """
    if q.ndim != 3:
        raise ValueError(f"bad decode shapes q={q.shape}")
    _check_decode_operands(q, k_pages, v_pages, page_table, seq_lens)
    kernel = (
        paged_attention if _use_paged_kernel(fused, q.shape[-2:], k_pages)
        else _verify_attention_dense
    )
    out = kernel(
        q[:, None], k_pages, v_pages, page_table, seq_lens, k_scale, v_scale
    )
    return out[:, 0]


def verify_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    seq_lens: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    fused: bool | None = None,
) -> jax.Array:
    """Speculative-verify attention: K queued tokens per sequence attend
    the paged cache through ONE gather (serve verify path — and, through
    ``serve.decode.build_context_prefill``, the chunked-prefill path:
    the two are the same compiled shape).

    q (B, K, H, D) — position 0 is the last accepted token, positions
    1..K-1 the draft; pools/table/scales as in :func:`decode_attention`;
    seq_lens (B,) is the cached length INCLUDING position 0 (all K
    positions' K/V must already be written).  Position j attends the
    first ``seq_lens + j`` cache entries — the ragged-causal mask over
    in-flight draft tokens.  Returns (B, K, H, D); ``seq_len == 0``
    slots return zeros at every position.

    This is the HBM-sweep amortization speculative decoding buys: plain
    decode pays one full cache gather per generated token, the verify
    step pays ONE gather for K scored positions — up to K tokens
    emitted per sweep when the draft holds (Leviathan et al. 2023).

    ``fused`` selects the kernel exactly as in
    :func:`decode_attention` — the SAME Pallas kernel serves decode
    (K=1), verify (K=spec_k+1), and context prefill (K=chunk).
    """
    if q.ndim != 4:
        raise ValueError(f"bad verify shapes q={q.shape}")
    _check_decode_operands(q, k_pages, v_pages, page_table, seq_lens)
    if _use_paged_kernel(fused, q.shape[-2:], k_pages):
        return paged_attention(
            q, k_pages, v_pages, page_table, seq_lens, k_scale, v_scale
        )
    return _verify_attention_dense(
        q, k_pages, v_pages, page_table, seq_lens, k_scale, v_scale
    )
