"""Shared kernel plumbing: interpret-mode selection and shape blocking."""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANES = 128  # TPU lane width: last dim of every tile


def use_interpret() -> bool:
    """Pallas interpreter off-TPU — one kernel source, both backends.

    The analogue of the reference's #ifdef GPU dual path (mpicuda2.cu:176),
    but with no second implementation to keep in sync.
    """
    return jax.default_backend() != "tpu"


def to_lanes(x: jax.Array, sublanes_multiple: int = 8) -> jax.Array:
    """Reshape a vector to (rows, 128), zero-padding to full tiles.

    TPU vector registers are (sublane, lane) tiles; 1D reductions are run
    as 2D reductions over this layout. Zero padding is neutral for
    sum-reductions.
    """
    n = x.shape[0]
    row_quantum = LANES * sublanes_multiple
    padded = (n + row_quantum - 1) // row_quantum * row_quantum
    if padded != n:
        x = jnp.pad(x, (0, padded - n))
    return x.reshape(-1, LANES)


def interpret_params():
    """TPU-simulating interpret mode for the DMA/semaphore kernels:
    ``pltpu.InterpretParams`` where this jax has it, plain
    ``interpret=True`` on older releases (which may reject the
    DMA/semaphore primitives at run time — same failure surface as
    before, minus the import-time crash)."""
    from jax.experimental.pallas import tpu as pltpu

    ip = getattr(pltpu, "InterpretParams", None)
    return ip() if ip is not None else True


def mosaic_params(**kw) -> dict:
    """``{"compiler_params": CompilerParams(**kw)}`` on TPU, ``{}`` in
    interpret mode (where Mosaic compiler knobs don't exist). Spread into
    ``pl.pallas_call(..., **mosaic_params(...))``."""
    if use_interpret():
        return {}
    from jax.experimental.pallas import tpu as pltpu

    return {"compiler_params": pltpu.CompilerParams(**kw)}
