"""Double-buffered remote-DMA halo stencil — the kernel-level async halo.

The reference's hot loop posts all Irecvs, then all Isends, then one
Waitall (ExchangeData, /root/reference/stencil2d/stencil2D.h:363-377),
so the NIC moves ghost strips while the host is free to compute. The
XLA-level analogue in ``halo.stencil.stencil_step_overlap`` merely hopes
the compiler schedules the 8 ``ppermute``s concurrently with the interior
FLOPs; this module makes the overlap structural. Each device's tile core
stays resident in VMEM for the WHOLE multi-step run, and every step's
ghost strips travel by inter-chip remote DMA
(``pltpu.make_async_remote_copy``) that is started before — and completes
under — the interior compute. Per direction there are TWO receive slots
used alternately (double buffering), so step s+1's strips can fly while
step s's are still being read, and a credit handshake (one semaphore per
send channel) stops a sender from overwriting a slot its receiver has not
consumed yet.

Per-device protocol (SPMD, inside shard_map over the 2D mesh):

    entry barrier with the 4 neighbors            [absorbs launch skew]
    for s in 0..steps-1:
        wait 1 credit per channel                 [only for s >= 2]
        start 4 RDMAs: core edge strips -> neighbors' recv[s % 2]
        interior <- 5-point(core interior)        [overlaps the DMAs]
        wait the 4 arrival semaphores             [the Waitall]
        ring <- 5-point(core ring, recv strips)
        signal 1 credit back to each strip's sender  [only if s+2 < steps]
        wait the 4 send semaphores                [source reuse is safe]

Channel naming: channel ``d`` fills the RECEIVER's ``d``-side halo, so a
device sends its ``opposite(d)`` core edge to its ``opposite(d)`` neighbor
(e.g. channel TOP carries my bottom core row to my south neighbor, whose
top halo row is exactly my bottom core row on the torus). Strips are one
cell deep — all a 5-point stencil reads — independent of the layout's
declared halo width; the caller re-wraps the padded tile afterwards.

Axes of size 1 wrap onto the device itself; those channels become local
VMEM-to-VMEM async copies (statically — the topology is compile-time), so
a 1x1 mesh runs the same kernel as a self-wrap with no remote traffic,
and the semaphore/credit machinery degenerates away where it is not
needed. Semaphores all drain to zero by kernel exit (credits are only
issued when a future step will consume them).

Off-TPU the kernel runs under the Mosaic TPU interpreter
(``pltpu.InterpretParams``), which simulates HBM/VMEM, DMAs, and
semaphores on the CPU mesh — the same one-source dual-backend policy as
``ops.common.use_interpret``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuscratch.halo.exchange import HaloSpec, halo_exchange
from tpuscratch.halo.stencil import rebuild
from tpuscratch.ops.common import interpret_params, use_interpret



Coeffs = tuple[float, float, float, float, float]
JACOBI: Coeffs = (0.25, 0.25, 0.25, 0.25, 0.0)

#: Channel order: the halo side each channel fills at its receiver.
TOP, BOTTOM, LEFT, RIGHT = range(4)
#: Corner channels (the generalized kernel only): receiver's pad corner.
NW, NE, SW, SE = range(4, 8)

#: Distinct collective_id for the barrier semaphore of this kernel family.
_COLLECTIVE_ID = 11
#: ...and for the generalized (depth-k, corner-carrying) kernel.
_COLLECTIVE_ID_DEEP = 12
#: ...and for the HBM-resident banded kernel (one invocation per step).
_COLLECTIVE_ID_HBM = 13

#: (dy, dx) per coefficient, in halo.stencil.nine_point coeff order
#: (n, s, w, e, nw, ne, sw, se, center).
_OFFS9 = (
    (-1, 0), (1, 0), (0, -1), (0, 1),
    (-1, -1), (-1, 1), (1, -1), (1, 1), (0, 0),
)


def as_nine(coeffs) -> tuple[float, ...]:
    """Normalize 5-point (n,s,w,e,c) to 9-point coeff order with zero
    diagonals; 9-tuples pass through."""
    c = tuple(float(x) for x in coeffs)
    if len(c) == 9:
        return c
    if len(c) == 5:
        return c[:4] + (0.0, 0.0, 0.0, 0.0) + c[4:]
    raise ValueError(f"coeffs must have 5 or 9 entries, got {len(c)}")


def _patch(s, r0: int, r1: int, c0: int, c1: int, coeffs9):
    """9-point update of padded-coordinate region [r0,r1)x[c0,c1), read
    from the loaded padded array ``s``. Zero coefficients are skipped
    statically, so a 5-point stencil pays no diagonal FLOPs."""
    h, w = r1 - r0, c1 - c0
    acc = None
    for (dy, dx), cc in zip(_OFFS9, coeffs9):
        if cc == 0.0:
            continue
        term = cc * s[r0 + dy : r0 + dy + h, c0 + dx : c0 + dx + w]
        acc = term if acc is None else acc + term
    return acc


def _interior(src, coeffs: Coeffs):
    """New values for core cells [1:H-1, 1:W-1] — no halo dependency."""
    cn, cs, cw, ce, cc = coeffs
    return (
        cn * src[0:-2, 1:-1]
        + cs * src[2:, 1:-1]
        + cw * src[1:-1, 0:-2]
        + ce * src[1:-1, 2:]
        + cc * src[1:-1, 1:-1]
    )


def _ring(src, top, bot, left, right, coeffs: Coeffs):
    """New values for the core's outermost ring, reading the freshly
    arrived 1-deep strips. Returns (new_top_row, new_bottom_row,
    new_left_col, new_right_col); the columns exclude the corner cells
    (those are produced by the row pieces)."""
    cn, cs, cw, ce, cc = coeffs
    H = src.shape[0]
    new_top = (
        cn * top
        + cs * src[1:2, :]
        + cw * jnp.concatenate([left[0:1, :], src[0:1, :-1]], axis=1)
        + ce * jnp.concatenate([src[0:1, 1:], right[0:1, :]], axis=1)
        + cc * src[0:1, :]
    )
    new_bot = (
        cn * src[-2:-1, :]
        + cs * bot
        + cw * jnp.concatenate([left[-1:, :], src[-1:, :-1]], axis=1)
        + ce * jnp.concatenate([src[-1:, 1:], right[-1:, :]], axis=1)
        + cc * src[-1:, :]
    )
    new_left = (
        cn * src[0 : H - 2, 0:1]
        + cs * src[2:H, 0:1]
        + cw * left[1 : H - 1, :]
        + ce * src[1 : H - 1, 1:2]
        + cc * src[1 : H - 1, 0:1]
    )
    new_right = (
        cn * src[0 : H - 2, -1:]
        + cs * src[2:H, -1:]
        + cw * src[1 : H - 1, -2:-1]
        + ce * right[1 : H - 1, :]
        + cc * src[1 : H - 1, -1:]
    )
    return new_top, new_bot, new_left, new_right


def _make_kernel(dims: tuple[int, int], axes: tuple[str, str], steps: int, coeffs: Coeffs):
    R, C = dims
    ns_remote = R > 1  # north/south are other devices
    ew_remote = C > 1

    def kernel(in_ref, o_ref, buf_ref, r_top, r_bot, r_left, r_right, s_top, s_bot, s_left, s_right, send_sem, recv_sem, freed_sem):
        H, W = in_ref.shape
        row = lax.axis_index(axes[0])
        col = lax.axis_index(axes[1])
        north = lax.rem(row + R - 1, R) * C + col
        south = lax.rem(row + 1, R) * C + col
        west = row * C + lax.rem(col + C - 1, C)
        east = row * C + lax.rem(col + 1, C)

        # channel -> (destination device, receive-buffer ref)
        # channel d fills the receiver's d-side halo, so its destination
        # is my opposite(d) neighbor and my own arrival lands in recv[d].
        dests = {TOP: south, BOTTOM: north, LEFT: east, RIGHT: west}
        senders = {TOP: north, BOTTOM: south, LEFT: west, RIGHT: east}
        bufs = {TOP: r_top, BOTTOM: r_bot, LEFT: r_left, RIGHT: r_right}
        remote = {TOP: ns_remote, BOTTOM: ns_remote, LEFT: ew_remote, RIGHT: ew_remote}

        # Edge strips cannot be DMA'd straight out of the core buffer: TPU
        # DMA addresses whole (sublane, lane) tiles, so a 1-row slice at an
        # arbitrary sublane offset or a 1-column lane slice is unaddressable.
        # Each strip is therefore staged by a VPU copy into its own
        # lane-padded (1, len) buffer (columns transposed to lane-major) and
        # the DMA moves the whole staging buffer; the padded tail is never
        # read. The reference's subarray datatypes solve the same
        # strided-strip problem on the MPI side (stencil2D.h:210-228).
        stages = {TOP: s_top, BOTTOM: s_bot, LEFT: s_left, RIGHT: s_right}

        def stage(src_ref, ch):
            if ch == TOP:      # my bottom row -> south's top halo
                s_top[:, 0:W] = src_ref[H - 1 : H, :]
            elif ch == BOTTOM:  # my top row -> north's bottom halo
                s_bot[:, 0:W] = src_ref[0:1, :]
            elif ch == LEFT:   # my right col -> east's left halo
                s_left[:, 0:H] = jnp.swapaxes(src_ref[:, -1:], 0, 1)
            else:              # my left col -> west's right halo
                s_right[:, 0:H] = jnp.swapaxes(src_ref[:, 0:1], 0, 1)

        if ns_remote or ew_remote:
            # Entry barrier: nobody sends until all four partner devices
            # have entered the kernel (their semaphores/scratch exist).
            barrier = pltpu.get_barrier_semaphore()
            n_remote = 0
            for ch in (TOP, BOTTOM, LEFT, RIGHT):
                if remote[ch]:
                    pltpu.semaphore_signal(
                        barrier, inc=1, device_id=dests[ch],
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    )
                    n_remote += 1
            pltpu.semaphore_wait(barrier, n_remote)

        def one_step(src_ref, dst_ref, slot: int, wait_credit: bool, give_credit: bool):
            copies = []
            for ch in (TOP, BOTTOM, LEFT, RIGHT):
                stage(src_ref, ch)
                if remote[ch]:
                    if wait_credit:
                        pltpu.semaphore_wait(freed_sem.at[ch], 1)
                    dma = pltpu.make_async_remote_copy(
                        src_ref=stages[ch].at[:],
                        dst_ref=bufs[ch].at[slot],
                        send_sem=send_sem.at[ch],
                        recv_sem=recv_sem.at[ch, slot],
                        device_id=dests[ch],
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    )
                else:
                    # self-wrap axis: a local VMEM-to-VMEM async copy; no
                    # credits needed — my own step order serializes reuse.
                    dma = pltpu.make_async_copy(
                        stages[ch].at[:],
                        bufs[ch].at[slot],
                        recv_sem.at[ch, slot],
                    )
                copies.append((ch, dma))
                dma.start()

            src = src_ref[:]
            dst_ref[1:-1, 1:-1] = _interior(src, coeffs)  # overlaps the DMAs

            for ch, dma in copies:
                dma.wait_recv() if remote[ch] else dma.wait()

            new_top, new_bot, new_left, new_right = _ring(
                src,
                bufs[TOP][slot][:, 0:W],
                bufs[BOTTOM][slot][:, 0:W],
                jnp.swapaxes(bufs[LEFT][slot][:, 0:H], 0, 1),
                jnp.swapaxes(bufs[RIGHT][slot][:, 0:H], 0, 1),
                coeffs,
            )
            dst_ref[0:1, :] = new_top
            dst_ref[-1:, :] = new_bot
            dst_ref[1:-1, 0:1] = new_left
            dst_ref[1:-1, -1:] = new_right

            for ch, dma in copies:
                if remote[ch]:
                    if give_credit:
                        pltpu.semaphore_signal(
                            freed_sem.at[ch], inc=1, device_id=senders[ch],
                            device_id_type=pltpu.DeviceIdType.LOGICAL,
                        )
                    dma.wait_send()

        # Static step schedule. Result must land in o_ref: with buffers
        # alternating every step, step 0 writes o_ref iff steps is odd.
        A, B = buf_ref, o_ref
        dst0 = B if steps % 2 == 1 else A

        def plan(s: int):
            """(src, dst, slot, wait_credit, give_credit) for step s."""
            src = in_ref if s == 0 else (dst0 if (s % 2 == 1) else (A if dst0 is B else B))
            dst = dst0 if s % 2 == 0 else (A if dst0 is B else B)
            return src, dst, s % 2, s >= 2, s + 2 <= steps - 1

        # Steps 0..min(steps, 4)-1 inline (covers prologue with no credit
        # wait and, for tiny step counts, the whole run)...
        head = min(steps, 4)
        for s in range(head):
            src, dst, slot, w, g = plan(s)
            one_step(src, dst, slot, w, g)

        # ...then the steady state s in [4, steps-2) as a fori_loop of
        # unrolled step pairs (all wait AND give credits; parity of s is
        # static inside the pair), and a static epilogue for the last
        # step(s), which wait but never give.
        if steps > head:
            mid = max(0, steps - 2 - head)  # steps in [head, steps-2): wait+give
            pairs, rem = divmod(mid, 2)
            s4, s5 = plan(4)[:2], plan(5)[:2]

            def pair(_, carry):
                one_step(s4[0], s4[1], 0, True, True)
                one_step(s5[0], s5[1], 1, True, True)
                return carry

            if pairs > 0:
                lax.fori_loop(0, pairs, pair, 0)
            s = head + 2 * pairs
            if rem:
                src, dst, slot, _, _ = plan(s)
                one_step(src, dst, slot, True, True)
                s += 1
            while s < steps:
                src, dst, slot, _, _ = plan(s)
                one_step(src, dst, slot, True, False)
                s += 1

    return kernel


def _make_kernel_deep(dims: tuple[int, int], axes: tuple[str, str], steps: int,
                      coeffs9: tuple[float, ...], k: int,
                      H: int, W: int):
    """The generalized remote-DMA halo kernel: ghost depth ``k`` (one
    exchange buys ``k`` fused substeps — the in-kernel trapezoid) and
    corner strips (8 channels), serving any 9-point-family stencil.

    Each device holds TWO (H+2k, W+2k) ghost-padded buffers in VMEM and
    ping-pongs substeps between them; per round it stages 4 edge strips
    (k deep) + 4 corner blocks (k x k) and moves them by double-buffered
    remote DMA under the first substep's interior compute, exactly like
    the k=1 specialized kernel. The reference's exchange carries the same
    8 transfers for any stencil width (ghost depth = stencil/2,
    /root/reference/stencil2d/stencil2D.h:116-117, corner sends
    stencil2D.h:389-428); here width is a fold-depth knob on top.
    """
    R, C = dims
    ns_remote = R > 1
    ew_remote = C > 1
    dg_remote = R > 1 or C > 1
    full, rem = divmod(steps, k)
    rounds = full + (1 if rem else 0)
    H2, W2 = H + 2 * k, W + 2 * k

    def kernel(in_ref, o_ref, pa, pb,
               r_top, r_bot, r_left, r_right, r_nw, r_ne, r_sw, r_se,
               s_top, s_bot, s_left, s_right, s_nw, s_ne, s_sw, s_se,
               send_sem, recv_sem, freed_sem):
        row = lax.axis_index(axes[0])
        col = lax.axis_index(axes[1])
        north = lax.rem(row + R - 1, R)
        south = lax.rem(row + 1, R)
        west = lax.rem(col + C - 1, C)
        east = lax.rem(col + 1, C)

        def dev(r, c):
            return r * C + c

        # Channel d fills the RECEIVER's d-side pad region, so its
        # destination is my opposite(d) neighbor (diagonals included:
        # my SE corner block is my SE neighbor's NW ghost corner).
        dests = {
            TOP: dev(south, col), BOTTOM: dev(north, col),
            LEFT: dev(row, east), RIGHT: dev(row, west),
            NW: dev(south, east), NE: dev(south, west),
            SW: dev(north, east), SE: dev(north, west),
        }
        senders = {
            TOP: dev(north, col), BOTTOM: dev(south, col),
            LEFT: dev(row, west), RIGHT: dev(row, east),
            NW: dev(north, west), NE: dev(north, east),
            SW: dev(south, west), SE: dev(south, east),
        }
        bufs = {TOP: r_top, BOTTOM: r_bot, LEFT: r_left, RIGHT: r_right,
                NW: r_nw, NE: r_ne, SW: r_sw, SE: r_se}
        stages = {TOP: s_top, BOTTOM: s_bot, LEFT: s_left, RIGHT: s_right,
                  NW: s_nw, NE: s_ne, SW: s_sw, SE: s_se}
        remote = {TOP: ns_remote, BOTTOM: ns_remote,
                  LEFT: ew_remote, RIGHT: ew_remote,
                  NW: dg_remote, NE: dg_remote, SW: dg_remote, SE: dg_remote}
        channels = (TOP, BOTTOM, LEFT, RIGHT, NW, NE, SW, SE)
        bufP = (pa, pb)

        # Load the core; pads of bufP[0] are garbage until the first
        # round's arrival fill, and nothing reads them before that.
        pa[k : H + k, k : W + k] = in_ref[:]

        def stage_all(src_ref):
            # edge strips k deep (columns lane-major), corners k x k
            s_top[:, 0:W] = src_ref[H : H + k, k : W + k]
            s_bot[:, 0:W] = src_ref[k : 2 * k, k : W + k]
            s_left[:, 0:H] = jnp.swapaxes(src_ref[k : H + k, W : W + k], 0, 1)
            s_right[:, 0:H] = jnp.swapaxes(src_ref[k : H + k, k : 2 * k], 0, 1)
            s_nw[:, 0:k] = src_ref[H : H + k, W : W + k]   # my SE corner
            s_ne[:, 0:k] = src_ref[H : H + k, k : 2 * k]   # my SW corner
            s_sw[:, 0:k] = src_ref[k : 2 * k, W : W + k]   # my NE corner
            s_se[:, 0:k] = src_ref[k : 2 * k, k : 2 * k]   # my NW corner

        def fill_pads(dst_ref, slot: int):
            dst_ref[0:k, k : W + k] = r_top[slot][:, 0:W]
            dst_ref[H + k : H2, k : W + k] = r_bot[slot][:, 0:W]
            dst_ref[k : H + k, 0:k] = jnp.swapaxes(r_left[slot][:, 0:H], 0, 1)
            dst_ref[k : H + k, W + k : W2] = jnp.swapaxes(
                r_right[slot][:, 0:H], 0, 1
            )
            dst_ref[0:k, 0:k] = r_nw[slot][:, 0:k]
            dst_ref[0:k, W + k : W2] = r_ne[slot][:, 0:k]
            dst_ref[H + k : H2, 0:k] = r_sw[slot][:, 0:k]
            dst_ref[H + k : H2, W + k : W2] = r_se[slot][:, 0:k]

        if dg_remote:
            barrier = pltpu.get_barrier_semaphore()
            n_remote = 0
            for ch in channels:
                if remote[ch]:
                    pltpu.semaphore_signal(
                        barrier, inc=1, device_id=dests[ch],
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    )
                    n_remote += 1
            pltpu.semaphore_wait(barrier, n_remote)

        def one_round(pidx: int, slot: int, wait_credit: bool,
                      give_credit: bool, substeps: int):
            src_ref = bufP[pidx]
            dst_ref = bufP[1 - pidx]
            stage_all(src_ref)
            copies = []
            for ch in channels:
                if remote[ch]:
                    if wait_credit:
                        pltpu.semaphore_wait(freed_sem.at[ch], 1)
                    dma = pltpu.make_async_remote_copy(
                        src_ref=stages[ch].at[:],
                        dst_ref=bufs[ch].at[slot],
                        send_sem=send_sem.at[ch],
                        recv_sem=recv_sem.at[ch, slot],
                        device_id=dests[ch],
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    )
                else:
                    dma = pltpu.make_async_copy(
                        stages[ch].at[:], bufs[ch].at[slot],
                        recv_sem.at[ch, slot],
                    )
                copies.append((ch, dma))
                dma.start()

            # substep 1, interior: reads core cells only — overlaps DMAs
            s = src_ref[:]
            dst_ref[k + 1 : H + k - 1, k + 1 : W + k - 1] = _patch(
                s, k + 1, H + k - 1, k + 1, W + k - 1, coeffs9
            )

            for ch, dma in copies:
                dma.wait_recv() if remote[ch] else dma.wait()

            # substep 1, frame: the four bands that read the fresh pads
            # (rows [1,k+1) and [H+k-1,H2-1) full-width, plus the side
            # columns between them) — together with the interior they
            # tile the substep-1 valid region [1,H2-1)x[1,W2-1)
            fill_pads(src_ref, slot)
            s = src_ref[:]
            dst_ref[1 : k + 1, 1 : W2 - 1] = _patch(
                s, 1, k + 1, 1, W2 - 1, coeffs9
            )
            dst_ref[H + k - 1 : H2 - 1, 1 : W2 - 1] = _patch(
                s, H + k - 1, H2 - 1, 1, W2 - 1, coeffs9
            )
            dst_ref[k + 1 : H + k - 1, 1 : k + 1] = _patch(
                s, k + 1, H + k - 1, 1, k + 1, coeffs9
            )
            dst_ref[k + 1 : H + k - 1, W + k - 1 : W2 - 1] = _patch(
                s, k + 1, H + k - 1, W + k - 1, W2 - 1, coeffs9
            )

            for ch, dma in copies:
                if remote[ch]:
                    if give_credit:
                        pltpu.semaphore_signal(
                            freed_sem.at[ch], inc=1, device_id=senders[ch],
                            device_id_type=pltpu.DeviceIdType.LOGICAL,
                        )
                    dma.wait_send()

            # substeps 2..substeps: shrinking trapezoid, all-local
            for j in range(2, substeps + 1):
                sj = bufP[(pidx + j - 1) % 2][:]
                bufP[(pidx + j) % 2][j : H2 - j, j : W2 - j] = _patch(
                    sj, j, H2 - j, j, W2 - j, coeffs9
                )

        def plan(r: int):
            """(pidx, slot, wait_credit, give_credit) for round r; the
            buffer index advances k substeps per completed round."""
            return (r * k) % 2, r % 2, r >= 2, r + 2 <= rounds - 1

        def subs(r: int) -> int:
            return rem if (rem and r == rounds - 1) else k

        head = min(rounds, 4)
        for r in range(head):
            pidx, slot, w, g = plan(r)
            one_round(pidx, slot, w, g, subs(r))

        if rounds > head:
            mid = max(0, rounds - 2 - head)  # never the last round
            pairs, prem = divmod(mid, 2)
            p4, p5 = plan(4), plan(5)

            def pair(_, carry):
                one_round(p4[0], p4[1], True, True, k)
                one_round(p5[0], p5[1], True, True, k)
                return carry

            if pairs > 0:
                lax.fori_loop(0, pairs, pair, 0)
            r = head + 2 * pairs
            if prem:
                pidx, slot, _, _ = plan(r)
                one_round(pidx, slot, True, True, k)
                r += 1
            while r < rounds:
                pidx, slot, _, _ = plan(r)
                one_round(pidx, slot, True, False, subs(r))
                r += 1

        # total substeps == steps, starting from buffer 0
        o_ref[:] = bufP[steps % 2][k : H + k, k : W + k]

    return kernel


def _run_stencil_dma_deep(tile, spec, steps, coeffs9, depth, vmem_limit_bytes):
    """Dispatch helper for the generalized kernel (see run_stencil_dma)."""
    lay = spec.layout
    H, W, k = lay.core_h, lay.core_w, depth
    dt = tile.dtype
    Hp = -(-H // 128) * 128
    Wp = -(-W // 128) * 128
    H2, W2 = H + 2 * k, W + 2 * k

    # the two padded buffers + pallas in/out dominate, but the recv/stage
    # scratch (2-slot k-deep edge strips at lane-padded Wp/Hp, 8 corner
    # recv blocks, 8 send stages) grows with k and must be counted or
    # Mosaic fails with an opaque scoped-vmem error instead of this
    # ValueError. Count every buffer at its (8, 128)-tile footprint —
    # Mosaic allocates sublane-by-lane tiles, so a (H2, W2) buffer
    # occupies roundup(H2, 8) x roundup(W2, 128) and a k-row strip
    # occupies roundup(k, 8) rows: recv rows/cols 4k(Wp+Hp) + stages
    # 2k(Wp+Hp) + corner recv 8*k*128 + corner stages 4*k*128
    r8 = lambda x: -(-x // 8) * 8
    r128 = lambda x: -(-x // 128) * 128
    kp = r8(k)
    scratch = 6 * kp * (Wp + Hp) + 12 * kp * 128
    need = (
        2 * r8(H2) * r128(W2) + 2 * r8(H) * r128(W) + scratch
    ) * dt.itemsize
    if need > vmem_limit_bytes:
        raise ValueError(
            f"padded core {H2}x{W2} x2 + depth-{k} strip scratch needs "
            f"~{need >> 20} MB VMEM (> limit {vmem_limit_bytes >> 20} MB)"
        )

    core = tile[lay.halo_y : lay.halo_y + H, lay.halo_x : lay.halo_x + W]
    kernel = _make_kernel_deep(
        spec.topology.dims, tuple(spec.axes), steps, coeffs9, k, H, W
    )
    interpret = interpret_params() if use_interpret() else False
    R, C = spec.topology.dims
    collective_kw = (
        {"collective_id": _COLLECTIVE_ID_DEEP} if (R > 1 or C > 1) else {}
    )
    new_core = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((H, W), dt),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((H2, W2), dt),         # padded ping
            pltpu.VMEM((H2, W2), dt),         # padded pong
            pltpu.VMEM((2, k, Wp), dt),       # recv: top rows, 2 slots
            pltpu.VMEM((2, k, Wp), dt),       # recv: bottom rows
            pltpu.VMEM((2, k, Hp), dt),       # recv: left cols (lane-major)
            pltpu.VMEM((2, k, Hp), dt),       # recv: right cols
            pltpu.VMEM((2, k, 128), dt),      # recv: NW corner
            pltpu.VMEM((2, k, 128), dt),      # recv: NE corner
            pltpu.VMEM((2, k, 128), dt),      # recv: SW corner
            pltpu.VMEM((2, k, 128), dt),      # recv: SE corner
            pltpu.VMEM((k, Wp), dt),          # stage: bottom rows out
            pltpu.VMEM((k, Wp), dt),          # stage: top rows out
            pltpu.VMEM((k, Hp), dt),          # stage: right cols out
            pltpu.VMEM((k, Hp), dt),          # stage: left cols out
            pltpu.VMEM((k, 128), dt),         # stage: SE corner out
            pltpu.VMEM((k, 128), dt),         # stage: SW corner out
            pltpu.VMEM((k, 128), dt),         # stage: NE corner out
            pltpu.VMEM((k, 128), dt),         # stage: NW corner out
            pltpu.SemaphoreType.DMA((8,)),    # send completion / channel
            pltpu.SemaphoreType.DMA((8, 2)),  # arrival / channel x slot
            pltpu.SemaphoreType.REGULAR((8,)),  # credits / channel
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_limit_bytes,
            has_side_effects=True,
            **collective_kw,
        ),
    )(core)
    return halo_exchange(rebuild(tile, new_core, lay), spec)


def _make_kernel_hbm(dims: tuple[int, int], axes: tuple[str, str],
                     band: int, nb: int, H: int, W: int, Hp: int, Wp: int,
                     coeffs9: tuple[float, ...]):
    """One STEP of the HBM-resident banded halo stencil (invoked once
    per step; the scan lives outside).  The core never enters VMEM whole:
    it streams through in ``band``-row windows (double-buffered manual
    DMA, the ops/stencil_stream schedule) while the four ghost strips
    travel by remote DMA under the stream.  Columns are carried between
    invocations as (Hp, 1) stage arrays so no strided HBM access ever
    happens (the reference moves the same strided subarrays without
    materializing them, stencil2D.h:210-228).

    9-POINT (round 5, VERDICT r4 missing #2): the diagonal corner
    values ride the EXISTING row channels, no new channels — the column
    strips are sent and received FIRST, then each edge row is staged
    extended by the freshly received ghost columns' end cells
    ([gl[edge] | row | gr[edge]]), which is exactly the receiver's
    corner value (my row H-1 at my column -1 IS my south neighbor's
    extended top ghost row's corner, the reference's corner-send
    payload, stencil2D.h:389-428).  Per band the diagonal terms are
    pure slices of the (H+2, 1) corner-extended ghost columns — no
    lane concats.  The chip-validated 5-point schedule (concurrent row
    and column sends) is kept verbatim when every diagonal coefficient
    is zero.

    Cross-invocation safety needs no credit handshake, but it DOES need
    per-sender entry gates rather than one counted barrier: a counted
    barrier can be satisfied by a fast neighbor's next-invocation signal
    while a lagging neighbor is still consuming the previous strips.
    Instead, each rank signals (per channel) the neighbor that sends TO
    it, and a sender transmits only after the signal from its
    DESTINATION — so a strip can never land before its receiver entered
    the invocation (hence finished the previous one, hence consumed its
    strips), and the signal chain bounds skew to one invocation.
    Semaphore state persists across invocations (the family's standard
    assumption: kernels drain their semaphores rather than rely on
    re-zeroing), so an early next-invocation signal waits its turn.
    """
    R, C = dims
    ns_remote = R > 1
    ew_remote = C > 1
    cn, cs, cw, ce, cnw, cne, csw, cse, cc = coeffs9
    diag = any(c != 0.0 for c in (cnw, cne, csw, cse))
    Wp2 = -(-(W + 2) // 128) * 128 if diag else Wp
    # diag row stages pack [row(W) | cornerW | cornerE]: the row stays
    # at lane offset 0 (aligned wide slices on both ends; [1:W+1]-style
    # offset-1 wide reads are suspected chip DNFs) and the two corner
    # cells ride at offsets W, W+1 (the 128-aligned tail tile)

    def kernel(in_hbm, colL_ref, colR_ref, out_hbm, ncolL_ref, ncolR_ref,
               rbuf, wbuf, gL, gR, glxu, glxd, grxu, grxd,
               r_top, r_bot, r_left, r_right,
               s_top, s_bot, s_left, s_right, erow_t, erow_b,
               rsem, wsem, esem, send_sem, recv_sem, entry_sem):
        if ns_remote or ew_remote:
            row = lax.axis_index(axes[0])
            col = lax.axis_index(axes[1])
            north = lax.rem(row + R - 1, R) * C + col
            south = lax.rem(row + 1, R) * C + col
            west = row * C + lax.rem(col + C - 1, C)
            east = row * C + lax.rem(col + 1, C)
            dests = {TOP: south, BOTTOM: north, LEFT: east, RIGHT: west}
            senders = {TOP: north, BOTTOM: south, LEFT: west, RIGHT: east}
        bufs = {TOP: r_top, BOTTOM: r_bot, LEFT: r_left, RIGHT: r_right}
        remote = {TOP: ns_remote, BOTTOM: ns_remote,
                  LEFT: ew_remote, RIGHT: ew_remote}
        stages = {TOP: s_top, BOTTOM: s_bot, LEFT: s_left, RIGHT: s_right}

        for ch in (TOP, BOTTOM, LEFT, RIGHT):
            if remote[ch]:
                # tell the rank that sends my ch strip that I am ready
                # to receive it (its entry gate for this channel)
                pltpu.semaphore_signal(
                    entry_sem.at[ch], inc=1, device_id=senders[ch],
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
        for ch in (TOP, BOTTOM, LEFT, RIGHT):
            if remote[ch]:
                # wait for MY destination's readiness before sending
                pltpu.semaphore_wait(entry_sem.at[ch], 1)

        def start_ch(ch):
            if remote[ch]:
                dma = pltpu.make_async_remote_copy(
                    src_ref=stages[ch].at[:],
                    dst_ref=bufs[ch].at[:],
                    send_sem=send_sem.at[ch],
                    recv_sem=recv_sem.at[ch],
                    device_id=dests[ch],
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
            else:
                dma = pltpu.make_async_copy(
                    stages[ch].at[:], bufs[ch].at[:], recv_sem.at[ch])
            dma.start()
            return ch, dma

        def recv_wait(ch, dma):
            dma.wait_recv() if remote[ch] else dma.wait()

        # edge rows: HBM -> VMEM. DMA windows must be 8-row (sublane
        # tile) aligned and 8-row multiples (chip-probed: 1-row windows
        # are a Mosaic remote-compile DNF even at offset 0), so fetch
        # the 8-row tiles holding the edges and VPU-copy the edge row
        # into the lane-padded send stage
        e_top = pltpu.make_async_copy(
            in_hbm.at[pl.ds(H - 8, 8)], erow_t.at[:, pl.ds(0, W)],
            esem.at[0])
        e_bot = pltpu.make_async_copy(
            in_hbm.at[pl.ds(0, 8)], erow_b.at[:, pl.ds(0, W)], esem.at[1])
        e_top.start()
        e_bot.start()
        # column stages: carried in as (Hp, 1), transposed to lane-major
        s_left[:, 0:H] = jnp.swapaxes(colR_ref[0:H, :], 0, 1)
        s_right[:, 0:H] = jnp.swapaxes(colL_ref[0:H, :], 0, 1)

        copies = []
        if diag:
            # columns FIRST: the row stages need the received ghost
            # columns' end cells as their corner extensions
            col_copies = [start_ch(LEFT), start_ch(RIGHT)]
            for ch, dma in col_copies:
                recv_wait(ch, dma)
            e_top.wait()
            e_bot.wait()
            # ONE aligned full-width store per row stage (chip-probed:
            # misaligned single-lane stores like s_top[:, W+1:W+2] are
            # a Mosaic remote-compile DNF); the corner cells are the
            # received ghost columns' end cells, read as sublane slices
            # of the transposed columns (legal at any offset)
            glT = jnp.swapaxes(r_left[:, 0:H], 0, 1)    # (H, 1)
            grT = jnp.swapaxes(r_right[:, 0:H], 0, 1)
            pad = jnp.zeros((1, Wp2 - W - 2), erow_t.dtype)
            s_top[:, 0:Wp2] = jnp.concatenate(
                [erow_t[7:8, 0:W], jnp.swapaxes(glT[H - 1 : H], 0, 1),
                 jnp.swapaxes(grT[H - 1 : H], 0, 1), pad], axis=1)
            s_bot[:, 0:Wp2] = jnp.concatenate(
                [erow_b[0:1, 0:W], jnp.swapaxes(glT[0:1], 0, 1),
                 jnp.swapaxes(grT[0:1], 0, 1), pad], axis=1)
            copies = col_copies + [start_ch(TOP), start_ch(BOTTOM)]
        else:
            e_top.wait()
            e_bot.wait()
            s_top[:, 0:W] = erow_t[7:8, 0:W]
            s_bot[:, 0:W] = erow_b[0:1, 0:W]
            copies = [start_ch(ch)
                      for ch in (TOP, BOTTOM, LEFT, RIGHT)]

        # band reads are EXACT band-row windows (8-row-tile aligned,
        # affine offsets, ONE descriptor geometry — the chip compiler
        # rejects clip/where offsets and branch-selected descriptor
        # shapes, chip-bisected): no overlap is re-read; band b's top
        # halo row travels as a loop-carried VALUE (its own window's
        # last row, saved before the slot is reused) and its bottom
        # halo row comes from band b+1's window, waited one band ahead
        def rd(slot, b):
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(b * band, band)], rbuf.at[slot],
                rsem.at[slot])

        def wr(slot, b):
            return pltpu.make_async_copy(
                wbuf.at[slot], out_hbm.at[pl.ds(b * band, band)],
                wsem.at[slot])

        rd(0, 0).start()
        rd(1, 1).start()

        # the strips arrive under the first window reads; ghost columns
        # transpose once to sublane-major for per-band slicing
        for ch, dma in copies:
            if diag and ch in (LEFT, RIGHT):
                continue  # already received above
            recv_wait(ch, dma)
        gL[0:H, :] = jnp.swapaxes(r_left[:, 0:H], 0, 1)
        gR[0:H, :] = jnp.swapaxes(r_right[:, 0:H], 0, 1)
        if diag:
            # PRE-SHIFTED corner-extended ghost columns: glxu[r] = ghost
            # at row r-1, glxd[r] = row r+1 (gL itself is row r), so the
            # per-band diagonal slices stay 8-aligned at pl.ds(b*band)
            # — dynamic sublane slices at +1/+2 offsets (and offset-1
            # sublane stores) are chip DNFs; the corner cells are the
            # received extended rows' end cells, read as single-lane
            # value slices and sublane-concatenated (small values)
            glT2 = jnp.swapaxes(r_left[:, 0:H], 0, 1)
            grT2 = jnp.swapaxes(r_right[:, 0:H], 0, 1)
            glxu[0:H] = jnp.concatenate(
                [jnp.swapaxes(r_top[:, W : W + 1], 0, 1),
                 glT2[0 : H - 1]], axis=0)
            glxd[0:H] = jnp.concatenate(
                [glT2[1:H], jnp.swapaxes(r_bot[:, W : W + 1], 0, 1)],
                axis=0)
            grxu[0:H] = jnp.concatenate(
                [jnp.swapaxes(r_top[:, W + 1 : W + 2], 0, 1),
                 grT2[0 : H - 1]], axis=0)
            grxd[0:H] = jnp.concatenate(
                [grT2[1:H], jnp.swapaxes(r_bot[:, W + 1 : W + 2], 0, 1)],
                axis=0)

        rd(0, 0).wait()

        def body(b, up_row):
            slot = lax.rem(b, 2)
            nxt = lax.rem(b + 1, 2)

            @pl.when(b + 1 < nb)
            def _():
                rd(nxt, b + 1).wait()

            @pl.when(b >= 2)
            def _():
                wr(slot, b - 2).wait()

            t = rbuf[slot]                      # (band, W) own rows
            t_next0 = rbuf[nxt][0:1]            # band b+1's first row
            dn_row = jnp.where(b == nb - 1, r_bot[:, 0:W], t_next0)
            up = jnp.concatenate([up_row, t[0 : band - 1]], axis=0)
            dn = jnp.concatenate([t[1:band], dn_row], axis=0)
            interior = (
                cn * up[:, 1 : W - 1] + cs * dn[:, 1 : W - 1]
                + cw * t[:, 0 : W - 2] + ce * t[:, 2:W]
                + cc * t[:, 1 : W - 1]
            )
            if diag:
                interior = (
                    interior
                    + cnw * up[:, 0 : W - 2] + cne * up[:, 2:W]
                    + csw * dn[:, 0 : W - 2] + cse * dn[:, 2:W]
                )
                # (band, 1) corner-extended ghost slices — all three
                # shifts pre-applied at assembly, so every dynamic
                # sublane slice is 8-aligned at b*band
                glu = glxu[pl.ds(b * band, band)]       # rows r-1
                gl = gL[pl.ds(b * band, band)]          # rows r
                gld = glxd[pl.ds(b * band, band)]       # rows r+1
                gru = grxu[pl.ds(b * band, band)]
                gr = gR[pl.ds(b * band, band)]
                grd = grxd[pl.ds(b * band, band)]
                left = (
                    cn * up[:, 0:1] + cs * dn[:, 0:1]
                    + cw * gl + ce * t[:, 1:2] + cc * t[:, 0:1]
                    + cnw * glu + cne * up[:, 1:2]
                    + csw * gld + cse * dn[:, 1:2]
                )
                right = (
                    cn * up[:, W - 1 : W] + cs * dn[:, W - 1 : W]
                    + cw * t[:, W - 2 : W - 1] + ce * gr
                    + cc * t[:, W - 1 : W]
                    + cnw * up[:, W - 2 : W - 1] + cne * gru
                    + csw * dn[:, W - 2 : W - 1] + cse * grd
                )
            else:
                gl = gL[pl.ds(b * band, band)]  # (band, 1) ghost cols
                gr = gR[pl.ds(b * band, band)]
                left = (
                    cn * up[:, 0:1] + cs * dn[:, 0:1]
                    + cw * gl + ce * t[:, 1:2] + cc * t[:, 0:1]
                )
                right = (
                    cn * up[:, W - 1 : W] + cs * dn[:, W - 1 : W]
                    + cw * t[:, W - 2 : W - 1] + ce * gr
                    + cc * t[:, W - 1 : W]
                )
            new = jnp.concatenate([left, interior, right], axis=1)
            # save the halo row band b+1 needs BEFORE this slot's buffer
            # is reposted for band b+2
            carry_row = t[band - 1 : band]
            wbuf[slot] = new
            # stage the new edge columns for the NEXT invocation's sends
            ncolL_ref[pl.ds(b * band, band)] = left
            ncolR_ref[pl.ds(b * band, band)] = right
            wr(slot, b).start()

            # repost at END of body (chip-raced: hoisting this above the
            # compute measured 2.67 vs 2.39 ms/step at 8192^2 — the
            # wait-one-ahead structure already overlaps reads with the
            # previous band's compute, and an early repost contends with
            # the in-flight next-band read)
            @pl.when(b + 2 < nb)
            def _():
                rd(slot, b + 2).start()

            return carry_row

        lax.fori_loop(0, nb, body, r_top[:, 0:W])
        for i in range(max(0, nb - 2), nb):
            wr(i % 2, i).wait()
        for ch, dma in copies:
            if remote[ch]:
                dma.wait_send()
        if Hp > H:
            z = jnp.zeros((Hp - H, 1), ncolL_ref.dtype)
            ncolL_ref[pl.ds(H, Hp - H)] = z
            ncolR_ref[pl.ds(H, Hp - H)] = z

    return kernel


def _hbm_cost(b: int, H: int, W: int, itemsize: int,
              diag: bool = False) -> int:
    """Tile-accurate VMEM footprint of the HBM-banded kernel at band
    ``b``: the four (b, W) read/write double-buffers plus ~3 band-width
    compute temporaries (the left/interior/right pieces of one band's
    update — chip-calibrated: at 8192^2 under a 100 MB limit band=512
    [7bW ~ 117 MB + fixed] is a Mosaic remote-compile DNF while
    band=256 [~87 MB total] runs), plus the FIXED scratch the band does
    not scale, at its (8, 128)-tile allocation granularity: six (Hp, 1)
    column buffers (gL/gR scratch, colL/colR inputs, ncolL/ncolR
    outputs — each lane-padded to 128), eight (1, Wp)/(1, Hp) strips
    (sublane-padded to 8 rows), and two (8, Wp) edge-row tiles."""
    Wp = -(-W // 128) * 128
    Hp = -(-H // 128) * 128
    fixed = 6 * Hp * 128 + 32 * (Wp + Hp) + 16 * Wp
    if diag:  # the four pre-shifted corner-extended ghost columns
        fixed += 4 * Hp * 128
    return (7 * b * W + fixed) * itemsize


def hbm_band(H: int, W: int, itemsize: int,
             budget_bytes: int, diag: bool = False) -> int:
    """Largest 8-multiple divisor band of ``H`` whose FULL kernel
    footprint (``_hbm_cost``: band buffers + compute temps + the fixed
    column/strip scratch) fits the budget, with >= 2 bands (the DMA
    windows are 8-row-tile aligned, so bands must be too)."""
    for d in range(H // 2, 7, -1):
        if (H % d == 0 and d % 8 == 0
                and _hbm_cost(d, H, W, itemsize, diag) <= budget_bytes):
            return d
    raise ValueError(
        f"no 8-aligned band of H={H} gives >= 2 bands within "
        f"{budget_bytes >> 20} MB VMEM (need H >= 16 with 8 | H, and "
        "the kernel footprint to fit the budget)"
    )


@functools.partial(
    jax.jit,
    static_argnames=("spec", "steps", "coeffs", "band", "vmem_limit_bytes"),
)
def run_stencil_dma_hbm(
    tile: jax.Array,
    spec: HaloSpec,
    steps: int,
    coeffs: Coeffs = JACOBI,
    band: int | None = None,
    vmem_limit_bytes: int = 100 << 20,
) -> jax.Array:
    """``run_stencil_dma`` for cores that do NOT fit VMEM: the core
    stays in HBM and streams through the kernel in ``band``-row windows
    while the ghost strips ride the (remote) DMA engine under the
    stream — one kernel invocation per step, entry-barrier ordered (see
    ``_make_kernel_hbm``).  Columns carry between steps as small VMEM
    stage arrays, so the strided column access the VMEM-resident kernel
    pays per step never touches HBM.  This serves the config the
    resident kernel must refuse (8192 ** 2 is a 1 GB core/2,
    BASELINE row 4).  5-point AND 9-point (round 5 — corner values ride
    the row channels, columns-first ordered; a 9-point call needs a
    ``neighbors=8`` spec for the trailing re-wrap).  Periodic
    topologies (the open-boundary fallback is
    ``run_stencil``/``run_stencil_deep``).
    """
    lay = spec.layout
    if tuple(tile.shape) != lay.padded_shape:
        raise ValueError(f"tile {tile.shape} != padded {lay.padded_shape}")
    if not all(spec.topology.periodic):
        raise ValueError(
            "the HBM-resident DMA kernel is periodic-only (design "
            "decision: open edges would need per-rank ghost pinning in "
            "every band); use run_stencil or run_stencil_deep for open "
            "boundaries"
        )
    if len(coeffs) == 9 and spec.neighbors != 8:
        raise ValueError(
            "9-point coeffs need a neighbors=8 HaloSpec: the trailing "
            "re-wrap must fill the corner ghosts the stencil reads"
        )
    coeffs = as_nine(coeffs)
    diag = any(c != 0.0 for c in coeffs[4:8])
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    H, W = lay.core_h, lay.core_w
    dt = tile.dtype
    if H % 8:
        raise ValueError(
            f"core height {H} must be a multiple of 8 (the DMA windows "
            "are 8-row-tile aligned)"
        )
    if band is None:
        band = hbm_band(H, W, dt.itemsize, vmem_limit_bytes, diag)
    if H % band or H // band < 2 or band % 8:
        raise ValueError(
            f"band {band} must be an 8-multiple divisor of H {H} with "
            "at least 2 bands"
        )
    if _hbm_cost(band, H, W, dt.itemsize, diag) > vmem_limit_bytes:
        raise ValueError(
            f"band {band} needs "
            f"~{_hbm_cost(band, H, W, dt.itemsize, diag) >> 20}"
            f" MB VMEM (> limit {vmem_limit_bytes >> 20} MB): the band "
            "buffers + compute temps + fixed column/strip scratch must "
            "fit (see _hbm_cost)"
        )
    nb = H // band
    Hp = -(-H // 128) * 128
    Wp = -(-W // 128) * 128
    # 9-point: row stages carry [cornerW | row | cornerE] (W+2 cells),
    # and the corner-extended ghost columns span rows [-1, H]
    Wp2 = -(-(W + 2) // 128) * 128 if diag else Wp
    hy, hx = lay.halo_y, lay.halo_x
    core = tile[hy : hy + H, hx : hx + W]
    pad_h = Hp - H

    def col_stage(c):
        return jnp.pad(c, ((0, pad_h), (0, 0))) if pad_h else c

    colL = col_stage(core[:, 0:1])
    colR = col_stage(core[:, W - 1 : W])
    kernel = _make_kernel_hbm(
        spec.topology.dims, tuple(spec.axes), band, nb, H, W, Hp, Wp,
        tuple(coeffs),
    )
    interpret = interpret_params() if use_interpret() else False
    R, C = spec.topology.dims
    collective_kw = (
        {"collective_id": _COLLECTIVE_ID_HBM} if (R > 1 or C > 1) else {}
    )
    call = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((H, W), dt),
            jax.ShapeDtypeStruct((Hp, 1), dt),
            jax.ShapeDtypeStruct((Hp, 1), dt),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, band, W), dt),      # read windows (exact bands)
            pltpu.VMEM((2, band, W), dt),      # write bands
            pltpu.VMEM((Hp, 1), dt),           # ghost col L, sublane-major
            pltpu.VMEM((Hp, 1), dt),           # ghost col R
            # pre-shifted corner-extended ghost cols — 9-point only
            pltpu.VMEM((Hp, 1) if diag else (1, 1), dt),
            pltpu.VMEM((Hp, 1) if diag else (1, 1), dt),
            pltpu.VMEM((Hp, 1) if diag else (1, 1), dt),
            pltpu.VMEM((Hp, 1) if diag else (1, 1), dt),
            pltpu.VMEM((1, Wp2), dt),          # recv: top ghost row
            pltpu.VMEM((1, Wp2), dt),          # recv: bottom ghost row
            pltpu.VMEM((1, Hp), dt),           # recv: left ghost col
            pltpu.VMEM((1, Hp), dt),           # recv: right ghost col
            pltpu.VMEM((1, Wp2), dt),          # stage: my bottom row
            pltpu.VMEM((1, Wp2), dt),          # stage: my top row
            pltpu.VMEM((1, Hp), dt),           # stage: my right col
            pltpu.VMEM((1, Hp), dt),           # stage: my left col
            pltpu.VMEM((8, Wp), dt),           # edge-row tile: bottom
            pltpu.VMEM((8, Wp), dt),           # edge-row tile: top
            pltpu.SemaphoreType.DMA((2,)),     # read slots
            pltpu.SemaphoreType.DMA((2,)),     # write slots
            pltpu.SemaphoreType.DMA((2,)),     # edge-row fetches
            pltpu.SemaphoreType.DMA((4,)),     # send completion
            pltpu.SemaphoreType.DMA((4,)),     # arrivals
            pltpu.SemaphoreType.REGULAR((4,)),  # per-channel entry gates
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_limit_bytes,
            has_side_effects=True,
            **collective_kw,
        ),
    )

    def one(carry, _):
        c, cl, cr = carry
        return call(c, cl, cr), ()

    (core, _, _), _ = lax.scan(one, (core, colL, colR), None, length=steps)
    return halo_exchange(rebuild(tile, core, lay), spec)


@functools.partial(jax.jit, static_argnames=("spec", "steps", "coeffs", "depth", "vmem_limit_bytes"))
def run_stencil_dma(
    tile: jax.Array,
    spec: HaloSpec,
    steps: int,
    coeffs: Coeffs = JACOBI,
    depth: int = 1,
    vmem_limit_bytes: int = 100 << 20,
) -> jax.Array:
    """``steps`` stencil iterations with the core VMEM-resident and
    every halo exchange done by double-buffered (remote) DMA inside ONE
    Pallas kernel. Call inside shard_map over ``spec.axes``, like
    ``run_stencil``; the trailing padded-tile halo is refreshed by one
    ordinary exchange so the result composes with the other impls.

    ``coeffs`` may be 5-point (n,s,w,e,c) or 9-point (nine_point order —
    corner blocks then ride the DMA alongside the edge strips, matching
    the reference's diagonal sends, stencil2D.h:389-428). ``depth`` > 1
    folds that many substeps per exchange INSIDE the kernel (the
    trapezoid scheme of run_stencil_deep, but with the ghost traffic on
    the DMA engine): one k-deep exchange, k fused substeps, k x fewer
    messages. The 5-point/depth-1 case keeps the specialized
    ring-decomposition kernel; anything else uses the generalized
    8-channel ghost-padded kernel.

    This is the structural realization of the reference's
    Isend-all/compute/Waitall overlap (stencil2D.h:363-377) — the transfers
    are in flight WHILE the interior is computed, by construction rather
    than by compiler scheduling luck.
    """
    lay = spec.layout
    if tuple(tile.shape) != lay.padded_shape:
        raise ValueError(f"tile {tile.shape} != padded {lay.padded_shape}")
    if lay.halo_y < 1 or lay.halo_x < 1:
        raise ValueError("stencil needs halo >= 1 on both axes")
    if not all(spec.topology.periodic):
        # design decision, not a TODO: an open edge would need per-rank
        # traced channel masks threaded through the credit handshake
        # (different ranks have different live channels, but shard_map
        # traces ONE program), for a path whose value is benchmarks on
        # periodic tori. Open boundaries run on run_stencil (per-step)
        # or run_stencil_deep impl='xla' (trapezoid, open-aware).
        raise ValueError(
            "DMA halo stencil requires a periodic topology; use "
            "run_stencil or run_stencil_deep(impl='xla') for open "
            "boundaries"
        )
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if len(coeffs) == 9 and spec.neighbors != 8:
        raise ValueError(
            "9-point coeffs need a neighbors=8 HaloSpec: the trailing "
            "re-wrap must fill the corner ghosts the stencil reads"
        )
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    if min(lay.core_h, lay.core_w) < max(3, depth):
        raise ValueError(
            f"core {lay.core_h}x{lay.core_w} too small: need >= "
            f"max(3, depth={depth}) on both axes"
        )
    if len(coeffs) == 9 or depth > 1:
        return _run_stencil_dma_deep(
            tile, spec, steps, as_nine(coeffs), depth, vmem_limit_bytes
        )

    H, W = lay.core_h, lay.core_w
    Hp = -(-H // 128) * 128  # lane-padded strip lengths (DMA granularity)
    Wp = -(-W // 128) * 128
    hy, hx = lay.halo_y, lay.halo_x
    core = tile[hy : hy + H, hx : hx + W]
    dt = core.dtype

    need = 4 * core.size * dt.itemsize
    if need > vmem_limit_bytes:
        raise ValueError(
            f"core {core.shape} needs ~{need >> 20} MB VMEM "
            f"(> limit {vmem_limit_bytes >> 20} MB)"
        )

    kernel = _make_kernel(spec.topology.dims, tuple(spec.axes), steps, tuple(coeffs))
    interpret = interpret_params() if use_interpret() else False
    R, C = spec.topology.dims
    # collective_id names the cross-device barrier; a 1x1 mesh has no
    # remote channels, hence no barrier, and Mosaic rejects the id.
    collective_kw = {"collective_id": _COLLECTIVE_ID} if (R > 1 or C > 1) else {}
    new_core = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((H, W), dt),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((H, W), dt),       # second core slot (ping-pong)
            pltpu.VMEM((2, 1, Wp), dt),   # recv: top halo row, 2 slots
            pltpu.VMEM((2, 1, Wp), dt),   # recv: bottom halo row
            pltpu.VMEM((2, 1, Hp), dt),   # recv: left halo col (lane-major)
            pltpu.VMEM((2, 1, Hp), dt),   # recv: right halo col (lane-major)
            pltpu.VMEM((1, Wp), dt),      # send stage: my bottom row
            pltpu.VMEM((1, Wp), dt),      # send stage: my top row
            pltpu.VMEM((1, Hp), dt),      # send stage: my right col, transposed
            pltpu.VMEM((1, Hp), dt),      # send stage: my left col, transposed
            pltpu.SemaphoreType.DMA((4,)),     # send completion per channel
            pltpu.SemaphoreType.DMA((4, 2)),   # arrival per channel x slot
            pltpu.SemaphoreType.REGULAR((4,)),  # credits per send channel
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_limit_bytes,
            has_side_effects=True,
            **collective_kw,
        ),
    )(core)
    return halo_exchange(rebuild(tile, new_core, lay), spec)
