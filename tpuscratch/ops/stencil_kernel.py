"""Pallas 5-point stencil kernel — the real Compute the reference stubs out.

The reference's stencil drivers ship a no-op ``Compute`` placeholder
(/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:27); its only real
device kernel is the 1-thread-per-block ``InitKernel``
(-cuda.cu:17-28). This module supplies what a benchmarkable stencil needs:
a fused VPU kernel computing the 4-neighbor Jacobi update of the core in
one pass over VMEM.

Two variants:
- ``five_point_pallas``: whole padded tile as one VMEM block — right for
  per-chip tiles up to a few MB (the distributed regime, where each rank's
  tile is modest and the interesting cost is the halo exchange).
- ``five_point_blocked``: 1D grid over row bands with one-row overlap
  (via an index_map that steps by the band height while the block is two
  rows taller) — right for single-chip grids too big for VMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # Element block dims: element-indexed (overlapping) blocks
    from jax.experimental.pallas import Element  # type: ignore[attr-defined]
except ImportError:  # not re-exported in this jax version
    try:
        from jax._src.pallas.core import Element
    except ImportError:
        # jax predates Element entirely: keep the module (and the whole
        # ``tpuscratch.ops`` package) importable — only the overlapping-
        # block kernels below need it, and they raise at call time
        def Element(*_a, **_k):  # noqa: N802 - stands in for the class
            raise NotImplementedError(
                "this jax version has no pallas Element block dims; the "
                "overlapping-block stencil kernels need a newer jax"
            )

from tpuscratch.halo.layout import TileLayout
from tpuscratch.halo.stencil import rebuild
from tpuscratch.ops.common import mosaic_params, use_interpret

Coeffs = tuple[float, float, float, float, float]
JACOBI: Coeffs = (0.25, 0.25, 0.25, 0.25, 0.0)


def _tile_kernel(t_ref, o_ref, *, layout: TileLayout, coeffs: Coeffs):
    hy, hx = layout.halo_y, layout.halo_x
    h, w = layout.core_h, layout.core_w
    cn, cs, cw, ce, cc = coeffs
    t = t_ref[:]
    o_ref[:] = (
        cn * t[hy - 1 : hy - 1 + h, hx : hx + w]
        + cs * t[hy + 1 : hy + 1 + h, hx : hx + w]
        + cw * t[hy : hy + h, hx - 1 : hx - 1 + w]
        + ce * t[hy : hy + h, hx + 1 : hx + 1 + w]
        + cc * t[hy : hy + h, hx : hx + w]
    )


@functools.partial(jax.jit, static_argnames=("layout", "coeffs"))
def five_point_pallas(tile: jax.Array, layout: TileLayout, coeffs: Coeffs = JACOBI) -> jax.Array:
    """One Jacobi step over the whole padded tile in one VMEM block.

    The kernel emits ONLY the new core (a fresh buffer); the halo border is
    re-wrapped by concatenation. Emitting the full tile (copy + core
    overwrite) invites the same in-place aliasing hazard the XLA path hit
    in interpret mode — see halo.stencil.rebuild.
    """
    if layout.halo_y < 1 or layout.halo_x < 1:
        raise ValueError("five_point needs halo >= 1 on both axes")
    if tuple(tile.shape) != layout.padded_shape:
        raise ValueError(f"tile {tile.shape} != padded {layout.padded_shape}")
    new_core = pl.pallas_call(
        functools.partial(_tile_kernel, layout=layout, coeffs=coeffs),
        out_shape=jax.ShapeDtypeStruct(
            (layout.core_h, layout.core_w), tile.dtype
        ),
        interpret=use_interpret(),
    )(tile)
    return rebuild(tile, new_core, layout)


def _trapezoid_kernel(t_ref, o_ref, *, substeps: int, crop: int, coeffs: Coeffs):
    from tpuscratch.halo.stencil import shrink_step

    a = t_ref[:]
    for _ in range(substeps):
        a = shrink_step(a, coeffs)
    if crop:
        a = a[crop:-crop, crop:-crop]
    o_ref[:] = a


def _largest_divisor_band(
    n: int, cost_of_band, budget_bytes: int, strict: bool = False
) -> int:
    """Largest divisor band of ``n`` with ``cost_of_band(band) <= budget``
    (shared by the banded kernels' block sizing). With ``strict``, raises
    when even the single-unit band exceeds the budget — launching anyway
    would fail in Mosaic with an opaque scoped-vmem error. (The 2D
    trapezoid caller stays non-strict: its budget is an input-block bound
    with deliberate margin, not a full-footprint model.)"""
    band = n
    while band > 1 and cost_of_band(band) > budget_bytes:
        band = next((d for d in range(band - 1, 0, -1) if n % d == 0), 1)
    if strict and cost_of_band(band) > budget_bytes:
        raise ValueError(
            f"no band fits: even band=1 needs {cost_of_band(1)} B "
            f"(> budget {budget_bytes} B); shrink the plane extents or "
            "raise the budget"
        )
    return band


def _trapezoid_band(layout: TileLayout, itemsize: int, budget_bytes: int) -> int:
    """Largest divisor band of core_h whose input block fits the VMEM
    budget (block is (band + 2*halo) x padded_w; the pyramid's temporaries
    are about two more blocks, handled by the margin in ``budget_bytes``)."""
    ph, pw = layout.padded_shape
    if ph * pw * itemsize <= budget_bytes:  # whole tile in one block
        return layout.core_h
    return _largest_divisor_band(
        layout.core_h,
        lambda band: (band + 2 * layout.halo_y) * pw * itemsize,
        budget_bytes,
    )


@functools.partial(
    jax.jit, static_argnames=("layout", "substeps", "coeffs", "budget_bytes")
)
def deep_trapezoid_pallas(
    tile: jax.Array,
    layout: TileLayout,
    substeps: int,
    coeffs: Coeffs = JACOBI,
    budget_bytes: int = 2 << 20,
) -> jax.Array:
    """``substeps`` Jacobi steps of the padded tile in one VMEM residency
    per row band: read each band from HBM once, run the shrinking
    valid-region pyramid entirely in VMEM, write its advanced core rows
    once.

    The deep-halo (trapezoid) scheme's compute side: where the XLA deep
    path costs ~one HBM pass per substep, this costs one read + one write
    per ``substeps`` — the difference between HBM-roofline and
    VMEM-roofline stepping. Small tiles run as a single block; tiles too
    big for VMEM (~16 MB/core) run as a 1D grid over row bands whose
    input blocks overlap by 2*halo rows (Element-indexed BlockSpec), at
    the price of ~2*halo/band redundant rows per band.

    Requires halo_y == halo_x >= substeps (the caller's exchange must have
    filled a halo at least ``substeps`` deep).
    """
    k = layout.halo_y
    if layout.halo_y != layout.halo_x:
        raise ValueError("square halo required")
    if not (1 <= substeps <= k):
        raise ValueError(f"substeps {substeps} must be in [1, halo {k}]")
    if tuple(tile.shape) != layout.padded_shape:
        raise ValueError(f"tile {tile.shape} != padded {layout.padded_shape}")
    kern = functools.partial(
        _trapezoid_kernel, substeps=substeps, crop=k - substeps, coeffs=coeffs
    )
    band = _trapezoid_band(layout, tile.dtype.itemsize, budget_bytes)
    if band == layout.core_h:
        return pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct(
                (layout.core_h, layout.core_w), tile.dtype
            ),
            interpret=use_interpret(),
        )(tile)
    ph, pw = layout.padded_shape
    return pl.pallas_call(
        kern,
        grid=(layout.core_h // band,),
        in_specs=[
            # band i reads padded rows [i*band, i*band + band + 2k)
            pl.BlockSpec(
                (Element(band + 2 * k), Element(pw)),
                lambda i: (i * band, 0),
            )
        ],
        out_specs=pl.BlockSpec((band, layout.core_w), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (layout.core_h, layout.core_w), tile.dtype
        ),
        interpret=use_interpret(),
    )(tile)


def _resident_step(a: jax.Array, coeffs: Coeffs) -> jax.Array:
    """One periodic 5-point update of a whole (unpadded) grid via rolls —
    the torus wrap is the roll's modular indexing, no ghost cells at all."""
    cn, cs, cw, ce, cc = coeffs
    if cn == cs == cw == ce and cc == 0.0:
        # symmetric Jacobi: 1 multiply + 3 adds (the VMEM-bound regime
        # cares — measured ~5% over the generic form on v5e)
        return cn * (
            (jnp.roll(a, 1, 0) + jnp.roll(a, -1, 0))
            + (jnp.roll(a, 1, 1) + jnp.roll(a, -1, 1))
        )
    out = (
        cn * jnp.roll(a, 1, 0)
        + cs * jnp.roll(a, -1, 0)
        + cw * jnp.roll(a, 1, 1)
        + ce * jnp.roll(a, -1, 1)
    )
    return out + cc * a if cc else out


def _resident_kernel(t_ref, o_ref, *, steps: int, unroll: int, coeffs: Coeffs):
    from jax import lax

    rounds, rem = divmod(steps, unroll)

    def it(_, a):
        for _ in range(unroll):
            a = _resident_step(a, coeffs)
        return a

    a = lax.fori_loop(0, rounds, it, t_ref[:])
    for _ in range(rem):
        a = _resident_step(a, coeffs)
    o_ref[:] = a


@functools.partial(
    jax.jit, static_argnames=("steps", "coeffs", "unroll", "vmem_limit_bytes")
)
def resident_periodic_pallas(
    core: jax.Array,
    steps: int,
    coeffs: Coeffs = JACOBI,
    unroll: int = 8,
    vmem_limit_bytes: int = 100 << 20,
) -> jax.Array:
    """``steps`` periodic Jacobi steps with the WHOLE grid resident in VMEM.

    The endpoint of the HBM-avoidance ladder: the plain path pays one HBM
    pass per step, the deep-halo trapezoid one pass per K steps — this pays
    one read + one write per ``steps``. The grid is loaded once, a
    ``fori_loop`` advances it entirely in VMEM (periodic wrap = ``roll``),
    and only the final state is written back. Single-device only: the torus
    wrap is internal, so there is no halo to exchange — the resident
    counterpart of the reference's single-rank stencil configuration.

    Needs ~6 grid-sized VMEM buffers (carry + rolled temporaries, the
    guard's sizing rule: ``6 * grid bytes <= vmem_limit_bytes``); capped
    by ``vmem_limit_bytes`` (v5e/v5p have 128 MB VMEM; Mosaic's default
    scoped window is 16 MB, so the limit is raised explicitly). A 1024^2
    f32 grid (4 MB) runs at ~4 us/step on one v5e core vs ~9.7 us/step for
    the HBM-roofline path. ``unroll`` trades instruction-cache pressure for
    loop/scheduling overhead; 8 measured best on v5e.
    """
    if core.ndim != 2:
        raise ValueError(f"resident stencil wants a 2D grid, got {core.shape}")
    if steps < 0:
        raise ValueError(f"negative steps {steps}")
    if unroll < 1:
        raise ValueError(f"unroll must be >= 1, got {unroll}")
    need = 6 * core.size * core.dtype.itemsize
    if need > vmem_limit_bytes:
        raise ValueError(
            f"grid {core.shape} needs ~{need >> 20} MB VMEM "
            f"(> limit {vmem_limit_bytes >> 20} MB); use the banded "
            "deep_trapezoid_pallas path for grids that don't fit"
        )
    interpret = use_interpret()
    params = mosaic_params(vmem_limit_bytes=vmem_limit_bytes)
    return pl.pallas_call(
        functools.partial(
            _resident_kernel, steps=steps, unroll=unroll, coeffs=coeffs
        ),
        out_shape=jax.ShapeDtypeStruct(core.shape, core.dtype),
        interpret=interpret,
        **params,
    )(core)


def _band3d_kernel(t_ref, o_ref, *, band: int, cy: int, cx: int, coeffs7):
    t = t_ref[:]  # (band + 2, cy + 2, cx + 2): one overlap plane each side
    sl = lambda dz, dy, dx: t[  # noqa: E731
        1 + dz : 1 + dz + band, 1 + dy : 1 + dy + cy, 1 + dx : 1 + dx + cx
    ]
    faces = ((-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0), (0, 0, -1), (0, 0, 1))
    new = coeffs7[6] * sl(0, 0, 0) if coeffs7[6] else None
    for d, w in zip(faces, coeffs7[:6]):
        term = w * sl(*d)
        new = term if new is None else new + term
    o_ref[:] = new


#: v5e/v5p scoped-VMEM ceiling the banded 3D kernel sizes itself against.
_VMEM_CEILING = 100 << 20


def _band3d_cost(band: int, cy: int, cx: int, itemsize: int) -> int:
    """Scoped-VMEM footprint model for one z-band: double-buffered input
    and output blocks plus ~3 output-sized slice temporaries (the factor
    measured on v5e — Mosaic accounts all of them against scoped vmem)."""
    in_block = (band + 2) * (cy + 2) * (cx + 2) * itemsize
    out_block = band * cy * cx * itemsize
    return 2 * in_block + 2 * out_block + 3 * out_block


@functools.partial(jax.jit, static_argnames=("core_shape", "coeffs7", "budget_bytes"))
def seven_point_banded_pallas(
    padded: jax.Array,
    core_shape: tuple[int, int, int],
    coeffs7,
    budget_bytes: int = _VMEM_CEILING,
) -> jax.Array:
    """7-point update of a 3D padded tile, banded over z-planes.

    The 3D sibling of ``five_point_blocked``: a 1D grid over z bands whose
    input blocks overlap by one plane (Element-indexed BlockSpec), each
    band's seven shifted reads fused in VMEM. Emits only the new core.
    The band is the largest divisor of cz whose FULL footprint (buffers +
    temporaries, ``_band3d_cost``) fits ``budget_bytes``, which is also
    the Mosaic scoped-vmem limit — one knob, no way to pick a band the
    compiler then rejects.
    """
    cz, cy, cx = core_shape
    if tuple(padded.shape) != (cz + 2, cy + 2, cx + 2):
        raise ValueError(
            f"padded {padded.shape} != core {core_shape} + 1-ghost ring"
        )
    band = _largest_divisor_band(
        cz,
        lambda b: _band3d_cost(b, cy, cx, padded.dtype.itemsize),
        budget_bytes,
        strict=True,
    )
    kern = functools.partial(
        _band3d_kernel, band=band, cy=cy, cx=cx, coeffs7=tuple(coeffs7)
    )
    return pl.pallas_call(
        kern,
        grid=(cz // band,),
        in_specs=[
            pl.BlockSpec(
                (Element(band + 2), Element(cy + 2), Element(cx + 2)),
                lambda i: (i * band, 0, 0),
            )
        ],
        out_specs=pl.BlockSpec((band, cy, cx), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((cz, cy, cx), padded.dtype),
        interpret=use_interpret(),
        **mosaic_params(vmem_limit_bytes=budget_bytes),
    )(padded)


def _strips3d_kernel(z_ref, my_ref, py_ref, mx_ref, px_ref, o_ref, *,
                     band: int, cy: int, cx: int, coeffs7):
    t = z_ref[:]                      # (band + 2, cy, cx): z-overlap only
    c = t[1 : band + 1]
    up_z, dn_z = t[0:band], t[2 : band + 2]
    ym = jnp.concatenate([my_ref[:], c[:, :-1, :]], axis=1)
    yp = jnp.concatenate([c[:, 1:, :], py_ref[:]], axis=1)
    xm = jnp.concatenate([mx_ref[:], c[:, :, :-1]], axis=2)
    xp = jnp.concatenate([c[:, :, 1:], px_ref[:]], axis=2)
    w = coeffs7
    out = (
        w[0] * up_z + w[1] * dn_z + w[2] * ym + w[3] * yp
        + w[4] * xm + w[5] * xp
    )
    o_ref[:] = out + w[6] * c if w[6] else out


@functools.partial(jax.jit, static_argnames=("core_shape", "coeffs7", "budget_bytes"))
def seven_point_strips_pallas(
    zpad: jax.Array,
    a_my: jax.Array,
    a_py: jax.Array,
    a_mx: jax.Array,
    a_px: jax.Array,
    core_shape: tuple[int, int, int],
    coeffs7,
    budget_bytes: int = _VMEM_CEILING,
) -> jax.Array:
    """7-point update taking the y/x boundary strips as kernel inputs.

    Saves the y/x concat materializations the padded-tile path pays on
    the XLA side (each a full-grid HBM pass per step): only the z-padded
    array (core + 2 arrival planes) is assembled outside; the in-band
    y/x shifts concatenate the strip blocks in VMEM.
    """
    cz, cy, cx = core_shape
    if tuple(zpad.shape) != (cz + 2, cy, cx):
        raise ValueError(f"zpad {zpad.shape} != core {core_shape} + 2 z planes")
    itemsize = zpad.dtype.itemsize

    def cost(b):
        in_block = (b + 2) * cy * cx * itemsize
        out_block = b * cy * cx * itemsize
        return 2 * in_block + 2 * out_block + 5 * out_block  # concat temps

    band = _largest_divisor_band(cz, cost, budget_bytes, strict=True)
    kern = functools.partial(
        _strips3d_kernel, band=band, cy=cy, cx=cx, coeffs7=tuple(coeffs7)
    )
    return pl.pallas_call(
        kern,
        grid=(cz // band,),
        in_specs=[
            pl.BlockSpec(
                (Element(band + 2), Element(cy), Element(cx)),
                lambda i: (i * band, 0, 0),
            ),
            pl.BlockSpec((band, 1, cx), lambda i: (i, 0, 0)),
            pl.BlockSpec((band, 1, cx), lambda i: (i, 0, 0)),
            pl.BlockSpec((band, cy, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((band, cy, 1), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((band, cy, cx), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((cz, cy, cx), zpad.dtype),
        interpret=use_interpret(),
        **mosaic_params(vmem_limit_bytes=budget_bytes),
    )(zpad, a_my, a_py, a_mx, a_px)


def _asm3d_compute(o_ref, up, dn, c, my, py, mx, px, cy: int, cx: int, w,
                   fterm=None, fc: float = 0.0):
    """Ring-decomposed 7-point band update: the interior is pure shifted
    slices (no temporaries beyond the fused sum), and only the four
    boundary LINES pay concats — (band,1,cx)/(band,cy-2,1) sized, ~cy/2
    times smaller than the full-plane concats of _strips3d_kernel.

    ``fterm``/``fc``: optional pointwise affine term — each output cell
    additionally gets ``fc * fterm`` at its own coordinates (the damped
    Jacobi smoother's rhs contribution, folded into each region's fused
    sum so no extra output pass happens)."""

    def f_at(r0, r1, c0, c1):
        if fterm is None:
            return 0.0
        return fc * fterm[:, r0:r1, c0:c1]

    o_ref[:, 1 : cy - 1, 1 : cx - 1] = (
        w[0] * up[:, 1:-1, 1:-1] + w[1] * dn[:, 1:-1, 1:-1]
        + w[2] * c[:, 0:-2, 1:-1] + w[3] * c[:, 2:, 1:-1]
        + w[4] * c[:, 1:-1, 0:-2] + w[5] * c[:, 1:-1, 2:]
        + w[6] * c[:, 1:-1, 1:-1]
        + f_at(1, cy - 1, 1, cx - 1)
    )
    o_ref[:, 0:1, :] = (
        w[0] * up[:, 0:1, :] + w[1] * dn[:, 0:1, :]
        + w[2] * my + w[3] * c[:, 1:2, :]
        + w[4] * jnp.concatenate([mx[:, 0:1, :], c[:, 0:1, :-1]], axis=2)
        + w[5] * jnp.concatenate([c[:, 0:1, 1:], px[:, 0:1, :]], axis=2)
        + w[6] * c[:, 0:1, :]
        + f_at(0, 1, 0, cx)
    )
    o_ref[:, cy - 1 : cy, :] = (
        w[0] * up[:, -1:, :] + w[1] * dn[:, -1:, :]
        + w[2] * c[:, -2:-1, :] + w[3] * py
        + w[4] * jnp.concatenate([mx[:, -1:, :], c[:, -1:, :-1]], axis=2)
        + w[5] * jnp.concatenate([c[:, -1:, 1:], px[:, -1:, :]], axis=2)
        + w[6] * c[:, -1:, :]
        + f_at(cy - 1, cy, 0, cx)
    )
    o_ref[:, 1 : cy - 1, 0:1] = (
        w[0] * up[:, 1:-1, 0:1] + w[1] * dn[:, 1:-1, 0:1]
        + w[2] * c[:, 0:-2, 0:1] + w[3] * c[:, 2:, 0:1]
        + w[4] * mx[:, 1:-1, :] + w[5] * c[:, 1:-1, 1:2]
        + w[6] * c[:, 1:-1, 0:1]
        + f_at(1, cy - 1, 0, 1)
    )
    o_ref[:, 1 : cy - 1, cx - 1 : cx] = (
        w[0] * up[:, 1:-1, -1:] + w[1] * dn[:, 1:-1, -1:]
        + w[2] * c[:, 0:-2, -1:] + w[3] * c[:, 2:, -1:]
        + w[4] * c[:, 1:-1, -2:-1] + w[5] * px[:, 1:-1, :]
        + w[6] * c[:, 1:-1, -1:]
        + f_at(1, cy - 1, cx - 1, cx)
    )


def _asm3d_kernel(*refs, band: int, cy: int, cx: int, nb: int, coeffs7,
                  has_y: bool, has_x: bool):
    z_ref, mz_ref, pz_ref = refs[0], refs[1], refs[2]
    k = 3
    if has_y:
        my_ref, py_ref = refs[k], refs[k + 1]
        k += 2
    if has_x:
        mx_ref, px_ref = refs[k], refs[k + 1]
        k += 2
    o_ref = refs[k]
    i = pl.program_id(0)
    t = z_ref[:]  # (band + 2, cy, cx): core planes, z-clamped at the rims

    def emit(up, dn, c):
        # absent strips mean the axis self-wraps (degenerate periodic):
        # the ghost line is the band's OWN far line, already in VMEM —
        # a carry-slice input would cost a near-full HBM pass (lane-dim
        # extraction of the whole core, ~0.4 ms/step at 512^2 planes,
        # measured) for data the block is holding anyway
        my = my_ref[:] if has_y else c[:, cy - 1 : cy, :]
        py = py_ref[:] if has_y else c[:, 0:1, :]
        mx = mx_ref[:] if has_x else c[:, :, cx - 1 : cx]
        px = px_ref[:] if has_x else c[:, :, 0:1]
        _asm3d_compute(
            o_ref, up, dn, c, my, py, mx, px, cy, cx, coeffs7,
        )

    # The clamped index map shifts the first and last bands' blocks by
    # one plane, so which rows are (up, core, down) is band-dependent —
    # statically branched on the grid index; the arrival planes slot in
    # as plane-sized concats on just those two bands.
    @pl.when(i == 0)
    def _():
        emit(
            jnp.concatenate([mz_ref[:], t[0 : band - 1]], axis=0),
            t[1 : band + 1],
            t[0:band],
        )

    @pl.when(jnp.logical_and(i > 0, i < nb - 1))
    def _():
        emit(t[0:band], t[2 : band + 2], t[1 : band + 1])

    @pl.when(i == nb - 1)
    def _():
        emit(
            t[1 : band + 1],
            jnp.concatenate([t[3 : band + 2], pz_ref[:]], axis=0),
            t[2 : band + 2],
        )


@functools.partial(jax.jit, static_argnames=("core_shape", "coeffs7", "budget_bytes"))
def seven_point_assembled_pallas(
    core: jax.Array,
    a_mz: jax.Array,
    a_pz: jax.Array,
    a_my: jax.Array,
    a_py: jax.Array,
    a_mx: jax.Array,
    a_px: jax.Array,
    core_shape: tuple[int, int, int],
    coeffs7,
    budget_bytes: int = _VMEM_CEILING,
) -> jax.Array:
    """7-point update assembled entirely inside the kernel pipeline — no
    host-side padded-array build at all.

    The two passes the strips path still paid on the XLA side are gone:
    the z-band pipeline reads the CORE directly through overlapping
    clamped Element blocks (the zpad concat was a full read+write of the
    grid per step), and the boundary values come in as their own banded
    inputs whose async block copies the pipeline overlaps with compute —
    consumed by ring-decomposed slices instead of full-plane
    concatenations. HBM traffic per step is one core read (x (band+2)/
    band overlap) + one core write + 2*nb arrival planes, i.e. the
    2-pass roofline BASELINE.md row 9 names. The reference's analogue is
    communicating strided subarrays without materializing them
    (/root/reference/stencil2d/stencil2D.h:210-228).

    ``a_my/a_py`` (and ``a_mx/a_px``) may be ``None`` per axis, meaning
    that axis self-wraps (degenerate periodic): the kernel then reads
    the ghost lines from its own blocks instead of strip inputs —
    extracting them outside would cost a near-full HBM pass (lane-dim
    slicing of the carry, measured ~0.4 ms/step at 512^2 planes).
    """
    cz, cy, cx = core_shape
    if tuple(core.shape) != core_shape:
        raise ValueError(f"core {core.shape} != {core_shape}")
    if cz < 3 or cy < 3 or cx < 3:
        raise ValueError(
            f"core {core_shape} too small for the assembled kernel "
            "(need >= 3 on every axis)"
        )
    itemsize = core.dtype.itemsize
    plane = cy * cx * itemsize

    def cost(b):
        # double-buffered in (b+2 planes) + out (b) + register-allocator
        # spill slots, which Mosaic charges against scoped VMEM and which
        # measure ~3.4x the out block for this kernel's five regional
        # stores (54.29M at band=16/512^2 planes, from the chip
        # compiler's allocation dump) + arrival planes and slack
        return (2 * (b + 2) + 2 * b + 3.5 * b) * plane + 6 * plane

    band = _largest_divisor_band(
        cz, cost, budget_bytes, strict=True
    )
    if cz // band < 2:
        # the branch structure needs >= 2 bands: drop to the largest
        # proper divisor (band=1 in the worst case — prime cz runs fine,
        # every band then takes a first/middle/last branch)
        band = next(d for d in range(cz // 2, 0, -1) if cz % d == 0)
    nb = cz // band
    has_y = a_my is not None
    has_x = a_mx is not None
    if (a_py is None) != (a_my is None) or (a_px is None) != (a_mx is None):
        raise ValueError("strip inputs must be present or absent per axis")
    kern = functools.partial(
        _asm3d_kernel, band=band, cy=cy, cx=cx, nb=nb,
        coeffs7=tuple(coeffs7), has_y=has_y, has_x=has_x,
    )
    zmax = cz - band - 2

    in_specs = [
        pl.BlockSpec(
            (Element(band + 2), Element(cy), Element(cx)),
            lambda i: (jnp.clip(i * band - 1, 0, zmax), 0, 0),
        ),
        pl.BlockSpec((1, cy, cx), lambda i: (0, 0, 0)),
        pl.BlockSpec((1, cy, cx), lambda i: (0, 0, 0)),
    ]
    inputs = [core, a_mz, a_pz]
    if has_y:
        in_specs += [
            pl.BlockSpec((band, 1, cx), lambda i: (i, 0, 0)),
            pl.BlockSpec((band, 1, cx), lambda i: (i, 0, 0)),
        ]
        inputs += [a_my, a_py]
    if has_x:
        in_specs += [
            pl.BlockSpec((band, cy, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((band, cy, 1), lambda i: (i, 0, 0)),
        ]
        inputs += [a_mx, a_px]

    return pl.pallas_call(
        kern,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((band, cy, cx), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((cz, cy, cx), core.dtype),
        interpret=use_interpret(),
        **mosaic_params(vmem_limit_bytes=budget_bytes),
    )(*inputs)


def _band_kernel(t_ref, o_ref, *, band: int, halo_x: int, width: int, coeffs: Coeffs):
    cn, cs, cw, ce, cc = coeffs
    t = t_ref[:]  # (band + 2, 2*halo_x + width): one overlap row each side
    w = width
    hx = halo_x
    new = (
        cn * t[0:band, hx : hx + w]
        + cs * t[2 : band + 2, hx : hx + w]
        + cw * t[1 : band + 1, hx - 1 : hx - 1 + w]
        + ce * t[1 : band + 1, hx + 1 : hx + 1 + w]
        + cc * t[1 : band + 1, hx : hx + w]
    )
    o_ref[:] = new


@functools.partial(jax.jit, static_argnames=("layout", "coeffs", "band"))
def five_point_blocked(
    tile: jax.Array,
    layout: TileLayout,
    coeffs: Coeffs = JACOBI,
    band: int = 256,
) -> jax.Array:
    """Jacobi step for cores too large for one VMEM block.

    The grid walks row bands of the core; each input block is the band plus
    one row above and below — overlapping reads expressed with
    Element-indexed block dims (the index_map steps by ``band`` elements
    while the block spans ``band + 2`` rows). Only the new core is
    produced; the caller's padded tile is re-wrapped around it. Requires
    halo >= 1 and core_h % band == 0.
    """
    if layout.halo_y < 1 or layout.halo_x < 1:
        raise ValueError("five_point needs halo >= 1 on both axes")
    if tuple(tile.shape) != layout.padded_shape:
        raise ValueError(f"tile {tile.shape} != padded {layout.padded_shape}")
    h, w = layout.core_h, layout.core_w
    band = min(band, h)
    if h % band:
        raise ValueError(f"core_h {h} not divisible by band {band}")
    hy, hx = layout.halo_y, layout.halo_x
    grid = h // band
    pw = layout.padded_shape[1]

    new_core = pl.pallas_call(
        functools.partial(
            _band_kernel, band=band, halo_x=hx, width=w, coeffs=coeffs
        ),
        grid=(grid,),
        in_specs=[
            # band i reads rows [hy-1 + i*band, hy+1 + i*band + band)
            pl.BlockSpec(
                (Element(band + 2), Element(pw)),
                lambda i: (hy - 1 + i * band, 0),
            )
        ],
        out_specs=pl.BlockSpec((band, w), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((h, w), tile.dtype),
        interpret=use_interpret(),
    )(tile)
    return rebuild(tile, new_core, layout)
