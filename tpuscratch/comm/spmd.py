"""The SPMD program runner: trace-then-execute replaces plan-then-execute.

The reference precompiles a communication plan (descriptor arrays) and then
executes it with Isend/Irecv/Waitall per iteration
(/root/reference/stencil2D.h:319-437,363-377). The XLA analogue: a
``shard_map``-decorated function IS the plan — traced once, compiled once,
and every execution replays the compiled collective schedule (XLA's
scheduler plays the role of Waitall). ``run_spmd`` is the one-liner that
builds and jits that program over a mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
from jax.sharding import Mesh, PartitionSpec


def run_spmd(
    mesh: Mesh,
    fn: Callable[..., Any],
    in_specs,
    out_specs,
    check_vma: bool = False,
    donate_argnums=(),
) -> Callable[..., Any]:
    """jit(shard_map(fn)) over ``mesh`` — the compiled SPMD program.

    ``check_vma=False`` by default because several parity patterns
    (root extraction, masked gathers) intentionally produce values that are
    not uniform across an axis.  ``donate_argnums`` passes through to jit
    (state-carrying loops — the decode step's KV cache — reuse the input
    buffer instead of copying it every step).  On jax releases predating
    ``jax.shard_map``, ``runtime.compat`` (imported at package init)
    installs it over the ``jax.experimental`` spelling.
    """
    return jax.jit(
        jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        ),
        donate_argnums=donate_argnums,
    )


def spec(*names) -> PartitionSpec:
    return PartitionSpec(*names)
