"""Communication layer: named collectives and point-to-point patterns.

The framework's NCCL/MPI-equivalent seam (SURVEY.md §2.8): every ``MPI_*``
data-plane call the reference exercises maps to an XLA collective over a
named mesh axis, riding ICI within a slice and DCN across slices.

Mapping table (reference -> here):
- MPI_Reduce/Allreduce  -> ``allreduce_*`` / ``reduce_to_root``
- MPI_Gather/Allgather  -> ``gather_to_root`` / ``all_gather``
- MPI_Bcast             -> ``broadcast``
- MPI_Scatter           -> ``scatter_from_root``
- MPI_Isend/Irecv rings -> ``ring_shift`` / ``neighbor_exchange`` (ppermute)
- MPI_Send/Recv pairs   -> ``send_pairs`` / ``pingpong``
- MPI_Scan/Exscan       -> ``prefix_sum``
- sub-communicators     -> collectives over one axis of a multi-axis mesh
"""

from tpuscratch.comm.collectives import (  # noqa: F401
    all_gather,
    all_to_all,
    allreduce_max,
    allreduce_min,
    allreduce_sum,
    broadcast,
    gather_to_root,
    prefix_sum,
    reduce_scatter,
    reduce_to_root,
    scatter_from_root,
)
from tpuscratch.comm.p2p import (  # noqa: F401
    neighbor_exchange,
    pingpong,
    ring_perm,
    ring_shift,
    send_pairs,
    token_ring,
)
from tpuscratch.comm.spmd import run_spmd  # noqa: F401
