"""Point-to-point patterns: rings, neighbor exchange, pairwise transfers.

The reference's p2p catalog — blocking pair exchange with probe-sized
buffers (/root/reference/mpi3.cpp:26-32), lock-step token passing
(mpi4.cpp:24-44), and nonblocking neighbor exchange with waitall
(mpi5.cpp:34-75) — all compile here to ``lax.ppermute`` with a static
permutation table. Three MPI concepts dissolve on TPU:

- **Probe/Get_count** (dynamic receive sizing): shapes are static under
  XLA; the "probe" happens at trace time, so a receiver always knows its
  buffer shape. There is deliberately no probe API.
- **Tags**: each ppermute is its own op; there is no shared mailbox to
  demultiplex, so direction tags (mpi5.cpp:47-52) have no equivalent.
- **Waitall**: XLA's scheduler sequences/overlaps the transfers; a
  program's data dependencies are the synchronization.

Permutation tables come from ``CartTopology`` (tpuscratch.runtime.topology)
or the helpers below; like every function in this module they must be
static Python values at trace time.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from jax import lax


def ring_perm(n: int, disp: int = 1, periodic: bool = True) -> list[tuple[int, int]]:
    """(src, dst) pairs shifting every rank by ``disp`` around a ring of n.

    Non-periodic rings drop the wrap pair(s): ranks at the open boundary
    simply have no partner (MPI_PROC_NULL semantics, mpi5.cpp:28-33).
    """
    pairs = []
    for i in range(n):
        j = i + disp
        if periodic:
            pairs.append((i, j % n))
        elif 0 <= j < n:
            pairs.append((i, j))
    return pairs


def ring_shift(x, axis: str, disp: int = 1, periodic: bool = True):
    """Every rank receives the value of its neighbor ``disp`` behind it.

    Ranks with no sender (open boundary) receive zeros. The ring size is
    the axis size — a static trace-time constant, so callers cannot
    mis-state it.
    """
    return lax.ppermute(x, axis, ring_perm(lax.axis_size(axis), disp, periodic))


def neighbor_exchange(x, axis: str, periodic: bool = False):
    """(from_left, from_right) — each rank's value shared with both sides.

    mpi5 parity: every rank Isends its id to rank±1 and Irecvs theirs;
    boundaries receive zeros where MPI would skip the transfer.
    """
    from_left = ring_shift(x, axis, disp=+1, periodic=periodic)
    from_right = ring_shift(x, axis, disp=-1, periodic=periodic)
    return from_left, from_right


def send_pairs(x, axis: str, pairs: Sequence[tuple[int, int]]):
    """Explicit pairwise transfers: value of src lands on dst, zeros
    elsewhere (mpi3's two-rank exchange is ``pairs=[(0,1),(1,0)]``)."""
    return lax.ppermute(x, axis, list(pairs))


def send_tree(tree, axis: str, pairs: Sequence[tuple[int, int]]):
    """:func:`send_pairs` over a whole pytree: every leaf rides the same
    static permutation (one ppermute per leaf; XLA schedules them as
    independent nonblocking transfers and the consumer's data
    dependencies are the waitall — the mpi5.cpp Isend/Irecv/Waitall
    shape for a multi-buffer payload).  The serve-side KV-page handoff
    ships ``{k, v[, k_scale, v_scale]}`` page payloads this way: the
    int8 scale planes travel in the SAME permutation as their pages, so
    a migrated page can never arrive separated from its dequantization
    metadata."""
    import jax

    pairs = list(pairs)
    return jax.tree.map(lambda t: lax.ppermute(t, axis, pairs), tree)


def pingpong(x, axis: str, a: int = 0, b: int = 1, rounds: int = 1):
    """Bounce a value between ranks a and b ``rounds`` times (one round =
    a->b->a). The latency-probe primitive (test-benchmark pingpong).

    Returns the bounced value (on rank a after full rounds).
    """
    there = [(a, b)]
    back = [(b, a)]
    y = x
    for _ in range(rounds):
        y = lax.ppermute(y, axis, there)
        y = lax.ppermute(y, axis, back)
    return y


def token_ring(x, axis: str, hops: int, increment=1):
    """Lock-step token circulation: the token makes ``hops`` hops around the
    ring, incremented at each hop — mpi4's counter passing generalized from
    2 ranks to the full ring. Uses a scan (static trip count) so the
    compiled program is one loop, not ``hops`` unrolled ppermutes.

    Every rank receives the circulating token each hop; after ``hops`` hops
    rank (hops % n) holds the token that started at rank 0.
    """
    perm = ring_perm(lax.axis_size(axis), 1, periodic=True)

    def hop(tok, _):
        tok = lax.ppermute(tok, axis, perm) + increment
        return tok, ()

    out, _ = lax.scan(hop, x, None, length=hops)
    return out
