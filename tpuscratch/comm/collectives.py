"""Named collectives over mesh axes — the MPI collective surface, XLA-native.

These are SPMD primitives: call them INSIDE a ``shard_map``-traced function
(see ``tpuscratch.comm.spmd.run_spmd``). Each wraps an XLA collective that
compiles to ICI/DCN transfers on TPU; none of them allocates communicators,
datatypes, or requests — the compiled program is the communication plan.

Parity notes (reference -> here):
- ``MPI_Allreduce`` within halves AND across the world
  (/root/reference/mpi9.cpp:51-54) -> ``allreduce_sum(x, 'half')`` vs
  ``allreduce_sum(x, ('half', 'local'))`` on a 2-axis mesh.
- ``MPI_Reduce`` to rank 0 (/root/reference/mpicuda2.cu:293) ->
  ``reduce_to_root``; non-roots get zeros, matching the undefined recv
  buffer non-roots have under MPI (here defined, for determinism).
- ``MPI_Gather`` root-collects triples (/root/reference/mpi6.cpp:89-100) ->
  ``gather_to_root``.
- ``MPI_Bcast`` of a node count (/root/reference/mpicuda2.cu:154) ->
  ``broadcast``.
- ``MPI_Scatter`` (sketched at /root/reference/mpicuda2.cu:145-152) ->
  ``scatter_from_root``.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def _axis_index(axis: AxisName):
    """Flat index along one axis or row-major across several axes."""
    if isinstance(axis, str):
        return lax.axis_index(axis)
    idx = lax.axis_index(axis[0])
    for name in axis[1:]:
        idx = idx * lax.axis_size(name) + lax.axis_index(name)
    return idx


def allreduce_sum(x, axis: AxisName):
    return lax.psum(x, axis)


def allreduce_max(x, axis: AxisName):
    return lax.pmax(x, axis)


def allreduce_min(x, axis: AxisName):
    return lax.pmin(x, axis)


def reduce_to_root(x, axis: AxisName, root: int = 0):
    """Sum-reduce; root rank holds the result, others hold zeros."""
    total = lax.psum(x, axis)
    return jnp.where(_axis_index(axis) == root, total, jnp.zeros_like(total))


def broadcast(x, axis: AxisName, root: int = 0):
    """Every rank receives root's value."""
    masked = jnp.where(_axis_index(axis) == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def all_gather(x, axis: AxisName, tiled: bool = False):
    """Concatenate every rank's shard along a new (or existing) leading dim."""
    return lax.all_gather(x, axis, tiled=tiled)


def gather_to_root(x, axis: AxisName, root: int = 0, tiled: bool = False):
    """Root holds the gathered array, others hold zeros (MPI_Gather shape)."""
    gathered = lax.all_gather(x, axis, tiled=tiled)
    keep = _axis_index(axis) == root
    return jnp.where(keep, gathered, jnp.zeros_like(gathered))


def scatter_from_root(x, axis: str, root: int = 0):
    """Root's array is split evenly along dim 0; rank i receives piece i.

    ``x`` is the full array on every rank's shard input (replicated in-spec);
    only root's copy matters — parity with MPI_Scatter where non-root send
    buffers are ignored.
    """
    n = lax.axis_size(axis)
    if x.shape[0] % n != 0:
        raise ValueError(
            f"scatter: leading dim {x.shape[0]} not divisible by axis size {n}"
        )
    rooted = broadcast(x, axis, root)  # ensure all ranks agree on root data
    piece = x.shape[0] // n
    start = _axis_index(axis) * piece
    return lax.dynamic_slice_in_dim(rooted, start, piece, axis=0)


def reduce_scatter(x, axis: str, scatter_dimension: int = 0, tiled: bool = False):
    return lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=tiled
    )


def all_to_all(x, axis: str, split_axis: int = 0, concat_axis: int = 0, tiled: bool = False):
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def prefix_sum(x, axis: AxisName, exclusive: bool = False):
    """Per-rank running sum along the axis — MPI_Scan / MPI_Exscan.

    Rank r receives ``sum(x_0..x_r)`` (inclusive) or ``sum(x_0..x_{r-1})``
    (exclusive; rank 0 gets zeros, where MPI_Exscan leaves it undefined).
    Rounds out the MPI collective family the reference's backend offers
    (SURVEY.md §2.8); the implementation is one all_gather + a static
    masked sum — the right trade at mesh sizes where the gather is one
    ICI hop, vs a log-depth ppermute tree.
    """
    idx = _axis_index(axis)
    gathered = lax.all_gather(x, axis)  # (n, *x.shape), same on every rank
    n = gathered.shape[0]
    ranks = jnp.arange(n)
    keep = (ranks < idx) if exclusive else (ranks <= idx)
    mask = keep.reshape((n,) + (1,) * x.ndim).astype(gathered.dtype)
    return jnp.sum(gathered * mask, axis=0)
