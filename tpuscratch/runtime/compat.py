"""Version gates for older jax releases (imported for side effects).

The framework is written against current jax spellings — ``jax.shard_map``
with its ``check_vma`` keyword, ``lax.axis_size`` — but some images pin an
older jax where the same capabilities live under earlier names
(``jax.experimental.shard_map`` with ``check_rep``; no ``axis_size``
helper).  Rather than scatter try/except at every call site, this module
installs the new spellings when absent, once, at package import
(``tpuscratch/__init__`` imports it before anything else).  On a current
jax it is a no-op.
"""

from __future__ import annotations

import functools

import jax
from jax import lax


def profiler_trace_supported() -> bool:
    """Whether ``jax.profiler.start_trace``/``stop_trace`` exist on this
    jax.  Existence is necessary but not sufficient — on some images the
    call itself fails at runtime (missing profiler backend), so
    ``runtime.profiling.trace`` ALSO guards the call and degrades to a
    warned no-op span; this predicate is the cheap static half."""
    prof = getattr(jax, "profiler", None)
    return (
        prof is not None
        and hasattr(prof, "start_trace")
        and hasattr(prof, "stop_trace")
    )


def _install() -> None:
    if not hasattr(lax, "axis_size"):

        def axis_size(axis_name):
            """``lax.axis_size`` backfill: psum of the unit *constant*
            folds to the static axis size inside shard_map (a Python int,
            not a tracer), so schedule math built on it stays trace-time."""
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size  # type: ignore[attr-defined]

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy

        @functools.wraps(_legacy)
        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
            # the keyword was renamed check_rep -> check_vma when shard_map
            # graduated from jax.experimental; semantics are unchanged
            return _legacy(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=check_vma, **kw,
            )

        jax.shard_map = shard_map  # type: ignore[attr-defined]

    if not hasattr(getattr(jax, "profiler", object()), "TraceAnnotation"):
        import contextlib

        # profiler timeline annotations are decorative: absent support
        # degrades to a no-op context, keeping annotate() callers working
        if hasattr(jax, "profiler"):
            jax.profiler.TraceAnnotation = (  # type: ignore[attr-defined]
                lambda name, **kw: contextlib.nullcontext()
            )

    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:  # no pallas at all: the kernels gate themselves
        return
    # pallas-TPU renames (TPU* prefixes dropped when pallas stabilized)
    if not hasattr(pltpu, "MemorySpace") and hasattr(pltpu, "TPUMemorySpace"):
        pltpu.MemorySpace = pltpu.TPUMemorySpace
    if not hasattr(pltpu, "CompilerParams") and hasattr(
        pltpu, "TPUCompilerParams"
    ):
        import inspect

        _tcp = pltpu.TPUCompilerParams
        _known = set(inspect.signature(_tcp.__init__).parameters)

        def _compiler_params(**kw):
            # fields the old class predates (e.g. has_side_effects) are
            # dropped: on a jax this old the Mosaic path only ever runs
            # in interpret mode, where they have no effect anyway
            return _tcp(**{k: v for k, v in kw.items() if k in _known})

        pltpu.CompilerParams = _compiler_params


_install()
