"""One typed configuration object replacing the reference's three config tiers.

The reference configures behavior through (a) compile-time ``#define``
switches — GPU, NO_LOG, REDUCE_CPU/REDUCE_GPU, DOUBLE_, MPI_RROBIN_,
NO_GPU_MALLOC_TIME, HOST_COPY, PAGE_LOCKED, MPI_ERR_USE_EXCEPTIONS
(/root/reference/mpicuda3.cu:18-24, mpi-pingpong-gpu-async.cpp:43-49,
mpierr.h:48) — (b) argv for sizes (mpi-pingpong-gpu.cpp:31,
mpi-2d-stencil-subarray-cuda.cu:131-138), and (c) env vars for runtime
discovery (MV2_COMM_WORLD_LOCAL_RANK etc., -cuda.cu:46-69). Here all of it
is one frozen dataclass, parseable from argv and env, passed explicitly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax.numpy as jnp

from tpuscratch.runtime.errors import ErrorPolicy

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float64": jnp.float64,  # requires jax_enable_x64; fp64 parity w/ DOUBLE_
    "int32": jnp.int32,
}


@dataclasses.dataclass(frozen=True)
class Config:
    # -- compute path ----------------------------------------------------
    dtype: str = "float32"           # DOUBLE_ switch parity, but runtime-typed
    use_pallas: bool = True          # GPU vs host-loop switch parity: pallas
    #                                  kernel vs plain jnp reference path
    block_rows: int = 512            # kernel block shape (BLOCK_SIZE parity,
    #                                  mpicuda3.cu:65 raised 256->512)
    reduce_on_device: bool = True    # REDUCE_GPU vs host-accumulate parity
    # -- mesh ------------------------------------------------------------
    mesh_shape: Optional[tuple[int, ...]] = None  # None = auto (all devices)
    periodic: bool = True
    # -- problem sizes (argv tier) ---------------------------------------
    tile_width: int = 16             # reference default tile (subarray.cpp:71)
    tile_height: int = 16
    stencil_width: int = 5           # reference default 5x5 stencil
    stencil_height: int = 5
    elements: int = 1 << 20          # message/vector size (argv parity)
    steps: int = 5                   # iteration count for iterative drivers
    impl: str = ""                   # impl selector ("" = driver default);
    #                                  stencil: xla/pallas/blocked/overlap/
    #                                  deep/dma/resident, dot: full/partials/
    #                                  xla, attention: pallas/xla
    # -- serving (serve/engine.py knobs; argv tier like the sizes above) --
    decode_slots: int = 8            # continuous-batching decode-batch width
    kv_pages: int = 64               # KV-cache pages per dp group
    page_size: int = 8               # tokens per KV page
    kv_dtype: str = "float32"        # KV-page dtype: float32 | int8 | fp8
    #                                  (int8 = quantized pages, ~1/4 bytes)
    spec: int = 0                    # speculative draft tokens per verify
    #                                  sweep (0 = speculation off)
    # -- instrumentation -------------------------------------------------
    log: bool = True                 # NO_LOG parity
    include_setup_time: bool = True  # NO_GPU_MALLOC_TIME parity
    error_policy: ErrorPolicy = ErrorPolicy.RAISE  # MPI_ERR_USE_EXCEPTIONS

    def __post_init__(self):
        # provenance: which fields were EXPLICITLY set (Config.load fills
        # this) — so callers can distinguish "user asked for the default
        # value" from "user said nothing" without sentinel comparisons.
        # Not a dataclass field: replace()/asdict() reset it.
        if not hasattr(self, "explicit"):
            object.__setattr__(self, "explicit", frozenset())

    # ---- derived -------------------------------------------------------

    @property
    def jnp_dtype(self):
        try:
            return _DTYPES[self.dtype]
        except KeyError:
            raise ValueError(
                f"unknown dtype {self.dtype!r}; choose from {sorted(_DTYPES)}"
            ) from None

    @property
    def halo_width(self) -> int:
        # ghost depth = stencil//2, as in stencil2D.h:116-117
        return self.stencil_width // 2

    @property
    def halo_height(self) -> int:
        return self.stencil_height // 2

    # ---- construction --------------------------------------------------

    @classmethod
    def from_argv(cls, argv: Sequence[str], **overrides) -> "Config":
        """CLI parity with the reference drivers: positional
        ``[tile_w tile_h [stencil_w stencil_h]]`` (-cuda.cu:131-138, including
        fixing its stencilHeight self-assignment bug) or ``elements`` for the
        benchmarks (mpi-pingpong-gpu.cpp:31). Any field is also settable as
        ``--name=value`` (e.g. ``--steps=50 --impl=pallas``)."""
        fields = dict(overrides)
        for flag, value in _parse_flags(argv).items():
            fields.setdefault(flag, value)
        for key, value in _parse_positional(argv).items():
            fields.setdefault(key, value)
        return cls(**fields)

    @classmethod
    def load(cls, argv: Optional[Sequence[str]] = None) -> "Config":
        """The example/driver entry: env tier first, argv tier on top
        (argv wins — the reference's precedence, where a CLI tile size
        overrides whatever the job script exported). The returned
        config's ``explicit`` frozenset names every field that was
        actually set by either tier, so callers can distinguish an
        explicit request for the default value from silence."""
        import sys

        argv = list(sys.argv[1:]) if argv is None else list(argv)
        merged = {
            **_parse_env(dict(os.environ)),
            **_parse_positional(argv),
            **_parse_flags(argv),
        }
        cfg = cls(**merged)
        object.__setattr__(cfg, "explicit", frozenset(merged))
        return cfg

    @classmethod
    def from_env(cls, env: Optional[dict] = None, **overrides) -> "Config":
        """Env tier: TPUSCRATCH_* variables (runtime discovery only)."""
        fields = dict(overrides)
        for key, value in _parse_env(dict(os.environ if env is None else env)).items():
            fields.setdefault(key, value)
        return cls(**fields)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def _coerce(name: str, default, value: str):
    """Parse a flag string by the FIELD DEFAULT's type (annotations are
    strings under ``from __future__ import annotations``)."""
    if name == "mesh_shape":
        return tuple(int(x) for x in value.split("x"))
    if name == "error_policy":
        return ErrorPolicy[value.upper()]
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(value)
    return value


def _parse_positional(argv: Sequence[str]) -> dict:
    """Positional argv tier: ``[elements]`` or
    ``[tile_w tile_h [stencil_w stencil_h]]``."""
    args = [a for a in argv if not a.startswith("-")]
    out = {}
    if len(args) == 1:
        out["elements"] = int(args[0])
    elif len(args) >= 2:
        out["tile_width"] = int(args[0])
        out["tile_height"] = int(args[1])
        if len(args) >= 3:
            out["stencil_width"] = int(args[2])
        if len(args) >= 4:
            out["stencil_height"] = int(args[3])
    return out


def _parse_env(env: dict) -> dict:
    """Env tier: TPUSCRATCH_* variables (runtime discovery only)."""
    out = {}
    if "TPUSCRATCH_DTYPE" in env:
        out["dtype"] = env["TPUSCRATCH_DTYPE"]
    if "TPUSCRATCH_NO_LOG" in env:
        out["log"] = env["TPUSCRATCH_NO_LOG"] not in ("1", "true")
    if "TPUSCRATCH_MESH" in env:  # e.g. "2x4"
        out["mesh_shape"] = tuple(
            int(x) for x in env["TPUSCRATCH_MESH"].split("x")
        )
    if env.get("TPUSCRATCH_ABORT_ON_ERROR", "") in ("1", "true", "yes"):
        out["error_policy"] = ErrorPolicy.ABORT
    return out


def _parse_flags(argv: Sequence[str]) -> dict:
    """``--name=value`` pairs (dashes in names map to underscores)."""
    fields = {f.name: f for f in dataclasses.fields(Config)}
    out = {}
    for a in argv:
        if a.startswith("--"):
            if "=" not in a:
                # refuse the space-separated form rather than silently
                # dropping the flag and mis-parsing its value as a
                # positional argument
                raise ValueError(
                    f"flag {a} needs a value: use {a}=VALUE"
                )
            key, value = a[2:].split("=", 1)
            key = key.replace("-", "_")
            if key not in fields:
                raise ValueError(
                    f"unknown config flag --{key}; fields: {sorted(fields)}"
                )
            out[key] = _coerce(key, fields[key].default, value)
    return out
