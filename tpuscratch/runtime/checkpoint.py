"""Checkpoint / resume for iterative distributed computations.

The reference has no checkpointing — only per-rank result dumps
(mpi-2d-stencil-subarray.cpp:62; SURVEY.md §5 records the gap). A long
stencil run on a preemptible TPU slice needs one, so the framework closes
the gap with a deliberately small format: one directory per step holding
the pytree's leaves as ``.npy`` plus a JSON manifest (treedef, step,
user metadata). Atomic via write-to-temp + rename; ``latest_step`` +
``restore`` give resume-after-preemption.

Multi-host note: each process saves only addressable shards it owns in
this simple format; for sharded multi-host arrays prefer one directory per
process (``tag=f"proc{jax.process_index()}"``), mirroring the reference's
per-rank files keyed by coordinates.
"""

from __future__ import annotations

import json
import os
import pathlib
import shutil
import tempfile
from typing import Any, Optional

import jax
import numpy as np

_MANIFEST = "manifest.json"


def save(ckpt_dir: str | os.PathLike, step: int, tree: Any, metadata: Optional[dict] = None, tag: str = "state") -> pathlib.Path:
    """Atomically write ``tree`` as checkpoint ``step``. Returns the path."""
    root = pathlib.Path(ckpt_dir)
    root.mkdir(parents=True, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    tmp = pathlib.Path(
        tempfile.mkdtemp(prefix=f".tmp_step_{step}_", dir=root)
    )
    try:
        for i, leaf in enumerate(leaves):
            np.save(tmp / f"leaf_{i}.npy", np.asarray(leaf))
        (tmp / _MANIFEST).write_text(
            json.dumps(
                {
                    "step": step,
                    "tag": tag,
                    "n_leaves": len(leaves),
                    "treedef": str(treedef),
                    "metadata": metadata or {},
                }
            )
        )
        final = root / f"step_{step:09d}"
        if final.exists():
            shutil.rmtree(final)
        tmp.rename(final)  # atomic publish
        return final
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def steps(ckpt_dir: str | os.PathLike) -> list[int]:
    root = pathlib.Path(ckpt_dir)
    if not root.exists():
        return []
    out = []
    for p in root.iterdir():
        if p.is_dir() and p.name.startswith("step_") and (p / _MANIFEST).exists():
            out.append(int(p.name.split("_")[1]))
    return sorted(out)


def latest_step(ckpt_dir: str | os.PathLike) -> Optional[int]:
    found = steps(ckpt_dir)
    return found[-1] if found else None


def restore(ckpt_dir: str | os.PathLike, example_tree: Any, step: Optional[int] = None) -> tuple[Any, int, dict]:
    """Load (tree, step, metadata); ``example_tree`` supplies the treedef.

    Defaults to the latest step. Leaf count is validated against the
    example so a structure drift fails loudly instead of mis-zipping.
    """
    step, manifest = _read_manifest(ckpt_dir, step)
    path = pathlib.Path(ckpt_dir) / f"step_{step:09d}"
    leaves, treedef = jax.tree.flatten(example_tree)
    if manifest["n_leaves"] != len(leaves):
        raise ValueError(
            f"checkpoint has {manifest['n_leaves']} leaves, example tree "
            f"has {len(leaves)} — structure changed since save"
        )
    loaded = [
        np.load(path / f"leaf_{i}.npy") for i in range(manifest["n_leaves"])
    ]
    return jax.tree.unflatten(treedef, loaded), step, manifest["metadata"]


def _read_manifest(ckpt_dir: str | os.PathLike, step: Optional[int]) -> tuple[int, dict]:
    """Resolve ``step`` (default: latest) and load its manifest."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = pathlib.Path(ckpt_dir) / f"step_{step:09d}"
    return step, json.loads((path / _MANIFEST).read_text())


def peek_metadata(ckpt_dir: str | os.PathLike, step: Optional[int] = None) -> tuple[int, dict]:
    """(step, metadata) without loading any leaf arrays — the cheap
    pre-restore compatibility check (manifest.json only)."""
    step, manifest = _read_manifest(ckpt_dir, step)
    return step, manifest["metadata"]


def prune(ckpt_dir: str | os.PathLike, keep: int = 3) -> None:
    """Delete all but the newest ``keep`` checkpoints."""
    for s in steps(ckpt_dir)[:-keep] if keep > 0 else steps(ckpt_dir):
        shutil.rmtree(pathlib.Path(ckpt_dir) / f"step_{s:09d}", ignore_errors=True)
