"""Checkpoint / resume for iterative distributed computations.

The reference has no checkpointing — only per-rank result dumps
(mpi-2d-stencil-subarray.cpp:62; SURVEY.md §5 records the gap). A long
stencil run on a preemptible TPU slice needs one, so the framework closes
the gap with a deliberately small format: one directory per step holding
the pytree's leaves as ``.npy`` plus a JSON manifest (treedef, step,
per-leaf shape/dtype/file-size, user metadata). Atomic via
write-to-temp + rename; ``latest_step`` + ``restore`` give
resume-after-preemption.

Crash safety: a same-step overwrite renames the published dir ASIDE
(call-unique name), publishes the new one, then deletes the aside — so
no kill point loses an already-published step.  The read path
(``steps``/``restore``) RECOGNIZES a stranded aside as that step and
never renames or deletes anything, so concurrent readers cannot race an
in-flight save; the writer's next ``save`` runs :func:`_gc`, which
renames an unreplaced aside back and deletes orphaned ``.tmp_step_*``
write temps.
``save`` takes a ``hook`` called at each internal stage — the chaos
harness's injection point (``tests/test_checkpoint_resume.py`` SIGKILLs
a worker at every stage and proves resume always finds a valid step).

Multi-host note: each process saves only addressable shards it owns in
this simple format; for sharded multi-host arrays prefer one directory per
process (``tag=f"proc{jax.process_index()}"``), mirroring the reference's
per-rank files keyed by coordinates.  The aside/GC scheme assumes one
writer per directory, same as the atomic-rename scheme before it.
"""

from __future__ import annotations

import json
import os
import pathlib
import shutil
import tempfile
import uuid
from typing import Any, Callable, Optional

import jax
import numpy as np

_MANIFEST = "manifest.json"
_TMP_PREFIX = ".tmp_step_"
_OLD_PREFIX = ".old_step_"


def _aside_step(name: str) -> int:
    return int(name[len(_OLD_PREFIX):].split("_")[0])


def _gc(root: pathlib.Path) -> None:
    """Collect debris from crashed saves — called by the single WRITER
    (``save``) only; the read path never mutates (it *recognizes*
    stranded asides instead, :func:`_step_dir`).  Orphaned write temps
    are deleted; an aside whose replacement never published is renamed
    BACK, otherwise deleted."""
    if not root.exists():
        return
    for p in root.iterdir():
        if not p.is_dir():
            continue
        if p.name.startswith(_TMP_PREFIX):
            shutil.rmtree(p, ignore_errors=True)
        elif p.name.startswith(_OLD_PREFIX):
            final = root / f"step_{_aside_step(p.name):09d}"
            if final.exists():
                shutil.rmtree(p, ignore_errors=True)
            else:
                p.rename(final)


def _step_dir(root: pathlib.Path, step: int) -> pathlib.Path:
    """Directory holding checkpoint ``step`` — normally
    ``step_<step>``, falling back to a stranded ``.old_step_<step>_*``
    aside (a crash between the aside-rename and the publish).  Pure
    lookup: readers never rename, so they can never race the writer's
    swap window."""
    final = root / f"step_{step:09d}"
    if final.exists():
        return final
    for p in root.iterdir():
        if (p.is_dir() and p.name.startswith(_OLD_PREFIX)
                and _aside_step(p.name) == step
                and (p / _MANIFEST).exists()):
            return p
    return final


def save(ckpt_dir: str | os.PathLike, step: int, tree: Any,
         metadata: Optional[dict] = None, tag: str = "state",
         hook: Optional[Callable[[str], None]] = None) -> pathlib.Path:
    """Atomically write ``tree`` as checkpoint ``step``. Returns the path.

    ``hook`` (chaos/testing only) is called with a stage name at each
    internal boundary: ``"begin"``, ``"leaf_<i>"`` after each leaf
    write, ``"manifest"``, ``"swap"`` after an existing same-step dir is
    renamed aside, ``"publish"`` after the temp dir is renamed into
    place, ``"end"`` after the aside dir is removed.  A hook that raises
    (or kills the process) at ANY stage leaves the directory with every
    previously-published step intact — either directly or via the next
    call's :func:`_gc`."""
    root = pathlib.Path(ckpt_dir)
    root.mkdir(parents=True, exist_ok=True)
    _gc(root)
    fire = hook if hook is not None else (lambda stage: None)
    leaves, treedef = jax.tree.flatten(tree)
    tmp = pathlib.Path(
        tempfile.mkdtemp(prefix=f"{_TMP_PREFIX}{step}_", dir=root)
    )
    final = root / f"step_{step:09d}"
    old: Optional[pathlib.Path] = None
    try:
        fire("begin")
        leaf_meta = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            path_i = tmp / f"leaf_{i}.npy"
            np.save(path_i, arr)
            # per-leaf identity + on-disk byte size: restore's cheap
            # torn-write check (a truncated .npy fails BEFORE np.load)
            leaf_meta.append({
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "size": path_i.stat().st_size,
            })
            fire(f"leaf_{i}")
        (tmp / _MANIFEST).write_text(
            json.dumps(
                {
                    "step": step,
                    "tag": tag,
                    "n_leaves": len(leaves),
                    "treedef": str(treedef),
                    "leaves": leaf_meta,
                    "metadata": metadata or {},
                }
            )
        )
        fire("manifest")
        if final.exists():
            # overwrite: aside-publish-delete, never delete-then-publish
            # (a crash between rmtree and rename would lose the step).
            # The aside name is unique PER CALL, not per process: a
            # watchdog-abandoned save's zombie thread must never collide
            # with its retry on the same aside path
            old = root / (
                f"{_OLD_PREFIX}{step}_{os.getpid()}_{uuid.uuid4().hex[:8]}"
            )
            final.rename(old)
            fire("swap")
        tmp.rename(final)  # atomic publish
        fire("publish")
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
            old = None
        fire("end")
        return final
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        if old is not None:
            if final.exists():
                shutil.rmtree(old, ignore_errors=True)
            else:
                old.rename(final)  # put the published step back
        raise


def steps(ckpt_dir: str | os.PathLike) -> list[int]:
    """Published step numbers, NEWEST state of the directory — including
    steps stranded under ``.old_step_*`` asides by a crash between the
    aside-rename and the publish.  Pure read: nothing is renamed or
    deleted here (the writer's next ``save`` does that), so concurrent
    readers can never break an in-flight save."""
    root = pathlib.Path(ckpt_dir)
    if not root.exists():
        return []
    published = set()
    stranded = set()
    for p in root.iterdir():
        if not p.is_dir() or not (p / _MANIFEST).exists():
            continue
        if p.name.startswith("step_"):
            published.add(int(p.name.split("_")[1]))
        elif p.name.startswith(_OLD_PREFIX):
            stranded.add(_aside_step(p.name))
    return sorted(published | stranded)


def latest_step(ckpt_dir: str | os.PathLike) -> Optional[int]:
    found = steps(ckpt_dir)
    return found[-1] if found else None


def restore(ckpt_dir: str | os.PathLike, example_tree: Any,
            step: Optional[int] = None,
            mesh_shape: Optional[dict] = None,
            reshard: bool = False) -> tuple[Any, int, dict]:
    """Load (tree, step, metadata); ``example_tree`` supplies the treedef.

    Defaults to the latest step. Validation is per-leaf, not just a
    count: each leaf's on-disk file size is checked against the manifest
    (torn/truncated writes fail before the load) and its shape and dtype
    against the example tree (a corrupted or drifted leaf fails loudly
    instead of mis-loading silently).

    ``mesh_shape`` (e.g. ``{"dp": 2, "sp": 2}``): callers restoring
    MESH-SHARDED leaves — the ZeRO trainer's dp-sharded flat optimizer
    moments — pass the mesh they will lay the state out on; if the
    manifest metadata recorded a different ``mesh_shape`` at save time,
    restore raises a :class:`runtime.errors.CommError` BEFORE any leaf
    load (the sharded layout is part of the data's meaning, and a
    shape-coincidence mis-load would silently scramble shards).

    ``reshard=True`` is the elastic escape hatch: the mesh-shape gate is
    waived and each leaf is loaded in its SAVED layout — validated
    against the manifest's recorded shape/dtype instead of the example
    tree where the two disagree — so the caller can regroup it onto the
    live mesh explicitly (``models.zero.reshard_state`` for ZeRO
    moments; the chunk drivers re-decompose their tiles).  The treedef
    and leaf count must still match: resharding re-lays-out data, it
    does not migrate structures.
    """
    step, manifest = _read_manifest(ckpt_dir, step)
    if mesh_shape is not None and not reshard:
        saved = manifest.get("metadata", {}).get("mesh_shape")
        if saved is not None and saved != mesh_shape:
            from tpuscratch.runtime.errors import CommError

            saved_plan = manifest.get("metadata", {}).get("plan")
            raise CommError(
                "ckpt/restore",
                f"checkpoint step {step} in {ckpt_dir} holds leaves "
                f"sharded for mesh {saved}"
                + (f" (plan {saved_plan})" if saved_plan else "")
                + f", caller's mesh is {mesh_shape} — mesh-sharded "
                f"state cannot be re-laid-out implicitly; pass "
                f"reshard=True to load the saved layout and regroup it "
                f"onto the live mesh (models.zero.reshard_state for "
                f"ZeRO optimizer moments)",
            )
    path = _step_dir(pathlib.Path(ckpt_dir), step)
    leaves, treedef = jax.tree.flatten(example_tree)
    if manifest["n_leaves"] != len(leaves):
        raise ValueError(
            f"checkpoint has {manifest['n_leaves']} leaves, example tree "
            f"has {len(leaves)} — structure changed since save"
        )
    leaf_meta = manifest.get("leaves")  # absent in legacy checkpoints
    loaded = []
    for i, example in enumerate(leaves):
        f = path / f"leaf_{i}.npy"
        if leaf_meta is not None:
            size = f.stat().st_size
            if size != leaf_meta[i]["size"]:
                raise ValueError(
                    f"checkpoint leaf {i} is {size} B on disk, manifest "
                    f"recorded {leaf_meta[i]['size']} B — torn or "
                    f"corrupted write"
                )
        arr = np.load(f)
        ex_shape = tuple(np.shape(example))
        ex_dtype = np.dtype(
            getattr(example, "dtype", None) or np.asarray(example).dtype
        )
        if arr.shape != ex_shape or arr.dtype != ex_dtype:
            if reshard and leaf_meta is not None \
                    and list(arr.shape) == leaf_meta[i]["shape"] \
                    and str(arr.dtype) == leaf_meta[i]["dtype"]:
                # the saved layout, intact per the manifest: hand it to
                # the caller's explicit regroup
                loaded.append(arr)
                continue
            raise ValueError(
                f"checkpoint leaf {i} has shape {arr.shape} dtype "
                f"{arr.dtype}; example tree expects {ex_shape} "
                f"{ex_dtype} — structure drifted since save"
            )
        loaded.append(arr)
    return jax.tree.unflatten(treedef, loaded), step, manifest["metadata"]


def _read_manifest(ckpt_dir: str | os.PathLike, step: Optional[int]) -> tuple[int, dict]:
    """Resolve ``step`` (default: latest) and load its manifest."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = _step_dir(pathlib.Path(ckpt_dir), step)
    return step, json.loads((path / _MANIFEST).read_text())


def peek_metadata(ckpt_dir: str | os.PathLike, step: Optional[int] = None) -> tuple[int, dict]:
    """(step, metadata) without loading any leaf arrays — the cheap
    pre-restore compatibility check (manifest.json only)."""
    step, manifest = _read_manifest(ckpt_dir, step)
    return step, manifest["metadata"]


def prune(ckpt_dir: str | os.PathLike, keep: int = 3) -> None:
    """Delete all but the newest ``keep`` checkpoints."""
    for s in steps(ckpt_dir)[:-keep] if keep > 0 else steps(ckpt_dir):
        shutil.rmtree(pathlib.Path(ckpt_dir) / f"step_{s:09d}", ignore_errors=True)
