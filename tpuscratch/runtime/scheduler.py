"""The mesh co-scheduler: N chunked programs time-slicing ONE slice.

The reference repo's L0 layer is PBS/SLURM job scripts — a scheduler
over SPMD programs one level above the runtime (PAPER capability 9:
every binary ships with its batch submission).  Its TPU-native
reproduction cannot be shell scripts: the unit of preemption here is
the CHUNK boundary of a ``runtime.chunked.ChunkedProgram`` — the state
was just published (or handed to the async writer, whose barrier the
program drains at its own exit per the PR-11 contract), so switching
workloads there is exactly as safe as a SLURM walltime kill landing
between checkpoints, minus the kill.

:class:`MeshScheduler` holds N programs and, each iteration, asks a
:class:`Policy` which one ticks next.  All programs target the SAME
mesh — JAX dispatches their compiled chunks serially from the host
thread, so interleaving ticks IS time-slicing the slice; no program
needs to know.  Context switches emit ``sched/switch`` events, the
run summary ``sched/run``; both feed ``obs.goodput.by_workload``, which
partitions the one JSONL stream into per-workload goodput reports whose
walls sum to the scheduler's wall exactly (the MegaScale accounting
discipline applied ACROSS jobs instead of within one).

Policies (pluggable — ``pick(ready, current, run_len)``):

- :class:`RoundRobin`: equal quantum (in ticks) per workload.
- :class:`Priority`: strict priority classes, round-robin within the
  top class — a serving-burst job added mid-run with higher priority
  PREEMPTS background training at the next chunk boundary.
- :class:`GoodputShare`: deficit scheduling toward busy-second share
  targets — pick the workload furthest below its target share.

Failure handling is the supervisor's restart discipline, per entry: a
``RESTARTABLE`` failure aborts the program (its flight data files, the
async writer is abandoned-with-log), backs off, and re-invokes the
program's ``remake`` factory — which resumes from ``ckpt_dir`` and
replays bit-identically while the OTHER workloads keep ticking.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from tpuscratch.ft.supervisor import RESTARTABLE, RestartBudget, \
    RestartsExhausted
from tpuscratch.obs.sink import NullSink
from tpuscratch.runtime.chunked import ChunkedProgram

__all__ = ["GoodputShare", "MeshScheduler", "Priority", "RoundRobin"]


class _Entry:
    """One scheduled workload: the live program + its arbitration and
    accounting state."""

    def __init__(self, name, program, remake, priority, share, budget,
                 order):
        self.name = name
        self.program = program
        self.remake = remake
        self.priority = priority
        self.share = share
        self.budget = budget
        self.order = order       # insertion order: the deterministic tie-break
        self.busy_s = 0.0        # scheduler wall spent ticking this workload
        self.ticks = 0
        self.restarts = 0
        self.last_pick = -1      # iteration this entry last ran
        self.finished = False


class RoundRobin:
    """Equal time: rotate through the ready workloads, ``quantum``
    consecutive ticks each."""

    def __init__(self, quantum: int = 1):
        if quantum < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum}")
        self.quantum = quantum

    def pick(self, ready: list, current: Optional[str], run_len: int) -> str:
        names = [e.name for e in ready]
        if current in names and run_len < self.quantum:
            return current
        # least-recently-run first; insertion order breaks the tie
        return min(ready, key=lambda e: (e.last_pick, e.order)).name


class Priority:
    """Strict priority classes (higher ``priority`` wins), round-robin
    within the top class.  A higher-priority arrival preempts the
    current workload at its next chunk boundary — the serving-burst
    -over-background-training policy."""

    def __init__(self, quantum: int = 1):
        self._rr = RoundRobin(quantum)

    def pick(self, ready: list, current: Optional[str], run_len: int) -> str:
        top = max(e.priority for e in ready)
        top_ready = [e for e in ready if e.priority == top]
        cur = current if current in [e.name for e in top_ready] else None
        return self._rr.pick(top_ready, cur, run_len if cur else 0)


class GoodputShare:
    """Deficit scheduling toward busy-share targets: each pick goes to
    the ready workload FURTHEST below its normalized target share of
    the busy seconds so far.  ``targets`` maps workload name to weight
    (missing names fall back to the entry's ``share``, else 1.0);
    weights are normalized over the READY set, so a finished workload's
    share is redistributed."""

    def __init__(self, targets: Optional[dict] = None):
        self.targets = dict(targets) if targets else {}

    def _weight(self, entry) -> float:
        w = self.targets.get(entry.name)
        if w is None:
            w = entry.share if entry.share is not None else 1.0
        return max(float(w), 0.0)

    def pick(self, ready: list, current: Optional[str], run_len: int) -> str:
        total_w = sum(self._weight(e) for e in ready) or float(len(ready))
        busy = sum(e.busy_s for e in ready)

        def deficit(e):
            target = self._weight(e) / total_w
            have = (e.busy_s / busy) if busy > 0 else 0.0
            return target - have

        # max deficit wins; least-recently-run then insertion order
        # break the tie deterministically
        return max(ready, key=lambda e: (deficit(e), -e.last_pick,
                                         -e.order)).name


class MeshScheduler:
    """Co-schedule N :class:`ChunkedProgram`\\ s on one mesh.

    ``policy`` defaults to :class:`RoundRobin`.  ``sink`` receives the
    ``sched/switch``/``sched/finish``/``sched/run`` stream (untagged —
    scheduler events belong to no workload; each program keeps writing
    its OWN workload-tagged events through its own sink, normally the
    same underlying JSONL file).  ``on_tick(scheduler)`` runs after
    every tick — the mid-run arrival hook (``add`` a burst job from it).

    ``run()`` returns ``{name: result}`` of every program's
    ``finish()``.  A restartable failure in one workload restarts THAT
    workload (per-entry ``RestartBudget``) while the others keep
    ticking; past its budget, the scheduler aborts the remaining
    programs (flight data files) and raises ``RestartsExhausted``.
    """

    def __init__(self, *, policy=None, sink=None, recorder=None,
                 restartable: tuple = RESTARTABLE,
                 log: Callable[[str], None] = lambda s: None,
                 sleep: Callable[[float], None] = time.sleep,
                 on_tick: Optional[Callable[["MeshScheduler"], None]] = None):
        self.policy = policy if policy is not None else RoundRobin()
        self.sink = sink if sink is not None else NullSink()
        self.rec = recorder
        self.restartable = restartable
        self.log = log
        self.sleep = sleep
        self.on_tick = on_tick
        self.entries: dict[str, _Entry] = {}
        self.ticks = 0
        self.switches = 0
        self.current: Optional[str] = None
        self.results: dict = {}
        self._run_len = 0

    def add(self, program_or_factory, *, name: Optional[str] = None,
            priority: int = 0, share: Optional[float] = None,
            restarts: Optional[RestartBudget] = None) -> str:
        """Register a workload (mid-run arrivals welcome — the policy
        sees it at the next boundary).  ``program_or_factory`` is a
        built :class:`ChunkedProgram` or a zero-arg factory; ``name``
        defaults to the program's ``workload`` and must be unique.
        ``restarts=None`` disables per-entry restarts (a failure
        propagates)."""
        if callable(program_or_factory) and not isinstance(
                program_or_factory, ChunkedProgram):
            remake = program_or_factory
            program = remake()
        else:
            program = program_or_factory
            remake = program.remake
        name = name if name is not None else program.workload
        if name in self.entries:
            raise ValueError(f"duplicate workload {name!r}")
        self.entries[name] = _Entry(name, program, remake, priority, share,
                                    restarts, len(self.entries))
        return name

    # ---- the arbitration loop -------------------------------------------

    def _ready(self) -> list:
        return [e for e in self.entries.values() if not e.finished]

    def _restart_or_raise(self, entry: _Entry, exc: BaseException) -> None:
        entry.program.abort()
        retryable = (entry.budget is not None
                     and isinstance(exc, self.restartable)
                     and entry.remake is not None)
        if retryable and entry.restarts >= entry.budget.max_restarts:
            entry.program.sink.emit(
                "ft/give_up", restarts=entry.restarts,
                error=f"{type(exc).__name__}: {exc}")
            self._abort_others(entry.name)
            raise RestartsExhausted(
                f"{entry.name}: restart budget "
                f"{entry.budget.max_restarts} exhausted") from exc
        if not retryable:
            self._abort_others(entry.name)
            raise exc
        entry.restarts += 1
        op = getattr(exc, "op", None) or getattr(exc, "site", None)
        self.log(f"sched restart {entry.name} "
                 f"{entry.restarts}/{entry.budget.max_restarts}: "
                 f"{type(exc).__name__}: {exc}")
        d = entry.budget.delay(entry.restarts)
        if d > 0:
            self.sleep(d)
        # AFTER the backoff — duration-carrying events are end-stamped
        # (the goodput convention), so [t - backoff_s, t] is the slept
        # window, booked to THIS workload by its tagged sink
        entry.program.sink.emit(
            "ft/restart", restart=entry.restarts,
            error=f"{type(exc).__name__}: {exc}", backoff_s=round(d, 6),
            **({"op": op} if op else {}),
        )
        entry.program = entry.remake()

    def _abort_others(self, failed: str) -> None:
        for other in self.entries.values():
            if other.name != failed and other.program.started \
                    and not other.program.finished:
                other.program.abort()

    def run(self) -> dict:
        """Arbitrate until every workload finished; return their
        results by name."""
        t0 = time.perf_counter()
        try:
            while self.tick() is not None:
                pass
        except BaseException:
            self._emit_run(t0, failed=True)
            raise
        self._emit_run(t0)
        self.sink.flush()
        return self.results

    def tick(self) -> Optional[str]:
        """One arbitration step (the non-blocking form — compose the
        scheduler itself under an outer loop).  Returns the workload
        ticked, or ``None`` when all are finished.  A restartable
        failure restarts that entry in place (backoff slept here)."""
        ready = self._ready()
        if not ready:
            return None
        name = self.policy.pick(ready, self.current, self._run_len)
        entry = self.entries[name]
        if name != self.current:
            if self.current is not None:
                self.switches += 1
            self.sink.emit("sched/switch", workload=name,
                           prev=self.current, tick=self.ticks)
            self.current = name
            self._run_len = 0
        tick_t0 = time.perf_counter()
        try:
            entry.program.ensure_started()
            if not entry.program.done:
                entry.program.tick()
            if entry.program.done:
                self.results[name] = entry.program.finish()
                entry.finished = True
        except BaseException as exc:  # noqa: BLE001 — dispatched below
            entry.busy_s += time.perf_counter() - tick_t0
            entry.ticks += 1
            entry.last_pick = self.ticks
            self.ticks += 1
            self._run_len += 1
            self._restart_or_raise(entry, exc)
            return name
        entry.busy_s += time.perf_counter() - tick_t0
        entry.ticks += 1
        entry.last_pick = self.ticks
        self.ticks += 1
        self._run_len += 1
        if entry.finished:
            self.sink.emit("sched/finish", workload=name,
                           ticks=entry.ticks, busy_s=round(entry.busy_s, 6))
        if self.on_tick is not None:
            self.on_tick(self)
        return name

    def _emit_run(self, t0: float, failed: bool = False) -> None:
        wall = time.perf_counter() - t0
        busy = sum(e.busy_s for e in self.entries.values())
        fields = {
            "wall_s": round(wall, 6), "ticks": self.ticks,
            "switches": self.switches,
            "workloads": len(self.entries),
            "overhead_s": round(max(wall - busy, 0.0), 6),
            "policy": type(self.policy).__name__,
        }
        targets = self._targets()
        if targets:
            fields["targets"] = targets
        if failed:
            fields["error"] = True
        self.sink.emit("sched/run", **fields)

    def _targets(self) -> dict:
        """The policy's share targets (for the goodput arbitration
        table): GoodputShare's weights, else any per-entry shares."""
        if isinstance(self.policy, GoodputShare):
            out = {}
            for e in self.entries.values():
                out[e.name] = self.policy._weight(e)
            total = sum(out.values())
            return ({k: v / total for k, v in out.items()} if total > 0
                    else {})
        shares = {e.name: e.share for e in self.entries.values()
                  if e.share is not None}
        total = sum(shares.values())
        return ({k: v / total for k, v in shares.items()} if total > 0
                else {})
