"""The chunked-program runtime: ONE chunk loop for every workload.

The trainer (``models.trainer``), the halo driver (``halo.driver``) and
the solver runner (``solvers.runner``) each grew the same loop by hand:
dispatch a compiled chunk inside a flight-recorder span, emit a
``<workload>/chunk`` event, checkpoint the state at the boundary
(blocking ``ckpt/save`` under ``ft.retry``, or the PR-11
snapshot-then-publish split via ``runtime.async_ckpt``), and give chaos
its two boundary hooks — a transient fault before the chunk and a
simulated preemption after the save.  Three copies of that wiring is
how drift happens (PR 11 added ``async_ckpt=`` three times); this
module is the one implementation, and the three drivers are thin
adapters over it (a guard test asserts they stay that way).

A :class:`ChunkedProgram` is the loop REIFIED: instead of a function
that runs to completion, it is an object that advances one chunk per
``tick()`` — which is exactly what a co-scheduler needs.  Every tick
boundary is a clean preemption point (the state was just published, or
handed to the async writer whose barrier the program drains at its own
exit), so ``runtime.scheduler.MeshScheduler`` can interleave ticks of
N programs on one mesh without any of them knowing.  ``run()`` is the
classic blocking form: start, tick until done, finish.

The adapter contract (what the three drivers plug in):

- ``run_chunk(cp, pos)``: dispatch the compiled chunk and FENCE it
  (``block_until_ready``); return an opaque payload.  The runtime
  brackets the call in a ``{prefix}/chunk`` span.
- ``make_event(cp, pos, payload, span) -> ChunkResult``: fold the
  payload into adapter state and produce the chunk event fields plus
  the new position.  A ``rollback=True`` result (the trainer's guard
  ladder) skips the event/save/preempt tail and resumes from the
  returned position.
- ``snapshot(cp, pos) -> (tree, metadata)``: the state to publish at
  ``pos``.  Async path: staged device→host inside the ``ckpt/snapshot``
  span by the :class:`~tpuscratch.runtime.async_ckpt.AsyncCheckpointer`.
  Blocking path: materialized to numpy, saved under ``ft.retry`` inside
  the ``ckpt/save`` span, pruned to ``keep``.
- ``epilogue(cp)``: the driver's run summary (its ``*/run`` event,
  phase totals, result value) — runs after the contexts closed, so the
  async barrier has drained.

Every event a program emits is stamped ``workload=<name>`` by
:class:`WorkloadSink` — the tag ``obs.goodput.by_workload`` partitions
one co-scheduled JSONL stream on.
"""

from __future__ import annotations

import contextlib
import dataclasses
import sys
from typing import Any, Callable, Optional

import jax
import numpy as np

from tpuscratch.ft.chaos import bind_sink
from tpuscratch.ft.retry import RetryPolicy, retry
from tpuscratch.obs.sink import NullSink
from tpuscratch.obs.trace import FlightRecorder, file_flight_data
from tpuscratch.runtime import checkpoint

__all__ = ["ChunkResult", "ChunkedProgram", "WorkloadSink"]


class WorkloadSink:
    """A tagging proxy over an ``obs.sink``: every event gains a
    ``workload=<name>`` field, so N programs sharing one JSONL stream
    stay separable (``obs.goodput.by_workload`` splits on the tag).
    Everything else — thread-safety, buffering, ``enabled`` — is the
    wrapped sink's; a wrapped ``NullSink`` still costs a no-op."""

    def __init__(self, inner, workload: str):
        while isinstance(inner, WorkloadSink):  # never stack tags
            inner = inner.inner
        self.inner = inner
        self.workload = workload

    @property
    def enabled(self) -> bool:
        return self.inner.enabled

    def emit(self, event: str, **fields) -> None:
        fields.setdefault("workload", self.workload)
        self.inner.emit(event, **fields)

    def emit_metrics(self, snapshot: dict, event: str = "metrics",
                     scope=None) -> None:
        self.inner.emit_metrics(snapshot, event=event, scope=scope)

    def flush(self) -> None:
        self.inner.flush()

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name):  # path, host, ...
        return getattr(self.inner, name)


@dataclasses.dataclass
class ChunkResult:
    """What one chunk did: the new position, the ``{prefix}/chunk``
    event fields (``None``: emit nothing), whether to checkpoint, and
    the two early exits — ``rollback`` (discard the chunk, resume from
    ``pos``; the trainer's guard ladder) and ``stop`` (converged)."""

    pos: int
    event: Optional[dict] = None
    save: bool = True
    rollback: bool = False
    stop: bool = False


class ChunkedProgram:
    """A checkpointed chunk loop as a steppable object.

    ``workload`` names the program (the event tag and the scheduler
    key); ``prefix`` is the event namespace (``{prefix}/chunk`` spans
    and events — defaults to ``workload``, kept separate so two train
    jobs can share the ``train/chunk`` event kind under distinct tags).
    ``total`` is the terminal position; ``pos`` the (resumed) start.

    Checkpointing: ``ckpt_dir=None`` or ``snapshot=None`` disables it
    (an ephemeral burst job).  ``async_ckpt=True`` builds an
    :class:`AsyncCheckpointer` (``write_retry`` is its writer policy);
    otherwise blocking saves run under ``save_retry`` when set.

    Chaos: ``fail_site`` fires ``maybe_fail`` before each chunk (the
    halo/solver ``comm/*`` sites), ``preempt_site`` fires
    ``maybe_preempt`` after the save; the plan is bound to the tagged
    sink so injected-fault events carry the workload tag.

    ``remake`` is the restart factory: a zero-arg callable returning a
    FRESH program resumed from ``ckpt_dir`` — what
    ``ft.supervisor.supervise_program`` and the scheduler's per-entry
    restart path re-invoke after a ``Preempted``/``CommError``.
    """

    def __init__(
        self,
        *,
        workload: str,
        total: int,
        run_chunk: Callable[["ChunkedProgram", int], Any],
        make_event: Callable[["ChunkedProgram", int, Any, Any], ChunkResult],
        prefix: Optional[str] = None,
        pos: int = 0,
        snapshot: Optional[Callable[["ChunkedProgram", int], tuple]] = None,
        epilogue: Optional[Callable[["ChunkedProgram"], Any]] = None,
        span_args: Optional[Callable[[int], dict]] = None,
        save_span_args: Optional[Callable[[int], dict]] = None,
        on_saved: Optional[Callable[["ChunkedProgram", int], None]] = None,
        post_boundary: Optional[Callable[["ChunkedProgram", int], bool]] = None,
        fail_site: Optional[str] = None,
        fail_op: Optional[str] = None,
        preempt_site: Optional[str] = None,
        ckpt_dir: Optional[str] = None,
        keep: int = 3,
        save_retry: Optional[RetryPolicy] = None,
        write_retry: Optional[RetryPolicy] = None,
        async_ckpt: bool = False,
        sink=None,
        recorder: Optional[FlightRecorder] = None,
        metrics=None,
        chaos=None,
        log: Callable[[str], None] = lambda s: None,
        remake: Optional[Callable[[], "ChunkedProgram"]] = None,
    ):
        self.workload = workload
        self.prefix = prefix if prefix is not None else workload
        self.total = total
        self.pos = pos
        self.sink = (sink if isinstance(sink, WorkloadSink)
                     else WorkloadSink(sink if sink is not None else NullSink(),
                                       workload))
        self.rec = recorder if recorder is not None else FlightRecorder()
        self.metrics = metrics
        self.ckpt_dir = ckpt_dir
        self.keep = keep
        self.remake = remake
        self.result: Any = None
        self.finished = False
        self._stopped = False
        self._stack: Optional[contextlib.ExitStack] = None
        self._run_chunk = run_chunk
        self._make_event = make_event
        self._snapshot = snapshot
        self._epilogue = epilogue
        self._span_args = span_args
        self._save_span_args = save_span_args
        self._on_saved = on_saved
        self._post_boundary = post_boundary
        self._fail_site = fail_site
        self._fail_op = fail_op
        self._preempt_site = preempt_site
        self._save_retry = save_retry
        self._chaos = chaos
        self._log = log
        self._save_hook = chaos.save_hook() if chaos is not None else None
        if chaos is not None:
            # injected-fault events land in the run's own (tagged) stream
            bind_sink(chaos, self.sink)
        self.ckp = None
        if async_ckpt and snapshot is not None and ckpt_dir is not None:
            from tpuscratch.runtime.async_ckpt import AsyncCheckpointer

            self.ckp = AsyncCheckpointer(retry=write_retry, chaos=chaos,
                                         sink=self.sink, metrics=metrics,
                                         log=log)

    # ---- lifecycle ------------------------------------------------------

    @property
    def started(self) -> bool:
        return self._stack is not None

    @property
    def done(self) -> bool:
        """No more chunks to run (``finish()`` may still be owed)."""
        return self.finished or self._stopped or self.pos >= self.total

    def start(self) -> None:
        """Enter the run contexts: flight-data filing (a failed run
        still files its spans, phase totals and event tail) around the
        async-checkpoint barrier (drain on clean exit, abandon-with-log
        while unwinding) — the nesting all three legacy loops used."""
        if self._stack is not None:
            raise RuntimeError(f"{self.workload}: already started")
        if self.finished:
            raise RuntimeError(f"{self.workload}: already finished")
        self._stack = contextlib.ExitStack()
        self._stack.enter_context(file_flight_data(self.sink, self.rec))
        if self.ckp is not None:
            self._stack.enter_context(self.ckp)

    def ensure_started(self) -> None:
        if self._stack is None and not self.finished:
            self.start()

    def finish(self):
        """Close the contexts (the async barrier drains here — a write
        failure surfaces before the epilogue claims success), then run
        the adapter epilogue and return its result."""
        if self.finished:
            return self.result
        if self._stack is not None:
            stack, self._stack = self._stack, None
            stack.close()
        self.finished = True
        if self._epilogue is not None:
            self.result = self._epilogue(self)
        return self.result

    def abort(self) -> None:
        """Unwind the contexts under the in-flight exception
        (``sys.exc_info()``): flight data is filed, the async writer is
        abandoned-with-log.  The scheduler/supervisor call this before
        re-invoking ``remake``."""
        stack, self._stack = self._stack, None
        if stack is not None:
            stack.__exit__(*sys.exc_info())

    def drain(self) -> None:
        """Barrier on the in-flight async write (no-op when blocking) —
        the adapter rollback path's "what is the last COMMITTED step"
        precondition."""
        if self.ckp is not None:
            self.ckp.drain()

    # ---- the one chunk loop ---------------------------------------------

    def tick(self) -> ChunkResult:
        """Advance one chunk: chaos fail site → ``{prefix}/chunk`` span
        around the fenced dispatch → chunk event → checkpoint →
        ``preempt_site`` → stop rule.  Exactly the legacy loop body; a
        raised ``Preempted``/``CommError`` leaves the program abortable
        and re-makeable."""
        if self.done:
            raise RuntimeError(f"{self.workload}: tick() past the end")
        self.ensure_started()
        pos = self.pos
        if self._chaos is not None and self._fail_site is not None:
            self._chaos.maybe_fail(self._fail_site, index=pos,
                                   op=self._fail_op)
        args = (self._span_args(pos) if self._span_args is not None
                else {"step_begin": pos})
        sp = self.rec.open_span(f"{self.prefix}/chunk", **args)
        payload = self._run_chunk(self, pos)
        self.rec.close_span(sp)
        res = self._make_event(self, pos, payload, sp)
        self.pos = res.pos
        if res.rollback:
            return res
        if res.event is not None:
            self.sink.emit(f"{self.prefix}/chunk", **res.event)
        if res.save and self._snapshot is not None and self.ckpt_dir is not None:
            self._save(res.pos)
        if self._on_saved is not None:
            self._on_saved(self, res.pos)
        if self._chaos is not None and self._preempt_site is not None:
            # AFTER the save: the restarted program resumes exactly
            # here.  No async drain — the checkpointer's context exit
            # completes a carried write before any re-invocation
            self._chaos.maybe_preempt(self._preempt_site, index=res.pos)
        if res.stop or (self._post_boundary is not None
                        and self._post_boundary(self, res.pos)):
            self._stopped = True
        return res

    def _save(self, pos: int) -> None:
        sargs = (self._save_span_args(pos) if self._save_span_args is not None
                 else {"step": pos})
        if self.ckp is not None:
            # async: pay only the device→pinned-host copy here; the
            # serialize+publish runs on the background writer (its
            # ckpt/write event is stamped when it truly finishes)
            sp = self.rec.open_span("ckpt/snapshot", **sargs)
            tree, meta = self._snapshot(self, pos)
            self.ckp.snapshot(self.ckpt_dir, pos, tree, metadata=meta,
                              keep=self.keep)
            self.rec.close_span(sp)
            self.sink.emit("ckpt/snapshot", step=pos,
                           wall_s=round(sp.seconds, 6))
        else:
            tree, meta = self._snapshot(self, pos)
            snap = jax.tree.map(np.asarray, tree)

            def do_save(at=pos, snap=snap, meta=meta):
                return checkpoint.save(self.ckpt_dir, at, snap,
                                       metadata=meta, hook=self._save_hook)

            sp = self.rec.open_span("ckpt/save", **sargs)
            if self._save_retry is not None:
                retry(do_save, self._save_retry, op="ckpt/save",
                      log=self._log)
            else:
                do_save()
            checkpoint.prune(self.ckpt_dir, self.keep)
            self.rec.close_span(sp)
            self.sink.emit("ckpt/save", step=pos,
                           wall_s=round(sp.seconds, 6))

    def run(self):
        """The blocking form the three legacy entry points keep: start,
        tick to completion, finish.  A failure aborts (files flight
        data) and re-raises — the supervisor's restart surface."""
        self.ensure_started()
        try:
            while not self.done:
                self.tick()
        except BaseException:
            self.abort()
            raise
        return self.finish()

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        state = ("finished" if self.finished
                 else "running" if self.started else "pending")
        return (f"ChunkedProgram({self.workload!r}, pos={self.pos}/"
                f"{self.total}, {state})")
