"""Tracing / profiling: spans, cross-rank wall-time, XLA profiler hooks.

The reference's instrumentation is manual wall-clock spans — clock()
begin/end gathered to rank 0 with the max-min convention
(mpicuda3.cu:176-179,315-325), MPI_Wtime segment timing separating network
from copy (mpi-pingpong-gpu.cpp:51-57), and a carve-out for one-time setup
cost (NO_GPU_MALLOC_TIME, mpicuda3.cu:221-240). This module keeps those
conventions and adds what the XLA runtime offers beyond them:

- ``span``: a named, nestable wall-clock bracket with correct async
  semantics (``block_until_ready`` on entry values it is asked to close
  over) — the MPI_Wtime idiom without the async-dispatch footgun.
- ``Timeline``: collects spans; ``cross_rank_span`` merges per-process
  timelines with max(end)-min(begin).
- ``trace``: context manager around ``jax.profiler`` emitting a
  TensorBoard-readable XLA trace (device timelines, fusion names) — the
  part clock() could never see.

The merge conventions themselves now live in ``tpuscratch.obs.metrics``
(the observability subsystem): ``cross_rank_span`` delegates to its
``span_max_min``, and ``obs.metrics.mesh_span`` is the device-side
variant that runs the max/min through the mesh collectives.
"""

from __future__ import annotations

import contextlib
import dataclasses
import warnings
from typing import Iterator, Optional

import jax

from tpuscratch.obs.metrics import span_max_min
from tpuscratch.obs.trace import FlightRecorder


@dataclasses.dataclass(frozen=True)
class Span:
    name: str
    begin: float
    end: float

    @property
    def seconds(self) -> float:
        return self.end - self.begin


class Timeline:
    """Per-process span collector (one per rank; merge via
    cross_rank_span).  Since the flight recorder landed there is ONE
    span implementation — ``obs.trace.FlightRecorder``'s sync-fencing
    bracket — and Timeline is a thin delegate over it: every span ALSO
    lands in ``self.recorder``'s ring (pass a shared recorder to pool
    several layers' spans into one Chrome trace), while ``self.spans``
    keeps the legacy per-collector list the merge helpers read."""

    def __init__(self, recorder: Optional[FlightRecorder] = None) -> None:
        self.recorder = recorder if recorder is not None else FlightRecorder()
        self.spans: list[Span] = []

    @contextlib.contextmanager
    def span(self, name: str, *sync) -> Iterator[None]:
        """Wall-clock bracket. Any ``sync`` arrays are blocked on at
        entry so async dispatch cannot leak pending work into the span.
        Delegates to the recorder's open/close — the one bracket
        implementation — and mirrors the result into ``self.spans``
        (on the exception path too, matching the recorder's ring)."""
        ev = self.recorder.open_span(name, sync=sync)
        try:
            yield
        finally:
            self.recorder.close_span(ev)
            self.spans.append(Span(name, ev.begin, ev.end))

    def seconds(self, name: str) -> float:
        """Total time across spans with this name."""
        total = sum(s.seconds for s in self.spans if s.name == name)
        if not any(s.name == name for s in self.spans):
            raise KeyError(name)
        return total

    def report(self) -> str:
        lines = [f"{s.name}: {s.seconds * 1e3:.3f} ms" for s in self.spans]
        return "\n".join(lines)


def cross_rank_span(timelines: list[Timeline], name: str) -> float:
    """max(end) - min(begin) for ``name`` across per-rank timelines — the
    mpicuda3 convention as a pure function over collected spans."""
    begins, ends = [], []
    for tl in timelines:
        for s in tl.spans:
            if s.name == name:
                begins.append(s.begin)
                ends.append(s.end)
    return span_max_min(begins, ends)


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """XLA profiler trace (TensorBoard format) around a block of work.

    When trace support is unavailable on this jax — the API absent
    (``compat.profiler_trace_supported``) or ``start_trace`` itself
    failing at runtime, as on images whose jax 0.4.37 ships without a
    working profiler backend — the bracket degrades to a no-op span with
    a logged warning instead of killing the instrumented run: profiling
    must never be the thing that takes serving down."""
    from tpuscratch.runtime import compat

    if not compat.profiler_trace_supported():
        warnings.warn(
            "jax.profiler trace support unavailable on this jax; "
            "runtime.profiling.trace degraded to a no-op span",
            RuntimeWarning, stacklevel=3,
        )
        yield
        return
    try:
        jax.profiler.start_trace(logdir, create_perfetto_link=False)
    except Exception as e:
        warnings.warn(
            f"jax.profiler.start_trace failed ({e}); trace degraded to "
            "a no-op span",
            RuntimeWarning, stacklevel=3,
        )
        yield
        return
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region visible in profiler timelines (TraceAnnotation; a
    no-op context on jax builds without it — including builds with no
    ``jax.profiler`` module at all, where compat's attribute fallback
    has nothing to hang off)."""
    prof = getattr(jax, "profiler", None)
    if prof is None or not hasattr(prof, "TraceAnnotation"):
        return contextlib.nullcontext()
    return prof.TraceAnnotation(name)
