"""Asynchronous checkpointing: device→pinned-host snapshot, background
publish.

The blocking save path (``runtime.checkpoint.save`` called inline by
the trainer, the halo driver, and the solver runner) holds the step
loop for the FULL serialize+publish wall — the largest measurable
goodput sink the chaos accounting surfaces (``obs.goodput``'s
``checkpoint`` bucket; the MegaScale NSDI'24 observation that recovery
and checkpoint COST, not failure count, set effective throughput).
This module splits that wall in two:

- **snapshot** (blocking, cheap): every leaf is copied device→host into
  a pooled pinned buffer from ``native.hostpool`` — the PAPER L2
  ``host_allocator`` lineage (mpi-pingpong-gpu-async.cpp's staging
  role), until now only backing benches.  Control returns to the step
  loop as soon as the copy lands; the snapshot is immutable host memory,
  so later steps may donate/overwrite the device buffers freely.
- **write** (background): one daemon thread serializes the host
  snapshot through the UNCHANGED crash-consistent aside-rename protocol
  in ``runtime.checkpoint.save`` (so published checkpoints are
  byte-identical to the blocking path's), under ``ft.retry`` with the
  per-attempt stall watchdog, then prunes.

Concurrency contract: **at most one write in flight**.  ``snapshot``
drains the previous write before staging the next (a slow disk degrades
toward the blocking path instead of queueing unbounded pinned memory);
the chunk runtimes drain at supervisor preemption points and at exit,
so a ``Preempted`` run hands its successor a fully-published directory.
A writer failure (post-retry) is re-raised at the next barrier — the
step loop's normal failure surface, where the supervisor's restart
class catches it.

Telemetry: the runtimes emit the blocking half as ``ckpt/snapshot``
(they own the span); the writer emits ``ckpt/write`` from its own
thread at completion (the goodput end-stamp convention — ``Sink`` is
thread-safe), so ``obs.goodput`` books the residual blocking cost and
the overlapped write separately and the badput buckets still sum to
wall exactly.  Chaos sites: ``ckpt/snapshot`` (fail/stall/SIGKILL
before the copy) and ``ckpt/write`` (the full named-stage matrix inside
the background save) — see ``ft.chaos``.

When the native pool is unavailable (no ``libtpuscratch_native.so``)
the stage degrades to plain copied numpy buffers: the overlap is kept,
only the page-locking is lost.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np

from tpuscratch.ft.retry import RetryPolicy, retry
from tpuscratch.runtime import checkpoint

__all__ = ["DEFAULT_WRITE_RETRY", "AsyncCheckpointer"]

#: the background writer's policy: absorb transient IO faults fast (the
#: DEFAULT_SAVE_RETRY curve) and abandon a stalled attempt via the
#: thread watchdog — a hung filesystem must surface at the next barrier
#: as a retryable failure, never wedge the drain
DEFAULT_WRITE_RETRY = RetryPolicy(max_attempts=3, base_s=0.01, max_s=0.1,
                                  attempt_timeout_s=60.0)


class AsyncCheckpointer:
    """Snapshot-then-publish checkpointing with one background writer.

    ``pool``: a ``native.hostpool.HostPool`` for the pinned staging
    buffers (default: the process-wide ``default_pool()`` when the
    native library is available, else plain numpy copies).  ``retry``:
    the writer's ``ft.RetryPolicy`` (default
    :data:`DEFAULT_WRITE_RETRY`).  ``chaos``: an ``ft.ChaosPlan`` —
    plugs the ``ckpt/snapshot`` / ``ckpt/write`` injection sites in.
    ``sink``: receives one ``ckpt/write`` event per completed
    background write (emitted from the writer thread at its true end
    stamp).  ``metrics``: a ``MetricsRegistry`` — each snapshot updates
    the ``hostpool/*`` gauges from ``HostPool.stats()`` plus
    ``ckpt/snapshot_bytes``/``ckpt/async_writes``, so the staging
    footprint is observable.
    """

    def __init__(self, *, pool=None, retry: Optional[RetryPolicy] = None,
                 chaos=None, sink=None, metrics=None,
                 log: Callable[[str], None] = lambda s: None):
        if pool is None:
            try:
                from tpuscratch.native import hostpool

                if hostpool.available():
                    pool = hostpool.default_pool()
            except Exception:
                pool = None
        self._pool = pool
        self._retry = retry if retry is not None else DEFAULT_WRITE_RETRY
        self._chaos = chaos
        self._sink = sink
        self._metrics = metrics
        self._log = log
        # ONE persistent daemon writer + a one-slot handoff (per-save
        # thread spawn would cost ~1 ms under load — more than a small
        # state's entire blocking save)
        self._jobs: Optional[queue.SimpleQueue] = None
        self._worker: Optional[threading.Thread] = None
        self._done = threading.Event()
        self._done.set()
        self._error: Optional[BaseException] = None
        self.writes = 0          # completed background writes
        self.snapshot_bytes = 0  # bytes staged by the LAST snapshot

    def _worker_loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            try:
                job()
            finally:
                self._done.set()

    def _submit(self, job: Callable[[], None]) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._jobs = queue.SimpleQueue()
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True, name="ckpt-writer"
            )
            self._worker.start()
        self._done.clear()
        self._jobs.put(job)

    # ---- the barrier ---------------------------------------------------

    def in_flight(self) -> bool:
        return not self._done.is_set()

    def drain(self) -> None:
        """Block until the in-flight write (if any) finishes; re-raise
        its failure here — the caller's thread is the step loop, whose
        failure surface the supervisor already owns."""
        self._done.wait()
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def close(self) -> None:
        """Drain, then retire the worker thread."""
        try:
            self.drain()
        finally:
            if self._worker is not None and self._worker.is_alive():
                self._jobs.put(None)
            self._worker = None

    def abandon(self) -> None:
        """Close, but SWALLOW a write failure (logged) — the exit path
        of a loop already unwinding on a primary exception, which a
        secondary writer error must not mask."""
        try:
            self.close()
        except BaseException as exc:  # noqa: BLE001 — logged, not lost
            self._log(f"ckpt/write failed during unwind: "
                      f"{type(exc).__name__}: {exc}")

    # ---- snapshot + background publish ---------------------------------

    def _stage(self, leaf):
        """One leaf device→host: a pooled pinned buffer when available
        (zero-size and pool-exhausted leaves fall back to a plain
        copy).  Returns (host_array, buffer_or_None)."""
        arr = np.asarray(leaf)
        if self._pool is not None and arr.nbytes > 0:
            try:
                buf = self._pool.alloc(arr.nbytes)
            except MemoryError:
                buf = None
            if buf is not None:
                view = buf.view(arr.dtype, arr.shape)
                np.copyto(view, arr)
                return view, buf
        # fallback: an owned copy — REQUIRED even here; a zero-copy view
        # of a donated device buffer would be clobbered by later steps
        return np.array(arr, copy=True), None

    def snapshot(self, ckpt_dir, step: int, tree, *,
                 metadata: Optional[dict] = None, tag: str = "state",
                 keep: Optional[int] = None) -> float:
        """Stage ``tree`` to host and hand it to the background writer;
        returns the blocking (staging) seconds.  Drains any previous
        write first — at most one in flight."""
        self.drain()
        if self._chaos is not None:
            self._chaos.maybe_fail("ckpt/snapshot", op="ckpt/snapshot")
        t0 = time.perf_counter()
        leaves, treedef = jax.tree.flatten(tree)
        staged = [self._stage(leaf) for leaf in leaves]
        host_tree = jax.tree.unflatten(treedef, [v for v, _ in staged])
        bufs = [b for _, b in staged if b is not None]
        self.snapshot_bytes = sum(v.nbytes for v, _ in staged)
        blocking_s = time.perf_counter() - t0
        self._observe()
        write_hook = (self._chaos.stage_hook("ckpt/write")
                      if self._chaos is not None else None)
        # the closure holds the ONLY references to the host snapshot; a
        # dict box lets the writer drop them before freeing the buffers
        box = {"tree": host_tree}

        def write():
            w0 = time.perf_counter()

            def do_save():
                path = checkpoint.save(ckpt_dir, step, box["tree"],
                                       metadata=metadata, tag=tag,
                                       hook=write_hook)
                if keep is not None:
                    checkpoint.prune(ckpt_dir, keep)
                return path

            try:
                retry(do_save, self._retry, op="ckpt/write", log=self._log)
            except BaseException as exc:  # surfaced at the next drain
                self._error = exc
                return
            finally:
                # drop the snapshot refs, then return the pinned buffers;
                # a watchdog-abandoned attempt's zombie thread may still
                # hold views — free() refuses then, and the buffer leaks
                # to the pool finalizer instead of corrupting a reuse
                box.clear()
                for b in bufs:
                    try:
                        b.free()
                    except ValueError:
                        self._log("ckpt/write: leaked a staging buffer "
                                  "still viewed by an abandoned attempt")
            self.writes += 1
            if self._metrics is not None:
                self._metrics.counter("ckpt/async_writes").inc()
            if self._sink is not None:
                self._sink.emit(
                    "ckpt/write", step=step,
                    wall_s=round(time.perf_counter() - w0, 6),
                )

        self._submit(write)
        return blocking_s

    def _observe(self) -> None:
        if self._metrics is None:
            return
        self._metrics.gauge("ckpt/snapshot_bytes").set(self.snapshot_bytes)
        if self._pool is not None:
            try:
                stats = self._pool.stats()
            except Exception:
                return
            for key in ("bytes_in_use", "bytes_cached", "high_water",
                        "live_buffers", "trim_calls", "locked_bytes"):
                self._metrics.gauge(f"hostpool/{key}").set(stats[key])

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is None:
            self.close()
        else:
            self.abandon()
