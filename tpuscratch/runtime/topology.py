"""Process-grid topology as a pure value object.

TPU-native replacement for MPI communicator topology (the reference's
``MPI_Cart_create``/``MPI_Cart_coords``/``MPI_Cart_shift``/``MPI_Cart_rank``
layer — /root/reference/mpi10.cpp:27-42 and
/root/reference/stencil2D.h:232-299). Instead of opaque communicator
handles mutated by library calls, topology here is an immutable, hashable
dataclass whose rank<->coords math is pure Python (unit-testable with no
devices at all) and whose neighbor tables compile directly into
``lax.ppermute`` permutation lists.

Conventions:
- Coordinates are row-major: ``rank = coords[0]*dims[1]*... + ...``, matching
  both MPI's cartesian default and the device order of a reshaped
  ``jax.devices()`` list, so topology rank == mesh device index.
- 2D coordinate order is ``(row, col)``; row 0 is the TOP of the grid,
  col 0 is the LEFT, matching the reference's sample-output orientation
  (rank (0,0) writes file ``0_0`` whose top-left halo corner wraps to the
  bottom-right rank — /root/reference/stencil2d/sample-output/0_0).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import math
from typing import Iterator, Optional, Sequence


class Direction(enum.Enum):
    """The 8-neighborhood of a 2D grid cell, as (drow, dcol) offsets.

    Equivalent of the reference's ``MPIGridCellID`` direction enum
    (/root/reference/stencil2D.h:86-88). TOP means "the neighbor above me"
    (row - 1).
    """

    TOP = (-1, 0)
    BOTTOM = (1, 0)
    LEFT = (0, -1)
    RIGHT = (0, 1)
    TOP_LEFT = (-1, -1)
    TOP_RIGHT = (-1, 1)
    BOTTOM_LEFT = (1, -1)
    BOTTOM_RIGHT = (1, 1)

    @property
    def offset(self) -> tuple[int, int]:
        return self.value

    @property
    def opposite(self) -> "Direction":
        dr, dc = self.value
        return Direction((-dr, -dc))

    @property
    def is_diagonal(self) -> bool:
        dr, dc = self.value
        return dr != 0 and dc != 0


# Stable iteration order used when building exchange plans: edges then corners.
ALL_DIRECTIONS: tuple[Direction, ...] = (
    Direction.TOP,
    Direction.BOTTOM,
    Direction.LEFT,
    Direction.RIGHT,
    Direction.TOP_LEFT,
    Direction.TOP_RIGHT,
    Direction.BOTTOM_LEFT,
    Direction.BOTTOM_RIGHT,
)


@dataclasses.dataclass(frozen=True)
class CartTopology:
    """An N-dimensional cartesian process grid with optional periodic axes.

    ``dims`` is the grid shape; ``periodic[i]`` enables wraparound on axis i
    (the reference's stencil drivers use fully periodic 2D grids:
    /root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:48-52).
    """

    dims: tuple[int, ...]
    periodic: tuple[bool, ...] = ()

    def __post_init__(self) -> None:
        object.__setattr__(self, "dims", tuple(self.dims))
        if not self.dims or any(d <= 0 for d in self.dims):
            raise ValueError(f"invalid grid dims {self.dims!r}")
        per = self.periodic or tuple(False for _ in self.dims)
        if len(per) != len(self.dims):
            raise ValueError(
                f"periodic {self.periodic!r} does not match dims {self.dims!r}"
            )
        object.__setattr__(self, "periodic", tuple(bool(p) for p in per))

    # ---- basic queries -------------------------------------------------

    @property
    def ndim(self) -> int:
        return len(self.dims)

    @property
    def size(self) -> int:
        return math.prod(self.dims)

    def ranks(self) -> Iterator[int]:
        return iter(range(self.size))

    # ---- rank <-> coords ----------------------------------------------

    def coords(self, rank: int) -> tuple[int, ...]:
        """Row-major rank -> coordinates (MPI_Cart_coords equivalent)."""
        if not 0 <= rank < self.size:
            raise ValueError(f"rank {rank} out of range for grid {self.dims}")
        out = []
        for extent in reversed(self.dims):
            out.append(rank % extent)
            rank //= extent
        return tuple(reversed(out))

    def rank_at(self, coords: Sequence[int]) -> Optional[int]:
        """Coordinates -> rank, applying periodic wrap (MPI_Cart_rank).

        Returns None when coords fall off a non-periodic axis — the
        equivalent of MPI_PROC_NULL from MPI_Cart_shift on an open boundary.
        """
        if len(coords) != self.ndim:
            raise ValueError(f"coords {coords!r} do not match dims {self.dims!r}")
        rank = 0
        for c, extent, per in zip(coords, self.dims, self.periodic):
            if not 0 <= c < extent:
                if not per:
                    return None
                c %= extent
            rank = rank * extent + c
        return rank

    # ---- neighbors -----------------------------------------------------

    def neighbor(self, rank: int, offset: Sequence[int] | Direction) -> Optional[int]:
        """Rank at ``coords(rank) + offset`` or None off an open boundary."""
        if isinstance(offset, Direction):
            offset = offset.offset
        here = self.coords(rank)
        return self.rank_at(tuple(c + d for c, d in zip(here, offset)))

    def shift(self, rank: int, axis: int, disp: int = 1) -> tuple[Optional[int], Optional[int]]:
        """(source, dest) ranks for a displacement along one axis.

        MPI_Cart_shift semantics (/root/reference/mpi10.cpp:41-42): ``source``
        is the rank whose data reaches me under this shift, ``dest`` is the
        rank my data reaches. Open boundaries yield None (MPI_PROC_NULL).
        """
        off = [0] * self.ndim
        off[axis] = disp
        dest = self.neighbor(rank, off)
        off[axis] = -disp
        source = self.neighbor(rank, off)
        return source, dest

    def neighbors8(self, rank: int) -> dict[Direction, Optional[int]]:
        """All 8 neighbors of a rank on a 2D grid (stencil2D.h:259-299)."""
        self._require_2d()
        return {d: self.neighbor(rank, d) for d in ALL_DIRECTIONS}

    # ---- ppermute compilation ------------------------------------------

    def send_permutation(self, offset: Sequence[int] | Direction) -> list[tuple[int, int]]:
        """(src, dst) pairs where every rank sends to its ``offset`` neighbor.

        This is the bridge from topology to ``jax.lax.ppermute``: the
        permutation that realizes one direction of a halo/ring exchange.
        Diagonal offsets produce a single diagonal permutation — no need to
        compose two axis shifts. Ranks whose neighbor falls off an open
        boundary simply do not appear as sources (their ppermute output is
        zero-filled, the analogue of MPI_PROC_NULL skipping the transfer).
        """
        pairs = []
        for r in self.ranks():
            n = self.neighbor(r, offset)
            if n is not None:
                pairs.append((r, n))
        return pairs

    def ring_permutation(self, axis: int = 0, disp: int = 1) -> list[tuple[int, int]]:
        """Permutation shifting every rank by ``disp`` along ``axis``."""
        off = [0] * self.ndim
        off[axis] = disp
        return self.send_permutation(off)

    # ---- pretty printing ------------------------------------------------

    def grid_string(self) -> str:
        """Rank map like the reference's PrintCartesianGrid (stencil2D.h:513-530)."""
        self._require_2d()
        rows, cols = self.dims
        width = len(str(self.size - 1))
        lines = []
        for r in range(rows):
            lines.append(" ".join(f"{self.rank_at((r, c)):>{width}}" for c in range(cols)))
        return "\n".join(lines)

    def _require_2d(self) -> None:
        if self.ndim != 2:
            raise ValueError(f"operation requires a 2D grid, got dims {self.dims}")


def square_grid(nranks: int, periodic: bool = True) -> CartTopology:
    """A sqrt(N) x sqrt(N) periodic grid, the reference drivers' default
    layout (/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:48-52)."""
    side = math.isqrt(nranks)
    if side * side != nranks:
        raise ValueError(f"{nranks} ranks do not form a square grid")
    return CartTopology((side, side), (periodic, periodic))


def factor2d(n: int) -> tuple[int, int]:
    """Most-square (rows, cols) factorization of n, rows <= cols."""
    best = (1, n)
    for rows in range(1, math.isqrt(n) + 1):
        if n % rows == 0:
            best = (rows, n // rows)
    return best


def factor3d(n: int) -> tuple[int, int, int]:
    """Most-cubic (z, rows, cols) factorization of n, z <= rows <= cols."""
    best, best_spread = (1, 1, n), n
    for z in range(1, round(n ** (1 / 3)) + 2):
        if n % z:
            continue
        rows, cols = factor2d(n // z)
        if z <= rows and cols - z < best_spread:
            best, best_spread = (z, rows, cols), cols - z
    return best
