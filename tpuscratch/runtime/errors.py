"""Error-handling layer with dual policy: raise or print-and-abort.

TPU-native replacement for the reference's ``mpierr.h`` /
``cuda_error_handler.h`` pair, which wrap every MPI/CUDA call and select at
compile time (``MPI_ERR_USE_EXCEPTIONS``) between throwing an exception and
printing the formatted error then calling ``MPI_Abort``
(/root/reference/mpierr.h:30-52, /root/reference/cuda_error_handler.h:47-86).
Here the policy is a runtime value carried in ``Config`` instead of a macro,
and the "error class" string MPI provides becomes the exception's type name.

XLA note: most failures the reference guards against (bad device pointers,
launch errors, mismatched message sizes) are impossible by construction under
jax — arrays carry their placement and shapes are checked at trace time. What
remains worth guarding is host-side orchestration: mesh construction, shape
mismatches between plan and data, device discovery, file IO. Async-execution
errors (the class the reference documents as uncatchable at launch,
cuda_error_handler.h:21-23) surface in jax at ``block_until_ready`` — the
``guarded`` wrapper here is the right place to catch those too.
"""

from __future__ import annotations

import contextlib
import functools
import os
import sys
import traceback
from enum import Enum
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


class CommError(RuntimeError):
    """A failure in the communication/runtime layer, tagged with context."""

    def __init__(self, op: str, message: str, rank: Optional[int] = None):
        self.op = op
        self.rank = rank
        self.message = message
        super().__init__(format_comm_err(op, message, rank))

    def with_op(self, op: str, rank: Optional[int] = None) -> "CommError":
        """Attach op/rank context post-hoc when the error was raised
        without it (chaos-injected faults don't know which op wraps
        them); ``guarded`` calls this on pass-through so ft retry logs
        name the actual failing op.  A non-empty existing op wins."""
        if not self.op:
            self.op = op
        if self.rank is None:
            self.rank = rank
        self.args = (format_comm_err(self.op, self.message, self.rank),)
        return self


def format_comm_err(op: str, message: str, rank: Optional[int] = None) -> str:
    """Format op + error + class, mirroring format_mpi_err_msg
    (/root/reference/mpierr.h:15-28) which prints both the error string and
    the error-class string."""
    where = f"[rank {rank}] " if rank is not None else ""
    return f"{where}{op}: {message}"


class ErrorPolicy(Enum):
    """RAISE = exception propagation; ABORT = print then hard-exit, the
    analogue of HANDLE_MPI_ERROR_STDERR + MPI_Abort (mpierr.h:37-43)."""

    RAISE = "raise"
    ABORT = "abort"


def _handle(exc: BaseException, op: str, policy: ErrorPolicy, rank: Optional[int]) -> None:
    if policy is ErrorPolicy.ABORT:
        print(
            format_comm_err(op, f"{type(exc).__name__}: {exc}", rank),
            file=sys.stderr,
            flush=True,
        )
        traceback.print_exc()
        # The whole-job teardown MPI_Abort performs is the scheduler's job on
        # TPU slices; locally a nonzero hard exit is the faithful analogue.
        os._exit(1)
    raise CommError(op, f"{type(exc).__name__}: {exc}", rank) from exc


@contextlib.contextmanager
def guarded(op: str, policy: ErrorPolicy = ErrorPolicy.RAISE, rank: Optional[int] = None):
    """Context manager guarding a block of runtime/comm calls.

    Usage parity with the reference's ``MPI_(MPI_Init(...))`` wrapping of
    every call (mpierr.h:48-52):

        with guarded("mesh construction", cfg.error_policy, rank):
            mesh = make_mesh_2d(...)
    """
    try:
        yield
    except CommError as exc:
        # Already wrapped by an inner guard: don't re-wrap — but fill in
        # missing op/rank context (an injected fault raised without an op
        # picks up this guard's), and an ABORT policy must still abort
        # (MPI_Abort parity).
        exc.with_op(op, rank)
        if policy is ErrorPolicy.ABORT:
            _handle(exc, exc.op, policy, exc.rank if exc.rank is not None else rank)
        raise
    except Exception as exc:  # SystemExit/KeyboardInterrupt pass through
        _handle(exc, op, policy, rank)


def guard_call(
    fn: Callable[..., T],
    *args,
    op: Optional[str] = None,
    policy: ErrorPolicy = ErrorPolicy.RAISE,
    rank: Optional[int] = None,
    **kwargs,
) -> T:
    """Functional form: ``guard_call(jax.block_until_ready, out, op="dot")``."""
    name = op or getattr(fn, "__name__", "call")
    with guarded(name, policy, rank):
        return fn(*args, **kwargs)


def guards(op: Optional[str] = None, policy: ErrorPolicy = ErrorPolicy.RAISE):
    """Decorator form for whole entry points (each reference main() wraps its
    body in try/catch under the exceptions policy, e.g. mpi2.cpp)."""

    def deco(fn: Callable[..., T]) -> Callable[..., T]:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs) -> T:
            with guarded(op or fn.__name__, policy):
                return fn(*args, **kwargs)

        return wrapper

    return deco
