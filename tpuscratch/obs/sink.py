"""Per-host JSONL event sink with run metadata.

One line per event, one file per host — the structured replacement for
the reference's rank-prefixed printf logging (its per-rank coord-named
dump files, generalized from result arrays to telemetry).  The first
line of every file is a ``run`` event carrying the run metadata
(argv-ish identity: who wrote this file, when, with what config), so a
bare JSONL artifact is self-describing and ``obs.report`` can collapse
it without side channels.

Writes are buffered (``flush_every`` events) and each event costs one
dict build + one ``json.dumps`` — cheap enough to emit per engine tick.
A ``weakref.finalize`` hook (GC + interpreter exit) flushes the buffered
tail, so short runs and crashed runs that never reach ``close()`` don't
silently lose events — and dropped unclosed sinks don't pin their file
descriptors.
``NullSink`` is the disabled path: every emit is a constant-time no-op,
so instrumented layers hold a sink unconditionally instead of
``if sink is not None`` at every site.

This module deliberately does not import jax — host-side tooling built
on it stays cheap to import and jax-decoupled (the package init still
imports jax, so module-level lightness is about import cost, not a
jax-free CLI).  The per-host process index is whatever the caller
passes (``ServeEngine``/``trainer`` pass ``jax.process_index()``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Optional

__all__ = ["NullSink", "Sink", "open_sink"]


def _close_file(f, buf: list, lock) -> None:
    """Flush the buffered tail and close — the finalizer body.  A plain
    function over (file, buffer, lock) so ``weakref.finalize`` holds no
    reference to the Sink itself (a dropped unclosed sink is collectable
    and closes at GC; survivors close at interpreter exit).  Takes the
    sink's emit lock: a background writer thread's in-flight emit must
    not race the close (a dropped line, or a write to a closed file
    raising inside the writer)."""
    with lock:
        if f.closed:
            return
        if buf:
            f.write("\n".join(buf) + "\n")
            buf.clear()
        f.flush()
        f.close()


class NullSink:
    """The disabled sink: accepts every emit, writes nothing."""

    enabled = False
    path = None

    def emit(self, event: str, **fields) -> None:
        pass

    def emit_metrics(self, snapshot: dict, event: str = "metrics",
                     scope=None) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullSink":
        return self

    def __exit__(self, *exc) -> None:
        pass


class Sink:
    """Append-only JSONL event writer.

    ``run`` metadata is written as the file's first event.  ``host``
    disambiguates multi-host runs: a non-zero host suffixes the filename
    (``run.jsonl`` -> ``run.h3.jsonl``) so hosts never interleave writes
    in one file — the per-host half of "per-host JSONL sink"; merging is
    the reader's job (``obs.report`` accepts several files).

    Emits are THREAD-SAFE (one lock around the buffer + file): the async
    checkpointer's background writer stamps its ``ckpt/write`` event
    from its own thread at the moment the write actually finishes — the
    goodput end-stamp convention — while the step loop keeps emitting.

    ``rotate_bytes`` bounds the file: when a flush leaves the active
    segment at or past the threshold, segments shift logrotate-style
    (``path`` -> ``path.1`` -> ... -> ``path.<max_segments>``, the
    oldest dropped) and a fresh active file opens with its own ``run``
    first line, so every segment stays self-describing and total disk
    is bounded by ``(max_segments + 1) * ~rotate_bytes`` instead of
    growing without bound over a long-lived fleet.  0 (the default)
    disables rotation.
    """

    enabled = True

    def __init__(self, path: str, run: Optional[dict] = None,
                 host: int = 0, flush_every: int = 64,
                 rotate_bytes: int = 0, max_segments: int = 8) -> None:
        if host:
            root, ext = os.path.splitext(path)
            path = f"{root}.h{host}{ext or '.jsonl'}"
        self.path = path
        self.host = host
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._flush_every = max(1, flush_every)
        self._rotate_bytes = max(0, int(rotate_bytes))
        self._max_segments = max(1, int(max_segments))
        self._run_meta = dict(run or {})
        self.rotations = 0
        self._t0 = time.time()
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a")
        # buffered writes must not be lost by a run that never reaches
        # close(): a short script that just falls off the end, a crashed
        # run whose exception unwinds past the sink, or a sink simply
        # dropped without close().  weakref.finalize fires on GC AND at
        # interpreter exit without pinning the sink (an atexit-bound
        # method would keep every unclosed sink + fd alive for the
        # process lifetime).  SIGKILL still loses the tail — that torn
        # final line is why obs.report tolerates corrupt lines.
        self._finalizer = weakref.finalize(
            self, _close_file, self._f, self._buf, self._lock
        )
        self.emit("run", host=host, **(run or {}))

    def emit(self, event: str, **fields) -> None:
        """One JSONL line: ``{"event": ..., "t": <s since sink open>,
        **fields}``.  Fields must be JSON-serializable."""
        rec = {"event": event, "t": round(time.time() - self._t0, 6)}
        rec.update(fields)
        line = json.dumps(rec)
        with self._lock:
            if self._f.closed:
                # a background writer outliving the sink: drop the line
                # rather than raise inside a daemon thread (the same
                # tolerance the torn-tail reader grants a SIGKILL)
                return
            self._buf.append(line)
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def emit_metrics(self, snapshot: dict, event: str = "metrics",
                     scope=None) -> None:
        """A registry snapshot (``MetricsRegistry.snapshot()``) as one
        event, metrics nested under ``"metrics"``.  ``scope`` (usually
        ``MetricsRegistry.id``) names WHICH registry this is a snapshot
        of: a reader keeps only the newest snapshot per scope (they are
        cumulative) but merges across scopes (distinct registries, e.g.
        one engine per batch size in a sweep)."""
        if scope is None:
            self.emit(event, metrics=snapshot)
        else:
            self.emit(event, metrics=snapshot, scope=scope)

    def _flush_locked(self) -> None:
        if self._f.closed:
            return
        if self._buf:
            self._f.write("\n".join(self._buf) + "\n")
            self._buf.clear()
        self._f.flush()
        if self._rotate_bytes and self._f.tell() >= self._rotate_bytes:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Shift segments and reopen — caller holds the lock (so the
        fresh segment's ``run`` line is written directly, not via
        ``emit``, which would deadlock on the non-reentrant lock)."""
        self._finalizer.detach()  # the old finalizer must not re-close
        self._f.close()
        oldest = f"{self.path}.{self._max_segments}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self._max_segments - 1, 0, -1):
            seg = f"{self.path}.{i}"
            if os.path.exists(seg):
                os.replace(seg, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a")
        self.rotations += 1
        self._finalizer = weakref.finalize(
            self, _close_file, self._f, self._buf, self._lock
        )
        rec = {"event": "run", "t": round(time.time() - self._t0, 6),
               "host": self.host, "segment": self.rotations}
        rec.update(self._run_meta)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        self._finalizer()  # runs at most once: flush the tail + close

    def __enter__(self) -> "Sink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open_sink(path: Optional[str], run: Optional[dict] = None,
              host: int = 0, **kw):
    """``Sink`` when ``path`` is set, ``NullSink`` otherwise — the one
    construction idiom every instrumented layer uses, so "no obs
    requested" costs a no-op object rather than branches at call sites."""
    if path is None:
        return NullSink()
    return Sink(path, run=run, host=host, **kw)
