"""The bench regression gate: diff two ``bench/record.py`` artifacts.

``python -m tpuscratch.obs.regress BASE.json NEW.json [--noise 0.1]``

``bench/record.py --json`` appends one JSON row per measurement; this
CLI matches the two files' rows by ``(config, metric)`` (last row wins —
append-mode files carry history; corrupt/torn lines are skipped with a
warning, ``obs.report``'s loader tolerance), compares every numeric
field whose direction it knows (tokens/s up is good, p50/p99/bytes down
is good) against a fractional noise band, and **exits nonzero when
anything regressed** — the BENCH_* trajectory as an enforceable gate instead of a
decorative table.  ``record.py --check BASE.json`` runs the same
comparison in-process right after measuring.

Direction inference is by name substring (see ``_HIGHER``/``_LOWER``);
fields with no inferable direction (platform, flops_per_token, device
counts, nested sweeps) are ignored.  A metric present in BASE but
missing from NEW is reported as ``missing`` — a warning, not a failure,
because configs legitimately skip on absent hardware (``Needs``).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import sys
from typing import Iterable, Mapping, Optional

__all__ = ["Finding", "compare", "format_findings", "index_rows",
           "load_rows", "main", "noise_floor"]

#: name substrings ⇒ bigger is better
#: ("achieved" covers the ledger-derived achieved-fraction/-rate rows
#: of the overlap ablation, config 14 — checked before "_s"/"ratio"
#: could mislabel them — AND the config-12 decode-sweep roofline row's
#: ``achieved_frac``/``achieved_hbm_gbps`` (ISSUE 12): the fraction of
#: peak HBM bandwidth the paged-attention sweep reaches must only go
#: up, the pin on the fused kernel the way the 0.55x byte gate pins
#: int8.  Its ``fused_speedup`` (fused Pallas kernel over the dense
#: oracle, TPU-only) rides "speedup" — up.  The row's stated
#: ``peak_hbm_gbps`` denominator is CONFIGURATION, skipped below —
#: restating the peak must not masquerade as a kernel change.)
#: ("goodput" covers the config-16 elastic-FT rows' goodput_fraction —
#: the share of wall spent on committed steps, up)
#: (the config-12 tiered-KV row, ISSUE 13: ``resident``/``users`` cover
#: ``resident_users`` — concurrent residents at fixed HBM must only go
#: up; its cost axes ride existing substrings — ``cold_hit_p99_s`` via
#: "p99", ``host_bytes_per_token`` via "bytes" — plus "cold" below so a
#: renamed cold-path field can never silently lose its direction)
#: ("decode_spec" pins the serve_decode_spec row's headline ``value``
#: — a tokens/s rate — which the "_s" substring in its METRIC NAME
#: would otherwise mislabel lower-is-better: a latent inversion that
#: only fires when the rate moves beyond noise, and then gates speedups
#: as regressions.  Targeted on purpose: a bare "spec" would drag the
#: ``spec_k`` configuration field into the comparison.)
#: (the config-17 fleet-router row, ISSUE 14: ``affinity_hit``/
#: ``affinity_token`` cover the routing-index counters — prefix-affine
#: routing finding fewer matches on the canonical mix is a regression
#: of the index; targeted on purpose, a bare "affinity" would drag the
#: ``prefill_frac_affinity_off`` CONTROL field — lower-is-better —
#: into _HIGHER, the decode_spec latent-inversion lesson.
#: ``shared``/``subpage`` cover the static sharing counters — tokens
#: served from pages instead of prefilled must only go up at a fixed
#: workload.  The row's aggregate rate rides "tokens_per"; its
#: per-class TTFT tails are pinned lower by "ttft" below, with the
#: widened _NOISE_FLOORS band.)
#: (``decode_macro`` pins the config-12 macro-decode row's headline —
#: its ``value`` is the T=16 token rate, higher; the row's static
#: dispatch fields ride the ``dispatches``/``host_sync`` _LOWER
#: entries with the tight band.)
#: (the config-19 traffic-chaos row, ISSUE 17: ``readmitted`` counts
#: replica-kill victims re-admitted through the quarantine/requeue
#: path — at a FIXED chaos plan every victim must be re-admitted, so
#: the count falling means requests started leaking into ``dropped``
#: instead; its per-class goodput fractions ride "goodput".)
_HIGHER = ("per_s", "per_sec", "gbps", "tflops", "efficiency",
           "throughput", "updates", "tokens_per", "accept", "speedup",
           "achieved", "goodput", "resident", "users", "decode_spec",
           "decode_macro", "affinity_hit", "affinity_token", "shared",
           "subpage", "readmitted")
#: name substrings ⇒ smaller is better (checked after _HIGHER)
#: (note the ordering: ``accept_len_mean`` and ``spec_speedup`` match
#: _HIGHER before "ratio"/"bytes" substrings could ever mislabel them —
#: accepted draft length and speculative speedup regress DOWNWARD;
#: ``prefill_frac`` is the prefix-sharing row's fraction of prompt
#: tokens actually prefilled and ``degraded`` counts disaggregated
#: handoffs that fell back to local prefill — both regress UPWARD.
#: The config-15 solver rows add: ``iterations``/``cycles`` — a solver
#: taking more V-cycles/CG iterations to tolerance regressed;
#: ``psum``/``ppermute`` — the communication-avoiding claims are
#: per-iteration collective COUNTS (one fused psum per pipelined-CG
#: iteration, 6/s ppermutes per s-step sweep), so a count creeping up
#: is a regression of the proof itself.  ``halo_bytes`` rides the
#: existing "bytes" substring; ``deep_speedup``/``pipelined_speedup``
#: ride "speedup"; ``comm_ratio`` (halo bytes per computed cell) rides
#: "ratio" — down.)
#: (the config-16 elastic-FT badput directions: ``checkpoint`` and
#: ``restart`` bucket SHARES — and any other badput share — regress
#: UPWARD; a lost-capacity/goodput win is their going down.  The
#: trailing ``restarts``/``checkpoint_s`` style fields ride the same
#: substrings.)
#: (``ttft`` pins the config-17 per-class time-to-first-token fields —
#: their ``_p50_s``/``_p99_s`` suffixes already match, the explicit
#: substring keeps a renamed TTFT field from losing its direction.)
#: (the config-12 macro-decode row, ISSUE 15: ``dispatches`` and
#: ``host_sync`` are the per-token orchestration costs macro-step
#: decode exists to amortize — exact engine counters over exact token
#: counts, so they keep the tight static band; a dispatches/token
#: creeping back toward 1 means the scan stopped covering the ticks.)
#: (the config-18 co-scheduling row, ISSUE 16: ``share_err`` is the
#: achieved-vs-target share error of the MeshScheduler's arbitration —
#: drifting from the policy target is a scheduler regression;
#: ``switch`` pins the per-context-switch overhead seconds.  The row's
#: aggregate/solo goodput fractions ride the existing "goodput"
#: _HIGHER entry; the raw ``switches`` COUNT is workload shape,
#: skipped.)
#: (the config-19 row's ``dropped`` is the zero-loss law as a gated
#: counter — any value above the recorded 0 is a lost request; its
#: TTFT tails ride the existing "ttft" substring + widened floor.)
#: (the config-20 overload row, ISSUE 18: ``sheds``/``shed_frac`` are
#: the load-shedding counters at a FIXED storm — deterministic on the
#: logical shed clock, so they keep the tight static band; more sheds
#: at the same storm means capacity or scheduling regressed.  The
#: per-class ``sheds_latency`` field doubles as the zero-top-shed gate:
#: recorded 0, any value above it fails.  ``retries``/``abandoned``
#: pin the retry-storm amplification — the closed loop resubmitting
#: more, or giving up on more, at the same storm is a regression.
#: ``sheds`` not a bare "shed": "shed" is a substring of "finished".)
_LOWER = ("latency", "p50", "p99", "bytes", "ratio", "_s", "seconds",
          "overhead", "bubble", "crossover", "prefill_frac", "degraded",
          "iterations", "cycles", "psum", "ppermute", "checkpoint",
          "restart", "badput", "cold", "ttft", "dispatches", "host_sync",
          "share_err", "switch", "dropped", "sheds", "shed_frac",
          "retries", "abandoned")

#: checked BEFORE _HIGHER: the config-15 per-SWEEP collective budget
#: fields ("ppermutes_per_sweep", "halo_bytes_per_sweep") would
#: otherwise be mislabeled higher-is-better by _HIGHER's "per_s"
#: substring (meant for per-second rates) — these are costs, down.
#: (``decomp_`` pins the config-22 per-class latency-decomposition
#: bucket means, ISSUE 20: ``decomp_<bucket>_s_<class>`` — every
#: bucket second (queue wait, shed wait, handoff, kill/degrade WASTE,
#: stall remainder) is a cost at the fixed chaos workload, down.
#: Registered FIRST on purpose: the class suffix is a tenant-chosen
#: name, and a class called e.g. "throughput" would otherwise drag its
#: buckets into _HIGHER upside down.)
_LOWER_FIRST = ("per_sweep", "decomp_")
#: fields that are identity/configuration, never compared
#: (``replicas`` is the config-17 fleet size — workload shape, like dp)
#: (``switches``/``workloads`` are the config-18 arbitration shape —
#: how many context switches/jobs the policy produced at this quantum,
#: not a cost; the per-switch overhead carries the direction.  Its
#: achieved/target shares and raw walls are CONTEXT: ``share_err``
#: carries the arbitration direction and the goodput fractions carry
#: the wall story — ``share_solver``'s accidental ``_s`` substring and
#: the wall clocks must not gate; a few-ms solver share swings tens of
#: percent on the proxy with nothing regressed.)
#: (``kills``/``stalls``/``requests``/``peak_open`` are the config-19
#: chaos/workload shape — how much churn the fixed plan injected and
#: how deep the open loop ran, not costs; its raw chaos/clean walls
#: are context like config 18's — the median-of-3 token rates and the
#: direction-gated counters carry the story.  Config 20's storm wall
#: (``wall_s_storm``) and tick counts ride the same reasoning — the
#: bounded-open-queue claim is asserted in ``bench_overload``, not
#: gated here.)
_SKIP = {"config", "dp", "n_devices", "steps", "accum", "host",
         "flops_per_token", "degenerate", "peak_hbm_gbps", "replicas",
         "switches", "workloads", "share_train", "share_solver",
         "target_train", "target_solver", "wall_s_cosched",
         "wall_s_solo", "kills", "stalls", "requests", "peak_open",
         "wall_s_chaos", "wall_s_clean", "wall_s_storm",
         "ticks_storm", "ticks_clean",
         # config 22 (ISSUE 20): trace/workload shape and context —
         # n_traces/waste_traces are deterministic chaos-schedule
         # counts, the walls/ticks are context like config 19's, and
         # trace_overhead_frac is HARD-gated in-config (RuntimeError
         # at >= 2%); its recorded value is often exactly 0.0 (min
         # over interleaved pairs), and a zero base would inf-trip the
         # delta on any nonzero re-measurement
         "n_traces", "waste_traces", "ticks", "wall_s_traced",
         "wall_s_untraced", "trace_overhead_frac",
         # per-class completion counts are the fixed closed-loop
         # quotas, not costs — and "completed_latency" would otherwise
         # ride the "latency" _LOWER substring upside down
         "completed_latency", "completed_batch"}

#: per-field MEASURED-noise floors (fractional band, substring-matched
#: like the direction tables; first match wins): wall-clock fields
#: swing on SAME-CODE control runs — +11.6–27.5% in the PR-13
#: ``--check`` pairs, and a PR-14 three-run control of config 12 on
#: the 1-core proxy measured p50/p99 tails to 51%, rate ratios
#: (spec_speedup, achieved_frac) to 47%, and serve token rates to 39%
#: single-shot (config 12's serve rates are median-of-3 re-measured
#: since PR 14, which pulls them inside these floors) — while every
#: STATIC field (bytes, counts, exact-counter fractions like
#: prefill_frac) sat at exactly 0.0%.  The band a field is judged
#: against is ``max(--noise, floor)`` — a floor can only WIDEN a
#: field's band, never narrow it; the static fields keep the tight
#: default, and CHIP rows (``platform == "tpu"``) skip the floors
#: entirely (see :func:`noise_floor`) so the pinned chip trajectory is
#: never judged against CPU-proxy noise.  A REAL regression still
#: gates: the injected-regression tests drive 2x swings, past every
#: floor.
_NOISE_FLOORS = (
    ("ttft", 0.55),            # per-request tail timings (scheduler noise)
    ("p99", 0.55),             # tail percentiles, and p99/p99 ratios
    ("p50", 0.55),             # medians of the same wall-clock samples
    ("max_s", 0.55),
    ("cold_hit_p", 0.55),      # cold_hit_p50/p99 stall timings ONLY —
                               # the cold_hits COUNT is static, tight band
    ("speedup", 0.50),         # ratio of two measured rates: both runs'
    ("residency_gain", 0.50),  # noise compounds
    ("achieved", 0.50),        # measured rate over a stated peak
    ("tokens_per_s_t", 0.55),  # the macro row's per-T rates: SINGLE-
                               # STREAM windows (batch capped by the
                               # T=16 page reservation), tick walls in
                               # the 0.1-1 ms scheduler-noise regime —
                               # an idle-machine same-code pair swung
                               # tokens_per_s_t4 by 42.5% even median-
                               # of-3 (before the generic 0.40 band)
    ("tokens_per_s", 0.40),    # wall-clock token rates (median-of-3
    ("decode_spec", 0.40),     # re-measured on the serve configs)
    ("decode_macro", 0.55),    # the macro row's headline (= its T=16
                               # single-stream rate, the band above);
                               # the row's dispatch counters are
                               # static (no floor)
    ("share_err", 0.50),       # achieved-vs-target share: a ratio of
                               # measured busy walls on tiny CPU chunks
    ("switch", 0.55),          # per-switch overhead: sub-ms residuals
                               # of wall minus busy, scheduler-noise
                               # dominated on the proxy
    ("goodput", 0.40),         # goodput fractions of short CPU runs —
                               # chunk walls in the ms regime
    ("decomp_", 0.55),         # per-class bucket MEANS (config 22):
                               # wall-clock waits/work seconds in the
                               # scheduler-noise regime, same as ttft
)


def noise_floor(name: str, platform: str = "") -> float:
    """The measured-noise floor (fraction) for a metric/field name;
    0.0 when no floor applies (the CLI ``--noise`` band rules alone).

    Floors are a CPU-PROXY discipline: they exist because the 1-core
    dev box cannot hold a wall-clock rate steady, and they must not
    leak onto chip artifacts — a real 35% chip regression has no noise
    excuse — so ``platform == "tpu"`` rows always return 0.0 and keep
    the tight default band."""
    if platform.lower() == "tpu":
        return 0.0
    low = name.lower()
    for sub, floor in _NOISE_FLOORS:
        if sub in low:
            return floor
    return 0.0


def direction(name: str) -> Optional[str]:
    """'higher' | 'lower' | None for a metric/field name."""
    low = name.lower()
    if any(s in low for s in _LOWER_FIRST):
        return "lower"
    if any(s in low for s in _HIGHER):
        return "higher"
    if any(s in low for s in _LOWER):
        return "lower"
    return None


@dataclasses.dataclass(frozen=True)
class Finding:
    """One compared number (or one structural note)."""

    config: object
    metric: str
    field: str
    base: Optional[float]
    new: Optional[float]
    delta: Optional[float]          # (new - base) / base, sign as stored
    status: str                     # ok | regressed | improved | missing | added

    def line(self) -> str:
        tag = {"regressed": "REGRESSED", "improved": "improved",
               "missing": "MISSING", "added": "added"}.get(self.status, "ok")
        if self.base is None or self.new is None:
            return f"  {self.metric}.{self.field}: {tag}"
        pct = 100 * (self.delta or 0.0)
        return (
            f"  {self.metric}.{self.field}: {self.base:.6g} -> "
            f"{self.new:.6g} ({pct:+.1f}%) {tag}"
        )


def index_rows(rows: Iterable[dict]) -> dict[tuple, dict]:
    """{(config, metric): row} — last occurrence wins (append-mode
    artifacts carry every historical run; the newest is the state)."""
    out: dict[tuple, dict] = {}
    for row in rows:
        metric = row.get("metric")
        if metric is None:
            continue
        out[(row.get("config"), metric)] = row
    return out


def load_rows(path: str) -> dict[tuple, dict]:
    """Indexed rows of one record artifact, loaded through
    ``obs.report.load_events`` — the ONE torn-tail-tolerant JSONL
    loader: blank lines skipped, corrupt/truncated and non-object lines
    dropped with a located ``RuntimeWarning`` (stderr, for the CLI).
    The loader's ``_file`` annotation is a string field, so the
    comparison (numeric, direction-bearing fields only) never sees
    it."""
    from tpuscratch.obs.report import load_events

    return index_rows(load_events([path]))


def _comparable(row: dict) -> dict[str, float]:
    """{field: value} of every direction-bearing numeric field."""
    out = {}
    for key, val in row.items():
        if key in _SKIP or key == "metric":
            continue
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        if not math.isfinite(val):
            continue
        name = row.get("metric", "") if key == "value" else key
        if direction(name) is not None:
            out[key] = float(val)
    return out


def compare(base: Mapping[tuple, dict], new: Mapping[tuple, dict],
            noise: float = 0.1) -> list[Finding]:
    """All findings, worst first.  ``noise`` is the fractional band a
    change must exceed (in the BAD direction) to count as a regression;
    symmetric for ``improved``.  Per field the band is
    ``max(noise, noise_floor(field))`` — tail/ratio-of-rates fields
    carry measured-noise floors so same-code pairs stop flagging
    (see ``_NOISE_FLOORS``)."""
    findings = []
    for key in sorted(base, key=str):
        cfg, metric = key
        if key not in new:
            findings.append(Finding(cfg, metric, "*", None, None, None,
                                    "missing"))
            continue
        b_row, n_row = base[key], new[key]
        b_num, n_num = _comparable(b_row), _comparable(n_row)
        for field in sorted(b_num):
            if field not in n_num:
                raw = n_row.get(field)
                if (isinstance(raw, float) and not math.isfinite(raw)):
                    # present but NaN/inf: the measurement degenerated —
                    # that is a failing state, not a skipped config
                    findings.append(Finding(cfg, metric, field,
                                            b_num[field], None, None,
                                            "regressed"))
                else:
                    # a renamed/dropped field must not silently disable
                    # its gate: surface it, like a whole-metric
                    # disappearance
                    findings.append(Finding(cfg, metric, field,
                                            b_num[field], None, None,
                                            "missing"))
                continue
            bv, nv = b_num[field], n_num[field]
            name = metric if field == "value" else field
            d = direction(name)
            band = max(noise, noise_floor(
                name, str(n_row.get("platform") or
                          b_row.get("platform") or "")
            ))
            if bv == 0:
                delta = 0.0 if nv == 0 else math.inf
            else:
                delta = (nv - bv) / abs(bv)
            worse = delta < -band if d == "higher" else delta > band
            better = delta > band if d == "higher" else delta < -band
            status = ("regressed" if worse
                      else "improved" if better else "ok")
            findings.append(Finding(cfg, metric, field, bv, nv, delta,
                                    status))
    for key in sorted(set(new) - set(base), key=str):
        findings.append(Finding(key[0], key[1], "*", None, None, None,
                                "added"))
    order = {"regressed": 0, "missing": 1, "improved": 2, "added": 3,
             "ok": 4}
    findings.sort(key=lambda f: (order[f.status], str(f.config), f.metric,
                                 f.field))
    return findings


def has_regression(findings: Iterable[Finding]) -> bool:
    return any(f.status == "regressed" for f in findings)


def format_findings(findings: list[Finding], noise: float) -> str:
    n_reg = sum(f.status == "regressed" for f in findings)
    n_ok = sum(f.status == "ok" for f in findings)
    n_imp = sum(f.status == "improved" for f in findings)
    lines = [
        f"regression gate (noise band ±{100 * noise:.0f}%): "
        f"{n_reg} regressed, {n_imp} improved, {n_ok} within band"
    ]
    for f in findings:
        if f.status != "ok":
            lines.append(f.line())
    if n_reg == 0 and len(lines) == 1:
        lines.append("  all compared metrics within the noise band")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuscratch.obs.regress", description=__doc__
    )
    ap.add_argument("base", help="baseline record JSON (bench/record --json)")
    ap.add_argument("new", help="candidate record JSON to gate")
    ap.add_argument("--noise", type=float, default=0.1,
                    help="fractional noise band (default 0.1 = 10%%)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON instead of a table")
    args = ap.parse_args(argv)
    findings = compare(load_rows(args.base), load_rows(args.new),
                       noise=args.noise)
    if args.json:
        rows = []
        for f in findings:
            row = dataclasses.asdict(f)
            if row["delta"] is not None and not math.isfinite(row["delta"]):
                # a 0 -> nonzero comparison carries delta=inf; None keeps
                # the artifact strict JSON (no ``Infinity`` token)
                row["delta"] = None
            rows.append(row)
        print(json.dumps(rows, allow_nan=False))
    else:
        print(format_findings(findings, args.noise))
    return 1 if has_regression(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
