"""Always-on bounded flight recorder: spans, instants, Chrome trace.

The reference's only timeline is clock() brackets printed per segment
(mpicuda3.cu:176-179, mpi-pingpong-gpu.cpp:51-57); this module is that
idiom grown into what production fleets actually fly with — a
:class:`FlightRecorder` that is cheap enough to leave ON (a thread-safe
ring buffer of begin/end spans and instant events with monotonic
timestamps; the same < 2% budget as the metrics path, asserted in the
train-bench overhead check) and exports Chrome trace-event JSON that
loads directly in Perfetto / ``chrome://tracing``.

Design points:

- **Bounded**: the ring holds the newest ``capacity`` events; a
  continuously-serving engine never grows without bound.  Per-phase
  AGGREGATES (total seconds, count, max) are kept exactly and
  separately, so eviction loses detail, never accounting.
- **One span implementation**: ``runtime/profiling.Timeline`` is now a
  thin delegate over :meth:`FlightRecorder.open_span` /
  :meth:`close_span` — the sync-fencing bracket lives HERE only.
- **Per-host lanes**: each host exports its own trace
  (:meth:`FlightRecorder.chrome_trace` with ``pid=host``);
  :func:`merge_chrome_traces` concatenates them into one file with one
  lane per host.  Cross-host SPAN math stays on the existing machinery:
  feed :func:`span_stamps` into ``obs.metrics.mesh_span`` for the
  max-min merge, and :func:`mesh_straggler` runs the per-phase max/min
  skew through ``mesh_reduce`` to name the slowest rank.

This module does not import jax at module level (the lazy import fires
only when a span is asked to fence device values), so host-side tooling
stays cheap to import.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import math
import threading
import time
import uuid
from typing import Iterable, Mapping, Optional, Sequence, Union

__all__ = [
    "FlightRecorder",
    "InstantEvent",
    "PhaseStat",
    "SpanEvent",
    "StragglerReport",
    "detect_stragglers",
    "emit_phase_totals",
    "file_flight_data",
    "fold_phase_events",
    "merge_chrome_traces",
    "mesh_straggler",
    "span_stamps",
    "validate_chrome_trace",
]


class SpanEvent:
    """One begin/end bracket.  ``end`` is ``None`` while open; ``args``
    is a mutable dict exported into the Chrome event's ``args`` (callers
    may add fields between open and close)."""

    __slots__ = ("name", "begin", "end", "tid", "args",
                 "seq_open", "seq_close")

    def __init__(self, name: str, begin: float, tid: int, args: dict):
        self.name = name
        self.begin = begin
        self.end: Optional[float] = None
        self.tid = tid
        self.args = args
        self.seq_open = next(_OP_SEQ)
        self.seq_close = -1  # stamped at close

    @property
    def seconds(self) -> float:
        if self.end is None:
            raise ValueError(f"span {self.name!r} still open")
        return self.end - self.begin


class InstantEvent:
    """A zero-duration mark (a restart, an injected fault, a compile)."""

    __slots__ = ("name", "ts", "tid", "args", "seq")

    def __init__(self, name: str, ts: float, tid: int, args: dict):
        self.name = name
        self.ts = ts
        self.tid = tid
        self.args = args
        self.seq = next(_OP_SEQ)


@dataclasses.dataclass
class PhaseStat:
    """Exact per-phase aggregate — survives ring eviction."""

    seconds: float = 0.0
    count: int = 0
    max_s: float = 0.0


#: process-unique recorder ids (the ``MetricsRegistry.id`` convention):
#: ``trace/phase`` events carry one as ``scope`` so several recorders
#: sharing one sink file merge instead of last-wins
_REC_SALT = uuid.uuid4().hex[:8]
_REC_IDS = itertools.count()

#: global operation sequence, stamped at every span open, span close,
#: and instant (``next()`` on a C-level count is atomic under the GIL).
#: The Chrome export sorts ties on it, so equal timestamps — a coarse
#: or injected clock, nested spans opened in one tick — still export in
#: TRUE chronological order (B of the outer span before B of the inner,
#: E of the inner before E of the outer), which the validator's stack
#: pairing requires.
_OP_SEQ = itertools.count()


class FlightRecorder:
    """Thread-safe bounded recorder of spans and instants.

    The hot path is two ``perf_counter`` stamps plus one deque append
    under a lock — cheap enough to bracket every engine tick and train
    chunk unconditionally (the "always-on" half of the contract; the
    "bounded" half is the ring's ``capacity``).
    """

    def __init__(self, capacity: int = 4096,
                 clock=time.perf_counter) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._clock = clock
        self._ring: "list" = []
        self._capacity = capacity
        self._lock = threading.Lock()
        self._phases: dict[str, PhaseStat] = {}
        self._open: set = set()   # spans opened but not yet closed
        self.t0 = clock()   # export zero point (host-local)
        self.dropped = 0    # events evicted from the ring so far
        self.id = f"rec-{_REC_SALT}-{next(_REC_IDS)}"

    # ---- recording -----------------------------------------------------

    def open_span(self, name: str, sync: Sequence = (), **args) -> SpanEvent:
        """Begin a span.  ``sync`` arrays are blocked on first, so async
        dispatch cannot leak pending device work into the bracket."""
        if sync:
            import jax

            for s in sync:
                jax.block_until_ready(s)
        ev = SpanEvent(name, self._clock(), threading.get_ident(), args)
        with self._lock:
            self._open.add(ev)
        return ev

    def close_span(self, ev: SpanEvent) -> SpanEvent:
        """Stamp the end and commit the span to the ring + aggregates."""
        ev.end = self._clock()
        ev.seq_close = next(_OP_SEQ)
        dur = ev.end - ev.begin
        with self._lock:
            self._open.discard(ev)
            ph = self._phases.get(ev.name)
            if ph is None:
                ph = self._phases[ev.name] = PhaseStat()
            ph.seconds += dur
            ph.count += 1
            if dur > ph.max_s:
                ph.max_s = dur
            self._push(ev)
        return ev

    @contextlib.contextmanager
    def span(self, name: str, sync: Sequence = (), **args):
        """``with recorder.span("phase") as ev: ...`` — THE bracket
        implementation (``Timeline.span`` delegates here)."""
        ev = self.open_span(name, sync=sync, **args)
        try:
            yield ev
        finally:
            self.close_span(ev)

    def close_open_spans(self) -> int:
        """Close every span still open (a crashed invocation's in-flight
        brackets), committing the partial wall to the ring + aggregates;
        returns how many were closed.  Balanced callers never need this
        — it exists for the failure path (:func:`file_flight_data`), so
        a phase that was mid-flight when the run died still counts."""
        with self._lock:
            leaked = sorted(self._open, key=lambda ev: ev.begin)
        n = 0
        for ev in leaked:
            if ev.end is None:  # not raced shut by its owning thread
                self.close_span(ev)
                n += 1
        return n

    def instant(self, name: str, **args) -> InstantEvent:
        ev = InstantEvent(name, self._clock(), threading.get_ident(), args)
        with self._lock:
            self._push(ev)
        return ev

    def _push(self, ev) -> None:  # caller holds the lock
        if len(self._ring) >= self._capacity:
            # drop the OLDEST half in one slice instead of one-at-a-time
            # popleft churn; ``dropped`` keeps the evidence
            keep = self._capacity // 2
            self.dropped += len(self._ring) - keep
            del self._ring[: len(self._ring) - keep]
        self._ring.append(ev)

    # ---- reading -------------------------------------------------------

    def events(self) -> list:
        """Snapshot of the ring (oldest first)."""
        with self._lock:
            return list(self._ring)

    def phase_totals(self) -> dict[str, PhaseStat]:
        """Exact cumulative {span name: aggregate} — independent of the
        ring, so a long run's totals are never eviction-truncated."""
        with self._lock:
            return {
                k: PhaseStat(p.seconds, p.count, p.max_s)
                for k, p in self._phases.items()
            }

    # ---- Chrome trace export -------------------------------------------

    def chrome_trace(self, pid: int = 0,
                     label: Optional[str] = None) -> dict:
        """The ring as Chrome trace-event JSON (the dict; ``json.dump``
        it and load the file in Perfetto).  Spans export as paired
        ``B``/``E`` events, instants as ``i``; timestamps are
        microseconds relative to the recorder's ``t0``, host-local —
        merging hosts is lane-merging (:func:`merge_chrome_traces`), not
        clock alignment."""
        tids: dict[int, int] = {}

        def tid_of(raw: int) -> int:
            return tids.setdefault(raw, len(tids))

        out = []  # (tid, ts, op-seq, event)
        for ev in self.events():
            tid = tid_of(ev.tid)
            if isinstance(ev, SpanEvent):
                if ev.end is None:
                    continue  # still open: not exportable as a pair
                base = {"name": ev.name, "pid": pid, "tid": tid}
                out.append((tid, (ev.begin - self.t0) * 1e6, ev.seq_open,
                            dict(base, ph="B",
                                 ts=(ev.begin - self.t0) * 1e6,
                                 args=dict(ev.args))))
                out.append((tid, (ev.end - self.t0) * 1e6, ev.seq_close,
                            dict(base, ph="E",
                                 ts=(ev.end - self.t0) * 1e6)))
            else:
                out.append((tid, (ev.ts - self.t0) * 1e6, ev.seq, {
                    "name": ev.name, "ph": "i", "s": "t",
                    "ts": (ev.ts - self.t0) * 1e6, "pid": pid, "tid": tid,
                    "args": dict(ev.args),
                }))
        meta = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label or f"host{pid}"},
        }]
        # B/E pairs must be time-ordered within each lane for the viewer,
        # and the validator's stack pairing needs TRUE order under equal
        # timestamps (coarse/injected clocks): the op-seq counter was
        # stamped in real open/close order, so it is the exact tiebreak
        out.sort(key=lambda e: e[:3])
        return {
            "traceEvents": meta + [e[3] for e in out],
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": self.dropped},
        }


def merge_chrome_traces(
    traces: Union[Mapping[int, dict], Iterable[dict]]
) -> dict:
    """Merge per-host Chrome traces into one file, one lane (pid) per
    host.  ``traces`` is {host: trace} or an iterable (hosts numbered in
    order).  Events are re-pid'ed; timestamps stay host-local — the
    viewer shows each host's lane on its own clock, which is exactly the
    per-rank dump-file layout of the reference, merged for one screen."""
    if isinstance(traces, Mapping):
        items = sorted(traces.items())
    else:
        items = list(enumerate(traces))
    events = []
    dropped = 0
    for host, tr in items:
        for ev in tr.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = host
            events.append(ev)
        other = tr.get("otherData", {})
        dropped += int(other.get("dropped_events", 0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"dropped_events": dropped},
    }


def validate_chrome_trace(trace: dict) -> int:
    """The golden schema check: JSON-serializable, every ``B`` paired
    with a same-name ``E`` in stack order per (pid, tid) lane, async
    ``b``/``e`` pairs nested per (pid, id), flow chains (``s`` →
    ``t``* → ``f``) complete per flow id with pid AND tid on every
    step, and timestamps non-decreasing per lane.  Returns the number
    of data events checked; raises ``ValueError`` on the first
    violation.  Equal timestamps rely on the writer's op-seq tiebreak
    (``FlightRecorder.chrome_trace`` sorts on it), so TRUE record
    order survives coarse clocks — the stack pairing here is what that
    rule protects."""
    import json

    json.dumps(trace)  # must be serializable as-is
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("traceEvents missing or not a list")
    stacks: dict[tuple, list] = {}
    async_stacks: dict[tuple, list] = {}
    # flow id -> state: "open" after s (t keeps it open), closed = gone
    flows: dict = {}
    last_ts: dict[tuple, float] = {}
    n = 0
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            continue
        lane = (ev.get("pid"), ev.get("tid"))
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            raise ValueError(f"event without numeric ts: {ev!r}")
        if ts < last_ts.get(lane, -math.inf):
            raise ValueError(
                f"non-monotonic ts in lane {lane}: {ts} after "
                f"{last_ts[lane]}"
            )
        last_ts[lane] = ts
        if ph == "B":
            stacks.setdefault(lane, []).append(ev["name"])
        elif ph == "E":
            stack = stacks.get(lane) or []
            if not stack:
                raise ValueError(f"unmatched E event in lane {lane}: {ev!r}")
            top = stack.pop()
            if top != ev["name"]:
                raise ValueError(
                    f"mispaired span in lane {lane}: E {ev['name']!r} "
                    f"closes B {top!r}"
                )
        elif ph in ("b", "e"):
            # async-nestable pair: matched per (pid, id), names must
            # pair in stack order (a request's root span in obs.reqtrace)
            if "id" not in ev:
                raise ValueError(f"async event without id: {ev!r}")
            key = (ev.get("pid"), ev["id"])
            if ph == "b":
                async_stacks.setdefault(key, []).append(ev["name"])
            else:
                stack = async_stacks.get(key) or []
                if not stack:
                    raise ValueError(
                        f"unmatched async e for id {ev['id']!r}: {ev!r}"
                    )
                top = stack.pop()
                if top != ev["name"]:
                    raise ValueError(
                        f"mispaired async span id {ev['id']!r}: "
                        f"e {ev['name']!r} closes b {top!r}"
                    )
        elif ph in ("s", "t", "f"):
            # flow chain: starts with s, continues with t, ends with f;
            # every step needs BOTH pid and tid (the viewer anchors flow
            # arrows to lane points — an unpaired step renders nowhere)
            if ev.get("pid") is None or ev.get("tid") is None:
                raise ValueError(f"flow event without pid/tid: {ev!r}")
            if "id" not in ev:
                raise ValueError(f"flow event without id: {ev!r}")
            fid = ev["id"]
            if ph == "s":
                if fid in flows:
                    raise ValueError(f"flow id {fid!r} started twice")
                flows[fid] = "open"
            else:
                if flows.get(fid) != "open":
                    raise ValueError(
                        f"flow {ph!r} for id {fid!r} without open s"
                    )
                if ph == "f":
                    del flows[fid]
        elif ph not in ("i", "I", "X"):
            raise ValueError(f"unknown phase {ph!r}: {ev!r}")
        n += 1
    for lane, stack in stacks.items():
        if stack:
            raise ValueError(f"unclosed span(s) in lane {lane}: {stack}")
    for key, stack in async_stacks.items():
        if stack:
            raise ValueError(
                f"unclosed async span(s) for id {key[1]!r}: {stack}"
            )
    if flows:
        raise ValueError(
            f"unterminated flow chain(s): {sorted(map(repr, flows))}"
        )
    return n


def emit_phase_totals(sink, recorder: FlightRecorder) -> None:
    """One cumulative ``trace/phase`` event per span name — the per-host
    phase aggregates the straggler table (``obs.report``) and the
    goodput straggler-wait carve-out read.  Cumulative semantics: a
    reader keeps the NEWEST event per (file, host, scope, phase) —
    ``scope`` is the recorder's id, so several recorders sharing one
    sink file (a sweep's per-engine recorders, supervised restarts'
    fresh per-invocation recorders) ADD instead of last-wins, like
    scoped metric snapshots.  Shared by the trainer, the halo driver,
    and the serving engine (``sink`` is duck-typed: anything with
    ``.enabled``/``.emit``)."""
    if not getattr(sink, "enabled", False):
        return
    host = getattr(sink, "host", 0) or 0
    for name, ph in sorted(recorder.phase_totals().items()):
        sink.emit("trace/phase", phase=name, host=host,
                  scope=recorder.id,
                  seconds=round(ph.seconds, 6), count=ph.count,
                  max_s=round(ph.max_s, 6))


@contextlib.contextmanager
def file_flight_data(sink, recorder: FlightRecorder):
    """Guarantee a failed invocation still files its flight data: when
    the body raises (preemption, an injected CommError, a genuine
    crash), close the recorder's in-flight spans — a chunk that was
    mid-step when the run died still counts its partial wall — then
    emit the cumulative ``trace/phase`` totals and flush the sink's
    buffered tail before re-raising.  The happy path files nothing;
    callers emit their totals at the natural end-of-run point.  THE
    shared failure-path block of the trainer and the halo driver."""
    try:
        yield recorder
    except BaseException:
        recorder.close_open_spans()
        emit_phase_totals(sink, recorder)
        sink.flush()
        raise


def fold_phase_events(
    events: Iterable[Mapping],
) -> dict[str, dict[int, float]]:
    """``{phase: {host: cumulative seconds}}`` from loaded ``trace/phase``
    event dicts — THE fold both readers share (``obs.report.stragglers``
    and the goodput straggler-wait carve-out must agree on the same
    artifact).  Cumulative semantics, mirroring scoped metric snapshots:
    the newest event per (file, host, scope, phase) wins (a recorder
    re-emits growing totals), the same (host, scope, phase) seen in
    several files keeps the larger total (a duplicated artifact must not
    double-count), and DISTINCT scopes — different recorders: a sweep's
    per-engine ones, supervised restarts' fresh ones — add, so one host
    running several instrumented components is still one host with all
    its work counted."""
    latest: dict[tuple, float] = {}
    for rec in events:
        if rec.get("event") != "trace/phase":
            continue
        secs = rec.get("seconds")
        if isinstance(secs, bool) or not isinstance(secs, (int, float)) \
                or not math.isfinite(secs):
            continue
        key = (rec.get("_file"), rec.get("host", 0), rec.get("scope"),
               rec.get("phase"))
        latest[key] = float(secs)
    by_scope: dict[tuple, float] = {}
    for (_file, host, scope, phase), secs in latest.items():
        k = (host, scope, phase)
        by_scope[k] = max(by_scope.get(k, 0.0), secs)
    per_phase: dict[str, dict[int, float]] = {}
    for (host, _scope, phase), secs in by_scope.items():
        cur = per_phase.setdefault(phase, {})
        cur[host] = cur.get(host, 0.0) + secs
    return per_phase


def span_stamps(recorder: FlightRecorder,
                name: str) -> tuple[list[float], list[float]]:
    """(begins, ends) of every closed ``name`` span in the ring — the
    per-rank stamp lists ``obs.metrics.mesh_span`` merges with the
    max-min convention."""
    begins, ends = [], []
    for ev in recorder.events():
        if isinstance(ev, SpanEvent) and ev.name == name \
                and ev.end is not None:
            begins.append(ev.begin)
            ends.append(ev.end)
    return begins, ends


# ---- straggler detection ----------------------------------------------


@dataclasses.dataclass(frozen=True)
class StragglerReport:
    """One phase's cross-host skew: who was slowest, by how much."""

    phase: str
    slowest: int      # host / mesh-position index
    fastest: int
    max_s: float
    min_s: float

    @property
    def skew(self) -> float:
        """slowest / fastest time ratio (inf when the fastest is 0)."""
        if self.min_s <= 0:
            return math.inf if self.max_s > 0 else 1.0
        return self.max_s / self.min_s

    def summary(self) -> str:
        return (
            f"{self.phase}: host {self.slowest} slowest "
            f"({self.max_s * 1e3:.3f} ms vs host {self.fastest} "
            f"{self.min_s * 1e3:.3f} ms, skew {self.skew:.2f}x)"
        )


def detect_stragglers(
    per_host: Mapping[str, Mapping[int, float]],
    min_skew: float = 1.2,
) -> list[StragglerReport]:
    """Pure host-side straggler scan: ``{phase: {host: seconds}}`` →
    one report per phase whose max/min ratio reaches ``min_skew``
    (phases seen on < 2 hosts carry no skew signal and are skipped).
    The ``merge_snapshots`` twin of :func:`mesh_straggler`."""
    out = []
    for phase, hosts in sorted(per_host.items()):
        if len(hosts) < 2:
            continue
        slowest = max(hosts, key=lambda h: hosts[h])
        fastest = min(hosts, key=lambda h: hosts[h])
        rep = StragglerReport(phase, slowest, fastest,
                              float(hosts[slowest]), float(hosts[fastest]))
        if rep.skew >= min_skew:
            out.append(rep)
    return out


def mesh_straggler(mesh, phase: str,
                   per_rank_seconds: Sequence[float]) -> StragglerReport:
    """Per-phase skew THROUGH the mesh collectives: one ``mesh_reduce``
    finds max/min seconds device-side (the mpicuda3 gather), a second
    runs the MAXLOC/MINLOC trick — each rank contributes its index only
    where its time ties the extremum — so the report NAMES the slow rank,
    not just the gap.  ``per_rank_seconds`` is row-major over the mesh
    positions (the ``mesh_reduce`` contract)."""
    from tpuscratch.obs.metrics import mesh_reduce

    secs = [float(s) for s in per_rank_seconds]
    red = mesh_reduce(mesh, [[s, -s] for s in secs], ops=("max",))["max"]
    max_s, min_s = float(red[0]), -float(red[1])
    # f32 device round trip: ties need a tolerance proportional to scale
    tol = max(1e-6, 1e-4 * abs(max_s))
    loc_rows = [
        [i if s >= max_s - tol else -1, i if s <= min_s + tol else -1]
        for i, s in enumerate(secs)
    ]
    loc = mesh_reduce(mesh, loc_rows, ops=("max",))["max"]
    return StragglerReport(phase, slowest=int(loc[0]), fastest=int(loc[1]),
                           max_s=max_s, min_s=min_s)
