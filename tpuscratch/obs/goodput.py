"""Goodput / badput accounting and MFU over a run's JSONL event stream.

The ROADMAP north star is "as fast as the hardware allows"; the two
numbers that make that claim auditable are **MFU** (model FLOPs actually
retired per second over the chip's peak — the PaLM convention) and the
**goodput fraction** (what share of wall time was spent computing
committed steps, vs the badput taxonomy a production run bleeds into:
compile, checkpoint IO, rollback replay, restart backoff, straggler
wait).  This module computes both from the artifact alone — the per-host
JSONL stream every instrumented layer already writes — plus the static
``obs.ledger`` FLOP count for the MFU numerator.

Accounting contract (the part a report must PROVE, not eyeball): every
event that carries a duration is emitted at the END of its activity, so
``[t - duration, t]`` is an interval on the sink's clock.  The report
lays all attributed intervals on the ``[first event, last event]``
window, clips overlaps (earliest claim wins), scales down in the
(measurement-slop) case where attributions exceed the window, and calls
the remainder ``other`` — so the buckets **sum to the wall time
exactly, by construction**.  ``straggler_wait`` is carved out of
``other`` from the cross-host ``trace/phase`` skew when per-host data
exists (a fast host's idle time hides in its unattributed wall).

Duration sources (event kind → field → bucket):

==============  ============  ==========
train/chunk     chunk_s       step  (its ``compile_s`` share → compile)
halo/chunk      wall_s        step  (its ``compile_s`` share → compile)
solver/chunk    wall_s        step  (its ``compile_s`` share → compile)
serve/tick      tick_s        step  (compile-ticked ticks → compile)
ckpt/save       wall_s        checkpoint
ckpt/snapshot   wall_s        checkpoint
ft/rollback     lost_s        rollback
ft/restart      backoff_s     restart
==============  ============  ==========

The async-checkpoint split (``runtime.async_ckpt``): ``ckpt/snapshot``
is the BLOCKING cost the step loop actually paid — the device→host
copy plus, crucially, the barrier drain of a still-running previous
write (the snapshot bracket opens before the drain), so a write too
slow to hide behind the next chunk books here automatically.
``ckpt/write`` is deliberately NOT an interval in the partition: it
runs on a background thread CONCURRENTLY with whatever the loop does
next, so its wall is not the loop's wall — counting it would book time
the run never lost.  The event exists for visibility (count, wall, the
config-16 write totals); the partition sees the async path only
through what it blocked.

Compile detection is per layer: the trainer brackets each step and sums
the walls of steps whose ``CompileCounter`` ticked into ``compile_s``;
the halo driver stamps a chunk whose program was freshly built (the jit
compile fires inside that chunk's first call, so the bracket is
compile-dominated — the same convention at chunk granularity); a
``serve/tick`` whose cumulative ``decode_compiles``/``prefill_compiles``
counters moved books its whole ``tick_s`` to compile (the engine's
zero-steady-state-recompile contract makes such ticks rare and
compile-dominated).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

__all__ = ["BUCKETS", "GoodputReport", "WorkloadGoodput", "by_workload",
           "goodput_report"]

#: the wall-time partition, in report order.  ``step`` is the goodput
#: bucket; everything else is badput (``other`` = unattributed host
#: time: setup, dispatch, readback, restart re-init).
BUCKETS = ("step", "compile", "checkpoint", "rollback", "restart",
           "straggler_wait", "other")

#: event kind -> (duration field, bucket)
_DURATION_EVENTS = {
    "train/chunk": ("chunk_s", "step"),
    "halo/chunk": ("wall_s", "step"),
    "solver/chunk": ("wall_s", "step"),
    "serve/tick": ("tick_s", "step"),
    "ckpt/save": ("wall_s", "checkpoint"),
    "ckpt/snapshot": ("wall_s", "checkpoint"),
    "ft/rollback": ("lost_s", "rollback"),
    "ft/restart": ("backoff_s", "restart"),
}


@dataclasses.dataclass(frozen=True)
class GoodputReport:
    """The answer to "what did the wall time buy".

    ``buckets`` partitions ``wall_s`` (multi-host streams sum to
    host-seconds): ``sum(buckets.values()) == wall_s`` exactly.  ``mfu``
    / ``model_flops_per_s`` are set when the caller supplied the FLOP
    side (``flops_per_step`` or ``flops_per_token`` from the ledger, and
    a peak for the fraction)."""

    wall_s: float
    buckets: dict[str, float]
    steps: int
    tokens: int
    mfu: Optional[float] = None
    model_flops_per_s: Optional[float] = None

    @property
    def goodput_fraction(self) -> float:
        return self.buckets["step"] / self.wall_s if self.wall_s else 0.0

    @property
    def badput(self) -> dict[str, float]:
        """The non-goodput buckets, nonzero ones only."""
        return {k: v for k, v in self.buckets.items()
                if k != "step" and v > 0}

    def check(self, tol: float = 1e-6) -> None:
        """Assert the partition invariant (tests call this; it should
        never fire — the construction guarantees it)."""
        total = sum(self.buckets.values())
        if abs(total - self.wall_s) > tol * max(1.0, self.wall_s):
            raise AssertionError(
                f"buckets sum {total} != wall {self.wall_s}"
            )

    def summary(self) -> str:
        lines = [
            f"wall {self.wall_s:.3f} s: goodput "
            f"{100 * self.goodput_fraction:.1f}% "
            f"({self.steps} steps, {self.tokens} tokens)"
        ]
        if self.mfu is not None:
            lines[0] += f", MFU {100 * self.mfu:.2f}%"
        elif self.model_flops_per_s is not None:
            lines[0] += f", {self.model_flops_per_s / 1e12:.3f} TFLOP/s model"
        for k in BUCKETS:
            v = self.buckets.get(k, 0.0)
            if v <= 0 and k != "step":
                continue
            share = 100 * v / self.wall_s if self.wall_s else 0.0
            lines.append(f"  {k:<15} {v:9.3f} s  {share:5.1f}%")
        return "\n".join(lines)


def _num(rec: dict, key: str) -> Optional[float]:
    v = rec.get(key)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if not math.isfinite(v):
        return None
    return float(v)


def _account_group(events: Sequence[dict]) -> tuple[float, dict, int, int]:
    """One host file's partition: (wall, buckets, steps, tokens)."""
    ts = [t for t in (_num(r, "t") for r in events) if t is not None]
    if not ts:
        return 0.0, {k: 0.0 for k in BUCKETS}, 0, 0
    t0, t1 = min(ts), max(ts)
    wall = t1 - t0
    # attributed intervals: (start, end, {bucket: seconds})
    intervals = []
    steps = tokens = 0
    seen_cc: Optional[float] = None  # last cumulative serve compile count
    for rec in events:
        kind = rec.get("event")
        src = _DURATION_EVENTS.get(kind)
        if src is None:
            continue
        field, bucket = src
        dur = _num(rec, field)
        end = _num(rec, "t")
        if dur is None or end is None or dur <= 0:
            continue
        start = max(t0, end - dur)
        parts = {bucket: end - start}
        if kind in ("train/chunk", "halo/chunk", "solver/chunk"):
            comp = _num(rec, "compile_s") or 0.0
            comp = min(comp, parts["step"])
            if comp > 0:
                parts = {"step": parts["step"] - comp, "compile": comp}
        elif kind == "serve/tick":
            # the tick events carry CUMULATIVE compile counters; a tick
            # where they moved is a compile-dominated bracket (any
            # change counts: a fresh engine in the same file resets
            # the cumulative counts downward and recompiles)
            cc = ((_num(rec, "decode_compiles") or 0.0)
                  + (_num(rec, "prefill_compiles") or 0.0))
            ticked = cc > 0 if seen_cc is None else cc != seen_cc
            seen_cc = cc
            if ticked:
                parts = {"compile": parts.pop("step")}
        if kind == "train/chunk":
            steps += int(_num(rec, "steps") or 0)
            tk = _num(rec, "tokens")
            if tk is None:
                rate, cs = _num(rec, "tokens_per_s"), _num(rec, "chunk_s")
                tk = rate * cs if rate is not None and cs is not None else 0
            tokens += int(tk)
        intervals.append((start, end, parts))
    # sweep: clip overlaps (earliest claim wins) so attributed <= wall
    intervals.sort(key=lambda iv: iv[0])
    buckets = {k: 0.0 for k in BUCKETS}
    cursor = t0
    for start, end, parts in intervals:
        s = max(start, cursor)
        e = min(end, t1)
        if e <= s:
            continue
        frac = (e - s) / (end - start)
        for b, v in parts.items():
            buckets[b] += v * frac
        cursor = max(cursor, e)
    attributed = sum(buckets.values())
    if attributed > wall > 0:
        # durations can overhang the event window by measurement slop;
        # scale down so the partition stays exact
        scale = wall / attributed
        buckets = {k: v * scale for k, v in buckets.items()}
        attributed = wall
    buckets["other"] = wall - attributed
    return wall, buckets, steps, tokens


def _straggler_wait(events: Sequence[dict]) -> float:
    """Cross-host idle time from ``trace/phase`` events: per phase, the
    fast hosts' shortfall against the slowest (the time they spent
    waiting at the collective).  The cumulative-event fold is
    ``obs.trace.fold_phase_events`` — the same one the
    ``obs.report.stragglers`` table reads, so the bucket and the table
    always agree on one artifact."""
    from tpuscratch.obs.trace import fold_phase_events

    per_phase = fold_phase_events(events)
    wait = 0.0
    for hosts in per_phase.values():
        if len(hosts) < 2:
            continue
        slowest = max(hosts.values())
        wait += sum(slowest - v for v in hosts.values())
    return wait


def goodput_report(
    events: Sequence[dict],
    *,
    wall_s: Optional[float] = None,
    flops_per_step: Optional[float] = None,
    flops_per_token: Optional[float] = None,
    peak_flops_per_s: Optional[float] = None,
) -> GoodputReport:
    """Build a :class:`GoodputReport` from a loaded event stream
    (``obs.report.load_events`` output, or any list of event dicts).

    Events are grouped per source file (``_file``, present when loaded
    through ``load_events``; absent ⇒ one group) AND per sink session
    within the file — every ``run`` metadata event after the first marks
    a reopened sink with a fresh clock (a crashed run resumed by a new
    process appends to the same path), so each session's timestamps are
    only compared with themselves; session walls and buckets sum.
    ``wall_s`` overrides the measured window (single-group streams only
    — e.g. an external fence around the run); the ``other`` bucket
    absorbs the difference so the partition stays exact.

    MFU: ``flops_per_step`` (the ledger's ``analyze(step).flops``) ×
    committed steps, or ``flops_per_token`` × tokens, over ``wall_s`` —
    and over ``peak_flops_per_s`` for the fraction."""
    groups: dict = {}
    seen: dict = {}     # file -> events seen (any kind)
    session: dict = {}  # file -> current sink-session ordinal
    for rec in events:
        f = rec.get("_file")
        if rec.get("event") == "run" and seen.get(f):
            # a reopened sink: its "run" header restarts the clock, so
            # this file's subsequent events are a NEW accounting window
            session[f] = session.get(f, 0) + 1
        seen[f] = seen.get(f, 0) + 1
        groups.setdefault((f, session.get(f, 0)), []).append(rec)
    wall = 0.0
    buckets = {k: 0.0 for k in BUCKETS}
    steps = tokens = 0
    for recs in groups.values():
        w, b, s, t = _account_group(recs)
        wall += w
        for k, v in b.items():
            buckets[k] += v
        steps += s
        tokens += t
    if wall_s is not None:
        if len(groups) > 1:
            raise ValueError(
                "wall_s override only applies to a single-host, "
                f"single-session stream (got {len(groups)} groups)"
            )
        buckets["other"] += wall_s - wall
        if buckets["other"] < 0:
            # the external fence was shorter than the stream window —
            # trust the stream, which is what the buckets partition
            buckets["other"] = 0.0
            wall_s = sum(buckets.values())
        wall = wall_s
    # straggler wait is already inside somebody's unattributed time:
    # carve it from ``other`` so the partition stays a partition
    sw = min(_straggler_wait(events), buckets["other"])
    buckets["straggler_wait"] = sw
    buckets["other"] -= sw
    total_flops = None
    if flops_per_step is not None:
        total_flops = flops_per_step * steps
    elif flops_per_token is not None:
        total_flops = flops_per_token * tokens
    rate = total_flops / wall if total_flops is not None and wall > 0 else None
    mfu = (rate / peak_flops_per_s
           if rate is not None and peak_flops_per_s else None)
    return GoodputReport(wall_s=wall, buckets=buckets, steps=steps,
                         tokens=tokens, mfu=mfu, model_flops_per_s=rate)


# ---- per-workload partitioning (the co-scheduled stream) ----------------


@dataclasses.dataclass(frozen=True)
class WorkloadGoodput:
    """One co-scheduled stream split into per-workload reports.

    ``wall_s`` is the scheduler's arbitration window (first
    ``sched/switch`` to ``sched/run``); the per-workload ``reports``
    each account only that workload's OWN time slices, so their walls
    partition ``wall_s`` exactly — :meth:`check` asserts it, plus each
    report's own bucket invariant.  Switch overhead books to the
    INCOMING workload's slice (``sched/switch`` is stamped before the
    tick), where it lands in ``other``."""

    wall_s: float
    reports: dict[str, GoodputReport]
    switches: int
    slices: dict[str, int]   # workload -> number of scheduling slices
    targets: Optional[dict] = None

    @property
    def shares(self) -> dict[str, float]:
        """Each workload's fraction of the scheduler wall."""
        return {k: (r.wall_s / self.wall_s if self.wall_s else 0.0)
                for k, r in self.reports.items()}

    def check(self, tol: float = 1e-6) -> None:
        """Assert the two-level partition: every per-workload report's
        buckets sum to its wall, and the walls sum to the scheduler
        wall."""
        for rep in self.reports.values():
            rep.check(tol)
        total = math.fsum(r.wall_s for r in self.reports.values())
        if abs(total - self.wall_s) > tol * max(1.0, self.wall_s):
            raise AssertionError(
                f"per-workload walls sum {total} != scheduler wall "
                f"{self.wall_s}"
            )

    def table(self) -> list[dict]:
        """The arbitration table: one row per workload — slices, wall,
        achieved share (vs the policy ``target`` and its ``share_err``
        when targets are known), goodput fraction."""
        shares = self.shares
        rows = []
        for name, rep in self.reports.items():
            row = {
                "workload": name,
                "slices": self.slices.get(name, 0),
                "wall_s": round(rep.wall_s, 6),
                "share": round(shares[name], 6),
                "goodput_fraction": round(rep.goodput_fraction, 6),
            }
            if self.targets and name in self.targets:
                row["target"] = round(float(self.targets[name]), 6)
                row["share_err"] = round(
                    abs(shares[name] - float(self.targets[name])), 6)
            rows.append(row)
        return rows

    def summary(self) -> str:
        lines = [f"scheduler wall {self.wall_s:.3f} s, "
                 f"{self.switches} switch(es)"]
        for row in self.table():
            line = (f"  {row['workload']:<10} {row['slices']:3d} slices  "
                    f"wall {row['wall_s']:8.3f} s  "
                    f"share {100 * row['share']:5.1f}%  "
                    f"goodput {100 * row['goodput_fraction']:5.1f}%")
            if "target" in row:
                line += (f"  target {100 * row['target']:5.1f}% "
                         f"(err {100 * row['share_err']:.1f}pt)")
            lines.append(line)
        return "\n".join(lines)


def _parse_intervals(events: Sequence[dict]):
    """``_account_group``'s per-event parse with the start stamp
    UNCLAMPED (slice clipping owns the window): returns
    ``([(start, end, parts, dur)], steps, tokens)``."""
    intervals = []
    steps = tokens = 0
    seen_cc: Optional[float] = None
    for rec in events:
        kind = rec.get("event")
        src = _DURATION_EVENTS.get(kind)
        if src is None:
            continue
        field, bucket = src
        dur = _num(rec, field)
        end = _num(rec, "t")
        if dur is None or end is None or dur <= 0:
            continue
        parts = {bucket: dur}
        if kind in ("train/chunk", "halo/chunk", "solver/chunk"):
            comp = _num(rec, "compile_s") or 0.0
            comp = min(comp, parts["step"])
            if comp > 0:
                parts = {"step": parts["step"] - comp, "compile": comp}
        elif kind == "serve/tick":
            cc = ((_num(rec, "decode_compiles") or 0.0)
                  + (_num(rec, "prefill_compiles") or 0.0))
            ticked = cc > 0 if seen_cc is None else cc != seen_cc
            seen_cc = cc
            if ticked:
                parts = {"compile": parts.pop("step")}
        if kind == "train/chunk":
            steps += int(_num(rec, "steps") or 0)
            tk = _num(rec, "tokens")
            if tk is None:
                rate, cs = _num(rec, "tokens_per_s"), _num(rec, "chunk_s")
                tk = rate * cs if rate is not None and cs is not None else 0
            tokens += int(tk)
        intervals.append((end - dur, end, parts, dur))
    return intervals, steps, tokens


def _account_slices(events: Sequence[dict],
                    slices: Sequence[tuple[float, float]]) -> GoodputReport:
    """One workload's report over ITS scheduling slices: every
    attributed interval is clipped to the slices (an interval spilling
    over a switch boundary only books the part inside — the rest of
    that wall belongs to whoever held the mesh), overlaps clipped
    earliest-claim-first, the remainder ``other`` — buckets sum to the
    slice wall exactly, by the same construction as the whole-stream
    report."""
    wall = math.fsum(e - s for s, e in slices)
    intervals, steps, tokens = _parse_intervals(events)
    pieces = []
    for start, end, parts, dur in intervals:
        for s, e in slices:
            cs, ce = max(start, s), min(end, e)
            if ce > cs:
                pieces.append((cs, ce, parts, dur))
    pieces.sort(key=lambda p: p[0])
    buckets = {k: 0.0 for k in BUCKETS}
    cursor = None
    for cs, ce, parts, dur in pieces:
        s = cs if cursor is None else max(cs, cursor)
        if ce <= s:
            continue
        frac = (ce - s) / dur
        for b, v in parts.items():
            buckets[b] += v * frac
        cursor = ce if cursor is None else max(cursor, ce)
    attributed = sum(buckets.values())
    if attributed > wall > 0:
        scale = wall / attributed
        buckets = {k: v * scale for k, v in buckets.items()}
        attributed = wall
    buckets["other"] = max(wall - attributed, 0.0)
    return GoodputReport(wall_s=wall, buckets=buckets, steps=steps,
                         tokens=tokens)


def by_workload(events: Sequence[dict], *,
                targets: Optional[dict] = None) -> WorkloadGoodput:
    """Split one (co-scheduled) event stream into per-workload goodput
    reports, keyed on the ``workload=`` tag
    ``runtime.chunked.WorkloadSink`` stamps.

    With ``sched/switch`` events present, the scheduler's arbitration
    window [first switch, ``sched/run``] is cut into slices — each
    switch opens the named workload's slice, closed by the next switch
    — and every workload is accounted ONLY inside its own slices, so
    the per-workload walls partition the scheduler wall exactly
    (:meth:`WorkloadGoodput.check`).  Without switches (solo or
    back-to-back runs in one stream), each workload accounts its own
    event window and the walls sum.  ``targets`` (workload -> intended
    share) defaults to the ``sched/run`` event's ``targets`` field when
    the policy published one; it feeds the ``table()`` ``share_err``
    column."""
    sw = [r for r in events
          if r.get("event") == "sched/switch"
          and _num(r, "t") is not None and isinstance(r.get("workload"), str)]
    sw.sort(key=lambda r: _num(r, "t"))
    runs = [r for r in events if r.get("event") == "sched/run"]
    run_ev = runs[-1] if runs else None
    if targets is None and run_ev is not None:
        tg = run_ev.get("targets")
        if isinstance(tg, dict):
            targets = {str(k): float(v) for k, v in tg.items()}
    if not sw:
        # no arbitration in the stream: account each workload over its
        # own window (the back-to-back solo baseline)
        names: list[str] = []
        for rec in events:
            w = rec.get("workload")
            if isinstance(w, str) and w not in names:
                names.append(w)
        reports = {}
        for name in names:
            w_, b, s, t = _account_group(
                [r for r in events if r.get("workload") == name])
            reports[name] = GoodputReport(wall_s=w_, buckets=b, steps=s,
                                          tokens=t)
        wall = math.fsum(r.wall_s for r in reports.values())
        return WorkloadGoodput(wall_s=wall, reports=reports, switches=0,
                               slices={k: 1 for k in reports},
                               targets=targets)
    end = _num(run_ev, "t") if run_ev is not None else None
    if end is None:
        ts = [t for t in (_num(r, "t") for r in events) if t is not None]
        end = max(ts)
    bounds = [_num(r, "t") for r in sw]
    bounds.append(max(end, bounds[-1]))
    slices: dict[str, list[tuple[float, float]]] = {}
    for i, rec in enumerate(sw):
        s, e = bounds[i], bounds[i + 1]
        if e > s:
            slices.setdefault(rec["workload"], []).append((s, e))
    reports = {}
    nslices = {}
    for name, sl in slices.items():
        reports[name] = _account_slices(
            [r for r in events if r.get("workload") == name], sl)
        nslices[name] = len(sl)
    switches = run_ev.get("switches") if run_ev is not None else None
    if not isinstance(switches, int) or isinstance(switches, bool):
        switches = max(len(sw) - 1, 0)
    return WorkloadGoodput(wall_s=bounds[-1] - bounds[0], reports=reports,
                           switches=switches, slices=nslices,
                           targets=targets)


#: the classmethod-style spelling the satellite names:
#: ``GoodputReport.by_workload(events)``
GoodputReport.by_workload = staticmethod(by_workload)
