"""tpuscratch.obs — mesh-wide observability.

The reference instruments everything by hand: clock() spans gathered to
rank 0 under the max-min convention (mpicuda3.cu:176-179,315-325),
MPI_Wtime segment brackets separating network from copy
(mpi-pingpong-gpu.cpp:51-57), and explicit carve-outs for one-time setup
cost (NO_GPU_MALLOC_TIME, mpicuda3.cu:221-240).  This package is that
discipline grown into a subsystem, the operational loop production
training fleets run (per-step device metrics, communication accounting,
recompile detection):

- **metrics** — a low-overhead host-side registry of counters / gauges /
  histograms with mesh-aware cross-rank aggregation (reductions run
  through ``comm.collectives`` on the mesh itself) and the max-min span
  merge absorbed from ``runtime/profiling``; plus :class:`CompileCounter`,
  the zero-steady-state-recompile hook promoted out of ``serve/decode``.
- **ledger** — a static communication/compute ledger: walk a jitted
  program's compiled HLO and ``cost_analysis()`` to report per-collective
  counts and payload bytes, FLOPs and HBM traffic; analytic wire-byte
  formulas (ring all-reduce moves ``2*(n-1)/n * bytes``) and an
  achieved-fraction-of-roofline diff against measured span times.
- **sink** — a per-host JSONL event sink with run metadata; every
  instrumented layer (trainer, ServeEngine, halo drivers, benches)
  writes through it.
- **report** — ``python -m tpuscratch.obs.report run.jsonl`` collapses a
  run's JSONL into a summary table (including the per-phase straggler
  table when >= 2 hosts reported).
- **trace** — the always-on bounded flight recorder: begin/end spans and
  instants in a thread-safe ring, exported as Chrome trace-event JSON
  for Perfetto; ``runtime/profiling.Timeline`` delegates here (one span
  implementation), and per-phase skew through ``mesh_reduce`` names the
  slowest rank.
- **goodput** — MFU and the goodput/badput wall-time partition (compile,
  checkpoint, rollback replay, restart backoff, straggler wait) computed
  from the JSONL artifact plus the ledger's FLOPs; buckets sum to the
  wall time by construction.
- **regress** — ``python -m tpuscratch.obs.regress BASE.json NEW.json``
  diffs two ``bench/record`` artifacts against a noise band and exits
  nonzero on regression (also ``bench/record --check BASE.json``).
- **reqtrace** — fleet-wide per-request causal tracing: every lifecycle
  edge (submit, queue, shed, dispatch, prefill, handoff, decode
  occupancy, kill/evacuate/re-admit, finish) lands in one span tree per
  request, and each drained request's bucket decomposition sums to its
  end-to-end latency EXACTLY (``RequestTrace.check``); exports the tree
  as Perfetto flow-event JSON through the ``trace`` validator.
"""

from tpuscratch.obs.metrics import (  # noqa: F401
    CompileCounter,
    Counter,
    Gauge,
    Histogram,
    MeshSpan,
    MetricsRegistry,
    merge_snapshots,
    mesh_reduce,
    mesh_span,
    span_max_min,
)
from tpuscratch.obs.ledger import (  # noqa: F401
    CollectiveOp,
    Ledger,
    RooflineReport,
    all_gather_wire_bytes,
    all_to_all_wire_bytes,
    analyze,
    parse_collectives,
    reduce_scatter_wire_bytes,
    ring_all_reduce_wire_bytes,
    roofline,
)
from tpuscratch.obs.sink import NullSink, Sink, open_sink  # noqa: F401
from tpuscratch.obs.trace import (  # noqa: F401
    FlightRecorder,
    StragglerReport,
    detect_stragglers,
    emit_phase_totals,
    merge_chrome_traces,
    mesh_straggler,
    span_stamps,
    validate_chrome_trace,
)
from tpuscratch.obs.goodput import (  # noqa: F401
    BUCKETS,
    GoodputReport,
    goodput_report,
)
from tpuscratch.obs.reqtrace import (  # noqa: F401
    REQ_BUCKETS,
    NullReqTracer,
    ReqTracer,
    RequestTrace,
    rid_sampled,
)
