"""Static communication/compute ledger over compiled XLA programs.

The reference REASONS about its communication cost in comments and
derives bandwidth by hand from bytes it knows it sent
(mpi-pingpong-gpu.cpp:51-57); here the compiled program itself is the
source of truth.  ``analyze`` walks a jitted function's optimized HLO —
every ``all-reduce`` / ``all-gather`` / ``all-to-all`` /
``reduce-scatter`` / ``collective-permute`` the partitioner actually
emitted, with payload bytes from the instruction's result shape and the
participant count from its replica groups — plus XLA's
``cost_analysis()`` for FLOPs and bytes-accessed (HBM traffic).

Wire-byte accounting uses the standard analytic forms (validated
against known collectives in tests/test_obs_ledger.py):

- ring all-reduce moves ``2*(n-1)/n * payload`` per device
  (reduce-scatter pass + all-gather pass);
- all-gather ``(n-1)/n * result`` (each device receives all shards but
  its own);
- reduce-scatter ``(n-1) * shard`` (each device sends all but its own
  share of its input);
- all-to-all ``(n-1)/n * payload`` (everything except the self-block);
- collective-permute ``payload`` (one hop, whole buffer).

``roofline`` diffs the ledger against a MEASURED span time into an
achieved-fraction report: what share of peak FLOP/s, HBM bandwidth, and
link bandwidth the measured run reached, and which bound binds.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

__all__ = [
    "CollectiveOp",
    "GradSyncBytes",
    "KVHostTraffic",
    "Ledger",
    "RooflineReport",
    "all_gather_wire_bytes",
    "all_to_all_wire_bytes",
    "analyze",
    "grad_sync_wire_bytes",
    "kv_cache_bytes",
    "kv_host_traffic_bytes",
    "kv_page_bytes",
    "parse_collectives",
    "reduce_scatter_wire_bytes",
    "ring_all_reduce_wire_bytes",
    "roofline",
]

#: bytes per element for HLO shape dtypes
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]+)\[([0-9,]*)\]")

#: one collective instruction: ``%name = <shape(s)> <op>(...)`` — the
#: async ``-start`` spelling counts once, its ``-done`` not at all
_COLL_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<kind>all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute)(?P<start>-start)?\("
)

_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[\s*\d+\s*,\s*(\d+)\s*\]")
_PAIR_RE = re.compile(r"\{\d+\s*,\s*\d+\}")


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective instruction in the compiled program."""

    kind: str          # all-reduce | all-gather | all-to-all |
    #                    reduce-scatter | collective-permute
    payload_bytes: int  # result payload (tuple results summed)
    group_size: int    # ranks per replica group (pair count for permute)

    @property
    def wire_bytes(self) -> float:
        """Analytic per-device wire traffic for this op (see module
        docstring for the formulas and what each payload refers to)."""
        n, b = self.group_size, self.payload_bytes
        if n <= 1:
            return 0.0
        if self.kind == "all-reduce":
            return ring_all_reduce_wire_bytes(n, b)
        if self.kind == "all-gather":
            # result is the FULL gathered array: n shards of b/n each
            return all_gather_wire_bytes(n, b // n)
        if self.kind == "reduce-scatter":
            # result is one shard
            return reduce_scatter_wire_bytes(n, b)
        if self.kind == "all-to-all":
            return all_to_all_wire_bytes(n, b)
        return float(b)  # collective-permute: one hop, whole buffer


def ring_all_reduce_wire_bytes(n: int, payload: int) -> float:
    """Ring all-reduce per-device traffic: ``2*(n-1)/n * payload``
    (a reduce-scatter pass then an all-gather pass, each moving
    ``(n-1)/n`` of the buffer)."""
    return 2.0 * (n - 1) / n * payload


def all_gather_wire_bytes(n: int, shard_bytes: int) -> float:
    """All-gather per-device traffic: ``(n-1) * shard`` (receive every
    shard but your own)."""
    return float((n - 1) * shard_bytes)


def reduce_scatter_wire_bytes(n: int, shard_bytes: int) -> float:
    """Reduce-scatter per-device traffic: ``(n-1) * shard`` (send all
    but your own share)."""
    return float((n - 1) * shard_bytes)


def all_to_all_wire_bytes(n: int, payload: int) -> float:
    """All-to-all per-device traffic: ``(n-1)/n * payload`` (every block
    except the one staying home)."""
    return (n - 1) / n * payload


def _data_shapes(token: str) -> list[int]:
    """Byte sizes of every non-scalar data shape in an HLO shape token
    (``f32[4,8]{1,0}`` or a tuple); layouts ignored, scalar shapes
    dropped (async ops carry ``u32[]`` context scalars that are not
    payload)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(token):
        if dtype not in _DTYPE_BYTES or not dims:
            continue  # token-shaped operand or context scalar: not data
        elems = 1
        for d in dims.split(","):
            elems *= int(d)
        sizes.append(elems * _DTYPE_BYTES[dtype])
    return sizes


def _payload_bytes(kind: str, start: bool, token: str) -> int:
    """Result-payload bytes of one collective instruction.

    Sync spellings SUM the result shapes: a plain shape is its own sum,
    all-to-all tuples are per-peer pieces, and combined variadic
    collectives (XLA's AllReduceCombiner fusing many gradient psums into
    one instruction) are the concatenation of their operands' results.
    Async ``-start`` spellings return ``(operands..., results...,
    contexts...)``; the result is recovered per kind: the largest buffer
    for all-gather (result = n x operand) and the equal-shaped
    all-reduce / collective-permute, the smallest for reduce-scatter
    (result = operand / n), half the data total for all-to-all (operand
    halves mirror result halves)."""
    sizes = _data_shapes(token)
    if not sizes:
        return 0
    if not start:
        return sum(sizes)
    if kind == "reduce-scatter":
        return min(sizes)
    if kind == "all-to-all":
        return sum(sizes) // 2
    return max(sizes)


def parse_collectives(hlo_text: str) -> tuple[CollectiveOp, ...]:
    """Every collective instruction in optimized-HLO text, in program
    order.  Handles sync and async (``-start``/``-done``) spellings —
    a ``-start``'s tuple result carries operand AND result buffers, so
    the payload is recovered per kind (see :func:`_payload_bytes`)
    rather than summed — and both replica-group formats (explicit
    ``{{0,1},{2,3}}`` and iota ``[groups,size]<=[n]``)."""
    ops = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        kind = m.group("kind")
        if kind == "collective-permute":
            _, _, tail = line.partition("source_target_pairs=")
            group = len(_PAIR_RE.findall(tail)) or 1
        else:
            g = _GROUPS_RE.search(line)
            if g is not None:
                group = len(g.group(1).split(","))
            else:
                gi = _GROUPS_IOTA_RE.search(line)
                group = int(gi.group(1)) if gi else 1
        ops.append(
            CollectiveOp(
                kind=kind,
                payload_bytes=_payload_bytes(
                    kind, m.group("start") is not None, m.group("shape")
                ),
                group_size=group,
            )
        )
    return tuple(ops)


@dataclasses.dataclass(frozen=True)
class Ledger:
    """What one compiled program does, statically: its collectives, and
    XLA's per-execution cost model (flops / bytes accessed are
    ``cost_analysis()`` numbers; absent keys come through as 0.0)."""

    collectives: tuple[CollectiveOp, ...]
    flops: float
    bytes_accessed: float

    def counts(self) -> dict[str, int]:
        """{collective kind: instruction count}."""
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def count(self, kind: str) -> int:
        """Instruction count of ONE collective kind (0 when absent) —
        the solver proofs' working form: a ``lax.while_loop`` body
        appears exactly once in the optimized HLO, so a solver whose
        iteration loop is a while_loop exposes its per-iteration
        collective budget statically (pipelined CG's one-psum claim and
        the s-step smoother's exchange count are asserted through
        this, the way ``grad_sync_wire_bytes`` pinned the ZeRO leg)."""
        return self.counts().get(kind, 0)

    def payload_bytes(self) -> dict[str, int]:
        """{collective kind: summed result-payload bytes}."""
        out: dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + op.payload_bytes
        return out

    def wire_bytes(self) -> dict[str, float]:
        """{collective kind: summed analytic per-device wire bytes}."""
        out: dict[str, float] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0.0) + op.wire_bytes
        return out

    def total_wire_bytes(self) -> float:
        return sum(op.wire_bytes for op in self.collectives)

    def summary(self) -> str:
        lines = [
            f"flops/exec: {self.flops:.3e}   "
            f"bytes accessed: {self.bytes_accessed:.3e}"
        ]
        counts, wire = self.counts(), self.wire_bytes()
        for kind in sorted(counts):
            lines.append(
                f"{kind}: {counts[kind]} op(s), "
                f"payload {self.payload_bytes()[kind]} B, "
                f"wire ~{wire[kind]:.0f} B/device"
            )
        if not counts:
            lines.append("no collectives")
        return "\n".join(lines)


@dataclasses.dataclass(frozen=True)
class GradSyncBytes:
    """Per-device wire bytes of one train step's parameter/gradient
    synchronization, split by collective kind (attention/MoE traffic —
    ppermute, all-to-all — is deliberately excluded; those move
    activations, not gradients):

    - ``all_reduce``: every reducing all-reduce — the replicated path's
      full gradient sync, plus the sp-copy psums and scalar loss pmeans
      both paths share (scalar ops contribute ~0);
    - ``reduce_scatter``: the ZeRO path's gradient sync — each rank
      receives only its ``1/|dp|`` shard;
    - ``all_gather``: the ZeRO path's trailing param gather (rebuilding
      replicated params from updated shards).

    ``grad`` (all_reduce + reduce_scatter) is the gradient-reduction
    leg — the quantity the ≤ 0.55x regression guard watches: a ZeRO
    step that reintroduces a full gradient all-reduce doubles it.
    ``total`` adds the trailing all-gather — the whole sync cost of one
    update, which gradient accumulation (``accum_steps=k``) pays once
    per k microbatches instead of per microbatch."""

    all_reduce: float
    reduce_scatter: float
    all_gather: float

    @property
    def grad(self) -> float:
        return self.all_reduce + self.reduce_scatter

    @property
    def total(self) -> float:
        return self.grad + self.all_gather

    def per_microbatch(self, accum_steps: int = 1) -> float:
        """Sync bytes amortized per microbatch under deferred-sync
        accumulation: the one reduce-scatter + all-gather is paid once
        per ``accum_steps`` microbatches."""
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        return self.total / accum_steps


def grad_sync_wire_bytes(ledger: "Ledger") -> GradSyncBytes:
    """The gradient-synchronization slice of a train-step ledger: summed
    analytic wire bytes of its all-reduce, reduce-scatter, and
    all-gather instructions (see :class:`GradSyncBytes` for what each
    leg means).  Validated exactly against the ``(n-1)*shard`` /
    ``(n-1)/n*result`` formulas for the ZeRO step in
    tests/test_zero.py."""
    wire = ledger.wire_bytes()
    return GradSyncBytes(
        all_reduce=wire.get("all-reduce", 0.0),
        reduce_scatter=wire.get("reduce-scatter", 0.0),
        all_gather=wire.get("all-gather", 0.0),
    )


def kv_cache_bytes(cache) -> int:
    """Total buffer bytes of a serve KV-cache pytree
    (``serve.kvcache.init_kv_cache`` output — page pools plus, for int8
    pools, the per-page per-head scale planes).

    This is the static half of the quantized-KV claim, the same proof
    pattern as :func:`grad_sync_wire_bytes` for the ZeRO 0.5x
    gradient-leg: decode gathers the whole cached prefix per token, so
    cache bytes ARE its HBM/wire roofline, and int8 pages land at
    ``1/4 + 1/(page_size * d_head)`` of the fp32 bytes regardless of
    measurement noise — pinned ≤ 0.55x by a regression test
    (tests/test_serve.py) at the record-config-12 geometry."""
    leaves = cache.values() if hasattr(cache, "values") else cache
    return int(sum(leaf.size * leaf.dtype.itemsize for leaf in leaves))


def kv_page_bytes(cache) -> float:
    """Exact bytes ONE logical page drags across the memory tiers: the
    K and V page blocks of every layer plus, on the quantized rungs,
    their per-page per-head scale rows — ``kv_cache_bytes`` divided
    down the pages axis (every cache leaf carries pages on axis 1, so
    the division is exact, not approximate).

    Analytic form at geometry (L layers, page ``p`` tokens, H heads,
    d_head D, element size ``e``): ``L * (2*p*H*D*e + 2*H*4[quantized])``
    — validated against this function in tests/test_serve_tiered.py,
    and pinned equal to ``serve.kvcache.HostPageStore.page_nbytes`` so
    static traffic accounting and actual host-buffer footprint can
    never drift apart."""
    leaves = cache.values() if hasattr(cache, "values") else cache
    total = 0.0
    for leaf in leaves:
        total += (leaf.size // leaf.shape[1]) * leaf.dtype.itemsize
    return total


@dataclasses.dataclass(frozen=True)
class KVHostTraffic:
    """Static host↔device paging traffic of a tiered-KV engine — the
    ledger proof form (the ``grad_sync_wire_bytes`` /
    ``kv_cache_bytes`` pattern applied to the D2H/H2D legs): page-move
    COUNTS are exact engine counters (every payload copy increments
    exactly one), per-page bytes are exact pool geometry, so the byte
    totals are proven, not sampled — only wall time is ever measured.

    ``spilled_pages`` counts payload D2H copies (reserved-but-unwritten
    pages spill as pure bookkeeping and move zero bytes — they carry no
    payload); ``prefetched_pages`` counts payload H2D copies including
    warm-prefix restores."""

    spilled_pages: int
    prefetched_pages: int
    page_bytes: float

    @property
    def spill_bytes(self) -> float:
        return self.spilled_pages * self.page_bytes

    @property
    def prefetch_bytes(self) -> float:
        return self.prefetched_pages * self.page_bytes

    @property
    def total_bytes(self) -> float:
        return self.spill_bytes + self.prefetch_bytes

    def per_token(self, tokens: int) -> float:
        """Host↔device bytes per emitted token — the config-12
        ``serve_kv_tiered`` row's cost axis."""
        if tokens < 1:
            raise ValueError(f"tokens must be >= 1, got {tokens}")
        return self.total_bytes / tokens


def kv_host_traffic_bytes(cache, spilled_pages: int,
                          prefetched_pages: int) -> KVHostTraffic:
    """The tiered-KV traffic ledger for one pool: exact page-move
    counts (the engine's ``host_spilled_pages`` /
    ``host_prefetched_pages``) priced at the pool's exact per-page
    bytes.  Validated in tests against BOTH the analytic per-page form
    and the host store's actually-moved byte counters — three
    independent accountings that must agree exactly."""
    return KVHostTraffic(
        spilled_pages=int(spilled_pages),
        prefetched_pages=int(prefetched_pages),
        page_bytes=kv_page_bytes(cache),
    )


def _cost_entry(compiled) -> dict:
    """Normalize ``compiled.cost_analysis()`` across jax versions: it has
    returned a dict, a list of one dict per partition, and None."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


def analyze(fn, *args, **kwargs) -> Ledger:
    """Ledger of a jittable: ``fn`` is a jitted function (anything with
    ``.lower``), lowered and compiled against ``*args``/``**kwargs``
    (abstract shapes suffice — values are never executed)."""
    if not hasattr(fn, "lower"):
        import jax

        fn = jax.jit(fn)
    compiled = fn.lower(*args, **kwargs).compile()
    cost = _cost_entry(compiled)
    return Ledger(
        collectives=parse_collectives(compiled.as_text()),
        flops=float(cost.get("flops", 0.0)),
        bytes_accessed=float(cost.get("bytes accessed", 0.0)),
    )


@dataclasses.dataclass(frozen=True)
class RooflineReport:
    """A static ledger diffed against one MEASURED span: achieved rates
    and their fraction of the stated peaks.  Fractions are None when the
    corresponding peak was not given."""

    measured_s: float
    flops_per_s: float
    hbm_bytes_per_s: float
    wire_bytes_per_s: float
    flops_fraction: Optional[float]
    hbm_fraction: Optional[float]
    wire_fraction: Optional[float]

    @property
    def bound(self) -> str:
        """Which stated peak the run came closest to saturating."""
        cands = {
            "compute": self.flops_fraction,
            "memory": self.hbm_fraction,
            "network": self.wire_fraction,
        }
        cands = {k: v for k, v in cands.items() if v is not None}
        if not cands:
            return "unknown"
        return max(cands, key=cands.get)

    def summary(self) -> str:
        def pct(f):
            return "n/a" if f is None else f"{100 * f:.1f}%"

        return (
            f"measured {self.measured_s * 1e3:.3f} ms: "
            f"{self.flops_per_s / 1e12:.3f} TFLOP/s "
            f"({pct(self.flops_fraction)} of peak), "
            f"HBM {self.hbm_bytes_per_s / 1e9:.2f} GB/s "
            f"({pct(self.hbm_fraction)}), "
            f"wire {self.wire_bytes_per_s / 1e9:.2f} GB/s "
            f"({pct(self.wire_fraction)}) -> {self.bound}-bound"
        )


def roofline(
    ledger: Ledger,
    measured_s: float,
    executions: int = 1,
    peak_flops_per_s: Optional[float] = None,
    peak_hbm_bytes_per_s: Optional[float] = None,
    peak_wire_bytes_per_s: Optional[float] = None,
) -> RooflineReport:
    """Diff the static ledger against a measured wall time (one span
    covering ``executions`` runs of the program): achieved FLOP/s, HBM
    GB/s, and wire GB/s, each as a fraction of the given peak — the
    "what fraction of the roofline did we reach, and which ceiling is
    it" report every perf PR argues from."""
    if measured_s <= 0:
        raise ValueError(f"measured_s must be > 0, got {measured_s}")
    flops_rate = ledger.flops * executions / measured_s
    hbm_rate = ledger.bytes_accessed * executions / measured_s
    wire_rate = ledger.total_wire_bytes() * executions / measured_s

    def frac(rate, peak):
        return None if peak is None else rate / peak

    return RooflineReport(
        measured_s=measured_s,
        flops_per_s=flops_rate,
        hbm_bytes_per_s=hbm_rate,
        wire_bytes_per_s=wire_rate,
        flops_fraction=frac(flops_rate, peak_flops_per_s),
        hbm_fraction=frac(hbm_rate, peak_hbm_bytes_per_s),
        wire_fraction=frac(wire_rate, peak_wire_bytes_per_s),
    )
