"""Per-request causal span trees with EXACT latency decomposition.

The fleet sheds, retries, kills racks and re-admits victims, but the
only latency truth so far was aggregate per-class TTFT percentiles —
when one request blows its SLO nothing could say WHERE the time went.
This module is Dapper-style request-scoped tracing (Sigelman et al.,
2010) composed with the MegaScale exact wall-partition discipline that
``obs.goodput`` already applies run-scoped: every lifecycle edge of a
request (router submit → queue → shed/retry → dispatch → prefill or
staged-disagg prefill → handoff → per-macro-tick decode occupancy →
finish/evict/quarantine, including the chaos legs kill → evacuate →
re-admission → re-prefill) lands as a causally-linked span keyed by
rid, and each drained request yields a :class:`RequestTrace` whose
bucket decomposition sums to its end-to-end latency EXACTLY
(:meth:`RequestTrace.check` — the goodput law applied per request).

Design points:

- **Observes, never perturbs.**  Hooks append host-side
  ``perf_counter`` stamps to per-rid lists — no device syncs, no
  scheduling decisions, no RNG draws — so a traced fleet's output
  digest is bit-identical to the untraced fleet's (asserted by record
  config 22).  ``NullReqTracer`` is the disabled path: every hook is a
  constant-time no-op, so instrumented layers hold a tracer
  unconditionally (the ``NullSink`` idiom).
- **Exact by construction.**  Attribution runs the goodput clipping
  sweep per request: claims (work spans, closed wait intervals) sort
  by start, clip to ``[cursor, finish_t]``, and advance the cursor —
  so attributed intervals are disjoint and inside the request wall,
  the ``other`` bucket is the exact remainder, and the buckets sum to
  the wall by construction, not by hope.
- **Waste is explicit.**  Work spans recorded under an attempt that a
  replica kill invalidated (and staged prefills a handoff degrade
  threw away) re-bucket to ``waste`` at attribution — a victim's
  trace SHOWS its re-prefill cost instead of smearing it into queue
  time.  Shed → resubmit gaps are ``shed_wait``; post-kill
  re-admission waits are ``waste``.
- **Seeded sampling.**  :func:`rid_sampled` is a pure function of
  (rid, sample_rate, salt) — the same rid samples identically on
  every replica and every run, so a sampled request's tree is always
  complete (no half-traced requests) and the 100k-request acceptance
  run can trace 1% affordably.
- **Perfetto export.**  :meth:`ReqTracer.chrome_trace` renders one
  lane per request: a ``b``/``e`` async root spanning submit→finish,
  the CLIPPED bucket intervals as ``B``/``E`` pairs (disjoint, so the
  validator's stack pairing holds), marks as ``i`` instants, and
  ``s``/``f`` flow events linking shed→retry and kill→re-admission
  attempt chains — validated by the extended
  ``obs.trace.validate_chrome_trace``.
"""

from __future__ import annotations

from typing import Optional, Sequence

from tpuscratch.obs.metrics import Reservoir

__all__ = [
    "REQ_BUCKETS",
    "NullReqTracer",
    "ReqTracer",
    "RequestTrace",
    "rid_sampled",
]

#: the per-request wall partition, in waterfall order.  ``waste`` is
#: stall/re-admission waste: killed-attempt work, degraded staged
#: prefills, post-kill re-admission waits.  ``other`` is the exact
#: unattributed remainder (host orchestration between spans).
REQ_BUCKETS = (
    "queue", "shed_wait", "prefill", "handoff", "decode", "waste", "other",
)

_WORK_BUCKET = {"prefill": "prefill", "handoff": "handoff",
                "decode": "decode"}
_WAIT_BUCKET = {"queue": "queue", "shed": "shed_wait", "readmit": "waste"}


def rid_sampled(rid: int, sample_rate: float, salt: int = 0) -> bool:
    """Pure sampling decision: a seeded hash of (rid, salt) against
    ``sample_rate`` — no call-order state, so every layer that asks
    about a rid gets the same answer and a sampled request's tree is
    always complete.  ``>= 1`` always samples, ``<= 0`` never.

    The mix is splitmix64, NOT a CRC: CRC32 is linear, so two equal-
    length ``f"{rid}:{salt}"`` strings differ by a CONSTANT xor and
    nearby salts would select (nearly) the same rid population — a
    salt exists precisely to draw an independent sample."""
    if sample_rate >= 1.0:
        return True
    if sample_rate <= 0.0:
        return False
    m = (1 << 64) - 1
    x = (int(rid) + (int(salt) + 1) * 0x9E3779B97F4A7C15) & m
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & m
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & m
    x ^= x >> 31
    return (x / 2**64) < sample_rate


class _Span:
    """One recorded claim on the request's wall: a work span (prefill /
    handoff / decode) or a closed wait interval."""

    __slots__ = ("kind", "t0", "t1", "attempt", "bucket", "waste", "args")

    def __init__(self, kind: str, t0: float, t1: float, attempt: int,
                 bucket: str, waste: bool = False,
                 args: Optional[dict] = None):
        self.kind = kind
        self.t0 = t0
        self.t1 = t1
        self.attempt = attempt
        self.bucket = bucket
        self.waste = waste
        self.args = args


class _Live:
    """Mutable per-rid tracing state between ``begin`` and ``collect``."""

    __slots__ = ("rid", "cls", "submit_t", "spans", "marks", "attempt",
                 "killed", "wait", "state", "shed_t", "finish_t",
                 "outcome", "links")

    def __init__(self, rid: int, cls: Optional[str], submit_t: float):
        self.rid = rid
        self.cls = cls
        self.submit_t = submit_t
        self.spans: list[_Span] = []
        self.marks: list[tuple[str, float, Optional[dict]]] = []
        self.attempt = 0
        self.killed: set[int] = set()
        # the one open wait interval: (t0, tag) or None
        self.wait: Optional[tuple[float, str]] = (submit_t, "queue")
        self.state = "open"  # open | shed
        self.shed_t = 0.0
        self.finish_t: Optional[float] = None
        self.outcome = ""
        # (from_attempt, to_attempt, reason) — the flow-event edges
        self.links: list[tuple[int, int, str]] = []


class RequestTrace:
    """One drained request's causal tree: the bucket decomposition (sums
    to the end-to-end wall exactly), the clipped segments behind it, and
    the instant marks — everything the waterfall view and the Perfetto
    export render."""

    __slots__ = ("rid", "cls", "submit_t", "finish_t", "outcome",
                 "attempts", "killed", "buckets", "segments", "marks")

    def __init__(self, rid: int, cls: Optional[str], submit_t: float,
                 finish_t: float, outcome: str, attempts: int,
                 killed: tuple[int, ...], buckets: dict[str, float],
                 segments: tuple, marks: tuple):
        self.rid = rid
        self.cls = cls
        self.submit_t = submit_t
        self.finish_t = finish_t
        self.outcome = outcome
        self.attempts = attempts
        self.killed = killed
        self.buckets = buckets
        #: ((attempt, bucket, t0, t1), ...) — clipped, disjoint, in
        #: time order, all inside [submit_t, finish_t]
        self.segments = segments
        #: ((kind, t, args), ...)
        self.marks = marks

    @property
    def e2e_s(self) -> float:
        return self.finish_t - self.submit_t

    @property
    def ttft_s(self) -> Optional[float]:
        for kind, t, _args in self.marks:
            if kind == "first_token":
                return t - self.submit_t
        return None

    def check(self, tol: float = 1e-6) -> None:
        """The per-request goodput law: buckets non-negative and summing
        to the end-to-end wall exactly (tolerance covers float
        re-association only).  Raises ``ValueError`` on violation."""
        for name in REQ_BUCKETS:
            v = self.buckets.get(name, 0.0)
            if v < -tol:
                raise ValueError(
                    f"rid {self.rid}: negative bucket {name}={v:.9f}"
                )
        total = sum(self.buckets.values())
        wall = self.e2e_s
        if abs(total - wall) > tol * max(1.0, wall):
            raise ValueError(
                f"rid {self.rid}: buckets sum {total:.9f} != e2e "
                f"{wall:.9f} (diff {total - wall:.3e})"
            )


class NullReqTracer:
    """The disabled tracer: accepts every hook, records nothing —
    instrumented layers hold one unconditionally (the ``NullSink``
    idiom), so the untraced hot path costs a no-op method call."""

    enabled = False

    def sampled(self, rid: int) -> bool:
        return False

    def begin(self, rid, t, cls=None) -> None:
        pass

    def shed(self, rid, t, reason="") -> None:
        pass

    def killed(self, rid, t, **args) -> None:
        pass

    def work(self, rid, kind, t0, t1, **args) -> None:
        pass

    def work_batch(self, rids, kind, t0, t1, **args) -> None:
        pass

    def mark(self, rid, kind, t, **args) -> None:
        pass

    def degrade(self, rid, t) -> None:
        pass

    def finish(self, rid, t, outcome="finished") -> None:
        pass

    def collect(self) -> list:
        return []


class ReqTracer:
    """The live tracer: rid-keyed span trees, exact decomposition at
    drain, per-class reservoir aggregation, Perfetto export.

    One tracer is SHARED by the router and every replica (the router's
    constructor propagates it), so a request's tree stays whole as it
    moves between layers.  All hooks are idempotent where two layers
    can observe the same edge (router begin + engine begin, router
    kill + engine evacuate)."""

    enabled = True

    def __init__(self, sample_rate: float = 1.0, salt: int = 0,
                 sink=None, reservoir_k: int = 4096, seed: int = 0):
        if not (0.0 <= sample_rate <= 1.0):
            raise ValueError(
                f"sample_rate must be in [0, 1], got {sample_rate}"
            )
        self.sample_rate = float(sample_rate)
        self.salt = int(salt)
        self.sink = sink
        self._reservoir_k = reservoir_k
        self._seed = seed
        self._live: dict[int, _Live] = {}
        self._pending_done: list[int] = []
        #: {rid: RequestTrace} of every collected request
        self.traces: dict[int, RequestTrace] = {}
        # per-(cls, bucket) decomposition reservoirs + per-cls e2e/ttft
        self._res: dict[tuple, Reservoir] = {}

    # ---- lifecycle hooks ----------------------------------------------

    def sampled(self, rid: int) -> bool:
        return rid_sampled(rid, self.sample_rate, self.salt)

    def begin(self, rid: int, t: float, cls: Optional[str] = None) -> None:
        """Router/engine submit.  New rid: open its tree with a queue
        wait.  A SHED rid resubmitting (retry storms reuse the rid):
        close the shed→resubmit gap as ``shed_wait``, bump the attempt,
        link the chain, reopen the queue wait.  An already-open rid
        (engine submit after router submit): no-op."""
        lv = self._live.get(rid)
        if lv is None:
            if rid in self.traces or not self.sampled(rid):
                return
            self._live[rid] = _Live(rid, cls, t)
            return
        if lv.cls is None and cls is not None:
            lv.cls = cls
        if lv.state == "shed":
            if t > lv.shed_t:
                lv.spans.append(_Span("wait:shed", lv.shed_t, t,
                                      lv.attempt, "shed_wait"))
            lv.links.append((lv.attempt, lv.attempt + 1, "retry"))
            lv.attempt += 1
            lv.state = "open"
            lv.wait = (t, "queue")

    def shed(self, rid: int, t: float, reason: str = "") -> None:
        """Router shed: the open queue wait closes as ``shed_wait`` (the
        time was spent waiting for a dispatch that never came) and the
        tree parks until a retry resubmits or the client abandons."""
        lv = self._live.get(rid)
        if lv is None:
            return
        if lv.wait is not None:
            w0, tag = lv.wait
            if t > w0:
                # a doomed queue wait is shed_wait; a post-kill
                # re-admission wait that ends in a shed stays waste
                bucket = ("waste" if tag == "readmit" else "shed_wait")
                lv.spans.append(_Span(f"wait:{tag}", w0, t, lv.attempt,
                                      bucket))
            lv.wait = None
        lv.state = "shed"
        lv.shed_t = t
        lv.marks.append(("shed", t, {"reason": reason} if reason else None))

    def killed(self, rid: int, t: float, **args) -> None:
        """Replica kill / evacuation: the current attempt's work spans
        re-bucket to ``waste`` at attribution, the open wait closes at
        the kill, and the re-admission wait (also ``waste``) opens.
        Idempotent per attempt — the router and the engine may both
        report the same victim."""
        lv = self._live.get(rid)
        if lv is None or lv.attempt in lv.killed:
            return
        if lv.wait is not None and lv.wait[1] == "readmit":
            # still waiting out the previous kill/degrade: a second
            # layer reporting the same victim, not a new attempt
            return
        if lv.wait is not None:
            w0, tag = lv.wait
            if t > w0:
                lv.spans.append(_Span(f"wait:{tag}", w0, t, lv.attempt,
                                      _WAIT_BUCKET.get(tag, "other")))
            lv.wait = None
        lv.killed.add(lv.attempt)
        lv.marks.append(("kill", t, dict(args) if args else None))
        lv.links.append((lv.attempt, lv.attempt + 1, "readmit"))
        lv.attempt += 1
        lv.wait = (t, "readmit")

    def work(self, rid: int, kind: str, t0: float, t1: float,
             **args) -> None:
        """One work span (``prefill`` / ``handoff`` / ``decode``).  The
        open wait interval closes at the work's start — waits end where
        real work begins.  ``failed=True`` marks the span waste (an
        in-engine retry's burned attempt)."""
        lv = self._live.get(rid)
        if lv is None:
            return
        if lv.wait is not None:
            w0, tag = lv.wait
            if t0 > w0:
                lv.spans.append(_Span(f"wait:{tag}", w0, t0, lv.attempt,
                                      _WAIT_BUCKET.get(tag, "other")))
            lv.wait = None
        failed = bool(args.pop("failed", False))
        lv.spans.append(_Span(kind, t0, t1, lv.attempt,
                              _WORK_BUCKET.get(kind, "other"),
                              waste=failed, args=args or None))

    def work_batch(self, rids: Sequence[int], kind: str, t0: float,
                   t1: float, **args) -> None:
        """One sweep's span fanned out to every participating rid — the
        per-macro-tick decode occupancy stamp (each rid's lane shows the
        sweeps it rode; clipping de-overlaps at attribution)."""
        for rid in rids:
            self.work(rid, kind, t0, t1, **args)

    def mark(self, rid: int, kind: str, t: float, **args) -> None:
        """A zero-duration lifecycle instant (dispatch, first_token,
        admit_prefilled, fault, replay)."""
        lv = self._live.get(rid)
        if lv is None:
            return
        lv.marks.append((kind, t, dict(args) if args else None))

    def degrade(self, rid: int, t: float) -> None:
        """Disagg handoff degrade: the staged prefill + handoff attempts
        are thrown away and the request re-enters the decode engine's
        queue — their spans re-bucket to waste, and the wait until the
        re-prefill is re-admission waste."""
        lv = self._live.get(rid)
        if lv is None:
            return
        for sp in lv.spans:
            if sp.attempt == lv.attempt and sp.bucket in ("prefill",
                                                          "handoff"):
                sp.waste = True
        lv.marks.append(("degrade", t, None))
        lv.links.append((lv.attempt, lv.attempt + 1, "degrade"))
        lv.attempt += 1
        lv.wait = (t, "readmit")

    def finish(self, rid: int, t: float, outcome: str = "finished") -> None:
        """Terminal edge (evict / quarantine / front-retire): stamp the
        end of the wall and queue the tree for collection."""
        lv = self._live.get(rid)
        if lv is None or lv.finish_t is not None:
            return
        if lv.wait is not None:
            w0, tag = lv.wait
            if t > w0:
                lv.spans.append(_Span(f"wait:{tag}", w0, t, lv.attempt,
                                      _WAIT_BUCKET.get(tag, "other")))
            lv.wait = None
        lv.finish_t = t
        lv.outcome = outcome
        self._pending_done.append(rid)

    # ---- collection ----------------------------------------------------

    def collect(self) -> list[RequestTrace]:
        """Materialize every finished tree: run the exact attribution,
        ASSERT the per-request law (``RequestTrace.check`` — the live
        half of the config-22 gate), fold the buckets into the
        per-class reservoirs, and emit one ``reqtrace/request`` sink
        event per request.  Called at every engine/router tick end;
        cheap when nothing finished."""
        if not self._pending_done:
            return []
        out = []
        for rid in self._pending_done:
            lv = self._live.pop(rid, None)
            if lv is None:
                continue
            tr = self._attribute(lv)
            tr.check()
            cls = tr.cls or ""
            for name in REQ_BUCKETS:
                self._reservoir((cls, name)).observe(tr.buckets[name])
            self._reservoir((cls, "e2e")).observe(tr.e2e_s)
            ttft = tr.ttft_s
            if ttft is not None:
                self._reservoir((cls, "ttft")).observe(ttft)
            self.traces[rid] = tr
            out.append(tr)
            if self.sink is not None and self.sink.enabled:
                self.sink.emit(
                    "reqtrace/request",
                    rid=tr.rid, cls=cls, outcome=tr.outcome,
                    attempts=tr.attempts, e2e_s=round(tr.e2e_s, 6),
                    **({"ttft_s": round(ttft, 6)}
                       if ttft is not None else {}),
                    **{f"{b}_s": round(tr.buckets[b], 6)
                       for b in REQ_BUCKETS},
                    segments=[
                        [a, b, round(t0 - tr.submit_t, 6),
                         round(t1 - tr.submit_t, 6)]
                        for a, b, t0, t1 in tr.segments
                    ],
                    marks=[[k, round(t - tr.submit_t, 6)]
                           for k, t, _a in tr.marks],
                )
        self._pending_done.clear()
        return out

    def _reservoir(self, key: tuple) -> Reservoir:
        r = self._res.get(key)
        if r is None:
            r = self._res[key] = Reservoir(self._reservoir_k,
                                           seed=self._seed)
        return r

    def _attribute(self, lv: _Live) -> RequestTrace:
        """The goodput clipping sweep, per request: claims sort by
        start, clip to ``[cursor, finish_t]``, advance the cursor — so
        attributed intervals are disjoint and inside the wall, and the
        ``other`` bucket is the exact remainder."""
        finish_t = lv.finish_t if lv.finish_t is not None else lv.submit_t
        wall = finish_t - lv.submit_t
        claims = []
        for sp in lv.spans:
            # a killed attempt's WORK is waste (it will be redone);
            # its waits keep their bucket — queue time is queue time,
            # and hiding it under waste would mask backpressure
            wasted = sp.waste or (sp.attempt in lv.killed
                                  and sp.bucket in _WORK_BUCKET.values())
            claims.append((sp.t0, sp.t1, sp.attempt,
                           "waste" if wasted else sp.bucket))
        claims.sort(key=lambda c: (c[0], c[1]))
        buckets = {name: 0.0 for name in REQ_BUCKETS}
        segments = []
        cursor = lv.submit_t
        attributed = 0.0
        for t0, t1, attempt, bucket in claims:
            s = max(t0, cursor)
            e = min(t1, finish_t)
            if e <= s:
                continue
            buckets[bucket] += e - s
            attributed += e - s
            segments.append((attempt, bucket, s, e))
            cursor = e
        buckets["other"] = max(0.0, wall - attributed)
        return RequestTrace(
            rid=lv.rid, cls=lv.cls, submit_t=lv.submit_t,
            finish_t=finish_t, outcome=lv.outcome or "finished",
            attempts=lv.attempt + 1, killed=tuple(sorted(lv.killed)),
            buckets=buckets, segments=tuple(segments),
            marks=tuple(lv.marks),
        )

    # ---- aggregation ---------------------------------------------------

    def decomposition(self) -> dict[str, dict[str, dict[str, float]]]:
        """{class: {bucket|e2e|ttft: {count, mean, p50, p99}}} over every
        collected request — the per-class TTFT/E2E decomposition
        percentiles, bounded by the reservoirs."""
        out: dict[str, dict[str, dict[str, float]]] = {}
        for (cls, name), res in sorted(self._res.items()):
            if res.count == 0:
                continue
            out.setdefault(cls, {})[name] = {
                "count": res.count,
                "mean": res.mean,
                "p50": res.percentile(50),
                "p99": res.percentile(99),
            }
        return out

    # ---- Perfetto export -----------------------------------------------

    def chrome_trace(self, pid: int = 0) -> dict:
        """Every collected request as Chrome trace-event JSON: one lane
        (tid) per rid holding a ``b``/``e`` async root over the whole
        wall, the clipped bucket segments as ``B``/``E`` pairs (disjoint
        by construction, so the validator's stack pairing holds), marks
        as ``i`` instants, and ``s``/``f`` flows linking the attempt
        chain across sheds/kills/degrades.  Timestamps are microseconds
        relative to the earliest submit; ties break on the op-seq
        counter (record order), the ``obs.trace`` rule."""
        traces = sorted(self.traces.values(), key=lambda tr: tr.rid)
        meta = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "requests"},
        }]
        if not traces:
            return {"traceEvents": meta, "displayTimeUnit": "ms"}
        t0 = min(tr.submit_t for tr in traces)

        def us(t: float) -> float:
            return (t - t0) * 1e6

        out = []  # (tid, ts, seq, event)
        seq = 0
        for tr in traces:
            tid = tr.rid
            root = f"request {tr.rid}"
            base = {"pid": pid, "tid": tid}
            out.append((tid, us(tr.submit_t), seq, dict(
                base, name=root, ph="b", cat="request", id=tr.rid,
                ts=us(tr.submit_t),
                args={"cls": tr.cls or "", "outcome": tr.outcome,
                      "attempts": tr.attempts},
            )))
            seq += 1
            for attempt, bucket, s, e in tr.segments:
                out.append((tid, us(s), seq, dict(
                    base, name=bucket, ph="B", ts=us(s),
                    args={"attempt": attempt},
                )))
                seq += 1
                out.append((tid, us(e), seq,
                            dict(base, name=bucket, ph="E", ts=us(e))))
                seq += 1
            for kind, t, margs in tr.marks:
                out.append((tid, us(t), seq, dict(
                    base, name=kind, ph="i", s="t", ts=us(t),
                    args=dict(margs) if margs else {},
                )))
                seq += 1
            # flow chain across attempts: one s→f edge per transition,
            # anchored at the transition instant in this request's lane
            for i, (_src, dst, reason) in enumerate(
                    _attempt_edges(tr)):
                flow_id = f"{tr.rid}.{i}"
                t_edge = _edge_time(tr, i)
                out.append((tid, us(t_edge[0]), seq, dict(
                    base, name=reason, ph="s", cat="attempt",
                    id=flow_id, ts=us(t_edge[0]),
                )))
                seq += 1
                out.append((tid, us(t_edge[1]), seq, dict(
                    base, name=reason, ph="f", bp="e", cat="attempt",
                    id=flow_id, ts=us(t_edge[1]),
                )))
                seq += 1
            out.append((tid, us(tr.finish_t), seq, dict(
                base, name=root, ph="e", cat="request", id=tr.rid,
                ts=us(tr.finish_t),
            )))
            seq += 1
        out.sort(key=lambda e: e[:3])
        return {
            "traceEvents": meta + [e[3] for e in out],
            "displayTimeUnit": "ms",
        }


def _attempt_edges(tr: RequestTrace) -> list[tuple[int, int, str]]:
    """The attempt-transition edges of a collected trace, recovered
    from its marks (shed / kill / degrade each advance the attempt)."""
    edges = []
    a = 0
    for kind, _t, _args in tr.marks:
        if kind in ("shed", "kill", "degrade"):
            edges.append((a, a + 1, kind))
            a += 1
    return edges


def _edge_time(tr: RequestTrace, i: int) -> tuple[float, float]:
    """(source, target) stamps of attempt edge ``i``: the transition
    mark and the next recorded point after it (the resubmit/re-prefill
    landing), falling back to the finish stamp."""
    ts = [t for kind, t, _a in tr.marks
          if kind in ("shed", "kill", "degrade")]
    t_src = ts[i]
    candidates = [s for _a, _b, s, _e in tr.segments if s > t_src]
    candidates += [t for _k, t, _a in tr.marks if t > t_src]
    t_dst = min(candidates) if candidates else tr.finish_t
    return t_src, max(t_dst, t_src)
