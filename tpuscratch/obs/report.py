"""Collapse an obs JSONL run into a summary table.

``python -m tpuscratch.obs.report run.jsonl [run.h1.jsonl ...]
[--event serve/tick] [--json]``

Reads one or more per-host sink files (``obs.sink.Sink`` output), groups
events by kind, and prints per-event counts plus min/p50/mean/max for
every numeric field — the rank-0 "gather the per-rank numbers and print
the table" step of the reference's drivers (mpicuda3.cu:315-325), run
after the fact over the artifact instead of inside the job.

``metrics`` events (registry snapshots) are folded with
``obs.metrics.merge_snapshots`` semantics: the LAST snapshot per
(file, scope) wins — snapshots of one registry are cumulative, and
``scope`` (``Sink.emit_metrics(..., scope=registry.id)``) identifies the
registry — then the survivors merge across scopes and hosts (distinct
registries are disjoint populations: one engine per batch size in a
sweep, one trainer per run).

This module's own imports are light (json/argparse + the stdlib-only
``obs.metrics``); running it as ``python -m tpuscratch.obs.report``
still executes the ``tpuscratch`` package init (which imports jax), so
the CLI needs the framework's environment — the summarize/format
functions themselves are importable into any tool that has the package.
"""

from __future__ import annotations

import argparse
import json
import sys
import warnings
from typing import Iterable, Optional

from tpuscratch.obs.metrics import merge_snapshots, percentile
from tpuscratch.obs.trace import detect_stragglers, fold_phase_events

__all__ = ["load_events", "stragglers", "summarize", "decompose",
           "request_waterfall", "format_table", "main"]


def load_events(paths: Iterable[str]) -> list[dict]:
    """All events from the given JSONL files, in file order.  Blank
    lines are skipped; a corrupt/truncated line is SKIPPED with a
    warning naming its location instead of failing the whole file — a
    torn final line is the normal state of an artifact whose writer was
    SIGKILLed mid-flush, and the surviving events are exactly what a
    post-mortem needs."""
    events = []
    for path in paths:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    warnings.warn(
                        f"{path}:{lineno}: skipping corrupt JSONL line "
                        f"({e.msg})",
                        RuntimeWarning, stacklevel=2,
                    )
                    continue
                if not isinstance(rec, dict):
                    warnings.warn(
                        f"{path}:{lineno}: skipping non-object JSONL line",
                        RuntimeWarning, stacklevel=2,
                    )
                    continue
                rec["_file"] = path
                events.append(rec)
    return events


def stragglers(events: list[dict], min_skew: float = 1.0) -> list[dict]:
    """The per-phase host-skew table from ``trace/phase`` events: for
    every phase reported by >= 2 hosts, name the slowest host, the
    fastest, and the skew ratio.  The cumulative-event fold (newest per
    (file, host, phase), same-host files take the larger total) is
    ``obs.trace.fold_phase_events`` — shared with the goodput
    straggler-wait carve-out, so the two readers always agree."""
    per_phase = fold_phase_events(events)
    return [
        {
            "phase": r.phase, "slowest": r.slowest, "fastest": r.fastest,
            "max_s": r.max_s, "min_s": r.min_s,
            # infinite skew (a 0.0-rounded fastest host) exports as None:
            # ``json.dumps`` would otherwise emit the non-standard
            # ``Infinity`` token and break strict consumers
            "skew": round(r.skew, 4) if r.skew != float("inf") else None,
        }
        for r in detect_stragglers(per_phase, min_skew=min_skew)
    ]


def summarize(events: list[dict],
              only_event: Optional[str] = None) -> dict:
    """{event kind: {"count": n, "fields": {field: stats}}} plus a
    merged ``"metrics"`` entry (cross-host merge of each file's last
    registry snapshot), a ``"stragglers"`` table (per-phase host skew
    from ``trace/phase`` events, when >= 2 hosts reported), and the
    ``"run"`` metadata events verbatim."""
    by_kind: dict[str, list[dict]] = {}
    # (file, scope) -> newest snapshot of that registry
    last_snapshot: dict[tuple, dict] = {}
    runs = []
    for rec in events:
        kind = rec.get("event", "?")
        if kind == "run":
            runs.append({k: v for k, v in rec.items()
                         if not k.startswith("_")})
            continue
        if kind == "metrics" and isinstance(rec.get("metrics"), dict):
            last_snapshot[(rec["_file"], rec.get("scope"))] = rec["metrics"]
            continue
        if kind == "trace/phase" and only_event != "trace/phase":
            # cumulative snapshots: folded by stragglers() — but an
            # explicit --event trace/phase request gets the raw stats
            continue
        if only_event is not None and kind != only_event:
            continue
        by_kind.setdefault(kind, []).append(rec)

    out: dict = {"runs": runs, "events": {}}
    # the skew table reads the whole stream, so it only belongs on the
    # unfiltered summary — an --event view must not smuggle other kinds
    skew_rows = stragglers(events) if only_event is None else []
    if skew_rows:
        out["stragglers"] = skew_rows
    for kind, recs in sorted(by_kind.items()):
        fields: dict[str, list[float]] = {}
        for rec in recs:
            for key, val in rec.items():
                if key in ("event", "t") or key.startswith("_"):
                    continue
                if isinstance(val, bool) or not isinstance(
                    val, (int, float)
                ):
                    continue
                fields.setdefault(key, []).append(float(val))
        out["events"][kind] = {
            "count": len(recs),
            "fields": {
                key: {
                    "min": min(vals),
                    "p50": percentile(vals, 50),
                    "mean": sum(vals) / len(vals),
                    "max": max(vals),
                }
                for key, vals in sorted(fields.items())
            },
        }
    if last_snapshot:
        out["metrics"] = merge_snapshots(last_snapshot.values())
    # the per-class latency decomposition (reqtrace/request events) —
    # unfiltered summaries only, same rule as the skew table
    if only_event is None:
        decomp = decompose(events)
        if decomp:
            out["decomposition"] = decomp
    return out


def decompose(events: list[dict]) -> dict:
    """Per-class latency decomposition from ``reqtrace/request`` events
    (``obs.reqtrace.ReqTracer.collect``): {class: {field: stats}} over
    every traced request's bucket seconds plus e2e/ttft — the artifact
    twin of ``ReqTracer.decomposition()`` (reservoir-bounded, live)
    rebuilt exactly from the JSONL (unbounded, post-mortem)."""
    per_cls: dict[str, dict[str, list[float]]] = {}
    for rec in events:
        if rec.get("event") != "reqtrace/request":
            continue
        fields = per_cls.setdefault(str(rec.get("cls", "")), {})
        for key, val in rec.items():
            if not key.endswith("_s") or isinstance(val, bool) \
                    or not isinstance(val, (int, float)):
                continue
            fields.setdefault(key, []).append(float(val))
    return {
        cls: {
            key: {
                "count": len(vals),
                "mean": sum(vals) / len(vals),
                "p50": percentile(vals, 50),
                "p99": percentile(vals, 99),
            }
            for key, vals in sorted(fields.items())
        }
        for cls, fields in sorted(per_cls.items())
    }


def request_waterfall(events: list[dict], rid: int) -> str:
    """One request's causal span tree as an ASCII waterfall: every
    attributed segment (attempt-grouped, submit-relative) as a scaled
    bar, every lifecycle mark on its own line, the bucket totals, and
    the exact-sum line (``sum(buckets) == e2e`` — the
    ``RequestTrace.check`` invariant, re-checked from the artifact).
    The NEWEST ``reqtrace/request`` event for ``rid`` wins (a retried
    fleet run may trace the rid twice)."""
    rec = None
    for r in events:
        if r.get("event") == "reqtrace/request" and r.get("rid") == rid:
            rec = r
    if rec is None:
        return f"no reqtrace/request event for rid {rid}"
    e2e = float(rec.get("e2e_s", 0.0))
    scale = 40.0 / e2e if e2e > 0 else 0.0
    lines = [
        f"request {rid}  class={rec.get('cls', '')!r}  "
        f"outcome={rec.get('outcome', '?')}  attempts={rec.get('attempts')}"
        f"  e2e {_fmt(e2e)} s"
        + (f"  ttft {_fmt(rec['ttft_s'])} s" if "ttft_s" in rec else "")
    ]
    segs = [tuple(s) for s in rec.get("segments", [])]
    width = max([len(str(b)) for _a, b, _t0, _t1 in segs] or [6])
    last_attempt = None
    for attempt, bucket, t0, t1 in segs:
        if attempt != last_attempt:
            lines.append(f"  attempt {attempt}:")
            last_attempt = attempt
        pad = int(round(t0 * scale))
        bar = max(1, int(round((t1 - t0) * scale)))
        lines.append(
            f"    {str(bucket).ljust(width)}  "
            f"[{_fmt(t0):>10} .. {_fmt(t1):>10}] "
            f"{' ' * pad}{'#' * bar}"
        )
    marks = [tuple(m) for m in rec.get("marks", [])]
    if marks:
        lines.append("  marks:")
        for kind, t in marks:
            lines.append(f"    {str(kind).ljust(width)}  at {_fmt(t)} s")
    lines.append("  buckets:")
    total = 0.0
    for key in sorted(k for k in rec if k.endswith("_s")
                      and k not in ("e2e_s", "ttft_s")):
        total += float(rec[key])
        lines.append(f"    {key.ljust(width + 2)}  {_fmt(rec[key])} s")
    ok = abs(total - e2e) <= 1e-5 * max(1.0, e2e) + 1e-5
    lines.append(
        f"  sum(buckets) {_fmt(total)} s == e2e {_fmt(e2e)} s: "
        f"{'exact' if ok else 'BROKEN'}"
    )
    return "\n".join(lines)


def _fmt(v: float) -> str:
    if v != v:  # nan
        return "nan"
    if v == 0:
        return "0"
    if abs(v) >= 1e5 or abs(v) < 1e-3:
        return f"{v:.3e}"
    return f"{v:.6g}"


def format_table(summary: dict) -> str:
    """The human rendering: one block per event kind, one row per
    numeric field."""
    lines = []
    for run in summary.get("runs", []):
        meta = " ".join(
            f"{k}={run[k]}" for k in sorted(run) if k not in ("event", "t")
        )
        lines.append(f"run: {meta}")
    for kind, info in summary.get("events", {}).items():
        lines.append(f"\n{kind}  (n={info['count']})")
        fields = info["fields"]
        if fields:
            width = max(len(k) for k in fields)
            lines.append(
                f"  {'field'.ljust(width)}  {'min':>12} {'p50':>12} "
                f"{'mean':>12} {'max':>12}"
            )
            for key, st in fields.items():
                lines.append(
                    f"  {key.ljust(width)}  {_fmt(st['min']):>12} "
                    f"{_fmt(st['p50']):>12} {_fmt(st['mean']):>12} "
                    f"{_fmt(st['max']):>12}"
                )
    skew_rows = summary.get("stragglers")
    if skew_rows:
        lines.append("\nstragglers (per-phase host skew, slowest first)")
        width = max(len(r["phase"]) for r in skew_rows)

        def _skew(r):
            return float("inf") if r["skew"] is None else r["skew"]

        for r in sorted(skew_rows, key=lambda r: -_skew(r)):
            skew_txt = "inf" if r["skew"] is None else f"{r['skew']:.2f}x"
            lines.append(
                f"  {r['phase'].ljust(width)}  host {r['slowest']} slowest "
                f"{_fmt(r['max_s'])} s vs host {r['fastest']} "
                f"{_fmt(r['min_s'])} s  (skew {skew_txt})"
            )
    decomp = summary.get("decomposition")
    if decomp:
        lines.append("\nper-class latency decomposition (reqtrace)")
        for cls, fields in decomp.items():
            lines.append(f"  class {cls!r}")
            width = max(len(k) for k in fields)
            lines.append(
                f"    {'field'.ljust(width)}  {'n':>6} {'mean':>12} "
                f"{'p50':>12} {'p99':>12}"
            )
            for key, st in fields.items():
                lines.append(
                    f"    {key.ljust(width)}  {st['count']:>6} "
                    f"{_fmt(st['mean']):>12} {_fmt(st['p50']):>12} "
                    f"{_fmt(st['p99']):>12}"
                )
    metrics = summary.get("metrics")
    if metrics:
        lines.append("\nmetrics (final snapshot, merged across hosts)")
        width = max(len(k) for k in metrics)
        for name, m in metrics.items():
            kind = m.get("kind", "?")
            if kind == "counter":
                detail = f"count {_fmt(m['value'])}"
            elif kind == "gauge":
                detail = (
                    f"value {_fmt(m['value'])}  "
                    f"[min {_fmt(m['min'])}, max {_fmt(m['max'])}]"
                )
            else:
                detail = (
                    f"n {m.get('count', 0)}  mean {_fmt(m.get('mean', 0.0))}"
                    f"  [min {_fmt(m.get('min', 0.0))}, "
                    f"max {_fmt(m.get('max', 0.0))}]"
                )
            lines.append(f"  {name.ljust(width)}  {kind:<9} {detail}")
    return "\n".join(lines) if lines else "no events"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuscratch.obs.report", description=__doc__
    )
    ap.add_argument("paths", nargs="+", help="obs JSONL file(s)")
    ap.add_argument("--event", default=None,
                    help="only summarize this event kind")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    ap.add_argument("--request", type=int, default=None, metavar="RID",
                    help="print one traced request's span-tree waterfall "
                         "(reqtrace/request events) instead of the summary")
    args = ap.parse_args(argv)
    if args.request is not None:
        print(request_waterfall(load_events(args.paths), args.request))
        return 0
    summary = summarize(load_events(args.paths), only_event=args.event)
    if args.json:
        print(json.dumps(summary))
    else:
        print(format_table(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
