"""Host-side metrics registry with mesh-aware cross-rank aggregation.

The host side of the observability loop: counters (monotonic),
gauges (last value, with min/max watermarks), and histograms (bounded
sample window + exact count/total) registered by name, snapshotted into
plain dicts a :class:`~tpuscratch.obs.sink.Sink` can serialize.
Everything on the hot path is a Python attribute update — the cost
budget is "cheap enough to run every engine tick" (< 2% of a compiled
decode step, asserted in the train-bench overhead check).

Cross-rank aggregation keeps the reference's two conventions:

- the **max-min span merge** (mpicuda3.cu:315-325): a phase's wall time
  across ranks is ``max(end) - min(begin)``, absorbed here from
  ``runtime/profiling`` (which now delegates) as :func:`span_max_min`;
- **reduce-to-root of per-rank numbers** (mpicuda3.cu:176-179): here
  :func:`mesh_reduce` runs the reduction through ``comm.collectives``
  on the mesh itself — sum/max/min over every mesh axis in one compiled
  program — and :func:`merge_snapshots` is its host-side pure-function
  twin for snapshots already gathered to one process.

:class:`CompileCounter` is promoted here from ``serve/decode`` (the
serving module re-exports it): counting traces of a jitted body is the
recompile detector for EVERY layer — the serving engine's
zero-steady-state-recompile assertion and the trainer's N-steps-no-retrace
coverage both hang off it.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import math
import random
import uuid
from typing import Iterable, Sequence

__all__ = [
    "CompileCounter",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MeshSpan",
    "Reservoir",
    "merge_snapshots",
    "mesh_reduce",
    "mesh_span",
    "percentile",
    "span_max_min",
]


def percentile(xs: Sequence[float], q: float) -> float:
    """Nearest-rank percentile — the ONE implementation (``bench.timing``
    and ``obs.report`` delegate here; ``Histogram.percentile`` uses it
    over its window)."""
    ys = sorted(xs)
    if not ys:
        raise ValueError("empty sample")
    idx = min(len(ys) - 1, max(0, round(q / 100 * (len(ys) - 1))))
    return ys[idx]


class CompileCounter:
    """Counts traces of a jitted program body.  jax retraces exactly on
    compilation-cache misses, so the count IS the compile count — the
    hook the serving engine's steady-state zero-recompile assertion and
    the trainer's no-retrace coverage read."""

    def __init__(self) -> None:
        self.count = 0

    def wrap(self, fn):
        def counted(*args):
            self.count += 1
            return fn(*args)

        return counted


class Counter:
    """Monotonic event count (inserts, evictions, recompiles, ...)."""

    kind = "counter"

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self) -> dict:
        return {"kind": self.kind, "value": self.value}


class Gauge:
    """Last-set value plus min/max watermarks (queue depth, free pages)."""

    kind = "gauge"

    def __init__(self) -> None:
        self.value: float = math.nan
        self.min = math.inf
        self.max = -math.inf

    def set(self, v: float) -> None:
        self.value = v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def snapshot(self) -> dict:
        return {
            "kind": self.kind, "value": self.value,
            "min": self.min, "max": self.max,
        }


class Histogram:
    """Observation distribution: exact count/total/min/max plus a bounded
    window of recent samples for percentiles (a continuously-serving
    engine must not grow one float per tick without bound — the same
    discipline as the engine's span-window trim)."""

    kind = "histogram"

    def __init__(self, window: int = 4096) -> None:
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.window: collections.deque[float] = collections.deque(
            maxlen=window
        )

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.window.append(v)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else math.nan

    def percentile(self, q: float) -> float:
        """Percentile over the RECENT window, not engine lifetime."""
        return percentile(self.window, q)

    def snapshot(self) -> dict:
        out = {
            "kind": self.kind, "count": self.count, "total": self.total,
            "min": self.min, "max": self.max, "mean": self.mean,
        }
        if self.window:
            out["p50"] = self.percentile(50)
            out["p99"] = self.percentile(99)
        return out


class Reservoir:
    """Uniform reservoir sample (Algorithm R) with exact
    count/total/min/max — the bounded-memory tail for STREAM-scale
    populations (ISSUE 17).

    :class:`Histogram`'s deque window keeps the most RECENT samples, so
    over a 500k-request drain its p99 describes the last 4096 finishes,
    not the drain.  The reservoir instead keeps a uniform sample of the
    WHOLE stream in the same bounded memory: every observation has
    probability ``k/count`` of being in the sample, so the percentile
    estimate covers the full population — and whenever ``count <= k``
    the sample IS the population and the tails are exact (``.exact``),
    which keeps small-drain reports bit-equal to the old per-request
    lists.  Replacement draws come from a seeded generator: the same
    observation stream reports the same percentiles on every run (the
    chaos bit-identity discipline applied to metrics)."""

    kind = "reservoir"

    def __init__(self, k: int = 4096, seed: int = 0) -> None:
        if k < 1:
            raise ValueError(f"reservoir size must be >= 1, got {k}")
        self.k = k
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.sample: list[float] = []
        self._rng = random.Random(seed)

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if len(self.sample) < self.k:
            self.sample.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.k:
                self.sample[j] = v

    @property
    def exact(self) -> bool:
        """True while the sample still holds EVERY observation — the
        percentiles are exact, not estimates."""
        return self.count <= self.k

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else math.nan

    def percentile(self, q: float) -> float:
        """Percentile over the uniform sample (exact when ``.exact``)."""
        return percentile(self.sample, q)

    def snapshot(self) -> dict:
        out = {
            "kind": self.kind, "count": self.count, "total": self.total,
            "min": self.min, "max": self.max, "mean": self.mean,
            "exact": self.exact,
        }
        if self.sample:
            out["p50"] = self.percentile(50)
            out["p99"] = self.percentile(99)
        return out


#: registry id salt — snapshots of the SAME registry are cumulative (a
#: newer one supersedes), snapshots of DIFFERENT registries are disjoint
#: populations (they merge); the id is how a reader tells the two apart
#: (``sink.emit_metrics(..., scope=registry.id)``).  Globally unique,
#: not per-process-counted: appended runs share one JSONL file, so two
#: processes' first registries must not collide on "reg0".
_REG_SALT = uuid.uuid4().hex[:8]
_REG_IDS = itertools.count()


class MetricsRegistry:
    """Named metric store: ``counter``/``gauge``/``histogram`` get-or-create
    by name (a name is permanently one kind — mixing kinds under one name
    raises rather than silently shadowing)."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self.id = f"reg-{_REG_SALT}-{next(_REG_IDS)}"

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls()
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} is a {type(m).__name__}, "
                f"not a {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def reservoir(self, name: str) -> Reservoir:
        return self._get(name, Reservoir)

    def names(self) -> list[str]:
        return sorted(self._metrics)

    def snapshot(self) -> dict[str, dict]:
        """{name: metric snapshot} — plain JSON-serializable dicts."""
        return {k: m.snapshot() for k, m in sorted(self._metrics.items())}


def span_max_min(begins: Sequence[float], ends: Sequence[float]) -> float:
    """Cross-rank wall time: ``max(ends) - min(begins)`` — the mpicuda3
    gather-to-rank-0 convention as a pure function over per-rank
    timestamp lists (absorbed from ``runtime/profiling``; ``bench.timing``
    and ``profiling.cross_rank_span`` both route here)."""
    if not begins or not ends:
        raise ValueError("empty timestamp lists")
    return max(ends) - min(begins)


def merge_snapshots(snapshots: Iterable[dict]) -> dict[str, dict]:
    """Merge per-rank ``MetricsRegistry.snapshot()`` dicts host-side:
    counters and histogram counts/totals sum; gauge/histogram watermarks
    take min-of-mins / max-of-maxes; a gauge's ``value`` becomes the
    cross-rank max (the conservative "worst rank" reading).  The pure
    twin of :func:`mesh_reduce` for snapshots already on one host."""
    out: dict[str, dict] = {}
    for snap in snapshots:
        for name, m in snap.items():
            if name not in out:
                out[name] = dict(m)
                continue
            o = out[name]
            if o["kind"] != m["kind"]:
                raise ValueError(
                    f"metric {name!r}: kind {o['kind']} vs {m['kind']}"
                )
            if m["kind"] == "counter":
                o["value"] += m["value"]
            elif m["kind"] == "gauge":
                o["value"] = max(o["value"], m["value"])
                o["min"] = min(o["min"], m["min"])
                o["max"] = max(o["max"], m["max"])
            else:  # histogram
                o["count"] += m["count"]
                o["total"] += m["total"]
                o["min"] = min(o["min"], m["min"])
                o["max"] = max(o["max"], m["max"])
                o["mean"] = o["total"] / o["count"] if o["count"] else 0.0
                # window percentiles are per-rank views; a merged exact
                # percentile would need the raw samples — drop them
                o.pop("p50", None)
                o.pop("p99", None)
    return out


def mesh_reduce(mesh, per_rank, ops: Sequence[str] = ("sum",)):
    """Reduce per-rank metric vectors ACROSS the mesh via
    ``comm.collectives`` — the device-side twin of :func:`merge_snapshots`.

    ``per_rank`` is (n_ranks, k) (or (n_ranks,)): row i is mesh position
    i's values (row-major over the mesh axes, the ``make_mesh`` device
    order contract).  One compiled shard_map program runs every requested
    reduction over ALL mesh axes at once; returns {op: np.ndarray(k)}.
    On a multi-host mesh each host contributes the rows it owns and the
    collective does the gather the reference did with MPI_Reduce to
    rank 0 (mpicuda3.cu:176-179) — except every rank gets the answer.
    """
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import collectives as C
    from tpuscratch.comm import run_spmd

    arr = np.asarray(per_rank, np.float64)
    squeeze = arr.ndim == 1
    if squeeze:
        arr = arr[:, None]
    n = int(np.prod(mesh.devices.shape))
    if arr.shape[0] != n:
        raise ValueError(
            f"per_rank has {arr.shape[0]} rows, mesh has {n} positions"
        )
    axes = tuple(mesh.axis_names)
    reducers = {
        "sum": C.allreduce_sum, "max": C.allreduce_max,
        "min": C.allreduce_min,
    }
    for op in ops:
        if op not in reducers:
            raise ValueError(f"unknown reduce op {op!r}; choose {sorted(reducers)}")

    def body(v):  # v: this rank's (1, k) row
        return tuple(reducers[op](v, axes) for op in ops)

    prog = run_spmd(
        mesh, body,
        P(axes if len(axes) > 1 else axes[0]),
        tuple(P() for _ in ops),
    )
    results = prog(jnp.asarray(arr, jnp.float32))
    out = {}
    for op, r in zip(ops, results):
        r = np.asarray(r)[0]
        out[op] = r[0] if squeeze else r
    return out


@dataclasses.dataclass(frozen=True)
class MeshSpan:
    """Cross-rank merged span: the max-min wall plus the per-rank spread
    (max begin skew / rank seconds) the pure merge throws away."""

    name: str
    seconds: float        # max(end) - min(begin)
    rank_seconds_max: float
    rank_seconds_min: float


def mesh_span(mesh, name: str, begins, ends,
              use_device: bool = True) -> MeshSpan:
    """max-min merge of one named span's per-rank (begin, end) stamps —
    through the mesh collectives when ``use_device`` (min(begin) via
    pmin, max(end) via pmax: the device-side mpicuda3 gather), or the
    pure host merge otherwise."""
    begins = list(begins)
    ends = list(ends)
    if use_device:
        # perf_counter stamps are O(1e4) s where f32 resolution is ~1 ms;
        # shifting to offsets from the earliest begin (pure relabeling —
        # spans are differences) keeps the device reduce at ~us precision
        t0 = min(begins)
        red = mesh_reduce(
            mesh,
            [[b - t0, e - t0, e - b] for b, e in zip(begins, ends)],
            ops=("min", "max"),
        )
        return MeshSpan(
            name,
            seconds=float(red["max"][1] - red["min"][0]),
            rank_seconds_max=float(red["max"][2]),
            rank_seconds_min=float(red["min"][2]),
        )
    durs = [e - b for b, e in zip(begins, ends)]
    return MeshSpan(name, span_max_min(begins, ends), max(durs), min(durs))
