"""tpuscratch — a TPU-native distributed-computing framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the CUDA+MPI
scratchpad ``ugovaretto-accel/cuda-mpi-scratch`` (surveyed in ``SURVEY.md``):

- **runtime**  — mesh/topology bring-up, typed config, error policies,
  rank-prefixed logging (replaces ``MPI_Init``/``mpierr.h``/cartesian setup).
- **comm**     — named collectives and point-to-point patterns over mesh axes
  (replaces the raw ``MPI_*`` call surface: psum/ppermute/all_gather/...).
- **dtypes**   — structured slice specs, the functional equivalent of MPI
  derived datatypes (indexed / struct / subarray / hindexed).
- **halo**     — the flagship: generic 2D AND 3D domain decomposition with
  ghost-cell exchange (8-neighbor 2D, 6/26-neighbor 3D; replaces
  ``stencil2D.h`` and extends it a dimension).
- **ops**      — Pallas TPU kernels: reductions, stencil compute (2D + 3D
  banded/strip variants), flash attention, remote-DMA halo, fills
  (replaces the CUDA ``__global__`` kernels).
- **parallel** — the parallelism strategies: ring + Ulysses attention,
  GPipe pipeline, expert (MoE) all_to_all, sequence-parallel SSM scan,
  distributed 2D FFT.
- **solvers**  — the algorithm layer: CG, spectral, 2D/3D multigrid and
  MG-preconditioned CG over the halo/collective machinery.
- **models**   — composed demonstrations: the MoE transformer training
  step, the selective-SSM block, the checkpointed trainer.
- **bench**    — timing harnesses: pingpong latency/BW, distributed dot,
  stencil throughput (2D + 3D), collective busBW, matmul-DFT TFLOP/s
  (replaces ``test-benchmark/``).

Everything is runnable on a single host via a CPU device mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``), mirroring how the
reference validates multi-node behavior with many ranks on one box.
"""

__version__ = "0.1.0"

from tpuscratch.runtime import compat as _compat  # noqa: F401  (version gates first)
from tpuscratch.runtime.topology import CartTopology, Direction  # noqa: F401
from tpuscratch.runtime.mesh import make_mesh, make_mesh_1d, make_mesh_2d  # noqa: F401
from tpuscratch.runtime.config import Config  # noqa: F401
from tpuscratch.runtime.context import RuntimeContext, initialize  # noqa: F401
